"""Case-level hydrodynamics driver for one FOWT.

Glue between the load-case table and the Morison kernels: builds the
sea-state arrays for a case (spectra -> component amplitudes,
``raft_fowt.py:1737-1774``) and exposes the per-stage entry points the
Model dynamics solver (and the parity tests) use.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from raft_tpu.ops import waves as wv
from raft_tpu.physics import morison
from raft_tpu.physics.statics import platform_kinematics, node_T
from raft_tpu.structure.schema import coerce
from raft_tpu.utils.dtypes import compute_dtypes


def make_sea_state(case, w):
    """(S, zeta, beta[rad]) arrays of shape (nWaves, nw) / (nWaves,).

    raft_fowt.py:1742-1774; zeta = sqrt(2 S dw)."""
    w = np.asarray(w)
    dw = w[1] - w[0]
    if np.isscalar(case["wave_heading"]):
        nWaves = 1
    else:
        nWaves = len(case["wave_heading"])
    heading = coerce(case, "wave_heading", shape=nWaves, default=0)
    spectrum = coerce(case, "wave_spectrum", shape=nWaves, dtype=str, default="JONSWAP")
    period = coerce(case, "wave_period", shape=nWaves)
    height = coerce(case, "wave_height", shape=nWaves)
    gamma = coerce(case, "wave_gamma", shape=nWaves, default=0)

    S = np.zeros((nWaves, len(w)))
    zeta = np.zeros((nWaves, len(w)))
    for ih in range(nWaves):
        if spectrum[ih] == "unit":
            S[ih] = 1.0
            zeta[ih] = np.sqrt(2 * S[ih] * dw)
        elif spectrum[ih] == "constant":
            S[ih] = height[ih]
            zeta[ih] = np.sqrt(2 * S[ih] * dw)
        elif spectrum[ih] == "JONSWAP":
            S[ih] = np.asarray(wv.jonswap(w, height[ih], period[ih], gamma=gamma[ih]))
            zeta[ih] = np.sqrt(2 * S[ih] * dw)
        elif spectrum[ih] in ("none", "still"):
            pass
        else:
            raise ValueError(f"unknown wave spectrum {spectrum[ih]!r}")
    beta = np.deg2rad(heading)
    return S, zeta, beta


def add_rotor_added_mass(A, fs, Tn):
    """Add the submerged (MHK) rotor blade added mass about each rotor
    node (raft_fowt.py:1618-1625).  Shared by the host-side FOWTHydro
    build and the traced geometry evaluator so the two paths cannot
    diverge.  A (nDOF, nDOF); Tn (N, 6, nDOF) node reduction rows."""
    for ir, rot in enumerate(fs.rotors):
        if rot.hydro is not None:
            Tn_n = jnp.asarray(Tn[int(fs.rotor_node[ir])])
            A = A + Tn_n.T @ jnp.asarray(rot.hydro["A_hydro"]) @ Tn_n
    return A


class FOWTHydro:
    """Per-FOWT hydro state: strips + pose-dependent tensors."""

    def __init__(self, fs, w, k):
        self.fs = fs
        self.w = np.asarray(w)
        self.k = np.asarray(k)
        self.nw = len(self.w)
        self.strips = morison.build_strips(fs, k_array=self.k)
        # hydro *constants* (added mass, inertial-excitation tensors) are
        # evaluated at the reference pose, as in the standard reference
        # flow (calcHydroConstants is called with the FOWT at its
        # reference position, raft_model.py:620); only the wave-field
        # evaluation points and member axes track the mean offset.
        from raft_tpu.utils.devices import on_cpu, to_host

        with on_cpu():
            r0_nodes, R0, root0, Tn0 = self._kinematics(np.zeros(fs.nDOF))
            self.hc0 = to_host(
                morison.hydro_constants(fs, self.strips, R0, r0_nodes, Tn0)
            )
            # submerged (MHK) rotor added mass via blade members
            self.hc0["A_hydro"] = np.asarray(
                add_rotor_added_mass(jnp.asarray(self.hc0["A_hydro"]), fs, Tn0))
            self.set_position(np.zeros(fs.nDOF))

    def _kinematics(self, Xi0):
        """Node positions / platform rotation / per-node reduction rows.

        Single rigid bodies use the exact nonlinear rigid kinematics;
        general (flexible/multibody) structures use the linear map
        r = r0 + (T Xi0) with the build-time T (small mean deflections).
        """
        fs = self.fs
        Xi0 = jnp.asarray(Xi0, dtype=float)
        if fs.is_single_body:
            r_nodes, R_ptfm, r_root = platform_kinematics(fs, Xi0)
            Tn = node_T(r_nodes, r_root)
            return r_nodes, R_ptfm, r_root, Tn
        # nonlinear rigid-link/beam mean-offset kinematics at the
        # self-consistent displaced pose (setNodesPosition + reduceDOF
        # fixed point — the reference reaches it by calling setPosition
        # at every statics-solver evaluation, raft_fowt.py:669-780)
        disp, T_disp = fs.topology.self_consistent_displacements(
            fs.T, fs.reducedDOF, fs.root_id, np.asarray(Xi0))
        r_np = fs.node_r0 + disp[:, :3]
        r_nodes = jnp.asarray(r_np)
        Tn = jnp.asarray(T_disp.reshape(fs.n_nodes, 6, fs.nDOF))
        self._node_rot = jnp.asarray(disp[:, 3:])  # member axes track node rotations
        return r_nodes, jnp.eye(3), r_nodes[fs.root_id], Tn

    def set_position(self, Xi0):
        self.Xi0 = jnp.asarray(Xi0, dtype=float)
        self._node_rot = None
        self.r_nodes, self.R_ptfm, self.r_root, self.Tn = self._kinematics(self.Xi0)
        r, q, p1, p2 = morison.strip_frames(
            self.strips, self.R_ptfm, self.r_nodes, node_rot=self._node_rot)
        sub = r[:, 2] < 0
        self.hc = dict(
            self.hc0,
            r=r, q=q, p1=p1, p2=p2, sub=sub,
            active=sub & jnp.asarray(self.strips.active),
        )

    @property
    def A_hydro_morison(self):
        return self.hc["A_hydro"]

    def hydro_excitation(self, case):
        S, zeta, beta = make_sea_state(case, self.w)
        self.S, self.zeta, self.beta = S, zeta, beta
        out = morison.hydro_excitation(
            self.fs, self.strips, self.hc,
            jnp.asarray(zeta).astype(compute_dtypes(zeta)[1]), jnp.asarray(beta),
            jnp.asarray(self.w), jnp.asarray(self.k), self.Tn, self.r_nodes,
        )
        self.u = out["u"]

        # submerged rotor inertial excitation from hub wave kinematics
        # (raft_fowt.py:1861-1883)
        fs = self.fs
        for ir, rot in enumerate(fs.rotors):
            if rot.hydro is None:
                continue
            node = int(fs.rotor_node[ir])
            r_hub = self.r_nodes[node] + jnp.asarray(rot.q_rel) * rot.overhang
            F_add = []
            I6 = jnp.asarray(rot.hydro["I_hydro"])
            for ih in range(len(beta)):
                _, ud, _ = wv.wave_kinematics(
                    jnp.asarray(zeta[ih]).astype(compute_dtypes(zeta)[1])[None, :],
                    float(beta[ih]), jnp.asarray(self.w), jnp.asarray(self.k),
                    fs.depth, r_hub, rho=fs.rho_water, g=fs.g)
                ud = ud.reshape(3, -1)  # (3, nw)
                # I_hydro is assembled ABOUT THE ROTOR NODE (blade_hydro
                # includes the element moment arms), so no extra lever here
                f3 = jnp.einsum("ij,jw->iw", I6[:3, :3], ud)
                m3 = jnp.einsum("ij,jw->iw", I6[3:, :3], ud)
                F_add.append(jnp.einsum(
                    "ia,iw->aw", self.Tn[node], jnp.concatenate([f3, m3])))
            out["F_hydro_iner"] = out["F_hydro_iner"] + jnp.stack(F_add)
        return out

    def hydro_linearization(self, Xi, ih=0):
        return morison.hydro_linearization(
            self.fs, self.strips, self.hc, self.u[ih], jnp.asarray(Xi),
            jnp.asarray(self.w), self.Tn, self.r_nodes,
        )

    def drag_excitation(self, Bmat, ih):
        return morison.drag_excitation(
            self.fs, self.strips, self.hc, Bmat, self.u[ih], self.Tn, self.r_nodes
        )

    def current_loads(self, case):
        speed = coerce(case, "current_speed", shape=0, default=0.0)
        heading = coerce(case, "current_heading", shape=0, default=0)
        Zref = 0.0
        for rot in self.fs.rotors:
            if rot.Zhub < 0:
                Zref = rot.Zhub
        return morison.current_loads(
            self.fs, self.strips, self.hc, speed, heading, Zref, self.Tn, self.r_nodes
        )
