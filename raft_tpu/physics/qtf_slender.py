"""Slender-body difference-frequency QTFs (potSecOrder == 1).

Internal computation of the quadratic transfer functions by the
slender-body approximation — the reference's most expensive kernel
(``/root/reference/raft/raft_fowt.py`` ``calcQTF_slenderBody``
:1988-2079; ``/root/reference/raft/raft_member.py`` :1488-1674;
``correction_KAY`` :1676-1791; second-order wave field helpers in
``helpers.py:239-375``).  Force components per Pinkster (1979) and
Rainey, plus the Kim & Yue (1989/1990) analytic second-order
diffraction correction for surface-piercing vertical cylinders.

TPU decomposition: the (w1 x w2) upper-triangle pair axis — the loop
the reference times with its only wall-clock instrumentation
(raft_model.py:1122-1126) — becomes a ``vmap`` over pair indices, with
all member nodes vectorised inside each pair evaluation.  The Kim & Yue
Hankel-function series depends only on static geometry and the static
QTF frequency grid, so its sums are precomputed with scipy at case
setup and enter as constants.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.ops import transforms as tf
from raft_tpu.ops import waves as wv
from raft_tpu.ops import waves2
from raft_tpu.utils.dtypes import compute_dtypes


def member_qtf(mem, a_i_member, Xi, beta, w2nd, k2nd, depth, rho, g,
               pair_idx=None):
    """Upper-triangle QTF contribution of one rigid member (6 DOF about
    the PRP).  Twin of Member.calcQTF_slenderBody
    (raft_member.py:1488-1674), vmapped over frequency pairs.

    mem : MemberGeometry (reference pose);
    a_i_member : (ns,) signed axial areas from the hydro-constants stage;
    Xi : (6, nw2) motion RAOs at the QTF frequencies; beta [rad].
    Returns qtf (nw2, nw2, 6) complex (upper triangle filled); with
    ``pair_idx=(i1, i2)`` (the sharded-grid path,
    :func:`raft_tpu.parallel.sweep.qtf_slender_sharded`) returns the
    flat (npairs, 6) pair forces for those indices instead.
    """
    nw2 = len(w2nd)
    ns = mem.ns
    w2nd = jnp.asarray(w2nd)
    k2nd = jnp.asarray(k2nd)
    # complex width follows the inputs (f32 sweeps stay complex64;
    # the f64 parity path stays complex128) instead of the bare
    # `complex` literal that pinned complex128 under x64
    cdt = compute_dtypes(w2nd, Xi)[1]
    Xi = jnp.asarray(Xi).astype(cdt)

    rA = jnp.asarray(mem.rA0)
    rB = jnp.asarray(mem.rB0)
    if mem.rA0[2] > 0 and mem.rB0[2] > 0:
        if pair_idx is not None:
            return jnp.zeros((len(pair_idx[0]), 6), dtype=cdt)
        return jnp.zeros((nw2, nw2, 6), dtype=cdt)

    q = jnp.asarray(mem.q0)
    p1 = jnp.asarray(mem.p10)
    p2 = jnp.asarray(mem.p20)
    qMat = tf.vec_vec_trans(q)
    p1Mat = tf.vec_vec_trans(p1)
    p2Mat = tf.vec_vec_trans(p2)

    r = np.asarray(mem.rA0)[None, :] + np.asarray(mem.q0)[None, :] * mem.ls[:, None]
    r_j = jnp.asarray(r)
    sub = jnp.asarray(r[:, 2] < 0)

    # strip coefficients and volumes (static)
    Ca_p1 = jnp.asarray(mem.Ca_p1)
    Ca_p2 = jnp.asarray(mem.Ca_p2)
    Ca_End = jnp.asarray(mem.Ca_End)
    circ = mem.circular
    ds = mem.ds
    drs = mem.drs
    dls = mem.dls
    if circ:
        v_side = 0.25 * np.pi * ds[:, 0] ** 2 * dls
        v_end = np.pi / 12.0 * np.abs((ds[:, 0] + drs[:, 0]) ** 3 - (ds[:, 0] - drs[:, 0]) ** 3)
    else:
        v_side = ds[:, 0] * ds[:, 1] * dls
        v_end = np.pi / 12.0 * (np.mean(ds + drs, axis=1) ** 3 - np.mean(ds - drs, axis=1) ** 3)
    scale = np.where(
        (r[:, 2] + 0.5 * dls > 0) & (dls > 0), (0.5 * dls - r[:, 2]) / np.where(dls == 0, 1, dls), 1.0
    )
    v_side = jnp.asarray(v_side * scale)
    v_end = jnp.asarray(v_end)
    a_i = jnp.asarray(a_i_member)

    CmMat = (1.0 + Ca_p1)[:, None, None] * p1Mat + (1.0 + Ca_p2)[:, None, None] * p2Mat
    CaMat = Ca_p1[:, None, None] * p1Mat + Ca_p2[:, None, None] * p2Mat

    # ---- per-node first-order kinematics over the QTF grid
    Xi_b = jnp.broadcast_to(Xi[None, :, :], (ns, 6, nw2))
    dr_n, nodeV, _ = wv.get_kinematics(r_j, Xi_b, w2nd)        # (ns, 3, nw2)
    u_n, _, _ = wv.wave_kinematics(
        jnp.ones(nw2, dtype=cdt), beta, w2nd, k2nd, depth, r_j, rho=rho, g=g)

    grad_u = jax.vmap(
        lambda rr: jax.vmap(lambda w_, k_: waves2.grad_u1(w_, k_, beta, depth, rr))(w2nd, k2nd)
    )(r_j)                                                      # (ns, nw2, 3, 3)
    grad_dudt = 1j * w2nd[None, :, None, None] * grad_u
    vrel_ax = jnp.einsum("niw,i->nw", u_n - nodeV, q)           # (ns, nw2)
    grad_p1st = jax.vmap(
        lambda rr: jax.vmap(lambda k_: waves2.grad_pres1st(k_, beta, depth, rr, rho=rho, g=g))(k2nd)
    )(r_j)                                                      # (ns, nw2, 3)

    # ---- waterline quantities (raft_member.py:1517-1534)
    crosses = bool(r[-1, 2] * r[0, 2] < 0)
    if crosses:
        fr = (0.0 - r[0, 2]) / (r[-1, 2] - r[0, 2])
        r_int = jnp.asarray(r[0] + (r[-1] - r[0]) * fr)
        u_wl, ud_wl, eta = wv.wave_kinematics(
            jnp.ones(nw2, dtype=cdt), beta, w2nd, k2nd, depth, r_int, rho=1.0, g=1.0)
        dr_wl, _, a_wl = wv.get_kinematics(r_int, Xi, w2nd)
        eta_r = eta - dr_wl[2, :]
        i_wl = int(np.where(r[:, 2] < 0)[0][-1])
        if circ:
            d_wl = 0.5 * (ds[i_wl, 0] + ds[i_wl + 1, 0]) if i_wl != ns - 1 else ds[i_wl, 0]
            a_wl_area = 0.25 * np.pi * d_wl**2
        else:
            if i_wl != ns - 1:
                d1 = 0.5 * (ds[i_wl, 0] + ds[i_wl + 1, 0])
                d2 = 0.5 * (ds[i_wl, 1] + ds[i_wl + 1, 1])
            else:
                d1, d2 = ds[i_wl, 0], ds[i_wl, 1]
            a_wl_area = d1 * d2
    else:
        r_int = jnp.zeros(3)
        ud_wl = jnp.zeros((3, nw2), dtype=cdt)
        a_wl = jnp.zeros((3, nw2), dtype=cdt)
        eta_r = jnp.zeros(nw2, dtype=cdt)
        a_wl_area = 0.0

    # projected-gravity vector (raft_member.py:1529-1531)
    g_e1 = -g * (
        jnp.cross(Xi[3:, :].T, p1[None, :])[:, 2][None, :] * p1[:, None]
        + jnp.cross(Xi[3:, :].T, p2[None, :])[:, 2][None, :] * p2[:, None]
    )  # (3, nw2)

    # the waterline term reuses the strip-loop coefficient variables
    # after the loop, i.e. those of the last strip (reference behavior)
    CmMat_wl = (1.0 + Ca_p1[-1]) * p1Mat + (1.0 + Ca_p2[-1]) * p2Mat
    CaMat_wl = Ca_p1[-1] * p1Mat + Ca_p2[-1] * p2Mat

    idx1, idx2 = np.triu_indices(nw2)
    lever = r_j  # forces translated about the PRP origin (r relative to PRP)

    def pair(i1, i2):
        w1_, w2_ = w2nd[i1], w2nd[i2]
        k1_, k2_ = k2nd[i1], k2nd[i2]

        acc2, p2nd = jax.vmap(
            lambda rr: waves2.pot_2nd_ord(w1_, w2_, k1_, k2_, beta, depth, rr, g=g, rho=rho)
        )(r_j)  # (ns,3), (ns,)
        f_2ndPot = rho * v_side[:, None] * jnp.einsum("nij,nj->ni", CmMat, acc2)

        conv = 0.25 * (
            jnp.einsum("nij,nj->ni", grad_u[:, i1], jnp.conj(u_n[:, :, i2]))
            + jnp.einsum("nij,nj->ni", jnp.conj(grad_u[:, i2]), u_n[:, :, i1])
        )
        f_conv = rho * v_side[:, None] * jnp.einsum("nij,nj->ni", CmMat, conv)

        axdv = jax.vmap(
            lambda rr, v1, v2: waves2.axdiv_acc(w1_, w2_, k1_, k2_, beta, depth, rr, v1, v2, q, g=g)
        )(r_j, nodeV[:, :, i1], nodeV[:, :, i2])
        f_axdv = rho * v_side[:, None] * jnp.einsum("nij,nj->ni", CaMat, axdv)

        acc_nabla = 0.25 * (
            jnp.einsum("nij,nj->ni", grad_dudt[:, i1], jnp.conj(dr_n[:, :, i2]))
            + jnp.einsum("nij,nj->ni", jnp.conj(grad_dudt[:, i2]), dr_n[:, :, i1])
        )
        f_nabla = rho * v_side[:, None] * jnp.einsum("nij,nj->ni", CmMat, acc_nabla)

        # Rainey body-rotation terms (raft_member.py:1587-1607)
        OM1 = -tf.skew(1j * w1_ * Xi[3:, i1])
        OM2 = -tf.skew(1j * w2_ * Xi[3:, i2])
        f_rslb = -0.25 * 2 * jnp.einsum(
            "nij,nj->ni", CaMat,
            (OM1 @ q)[None, :] * jnp.conj(vrel_ax[:, i2])[:, None]
            + (jnp.conj(OM2) @ q)[None, :] * vrel_ax[:, i1][:, None],
        )
        f_rslb = rho * v_side[:, None] * f_rslb

        u1a = u_n[:, :, i1] - nodeV[:, :, i1]
        u2a = u_n[:, :, i2] - nodeV[:, :, i2]
        V1 = grad_u[:, i1] + OM1[None, :, :]
        V2 = grad_u[:, i2] + OM2[None, :, :]
        aux = 0.25 * (
            jnp.einsum("nij,nj->ni", V1, jnp.conj(jnp.einsum("nij,nj->ni", CaMat, u2a)))
            + jnp.einsum("nij,nj->ni", jnp.conj(V2), jnp.einsum("nij,nj->ni", CaMat, u1a))
        )
        aux = aux - jnp.einsum("ij,nj->ni", qMat, aux)
        f_rslb = f_rslb + rho * v_side[:, None] * aux

        u1p = u1a - jnp.einsum("ij,nj->ni", qMat, u1a)
        u2p = u2a - jnp.einsum("ij,nj->ni", qMat, u2a)
        aux = 0.25 * (
            jnp.einsum("nij,nj->ni", CaMat, jnp.einsum("nij,nj->ni", V1, jnp.conj(u2p)))
            + jnp.einsum("nij,nj->ni", CaMat, jnp.einsum("nij,nj->ni", jnp.conj(V2), u1p))
        )
        f_rslb = f_rslb - rho * v_side[:, None] * aux

        # ---- axial/end effects (raft_member.py:1610-1631)
        f_2ndPot = f_2ndPot + a_i[:, None] * p2nd[:, None] * q[None, :]
        f_2ndPot = f_2ndPot + (rho * v_end * Ca_End)[:, None] * jnp.einsum("ij,nj->ni", qMat, acc2)
        f_conv = f_conv + (rho * v_end * Ca_End)[:, None] * jnp.einsum("ij,nj->ni", qMat, conv)
        f_nabla = f_nabla + (rho * v_end * Ca_End)[:, None] * jnp.einsum("ij,nj->ni", qMat, acc_nabla)
        p_nabla = 0.25 * (
            jnp.einsum("ni,ni->n", grad_p1st[:, i1], jnp.conj(dr_n[:, :, i2]))
            + jnp.einsum("ni,ni->n", jnp.conj(grad_p1st[:, i2]), dr_n[:, :, i1])
        )
        f_nabla = f_nabla + (a_i * p_nabla)[:, None] * q[None, :]
        p_drop = -2 * 0.25 * 0.5 * rho * jnp.einsum(
            "ni,ni->n",
            jnp.einsum("ij,nj->ni", p1Mat + p2Mat, u1a),
            jnp.conj(jnp.einsum("nij,nj->ni", CaMat, u2a)),
        )
        f_conv = f_conv + (a_i * p_drop)[:, None] * q[None, :]

        u1c = jnp.einsum("nij,nj->ni", CaMat, u1p)
        u2c = jnp.einsum("nij,nj->ni", CaMat, u2p)
        f_transv = 0.25 * a_i[:, None] * rho * (
            jnp.conj(u1c) * vrel_ax[:, i2][:, None] + u2c * jnp.conj(vrel_ax[:, i1])[:, None]
        )
        f_conv = f_conv + f_transv

        # sum strips -> 6-DOF about PRP, masked to submerged nodes
        def to6(f3):
            f3 = jnp.where(sub[:, None], f3, 0.0)
            mom = jnp.cross(lever, f3)
            return jnp.concatenate([jnp.sum(f3, axis=0), jnp.sum(mom, axis=0)])

        F = to6(f_2ndPot) + to6(f_conv) + to6(f_axdv) + to6(f_nabla) + to6(f_rslb)

        # ---- relative wave-elevation term at the waterline (1639-1667)
        if crosses:
            f_eta = 0.25 * (ud_wl[:, i1] * jnp.conj(eta_r[i2])
                            + jnp.conj(ud_wl[:, i2]) * eta_r[i1])
            f_eta = rho * a_wl_area * (CmMat_wl @ f_eta)
            a_eta = 0.25 * (a_wl[:, i1] * jnp.conj(eta_r[i2])
                            + jnp.conj(a_wl[:, i2]) * eta_r[i1])
            f_eta = f_eta - rho * a_wl_area * (CaMat_wl @ a_eta)
            f_eta = f_eta - 0.25 * rho * a_wl_area * (
                g_e1[:, i1] * jnp.conj(eta_r[i2]) + jnp.conj(g_e1[:, i2]) * eta_r[i1])
            F = F + jnp.concatenate([f_eta, jnp.cross(r_int, f_eta)])
        return F

    if pair_idx is not None:
        return jax.vmap(pair)(jnp.asarray(pair_idx[0]),
                              jnp.asarray(pair_idx[1]))
    Fpairs = jax.vmap(pair)(jnp.asarray(idx1), jnp.asarray(idx2))
    qtf = jnp.zeros((nw2, nw2, 6), dtype=cdt)
    qtf = qtf.at[idx1, idx2, :].set(Fpairs)
    return qtf


def member_qtf_coeff_interp(mem):
    """Strip coefficients at node locations — the reference interpolates
    per strip inside the loop (raft_member.py:1559-1561); the build-time
    members already carry them at the strips."""
    return mem.Ca_p1, mem.Ca_p2, mem.Ca_End


def kim_yue_correction(mem, beta, w2nd, k2nd, depth, rho, g, Nm=10):
    """Kim & Yue second-order diffraction correction (numpy, static).

    Twin of Member.correction_KAY (raft_member.py:1676-1791) evaluated
    for all upper-triangle pairs.  Returns (nw2, nw2, 6) complex."""
    from scipy.special import hankel1

    nw2 = len(w2nd)
    out = np.zeros((nw2, nw2, 6), dtype=np.complex128)
    if not mem.MCF:
        return out
    if not (mem.rA0[2] * mem.rB0[2] < 0):
        return out

    r = mem.rA0[None, :] + mem.q0[None, :] * mem.ls[:, None]
    radii = 0.5 * mem.ds[:, 0]
    R_wl = np.interp(0.0, r[:, 2], radii)
    rwl = mem.rA0 + (mem.rB0 - mem.rA0) * (0 - mem.rA0[2]) / (mem.rB0[2] - mem.rA0[2])

    cosB, sinB = np.cos(beta), np.sin(beta)
    beta_vec = np.array([cosB, sinB, 0.0])
    pforce = (np.dot(beta_vec, mem.p10) * mem.p10 + np.dot(beta_vec, mem.p20) * mem.p20)
    pforce = pforce / np.linalg.norm(pforce)

    def omega_n(k1R, k2R, n):
        H_N_i = 0.5 * (hankel1(n - 1, k1R) - hankel1(n + 1, k1R))
        H_N_j = 0.5 * np.conj(hankel1(n - 1, k2R) - hankel1(n + 1, k2R))
        H_Nm1_i = 0.5 * (hankel1(n, k1R) - hankel1(n + 2, k1R))
        H_Nm1_j = 0.5 * np.conj(hankel1(n, k2R) - hankel1(n + 2, k2R))
        return 1 / (H_Nm1_i * H_N_j) - 1 / (H_N_i * H_Nm1_j)

    for i1 in range(nw2):
        for i2 in range(i1, nw2):
            w1_, w2_ = w2nd[i1], w2nd[i2]
            k1_, k2_ = k2nd[i1], k2nd[i2]
            k1_k2 = np.array([k1_ * cosB - k2_ * cosB, k1_ * sinB - k2_ * sinB, 0.0])
            F = np.zeros(6, dtype=np.complex128)

            # waterline term
            k1R, k2R = k1_ * R_wl, k2_ * R_wl
            Fwl = 0 + 0j
            for nn in range(Nm + 1):
                Fwl += -rho * g * R_wl * 2j / np.pi / (k1R * k2R) * omega_n(k1R, k2R, nn)
            Fwl = np.real(Fwl) * np.exp(-1j * np.dot(k1_k2, rwl))
            F += np.asarray(tf.translate_force_3to6(jnp.asarray(Fwl * pforce), jnp.asarray(rwl)))

            # quadratic-velocity term, analytic integration per node zone
            for il in range(mem.ns - 1):
                z1 = r[il, 2]
                if z1 > 0:
                    continue
                z2 = min(r[il + 1, 2], 0.0)
                R1 = mem.ds[il, 0] / 2
                if mem.dls[il] == 0:
                    R1 = mem.ds[il, 0]
                R2 = mem.ds[il + 1, 0] / 2
                if mem.dls[il + 1] == 0:
                    R2 = mem.ds[il, 0]  # reference quirk (raft_member.py:1759)
                R = 0.5 * (R1 + R2)
                k1R, k2R = k1_ * R, k2_ * R
                H = depth / R
                k1h, k2h = k1R * H, k2R * H
                if w1_ == w2_:
                    Im = 0.5 * (np.sinh((k1_ + k2_) * (z2 + depth)) / (k1h + k2h) - (z2 + depth) / depth
                                - np.sinh((k1_ + k2_) * (z1 + depth)) / (k1h + k2h) + (z1 + depth) / depth)
                    Ip = 0.5 * (np.sinh((k1_ + k2_) * (z2 + depth)) / (k1h + k2h) + (z2 + depth) / depth
                                - np.sinh((k1_ + k2_) * (z1 + depth)) / (k1h + k2h) - (z1 + depth) / depth)
                else:
                    Im = 0.5 * (np.sinh((k1_ + k2_) * (z2 + depth)) / (k1h + k2h)
                                - np.sinh((k1_ - k2_) * (z2 + depth)) / (k1h - k2h)
                                - np.sinh((k1_ + k2_) * (z1 + depth)) / (k1h + k2h)
                                + np.sinh((k1_ - k2_) * (z1 + depth)) / (k1h - k2h))
                    Ip = 0.5 * (np.sinh((k1_ + k2_) * (z2 + depth)) / (k1h + k2h)
                                + np.sinh((k1_ - k2_) * (z2 + depth)) / (k1h - k2h)
                                - np.sinh((k1_ + k2_) * (z1 + depth)) / (k1h + k2h)
                                - np.sinh((k1_ - k2_) * (z1 + depth)) / (k1h - k2h))
                coshk1h, coshk2h = np.cosh(k1h), np.cosh(k2h)
                dF = 0 + 0j
                for nn in range(Nm + 1):
                    dF += rho * g * R * 2j / np.pi / (k1R * k2R) * omega_n(k1R, k2R, nn) * (
                        k1h * k2h / np.sqrt(k1h * np.tanh(k1h)) / np.sqrt(k2h * np.tanh(k2h))
                        * (Im + Ip * nn * (nn + 1) / k1R / k2R) / coshk1h / coshk2h)
                rmid = 0.5 * (r[il] + r[il + 1])
                dF = np.real(dF) * np.exp(-1j * np.dot(k1_k2, rwl))
                F += np.asarray(tf.translate_force_3to6(jnp.asarray(dF * pforce), jnp.asarray(rmid)))

            if k1_ < k2_:
                F = np.conj(F)
            out[i1, i2, :] = F
    return out


def pinkster_iv(Xi, F1st, block=512):
    """Pinkster term IV — rotation of the first-order inertial forces
    (raft_fowt.py:2052-2061) — for ALL upper-triangle (w1, w2) pairs in
    one broadcast cross product per block.

    Xi : (nDOF, nw2) motion RAOs on the QTF grid;
    F1st : (nDOF, nw2) first-order inertial forces.
    Returns (nw2, nw2, 6) complex with only the upper triangle filled
    (the lower triangle is completed by the callers' hermitian step).

    Replaces the O(nw2^2) host-side Python double loop: at the
    min_freq2nd-driven grid sizes the sharded driver targets (thousands
    of bins) the loop's millions of scalar cross products dominated the
    runtime the pair-axis sharding was built to remove.  Blocked over
    w1 to bound the (block, nw2, 3) temporaries.
    """
    nw2 = Xi.shape[1]
    Xr = np.asarray(Xi[3:6]).T          # (nw2, 3)
    Fl = np.asarray(F1st[:3]).T         # (nw2, 3)
    Fr_ = np.asarray(F1st[3:6]).T       # (nw2, 3)
    Xrc, Flc, Frc = np.conj(Xr), np.conj(Fl), np.conj(Fr_)
    out = np.zeros((nw2, nw2, 6), dtype=np.complex128)
    j = np.arange(nw2)
    for s in range(0, nw2, block):
        e = min(s + block, nw2)
        mask = (j[s:e, None] <= j[None, :])[..., None]  # upper triangle
        # entry (j1, j2): cross(Xi_rot[j1], conj(F[j2])) + cross(conj(Xi_rot[j2]), F[j1])
        out[s:e, :, 0:3] = 0.25 * mask * (
            np.cross(Xr[s:e, None, :], Flc[None, :, :])
            + np.cross(Xrc[None, :, :], Fl[s:e, None, :]))
        out[s:e, :, 3:6] = 0.25 * mask * (
            np.cross(Xr[s:e, None, :], Frc[None, :, :])
            + np.cross(Xrc[None, :, :], Fr_[s:e, None, :]))
    return out


def fowt_qtf_slender(model, waveHeadInd=0, Xi0=None, ifowt=0):
    """System-level slender-body QTF (FOWT.calcQTF_slenderBody twin).

    Xi0 : (nDOF, nw) motion RAOs on the first-order grid (None = fixed
    body).  Returns qtf (nw2, nw2, 1, nDOF) complex.
    """
    fs = model.fowtList[ifowt]
    fh = model.hydro[ifowt]
    stat = model.statics(ifowt)
    w2nd, k2nd = model.w1_2nd, model.k1_2nd
    nw2 = len(w2nd)
    nDOF = fs.nDOF
    beta = fh.beta[waveHeadInd]

    if Xi0 is None:
        Xi0 = np.zeros((nDOF, model.nw), dtype=np.complex128)
    Xi = np.zeros((nDOF, nw2), dtype=np.complex128)
    for i in range(nDOF):
        Xi[i] = np.interp(w2nd, model.w, Xi0[i], left=0, right=0)

    qtf = np.zeros((nw2, nw2, 1, nDOF), dtype=np.complex128)

    # Pinkster IV: rotation of first-order inertial forces (raft_fowt.py:2052-2061)
    F1st = np.asarray(stat["M_struc"]) @ (-(np.asarray(w2nd) ** 2) * Xi)
    qtf[:, :, 0, :6] = pinkster_iv(Xi, F1st)

    # per-member slender-body terms + Kim & Yue correction
    # a_i per member from the hydro-constants stage (zero pose)
    a_i_all = np.asarray(fh.hc0["a_i"])
    ofs = 0
    for mem in fs.members:
        a_i_m = a_i_all[ofs:ofs + mem.ns]
        ofs += mem.ns
        qtf[:, :, 0, :] += np.asarray(member_qtf(
            mem, a_i_m, Xi, beta, w2nd, k2nd, fs.depth, fs.rho_water, fs.g))
        qtf[:, :, 0, :] += kim_yue_correction(
            mem, beta, w2nd, k2nd, fs.depth, fs.rho_water, fs.g)

    # hermitian completion (raft_fowt.py:2070-2072)
    for i in range(nDOF):
        q_ = qtf[:, :, 0, i]
        qtf[:, :, 0, i] = q_ + np.conj(q_).T - np.diag(np.diag(np.conj(q_)))
    return qtf
