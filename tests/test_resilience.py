"""Fault-tolerance tests for the checkpointed sweep runtime.

Every failure path in :mod:`raft_tpu.parallel.resilience` is exercised
deterministically via :mod:`raft_tpu.utils.faults` with cheap toy
evaluators on a small CPU mesh (fast tier — no model build, no physics):

* resume after an injected mid-write truncation is bit-identical to an
  uninterrupted run, with the corrupt shard recomputed;
* manifest fingerprint mismatches (changed inputs / out_keys /
  shard_size) fail loudly instead of mixing stale shards;
* transient faults retry with backoff and then succeed;
* injected device-OOM halves the shard batch and still completes;
* NaN rows are quarantined with their case parameters (and recovered by
  the solo CPU re-evaluation when the pathology is transient);
* every recovery action is visible in the structured JSONL event log.
"""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.parallel import resilience
from raft_tpu.parallel.sweep import (
    make_mesh, run_sweep_checkpointed, run_sweep_checkpointed_full,
    sweep_cases, sweep_cases_full)
from raft_tpu.utils import faults
from raft_tpu.utils.structlog import log_event


def toy_full(c):
    """Cheap full-evaluator stand-in: dict case -> dict of outputs."""
    return {"PSD": jnp.stack([c["Hs"], c["Tp"], c["Hs"] * c["Tp"]]),
            "X0": c["Hs"] - c["Tp"]}


def toy_nan_full(c):
    """Toy evaluator with a deterministic pathology: NaN for Hs < 0."""
    bad = c["Hs"] < 0
    return {"PSD": jnp.where(bad, jnp.nan,
                             jnp.stack([c["Hs"], c["Tp"], c["Hs"] * c["Tp"]])),
            "X0": jnp.where(bad, jnp.nan, c["Hs"] - c["Tp"])}


def toy_case(h, t, b):
    return {"PSD": jnp.stack([h, t, b]), "X0": h + t + b}


def _cases(n, seed=0):
    rng = np.random.default_rng(seed)
    return dict(Hs=2.0 + 6.0 * rng.random(n), Tp=8.0 + 8.0 * rng.random(n))


def _events(path, name=None):
    with open(path) as f:
        evs = [json.loads(line) for line in f if line.strip()]
    return [e for e in evs if name is None or e["event"] == name]


@pytest.fixture
def log_path(tmp_path, monkeypatch):
    p = str(tmp_path / "events.jsonl")
    monkeypatch.setenv("RAFT_TPU_LOG", p)
    return p


MESH = None


def mesh2():
    global MESH
    if MESH is None:
        MESH = make_mesh(2)
    return MESH


# ------------------------------------------------------------ atomic writes


def test_checkpoint_roundtrip_manifest_and_no_tmp_left(tmp_path, log_path):
    cases = _cases(10)
    out_dir = str(tmp_path / "sweep")
    out1 = run_sweep_checkpointed_full(toy_full, cases, out_dir,
                                       shard_size=4, mesh=mesh2())
    assert out1["PSD"].shape == (10, 3)
    np.testing.assert_allclose(out1["X0"], cases["Hs"] - cases["Tp"])

    files = sorted(os.listdir(out_dir))
    assert not [f for f in files if f.endswith(".tmp")]
    with open(os.path.join(out_dir, "manifest.json")) as f:
        manifest = json.load(f)
    fp = manifest["fingerprint"]
    assert fp["n_cases"] == 10 and fp["shard_size"] == 4
    assert fp["out_keys"] == ["PSD", "X0"]
    assert set(fp["case_hashes"]) == {"Hs", "Tp"}
    assert all(manifest["shards"][str(s)]["status"] == "done"
               for s in range(3))

    # resume: all three shards load from disk, bit-identical
    out2 = run_sweep_checkpointed_full(toy_full, cases, out_dir,
                                       shard_size=4, mesh=mesh2())
    for k in out1:
        assert np.array_equal(out1[k], out2[k])
    assert len(_events(log_path, "shard_resume")) == 3


def test_truncation_crash_then_resume_bit_identical(tmp_path, log_path):
    """The acceptance scenario: a sweep killed mid-shard-write resumes
    to bit-identical results, recomputing only the corrupt shard."""
    cases = _cases(10, seed=1)
    clean = run_sweep_checkpointed_full(toy_full, cases,
                                        str(tmp_path / "clean"),
                                        shard_size=4, mesh=mesh2())

    out_dir = str(tmp_path / "crashy")
    with faults.inject("truncate:shard_write:1"):
        with pytest.raises(faults.InjectedFault):
            run_sweep_checkpointed_full(toy_full, cases, out_dir,
                                        shard_size=4, mesh=mesh2())
    # the injected fault left a TRUNCATED shard file at the final path
    p0 = os.path.join(out_dir, "shard_0000.npz")
    assert os.path.exists(p0)
    with pytest.raises(resilience.ShardCorruptError):
        resilience.load_shard(p0, ("PSD", "X0"))

    resumed = run_sweep_checkpointed_full(toy_full, cases, out_dir,
                                          shard_size=4, mesh=mesh2())
    for k in clean:
        assert np.array_equal(clean[k], resumed[k]), k
    corrupt = _events(log_path, "shard_corrupt")
    assert [e["shard"] for e in corrupt] == [0]


def test_corrupt_middle_shard_requeued_not_crashed(tmp_path, log_path):
    cases = _cases(12, seed=2)
    out_dir = str(tmp_path / "sweep")
    out1 = run_sweep_checkpointed_full(toy_full, cases, out_dir,
                                       shard_size=4, mesh=mesh2())
    faults.truncate_file(os.path.join(out_dir, "shard_0001.npz"))
    out2 = run_sweep_checkpointed_full(toy_full, cases, out_dir,
                                       shard_size=4, mesh=mesh2())
    for k in out1:
        assert np.array_equal(out1[k], out2[k])
    assert [e["shard"] for e in _events(log_path, "shard_corrupt")] == [1]
    # shards 0 and 2 were NOT recomputed
    assert sorted(e["shard"] for e in _events(log_path, "shard_resume")) \
        == [0, 2]


def test_stale_shard_with_missing_keys_recomputed(tmp_path, log_path):
    cases = _cases(8, seed=3)
    out_dir = str(tmp_path / "sweep")
    out1 = run_sweep_checkpointed_full(toy_full, cases, out_dir,
                                       shard_size=4, mesh=mesh2())
    # overwrite a shard with one missing output key (stale layout)
    p1 = os.path.join(out_dir, "shard_0001.npz")
    with np.load(p1) as z:
        np.savez(p1, PSD=z["PSD"])
    out2 = run_sweep_checkpointed_full(toy_full, cases, out_dir,
                                       shard_size=4, mesh=mesh2())
    for k in out1:
        assert np.array_equal(out1[k], out2[k])
    assert [e["shard"] for e in _events(log_path, "shard_corrupt")] == [1]


# -------------------------------------------------------- manifest validation


def test_manifest_mismatch_fails_loudly(tmp_path):
    cases = _cases(8, seed=4)
    out_dir = str(tmp_path / "sweep")
    run_sweep_checkpointed_full(toy_full, cases, out_dir,
                                shard_size=4, mesh=mesh2())

    changed = dict(cases, Hs=cases["Hs"] + 0.1)
    with pytest.raises(resilience.ManifestMismatchError, match="case_hashes"):
        run_sweep_checkpointed_full(toy_full, changed, out_dir,
                                    shard_size=4, mesh=mesh2())
    with pytest.raises(resilience.ManifestMismatchError, match="out_keys"):
        run_sweep_checkpointed_full(toy_full, cases, out_dir,
                                    shard_size=4, mesh=mesh2(),
                                    out_keys=("PSD",))
    with pytest.raises(resilience.ManifestMismatchError, match="shard_size"):
        run_sweep_checkpointed_full(toy_full, cases, out_dir,
                                    shard_size=8, mesh=mesh2())
    # unchanged config still resumes fine
    out = run_sweep_checkpointed_full(toy_full, cases, out_dir,
                                      shard_size=4, mesh=mesh2())
    assert out["PSD"].shape == (8, 3)


def test_unreadable_manifest_rejected(tmp_path):
    cases = _cases(4, seed=5)
    out_dir = str(tmp_path / "sweep")
    run_sweep_checkpointed_full(toy_full, cases, out_dir,
                                shard_size=4, mesh=mesh2())
    with open(os.path.join(out_dir, "manifest.json"), "w") as f:
        f.write("{ not json")
    with pytest.raises(resilience.ManifestMismatchError, match="unreadable"):
        run_sweep_checkpointed_full(toy_full, cases, out_dir,
                                    shard_size=4, mesh=mesh2())


# ------------------------------------------------------------ retry/backoff


def test_transient_faults_retry_then_succeed(tmp_path, log_path):
    cases = _cases(8, seed=6)
    clean = run_sweep_checkpointed_full(toy_full, cases,
                                        str(tmp_path / "clean"),
                                        shard_size=4, mesh=mesh2())
    with faults.inject("transient:shard_eval:2"):
        out = run_sweep_checkpointed_full(toy_full, cases,
                                          str(tmp_path / "faulty"),
                                          shard_size=4, mesh=mesh2(),
                                          backoff_s=0.01)
    for k in clean:
        assert np.array_equal(clean[k], out[k])
    retries = _events(log_path, "shard_retry")
    assert [e["attempt"] for e in retries] == [1, 2]
    # exponential backoff: second delay doubles the first
    assert retries[1]["delay_s"] == pytest.approx(2 * retries[0]["delay_s"])


def test_transient_faults_exhaust_retries(tmp_path):
    cases = _cases(4, seed=7)
    with faults.inject("transient:shard_eval:5"):
        with pytest.raises(faults.TransientInjectedError):
            run_sweep_checkpointed_full(toy_full, cases,
                                        str(tmp_path / "sweep"),
                                        shard_size=4, mesh=mesh2(),
                                        max_retries=2, backoff_s=0.01)


def test_oom_halves_shard_batch(tmp_path, log_path):
    cases = _cases(8, seed=8)
    clean = run_sweep_checkpointed_full(toy_full, cases,
                                        str(tmp_path / "clean"),
                                        shard_size=8, mesh=mesh2())
    with faults.inject("oom:shard_eval:1"):
        out = run_sweep_checkpointed_full(toy_full, cases,
                                          str(tmp_path / "oom"),
                                          shard_size=8, mesh=mesh2())
    for k in clean:
        assert np.array_equal(clean[k], out[k])
    splits = _events(log_path, "shard_oom_split")
    assert splits and splits[0]["rows"] == 8 and splits[0]["split"] == [4, 4]


# ------------------------------------------------------------- quarantine


def test_nan_quarantine_end_to_end(tmp_path, log_path):
    cases = _cases(8, seed=9)
    cases["Hs"][5] = -1.0  # deterministic pathology: toy_nan_full -> NaN
    out_dir = str(tmp_path / "sweep")
    out = run_sweep_checkpointed_full(toy_nan_full, cases, out_dir,
                                      shard_size=4, mesh=mesh2())
    # the poisoned row is NaN, every other row is clean
    assert np.isnan(out["X0"][5]) and np.isnan(out["PSD"][5]).all()
    mask = np.ones(8, bool)
    mask[5] = False
    assert np.isfinite(out["X0"][mask]).all()
    np.testing.assert_allclose(out["X0"][mask],
                               (cases["Hs"] - cases["Tp"])[mask])

    entries = resilience.load_quarantine(out_dir)
    assert len(entries) == 1
    e = entries[0]
    assert e["shard"] == 1 and e["index"] == 5
    assert e["case"]["Hs"] == pytest.approx(-1.0)
    assert set(e["keys_nonfinite"]) == {"PSD", "X0"}
    evs = _events(log_path, "shard_quarantine")
    assert [(v["shard"], v["index"], v["recovered"]) for v in evs] \
        == [(1, 5, False)]

    # resume: the quarantined shard is valid on disk -> no re-judging
    out2 = run_sweep_checkpointed_full(toy_nan_full, cases, out_dir,
                                       shard_size=4, mesh=mesh2())
    assert np.isnan(out2["X0"][5])
    assert len(resilience.load_quarantine(out_dir)) == 1


def test_injected_nan_recovered_by_solo_cpu_retry(tmp_path, log_path):
    """A transient NaN (injected once) is healed by the solo
    re-evaluation: the row is recomputed finite, nothing is quarantined,
    and the final results match the clean run bit-for-bit."""
    cases = _cases(8, seed=10)
    clean = run_sweep_checkpointed_full(toy_full, cases,
                                        str(tmp_path / "clean"),
                                        shard_size=4, mesh=mesh2())
    out_dir = str(tmp_path / "nanswp")
    with faults.inject("nan:shard_result:1"):
        out = run_sweep_checkpointed_full(toy_full, cases, out_dir,
                                          shard_size=4, mesh=mesh2())
    for k in clean:
        assert np.array_equal(clean[k], out[k]), k
    assert resilience.load_quarantine(out_dir) == []
    evs = _events(log_path, "shard_quarantine")
    assert [(v["shard"], v["index"], v["recovered"]) for v in evs] \
        == [(0, 0, True)]


def test_quarantine_without_solo_retry(tmp_path):
    cases = _cases(4, seed=11)
    out_dir = str(tmp_path / "sweep")
    with faults.inject("nan:shard_result:1"):
        out = run_sweep_checkpointed_full(toy_full, cases, out_dir,
                                          shard_size=4, mesh=mesh2(),
                                          quarantine_retry=False)
    assert np.isnan(out["X0"][0])
    entries = resilience.load_quarantine(out_dir)
    assert [e["index"] for e in entries] == [0]


def test_recomputed_clean_shard_clears_stale_quarantine(tmp_path):
    """A shard that quarantined rows, then got corrupted and recomputed
    CLEAN (transient pathology), must clear its stale quarantine entries."""
    cases = _cases(8, seed=14)
    out_dir = str(tmp_path / "sweep")
    with faults.inject("nan:shard_result:1"):
        run_sweep_checkpointed_full(toy_full, cases, out_dir, shard_size=4,
                                    mesh=mesh2(), quarantine_retry=False)
    assert [e["index"] for e in resilience.load_quarantine(out_dir)] == [0]
    faults.truncate_file(os.path.join(out_dir, "shard_0000.npz"))
    out = run_sweep_checkpointed_full(toy_full, cases, out_dir, shard_size=4,
                                      mesh=mesh2(), quarantine_retry=False)
    assert resilience.load_quarantine(out_dir) == []
    assert np.isfinite(out["X0"]).all()


# --------------------------------------------- input validation satellites


def test_batch_not_divisible_by_dp_autopads():
    """Ragged batches no longer raise: the tail is padded with masked
    repeat rows and dropped on gather (full coverage incl. the warning
    event lives in tests/test_bucketing.py)."""
    h = np.ones(3)
    out = sweep_cases(toy_case, h, h, h, mesh=mesh2())
    assert np.asarray(out["X0"]).shape == (3,)
    out = sweep_cases_full(toy_full, dict(Hs=h, Tp=h), mesh=mesh2())
    assert np.asarray(out["X0"]).shape == (3,)


def test_ragged_case_dict_rejected(tmp_path):
    ragged = dict(Hs=np.ones(8), Tp=np.ones(6))
    with pytest.raises(ValueError, match="ragged"):
        run_sweep_checkpointed_full(toy_full, ragged,
                                    str(tmp_path / "sweep"),
                                    shard_size=4, mesh=mesh2())
    with pytest.raises(ValueError, match="ragged"):
        sweep_cases_full(toy_full, ragged, mesh=mesh2())


# --------------------------------------------------- legacy driver parity


def test_legacy_checkpointed_driver_shares_runtime(tmp_path, log_path):
    rng = np.random.default_rng(12)
    h, t, b = rng.random(10), rng.random(10) + 8, rng.random(10)
    out_dir = str(tmp_path / "sweep")
    out1 = run_sweep_checkpointed(toy_case, h, t, b, out_dir,
                                  shard_size=4, mesh=mesh2())
    np.testing.assert_allclose(out1["X0"], h + t + b)
    assert os.path.exists(os.path.join(out_dir, "manifest.json"))
    faults.truncate_file(os.path.join(out_dir, "shard_0002.npz"))
    out2 = run_sweep_checkpointed(toy_case, h, t, b, out_dir,
                                  shard_size=4, mesh=mesh2())
    for k in out1:
        assert np.array_equal(out1[k], out2[k])
    with pytest.raises(resilience.ManifestMismatchError):
        run_sweep_checkpointed(toy_case, h + 1, t, b, out_dir,
                               shard_size=4, mesh=mesh2())


# ----------------------------------------------------- backend degradation


def test_backend_fallback_event_and_sweep_completes(tmp_path, log_path):
    cases = _cases(4, seed=13)
    with faults.inject("unhealthy:backend_probe:1"):
        mesh = resilience.resolve_mesh(make_mesh)
    assert mesh.devices.size >= 1
    evs = _events(log_path, "backend_fallback")
    assert len(evs) == 1 and evs[0]["to_platform"] == "cpu"
    out = run_sweep_checkpointed_full(toy_full, cases,
                                      str(tmp_path / "sweep"),
                                      shard_size=4, mesh=mesh)
    assert out["PSD"].shape == (4, 3)


# ------------------------------------------------------------- structlog


def test_log_event_survives_non_serializable_payload(tmp_path, monkeypatch):
    p = str(tmp_path / "events.jsonl")
    monkeypatch.setenv("RAFT_TPU_LOG", p)

    class Opaque:
        def __repr__(self):
            return "<opaque>"

    log_event("weird_payload", obj=Opaque(), arr_dtype=np.dtype("f8"),
              exc=ValueError("boom"))
    (rec,) = _events(p, "weird_payload")
    assert rec["obj"] == "<opaque>"
    assert rec["exc"] == "boom"


# ------------------------------------------------------------ fault specs


def test_fault_spec_parsing_and_counts():
    with faults.inject("transient:somewhere:2"):
        assert faults.take("transient", "somewhere")
        assert faults.take("transient", "somewhere")
        assert not faults.take("transient", "somewhere")  # exhausted
    assert not faults.take("transient", "somewhere")  # disarmed on exit
    with pytest.raises(ValueError):
        faults.inject("justakind")


def test_fault_env_var_arming(monkeypatch):
    monkeypatch.setenv("RAFT_TPU_FAULTS", "nan:envsite:1")
    assert faults.take("nan", "envsite")
    assert not faults.take("nan", "envsite")
    monkeypatch.setenv("RAFT_TPU_FAULTS", "")
    assert not faults.take("nan", "envsite")
