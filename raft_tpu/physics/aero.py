"""Rotor aerodynamics: blade-element-momentum theory in jax.

A TPU-native replacement for the CCBlade dependency the reference uses
(imported at ``/root/reference/raft/raft_rotor.py:18-21``; consumed via
``Rotor.runCCBlade`` :717-786 and ``Rotor.calcAero`` :806-1028).

Formulation: the single-residual BEM parameterisation of Ning (2014),
"A simple solution method to the blade element momentum equations with
guaranteed convergence" (the same method CCBlade implements), with
Prandtl hub/tip losses and the Buhl high-induction correction, blade
precurve/presweep curvature, power-law shear, and shaft tilt / nacelle
yaw inflow geometry, azimuthally averaged over nSector positions.

TPU-first design:
* the residual is solved by a fixed-count bisection (guaranteed bracket
  per Ning 2014) refined by Newton steps — trace-static, vmapped over
  (azimuth x blade element);
* load derivatives (dT/dU, dQ/dOmega, ...) come from ``jax.jacfwd``
  through the converged Newton refinement (implicit-function exactness)
  instead of CCBlade's hand-coded adjoints;
* the whole rotor evaluation is differentiable and batchable over wind
  speeds — a power/thrust curve is one ``vmap``.

The aero-servo coupling (PI pitch/torque control transfer functions,
raft_rotor.py:899-1012) and the IEC Kaimal rotor-averaged turbulence
spectrum (raft_rotor.py:1148-1246, pyIECWind.py:8-79) are implemented
at the bottom of this module.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.structure.schema import coerce
from raft_tpu.utils.dtypes import compute_dtypes

RAD2DEG = 57.29577951308232
RPM2RADPS = 0.1047  # reference's conversion constant (helpers.py:30-33)


# ------------------------------------------------------------------ build

@dataclass
class RotorAeroModel:
    """Static blade/airfoil/operating-schedule data for one rotor."""

    B: int
    Rhub: float
    Rtip: float
    precone: float          # [rad]
    shaft_tilt: float       # [rad]
    rho: float
    mu: float
    shearExp: float
    hubHt: float
    nSector: int

    r: np.ndarray           # (nr,) element radii
    chord: np.ndarray
    theta_deg: np.ndarray
    precurve: np.ndarray
    presweep: np.ndarray
    precurveTip: float
    presweepTip: float

    aoa_deg: np.ndarray     # (n_aoa,)
    cl: np.ndarray          # (nr, n_aoa)
    cd: np.ndarray          # (nr, n_aoa)

    U_sched: np.ndarray     # operating schedule (incl. parked extension)
    Omega_sched: np.ndarray # [rpm]
    pitch_sched: np.ndarray # [deg]

    cpmin: np.ndarray | None = None  # (nr, n_aoa) min pressure coefficient

    # control gains (aeroServoMod == 2)
    kp_0: np.ndarray | None = None
    ki_0: np.ndarray | None = None
    k_float: float = 0.0
    kp_tau: float = 0.0
    ki_tau: float = 0.0
    Ng: float = 1.0
    I_drivetrain: float = 0.0


def build_rotor_aero(turbine, ir=0, submerged=False):
    """Parse the turbine dict into a RotorAeroModel.

    Mirrors the airfoil/station processing of Rotor.__init__
    (raft_rotor.py:194-388): polars re-gridded onto a 200-point angle-
    of-attack grid and pchip-interpolated across relative thickness.
    """
    from scipy.interpolate import PchipInterpolator

    nrotors = turbine.get("nrotors", 1)
    blade = turbine["blade"]
    blade = blade[ir] if isinstance(blade, list) else blade

    nBlades = int(coerce(turbine, "nBlades", shape=nrotors, dtype=int)[ir])
    Rhub = coerce(turbine, "Rhub", shape=nrotors)[ir]
    # sign flip: the reference passes -precone to CCBlade (raft_rotor.py:363)
    precone = -coerce(turbine, "precone", shape=nrotors)[ir] * np.pi / 180
    shaft_tilt = coerce(turbine, "shaft_tilt", shape=nrotors)[ir] * np.pi / 180
    Rtip = float(blade["Rtip"])
    hubHt = coerce(turbine, "hHub", shape=nrotors, default=coerce(turbine, "Zhub", shape=nrotors, default=100)[ir])[ir]

    # angle-of-attack grid (raft_rotor.py:202-206)
    n_aoa = 200
    aoa = np.unique(np.hstack([
        np.linspace(-180, -30, int(n_aoa / 4 + 1)),
        np.linspace(-30, 30, int(n_aoa / 2)),
        np.linspace(30, 180, int(n_aoa / 4 + 1)),
    ]))

    airfoils = turbine["airfoils"]
    n_af = len(airfoils)
    names = [a["name"] for a in airfoils]
    thick = np.array([a["relative_thickness"] for a in airfoils])
    cl = np.zeros((n_af, len(aoa)))
    cd = np.zeros((n_af, len(aoa)))
    has_cpmin = all(len(np.array(a["data"])[0]) > 4 for a in airfoils)
    cpm = np.zeros((n_af, len(aoa))) if has_cpmin else None
    for i, a in enumerate(airfoils):
        tab = np.array(a["data"])
        cl[i] = np.interp(aoa, tab[:, 0], tab[:, 1])
        cd[i] = np.interp(aoa, tab[:, 0], tab[:, 2])
        if has_cpmin:
            cpm[i] = np.interp(aoa, tab[:, 0], tab[:, 4])
        # enforce +/-180 deg continuity (raft_rotor.py:243-251)
        cl[i, 0] = cl[i, -1]
        cd[i, 0] = cd[i, -1]

    station_airfoil = [b for [a, b] in blade["airfoils"]]
    station_position = np.array([a for [a, b] in blade["airfoils"]])
    nSt = len(station_airfoil)
    st_thick = np.zeros(nSt)
    st_cl = np.zeros((nSt, len(aoa)))
    st_cd = np.zeros((nSt, len(aoa)))
    st_cpm = np.zeros((nSt, len(aoa))) if has_cpmin else None
    for i in range(nSt):
        j = names.index(station_airfoil[i])
        st_thick[i] = thick[j]
        st_cl[i] = cl[j]
        st_cd[i] = cd[j]
        if has_cpmin:
            st_cpm[i] = cpm[j]

    nSector = int(coerce(blade, "nSector", default=4))
    nr = int(coerce(blade, "nr", default=20))
    grid = np.linspace(0.0, 1.0, nr, endpoint=False) + 0.5 / nr

    # pchip interpolation across relative thickness (raft_rotor.py:286-311)
    rthick = PchipInterpolator(station_position, st_thick)(grid)
    r_thick_unique, idx = np.unique(st_thick, return_index=True)
    cl_interp = np.flip(
        PchipInterpolator(r_thick_unique, st_cl[idx])(np.flip(rthick)), axis=0
    )
    cd_interp = np.flip(
        PchipInterpolator(r_thick_unique, st_cd[idx])(np.flip(rthick)), axis=0
    )
    cpm_interp = None
    if has_cpmin:
        cpm_interp = np.flip(
            PchipInterpolator(r_thick_unique, st_cpm[idx])(np.flip(rthick)),
            axis=0)

    # CCBlade's CCAirfoil evaluates the polars with a CUBIC spline in
    # angle of attack; approximate that in-trace by resampling the
    # station polars through a cubic spline onto a 6x-dense grid and
    # interpolating linearly there (sub-0.1% of the spline everywhere)
    from scipy.interpolate import CubicSpline

    aoa_dense = np.unique(np.concatenate([
        np.linspace(-180, -30, 6 * int(n_aoa / 4) + 1),
        np.linspace(-30, 30, 6 * int(n_aoa / 2)),
        np.linspace(30, 180, 6 * int(n_aoa / 4) + 1)]))
    cl_dense = np.stack([CubicSpline(aoa, c)(aoa_dense) for c in cl_interp])
    cd_dense = np.stack([CubicSpline(aoa, c)(aoa_dense) for c in cd_interp])
    cpm_dense = None
    if has_cpmin:
        cpm_dense = np.stack([CubicSpline(aoa, c)(aoa_dense) for c in cpm_interp])

    geom = np.array(blade["geometry"])
    dr = (Rtip - Rhub) / nr
    blade_r = np.linspace(Rhub, Rtip, nr, endpoint=False) + dr / 2
    chord = np.interp(blade_r, geom[:, 0], geom[:, 1])
    theta = np.interp(blade_r, geom[:, 0], geom[:, 2])
    precurve = np.interp(blade_r, geom[:, 0], geom[:, 3])
    presweep = np.interp(blade_r, geom[:, 0], geom[:, 4])

    wt_ops = turbine["wt_ops"]
    wt_ops = wt_ops[ir] if isinstance(wt_ops, list) else wt_ops
    U = np.asarray(coerce(wt_ops, "v", shape=-1), dtype=float)
    Om = np.asarray(coerce(wt_ops, "omega_op", shape=-1), dtype=float)
    pit = np.asarray(coerce(wt_ops, "pitch_op", shape=-1), dtype=float)
    # parked extension (raft_rotor.py:171-174)
    U = np.r_[U, U.max() * 1.4, 100]
    Om = np.r_[Om, 0, 0]
    pit = np.r_[pit, 90, 90]

    # submerged (MHK) rotors use water properties (raft_rotor.py:338-345)
    if submerged:
        rho_fl = float(turbine.get("rho_water", 1025.0))
        mu_fl = float(turbine.get("mu_water", 1.0e-3))
        shear_fl = float(turbine.get("shearExp_water", 0.12))
    else:
        rho_fl = float(turbine.get("rho_air", 1.225))
        mu_fl = float(turbine.get("mu_air", 1.81e-5))
        shear_fl = float(turbine.get("shearExp_air", 0.12))
    model = RotorAeroModel(
        B=nBlades, Rhub=Rhub, Rtip=Rtip, precone=precone, shaft_tilt=shaft_tilt,
        rho=rho_fl,
        mu=mu_fl,
        shearExp=shear_fl,
        hubHt=float(hubHt), nSector=nSector,
        r=blade_r, chord=chord, theta_deg=theta,
        precurve=precurve, presweep=presweep,
        precurveTip=float(blade.get("precurveTip", 0.0)),
        presweepTip=float(blade.get("presweepTip", 0.0)),
        aoa_deg=aoa_dense, cl=cl_dense, cd=cd_dense, cpmin=cpm_dense,
        U_sched=U, Omega_sched=Om, pitch_sched=pit,
    )

    # control gains (raft_rotor.py:788-802), optional
    if "pitch_control" in turbine:
        pc = turbine["pitch_control"]
        pc_angles = np.array(pc["GS_Angles"]) * RAD2DEG
        model.kp_0 = np.interp(pit, pc_angles, pc["GS_Kp"], left=0, right=0)
        model.ki_0 = np.interp(pit, pc_angles, pc["GS_Ki"], left=0, right=0)
        model.k_float = -pc["Fl_Kp"]
    if "torque_control" in turbine:
        model.kp_tau = -turbine["torque_control"]["VS_KP"]
        model.ki_tau = -turbine["torque_control"]["VS_KI"]
        model.Ng = turbine.get("gear_ratio", 1.0)
    model.I_drivetrain = float(coerce(turbine, "I_drivetrain",
                                      shape=nrotors, default=0.0)[ir])
    return model


def _curvature(r, precurve, presweep, precone):
    """Azimuthal-frame element coordinates, local cone angles and arc
    length — CCBlade's curvature definition."""
    x_az = -r * np.sin(precone) + precurve * np.cos(precone)
    z_az = r * np.cos(precone) + precurve * np.sin(precone)
    y_az = presweep.copy() if hasattr(presweep, "copy") else presweep

    n = len(r)
    cone = np.zeros(n)
    cone[0] = np.arctan2(-(x_az[1] - x_az[0]), z_az[1] - z_az[0])
    cone[1:-1] = 0.5 * (
        np.arctan2(-(x_az[1:-1] - x_az[:-2]), z_az[1:-1] - z_az[:-2])
        + np.arctan2(-(x_az[2:] - x_az[1:-1]), z_az[2:] - z_az[1:-1])
    )
    cone[-1] = np.arctan2(-(x_az[-1] - x_az[-2]), z_az[-1] - z_az[-2])

    s = np.zeros(n)
    s[0] = r[0]
    s[1:] = s[0] + np.cumsum(
        np.sqrt(np.diff(x_az) ** 2 + np.diff(y_az) ** 2 + np.diff(z_az) ** 2)
    )
    return x_az, y_az, z_az, cone, s


# ------------------------------------------------------------------- BEMT

def _solve_phi(Vx, Vy, sigma_p, theta_rad, loss_const_tip, loss_const_hub,
               cl_tab, cd_tab, aoa_rad, n_bisect=50, n_newton=4):
    """Solve the Ning (2014) residual for the inflow angle phi.

    All inputs per blade element (scalars / (n_aoa,) tables); returns
    (phi, a, ap).  Bisection on (eps, pi/2) — the guaranteed bracket for
    Vx, Vy > 0 — refined with differentiable Newton steps.
    """

    def _signed_floor(x, floor):
        s = jnp.where(x < 0, -1.0, 1.0)  # sign-preserving divide guard
        return s * jnp.maximum(jnp.abs(x), floor)

    def induction(phi):
        """Returns (a, ap, one_m_a, one_p_ap).

        (1-a) and (1+ap) are computed in algebraically-exact reciprocal
        forms — 1-a = 1/(1+k) (momentum), (3F-5/3+sqrt(g2))/g3 (Buhl),
        1/(1-k) (prop brake); 1+ap = 1/(1-kp) — NOT as 1 minus the
        induction factor.  At bracket endpoints k is O(1e10) and a
        rounds to exactly 1 in float32, so the subtractive form loses
        the residual's SIGN, sending the bracket selection to the wrong
        branch (measured: outer elements converging to phi=pi at
        feathered operating points under float32)."""
        sphi, cphi = jnp.sin(phi), jnp.cos(phi)
        sphi_safe = _signed_floor(sphi, 1e-9)
        alpha = phi - theta_rad
        cl = jnp.interp(alpha, aoa_rad, cl_tab)
        cd = jnp.interp(alpha, aoa_rad, cd_tab)
        cn = cl * cphi + cd * sphi
        ct = cl * sphi - cd * cphi
        # Prandtl losses
        Ftip = 2 / jnp.pi * jnp.arccos(
            jnp.clip(jnp.exp(-loss_const_tip / jnp.abs(sphi_safe)), 0.0, 1.0))
        Fhub = 2 / jnp.pi * jnp.arccos(
            jnp.clip(jnp.exp(-loss_const_hub / jnp.abs(sphi_safe)), 0.0, 1.0))
        F = jnp.maximum(Ftip * Fhub, 1e-6)
        k = sigma_p * cn / (4.0 * F * sphi_safe**2)
        kp = sigma_p * ct / (4.0 * F * sphi_safe * cphi)
        # axial induction: momentum / Buhl empirical (phi>0), prop brake
        g2 = jnp.maximum(2 * F * k - F * (4.0 / 3 - F), 1e-12)
        g3 = 2 * F * k - (25.0 / 9 - 2 * F)
        # 1 - a_buhl = (g3 - g1 + sqrt(g2))/g3 with g3-g1 = F - 5/3 exactly;
        # at g3 -> 0 both vanish together and the limit is 1/(2 sqrt(g2))
        # (the reference's special case), used explicitly near zero
        one_m_a_buhl = jnp.where(
            jnp.abs(g3) < 1e-6, 1.0 / (2.0 * jnp.sqrt(g2)),
            (F - 5.0 / 3 + jnp.sqrt(g2)) / _signed_floor(g3, 1e-6))
        one_m_a_mom = 1.0 / _signed_floor(1.0 + k, 1e-12)
        one_m_a_pos = jnp.where(k <= 2.0 / 3, one_m_a_mom, one_m_a_buhl)
        # brake branch: 1 - k/(k-1) = 1/(1-k)
        one_m_a_brake = jnp.where(k > 1.0, 1.0 / _signed_floor(1.0 - k, 1e-12), 1.0)
        one_m_a = jnp.where(phi > 0, one_m_a_pos, one_m_a_brake)
        one_p_ap = 1.0 / _signed_floor(1.0 - kp, 1e-12)
        return 1.0 - one_m_a, one_p_ap - 1.0, one_m_a, one_p_ap

    def residual(phi):
        _, _, one_m_a, one_p_ap = induction(phi)
        sphi, cphi = jnp.sin(phi), jnp.cos(phi)
        one_m_a = _signed_floor(one_m_a, 1e-12)
        one_p_ap = _signed_floor(one_p_ap, 1e-12)
        return sphi / one_m_a - Vx / Vy * cphi / one_p_ap

    eps = 1e-6

    def solve(f, phi0):
        """Primal-only solve: bracketed bisection + Newton refinement.
        Runs OUTSIDE the differentiation path (lax.custom_root)."""
        lo = jnp.asarray(eps, dtype=phi0.dtype)
        hi = jnp.asarray(jnp.pi / 2, dtype=phi0.dtype)
        # fall back to the propeller-brake bracket if no sign change
        r_lo, r_hi = f(lo), f(hi)
        use_main = r_lo * r_hi <= 0
        lo2 = jnp.asarray(jnp.pi / 2, dtype=phi0.dtype)
        hi2 = jnp.asarray(jnp.pi - eps, dtype=phi0.dtype)
        lo = jnp.where(use_main, lo, lo2)
        hi = jnp.where(use_main, hi, hi2)

        def bis(carry, _):
            lo, hi = carry
            mid = 0.5 * (lo + hi)
            same = f(mid) * f(lo) > 0
            return (jnp.where(same, mid, lo), jnp.where(same, hi, mid)), None

        (lo, hi), _ = jax.lax.scan(bis, (lo, hi), None, length=n_bisect)
        phi = 0.5 * (lo + hi)
        df = jax.grad(f)
        for _ in range(n_newton):
            d = df(phi)
            d = jnp.where(jnp.abs(d) < 1e-12, 1e-12, d)
            phi = phi - jnp.clip(f(phi) / d, -0.1, 0.1)
        return phi

    def tangent_solve(g, y):
        # scalar linear solve: dphi = y / R'(phi*), with a signed floor on
        # the slope so grazing roots cannot blow the tangents up
        slope = g(jnp.ones_like(y))
        slope = jnp.where(jnp.abs(slope) < 1e-8,
                          jnp.where(slope < 0, -1e-8, 1e-8), slope)
        return y / slope

    # Implicit differentiation of the converged root (IFT): derivatives
    # never see the bisection/Newton iterates.  Differentiating THROUGH
    # the refinement chain (jacfwd over scan+Newton) amplifies float32
    # roundoff catastrophically — measured dT/dU errors >10x and NaNs at
    # feathered operating points — while the IFT tangent is O(eps).
    phi = jax.lax.custom_root(residual, jnp.asarray(0.8, dtype=jnp.result_type(Vx, Vy, float)), solve, tangent_solve)

    a, ap, one_m_a, one_p_ap = induction(phi)
    return phi, a, ap, one_m_a, one_p_ap


def _wind_components(rot: RotorAeroModel, Uinf, Omega_radps, azimuth_rad,
                     tilt, yaw, x_az, y_az, z_az, cone):
    """Element inflow velocities in the blade-aligned frame (CCBlade
    wind-component geometry with shear, tilt, yaw, azimuth, curvature)."""
    sy, cy = jnp.sin(yaw), jnp.cos(yaw)
    st, ct = jnp.sin(tilt), jnp.cos(tilt)
    sa, ca = jnp.sin(azimuth_rad), jnp.cos(azimuth_rad)
    sc, cc = jnp.sin(cone), jnp.cos(cone)

    height = (y_az * sa + z_az * ca) * ct - x_az * st
    V = Uinf * (1.0 + height / rot.hubHt) ** rot.shearExp

    Vwind_x = V * ((cy * st * ca + sy * sa) * sc + cy * ct * cc)
    Vwind_y = V * (cy * st * sa - sy * ca)
    Vrot_x = -Omega_radps * y_az * sc
    Vrot_y = Omega_radps * z_az
    return Vwind_x + Vrot_x, Vwind_y + Vrot_y


def rotor_loads(rot: RotorAeroModel, Uinf, Omega_rpm, pitch_deg, tilt, yaw):
    """Azimuthally averaged hub loads [T, Y, Z, Q, My, Mz].

    Equivalent of CCBlade.evaluate consumed at raft_rotor.py:744; tilt
    and yaw in radians (the reference passes radians at runtime).
    """
    x_az, y_az, z_az, cone, s = _curvature(rot.r, rot.precurve, rot.presweep, rot.precone)
    x_az, y_az, z_az, cone = map(jnp.asarray, (x_az, y_az, z_az, cone))
    # CCBlade integrates the distributed loads over the element stations
    # themselves (np.trapz over r/s with NO zero end-padding); matching
    # that scheme is required for golden-level load parity at nr=20
    xf, yf, zf, conef, sf = x_az, y_az, z_az, cone, s

    Omega = Omega_rpm * jnp.pi / 30.0
    theta_rad = jnp.deg2rad(rot.theta_deg + pitch_deg)
    sigma_p = rot.B * rot.chord / (2.0 * jnp.pi * rot.r)
    lc_tip = rot.B / 2.0 * (rot.Rtip - rot.r) / rot.r
    lc_hub = rot.B / 2.0 * (rot.r - rot.Rhub) / rot.Rhub
    aoa_rad = jnp.deg2rad(rot.aoa_deg)

    azimuths = jnp.arange(rot.nSector) * (2 * jnp.pi / rot.nSector)

    def per_element(Vx, Vy, th, sg, lt, lh, cl_t, cd_t, ch):
        phi, a, ap, one_m_a, one_p_ap = _solve_phi(
            Vx, Vy, sg, th, lt, lh, cl_t, cd_t, aoa_rad)
        sphi, cphi = jnp.sin(phi), jnp.cos(phi)
        alpha = phi - th
        cl = jnp.interp(alpha, aoa_rad, cl_t)
        cd = jnp.interp(alpha, aoa_rad, cd_t)
        cn = cl * cphi + cd * sphi
        ct_ = cl * sphi - cd * cphi
        W2 = (Vx * one_m_a) ** 2 + (Vy * one_p_ap) ** 2
        qdyn = 0.5 * rot.rho * W2 * ch
        return cn * qdyn, ct_ * qdyn  # Np, Tp per unit span

    def per_azimuth(az):
        Vx, Vy = _wind_components(rot, Uinf, Omega, az, tilt, yaw,
                                  x_az, y_az, z_az, cone)
        Np, Tp = jax.vmap(per_element)(
            Vx, Vy, theta_rad, jnp.asarray(sigma_p), jnp.asarray(lc_tip),
            jnp.asarray(lc_hub), jnp.asarray(rot.cl), jnp.asarray(rot.cd),
            jnp.asarray(rot.chord),
        )
        Npf = Np
        Tpf = Tp
        ccf = jnp.cos(jnp.asarray(conef))
        scf = jnp.sin(jnp.asarray(conef))
        sfj = jnp.asarray(sf)

        # force per unit span in the azimuthal frame
        fx = Npf * ccf
        fy = Tpf
        fz = Npf * scf
        Fx = jnp.trapezoid(fx, sfj)
        Fy = jnp.trapezoid(fy, sfj)
        Fz = jnp.trapezoid(fz, sfj)
        # moment per unit span: r_az x f
        xfj, yfj, zfj = jnp.asarray(xf), jnp.asarray(yf), jnp.asarray(zf)
        mx = yfj * fz - zfj * fy
        my = zfj * fx - xfj * fz
        mz = xfj * fy - yfj * fx
        Mx = jnp.trapezoid(mx, sfj)
        My = jnp.trapezoid(my, sfj)
        Mz = jnp.trapezoid(mz, sfj)

        # rotate the azimuthal frame into the (non-rotating) hub frame
        sa, ca = jnp.sin(az), jnp.cos(az)
        F_h = jnp.stack([Fx, ca * Fy - sa * Fz, sa * Fy + ca * Fz])
        M_h = jnp.stack([Mx, ca * My - sa * Mz, sa * My + ca * Mz])
        return F_h, M_h

    F_h, M_h = jax.vmap(per_azimuth)(azimuths)
    F = rot.B * jnp.mean(F_h, axis=0)
    M = rot.B * jnp.mean(M_h, axis=0)
    # CCBlade load naming: T (thrust), Y, Z; Q (shaft torque), My, Mz.
    # The shaft torque is the negative x-moment of the aero reaction.
    return jnp.stack([F[0], F[1], F[2], -M[0], M[1], M[2]])


def rotor_loads_and_derivs(rot, Uinf, Omega_rpm, pitch_deg, tilt, yaw):
    """Loads plus (dT, dQ)/(dU, dOmega_rpm, dpitch_deg) via jacfwd."""
    f = lambda u, o, p: rotor_loads(rot, u, o, p, tilt, yaw)
    loads = f(Uinf, Omega_rpm, pitch_deg)
    grads = jax.jacfwd(lambda args: f(*args))((Uinf, Omega_rpm, pitch_deg))
    dT = jnp.stack([g[0] for g in grads])   # (3,) wrt U, Omega_rpm, pitch_deg
    dQ = jnp.stack([g[3] for g in grads])
    return loads, dT, dQ


def operating_point(rot: RotorAeroModel, Uhub):
    """Scheduled rotor speed and blade pitch (raft_rotor.py:734-736)."""
    Om = jnp.interp(Uhub, jnp.asarray(rot.U_sched), jnp.asarray(rot.Omega_sched))
    pit = jnp.interp(Uhub, jnp.asarray(rot.U_sched), jnp.asarray(rot.pitch_sched))
    return Om, pit


# ------------------------------------------------------------- calc aero

def calc_aero(rot: RotorAeroModel, rprops, case, w, speed=None,
              platform_heading=0.0, current=False):
    """Aero-servo coefficients about the rotor node in global frame.

    Equivalent of Rotor.calcAero (raft_rotor.py:806-1028) for
    aeroServoMod 1 (no control) and 2 (PI pitch/torque control):
    returns (f0 (6,), f (6,nw) complex, a (6,6,nw), b (6,6,nw)).

    rprops : RotorProps (geometry/orientation); case : load-case dict;
    w : (nw,) frequency grid.
    """
    import numpy as np

    from raft_tpu.ops import transforms as tf

    w = np.asarray(w)
    nw = len(w)
    if current:  # submerged (MHK) rotor driven by the current
        if speed is None:
            speed = float(coerce(case, "current_speed", shape=0, default=1.0))
        heading = float(coerce(case, "current_heading", shape=0, default=0.0))
    else:
        if speed is None:
            speed = float(coerce(case, "wind_speed", shape=0, default=10))
        heading = float(coerce(case, "wind_heading", shape=0, default=0.0))
    yaw_command = float(coerce(case, "yaw_misalign", shape=0, default=0.0))
    turbine_heading = float(coerce(case, "turbine_heading", shape=0, default=0.0))
    yaw_mode = getattr(rprops, "yaw_mode", 0)

    inflow_heading = np.radians(heading)
    # setYaw (raft_rotor.py:425-478)
    if yaw_mode == 0:
        yaw = inflow_heading - platform_heading + np.radians(yaw_command)
    elif yaw_mode == 1:
        yaw = np.radians(turbine_heading) - platform_heading
    elif yaw_mode == 2:
        yaw = np.radians(yaw_command)
    elif yaw_mode == 3:
        yaw = np.radians(yaw_command) - platform_heading
    else:
        raise ValueError("unsupported yaw_mode")

    R_q_rel = np.asarray(tf.rotation_matrix(0.0, -rprops.shaft_tilt,
                                            rprops.shaft_toe + yaw))
    R_ptfm = np.eye(3)  # platform rotation handled upstream for statics
    R_q = R_q_rel @ R_ptfm
    q = R_q_rel @ np.array([1.0, 0.0, 0.0])

    yaw_misalign = np.arctan2(q[1], q[0]) - inflow_heading
    turbine_tilt = np.arctan2(q[2], np.hypot(q[0], q[1]))

    Om, pit = operating_point(rot, speed)
    loads, dT, dQ = rotor_loads_and_derivs(
        rot, float(speed), float(Om), float(pit), -float(turbine_tilt),
        float(yaw_misalign))
    loads = np.asarray(loads)
    dT = np.asarray(dT)
    dQ = np.asarray(dQ)

    dT_dU, dT_dOm, dT_dPi = dT[0], dT[1] / RPM2RADPS, dT[2] * RAD2DEG
    dQ_dU, dQ_dOm, dQ_dPi = dQ[0], dQ[1] / RPM2RADPS, dQ[2] * RAD2DEG

    f0 = np.zeros(6)
    f0[:3] = R_q @ loads[:3]
    f0[3:] = R_q @ loads[3:]

    # rotor-averaged turbulence -> inflow amplitude spectrum
    turbulence = case.get("current_turbulence", 0.0) if current else case.get("turbulence", 0.0)
    hubHt = rprops.Zhub
    S_rot = kaimal_rot_psd(w, speed, turbulence, hubHt, rot.Rtip)
    V_w = np.sqrt(2 * S_rot * (w[1] - w[0])).astype(np.complex128)

    a = np.zeros((6, 6, nw))
    b = np.zeros((6, 6, nw))
    f = np.zeros((6, nw), dtype=np.complex128)
    # rotor-channel transfer-function data (raft_rotor.py:926-947,
    # consumed by saveTurbineOutputs raft_fowt.py:2630-2688)
    chan = dict(C=np.zeros(nw, dtype=np.complex128), kp_beta=0.0, ki_beta=0.0,
                kp_tau=0.0, ki_tau=0.0,
                aero_torque=float(loads[3]),
                aero_power=float(loads[3] * Om * 2 * np.pi / 60.0))

    if rprops.aeroServoMod == 1:
        b_in = np.zeros((6, 6, nw))
        b_in[0, 0, :] = dT_dU
        f_in = np.zeros((6, nw), dtype=np.complex128)
        f_in[0, :] = dT_dU * V_w
        for iw in range(nw):
            b[:, :, iw] = np.asarray(tf.rotate_matrix_6(b_in[:, :, iw], R_q))
        f[:3, :] = R_q @ f_in[:3, :]
    elif rprops.aeroServoMod == 2:
        kp_beta = -np.interp(speed, rot.U_sched, rot.kp_0)
        ki_beta = -np.interp(speed, rot.U_sched, rot.ki_0)
        kp_tau = rot.kp_tau * (kp_beta == 0)
        ki_tau = rot.ki_tau * (ki_beta == 0)
        zhub = rprops.Zhub
        # characteristic denominator + azimuth transfer function
        # (raft_rotor.py:926-931): phi_w = C * XiHub in the outputs stage
        Dden = (rot.I_drivetrain * w**2
                + (dQ_dOm + kp_beta * dQ_dPi - rot.Ng * kp_tau) * 1j * w
                + ki_beta * dQ_dPi - rot.Ng * ki_tau)
        chan.update(
            C=1j * w * (dQ_dU - rot.k_float * dQ_dPi / zhub) / Dden,
            kp_beta=float(kp_beta), ki_beta=float(ki_beta),
            kp_tau=float(kp_tau), ki_tau=float(ki_tau))
        # torque-to-thrust transfer function (raft_rotor.py:959-967)
        H_QT = ((dT_dOm + kp_beta * dT_dPi) * 1j * w + ki_beta * dT_dPi) / Dden
        f2 = (dT_dU - H_QT * dQ_dU) * V_w
        b2 = np.real(dT_dU - rot.k_float * dT_dPi / zhub
                     - H_QT * (dQ_dU - rot.k_float * dQ_dPi / zhub))
        a2 = np.real((dT_dU - rot.k_float * dT_dPi / zhub
                      - H_QT * (dQ_dU - rot.k_float * dQ_dPi / zhub)) / (1j * w))
        for iw in range(nw):
            a[:3, :3, iw] = R_q @ np.diag([a2[iw], 0, 0]) @ R_q.T
            b[:3, :3, iw] = R_q @ np.diag([b2[iw], 0, 0]) @ R_q.T
            f[:3, iw] = R_q @ np.array([f2[iw], 0, 0])

    # shift from hub to the rotor node (raft_rotor.py:1021-1026)
    r_off = q * rprops.overhang
    import jax.numpy as jnp

    f0 = np.asarray(tf.transform_force_6(jnp.asarray(f0), jnp.asarray(r_off)))
    for iw in range(nw):
        a[:, :, iw] = np.asarray(tf.translate_matrix_6to6(a[:, :, iw], r_off))
        b[:, :, iw] = np.asarray(tf.translate_matrix_6to6(b[:, :, iw], r_off))
        f[:, iw] = np.asarray(tf.transform_force_6(jnp.asarray(f[:, iw]), jnp.asarray(r_off)))
    return f0, f, a, b, dict(loads=loads, dT=dT, dQ=dQ, Omega_rpm=float(Om),
                             pitch_deg=float(pit), V_w=V_w, R_q=R_q, q=q,
                             **chan)


# -------------------------------------------- MHK: blade hydro + cavitation

def blade_hydro(turbine, ir, rprops, rho_water=1025.0, g=9.81, n_azimuth=None):
    """Build-time hydrodynamic summary of a SUBMERGED rotor's blades
    about the rotor node: added mass A (6,6), inertial-excitation
    I (6,6), buoyancy force/stiffness (Fvec (6,), Cmat (6,6)) and
    displaced volume.

    Equivalent of Rotor.calcHydroConstants + the blade-member
    buoyancy loop (raft_rotor.py:604-656, raft_fowt.py:937-1005):
    blade elements are rectangular members (chord x relative-thickness
    x chord cross-section) with airfoil added-mass coefficients,
    summed over the B blade azimuths.
    """
    from scipy.interpolate import PchipInterpolator

    blade = turbine["blade"]
    blade = blade[ir] if isinstance(blade, list) else blade
    nrotors = turbine.get("nrotors", 1)
    B = int(coerce(turbine, "nBlades", shape=nrotors, dtype=int)[ir])
    Rhub = float(coerce(turbine, "Rhub", shape=nrotors)[ir])
    Rtip = float(blade["Rtip"])
    nr = int(coerce(blade, "nr", default=20))
    dr = (Rtip - Rhub) / nr
    r_e = np.linspace(Rhub, Rtip, nr, endpoint=False) + dr / 2

    geom = np.array(blade["geometry"])
    chord = np.interp(r_e, geom[:, 0], geom[:, 1])
    twist = np.deg2rad(np.interp(r_e, geom[:, 0], geom[:, 2]))

    # station relative thickness + added-mass coefficients
    airfoils = turbine["airfoils"]
    names = [a["name"] for a in airfoils]
    thick = np.array([a["relative_thickness"] for a in airfoils])
    Ca_af = np.array([a.get("added_mass_coeff", [0.5, 1.0]) for a in airfoils])
    st_pos = np.array([a for [a, b] in blade["airfoils"]])
    st_thick = np.array([thick[names.index(b)] for [a, b] in blade["airfoils"]])
    st_Ca = np.array([Ca_af[names.index(b)] for [a, b] in blade["airfoils"]])
    grid = (r_e - Rhub) / (Rtip - Rhub)
    t_rel = PchipInterpolator(st_pos, st_thick)(grid)
    Ca_e = PchipInterpolator(st_pos, st_Ca)(grid)  # (nr, 2) [edge, flap]

    V_e = chord * (t_rel * chord) * dr  # rectangular cross-section volume

    azimuths = np.deg2rad(np.asarray(coerce(
        turbine, "azimuths", shape=-1,
        default=list(np.arange(B) * 360.0 / B)), dtype=float))

    R_q0 = np.asarray(rprops.R_q0)
    q_hub = R_q0 @ np.array([1.0, 0.0, 0.0])       # shaft axis (global)
    r_hub = np.asarray(rprops.q_rel) * rprops.overhang  # hub wrt rotor node

    A6 = np.zeros((6, 6))
    I6 = np.zeros((6, 6))
    Fvec = np.zeros(6)
    Cmat = np.zeros((6, 6))
    V_tot = 0.0
    from raft_tpu.ops import transforms as tf
    import jax.numpy as jnp

    for psi in azimuths:
        cpsi, spsi = np.cos(psi), np.sin(psi)
        for ie in range(nr):
            # span direction: 'up' blade rotated by psi about the shaft,
            # in the hub frame then to global
            u_loc = np.array([0.0, -spsi, cpsi])
            u = R_q0 @ u_loc
            e_t = np.cross(q_hub, u)
            e_t /= max(np.linalg.norm(e_t), 1e-12)
            th = twist[ie]
            p1 = e_t * np.cos(th) + q_hub * np.sin(th)   # chordwise
            p2 = np.cross(u, p1)                          # thickness dir
            r_el = r_hub + u * r_e[ie]

            zg = rprops.r_rel[2] + r_el[2]
            if zg >= 0:
                continue  # only submerged elements contribute
            A3 = rho_water * V_e[ie] * (
                Ca_e[ie, 0] * np.outer(p1, p1) + Ca_e[ie, 1] * np.outer(p2, p2))
            I3 = rho_water * V_e[ie] * (
                (1 + Ca_e[ie, 0]) * np.outer(p1, p1)
                + (1 + Ca_e[ie, 1]) * np.outer(p2, p2))
            A6 += np.asarray(tf.translate_matrix_3to6(
                jnp.asarray(A3), jnp.asarray(r_el)))
            H = np.asarray(tf.skew(jnp.asarray(r_el)))
            I6[:3, :3] += I3
            I6[3:, :3] += H.T @ I3
            W6, C6 = tf.weight_of_point_mass(
                -rho_water * V_e[ie], jnp.asarray(r_el), g=g)
            Fvec += np.asarray(W6)
            Cmat += np.asarray(C6)
            V_tot += V_e[ie]

    return dict(A_hydro=A6, I_hydro=I6, Fvec=Fvec, Cmat=Cmat, V=V_tot,
                r_hub=r_hub)


def calc_cavitation(rot: RotorAeroModel, rprops, case, Patm=101325.0,
                    Pvap=2300.0, rho=1025.0, g=9.81):
    """Cavitation margin per (blade, element) for a submerged rotor.

    Rotor.calcCavitation equivalent (raft_rotor.py:657-716):
    sigma_crit = (Patm + rho g |z| - Pvap) / (0.5 rho W^2) compared to
    -cpmin(alpha); negative margin = cavitation.  Requires cpmin polars
    (5th column of the airfoil data tables).
    """
    if rot.cpmin is None:
        return None
    speed = float(coerce(case, "current_speed", shape=0, default=1.0))
    Om, pit = operating_point(rot, speed)
    Om, pit = float(Om), float(pit)
    Omega = Om * np.pi / 30.0

    x_az, y_az, z_az, cone, _ = _curvature(rot.r, rot.precurve, rot.presweep,
                                           rot.precone)
    theta_r = np.deg2rad(rot.theta_deg + pit)
    sigma_p = rot.B * rot.chord / (2 * np.pi * rot.r)
    lct = rot.B / 2 * (rot.Rtip - rot.r) / rot.r
    lch = rot.B / 2 * (rot.r - rot.Rhub) / rot.Rhub
    aoa_rad = jnp.deg2rad(jnp.asarray(rot.aoa_deg))

    azimuths = np.arange(rot.nSector) * 2 * np.pi / rot.nSector
    cav = np.zeros((len(azimuths), len(rot.r)))
    for ia, az in enumerate(azimuths):
        Vx, Vy = _wind_components(rot, speed, Omega, az, -rprops.shaft_tilt,
                                  0.0, jnp.asarray(x_az), jnp.asarray(y_az),
                                  jnp.asarray(z_az), jnp.asarray(cone))
        for ie in range(len(rot.r)):
            phi, a, ap, _, _ = _solve_phi(
                Vx[ie], Vy[ie], sigma_p[ie], theta_r[ie],
                lct[ie], lch[ie], jnp.asarray(rot.cl[ie]),
                jnp.asarray(rot.cd[ie]), aoa_rad)
            phi, a, ap = float(phi), float(a), float(ap)
            W2 = (float(Vx[ie]) * (1 - a)) ** 2 + (float(Vy[ie]) * (1 + ap)) ** 2
            alpha = np.degrees(phi) - (rot.theta_deg[ie] + pit)
            cpmin_n = float(np.interp(alpha, rot.aoa_deg, rot.cpmin[ie]))
            # element depth: blade 'up' at azimuth 0, rotating about the
            # (tilted) shaft
            zrel = z_az[ie] * np.cos(az) * np.cos(rprops.shaft_tilt)
            depth = abs(rprops.Zhub + zrel)
            sigma_crit = (Patm + rho * g * depth - Pvap) / (0.5 * rho * max(W2, 1e-9))
            cav[ia, ie] = sigma_crit + cpmin_n
    return cav


# ------------------------------------------------- traced aero-servo path

_KAIMAL_TABLE = None


def _kaimal_G_table():
    """Build-time f64 tabulation of the special-function combination in
    the rotor-averaged Kaimal spectrum (raft_rotor.py:1243-1246):

        G(x) = L_1(x) - I_1(x) - 2/pi + (x/2) (-2 L_{-2}(x) + 2 I_2(x) + 1)

    The reference evaluates this directly with scipy (incl. its float64
    cancellation noise for x in ~[30, 100]); the traced path interpolates
    this dense log-spaced table instead, so the one scipy-only special
    function pair on the aero path becomes a constant tensor
    (SURVEY.md §7.3 hard-part 5)."""
    global _KAIMAL_TABLE
    if _KAIMAL_TABLE is None:
        from scipy.special import iv, modstruve

        x = np.logspace(-8, 5, 8192)
        with np.errstate(all="ignore"):
            G = (
                modstruve(1, x) - iv(1, x) - 2 / np.pi
                + (x / 2) * (-2 * modstruve(-2, x) + 2 * iv(2, x) + 1)
            )
        _KAIMAL_TABLE = (np.log(x), np.nan_to_num(G))
    return _KAIMAL_TABLE


def parse_turbulence(turbulence):
    """Static part of a case's turbulence spec.

    Returns (I_ref, V_ref_cls, TurbMod); I_ref is None when the spec is
    a numeric TI (which may then be a traced value)."""
    V_ref_cls = 50.0
    I_ref = None
    TurbMod = "NTM"
    if isinstance(turbulence, str):
        cls = ""
        ch = ""
        for ch in turbulence:
            if ch in ("I", "V"):
                cls += ch
            else:
                break
        if cls:
            I_ref = {"A+": 0.18, "A": 0.16, "B": 0.14, "C": 0.12}[ch]
            V_ref_cls = {"I": 50.0, "II": 42.5, "III": 37.5, "IV": 30.0}[cls]
            TurbMod = turbulence.split("_")[1]
        else:
            I_ref = None  # numeric string: TI value
    return I_ref, V_ref_cls, TurbMod


def kaimal_rot_psd_traced(w, V_ref, I_ref, hub_height, R_rot,
                          TurbMod="NTM", V_ref_cls=50.0):
    """Traced twin of :func:`kaimal_rot_psd`: V_ref and I_ref may be
    traced scalars; the special-function combination comes from the
    build-time table."""
    w = jnp.asarray(w)
    f = w / (2 * jnp.pi)
    HH = abs(float(hub_height))

    if TurbMod == "NTM":
        sigma_1 = I_ref * (0.75 * V_ref + 5.6)
    elif TurbMod == "ETM":
        V_ave = V_ref_cls * 0.2
        sigma_1 = 2 * I_ref * (0.072 * (V_ave / 2 + 3) * (V_ref / 2 - 4) + 10)
    elif TurbMod == "EWM":
        sigma_1 = 0.11 * V_ref
    else:
        raise ValueError(f"unsupported turbulence model {TurbMod}")

    L_1 = 0.7 * HH if HH <= 60 else 42.0
    L_u = 8.1 * L_1
    U = (4 * L_u / V_ref) * sigma_1**2 / ((1 + 6 * f * L_u / V_ref) ** (5.0 / 3.0))
    kappa = 12 * jnp.sqrt((f / V_ref) ** 2 + (0.12 / L_u) ** 2)
    t = R_rot * kappa
    logx, G = _kaimal_G_table()
    Gx = jnp.interp(jnp.log(jnp.maximum(2 * t, 1e-300)),
                    jnp.asarray(logx), jnp.asarray(G))
    t_safe = jnp.where(t == 0, 1.0, t)
    return jnp.where(t == 0, 0.0, 2 * U / t_safe**3 * Gx)


def calc_aero_traced(rot: RotorAeroModel, rprops, w, speed, heading_rad,
                     TI, yaw_command_rad=0.0, turbine_heading_rad=0.0,
                     turb_static=("NTM", 50.0)):
    """Fully traced aero-servo coefficients about the rotor node.

    jax twin of :func:`calc_aero` (Rotor.calcAero equivalent,
    raft_rotor.py:806-1028) with ``speed``, ``heading_rad``, ``TI`` and
    ``yaw_command_rad`` as traced scalars, so the whole aero path jits
    and vmaps over load cases.  Returns
    (f0 (6,), f (6,nw) complex, a (6,6,nw), b (6,6,nw), B_gyro (6,6), q).
    """
    from raft_tpu.ops import transforms as tf

    w = jnp.asarray(w)
    nw = w.shape[0]
    dw = w[1] - w[0]
    yaw_mode = getattr(rprops, "yaw_mode", 0)

    # setYaw (raft_rotor.py:425-478); platform heading handled upstream
    if yaw_mode == 0:
        yaw = heading_rad + yaw_command_rad
    elif yaw_mode == 1:
        yaw = turbine_heading_rad
    elif yaw_mode == 2:
        yaw = yaw_command_rad
    elif yaw_mode == 3:
        yaw = yaw_command_rad
    else:
        raise ValueError("unsupported yaw_mode")

    R_q = tf.rotation_matrix(0.0, -rprops.shaft_tilt, rprops.shaft_toe + yaw)
    q = R_q @ jnp.array([1.0, 0.0, 0.0])
    yaw_misalign = jnp.arctan2(q[1], q[0]) - heading_rad
    turbine_tilt = jnp.arctan2(q[2], jnp.hypot(q[0], q[1]))

    Om, pit = operating_point(rot, speed)
    loads, dT, dQ = rotor_loads_and_derivs(rot, speed, Om, pit,
                                           -turbine_tilt, yaw_misalign)
    dT_dU, dT_dOm, dT_dPi = dT[0], dT[1] / RPM2RADPS, dT[2] * RAD2DEG
    dQ_dU, dQ_dOm, dQ_dPi = dQ[0], dQ[1] / RPM2RADPS, dQ[2] * RAD2DEG

    f0 = jnp.concatenate([R_q @ loads[:3], R_q @ loads[3:]])

    TurbMod, V_ref_cls = turb_static
    S_rot = kaimal_rot_psd_traced(w, speed, TI, rprops.Zhub, rot.Rtip,
                                  TurbMod=TurbMod, V_ref_cls=V_ref_cls)
    cdt = compute_dtypes(S_rot, w)[1]
    V_w = jnp.sqrt(2 * S_rot * dw).astype(cdt)

    # hub-frame coefficients reduce to the thrust-axis outer product
    qq = jnp.outer(q, q)  # (3,3)
    if rprops.aeroServoMod == 1:
        a2 = jnp.zeros(nw)
        b2 = jnp.full(nw, dT_dU)
        f2 = dT_dU * V_w
    elif rprops.aeroServoMod == 2:
        kp_beta = -jnp.interp(speed, jnp.asarray(rot.U_sched), jnp.asarray(rot.kp_0))
        ki_beta = -jnp.interp(speed, jnp.asarray(rot.U_sched), jnp.asarray(rot.ki_0))
        kp_tau = rot.kp_tau * (kp_beta == 0)
        ki_tau = rot.ki_tau * (ki_beta == 0)
        zhub = rprops.Zhub
        H_QT = ((dT_dOm + kp_beta * dT_dPi) * 1j * w + ki_beta * dT_dPi) / (
            rot.I_drivetrain * w**2
            + (dQ_dOm + kp_beta * dQ_dPi - rot.Ng * kp_tau) * 1j * w
            + ki_beta * dQ_dPi - rot.Ng * ki_tau
        )
        f2 = (dT_dU - H_QT * dQ_dU) * V_w
        resp = (dT_dU - rot.k_float * dT_dPi / zhub
                - H_QT * (dQ_dU - rot.k_float * dQ_dPi / zhub))
        b2 = jnp.real(resp)
        a2 = jnp.real(resp / (1j * w))
    else:
        a2 = jnp.zeros(nw)
        b2 = jnp.zeros(nw)
        f2 = jnp.zeros(nw, dtype=cdt)

    a6 = jnp.zeros((nw, 6, 6)).at[:, :3, :3].set(a2[:, None, None] * qq)
    b6 = jnp.zeros((nw, 6, 6)).at[:, :3, :3].set(b2[:, None, None] * qq)
    f6 = jnp.zeros((nw, 6), dtype=cdt).at[:, :3].set(f2[:, None] * q)

    # shift from hub to the rotor node (raft_rotor.py:1021-1026)
    r_off = q * rprops.overhang
    f0 = tf.transform_force_6(f0, r_off)
    a6 = tf.translate_matrix_6to6(a6, r_off)          # batched over ω
    b6 = tf.translate_matrix_6to6(b6, r_off)
    f6 = tf.transform_force_6(f6, r_off)

    # gyroscopic damping (raft_fowt.py:1569-1581)
    IO = q * (rprops.I_drivetrain * Om * 2 * jnp.pi / 60)
    B_gyro = jnp.zeros((6, 6)).at[3:, 3:].set(tf.skew(IO))

    return (f0, jnp.moveaxis(f6, 0, -1), jnp.moveaxis(a6, 0, -1),
            jnp.moveaxis(b6, 0, -1), B_gyro, q)


# -------------------------------------------------------- Kaimal spectrum

def kaimal_rot_psd(w, V_ref, turbulence, hub_height, R_rot):
    """Rotor-averaged IEC Kaimal PSD of the longitudinal turbulence
    [(m/s)^2/(rad/s)]; numpy/scipy twin of Rotor.IECKaimal
    (raft_rotor.py:1148-1246) for the untraced case-setup path.

    turbulence: TI fraction (float) or IEC class string like 'IB_NTM'.
    """
    from scipy.special import iv, modstruve

    f = np.asarray(w) / 2 / np.pi
    HH = abs(hub_height)

    V_ref_cls = 50.0
    I_ref = 0.16
    TurbMod = "NTM"
    if isinstance(turbulence, str):
        cls = ""
        for ch in turbulence:
            if ch in ("I", "V"):
                cls += ch
            else:
                break
        if not cls:
            turbulence = float(turbulence)
        else:
            categ = ch
            I_ref = {"A+": 0.18, "A": 0.16, "B": 0.14, "C": 0.12}[categ]
            V_ref_cls = {"I": 50.0, "II": 42.5, "III": 37.5, "IV": 30.0}[cls]
            TurbMod = turbulence.split("_")[1]
    if isinstance(turbulence, (int, float)):
        I_ref = float(turbulence)
        TurbMod = "NTM"

    if TurbMod == "NTM":
        sigma_1 = I_ref * (0.75 * V_ref + 5.6)
    elif TurbMod == "ETM":
        V_ave = V_ref_cls * 0.2
        sigma_1 = 2 * I_ref * (0.072 * (V_ave / 2 + 3) * (V_ref / 2 - 4) + 10)
    elif TurbMod == "EWM":
        sigma_1 = 0.11 * V_ref
    else:
        raise ValueError(f"unsupported turbulence model {TurbMod}")

    L_1 = 0.7 * HH if HH <= 60 else 42.0
    L_u = 8.1 * L_1
    U = (4 * L_u / V_ref) * sigma_1**2 / ((1 + 6 * f * L_u / V_ref) ** (5.0 / 3.0))

    kappa = 12 * np.sqrt((f / V_ref) ** 2 + (0.12 / L_u) ** 2)
    x = 2 * R_rot * kappa
    with np.errstate(all="ignore"):
        Rot = (2 * U / (R_rot * kappa) ** 3) * (
            modstruve(1, x) - iv(1, x) - 2 / np.pi
            + R_rot * kappa * (-2 * modstruve(-2, x) + 2 * iv(2, x) + 1)
        )
    Rot[np.isnan(Rot)] = 0
    return Rot
