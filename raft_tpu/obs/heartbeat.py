"""Periodic device heartbeats for OOM forensics and liveness.

A sweep that dies of device OOM (or silently slows down as live
buffers pile up) is much easier to debug when the event log carries
the memory picture that *preceded* the failure: quarantined rows and
``shard_oom_split`` events then have heartbeats around them showing
per-device ``memory_stats()``, the live-buffer count and how far the
sweep had progressed.

Enable with ``RAFT_TPU_HEARTBEAT_S=<seconds>`` (0 disables — the
default).  The sampler is one daemon thread emitting ``heartbeat``
JSONL events (the structlog sink is lock-protected for exactly this
reason) and updating the ``device_bytes_in_use`` /
``device_peak_bytes_in_use`` / ``live_arrays`` gauges, whose high
watermarks survive into the metrics snapshot (``heartbeat`` block of
the bench breakdown).

On backends without allocator stats (the CPU backend returns ``None``
from ``memory_stats()``) the heartbeat still reports the live-buffer
count and shard progress.  Sampling must never take down the run: all
jax access is wrapped, and failures are reported in-band on the event.
"""

from __future__ import annotations

import contextlib
import re
import threading

from raft_tpu.obs import metrics
from raft_tpu.utils import config
from raft_tpu.utils.structlog import log_event

_MEM_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
             "largest_free_block_bytes")

#: the procfs status file the RSS sampler reads — module-level so tests
#: (and exotic hosts) can point it elsewhere
PROC_STATUS_PATH = "/proc/self/status"

# one-shot availability memo: a host without procfs (macOS, some
# sandboxes) fails the FIRST open and is never probed again — the rss
# gauges simply stay absent, with no per-beat reopen or warning spam
_PROC_AVAILABLE = [True]


def sample_host_rss():
    """``(rss_bytes, peak_bytes)`` of THIS process from
    ``/proc/self/status`` (``VmRSS``/``VmHWM`` — no psutil dependency):
    the host-side memory picture device ``memory_stats()`` cannot see
    (packed design pytrees, result caches, the CPU backend's arrays all
    live in host RSS).  ``(None, None)`` on hosts without procfs —
    permanently, after one failed open."""
    if not _PROC_AVAILABLE[0]:
        return None, None
    try:
        with open(PROC_STATUS_PATH) as f:
            text = f.read()
    except OSError:
        _PROC_AVAILABLE[0] = False
        return None, None

    def field(name):
        m = re.search(rf"^{name}:\s+(\d+)\s*kB", text, re.MULTILINE)
        return int(m.group(1)) * 1024 if m else None

    return field("VmRSS"), field("VmHWM")


def sample_devices(devices=None):
    """One host-side sample: per-device memory stats + live-buffer
    count.  Returns ``(device_rows, live_arrays)``; safe to call from
    any thread once a backend is initialized."""
    import jax

    rows = []
    devs = devices if devices is not None else jax.devices()
    for d in devs:
        row = {"id": getattr(d, "id", None),
               "kind": getattr(d, "device_kind", "?")}
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats:
            for k in _MEM_KEYS:
                if k in stats:
                    row[k] = int(stats[k])
        rows.append(row)
    try:
        live = len(jax.live_arrays())
    except Exception:
        live = None
    return rows, live


class Heartbeat(threading.Thread):
    """Daemon sampler thread (use :func:`maybe_heartbeat` to gate on
    the flag).  ``progress`` is a plain dict the owner mutates in
    place (e.g. ``{"shards_done": 3, "n_shards": 20}``); each beat
    snapshots it, so the heartbeat stream doubles as a liveness probe
    for the sweep itself."""

    def __init__(self, interval_s, devices=None, progress=None,
                 worker_id=None, leases=None):
        super().__init__(name="raft-tpu-heartbeat", daemon=True)
        self.interval_s = float(interval_s)
        self.devices = list(devices) if devices is not None else None
        self.progress = progress
        # fabric liveness: each beat carries the worker id and the
        # shard leases it currently holds, so a captured stream shows
        # who was alive holding what when a lease later expired
        self.worker_id = worker_id
        self.leases = leases  # callable -> list of held shard ids
        self.beats = 0
        # NB: not `_stop` — threading.Thread uses that name internally
        self._stop_evt = threading.Event()
        self._final_done = False

    def beat(self):
        try:
            rows, live = sample_devices(self.devices)
        except Exception as e:  # backend gone mid-run: report, don't die
            log_event("heartbeat", devices=[], live_arrays=None,
                      error=str(e)[:200])
            return
        in_use = [r["bytes_in_use"] for r in rows if "bytes_in_use" in r]
        peak = [r["peak_bytes_in_use"] for r in rows
                if "peak_bytes_in_use" in r]
        if in_use:
            metrics.gauge("device_bytes_in_use").set(max(in_use))
        if peak:
            metrics.gauge("device_peak_bytes_in_use").set(max(peak))
        if live is not None:
            metrics.gauge("live_arrays").set(live)
        kw = {}
        # host-process RSS next to the device picture: the gauges' high
        # watermarks survive into the metrics snapshot, so run records
        # capture peak host memory alongside device memory_stats
        rss, hwm = sample_host_rss()
        if rss is not None:
            metrics.gauge("host_rss_bytes").set(rss)
            kw["host_rss_bytes"] = rss
        if hwm is not None:
            metrics.gauge("host_rss_peak_bytes").set(hwm)
            kw["host_rss_peak_bytes"] = hwm
        # same window length the live /healthz endpoint reports, so a
        # captured beat and a concurrent scrape agree on the SLO view
        wins = metrics.sample_windows(
            float(config.get("SERVE_WINDOW_S") or 0) or None)
        if wins:
            # sliding-window time series (serve request latency): each
            # beat carries the last-N-seconds p50/p95/rate, so a capture
            # shows the SLO view over time, not just the final state
            kw["windows"] = wins
        if self.progress:
            kw["progress"] = dict(self.progress)
        if self.worker_id is not None:
            kw["worker_id"] = self.worker_id
        if self.leases is not None:
            try:
                kw["leases"] = sorted(self.leases())
            except Exception:  # ledger mid-mutation: beat without leases
                pass
        log_event("heartbeat", devices=rows, live_arrays=live, **kw)
        self.beats += 1

    def run(self):
        while not self._stop_evt.wait(self.interval_s):
            try:
                self.beat()
            except Exception:
                # the sampler must outlive any single bad sample (beat
                # already reports failures in-band where it can)
                pass

    def stop(self, final_beat=True):
        """Stop the sampler; by default take one last beat so the log
        (and the gauges' watermarks) end with the terminal memory
        picture.  Idempotent — the sweep runner stops the heartbeat
        explicitly before snapshotting metrics, and the context exit
        calling again is a no-op."""
        self._stop_evt.set()
        if self.is_alive():
            self.join(timeout=max(2.0, 2 * self.interval_s))
        if final_beat and not self._final_done:
            self._final_done = True
            self.beat()


@contextlib.contextmanager
def maybe_heartbeat(devices=None, progress=None, worker_id=None,
                    leases=None):
    """Start a :class:`Heartbeat` for the block when
    ``RAFT_TPU_HEARTBEAT_S`` > 0, else yield ``None`` at zero cost."""
    interval = config.get("HEARTBEAT_S")
    if not interval or interval <= 0:
        yield None
        return
    hb = Heartbeat(interval, devices=devices, progress=progress,
                   worker_id=worker_id, leases=leases)
    hb.start()
    try:
        yield hb
    finally:
        hb.stop()
