"""Declarative jaxpr contracts for the traced hot paths.

The public entry points — :func:`raft_tpu.models.dynamics.
solve_dynamics_fowt`, :func:`~raft_tpu.models.dynamics.system_response`,
:func:`raft_tpu.physics.morison.drag_lin_iter`, the design-sweep
evaluator (:func:`raft_tpu.api.make_design_evaluator`), the
shape-bucketed heterogeneous-design evaluator
(:mod:`raft_tpu.structure.bucketing`, entry ``bucket_evaluator`` —
its per-bucket primitive budget keeps padding waste honest) and the
solver-health status fold (:mod:`raft_tpu.utils.health`, entry
``health_status``) — are traced (``jax.make_jaxpr``, no
compile/execute) on the bundled spar design and checked against
contracts:

* **structure** — hard per-primitive ceilings.  The central one
  generalizes the PR-2 hand-written guard: the drag fixed-point body
  may contain at most ONE ``gather`` (the iteration-dependent node
  *response* lookup) and no ``dynamic_slice`` — geometry constants are
  gathered once in ``drag_lin_precompute``, and reintroducing an
  ``r_nodes[node_idx]``-style lookup into the iteration fails loudly;
* **host isolation** — no callback/debug primitives anywhere in a hot
  path (a single ``pure_callback`` serializes the whole pmapped solve);
* **dtype tightness** — under ``RAFT_TPU_DTYPE=float32`` no equation
  may *produce* a float64/complex128 value in the checked region: the
  whole trace for the flat kernels (``drag_lin_iter``,
  ``system_response``), the while/scan **loop bodies** for the
  composite entries (their one-time build/staging prefix legitimately
  manipulates f64 geometry constants before the downcast — the
  fixed-point iterations must not);
* **budget** — total and per-primitive equation counts within slack of
  a checked-in baseline (``primitive_baseline.json`` next to this
  module), so hot-path bloat fails with a primitive-level diff instead
  of landing as a silent slowdown.  Regenerate after an intentional
  change with ``python -m raft_tpu.analysis baseline --write``.

Tracing pins ``RAFT_TPU_SOLVER=native``, ``RAFT_TPU_SCAN_CHUNK`` and
the solver-health flags (``COND_CHECK``/``COND_THRESHOLD``/
``ITER_SCALE``) to their defaults and traces BOTH fixed-point drivers
('while'/'scan') and BOTH dtype policies, so the baseline is
reproducible on any host and the accelerator-path jaxpr is guarded
from a CPU CI runner.
"""

from __future__ import annotations

import contextlib
import json
import os
from collections import Counter
from dataclasses import dataclass, field

# primitives that round-trip through the host (or serialize the
# program): never allowed in a traced hot path
HOST_CALLBACK_PRIMS = (
    "pure_callback", "io_callback", "callback", "debug_callback",
    "debug_print", "host_callback_call", "outside_call",
)

_64BIT_DTYPES = ("float64", "complex128")

DEFAULT_DESIGN = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "designs", "spar_demo.yaml")

SPAR_CASE = {
    "wind_speed": 0, "wind_heading": 0, "turbulence": 0,
    "turbine_status": "operating", "yaw_misalign": 0,
    "wave_spectrum": "JONSWAP", "wave_period": 12, "wave_height": 6,
    "wave_heading": 0, "current_speed": 0, "current_heading": 0,
}

# budget slack: the baseline is a snapshot, not a straitjacket — small
# refactors (a fused where, an extra convert) must not flap CI, a
# re-gather loop or an accidental unroll must fail.
PRIM_RATIO, PRIM_ABS = 1.25, 4
TOTAL_RATIO, TOTAL_ABS = 1.15, 16


@dataclass(frozen=True)
class Contract:
    """Declarative limits for one entry point."""

    name: str
    max_prims: dict = field(default_factory=dict)  # prim -> hard ceiling
    forbid_prims: tuple = HOST_CALLBACK_PRIMS
    dtype_clean: str = "all"       # f32-policy scope: all | loops | ""
    fixed_point_modes: tuple = ()  # trace per fp driver ('' = fp-free)


CONTRACTS = {
    # ONE gather allowed: the per-iteration node-response lookup.  The
    # geometry gathers must stay in drag_lin_precompute.
    "drag_lin_iter": Contract(
        "drag_lin_iter", max_prims={"gather": 1, "dynamic_slice": 0}),
    "system_response": Contract(
        "system_response", max_prims={"gather": 0, "dynamic_slice": 0}),
    "solve_dynamics_fowt": Contract(
        "solve_dynamics_fowt", dtype_clean="loops",
        fixed_point_modes=("while", "scan")),
    # dtype contract intentionally off: the evaluator's statics /
    # equilibrium Newton loop runs at BUILD precision (f64 closure
    # constants under x64 hosts — the RAFT_TPU_DTYPE policy governs the
    # dynamics hot path only); that interior is covered by the
    # solve_dynamics_fowt entry above.
    "design_evaluator": Contract(
        "design_evaluator", dtype_clean="",
        fixed_point_modes=("while", "scan")),
    # the shape-bucketed heterogeneous-design evaluator
    # (raft_tpu.structure.bucketing) traced on the bundled spar packed
    # into ITS bucket: the per-bucket primitive budget keeps padding
    # waste honest — a bucket program is supposed to cost one padded
    # design's worth of primitives, so growth here means the padded
    # chain picked up per-design work (or mask plumbing regressed into
    # re-gathers); dtype contract off for the same statics-precision
    # reason as design_evaluator
    "bucket_evaluator": Contract(
        "bucket_evaluator", dtype_clean="",
        fixed_point_modes=("while", "scan")),
    # the FUSED rigid case evaluator (raft_tpu.api.make_case_evaluator
    # under the default RAFT_TPU_FUSED=on): the wave response comes
    # straight from the drag fixed point's final solve — the separable
    # per-omega drag-excitation fold — so the staged tail's
    # drag_excitation chain + second batched solve must NOT reappear
    # in the trace (budget-gated: the fused trace is the smaller one,
    # and growth back toward the staged count is the regression)
    "fused_case": Contract(
        "fused_case", dtype_clean="",
        fixed_point_modes=("while", "scan")),
    # the solver-health status-assembly path (raft_tpu.utils.health +
    # the evaluators' _case_status fold): pure elementwise bit
    # arithmetic — no gathers, no host callbacks, and under the f32
    # policy nothing 64-bit (the word itself stays int32; asserted in
    # tests/test_health.py)
    "health_status": Contract(
        "health_status", max_prims={"gather": 0, "dynamic_slice": 0}),
}


def baseline_path():
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "primitive_baseline.json")


# ------------------------------------------------------------ jaxpr walks

def _subjaxprs(eqn):
    for v in eqn.params.values():
        vs = v if isinstance(v, (list, tuple)) else (v,)
        for x in vs:
            inner = getattr(x, "jaxpr", x)
            if hasattr(inner, "eqns"):
                yield inner


def count_primitives(jaxpr):
    """Recursive primitive counter over an (closed)jaxpr, including
    call/control-flow sub-jaxprs."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    counts = Counter()
    for eqn in jaxpr.eqns:
        counts[eqn.primitive.name] += 1
        for inner in _subjaxprs(eqn):
            counts.update(count_primitives(inner))
    return counts


def produced_64bit(jaxpr):
    """(primitive, dtype) pairs for every equation whose *output* is a
    64-bit float/complex, recursively.  Inputs/constants are exempt —
    build-side f64 tensors may enter the trace, but only through an
    immediate downcast (whose output is 32-bit)."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    hits = []
    for eqn in jaxpr.eqns:
        for v in eqn.outvars:
            dt = getattr(getattr(v, "aval", None), "dtype", None)
            if dt is not None and str(dt) in _64BIT_DTYPES:
                hits.append((eqn.primitive.name, str(dt)))
        for inner in _subjaxprs(eqn):
            hits.extend(produced_64bit(inner))
    return hits


def produced_64bit_in_loops(jaxpr):
    """Like :func:`produced_64bit`, but only inside while/scan bodies —
    the per-iteration compute that multiplies any 64-bit leak by the
    trip count (and the batch)."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    hits = []
    for eqn in jaxpr.eqns:
        if eqn.primitive.name in ("while", "scan"):
            for inner in _subjaxprs(eqn):
                hits.extend(produced_64bit(inner))
        else:
            for inner in _subjaxprs(eqn):
                hits.extend(produced_64bit_in_loops(inner))
    return hits


# ---------------------------------------------------------------- tracing

@contextlib.contextmanager
def _flag_env(**flags):
    """Pin RAFT_TPU_* flags for the duration of a trace (values of None
    unset the variable)."""
    old = {}
    try:
        for k, v in flags.items():
            env = "RAFT_TPU_" + k
            old[env] = os.environ.get(env)
            if v is None:
                os.environ.pop(env, None)
            else:
                os.environ[env] = str(v)
        yield
    finally:
        for env, v in old.items():
            if v is None:
                os.environ.pop(env, None)
            else:
                os.environ[env] = v


class EntryPointTracer:
    """Builds the bundled model once and traces each entry point under a
    given (dtype_policy, fixed_point) variant."""

    def __init__(self, design=None):
        import raft_tpu

        self.model = raft_tpu.Model(design or DEFAULT_DESIGN)
        fh = self.model.hydro[0]
        fh.hydro_excitation(SPAR_CASE)
        self.fs = self.model.fowtList[0]
        self.fh = fh

    def variants(self, entry, dtype_modes):
        """Variant keys to trace/check for an entry: 'float64+while',
        'float32' (fp-free entries omit the driver part)."""
        fp_modes = CONTRACTS[entry].fixed_point_modes or ("",)
        return [d + ("+" + f if f else "")
                for d in dtype_modes for f in fp_modes]

    def trace(self, entry, variant):
        """ClosedJaxpr of ``entry`` under ``variant`` (no execution)."""
        import jax
        import jax.numpy as jnp

        from raft_tpu.models.dynamics import (solve_dynamics_fowt,
                                              system_response)
        from raft_tpu.physics import morison
        from raft_tpu.utils.dtypes import compute_dtypes

        dtype, _, fp = variant.partition("+")
        model, fs, fh = self.model, self.fs, self.fh
        nDOF, nw = fs.nDOF, model.nw
        # every trace-time flag that shapes the jaxpr is pinned (None =
        # registry default), so an operator's exported RAFT_TPU_* env —
        # e.g. COND_CHECK=1 left on while debugging — can neither flap
        # the CI budgets nor get baked into a regenerated baseline
        with _flag_env(DTYPE=dtype, FIXED_POINT=fp or None,
                       SOLVER="native", SCAN_CHUNK=None,
                       COND_CHECK=None, COND_THRESHOLD=None,
                       ITER_SCALE=None, FUSED=None, BUCKET_STEPS=None):
            rdt, cdt = compute_dtypes(policy=dtype)
            w = jnp.asarray(model.w, dtype=rdt)
            if entry == "drag_lin_iter":
                pre = morison.drag_lin_precompute(
                    fs, fh.strips, fh.hc, jnp.asarray(fh.u[0]).astype(cdt),
                    fh.Tn, fh.r_nodes, w, dtype=(rdt, cdt))
                Xi0 = jnp.full((nDOF, nw), 0.1 + 0j, dtype=cdt)
                return jax.make_jaxpr(
                    lambda Xi: morison.drag_lin_iter(pre, Xi))(Xi0)
            if entry == "system_response":
                Z = jnp.zeros((nw, nDOF, nDOF), dtype=cdt)
                F = jnp.zeros((2, nDOF, nw), dtype=cdt)
                return jax.make_jaxpr(system_response)(Z, F)
            if entry == "solve_dynamics_fowt":
                def solve(M, B, C, F, u0):
                    return solve_dynamics_fowt(
                        fs, fh.strips, fh.hc, u0, M, B, C, F, w,
                        fh.Tn, fh.r_nodes, n_iter=model.nIter,
                        Xi_start=model.XiStart)
                return jax.make_jaxpr(solve)(
                    jnp.zeros((nDOF, nDOF, nw), dtype=rdt),
                    jnp.zeros((nDOF, nDOF, nw), dtype=rdt),
                    jnp.zeros((nDOF, nDOF), dtype=rdt),
                    jnp.zeros((nDOF, nw), dtype=cdt),
                    jnp.asarray(fh.u[0]).astype(cdt))
            if entry == "design_evaluator":
                from raft_tpu.api import make_design_evaluator

                # rebuilt per variant: the evaluator reads the dtype
                # policy at trace time through its closure constants
                ev = make_design_evaluator(model)
                return jax.make_jaxpr(lambda p: ev(
                    {"Hs": p[0], "Tp": p[1], "beta": p[2],
                     "Cd_scale": p[3]}))(
                    jnp.asarray([6.0, 12.0, 0.0, 1.0], dtype=rdt))
            if entry == "bucket_evaluator":
                from raft_tpu.structure import bucketing

                sig = bucketing.bucket_signature(model)
                packed = bucketing.pack_design(model, sig)
                ev = bucketing.make_bucket_evaluator(sig)
                case = dict(
                    design={k2: jnp.asarray(v) for k2, v in packed.items()},
                    Hs=jnp.asarray(6.0, dtype=rdt),
                    Tp=jnp.asarray(12.0, dtype=rdt),
                    beta=jnp.asarray(0.0, dtype=rdt))
                return jax.make_jaxpr(ev)(case)
            if entry == "fused_case":
                from raft_tpu.api import make_case_evaluator

                # rebuilt per variant (trace-time flag closure reads)
                ev = make_case_evaluator(model)
                return jax.make_jaxpr(lambda p: ev(p[0], p[1], p[2]))(
                    jnp.asarray([6.0, 12.0, 0.0], dtype=rdt))
            if entry == "health_status":
                # the evaluators' status fold at representative shapes:
                # statics word | dynamics word | output-finiteness and
                # input-clip guards (mirrors raft_tpu.api._case_status)
                from raft_tpu.utils import health

                def fold(st_statics, drag_converged, cond_Z, X0, Xi):
                    status = health.set_bit(
                        st_statics, health.DRAG_CAP_HIT, ~drag_converged)
                    status = health.set_bit(
                        status, health.ILL_CONDITIONED_Z, cond_Z > 1e7)
                    status = health.set_bit(
                        status, health.NONFINITE_INTERMEDIATE,
                        ~(jnp.all(jnp.isfinite(X0))
                          & jnp.all(jnp.isfinite(Xi))))
                    return jnp.asarray(status, dtype=jnp.int32)

                return jax.make_jaxpr(fold)(
                    jnp.zeros((), dtype=jnp.int32), jnp.asarray(False),
                    jnp.zeros((), dtype=rdt),
                    jnp.zeros((nDOF,), dtype=rdt),
                    jnp.zeros((nDOF, nw), dtype=cdt))
        raise KeyError(f"unknown entry point {entry!r}")


# --------------------------------------------------------------- checking

def check_structure(entry, variant, jaxpr):
    """Contract violations (list of strings) for one traced variant —
    structural caps, host isolation, and the float32 dtype contract."""
    c = CONTRACTS[entry]
    counts = count_primitives(jaxpr)
    out = []
    for prim, cap in c.max_prims.items():
        if counts.get(prim, 0) > cap:
            out.append(
                f"{entry}[{variant}]: {counts[prim]} x {prim} "
                f"(contract allows {cap}) — hoist the lookup into the "
                "precompute stage")
    for prim in c.forbid_prims:
        if counts.get(prim, 0):
            out.append(f"{entry}[{variant}]: host callback primitive "
                       f"{prim!r} in a hot path")
    if c.dtype_clean and variant.startswith("float32"):
        finder = (produced_64bit if c.dtype_clean == "all"
                  else produced_64bit_in_loops)
        hits = finder(jaxpr)
        if hits:
            where = ("" if c.dtype_clean == "all"
                     else " inside fixed-point loop bodies")
            sample = ", ".join(f"{p}->{d}" for p, d in hits[:5])
            out.append(
                f"{entry}[{variant}]: {len(hits)} equation(s) produce "
                f"64-bit values under RAFT_TPU_DTYPE=float32{where} "
                f"({sample}" + (", ..." if len(hits) > 5 else "") + ")")
    return out


def check_budget(entry, variant, counts, baseline):
    """Budget violations against the stored baseline counters, with a
    primitive-level diff in the message."""
    base = (baseline.get("entries", {}).get(entry, {}).get(variant))
    if base is None:
        return [f"{entry}[{variant}]: no baseline entry — run "
                "`python -m raft_tpu.analysis baseline --write`"]
    out = []
    total = sum(counts.values())
    cap = int(base["total"] * TOTAL_RATIO + TOTAL_ABS)
    if total > cap:
        grew = {p: (base["prims"].get(p, 0), n)
                for p, n in counts.most_common()
                if n > base["prims"].get(p, 0)}
        diff = ", ".join(f"{p}: {b}->{n}" for p, (b, n) in
                         list(grew.items())[:8])
        out.append(
            f"{entry}[{variant}]: total primitive count {total} exceeds "
            f"budget {cap} (baseline {base['total']}); grew: {diff}")
    for p, n in counts.items():
        b = base["prims"].get(p, 0)
        if n > int(b * PRIM_RATIO + PRIM_ABS):
            out.append(
                f"{entry}[{variant}]: {p} x{n} exceeds budget "
                f"{int(b * PRIM_RATIO + PRIM_ABS)} (baseline {b})")
    return out


def load_baseline(path=None):
    path = path or baseline_path()
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def run_checks(design=None, dtype_modes=("float64", "float32"),
               update_baseline=False, entries=None, budget=True,
               tracer=None):
    """Trace every entry-point variant and check all contracts.

    Returns ``{"violations": [...], "log": [...], "counts": {...}}``.
    With ``update_baseline`` the measured counts replace the stored
    baseline (and budget checking is skipped).  ``tracer`` reuses an
    existing :class:`EntryPointTracer` (tests share one model build).
    """
    tracer = tracer or EntryPointTracer(design)
    baseline = load_baseline()
    design_name = os.path.basename(design or DEFAULT_DESIGN)
    if (budget and not update_baseline and baseline
            and baseline.get("design") != design_name):
        # comparing another design against the spar snapshot would
        # produce noise either way (spurious violations, or silently
        # loosened budgets) — refuse instead
        return {"violations": [
            f"primitive baseline was recorded for "
            f"{baseline.get('design')!r}, not {design_name!r}; run "
            "`python -m raft_tpu.analysis baseline --write "
            f"--design {design_name}` or check the bundled design"],
            "log": [], "counts": {}}
    violations, log = [], []
    measured = {}
    for entry in (entries or CONTRACTS):
        measured[entry] = {}
        for variant in tracer.variants(entry, tuple(dtype_modes)):
            jaxpr = tracer.trace(entry, variant)
            counts = count_primitives(jaxpr)
            measured[entry][variant] = {
                "total": sum(counts.values()),
                "prims": dict(sorted(counts.items()))}
            log.append(f"{entry}[{variant}]: "
                       f"{sum(counts.values())} primitives")
            violations += check_structure(entry, variant, jaxpr)
            if budget and not update_baseline:
                violations += check_budget(entry, variant, counts, baseline)
    if update_baseline and not violations:
        import jax

        payload = dict(
            design=os.path.basename(design or DEFAULT_DESIGN),
            jax=jax.__version__,
            pinned_flags=dict(SOLVER="native", SCAN_CHUNK="default",
                              COND_CHECK="default", ITER_SCALE="default"),
            slack=dict(prim_ratio=PRIM_RATIO, prim_abs=PRIM_ABS,
                       total_ratio=TOTAL_RATIO, total_abs=TOTAL_ABS),
            entries=measured)
        with open(baseline_path(), "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
            f.write("\n")
    return {"violations": violations, "log": log, "counts": measured}
