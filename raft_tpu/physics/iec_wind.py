"""IEC 61400-1 wind models: turbulence classes and extreme events.

Equivalent of the reference's ``pyIECWind_extreme``
(``/root/reference/raft/pyIECWind.py:8-405``): turbine/turbulence class
parameters, the NTM/ETM/EWM turbulence standard deviations, and the
extreme transient events (EOG 6.3.2.2, EDC 6.3.2.4, ECD 6.3.2.5,
EWS 6.3.2.6) as array-returning generators, plus the InflowWind
``.wnd`` writer for interchange.
"""

from __future__ import annotations

import numpy as np

TURBINE_CLASS_VREF = {"I": 50.0, "II": 42.5, "III": 37.5, "IV": 30.0}
TURBULENCE_CLASS_IREF = {"A+": 0.18, "A": 0.16, "B": 0.14, "C": 0.12}


class IECWindExtreme:
    """IEC 61400-1 extreme-condition wind generator."""

    def __init__(self, turbine_class="I", turbulence_class="B", z_hub=90.0,
                 D=126.0, vert_slope=0.0, dt=0.05, dir_change="both",
                 shear_orient="both"):
        self.turbine_class = turbine_class
        self.turbulence_class = turbulence_class
        self.z_hub = z_hub
        self.D = D
        self.vert_slope = vert_slope
        self.dt = dt
        self.dir_change = dir_change
        self.shear_orient = shear_orient
        self.setup()

    def setup(self):
        self.V_ref = TURBINE_CLASS_VREF[self.turbine_class]
        self.V_ave = 0.2 * self.V_ref
        self.I_ref = TURBULENCE_CLASS_IREF[self.turbulence_class]
        self.Sigma_1 = 42.0 if self.z_hub > 60 else 0.7 * self.z_hub

    # --- turbulence standard deviations (pyIECWind.py:54-79)
    def NTM(self, V_hub):
        return self.I_ref * (0.75 * V_hub + 5.6)

    def ETM(self, V_hub):
        c = 2.0
        return c * self.I_ref * (0.072 * (self.V_ave / c + 3) * (V_hub / c - 4) + 10)

    def EWM(self, V_hub):
        V_e50 = 1.4 * self.V_ref
        return 0.11 * V_hub, V_e50, 0.8 * V_e50, self.V_ref, 0.8 * self.V_ref

    # --- transient events; each returns dict of time-series columns
    def EOG(self, V_hub_in):
        """Extreme operating gust (6.3.2.2): Mexican-hat velocity dip/rise."""
        T = 10.5
        t = np.linspace(0.0, T, int(T / self.dt + 1))
        V_hub = V_hub_in * np.cos(np.radians(self.vert_slope))
        sigma_1 = self.NTM(V_hub)
        _, _, V_e1, _, _ = self.EWM(V_hub)
        V_gust = min(1.35 * (V_e1 - V_hub),
                     3.3 * (sigma_1 / (1 + 0.1 * (self.D / self.Sigma_1))))
        V_gust_t = np.where(
            t < T,
            -0.37 * V_gust * np.sin(3 * np.pi * t / T) * (1 - np.cos(2 * np.pi * t / T)),
            0.0,
        )
        return dict(t=t, V=np.full_like(t, V_hub), V_gust=V_gust_t,
                    sigma_1=sigma_1, V_gust_peak=V_gust)

    def EDC(self, V_hub_in):
        """Extreme direction change (6.3.2.4)."""
        T = 6.0
        t = np.linspace(0.0, T, int(T / self.dt + 1))
        V_hub = V_hub_in * np.cos(np.radians(self.vert_slope))
        sigma_1 = self.NTM(V_hub)
        theta_e = np.degrees(
            4.0 * np.arctan(sigma_1 / (V_hub * (1 + 0.01 * (self.D / self.Sigma_1)))))
        theta_e = min(theta_e, 180.0)
        ramp = 0.5 * theta_e * (1 - np.cos(np.pi * t / T))
        return dict(t=t, V=np.full_like(t, V_hub),
                    theta_pos=np.where(t < T, ramp, theta_e),
                    theta_neg=-np.where(t < T, ramp, theta_e),
                    sigma_1=sigma_1, theta_e=theta_e)

    def ECD(self, V_hub_in):
        """Extreme coherent gust with direction change (6.3.2.5)."""
        T = 10.0
        t = np.linspace(0.0, 2 * T, int(2 * T / self.dt + 1))
        V_hub = V_hub_in * np.cos(np.radians(self.vert_slope))
        V_cg = 15.0
        theta_cg = 180.0 if V_hub < 4 else 720.0 / V_hub
        rise = 0.5 * (1 - np.cos(np.pi * np.clip(t, 0, T) / T))
        return dict(t=t, V=V_hub + V_cg * rise,
                    theta_pos=theta_cg * rise, theta_neg=-theta_cg * rise,
                    V_cg=V_cg, theta_cg=theta_cg)

    def EWS(self, V_hub_in):
        """Extreme wind shear (6.3.2.6): transient vertical/horizontal
        linear shear on top of the power-law profile."""
        T = 12.0
        alpha = 0.2
        beta = 6.4
        t = np.linspace(0.0, T, int(T / self.dt + 1))
        V_hub = V_hub_in * np.cos(np.radians(self.vert_slope))
        sigma_1 = self.NTM(V_hub)
        amp = (2.5 + 0.2 * beta * sigma_1 * (self.D / self.Sigma_1) ** 0.25) / self.D
        shear_t = np.where(t < T, amp * (1 - np.cos(2 * np.pi * t / T)), 0.0)
        return dict(t=t, V=np.full_like(t, V_hub), shear_lin=shear_t,
                    shear_vert=np.full_like(t, alpha), sigma_1=sigma_1)


def write_wnd(path, data_columns, header_lines=()):
    """Write an InflowWind uniform-wind .wnd file (pyIECWind.py:373-404).

    data_columns: sequence of equal-length 1-D arrays in the order
    (t, V, dir, V_vert, shear_horz, shear_vert, shear_vert_lin, V_gust,
    upflow)."""
    data = np.column_stack(data_columns)
    with open(path, "w") as f:
        for h in header_lines:
            f.write(h if h.endswith("\n") else h + "\n")
        f.write("! Time  Wind  Wind  Vertical  Horiz.  Pwr. Law  Lin. Vert.  Gust   Upflow\n")
        f.write("!       Speed Dir.  Speed     Shear   Vert.Shr  Shear       Speed\n")
        for row in data:
            f.write(" ".join(f"{v: 10.4f}" for v in row) + "\n")
