"""Output-channel coverage: rotor response channels (speed / torque /
power / blade pitch via the control transfer functions), the
calcOutputs properties/eigen dicts, and the viz3Danim modes JSON.

Reference surface: saveTurbineOutputs rotor block
(raft_fowt.py:2609-2688), calcOutputs (raft_model.py:1319-1360),
write_modes_json (raft_fowt.py:2889-3070).
"""

import json
import os

import numpy as np
import pytest

import raft_tpu

pytestmark = pytest.mark.slow

VOLTURN = "/root/reference/designs/VolturnUS-S.yaml"


@pytest.fixture(scope="module")
def volturn_case_metrics():
    from raft_tpu.structure.schema import load_design

    design = load_design(VOLTURN)
    design["settings"]["min_freq"] = 0.005
    design["settings"]["max_freq"] = 0.2
    # single operating wind case
    design["cases"]["data"] = [
        [12.0, 0, 0.1, "operating", 0, "JONSWAP", 10.0, 4.0, 0],   # above rated
        [8.0, 0, 0.1, "operating", 0, "JONSWAP", 9.0, 3.0, 0]]     # below rated
    model = raft_tpu.Model(design)
    results = model.analyze_cases()
    return model, results


def test_rotor_channels(volturn_case_metrics):
    model, results = volturn_case_metrics
    m = results["case_metrics"][0][0]
    # rotor speed: mean at the scheduled operating point, nonzero std
    assert m["omega_avg"][0] == pytest.approx(7.56, rel=0.05)
    assert m["omega_std"][0] > 0
    assert m["omega_max"][0] == m["omega_avg"][0] + 2 * m["omega_std"][0]
    assert m["omega_PSD"].shape == (model.nw, 1)
    # torque / power positive means
    assert m["torque_avg"][0] > 0
    assert m["power_avg"][0] > 1e6  # 15 MW machine at 12 m/s: multi-MW
    # above rated: pitch control active (nonzero pitch variation), torque
    # gains zeroed by the gain-scheduling switch (raft_rotor.py:910-911)
    assert m["bPitch_avg"][0] > 0
    assert m["bPitch_std"][0] > 0
    assert m["torque_std"][0] == 0
    assert m["bPitch_PSD"].shape == (model.nw, 1)
    assert "wind_PSD" in m

    # below rated: torque control active, pitch at fine pitch
    m2 = results["case_metrics"][1][0]
    assert m2["torque_std"][0] > 0
    assert m2["bPitch_std"][0] == 0
    assert m2["omega_avg"][0] < m["omega_avg"][0]


def test_calc_outputs_properties(volturn_case_metrics):
    model, _ = volturn_case_metrics
    results = model.calc_outputs()
    p = results["properties"]
    stat = model.statics(0)
    assert p["total mass"] == pytest.approx(float(np.asarray(stat["M_struc"])[0, 0]))
    assert p["buoyancy (pgV)"] == pytest.approx(
        1025.0 * model.fowtList[0].g * float(stat["V"]), rel=1e-6)
    assert p["substructure mass"] > 1e7  # VolturnUS-S steel semi ~ 1.7e7 kg
    assert p["C system"].shape == (6, 6)
    assert p["C system"][2, 2] > 0  # positive heave stiffness
    assert p["F_lines0"].shape == (6,)
    assert p["F_lines0"][2] < 0  # mooring pulls down
    assert p["roll inertia at subCG"] > 0
    # eigen block present with 6 positive rigid-body frequencies
    fns = results["eigen"]["frequencies"]
    assert len(fns) == 6 and np.all(fns > 0)


def test_modes_json(volturn_case_metrics, tmp_path=None):
    import tempfile

    model, _ = volturn_case_metrics
    path = os.path.join(tempfile.mkdtemp(), "modes.json")
    model.write_modes_json(path)
    doc = json.load(open(path))
    assert doc["fileKind"] == "Modes"
    assert len(doc["Modes"]) == model.fowtList[0].nDOF
    assert len(doc["Connectivity"]) == len(doc["ElemProps"])
    n_nodes = len(doc["Nodes"])
    for mode in doc["Modes"]:
        assert len(mode["Displ"]) == n_nodes
        assert mode["frequency"] > 0


def test_plot2d_and_extended_responses(volturn_case_metrics):
    """plot2d (projected geometry + mooring profiles) and the 9-panel
    extended response-PSD figure render without error (Model.plot2d /
    plotResponses_extended equivalents, raft_model.py:1599/:1463)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    from raft_tpu.plotting import plot2d, plot_responses_extended

    model, results = volturn_case_metrics
    fig, ax = plot2d(model)                      # x-z side view
    assert len(ax.lines) > 10
    plt.close(fig)
    fig, ax = plot2d(model, Xuvec=(1, 0, 0), Yuvec=(0, 1, 0))  # plan view
    plt.close(fig)
    fig, axs = plot_responses_extended(model)
    assert len(axs) == 9
    for a in axs:
        assert len(a.lines) == 2                 # one per case
    plt.close(fig)
