"""Second-order (difference-frequency) wave forces.

Implements the externally-supplied-QTF path of the reference
(``/root/reference/raft/raft_fowt.py``: ``readQTF`` :2081-2129 for the
WAMIT ``.12d`` interchange format, ``calcHydroForce_2ndOrd``
:2158-2253 for the Pinkster (1980) §IV.3 force-spectrum realisation).
The slender-body internally-computed QTF (potSecOrder == 1) is a
follow-up milestone.

The force-spectrum evaluation ('qtf' interpolation mode, the reference
default) is: bilinearly interpolate the QTF onto the model's w x w
grid, then for each difference frequency mu_i sum the i-th
superdiagonal against the shifted wave spectrum:

    f(mu_i) = 4 sqrt( sum_j S(w_j) S(w_j+mu_i) |Q(w_j, w_j+mu_i)|^2 ) dw
    f_mean  = 2 sum_j S(w_j) Re Q(w_j, w_j) dw

which loses relative phase between components (as in the reference).
"""

from __future__ import annotations

import numpy as np


def read_qtf_12d(path, rho=1025.0, g=9.81, ulen=1.0, ndof=6):
    """Read a WAMIT .12d difference-frequency QTF file.

    Returns dict(w_2nd (nw,), heads_rad (nh,), qtf (nw, nw, nh, ndof))
    — dimensionalised (rho g ULEN, extra ULEN for moments) and
    hermitian-completed, matching readQTF (raft_fowt.py:2081-2129).
    """
    data = np.loadtxt(path)
    data[:, 0:2] = 2.0 * np.pi / data[:, 0:2]  # periods -> rad/s
    if not (data[:, 2] == data[:, 3]).all():
        raise ValueError("only unidirectional QTFs are supported")
    heads = np.deg2rad(np.sort(np.unique(data[:, 2])))
    w1 = np.unique(data[:, 0])
    w2 = np.unique(data[:, 1])
    if not (w1 == w2).all():
        raise ValueError("both frequency columns must contain the same values")

    qtf = np.zeros([len(w1), len(w2), len(heads), ndof], dtype=np.complex128)
    for row in data:
        i1 = np.searchsorted(w1, row[0])
        i2 = np.searchsorted(w2, row[1])
        ih = np.searchsorted(heads, np.deg2rad(row[2]))
        idof = round(row[4] - 1)
        factor = rho * g * ulen * (ulen if idof >= 3 else 1.0)
        qtf[i1, i2, ih, idof] = factor * (row[7] + 1j * row[8])
        if i1 != i2:  # hermitian completion
            qtf[i2, i1, ih, idof] = factor * (row[7] - 1j * row[8])
    return dict(w_2nd=w1, heads_rad=heads, qtf=qtf)


def write_qtf_12d(path, qtf, w_2nd, heads_rad, rho=1025.0, g=9.81,
                  ulen=1.0):
    """Write a difference-frequency QTF in the WAMIT .12d interchange
    format — the inverse of :func:`read_qtf_12d` and the checkpoint
    format the reference uses to persist expensive 2nd-order results
    (writeQTF, raft_fowt.py:2131-2156).

    ``qtf`` (nw, nw, nh, ndof) complex, dimensional; only the upper
    triangle i2 >= i1 is written (the matrix is hermitian).  Columns:
    T1, T2, head, head, DoF, |F|, phase, Re F, Im F with
    F = Q/(rho g ULEN) (extra ULEN for moments)."""
    qtf = np.asarray(qtf)
    w = np.asarray(w_2nd)
    with open(path, "w") as f:
        for ih in range(len(heads_rad)):
            hd = np.rad2deg(heads_rad[ih])
            for idof in range(qtf.shape[3]):
                factor = rho * g * ulen * (ulen if idof >= 3 else 1.0)
                for i1 in range(len(w)):
                    for i2 in range(i1, len(w)):
                        F = qtf[i1, i2, ih, idof] / factor
                        f.write(
                            f"{2 * np.pi / w[i1]: 8.6e} "
                            f"{2 * np.pi / w[i2]: 8.6e} "
                            f"{hd: 8.4e} {hd: 8.4e} {idof + 1} "
                            f"{np.abs(F): 8.6e} {np.angle(F): 8.6e} "
                            f"{F.real: 8.6e} {F.imag: 8.6e}\n")


def _interp_heading(qtf, heads, beta):
    if len(heads) == 1:
        return qtf[:, :, 0, :]
    b = np.clip(beta, heads[0], heads[-1])
    i = np.clip(np.searchsorted(heads, b) - 1, 0, len(heads) - 2)
    f = (b - heads[i]) / (heads[i + 1] - heads[i])
    return qtf[:, :, i, :] * (1 - f) + qtf[:, :, i + 1, :] * f


def hydro_force_2nd(qtf_data, beta, S0, w):
    """Mean drift + difference-frequency force amplitudes.

    calcHydroForce_2ndOrd 'qtf' mode (raft_fowt.py:2218-2245).
    Returns (f_mean (ndof,), f (ndof, nw) real amplitudes).
    """
    from scipy.interpolate import RegularGridInterpolator

    w = np.asarray(w)
    S0 = np.asarray(S0)
    nw = len(w)
    dw = w[1] - w[0]
    ndof = qtf_data["qtf"].shape[-1]
    w2nd = qtf_data["w_2nd"]
    Q_beta = _interp_heading(qtf_data["qtf"], qtf_data["heads_rad"], beta)

    f = np.zeros((ndof, nw))
    f_mean = np.zeros(ndof)
    pts = np.stack(np.meshgrid(w, w, indexing="ij"), axis=-1).reshape(-1, 2)
    for idof in range(ndof):
        Qr = RegularGridInterpolator((w2nd, w2nd), Q_beta[:, :, idof].real,
                                     bounds_error=False, fill_value=0)(pts)
        Qi = RegularGridInterpolator((w2nd, w2nd), Q_beta[:, :, idof].imag,
                                     bounds_error=False, fill_value=0)(pts)
        Q = (Qr + 1j * Qi).reshape(nw, nw)
        for imu in range(1, nw):
            Saux = np.zeros(nw)
            Saux[: nw - imu] = S0[imu:]
            Qd = np.zeros(nw, dtype=np.complex128)
            Qd[: nw - imu] = np.diag(Q, imu)
            f[idof, imu] = 4 * np.sqrt(np.sum(S0 * Saux * np.abs(Qd) ** 2)) * dw
        f_mean[idof] = 2 * np.sum(S0 * np.diag(Q.real)) * dw

    # shift difference frequencies onto the model grid (raft_fowt.py:2241-2245)
    f[:, 0:-1] = f[:, 1:]
    f[:, -1] = 0
    return f_mean, f
