"""Shared HTTP/1.1 wire helpers for the serving stack (stdlib only).

One deliberately small HTTP implementation, used from BOTH sides of the
fleet: :mod:`raft_tpu.serve.http` (the replica server) parses requests
and formats responses with it, and :mod:`raft_tpu.serve.router` (the
fleet front router) additionally uses :func:`proxy_request` as its
asyncio upstream client.  Keeping the parser/formatter here means the
router imports NO jax-facing serve module — it is a thin network
process that must start (and keep routing) even while every replica is
busy compiling.
"""

from __future__ import annotations

import asyncio
import json

STATUS_TEXT = {200: "OK", 202: "Accepted", 400: "Bad Request",
               403: "Forbidden", 404: "Not Found",
               405: "Method Not Allowed", 408: "Request Timeout",
               413: "Payload Too Large", 422: "Unprocessable Entity",
               429: "Too Many Requests", 500: "Internal Server Error",
               502: "Bad Gateway", 503: "Service Unavailable"}

MAX_BODY_BYTES = 8 * 1024 * 1024

#: peer hosts the admin endpoints (``POST /drain``) accept — drain is
#: an operator/router verb, never a tenant one
LOOPBACK_HOSTS = ("127.0.0.1", "::1", "localhost")


async def read_request(reader):
    """One HTTP request off the stream: ``(method, path, headers,
    body)``, or None on clean EOF."""
    line = await reader.readline()
    if not line:
        return None
    parts = line.decode("latin-1").strip().split()
    if len(parts) < 2:
        raise ValueError(f"bad request line {line!r}")
    method, path = parts[0].upper(), parts[1].split("?", 1)[0]
    headers = {}
    while True:
        h = await reader.readline()
        if h in (b"\r\n", b"\n", b""):
            break
        name, _, value = h.decode("latin-1").partition(":")
        headers[name.strip().lower()] = value.strip()
    n = int(headers.get("content-length", 0) or 0)
    if n > MAX_BODY_BYTES:
        raise ValueError(f"body of {n} bytes exceeds {MAX_BODY_BYTES}")
    body = await reader.readexactly(n) if n else b""
    return method, path, headers, body


def response_bytes(status, payload, keep_alive, extra_headers=None):
    """Serialize one response: dict/list payloads as JSON, anything
    else as plain text (``/metrics``)."""
    if isinstance(payload, (dict, list)):
        data = json.dumps(payload).encode()
        ctype = "application/json"
    else:
        data = str(payload).encode()
        ctype = "text/plain; version=0.0.4"
    head = [f"HTTP/1.1 {status} {STATUS_TEXT.get(status, 'Unknown')}",
            f"Content-Type: {ctype}",
            f"Content-Length: {len(data)}",
            f"Connection: {'keep-alive' if keep_alive else 'close'}"]
    for name, value in (extra_headers or {}).items():
        head.append(f"{name}: {value}")
    if status in (429, 503) and isinstance(payload, dict) \
            and "retry-after" not in {k.lower()
                                      for k in (extra_headers or {})}:
        head.append(
            f"Retry-After: {max(1, int(payload.get('retry_after_s') or 0) + 1)}")
    return ("\r\n".join(head) + "\r\n\r\n").encode() + data


class UpstreamError(RuntimeError):
    """A proxied request failed before a complete response arrived
    (connect refused/reset, short read, per-attempt timeout).  The
    router's failover ladder treats this as retryable: serving
    evaluations are idempotent by construction (content-addressed
    result/program caches make duplicate dispatch benign — the same
    argument that makes fabric double-compute safe)."""

    def __init__(self, reason, detail=""):
        super().__init__(f"{reason}: {detail}" if detail else reason)
        self.reason = reason


async def proxy_request(host, port, method, path, body=b"",
                        headers=None, timeout_s=30.0):
    """One upstream round trip (fresh connection, ``Connection:
    close``): returns ``(status, headers, body_bytes)`` or raises
    :class:`UpstreamError`.  Pure asyncio — the router calls this on
    its event loop; no thread, no http.client."""
    try:
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, port), timeout=timeout_s)
    except (OSError, asyncio.TimeoutError) as e:
        raise UpstreamError("connect", repr(e)) from e
    try:
        head = [f"{method} {path} HTTP/1.1",
                f"Host: {host}:{port}",
                "Connection: close",
                f"Content-Length: {len(body)}"]
        for name, value in (headers or {}).items():
            if name.lower() in ("host", "connection", "content-length"):
                continue
            head.append(f"{name}: {value}")
        writer.write(("\r\n".join(head) + "\r\n\r\n").encode() + body)
        await asyncio.wait_for(writer.drain(), timeout=timeout_s)

        async def _read_response():
            line = await reader.readline()
            if not line:
                raise UpstreamError("closed", "no status line")
            parts = line.decode("latin-1").split()
            if len(parts) < 2 or not parts[1].isdigit():
                raise UpstreamError("protocol", f"bad status line {line!r}")
            status = int(parts[1])
            resp_headers = {}
            while True:
                h = await reader.readline()
                if h in (b"\r\n", b"\n", b""):
                    break
                name, _, value = h.decode("latin-1").partition(":")
                resp_headers[name.strip().lower()] = value.strip()
            n = int(resp_headers.get("content-length", 0) or 0)
            data = await reader.readexactly(n) if n else await reader.read()
            return status, resp_headers, data

        return await asyncio.wait_for(_read_response(), timeout=timeout_s)
    except UpstreamError:
        raise
    except asyncio.TimeoutError as e:
        raise UpstreamError("timeout",
                            f"{method} {path} after {timeout_s}s") from e
    except (OSError, asyncio.IncompleteReadError, ValueError) as e:
        raise UpstreamError("dropped", repr(e)) from e
    finally:
        try:
            writer.close()
        except Exception:  # noqa: BLE001 — best-effort close
            pass
