"""Native C++ panel-method kernel tests.

Physics checks against closed-form potential-flow results:
* surge added mass of a deeply-drafted circular spar ~ rho pi a^2 T
  (2-D cylinder slice value Ca = 1, with 3-D end-effect reduction);
* symmetry of the added-mass matrix.
"""

import numpy as np
import pytest

from raft_tpu.io.panels import mesh_cylinder, write_pnl
from conftest import require_native_env


@pytest.fixture(scope="module")
def spar_mesh(native_bem_env):
    require_native_env(native_bem_env, "native")
    # vertical cylinder: radius 5 m, draft 60 m
    return mesh_cylinder(
        stations=[0.0, 60.0], diameters=[10.0, 10.0],
        rA=np.array([0.0, 0.0, -60.0]), q=np.array([0.0, 0.0, 1.0]),
        n_az=24, dz_max=2.5,
    )


def test_mesh_properties(spar_mesh):
    verts, cents, norms, areas = spar_mesh
    assert np.all(cents[:, 2] <= 0)
    # total side area ~ 2 pi a T; cap area ~ pi a^2
    assert abs(areas.sum() - (2 * np.pi * 5 * 60 + np.pi * 25)) / areas.sum() < 0.05
    # normals unit length
    assert np.allclose(np.linalg.norm(norms, axis=1), 1.0, atol=1e-9)


def test_radiation_added_mass(spar_mesh):
    from raft_tpu.native import radiation_added_mass

    verts, cents, norms, areas = spar_mesh
    rho = 1025.0
    A = radiation_added_mass(verts, cents, norms, areas, mirror=-1, rho=rho)
    a, T = 5.0, 60.0
    A11_strip = rho * np.pi * a**2 * T  # 2-D slice estimate
    # 3-D + discretisation effects: expect within ~20% of the strip value
    assert 0.75 * A11_strip < A[0, 0] < 1.15 * A11_strip
    assert np.isclose(A[0, 0], A[1, 1], rtol=1e-6)   # x/y symmetry
    assert abs(A[0, 1]) < 0.01 * A[0, 0]
    # matrix symmetry (Green's identity)
    assert np.allclose(A, A.T, rtol=5e-2, atol=1e-3 * A[0, 0])
    # heave added mass positive and much smaller than surge for a spar
    assert 0 < A[2, 2] < 0.5 * A[0, 0]


def test_pnl_writer(tmp_path, spar_mesh):
    verts, *_ = spar_mesh
    p = tmp_path / "mesh.pnl"
    write_pnl(p, verts)
    lines = p.read_text().splitlines()
    assert str(len(verts)) in lines[2]


# ------------------------- frequency-dependent solver (wave Green fn)

HAMS_FIXTURE = "/root/reference/raft/data/cylinder"


@pytest.mark.slow
def test_frequency_solver_vs_hams_fixture(native_bem_env):
    """Radiation A/B and excitation X vs the reference's shipped HAMS
    run (raft/data/cylinder: 1008-panel floating cylinder, depth 50,
    WAMIT-format outputs).  The native solver reads the SAME mesh, so
    differences are solver numerics only."""
    import os

    from raft_tpu.io.panels import read_pnl
    from raft_tpu.native import solve_bem

    require_native_env(native_bem_env, "native", "reference")
    if not os.path.exists(HAMS_FIXTURE):
        pytest.skip("HAMS cylinder fixture unavailable")
    v, c, nrm, a = read_pnl(os.path.join(HAMS_FIXTURE, "Input", "HullMesh.pnl"))
    gold1 = np.loadtxt(os.path.join(HAMS_FIXTURE, "Output", "Wamit_format", "Buoy.1"))
    gold3 = np.loadtxt(os.path.join(HAMS_FIXTURE, "Output", "Wamit_format", "Buoy.3"))

    oms = np.array([0.6, 1.2, 2.0, 3.0, 4.2, 5.4])
    A, B, X = solve_bem(v, c, nrm, a, oms, headings_deg=[0.0], depth=50.0,
                        rho=1.0, g=9.81)
    Ag = np.zeros((6, 6, len(oms)))
    Bg = np.zeros((6, 6, len(oms)))
    Xg = np.zeros((1, 6, len(oms)), complex)
    wi = {w: i for i, w in enumerate(oms)}
    for r in gold1:
        if r[0] in wi:
            Ag[int(r[1]) - 1, int(r[2]) - 1, wi[r[0]]] = r[3]
            Bg[int(r[1]) - 1, int(r[2]) - 1, wi[r[0]]] = r[4] * r[0]
    for r in gold3:
        if r[0] in wi:
            Xg[0, int(r[2]) - 1, wi[r[0]]] = (r[5] + 1j * r[6]) * 9.81

    assert np.max(np.abs(A - Ag)) / np.max(np.abs(Ag)) < 0.03
    assert np.max(np.abs(B - Bg)) / np.max(np.abs(Bg)) < 0.03
    assert np.max(np.abs(X - Xg)) / np.max(np.abs(Xg)) < 0.02

    # deep-water Haskind constant check on the GOLDEN data itself:
    # B_11 = K/(8 pi rho g Cg) * pi |X_surge|^2 for an axisymmetric
    # body (X_1(beta) = X_s cos beta), Cg = g/(2 omega) — anchors the
    # finite-depth energy-relation test's constant
    iw = wi[1.2]
    K12 = 1.2**2 / 9.81
    Cg = 9.81 / (2 * 1.2)
    B_hask = K12 / (8 * np.pi * 1.0 * 9.81 * Cg) * np.pi * np.abs(
        Xg[0, 0, iw]) ** 2
    assert abs(Bg[0, 0, iw] - B_hask) / B_hask < 0.02


@pytest.mark.slow
def test_oc4semi_potmod2_end_to_end(tmp_path, native_bem_env):
    """OC4semi runs potModMaster=2 END TO END with NO pre-existing
    coefficient files: members are auto-meshed, the native panel solver
    produces A/B/X through the WAMIT interchange round trip, and the
    dynamics solve consumes them.  Sanity vs the shipped MARIN/WAMIT
    dataset for the same platform (marin_semi.1) at panel-method
    engineering tolerance."""
    import os

    import raft_tpu
    from raft_tpu.io.wamit import read_wamit1
    from raft_tpu.structure.schema import load_design

    require_native_env(native_bem_env, "native", "reference")
    design = load_design("/root/reference/designs/OC4semi.yaml")
    design["platform"]["potModMaster"] = 2
    design["settings"]["min_freq"] = 0.01
    design["settings"]["max_freq"] = 0.16
    design["settings"]["nAz_BEM"] = 10     # coarse mesh for CI runtime
    design["settings"]["dz_BEM"] = 3.0
    model = raft_tpu.Model(design)

    w_bem = np.arange(0.15, 1.05, 0.15)
    bem = model.run_bem(save_dir=str(tmp_path), w_bem=w_bem,
                        headings=[0.0, 90.0, 180.0, 270.0])
    # install the computed coefficients so the dynamics solve below
    # consumes THESE (not a fresh default-grid solve via the lazy
    # bem_list property)
    model._bem_list = [bem]
    assert os.path.exists(tmp_path / "OC4-DeepCwind_semisubmersible.1") or \
        any(p.suffix == ".1" for p in tmp_path.iterdir())

    # sanity vs the shipped WAMIT-format data for this platform
    wg, Abar, Bbar = read_wamit1(
        "/root/reference/tests/test_data/OC4semi-WAMIT_Coefs/marin_semi.1")
    rho = 1025.0
    mask = np.isfinite(wg) & (wg >= 0.3) & (wg <= 1.0)
    A11g = np.interp(0.6, wg[mask], (rho * Abar[0, 0])[mask])
    A33g = np.interp(0.6, wg[mask], (rho * Abar[2, 2])[mask])
    A11 = np.interp(0.6, np.asarray(model.w), bem["A_BEM"][0, 0, :])
    A33 = np.interp(0.6, np.asarray(model.w), bem["A_BEM"][2, 2, :])
    assert abs(A11 - A11g) / abs(A11g) < 0.2
    assert abs(A33 - A33g) / abs(A33g) < 0.2

    # full dynamics with the native coefficients
    case = dict(model.cases[0]) if model.cases else dict(
        wave_spectrum="JONSWAP", wave_period=10.0, wave_height=4.0,
        wave_heading=0.0, wind_speed=0, wind_heading=0, turbulence=0,
        turbine_status="operating", yaw_misalign=0)
    Xi, info = model.solve_dynamics(case)
    assert np.isfinite(np.asarray(Xi)).all()


def test_interior_panel_removal(native_bem_env):
    """Panels buried inside an intersecting member are removed (the
    functional effect of the reference's boolean-union
    IntersectionMesh); surface panels survive."""
    import raft_tpu
    from raft_tpu.io.panels import mesh_fowt
    from raft_tpu.structure.schema import load_design

    require_native_env(native_bem_env, "reference")
    design = load_design("/root/reference/designs/OC4semi.yaml")
    design["platform"]["potModMaster"] = 2
    design["settings"]["nAz_BEM"] = 8
    design["settings"]["dz_BEM"] = 3.0
    model = raft_tpu.Model(design)
    fs = model.fowtList[0]
    v1, c1, n1, a1 = mesh_fowt(fs, dz_max=3.0, n_az=8, intersect=False)
    v2, c2, n2, a2 = mesh_fowt(fs, dz_max=3.0, n_az=8, intersect=True)
    # OC4's pontoons/braces run into the columns: the union surface is
    # smaller than the sum of member surfaces (interior portions
    # removed; junction panels are subdivided, so compare AREA, not
    # panel count — clipping refines the mesh along intersection curves)
    assert float(np.sum(a2)) < float(np.sum(a1))
    assert float(np.sum(a2)) > 0.7 * float(np.sum(a1))


def test_fd_green_series_vs_pv_integral():
    """John's eigenfunction series (the finite-depth C++ kernel's
    formulation) matches the direct PV-integral evaluation of the
    finite-depth wave Green function to ~1e-8 at scattered points and
    depths (raft_tpu/native/green_fd.py prototype)."""
    from raft_tpu.native.green_fd import (dispersion_roots, green_fd_reference,
                                          green_fd_series)

    for (K, h) in [(0.12, 50.0), (0.05, 30.0), (0.8, 20.0)]:
        k0, km = dispersion_roots(K, h, 64)
        assert abs(k0 * np.tanh(k0 * h) - K) < 1e-12 * K
        res = np.abs(km * np.tan(km * h) + K)
        assert np.max(res) < 1e-9
        for (Rh, z, zeta) in [(10.0, -5.0, -8.0), (3.0, -2.0, -15.0),
                              (12.0, -9.0, -1.0)]:
            gs = green_fd_series(Rh, z, zeta, K, h, n_modes=200)
            gr = green_fd_reference(Rh, z, zeta, K, h)
            assert abs(gs - gr) / abs(gr) < 1e-7


def test_fd_mode_count_tracks_panel_spacing():
    """The evanescent mode count scales so the small-R extrapolation
    cutoff Rc = 40 h / (pi n) stays at or below half the panel edge
    scale — near-field accuracy must track mesh refinement instead of
    being floored by the default 512 modes."""
    import warnings

    from raft_tpu.native import _fd_mode_count

    h = 50.0
    # coarse mesh (4 m panels): the default already resolves it
    assert _fd_mode_count(h, np.array([16.0]), 512) == 512
    # fine mesh (0.5 m panels): needs more modes; Rc <= d_panel/2
    n = _fd_mode_count(h, np.array([0.25]), 512)
    assert n > 512
    assert 40.0 * h / (np.pi * n) <= 0.5 * 0.5 + 1e-9
    # absurdly fine mesh: capped with a warning
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        n = _fd_mode_count(h, np.array([1e-4]), 512, n_cap=2048)
    assert n == 2048
    assert any("evanescent modes" in str(w.message) for w in rec)


@pytest.mark.slow
def test_fd_solver_shallow_energy_relation(native_bem_env):
    """Genuinely shallow water (depth 12 m, K h ~ 0.5-2): the
    finite-depth solver's radiation damping satisfies the
    finite-depth Haskind energy relation

        B_jj = k0 / (8 pi rho g Cg) * int_0^2pi |X_j(beta)|^2 dbeta

    with the FINITE-DEPTH group velocity
    Cg = (omega/k0)/2 (1 + 2 k0 h / sinh 2 k0 h) — a closed consistency
    test between the solver's near-field damping and its far-field
    radiation in genuinely shallow water (K h ~ 0.6-1.5).

    Gates at the measured panel-discretisation residual: the ratio
    B/B_Haskind converges toward 1 with mesh refinement (surge
    1.078 -> 1.064, heave 0.839 -> 0.858 over a 4x panel-count sweep);
    the shallow gap flow under the 6 m draft in 12 m depth converges
    slowly under centroid collocation.  The deep-water counterpart of
    the same constant is verified to 0.4% against the HAMS golden in
    test_frequency_solver_vs_hams_fixture."""
    from raft_tpu.io.panels import mesh_cylinder
    from raft_tpu.native import solve_bem_frequency
    from raft_tpu.native.green_fd import dispersion_roots

    require_native_env(native_bem_env, "native")
    h = 12.0
    verts, cents, norms, areas = mesh_cylinder(
        stations=[0.0, 6.0], diameters=[8.0, 8.0],
        rA=np.array([0.0, 0.0, -6.0]), q=np.array([0.0, 0.0, 1.0]),
        n_az=20, dz_max=1.0,
    )
    rho, g = 1025.0, 9.81
    nh = 16
    heads = np.linspace(0.0, 2 * np.pi, nh, endpoint=False)
    for omega in (0.7, 1.1):
        K = omega * omega / g
        assert K * h < 6.0  # exercises the FD series path
        A, B, X = solve_bem_frequency(verts, cents, norms, areas, omega,
                                      headings_rad=heads, depth=h, rho=rho,
                                      g=g)
        k0, _ = dispersion_roots(K, h, 1)
        Cg = (omega / k0) * 0.5 * (1 + 2 * k0 * h / np.sinh(2 * k0 * h))
        dbeta = 2 * np.pi / nh
        for j in (0, 2):  # surge, heave
            integ = np.sum(np.abs(X[:, j]) ** 2) * dbeta
            B_hask = k0 / (8 * np.pi * rho * g * Cg) * integ
            assert B[j, j] > 0
            assert 0.80 < B[j, j] / B_hask < 1.12, (omega, j)
