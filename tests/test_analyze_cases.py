"""End-to-end analyzeCases parity vs reference goldens.

Exercises the full chain: statics -> mooring equilibrium -> (aero-servo
constants) -> wave excitation -> iterative drag linearisation ->
impedance solve -> response statistics, against
*_true_analyzeCases.pkl.

Tolerances: the no-wind case matches at golden tolerance (1e-5); the
WIND case carries the ~1% BEMT-vs-CCBlade load/derivative deviation
through the aero damping and mean thrust, so motion PSDs are gated at
1.5e-2 relative to the spectral peak.

Known golden anomalies (measured, documented rather than hidden):

* The OC3 wind-case ``Tmoor_PSD`` golden has high-frequency content
  that cannot be reproduced from the reference's own documented
  moorMod-0 algorithm (tension Jacobian x motion amplitudes,
  raft_fowt.py:2364-2368) using the golden's own stored motion RAs —
  we match those RAs to 0.5% and the mean tensions to 1e-4, yet the
  slack-line tension std differs ~30%, with the discrepancy growing
  with frequency like a line-inertia term.  Tension spectra are
  therefore gated loosely for the wind case.
* The VolturnUS-S goldens embed a ~1.2e5 N mean surge force in the
  no-wind case (surge_avg 1.61 m vs 0.43 m) inconsistent with the
  reference's own hardcoded solveStatics target for the same design
  (tests/test_model.py wave case, which we match to 1e-8) — consistent
  with a wave-mean-drift term from a potSecOrder>0 configuration no
  longer in the shipped YAML.  VolturnUS analyzeCases parity is
  covered through the statics targets + per-stage goldens instead.
"""

import os
import pickle

import numpy as np
import pytest
from numpy.testing import assert_allclose

from tests.conftest import ref_data

import raft_tpu

pytestmark = pytest.mark.slow

METRICS = [
    "wave_PSD", "surge_PSD", "sway_PSD", "heave_PSD", "roll_PSD",
    "pitch_PSD", "yaw_PSD", "AxRNA_PSD", "Mbase_PSD", "Tmoor_PSD",
]


def test_analyze_cases_oc3_nowind():
    path = ref_data("OC3spar.yaml")
    if not os.path.exists(path):
        pytest.skip("reference data unavailable")
    model = raft_tpu.Model(path)
    res = model.analyze_cases()
    with open(path.replace(".yaml", "_true_analyzeCases.pkl"), "rb") as f:
        true = pickle.load(f)

    # case 0 has wind_speed == 0 (no aero); golden-tolerance parity
    iCase = 0
    assert model.cases[iCase]["wind_speed"] == 0
    for metric in METRICS:
        a = np.asarray(res["case_metrics"][iCase][0][metric])
        b = np.asarray(true["case_metrics"][iCase][0][metric])
        if metric == "Tmoor_PSD":
            # the reference's tension spectra inherit MoorPy's coarse
            # 0.1-step finite-difference tension Jacobian (including a
            # 0.1 *rad* rotational step); we replicate the secant but
            # small catenary-model differences remain visible at ~3e-5
            assert_allclose(a, b, rtol=3e-5, atol=1e-3, err_msg=metric)
        else:
            assert_allclose(a, b, rtol=1e-5, atol=1e-3, err_msg=metric)

    # ---- WIND case (case 1, 10 m/s operating): full aero-servo chain.
    iCase = 1
    assert model.cases[iCase]["wind_speed"] > 0
    mc = res["case_metrics"][iCase][0]
    gc = true["case_metrics"][iCase][0]
    # mean offsets carry the mean rotor thrust through the equilibrium
    assert_allclose(float(np.asarray(mc["surge_avg"])),
                    float(np.asarray(gc["surge_avg"])), rtol=2e-4)
    assert_allclose(float(np.asarray(mc["pitch_avg"])),
                    float(np.asarray(gc["pitch_avg"])), rtol=2e-3)
    # motion spectra: aero damping folds the ~1% BEMT derivative
    # deviation into the response peaks
    for metric in ("wave_PSD", "surge_PSD", "heave_PSD", "pitch_PSD",
                   "yaw_PSD", "AxRNA_PSD", "Mbase_PSD"):
        a = np.asarray(mc[metric])
        b = np.asarray(gc[metric])
        scale = np.max(np.abs(b)) + 1e-12
        assert np.max(np.abs(a - b)) / scale < 1.5e-2, metric
    # mean tensions at the wind-loaded offset
    assert_allclose(np.asarray(mc["Tmoor_avg"]), np.asarray(gc["Tmoor_avg"]),
                    rtol=1e-3)
    # tension spectra: loose gate only (see module docstring)
    a = np.asarray(mc["Tmoor_PSD"])
    b = np.asarray(gc["Tmoor_PSD"])
    assert np.max(np.abs(a - b)) / (np.max(np.abs(b)) + 1e-12) < 0.5
