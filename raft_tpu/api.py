"""High-level traced evaluation API: one design evaluation as a pure
jax function, ready to jit / vmap / shard_map.

The reference evaluates one (design, load case) pair by a long chain of
Python method calls mutating FOWT state (Model.analyzeCases,
raft_model.py:264-433).  Here the same chain — static equilibrium →
wave excitation → iterative drag linearisation → impedance solve →
response statistics — is closed over the build-time structure and
exposed as ``evaluate(Hs, Tp, beta)``:

* jit once, then every additional (case x design-parameter) evaluation
  is a batched tensor program;
* ``vmap`` adds case/sea-state axes;
* device-mesh sharding (see :mod:`raft_tpu.parallel.sweep`) scales the
  batch across a TPU pod with XLA inserting the collectives.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.models.dynamics import (fused_response_enabled,
                                      solve_dynamics_fowt, system_response)
from raft_tpu.models.statics_solve import solve_equilibrium
from raft_tpu.physics import morison
from raft_tpu.physics.mooring import mooring_stiffness
from raft_tpu.physics.statics import calc_statics, node_T, platform_kinematics
from raft_tpu.ops import waves as wv
from raft_tpu.utils import health
from raft_tpu.utils.dtypes import compute_dtypes


def _policy_cdt():
    """Trace-time complex dtype for the excitation-prefix
    allocations, honouring RAFT_TPU_DTYPE (the default derives to
    the x64-canonical complex dtype, i.e. the historical
    behaviour)."""
    return compute_dtypes()[1]


def _case_status(st_status, dyn_diag, X0, Xi, input_clipped=False):
    """Assemble one case's solver-health word (int32, vmap-safe): the
    statics Newton bits OR the dynamics-solve bits OR evaluator-level
    guards (non-finite outputs, clamped inputs).  Every traced
    evaluator returns this as the first-class ``"status"`` output —
    the in-band replacement for host warnings that cannot survive a
    pjit sweep (see :mod:`raft_tpu.utils.health`)."""
    status = st_status | dyn_diag["status"]
    status = health.set_bit(
        status, health.NONFINITE_INTERMEDIATE,
        ~(jnp.all(jnp.isfinite(X0)) & jnp.all(jnp.isfinite(Xi))))
    status = health.set_bit(status, health.INPUT_CLIPPED, input_clipped)
    return jnp.asarray(status, dtype=jnp.int32)


def _stamp_program_key(evaluate, factory, model, *extra):
    """Stamp the evaluator's AOT-bank identity
    (``evaluate._raft_program_key``): factory name + a content hash of
    the design dict + the trace-shaping factory arguments.  The sweep
    funnel (:func:`raft_tpu.parallel.sweep._cached_jit`) banks only
    stamped closures — without the stamp, nothing in the bank key
    distinguishes the constants a trace baked in, and two designs
    could collide on one exported program
    (:mod:`raft_tpu.aot.bank`)."""
    from raft_tpu.aot.bank import content_fingerprint

    evaluate._raft_program_key = (
        factory, content_fingerprint((model.design, extra)))
    return evaluate


def make_design_evaluator(model):
    """Build ``evaluate(params) -> outputs`` with traced *design*
    parameters — the 10k-design-sweep axis of the north star.

    params (all optional, broadcastable scalars):
      Hs, Tp, beta       sea state
      Cd_scale, Ca_scale strip drag / added-mass coefficient multipliers
      L_moor_scale       mooring unstretched-length multiplier

    Geometry shapes are fixed per design family; the parameters scale
    the build-time tensors inside the trace, so the whole map is
    jit/vmap-able over designs AND differentiable (e.g. optimize
    mooring length against a response metric with ``jax.grad``).

    For a design axis over *heterogeneous member layouts* (mixed
    spar/semi/MHK topologies in one DoE) use the shape-bucketed path
    instead: :func:`make_bucket_evaluator` /
    :func:`raft_tpu.parallel.sweep.sweep_heterogeneous` make the
    design itself a traced, validity-masked input padded to a shape
    bucket, so one compiled program serves every layout in the bucket.
    """
    import dataclasses

    fs = model.fowtList[0]
    ms0 = model.ms
    fh = model.hydro[0]
    ss0 = fh.strips
    w = jnp.asarray(model.w)
    k = jnp.asarray(model.k)
    dw = model.w[1] - model.w[0]
    nw = model.nw
    nDOF = fs.nDOF

    stat = model.statics()
    K_h = np.asarray(stat["C_struc"] + stat["C_hydro"])
    F_und = np.asarray(stat["W_struc"] + stat["W_hydro"] + stat["f0_additional"])
    M_struc = np.asarray(stat["M_struc"])

    def evaluate(params):
        Hs = params.get("Hs", 6.0)
        Tp = params.get("Tp", 12.0)
        beta = params.get("beta", 0.0)
        Cd_s = params.get("Cd_scale", 1.0)
        Ca_s = params.get("Ca_scale", 1.0)
        L_s = params.get("L_moor_scale", 1.0)

        ss = dataclasses.replace(
            ss0,
            Cd_q=jnp.asarray(ss0.Cd_q) * Cd_s,
            Cd_p1=jnp.asarray(ss0.Cd_p1) * Cd_s,
            Cd_p2=jnp.asarray(ss0.Cd_p2) * Cd_s,
            Cd_End=jnp.asarray(ss0.Cd_End) * Cd_s,
            Ca_q=jnp.asarray(ss0.Ca_q) * Ca_s,
            Ca_p1=jnp.asarray(ss0.Ca_p1) * Ca_s,
            Ca_p2=jnp.asarray(ss0.Ca_p2) * Ca_s,
            Ca_End=jnp.asarray(ss0.Ca_End) * Ca_s,
            Cm_p1_w=1.0 + Ca_s * (jnp.asarray(ss0.Cm_p1_w) - 1.0),
            Cm_p2_w=1.0 + Ca_s * (jnp.asarray(ss0.Cm_p2_w) - 1.0),
        )
        ms = None
        if ms0 is not None:
            ms = dataclasses.replace(ms0, L=jnp.asarray(ms0.L) * L_s)

        # mean offsets
        X0, _, _, _, st_status = solve_equilibrium(
            fs, ms, K_h, F_und, jnp.zeros(nDOF))

        r_nodes, R_ptfm, r_root = platform_kinematics(fs, X0)
        Tn = node_T(r_nodes, r_root)
        # hydro constants recomputed in-trace (coefficients are traced)
        hc = morison.hydro_constants(fs, ss, R_ptfm, r_nodes, Tn)

        S = wv.jonswap(w, Hs, Tp)
        zeta = jnp.sqrt(2.0 * S * dw).astype(_policy_cdt())
        exc = morison.hydro_excitation(
            fs, ss, hc, zeta[None, :], jnp.asarray([beta]), w, k, Tn, r_nodes)

        C_moor = jnp.zeros((nDOF, nDOF))
        if ms is not None:
            C_moor = C_moor.at[:6, :6].add(mooring_stiffness(ms, X0[:6]))
        M_lin = jnp.broadcast_to(
            (jnp.asarray(M_struc) + hc["A_hydro"])[:, :, None], (nDOF, nDOF, nw))
        B_lin = jnp.zeros((nDOF, nDOF, nw))
        C_lin = jnp.asarray(K_h) + C_moor
        F_lin = exc["F_hydro_iner"][0]

        Z, Xi_fused, Bmat, dyn_diag = solve_dynamics_fowt(
            fs, ss, hc, exc["u"][0], M_lin, B_lin, C_lin, F_lin,
            w, Tn, r_nodes, n_iter=model.nIter, Xi_start=model.XiStart,
            n_iter_extra=model.nIterExtra)
        if fused_response_enabled():
            Xi = Xi_fused  # fused hot path (see models.dynamics)
        else:
            F_wave = exc["F_hydro_iner"][0] + morison.drag_excitation(
                fs, ss, hc, Bmat, exc["u"][0], Tn, r_nodes)
            Xi = system_response(Z, F_wave[None])[0]
        return dict(
            X0=X0, Xi=Xi, RAO=wv.get_rao(Xi, zeta),
            PSD=0.5 * jnp.abs(Xi) ** 2 / dw, S=S,
            drag_resid=dyn_diag["drag_resid"],
            drag_converged=dyn_diag["drag_converged"],
            n_iter_drag=dyn_diag["n_iter_drag"],
            status=_case_status(st_status, dyn_diag, X0, Xi),
        )

    return _stamp_program_key(evaluate, "design_evaluator", model)


def make_bucket_evaluator(sig):
    """Traced case evaluator over PACKED HETEROGENEOUS DESIGNS — the
    shape-bucketed design axis (re-exported from
    :mod:`raft_tpu.structure.bucketing`; see that module for the
    padding/masking contract).

    ``sig`` is a bucket signature from
    :func:`raft_tpu.structure.bucketing.bucket_signature`; the returned
    ``evaluate(case)`` takes ``case["design"]`` (a
    :func:`~raft_tpu.structure.bucketing.pack_design` pytree) plus
    scalar ``Hs``/``Tp``/``beta`` and vmaps over the whole case dict,
    so ONE compiled program serves every member layout that packs into
    the bucket.  Most callers want the auto-binning dispatcher
    :func:`raft_tpu.parallel.sweep.sweep_heterogeneous` instead.

    Returns the PROCESS-CACHED evaluator for the signature (bucket
    evaluators close over nothing but ``sig``): the sweep memo lives on
    the evaluator's attribute dict, so handing every caller the same
    object is what keeps repeat sweeps compile-free."""
    from raft_tpu.structure.bucketing import get_bucket_evaluator

    return get_bucket_evaluator(sig)


def pack_for_serving(model):
    """Request→packed-row adapter for the evaluation service
    (:mod:`raft_tpu.serve`): resolve one built model into everything a
    serving batcher needs to coalesce its requests into a shared
    bucket program — ``(sig, packed, fingerprint)`` where ``sig`` is
    the :func:`raft_tpu.structure.bucketing.bucket_signature` routing
    key, ``packed`` the padded design pytree one request contributes as
    a batch row (:func:`~raft_tpu.structure.bucketing.pack_design`),
    and ``fingerprint`` the design-content hash that keys the service's
    result cache (:mod:`raft_tpu.serve.cache`).

    Raises :class:`raft_tpu.structure.bucketing.UnbucketableDesignError`
    for designs outside the bucketed single-case chain (flexible
    topologies, potential flow, farms) — the service rejects those at
    registration, not mid-tick."""
    from raft_tpu.aot.bank import content_fingerprint
    from raft_tpu.structure import bucketing

    sig = bucketing.bucket_signature(model)
    packed = bucketing.pack_design(model, sig)
    return sig, packed, content_fingerprint(model.design)


def case_to_traced(case, nWaves=1):
    """Translate a parsed case-table row (reference key names,
    docs/usage.rst:167) into the traced-evaluator case dict consumed by
    :func:`make_full_evaluator` — scalar wind/current parameters plus
    (nWaves,) sea-state arrays."""
    from raft_tpu.structure.schema import coerce

    turb = case.get("turbulence", 0.0)
    TI = float(turb) if not isinstance(turb, str) else 0.0
    return dict(
        wind_speed=float(coerce(case, "wind_speed", shape=0, default=0.0)),
        wind_heading_deg=float(coerce(case, "wind_heading", shape=0,
                                      default=0.0)),
        TI=TI,
        yaw_misalign_deg=float(coerce(case, "yaw_misalign", shape=0,
                                      default=0.0)),
        current_speed=float(coerce(case, "current_speed", shape=0,
                                   default=0.0)),
        current_heading_deg=float(coerce(case, "current_heading", shape=0,
                                         default=0.0)),
        Hs=jnp.asarray(coerce(case, "wave_height", shape=nWaves), dtype=float),
        Tp=jnp.asarray(coerce(case, "wave_period", shape=nWaves), dtype=float),
        gamma=jnp.asarray(coerce(case, "wave_gamma", shape=nWaves,
                                 default=0.0), dtype=float),
        beta_deg=jnp.asarray(coerce(case, "wave_heading", shape=nWaves),
                             dtype=float),
    )


def case_in_traced_domain(case):
    """True when a parsed case row is inside the traced evaluators'
    STATIC assumptions: operating turbine, JONSWAP seas, numeric
    turbulence intensity, one wave heading.  IEC turbulence-class
    strings ('IB_NTM', ...), parked/idle rotors and unit/still spectra
    are resolved by the host path's per-case branching
    (models/model.py:337-348, models/hydro.py:39-49) which the traced
    build bakes in — routing such cases through the trace would
    silently evaluate different physics."""
    if isinstance(case.get("turbulence", 0.0), str):
        return False
    if str(case.get("turbine_status", "operating")) != "operating":
        return False
    spec = case.get("wave_spectrum", "JONSWAP")
    specs = [spec] if isinstance(spec, str) else list(np.atleast_1d(spec))
    if any(str(s).upper() not in ("JONSWAP",) for s in specs):
        return False
    return np.ndim(case.get("wave_heading", 0.0)) == 0


def _interp_heading_traced(X_BEM, headings, beta_deg):
    """Traced wrap-around heading interpolation + rotation to global
    (jax twin of :func:`raft_tpu.io.wamit.interp_heading`)."""
    X_BEM = jnp.asarray(X_BEM)
    h = np.asarray(headings, dtype=float)
    ext_h = jnp.asarray(np.concatenate([[h[-1] - 360.0], h, [h[0] + 360.0]]))
    ext_X = jnp.concatenate([X_BEM[-1:], X_BEM, X_BEM[:1]], axis=0)
    beta = beta_deg % 360.0
    idx = jnp.clip(jnp.searchsorted(ext_h, beta, side="right") - 1,
                   0, len(h))
    f = (beta - ext_h[idx]) / (ext_h[idx + 1] - ext_h[idx])
    Xp = ext_X[idx] * (1 - f) + ext_X[idx + 1] * f  # (6, nw)

    b = jnp.deg2rad(beta_deg)
    sb, cb = jnp.sin(b), jnp.cos(b)
    return jnp.stack([
        Xp[0] * cb - Xp[1] * sb,
        Xp[0] * sb + Xp[1] * cb,
        Xp[2],
        Xp[3] * cb - Xp[4] * sb,
        Xp[3] * sb + Xp[4] * cb,
        Xp[5],
    ])


def _qtf_model_grid(qtf_data, w):
    """Bilinear-interpolate the QTF onto the model w x w grid per
    heading node (build time; linear interps over independent axes
    commute with the traced heading interpolation)."""
    from scipy.interpolate import RegularGridInterpolator

    w = np.asarray(w)
    nw = len(w)
    w2 = qtf_data["w_2nd"]
    qtf = qtf_data["qtf"]  # (nw2, nw2, nh, 6)
    nh, ndof = qtf.shape[2], qtf.shape[3]
    pts = np.stack(np.meshgrid(w, w, indexing="ij"), axis=-1).reshape(-1, 2)
    Qm = np.zeros((nh, nw, nw, ndof), dtype=np.complex128)
    for ih in range(nh):
        for idof in range(ndof):
            Qr = RegularGridInterpolator((w2, w2), qtf[:, :, ih, idof].real,
                                         bounds_error=False, fill_value=0)(pts)
            Qi = RegularGridInterpolator((w2, w2), qtf[:, :, ih, idof].imag,
                                         bounds_error=False, fill_value=0)(pts)
            Qm[ih, :, :, idof] = (Qr + 1j * Qi).reshape(nw, nw)
    return Qm


def _hydro_force_2nd_traced(Qm, heads_rad, beta, S0, dw):
    """Traced difference-frequency force realization
    (calcHydroForce_2ndOrd 'qtf' mode, raft_fowt.py:2218-2245).

    Qm : (nh, nw, nw, 6) model-grid QTF; beta traced [rad]; S0 (nw,).
    Returns (f_mean (6,), f (6, nw) real)."""
    nh, nw = Qm.shape[0], Qm.shape[1]
    heads = jnp.asarray(heads_rad)
    if nh == 1:
        Q = jnp.asarray(Qm)[0]
    else:
        b = jnp.clip(beta, heads[0], heads[-1])
        i = jnp.clip(jnp.searchsorted(heads, b) - 1, 0, nh - 2)
        f = (b - heads[i]) / (heads[i + 1] - heads[i])
        Q = jnp.asarray(Qm)[i] * (1 - f) + jnp.asarray(Qm)[i + 1] * f

    j = jnp.arange(nw)
    col = j[None, :] + j[:, None]              # (mu, j) -> j + mu
    valid = col < nw
    colc = jnp.minimum(col, nw - 1)
    Qd = Q[j[None, :], colc, :]                # (mu, j, 6) = Q[j, j+mu]
    Ssh = S0[colc]
    P = S0[None, :, None] * Ssh[:, :, None] * jnp.abs(Qd) ** 2
    P = jnp.where(valid[:, :, None], P, 0.0)
    f_mu = 4.0 * jnp.sqrt(jnp.sum(P, axis=1)) * dw       # (mu, 6)
    # shift difference frequencies onto the model grid (raft_fowt.py:2241-2245)
    f_out = jnp.concatenate([f_mu[1:], jnp.zeros((1, f_mu.shape[1]))], axis=0)
    diagQ = Q[j, j, :].real                               # (nw, 6)
    f_mean = 2.0 * jnp.sum(S0[:, None] * diagQ, axis=0) * dw
    return f_mean, f_out.T


def _lagrange3(vals, s_nodes, s):
    """Quadratic Lagrange interpolation of stacked sample arrays
    vals (3, ...) at traced scalar s."""
    s0, s1, s2 = (float(x) for x in s_nodes)
    l0 = (s - s1) * (s - s2) / ((s0 - s1) * (s0 - s2))
    l1 = (s - s0) * (s - s2) / ((s1 - s0) * (s1 - s2))
    l2 = (s - s0) * (s - s1) / ((s2 - s0) * (s2 - s1))
    v = jnp.asarray(vals)
    return l0 * v[0] + l1 * v[1] + l2 * v[2]


def make_full_evaluator(model, nWaves=1, turb_static=None, geometry=False):
    """Build the FULL-PHYSICS traced case evaluator for a single-FOWT
    model: aero-servo constants + gyroscopics, potential-flow A/B/X,
    multi-heading Morison excitation, external-QTF second-order forces,
    current loads, equilibrium with environmental mean forces, the
    drag-linearised impedance solve and the multi-source response — one
    pure jax function of the load-case parameters, jit/vmap-able over
    the (case x design) sweep axes.

    This is the end-to-end jit of Model.analyzeCases' per-case chain
    (raft_model.py:264-433, solveDynamics :966-1255) for a rigid FOWT.

    ``evaluate(case)`` takes a dict of (traced) values:
        wind_speed, wind_heading_deg, TI (turbulence intensity),
        yaw_misalign_deg, current_speed, current_heading_deg  — scalars
        Hs, Tp, gamma, beta_deg                               — (nWaves,)
    and returns X0, Xi (nWaves+1, nDOF, nw), RAO, PSD, S, plus the aero
    channel ingredients (f_aero, V_w, ...).

    Static per evaluator: nWaves, spectrum type (JONSWAP), operating
    turbine status, and the turbulence *class* (``turb_static``
    overrides the (TurbMod, V_ref_cls) pair, default NTM/class-I).

    geometry=True enables the traced GEOMETRY design axis — the WEIS
    design variables (member diameters/thicknesses, ballast fills,
    mooring length/stiffness; omdao_raft.py:26-343,
    parametersweep.py:56-100): ``case`` may then carry a ``geom`` dict
    (keys of :func:`raft_tpu.structure.members_traced.apply_geometry`
    plus ``L_moor_scale`` / ``EA_moor_scale``), statics + hydro
    constants are recomputed in-trace from the traced member geometry,
    and ONE compilation serves an entire geometry DoE — differentiable
    end-to-end (``jax.grad`` of any response metric wrt any geometry
    parameter via the implicit-function-theorem fixed points).
    Potential-flow designs (native-solver potMod members) get their
    A/B/X coefficients from a per-evaluator 3-point diameter-scale
    sampling of the native BEM solver, entering the trace as a
    quadratic interpolation in the scalar ``d_scale`` — the traced
    analogue of the WEIS loop re-running HAMS per design iteration.
    """
    import dataclasses

    fs = model.fowtList[0]
    assert model.nFOWT == 1, "full traced evaluator covers single-FOWT models"
    assert fs.is_single_body, "full traced evaluator covers rigid 6-DOF FOWTs"
    ms = model.ms
    fh = model.hydro[0]
    ss = fh.strips
    w = jnp.asarray(model.w)
    k = jnp.asarray(model.k)
    dw = model.w[1] - model.w[0]
    nw = model.nw
    nDOF = fs.nDOF

    stat = model.statics()
    K_h = np.asarray(stat["C_struc"] + stat["C_hydro"])
    C_elast = np.asarray(stat["C_elast"])
    F_und = np.asarray(stat["W_struc"] + stat["W_hydro"] + stat["f0_additional"])
    M_struc = np.asarray(stat["M_struc"])
    A_hydro = np.asarray(fh.hc0["A_hydro"])
    hc0 = fh.hc0
    # zero-pose reduction rows (N, 6, nDOF) — computed fresh, NOT taken
    # from fh.Tn which tracks whatever pose set_position last applied
    r0_nodes = jnp.asarray(fs.node_r0, dtype=float)
    Tn0 = node_T(r0_nodes, r0_nodes[fs.root_id])

    # potential-flow coefficients (constants on the model grid)
    bem = model.bem
    A_BEM = np.zeros((nDOF, nDOF, nw))
    B_BEM = np.zeros((nDOF, nDOF, nw))
    if bem is not None:
        A_BEM[:6, :6, :] = bem["A_BEM"]
        B_BEM[:6, :6, :] = bem["B_BEM"]
    has_X = bem is not None and np.any(np.abs(bem["X_BEM"]) > 0)
    # geometry axis for potential-flow designs: the WEIS loop re-runs
    # HAMS per design iteration (raft_model.py:1509 preprocess_HAMS,
    # omdao_raft.py member d/t inputs); here the native solver runs at
    # a few diameter scales ONCE and the coefficients enter the trace
    # as a quadratic interpolation in the (scalar) d_scale — so the
    # geometry DoE stays one compiled evaluator (validity test:
    # tests/test_geometry_axis.py::test_geometry_bem_interpolation)
    bem_samples = None
    if geometry and bem is not None:
        if fs.potFirstOrder == 1 and fs.hydroPath:
            raise ValueError(
                "geometry tracing with potential flow needs the NATIVE "
                "solver (file-loaded WAMIT coefficients cannot be "
                "re-solved per geometry)")
        settings = model.design.get("settings", {}) or {}
        scales = tuple(float(s) for s in settings.get(
            "bem_geom_scales", (0.92, 1.0, 1.08)))
        if len(scales) != 3 or len(set(scales)) != 3:
            raise ValueError("bem_geom_scales: exactly 3 DISTINCT sample "
                             "scales; d_scale should stay inside their span "
                             "(the quadratic fit extrapolates beyond it)")

        _bem_cache = []

        def bem_samples():
            """Sampled-coefficient table, solved lazily on the first
            geometry_constants trace that carries a d_scale (so DoEs
            that never vary the diameter pay no extra solves)."""
            if not _bem_cache:
                bems = [bem if abs(s - 1.0) < 1e-12
                        else model.run_bem(d_scale=s) for s in scales]
                _bem_cache.append(dict(
                    s=np.asarray(scales),
                    A=np.stack([np.asarray(b["A_BEM"]) for b in bems]),
                    B=np.stack([np.asarray(b["B_BEM"]) for b in bems]),
                    X=np.stack([np.asarray(b["X_BEM"]) for b in bems]),
                ))
            return _bem_cache[0]

    # external difference-frequency QTF on the model grid
    qtf = model.qtf
    Qm = _qtf_model_grid(qtf, model.w) if qtf is not None else None

    # rotor aero models (static schedules/polars)
    rotor_aero = model.rotor_aero if fs.nrotors else []
    from raft_tpu.physics.aero import calc_aero_traced, operating_point

    from raft_tpu.models.statics_solve import make_tolerances
    tol_vec, caps, refs = make_tolerances([fs])

    def geometry_constants(geom):
        """Per-design geometry stage: traced member geometry -> statics
        matrices + zero-pose hydro constants + scaled strips/mooring.
        Call once per design and feed the result to ``evaluate`` as
        ``case["geom_const"]`` to amortise over a case table (the
        geometry work is case-independent)."""
        from raft_tpu.models.hydro import add_rotor_added_mass
        from raft_tpu.structure.members_traced import apply_geometry

        fs2, ss_t = apply_geometry(fs, ss, geom, k=k)
        stat_t = calc_statics(fs2)
        hc0_t = morison.hydro_constants(fs2, ss_t, jnp.eye(3), r0_nodes, Tn0)
        A_hydro_t = add_rotor_added_mass(hc0_t["A_hydro"], fs, Tn0)
        ms_t = ms
        if ms is not None:
            ms_t = dataclasses.replace(
                ms,
                L=jnp.asarray(ms.L) * geom.get("L_moor_scale", 1.0),
                EA=jnp.asarray(ms.EA) * geom.get("EA_moor_scale", 1.0),
            )
        out = dict(
            ss=ss_t, ms=ms_t,
            K_h=stat_t["C_struc"] + stat_t["C_hydro"],
            C_elast=stat_t["C_elast"],
            F_und=stat_t["W_struc"] + stat_t["W_hydro"] + stat_t["f0_additional"],
            M_struc=stat_t["M_struc"],
            A_hydro=A_hydro_t,
            hc0=dict(hc0_t, A_hydro=A_hydro_t),
        )
        if bem_samples is not None and "d_scale" in geom:
            gs = jnp.asarray(geom["d_scale"], dtype=float)
            if gs.ndim != 0:
                raise ValueError(
                    "potential-flow geometry interpolation supports a "
                    "SCALAR d_scale (one uniform diameter scale); keep it "
                    "inside the bem_geom_scales span — the quadratic fit "
                    "extrapolates beyond it")
            tab = bem_samples()
            out["A_BEM6"] = _lagrange3(tab["A"], tab["s"], gs)
            out["B_BEM6"] = _lagrange3(tab["B"], tab["s"], gs)
            out["X_BEM6"] = _lagrange3(tab["X"], tab["s"], gs)
        return out

    def evaluate(case):
        wind_speed = case.get("wind_speed", 0.0)
        wind_heading = case.get("wind_heading_deg", 0.0)
        TI = case.get("TI", 0.0)
        yaw_cmd = jnp.deg2rad(case.get("yaw_misalign_deg", 0.0))
        cur_speed = case.get("current_speed", 0.0)
        cur_heading = case.get("current_heading_deg", 0.0)
        Hs = jnp.atleast_1d(jnp.asarray(case["Hs"], dtype=float))
        Tp = jnp.atleast_1d(jnp.asarray(case["Tp"], dtype=float))
        gamma = jnp.atleast_1d(jnp.asarray(case.get("gamma", 0.0)) * jnp.ones(nWaves))
        beta_deg = jnp.atleast_1d(jnp.asarray(case.get("beta_deg", 0.0)) * jnp.ones(nWaves))
        beta = jnp.deg2rad(beta_deg)

        # ---- traced geometry axis (see docstring)
        ss_t, ms_t = ss, ms
        K_h_t, C_elast_t, F_und_t = K_h, C_elast, F_und
        M_struc_t, A_hydro_t, hc0_t = M_struc, A_hydro, hc0
        A_BEM_t, B_BEM_t, X_BEM_t = A_BEM, B_BEM, None
        if geometry:
            gc = case.get("geom_const")
            if gc is None:
                gc = geometry_constants(case.get("geom", {}))
            ss_t, ms_t = gc["ss"], gc["ms"]
            K_h_t, C_elast_t, F_und_t = gc["K_h"], gc["C_elast"], gc["F_und"]
            M_struc_t, A_hydro_t, hc0_t = gc["M_struc"], gc["A_hydro"], gc["hc0"]
            if "A_BEM6" in gc:
                A_BEM_t = jnp.zeros((nDOF, nDOF, nw)).at[:6, :6, :].set(
                    gc["A_BEM6"])
                B_BEM_t = jnp.zeros((nDOF, nDOF, nw)).at[:6, :6, :].set(
                    gc["B_BEM6"])
                X_BEM_t = gc["X_BEM6"]

        # ---- aero-servo constants about the rotor nodes (zero-pose Tn,
        # matching the reference's calcTurbineConstants-at-case-start)
        f_aero0 = jnp.zeros(nDOF)
        f_aero = jnp.zeros((nDOF, nw), dtype=_policy_cdt())
        A_aero = jnp.zeros((nDOF, nDOF, nw))
        B_aero = jnp.zeros((nDOF, nDOF, nw))
        B_gyro = jnp.zeros((nDOF, nDOF))
        A00 = jnp.zeros((nw, max(fs.nrotors, 1)))
        B00 = jnp.zeros((nw, max(fs.nrotors, 1)))
        Om_out = jnp.zeros(max(fs.nrotors, 1))
        pitch_out = jnp.zeros(max(fs.nrotors, 1))
        input_clipped = jnp.asarray(False)
        for ir, rot in enumerate(rotor_aero):
            rprops = fs.rotors[ir]
            if rprops.aeroServoMod <= 0:
                continue
            current = rprops.Zhub < 0
            speed = cur_speed if current else wind_speed
            heading = jnp.deg2rad(cur_heading if current else wind_heading)
            ts = turb_static or ("NTM", 50.0)
            on = speed > 0
            speed_safe = jnp.maximum(speed, 0.1)
            input_clipped = input_clipped | (on & (speed < 0.1))
            f0, f6, a6, b6, Bg, qv = calc_aero_traced(
                rot, rprops, w, speed_safe, heading, TI, yaw_command_rad=yaw_cmd,
                turb_static=ts)
            node = int(fs.rotor_node[ir])
            Tn = Tn0[node]  # (6, nDOF)
            f_aero0 = f_aero0 + on * (Tn.T @ f0)
            f_aero = f_aero + on * (Tn.T @ f6)
            A_aero = A_aero + on * jnp.einsum("ia,ijw,jb->abw", Tn, a6, Tn)
            B_aero = B_aero + on * jnp.einsum("ia,ijw,jb->abw", Tn, b6, Tn)
            B_gyro = B_gyro + on * (Tn.T @ Bg @ Tn)
            A00 = A00.at[:, ir].set(on * a6[0, 0, :])
            B00 = B00.at[:, ir].set(on * b6[0, 0, :])
            Om_s, pit_s = operating_point(rot, speed_safe)
            Om_out = Om_out.at[ir].set(on * Om_s)
            pitch_out = pitch_out.at[ir].set(on * pit_s)

        # ---- current loads at the reference pose
        F_current = morison.current_loads(
            fs, ss_t, hc0_t, cur_speed, cur_heading,
            min([r.Zhub for r in fs.rotors if r.Zhub < 0], default=0.0),
            Tn0, jnp.asarray(fs.node_r0))

        # ---- mean-offset equilibrium under environmental mean loads
        from raft_tpu.models.statics_solve import solve_equilibrium_general, single_ms_closures
        force, stiff = single_ms_closures(ms_t, nDOF)
        F_env = F_current + f_aero0
        X0, _, _, _, st_status = solve_equilibrium_general(
            jnp.asarray(K_h_t), jnp.asarray(F_und_t), F_env, force, stiff,
            tol_vec, caps, refs, C_elast=jnp.asarray(C_elast_t))

        # ---- pose-dependent strip frames
        r_nodes, R_ptfm, r_root = platform_kinematics(fs, X0)
        Tn = node_T(r_nodes, r_root)
        r, q, p1, p2 = morison.strip_frames(ss_t, R_ptfm, r_nodes)
        sub = r[:, 2] < 0
        hc = dict(hc0_t, r=r, q=q, p1=p1, p2=p2, sub=sub,
                  active=sub & jnp.asarray(ss_t.active))

        # ---- sea states + first-order excitation (all headings)
        S = jax.vmap(lambda h, t, g_: wv.jonswap(w, h, t, gamma=g_))(Hs, Tp, gamma)
        zeta = jnp.sqrt(2.0 * S * dw).astype(_policy_cdt())
        exc = morison.hydro_excitation(fs, ss_t, hc, zeta, beta, w, k, Tn, r_nodes)

        F_BEM = jnp.zeros((nWaves, nDOF, nw), dtype=_policy_cdt())
        if has_X:
            X_tab = bem["X_BEM"] if X_BEM_t is None else X_BEM_t

            def bem_one(bd):
                phase = jnp.exp(-1j * k * (
                    fs.x_ref * jnp.cos(jnp.deg2rad(bd))
                    + fs.y_ref * jnp.sin(jnp.deg2rad(bd))))
                X = _interp_heading_traced(
                    X_tab, bem["headings"], (bd - fs.heading_adjust) % 360)
                return X * phase
            F_BEM = F_BEM.at[:, :6, :].set(
                jax.vmap(bem_one)(beta_deg) * zeta[:, None, :])

        # ---- second-order forces (external QTF)
        F_2nd = jnp.zeros((nWaves, nDOF, nw), dtype=_policy_cdt())
        F_2nd_mean = jnp.zeros((nWaves, nDOF))
        if Qm is not None:
            def qtf_one(b_h, S_h):
                return _hydro_force_2nd_traced(Qm, qtf["heads_rad"], b_h, S_h, dw)
            fm, f2 = jax.vmap(qtf_one)(beta, S)
            F_2nd = F_2nd.at[:, :6, :].set(f2.astype(_policy_cdt()))
            F_2nd_mean = F_2nd_mean.at[:, :6].set(fm)

        # ---- linear system (raft_model.py:1045-1048)
        C_moor = jnp.zeros((nDOF, nDOF))
        if ms is not None:
            C_moor = C_moor.at[:6, :6].add(mooring_stiffness(ms_t, X0[:6]))
        M_lin = A_aero + (M_struc_t + A_hydro_t)[:, :, None] + jnp.asarray(A_BEM_t)
        B_lin = B_aero + jnp.asarray(B_BEM_t) + B_gyro[:, :, None]
        C_lin = jnp.asarray(K_h_t) + C_moor + jnp.asarray(C_elast_t)
        F_lin = F_BEM[0] + exc["F_hydro_iner"][0] + F_2nd[0]

        Z, Xi_fused, Bmat, dyn_diag = solve_dynamics_fowt(
            fs, ss_t, hc, exc["u"][0], M_lin, B_lin, C_lin, F_lin,
            w, Tn, r_nodes, n_iter=model.nIter, Xi_start=model.XiStart,
            n_iter_extra=model.nIterExtra)

        # ---- per-heading responses + zero rotor-source row
        # (reference leaves the rotor excitation row zero,
        # raft_model.py:1246-1255).  With ONE wave heading the solve's
        # own final response is already F_lin + the drag-excitation
        # fold (F_lin carries F_BEM[0] + F_2nd[0] too) — the fused hot
        # path skips the staged chain; extra headings keep it (their
        # drag excitation is heading-specific).
        if nWaves == 1 and fused_response_enabled():
            Xi = Xi_fused[None]
        else:
            def fwave_one(ih):
                F_drag = morison.drag_excitation(fs, ss_t, hc, Bmat,
                                                 exc["u"][ih], Tn, r_nodes)
                return F_BEM[ih] + exc["F_hydro_iner"][ih] + F_drag + F_2nd[ih]
            F_waves = jnp.stack([fwave_one(ih) for ih in range(nWaves)])
            Xi = system_response(Z, F_waves)
        Xi = jnp.concatenate([Xi, jnp.zeros((1, nDOF, nw), dtype=Xi.dtype)])

        # ---- mean-drift fed back into the equilibrium for the reported
        # offsets (raft_model.py:316-328); Xi is not recomputed
        X0_out = X0
        if Qm is not None:
            X0_out, _, _, _, st2 = solve_equilibrium_general(
                jnp.asarray(K_h_t), jnp.asarray(F_und_t),
                F_env + jnp.sum(F_2nd_mean, axis=0), force, stiff,
                tol_vec, caps, refs, C_elast=jnp.asarray(C_elast_t))
            st_status = st_status | st2

        RAO = wv.get_rao(Xi[0], zeta[0])
        PSD = jnp.sum(0.5 * jnp.abs(Xi) ** 2 / dw, axis=0)
        return dict(
            X0=X0_out, Xi=Xi, RAO=RAO, PSD=PSD, S=S, zeta=zeta,
            f_aero=f_aero, A00=A00, B00=B00, f_aero0=f_aero0,
            Omega_rpm=Om_out, pitch_deg=pitch_out,
            F_2nd_mean=F_2nd_mean, Z=Z,
            drag_resid=dyn_diag["drag_resid"],
            drag_converged=dyn_diag["drag_converged"],
            n_iter_drag=dyn_diag["n_iter_drag"],
            status=_case_status(st_status, dyn_diag, X0_out, Xi,
                                input_clipped=input_clipped),
        )

    evaluate.geometry_constants = geometry_constants
    return _stamp_program_key(evaluate, "full_evaluator", model,
                              nWaves, geometry, turb_static)


def make_farm_evaluator(model, nWaves=1, turb_static=None):
    """FULL-PHYSICS traced case evaluator for a multi-FOWT array: the
    coupled chain of Model.solveStatics/solveDynamics for farms
    (raft_model.py:550-964, :966-1255 incl. the system assembly
    :1164-1236) as one pure jax function — per-FOWT aero-servo
    constants (waked per-FOWT wind speeds enter as case inputs; the
    wake solve itself lives in :mod:`raft_tpu.physics.wake`), the
    COUPLED static equilibrium over all platforms with shared-mooring
    network forces, per-FOWT Morison excitation with the array phase
    carried by each unit's absolute node positions, per-FOWT
    drag-linearised impedances, and the block system impedance with
    shared-mooring stiffness solved for every heading.

    ``evaluate(case)`` takes
        wind_speed — scalar or (nFOWT,) per-unit (waked) speeds
        wind_heading_deg, TI, current_speed, current_heading_deg
        Hs, Tp, gamma, beta_deg — (nWaves,)
    and returns X0 (nDOF_total,), Xi (nWaves+1, nDOF_total, nw), PSD,
    S, zeta, drag diagnostics per FOWT.

    jit/vmap/shard over case and design axes exactly like the
    single-FOWT evaluator; parity vs the orchestrated path is gated at
    1e-9 (tests/test_farm_evaluator.py).
    """
    import scipy.linalg

    fowts = model.fowtList
    nFOWT = model.nFOWT
    assert nFOWT >= 1
    for fs_i in fowts:
        assert fs_i.is_single_body, "farm evaluator covers rigid units"
    assert all(b is None for b in model.bem_list), \
        "potential-flow farms run through the orchestrated path for now"
    assert model.qtf is None, "external QTFs unsupported in the farm trace"

    w = jnp.asarray(model.w)
    k = jnp.asarray(model.k)
    dw = model.w[1] - model.w[0]
    nw = model.nw
    offs = model.dof_offsets
    nDOF_T = model.nDOF

    stats = [model.statics(i) for i in range(nFOWT)]
    hydro = model.hydro
    K_h = scipy.linalg.block_diag(
        *[np.asarray(s["C_struc"] + s["C_hydro"]) for s in stats])
    C_elast = scipy.linalg.block_diag(
        *[np.asarray(s["C_elast"]) for s in stats])
    F_und = np.concatenate(
        [np.asarray(s["W_struc"] + s["W_hydro"] + s["f0_additional"])
         for s in stats])
    M_structs = [np.asarray(s["M_struc"]) for s in stats]
    A_hydros = [np.asarray(hydro[i].hc0["A_hydro"]) for i in range(nFOWT)]
    hc0s = [hydro[i].hc0 for i in range(nFOWT)]
    sss = [hydro[i].strips for i in range(nFOWT)]
    Tn0s, r0s = [], []
    for fs_i in fowts:
        r0_i = jnp.asarray(fs_i.node_r0, dtype=float)
        r0s.append(r0_i)
        Tn0s.append(node_T(r0_i, r0_i[fs_i.root_id]))

    rotor_aero = model.rotor_aero if fowts[0].nrotors else []
    from raft_tpu.physics.aero import calc_aero_traced, operating_point

    from raft_tpu.models.statics_solve import make_tolerances
    tol_vec, caps, refs = make_tolerances(fowts)
    force, stiff = model._mooring_closures()  # pure jnp closures

    def evaluate(case):
        wind_speed = jnp.asarray(case.get("wind_speed", 0.0)) * jnp.ones(nFOWT)
        wind_heading = case.get("wind_heading_deg", 0.0)
        TI = case.get("TI", 0.0)
        yaw_cmd = jnp.deg2rad(case.get("yaw_misalign_deg", 0.0))
        cur_speed = case.get("current_speed", 0.0)
        cur_heading = case.get("current_heading_deg", 0.0)
        Hs = jnp.atleast_1d(jnp.asarray(case["Hs"], dtype=float))
        Tp = jnp.atleast_1d(jnp.asarray(case.get("Tp", 10.0), dtype=float))
        gamma = jnp.atleast_1d(jnp.asarray(case.get("gamma", 0.0)) * jnp.ones(nWaves))
        beta_deg = jnp.atleast_1d(jnp.asarray(case.get("beta_deg", 0.0)) * jnp.ones(nWaves))
        beta = jnp.deg2rad(beta_deg)

        # ---- per-FOWT aero-servo constants + current loads
        f_env_parts, aero = [], []
        input_clipped = jnp.asarray(False)
        for i, fs_i in enumerate(fowts):
            nDOF = fs_i.nDOF
            f0_i = jnp.zeros(nDOF)
            A_i = jnp.zeros((nDOF, nDOF, nw))
            B_i = jnp.zeros((nDOF, nDOF, nw))
            Bg_i = jnp.zeros((nDOF, nDOF))
            for ir, rot in enumerate(rotor_aero):
                rprops = fs_i.rotors[ir]
                if rprops.aeroServoMod <= 0:
                    continue
                current = rprops.Zhub < 0
                speed = cur_speed if current else wind_speed[i]
                heading = jnp.deg2rad(cur_heading if current else wind_heading)
                on = speed > 0
                speed_safe = jnp.maximum(speed, 0.1)
                input_clipped = input_clipped | (on & (speed < 0.1))
                f0, f6, a6, b6, Bg, qv = calc_aero_traced(
                    rot, rprops, w, speed_safe, heading, TI,
                    yaw_command_rad=yaw_cmd,
                    turb_static=turb_static or ("NTM", 50.0))
                Tn_n = Tn0s[i][int(fs_i.rotor_node[ir])]
                f0_i = f0_i + on * (Tn_n.T @ f0)
                A_i = A_i + on * jnp.einsum("ia,ijw,jb->abw", Tn_n, a6, Tn_n)
                B_i = B_i + on * jnp.einsum("ia,ijw,jb->abw", Tn_n, b6, Tn_n)
                Bg_i = Bg_i + on * (Tn_n.T @ Bg @ Tn_n)
            F_cur_i = morison.current_loads(
                fs_i, sss[i], hc0s[i], cur_speed, cur_heading,
                min([r.Zhub for r in fs_i.rotors if r.Zhub < 0], default=0.0),
                Tn0s[i], r0s[i])
            f_env_parts.append(F_cur_i + f0_i)
            aero.append((A_i, B_i, Bg_i))

        # ---- coupled equilibrium (shared mooring through the closures)
        from raft_tpu.models.statics_solve import solve_equilibrium_general
        F_env = jnp.concatenate(f_env_parts)
        X0, _, _, _, st_status = solve_equilibrium_general(
            jnp.asarray(K_h), jnp.asarray(F_und), F_env, force, stiff,
            tol_vec, caps, refs, C_elast=jnp.asarray(C_elast))

        # ---- sea states (shared across units; phases via positions)
        S = jax.vmap(lambda h, t, g_: wv.jonswap(w, h, t, gamma=g_))(Hs, Tp, gamma)
        zeta = jnp.sqrt(2.0 * S * dw).astype(_policy_cdt())

        # ---- per-FOWT excitation + drag-linearised impedance
        Z_blocks, resids, iters, dyn_statuses = [], [], [], []
        F_waves = [[] for _ in range(nWaves)]
        for i, fs_i in enumerate(fowts):
            nDOF = fs_i.nDOF
            X0_i = X0[offs[i]:offs[i + 1]]
            r_nodes, R_ptfm, r_root = platform_kinematics(fs_i, X0_i)
            Tn = node_T(r_nodes, r_root)
            r, q, p1, p2 = morison.strip_frames(sss[i], R_ptfm, r_nodes)
            sub = r[:, 2] < 0
            hc = dict(hc0s[i], r=r, q=q, p1=p1, p2=p2, sub=sub,
                      active=sub & jnp.asarray(sss[i].active))
            exc = morison.hydro_excitation(
                fs_i, sss[i], hc, zeta, beta, w, k, Tn, r_nodes)
            A_i, B_i, Bg_i = aero[i]
            C_moor = jnp.zeros((nDOF, nDOF))
            if model.ms_list[i] is not None:
                C_moor = C_moor.at[:6, :6].add(
                    mooring_stiffness(model.ms_list[i], X0_i[:6]))
            M_lin = A_i + (jnp.asarray(M_structs[i])
                           + jnp.asarray(A_hydros[i]))[:, :, None]
            B_lin = B_i + Bg_i[:, :, None]
            C_lin = (jnp.asarray(K_h[offs[i]:offs[i + 1], offs[i]:offs[i + 1]])
                     + C_moor
                     + jnp.asarray(C_elast[offs[i]:offs[i + 1],
                                           offs[i]:offs[i + 1]]))
            F_lin = exc["F_hydro_iner"][0]
            Z_i, _, Bmat, diag_i = solve_dynamics_fowt(
                fs_i, sss[i], hc, exc["u"][0], M_lin, B_lin, C_lin, F_lin,
                w, Tn, r_nodes, n_iter=model.nIter, Xi_start=model.XiStart,
            n_iter_extra=model.nIterExtra)
            Z_blocks.append(Z_i)
            resids.append(diag_i["drag_resid"])
            iters.append(diag_i["n_iter_drag"])
            dyn_statuses.append(diag_i["status"])
            for ih in range(nWaves):
                F_drag = morison.drag_excitation(
                    fs_i, sss[i], hc, Bmat, exc["u"][ih], Tn, r_nodes)
                F_waves[ih].append(exc["F_hydro_iner"][ih] + F_drag)

        # ---- system impedance: block FOWT impedances + shared-mooring
        # stiffness (raft_model.py:1164-1182)
        Z_sys = jnp.zeros((nw, nDOF_T, nDOF_T),
                          dtype=Z_blocks[0].dtype)
        for i in range(nFOWT):
            Z_sys = Z_sys.at[:, offs[i]:offs[i + 1], offs[i]:offs[i + 1]].add(
                Z_blocks[i])
        if model.ms_array is not None:
            r6_all = jnp.stack(
                [X0[offs[i]:offs[i] + 6] for i in range(nFOWT)])
            Ka = model.ms_array.stiffness(r6_all)
            for i in range(nFOWT):
                for j in range(nFOWT):
                    Z_sys = Z_sys.at[:, offs[i]:offs[i] + 6,
                                     offs[j]:offs[j] + 6].add(
                        Ka[6 * i:6 * i + 6, 6 * j:6 * j + 6][None])

        F_sys = jnp.stack([jnp.concatenate(Fw, axis=0) for Fw in F_waves])
        Xi = system_response(Z_sys, F_sys)
        Xi = jnp.concatenate(
            [Xi, jnp.zeros((1, nDOF_T, nw), dtype=Xi.dtype)])
        PSD = jnp.sum(0.5 * jnp.abs(Xi) ** 2 / dw, axis=0)
        # one status word for the coupled case: any unit's drag/dynamics
        # bits OR the coupled statics bits OR the output guards
        dyn_status = dyn_statuses[0]
        for st_i in dyn_statuses[1:]:
            dyn_status = dyn_status | st_i
        status = _case_status(st_status, dict(status=dyn_status), X0, Xi,
                              input_clipped=input_clipped)
        return dict(X0=X0, Xi=Xi, PSD=PSD, S=S, zeta=zeta,
                    drag_resid=jnp.stack(resids),
                    n_iter_drag=jnp.stack(iters),
                    status=status)

    return _stamp_program_key(evaluate, "farm_evaluator", model,
                              nWaves, turb_static)


def flexible_struct_params(model):
    """Geometry-dependent structural parameter pytree of a flexible
    model, for the ``make_flexible_evaluator`` geometry axis: every
    baked constant that changes under member d/t/ballast/mooring
    scaling (statics matrices incl. the FE-beam C_elast, zero-pose
    hydro-constant tensors, strip coefficient tables, mooring L/EA).
    Station LAYOUT (node positions, topology schedules, strip counts)
    is geometry-static, so pytrees from models rebuilt at different
    member scales share one structure — and therefore ONE compiled
    evaluator (the flexible analogue of the rigid traced geometry
    axis; the host rebuild replaces the in-trace FE re-derivation,
    trading differentiability for exact build parity)."""
    fs = model.fowtList[0]
    fh = model.hydro[0]
    stat = model.statics()
    ss = fh.strips
    ms = model.ms
    out = dict(
        K_h=np.asarray(stat["C_struc"] + stat["C_hydro"]),
        C_elast=np.asarray(stat["C_elast"]),
        F_und=np.asarray(stat["W_struc"] + stat["W_hydro"]
                         + stat["f0_additional"]),
        M_struc=np.asarray(stat["M_struc"]),
        hc0={kk: np.asarray(fh.hc0[kk])
             for kk in ("A_hydro", "Amat", "Imat", "a_i")},
        ss=dict(
            ds=np.asarray(ss.ds), drs=np.asarray(ss.drs),
            Cd_q=np.asarray(ss.Cd_q), Cd_p1=np.asarray(ss.Cd_p1),
            Cd_p2=np.asarray(ss.Cd_p2), Cd_End=np.asarray(ss.Cd_End),
            Ca_q=np.asarray(ss.Ca_q), Ca_p1=np.asarray(ss.Ca_p1),
            Ca_p2=np.asarray(ss.Ca_p2), Ca_End=np.asarray(ss.Ca_End),
            Cm_p1_w=np.asarray(ss.Cm_p1_w), Cm_p2_w=np.asarray(ss.Cm_p2_w),
        ),
    )
    if ms is not None:
        out["ms"] = dict(L=np.asarray(ms.L), EA=np.asarray(ms.EA),
                         w=np.asarray(ms.w))
    return out


def make_flexible_evaluator(model, nWaves=1, turb_static=None,
                            geometry=False):
    """FULL-PHYSICS traced case evaluator for a flexible/multibody
    single-FOWT model (reduced N-DOF structures, e.g. the 150-DOF
    VolturnUS-S-flexible): the displaced-pose node kinematics and the
    position-dependent transformation matrix T run in-trace through
    :class:`raft_tpu.structure.topology_traced.TracedTopology` (static
    traversal schedules, traced values), so the whole chain —
    equilibrium, nonlinear mean-offset kinematics, N-DOF Morison
    excitation, drag-linearised (nw, N, N) impedance solves — is one
    pure jax function of the case parameters (VERDICT r2 #3; matches
    Model.solveDynamics for flexible FOWTs, raft_model.py:966-1255 with
    setNodesPosition/reduceDOF, raft_fowt.py:553-780).

    Parity vs the orchestrated path is gated at 1e-9
    (tests/test_flexible_evaluator.py).

    geometry=True enables the flexible GEOMETRY design axis:
    ``case["struct_params"]`` (a :func:`flexible_struct_params` pytree,
    host-rebuilt per design sample — the flexible FE constants come
    from the exact build path rather than a traced twin) overrides all
    geometry-dependent baked constants, so one compiled evaluator
    serves a design DoE by vmapping over stacked parameter pytrees.
    """
    fs = model.fowtList[0]
    assert model.nFOWT == 1, "single-FOWT flexible evaluator"
    assert not fs.is_single_body, \
        "rigid FOWTs use make_full_evaluator (this is the N-DOF path)"
    assert all(b is None for b in model.bem_list)
    assert model.qtf is None
    from raft_tpu.structure.topology_traced import TracedTopology

    tt = TracedTopology(fs)
    ms = model.ms
    fh = model.hydro[0]
    ss = fh.strips
    w = jnp.asarray(model.w)
    k = jnp.asarray(model.k)
    dw = model.w[1] - model.w[0]
    nw = model.nw
    nDOF = fs.nDOF

    stat = model.statics()
    K_h = np.asarray(stat["C_struc"] + stat["C_hydro"])
    C_elast = np.asarray(stat["C_elast"])
    F_und = np.asarray(stat["W_struc"] + stat["W_hydro"] + stat["f0_additional"])
    M_struc = np.asarray(stat["M_struc"])
    hc0 = fh.hc0
    Tn0 = jnp.asarray(fs.T).reshape(fs.n_nodes, 6, nDOF)

    rotor_aero = model.rotor_aero if fs.nrotors else []
    from raft_tpu.physics.aero import calc_aero_traced

    from raft_tpu.models.statics_solve import make_tolerances, \
        single_ms_closures, solve_equilibrium_general
    tol_vec, caps, refs = make_tolerances([fs])
    force, stiff = single_ms_closures(ms, nDOF)

    def evaluate(case):
        wind_speed = case.get("wind_speed", 0.0)
        wind_heading = case.get("wind_heading_deg", 0.0)
        TI = case.get("TI", 0.0)
        yaw_cmd = jnp.deg2rad(case.get("yaw_misalign_deg", 0.0))
        cur_speed = case.get("current_speed", 0.0)
        cur_heading = case.get("current_heading_deg", 0.0)
        Hs = jnp.atleast_1d(jnp.asarray(case["Hs"], dtype=float))
        Tp = jnp.atleast_1d(jnp.asarray(case.get("Tp", 10.0), dtype=float))
        gamma = jnp.atleast_1d(jnp.asarray(case.get("gamma", 0.0)) * jnp.ones(nWaves))
        beta_deg = jnp.atleast_1d(jnp.asarray(case.get("beta_deg", 0.0)) * jnp.ones(nWaves))
        beta = jnp.deg2rad(beta_deg)

        # ---- flexible geometry axis: traced structural parameters
        # override the baked constants (see docstring)
        ss_t, ms_t = ss, ms
        K_h_t, C_elast_t, F_und_t = K_h, C_elast, F_und
        M_struc_t, hc0_t = M_struc, hc0
        force_t, stiff_t = force, stiff
        if geometry and case.get("struct_params") is not None:
            import dataclasses as _dc

            sp = case["struct_params"]
            K_h_t, C_elast_t = sp["K_h"], sp["C_elast"]
            F_und_t, M_struc_t = sp["F_und"], sp["M_struc"]
            hc0_t = dict(hc0, **sp["hc0"])
            ss_t = _dc.replace(
                ss, **{kk: jnp.asarray(v) for kk, v in sp["ss"].items()})
            if ms is not None and "ms" in sp:
                ms_t = _dc.replace(ms, L=jnp.asarray(sp["ms"]["L"]),
                                   EA=jnp.asarray(sp["ms"]["EA"]),
                                   w=jnp.asarray(sp["ms"]["w"]))
                force_t, stiff_t = single_ms_closures(ms_t, nDOF)

        # ---- aero-servo constants (zero-pose rotor-node T rows, the
        # reference's calcTurbineConstants-at-case-start)
        f_aero0 = jnp.zeros(nDOF)
        A_aero = jnp.zeros((nDOF, nDOF, nw))
        B_aero = jnp.zeros((nDOF, nDOF, nw))
        B_gyro = jnp.zeros((nDOF, nDOF))
        input_clipped = jnp.asarray(False)
        for ir, rot in enumerate(rotor_aero):
            rprops = fs.rotors[ir]
            if rprops.aeroServoMod <= 0:
                continue
            current = rprops.Zhub < 0
            speed = cur_speed if current else wind_speed
            heading = jnp.deg2rad(cur_heading if current else wind_heading)
            on = speed > 0
            speed_safe = jnp.maximum(speed, 0.1)
            input_clipped = input_clipped | (on & (speed < 0.1))
            f0, f6, a6, b6, Bg, qv = calc_aero_traced(
                rot, rprops, w, speed_safe, heading, TI,
                yaw_command_rad=yaw_cmd,
                turb_static=turb_static or ("NTM", 50.0))
            Tn_n = Tn0[int(fs.rotor_node[ir])]
            f_aero0 = f_aero0 + on * (Tn_n.T @ f0)
            A_aero = A_aero + on * jnp.einsum("ia,ijw,jb->abw", Tn_n, a6, Tn_n)
            B_aero = B_aero + on * jnp.einsum("ia,ijw,jb->abw", Tn_n, b6, Tn_n)
            B_gyro = B_gyro + on * (Tn_n.T @ Bg @ Tn_n)

        F_current = morison.current_loads(
            fs, ss_t, hc0_t, cur_speed, cur_heading,
            min([r.Zhub for r in fs.rotors if r.Zhub < 0], default=0.0),
            Tn0, jnp.asarray(fs.node_r0))

        # ---- equilibrium
        F_env = F_current + f_aero0
        X0, _, _, _, st_status = solve_equilibrium_general(
            jnp.asarray(K_h_t), jnp.asarray(F_und_t), F_env, force_t, stiff_t,
            tol_vec, caps, refs, C_elast=jnp.asarray(C_elast_t))

        # ---- traced displaced-pose kinematics (nonlinear rigid-link /
        # beam-chain node displacements + position-dependent T)
        r_nodes, node_rot, Tn = tt.kinematics(X0)
        r, q, p1, p2 = morison.strip_frames(
            ss_t, jnp.eye(3), r_nodes, node_rot=node_rot)
        sub = r[:, 2] < 0
        hc = dict(hc0_t, r=r, q=q, p1=p1, p2=p2, sub=sub,
                  active=sub & jnp.asarray(ss_t.active))

        # ---- excitation + drag-linearised N-DOF impedance solve
        S = jax.vmap(lambda h, t, g_: wv.jonswap(w, h, t, gamma=g_))(Hs, Tp, gamma)
        zeta = jnp.sqrt(2.0 * S * dw).astype(_policy_cdt())
        exc = morison.hydro_excitation(fs, ss_t, hc, zeta, beta, w, k, Tn, r_nodes)

        C_moor = jnp.zeros((nDOF, nDOF))
        if ms is not None:
            C_moor = C_moor.at[:6, :6].add(mooring_stiffness(ms_t, X0[:6]))
        M_lin = A_aero + (jnp.asarray(M_struc_t)
                          + jnp.asarray(hc0_t["A_hydro"]))[:, :, None]
        B_lin = B_aero + B_gyro[:, :, None]
        C_lin = jnp.asarray(K_h_t) + C_moor + jnp.asarray(C_elast_t)
        F_lin = exc["F_hydro_iner"][0]

        Z, _, Bmat, dyn_diag = solve_dynamics_fowt(
            fs, ss_t, hc, exc["u"][0], M_lin, B_lin, C_lin, F_lin,
            w, Tn, r_nodes, n_iter=model.nIter, Xi_start=model.XiStart,
            n_iter_extra=model.nIterExtra)

        def fwave_one(ih):
            F_drag = morison.drag_excitation(fs, ss_t, hc, Bmat, exc["u"][ih],
                                             Tn, r_nodes)
            return exc["F_hydro_iner"][ih] + F_drag
        F_waves = jnp.stack([fwave_one(ih) for ih in range(nWaves)])
        Xi = system_response(Z, F_waves)
        Xi = jnp.concatenate([Xi, jnp.zeros((1, nDOF, nw), dtype=Xi.dtype)])
        PSD = jnp.sum(0.5 * jnp.abs(Xi) ** 2 / dw, axis=0)
        return dict(X0=X0, Xi=Xi, PSD=PSD, S=S, zeta=zeta,
                    drag_resid=dyn_diag["drag_resid"],
                    drag_converged=dyn_diag["drag_converged"],
                    n_iter_drag=dyn_diag["n_iter_drag"],
                    status=_case_status(st_status, dyn_diag, X0, Xi,
                                        input_clipped=input_clipped))

    return _stamp_program_key(evaluate, "flexible_evaluator", model,
                              nWaves, geometry, turb_static)


def make_case_evaluator(model, n_stat_iter=12):
    """Build ``evaluate(Hs, Tp, beta) -> outputs`` for one design.

    All build-time structure (strips, topology, statics matrices) is
    resolved here; the returned function is pure jax on scalar sea-state
    inputs and fully differentiable.
    """
    fs = model.fowtList[0]
    ms = model.ms
    fh = model.hydro[0]
    ss = fh.strips
    w = jnp.asarray(model.w)
    k = jnp.asarray(model.k)
    dw = model.w[1] - model.w[0]
    nw = model.nw
    nDOF = fs.nDOF

    # closures stay host-side numpy: they lower to jit constants without
    # any device pull (the axon TPU tunnel only implements f32 d2h)
    stat = model.statics()
    K_h = np.asarray(stat["C_struc"] + stat["C_hydro"])
    F_und = np.asarray(stat["W_struc"] + stat["W_hydro"] + stat["f0_additional"])
    M_struc = np.asarray(stat["M_struc"])
    A_hydro = np.asarray(fh.hc0["A_hydro"])
    hc0 = fh.hc0

    def evaluate(Hs, Tp, beta):
        # --- mean offsets under zero mean environmental load
        X0, _, _, _, st_status = solve_equilibrium(
            fs, ms, K_h, F_und, jnp.zeros(nDOF))

        # --- pose-dependent geometry
        r_nodes, R_ptfm, r_root = platform_kinematics(fs, X0)
        Tn = node_T(r_nodes, r_root)
        r, q, p1, p2 = morison.strip_frames(ss, R_ptfm, r_nodes)
        sub = r[:, 2] < 0
        hc = dict(hc0, r=r, q=q, p1=p1, p2=p2, sub=sub,
                  active=sub & jnp.asarray(ss.active))

        # --- sea state + excitation
        S = wv.jonswap(w, Hs, Tp)
        zeta = jnp.sqrt(2.0 * S * dw).astype(_policy_cdt())
        exc = morison.hydro_excitation(
            fs, ss, hc, zeta[None, :], jnp.asarray([beta]), w, k, Tn, r_nodes
        )

        # --- linear system + iterative drag linearisation
        C_moor = jnp.zeros((nDOF, nDOF))
        if ms is not None:
            C_moor = C_moor.at[:6, :6].add(mooring_stiffness(ms, X0[:6]))
        M_lin = jnp.broadcast_to((M_struc + A_hydro)[:, :, None], (nDOF, nDOF, nw))
        B_lin = jnp.zeros((nDOF, nDOF, nw))
        C_lin = K_h + C_moor
        F_lin = exc["F_hydro_iner"][0]

        Z, Xi1, Bmat, dyn_diag = solve_dynamics_fowt(
            fs, ss, hc, exc["u"][0], M_lin, B_lin, C_lin, F_lin,
            w, Tn, r_nodes, n_iter=model.nIter, Xi_start=model.XiStart,
            n_iter_extra=model.nIterExtra,
        )
        if fused_response_enabled():
            # fused hot path: the solve's final response is already
            # F_lin + the separable drag-excitation fold — skip the
            # staged drag_excitation chain + second system solve
            Xi = Xi1  # (nDOF, nw)
        else:
            F_wave = F_lin * 0 + exc["F_hydro_iner"][0] + morison.drag_excitation(
                fs, ss, hc, Bmat, exc["u"][0], Tn, r_nodes
            )
            Xi = system_response(Z, F_wave[None])[0]  # (nDOF, nw)

        RAO = wv.get_rao(Xi, zeta)
        PSD = 0.5 * jnp.abs(Xi) ** 2 / dw
        return dict(X0=X0, Xi=Xi, RAO=RAO, PSD=PSD, S=S,
                    drag_resid=dyn_diag["drag_resid"],
                    drag_converged=dyn_diag["drag_converged"],
                    n_iter_drag=dyn_diag["n_iter_drag"],
                    status=_case_status(st_status, dyn_diag, X0, Xi))

    return _stamp_program_key(evaluate, "case_evaluator", model,
                              n_stat_iter)
