"""Flexible (beam-member) FOWT tests vs reference golden data
(VolturnUS-S-flexible: FE Timoshenko pontoons + tower, joint graph with
headings, 150 reduced DOFs).

Statics, hydro constants/linearisation/current loads, static
equilibrium, natural frequencies AND the end-to-end dynamics PSDs match
at golden level (~1e-9).  Two solver-semantics details were required
for the dynamics (root-caused in round 3; previously an unexplained
~1e-3 deviation blamed on test ordering):

* cap-limited drag linearisation keeps the response of the LAST
  LINEARISATION POINT — one under-relaxation fewer than a naive loop
  (raft_model.py:1133-1143; this design runs nIter=4, cap-limited,
  with the reference's own non-convergence warning);
* displaced-pose node kinematics lag the statics solver by one step —
  node positions use the build-time T, the rebuilt T applies only to
  the load projections (setNodesPosition/reduceDOF path,
  raft_fowt.py:753-780; `Topology.self_consistent_displacements`).
"""

import os
import pickle

import numpy as np
import pytest
from numpy.testing import assert_allclose

from tests.conftest import ref_data

import raft_tpu

pytestmark = pytest.mark.slow

PATH = ref_data("VolturnUS-S-flexible.yaml")

WAVE_CASE = {
    "wind_speed": 0, "wind_heading": 0, "turbulence": 0,
    "turbine_status": "operating", "yaw_misalign": 0,
    "wave_spectrum": "JONSWAP", "wave_period": 10, "wave_height": 4,
    "wave_heading": -30, "current_speed": 0, "current_heading": 0,
}
X0_WAVE = [3.95574228e-01, -2.14947913e-10, -9.11283754e-01,
           -2.56570154e-13, -2.34275902e-02, 1.24718855e-12]
FNS_UNLOADED = [0.00841995, 0.00843999, 0.01358328, 0.0374836, 0.03753538,
                0.04995812, 0.43542245, 0.43659318, 1.16944889, 1.43151485,
                1.43158417, 1.55760813]


@pytest.fixture(scope="module")
def model():
    if not os.path.exists(PATH):
        pytest.skip("reference data unavailable")
    return raft_tpu.Model(PATH)


def test_flexible_statics(model):
    stat = model.statics()
    assert model.fowtList[0].nDOF == 150
    with open(PATH.replace(".yaml", "_true_statics.pkl"), "rb") as f:
        true = pickle.load(f)
    for k in ["rCG", "rCG_sub", "m_ballast", "M_struc", "M_struc_sub",
              "C_struc", "W_struc", "rCB", "C_hydro", "W_hydro"]:
        assert_allclose(np.asarray(stat[k]), np.asarray(true[k]),
                        rtol=1e-5, atol=1e-3, err_msg=k)


def test_flexible_hydro(model):
    fh = model.hydro[0]
    with open(PATH.replace(".yaml", "_true_hydroConstants.pkl"), "rb") as f:
        true = pickle.load(f)
    assert_allclose(np.asarray(fh.A_hydro_morison), true["A_hydro_morison"],
                    rtol=1e-5, atol=1e-3)

    with open(PATH.replace(".yaml", "_true_hydroLinearization.pkl"), "rb") as f:
        true = pickle.load(f)
    fh.hydro_excitation({"wave_spectrum": "unit", "wave_heading": 0,
                         "wave_period": 10, "wave_height": 2})
    nDOF, nw = model.fowtList[0].nDOF, model.nw
    phase = np.linspace(0, 2 * np.pi, nw * nDOF).reshape(nDOF, nw)
    out = fh.hydro_linearization(0.1 * np.exp(1j * phase), ih=0)
    assert_allclose(np.asarray(out["B_hydro_drag"]), true["B_hydro_drag"],
                    rtol=1e-5, atol=1e-10)
    assert_allclose(np.asarray(out["F_hydro_drag"]), true["F_hydro_drag"], rtol=1e-5)

    with open(PATH.replace(".yaml", "_true_calcCurrentLoads.pkl"), "rb") as f:
        true = pickle.load(f)
    D = fh.current_loads({"current_speed": 2.0, "current_heading": 15})
    assert_allclose(np.asarray(D), true, rtol=1e-5, atol=1e-3)


def test_flexible_statics_solve(model):
    X = np.asarray(model.solve_statics(WAVE_CASE))
    assert_allclose(X[:6], X0_WAVE, rtol=1e-5, atol=1e-8)


def test_flexible_eigen(model):
    model.solve_statics(dict(WAVE_CASE, turbine_status="idle",
                             wave_height=0, wave_period=0))
    fns, modes = model.solve_eigen()
    # slightly wider than the reference's rtol: the equilibrium iterate
    # difference shifts the mooring tangent by O(1e-5) relative
    assert_allclose(fns[:12], FNS_UNLOADED, rtol=5e-5, atol=1e-6)


def test_flexible_dynamics(model):
    case = dict(zip(model.design["cases"]["keys"], model.design["cases"]["data"][0]))
    assert case["wind_speed"] == 0
    X0 = model.solve_statics(case)
    Xi, info = model.solve_dynamics(case, X0=X0)
    from raft_tpu.models.outputs import turbine_outputs

    metrics = turbine_outputs(model, case, X0, Xi, info["S"], info["zeta"])
    with open(PATH.replace(".yaml", "_true_analyzeCases.pkl"), "rb") as f:
        true = pickle.load(f)
    tm = true["case_metrics"][0][0]
    for name in ("surge", "heave", "pitch", "yaw"):
        a = np.asarray(metrics[f"{name}_PSD"])
        b = np.asarray(tm[f"{name}_PSD"])
        # golden-level parity (measured ~2.5e-9 worst channel; see
        # module docstring for the two solver-semantics details)
        assert np.max(np.abs(a - b) / (np.abs(b) + 1e-6)) < 1e-7, name

    # mooring tension spectra track the golden closely too
    a = np.asarray(metrics["Tmoor_PSD"])
    b = np.asarray(tm["Tmoor_PSD"])
    assert np.max(np.abs(a - b) / (np.abs(b) + np.max(np.abs(b)) * 1e-9)) < 5e-3

    # FE internal tower-base moment: the MOTIONS are golden (above), so
    # the remaining few-% deviation lives in the internal-load recovery
    # (stiffness differencing) — tracked separately
    a = np.asarray(metrics["Mbase_PSD"])
    b = np.asarray(tm["Mbase_PSD"])
    assert abs(a.max() - b.max()) / b.max() < 0.05
    assert abs(float(metrics["Mbase_std"][0]) - float(tm["Mbase_std"][0])) \
        / float(tm["Mbase_std"][0]) < 0.05
