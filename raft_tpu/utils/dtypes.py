"""Explicit compute-dtype policy for the dynamics hot path.

The drag-linearisation / impedance-solve chain historically allocated
its complex intermediates with the hard-coded ``dtype=complex`` — under
``jax_enable_x64`` that is complex128 *regardless* of the input dtypes,
silently upcasting float32 pipelines; with x64 off it is complex64
regardless of a float64 intent.  The policy here makes the choice
explicit and overridable:

* default (``RAFT_TPU_DTYPE`` unset): **derive from the inputs** — a
  float64 golden-parity run stays float64 end to end, a float32 bench
  batch stays float32/complex64;
* ``RAFT_TPU_DTYPE=float32`` forces the float32/complex64 compute path
  (the TPU-native pairing) even when the build-side tensors are f64;
* ``RAFT_TPU_DTYPE=float64`` forces f64/complex128 (requires
  ``jax_enable_x64``; silently canonicalised to f32 otherwise, as all
  jax dtypes are).

The env var is read at *trace* time: set it before building/jitting the
evaluator whose precision you want to pin.
"""

from __future__ import annotations

import jax.numpy as jnp

from raft_tpu.utils import config

# one alias table for both entry paths (env var and explicit policy)
_F32_NAMES = config.DTYPE_F32_NAMES
_F64_NAMES = config.DTYPE_F64_NAMES


def policy_name():
    """The active policy string: '' (derive from inputs), 'float32' or
    'float64' (the ``RAFT_TPU_DTYPE`` flag, alias-normalised and
    validated by the :mod:`raft_tpu.utils.config` registry)."""
    return config.get("DTYPE")


def compute_dtypes(*arrays, policy=None):
    """(real_dtype, complex_dtype) for hot-path compute.

    ``policy``: explicit 'float32'/'float64' override; default reads
    ``RAFT_TPU_DTYPE``, and with no policy set the real dtype is the
    result type of the given arrays (so float64 inputs keep golden
    parity and float32 inputs stay in the fast path).
    """
    if policy is None:
        p = policy_name()
    else:
        p = str(policy or "").strip().lower()
        if p and p not in _F32_NAMES + _F64_NAMES:
            raise ValueError(
                f"dtype policy {policy!r}: expected 'float32', 'float64' "
                "or None")
        p = ("float32" if p in _F32_NAMES else
             "float64" if p in _F64_NAMES else "")
    if p == "float32":
        rdt = jnp.dtype(jnp.float32)
    elif p == "float64":
        rdt = jnp.dtype(jnp.float64)
    else:
        cands = [a for a in arrays if a is not None]
        dt = jnp.result_type(*cands) if cands else jnp.result_type(float)
        if jnp.issubdtype(dt, jnp.complexfloating):
            rdt = jnp.dtype(jnp.float32 if dt == jnp.dtype(jnp.complex64)
                            else jnp.float64)
        elif jnp.issubdtype(dt, jnp.floating):
            rdt = jnp.dtype(dt)
        else:
            rdt = jnp.dtype(jnp.result_type(float))
    cdt = jnp.dtype(jnp.complex64 if rdt == jnp.dtype(jnp.float32)
                    else jnp.complex128)
    # canonicalise under the current x64 mode (f64 request with x64 off
    # must not hand callers a dtype jax will refuse to materialise)
    rdt = jnp.zeros((), dtype=rdt).dtype
    cdt = jnp.zeros((), dtype=cdt).dtype
    return rdt, cdt
