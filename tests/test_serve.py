"""Evaluation-service tests: the socket-free batcher core (tick
coalescing, bucket-group routing parity, cache, quotas, drain) plus one
subprocess end-to-end server (concurrent clients, SIGTERM drain,
metrics flush)."""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DESIGNS = os.path.join(ROOT, "raft_tpu", "designs")


# ------------------------------------------------------------- pure units


def test_result_cache_hit_and_evict():
    from raft_tpu.serve.cache import ResultCache, result_cache_key

    row = {"PSD": np.zeros((6, 40)), "status": np.int32(0)}
    nbytes = sum(np.asarray(v).nbytes for v in row.values())
    cache = ResultCache(max_bytes=int(nbytes * 2.5),
                        metrics_prefix="test_cache")
    k1 = result_cache_key("d", {"Hs": 5.0, "Tp": 10.0}, ("PSD", "status"))
    k2 = result_cache_key("d", {"Hs": 5.0, "Tp": 10.000001},
                          ("PSD", "status"))
    assert k1 != k2  # exact float bits, no rounding
    assert result_cache_key("d", {"Tp": 10.0, "Hs": 5.0},
                            ("PSD", "status")) == k1  # order-insensitive
    assert cache.get(k1) is None
    assert cache.put(k1, row)
    got = cache.get(k1)
    assert got is not None and np.array_equal(got["PSD"], row["PSD"])
    # fill past the byte budget: LRU (k1 was just touched) evicts k2
    assert cache.put(k2, row)
    k3 = result_cache_key("d", {"Hs": 7.0}, ("PSD", "status"))
    assert cache.get(k1) is not None  # refresh k1 recency
    assert cache.put(k3, row)
    assert cache.evictions == 1
    assert cache.get(k2) is None and cache.get(k1) is not None
    # an entry larger than the whole budget is refused, not crashed on
    assert not cache.put(k3, {"big": np.zeros(10**6)})
    stats = cache.stats()
    assert stats["entries"] == 2 and stats["evictions"] == 1


def test_token_bucket_and_quotas():
    from raft_tpu.serve.quota import ClientQuotas, TokenBucket

    clock = [0.0]
    b = TokenBucket(rate=2.0, burst=2.0, clock=lambda: clock[0])
    assert b.acquire() and b.acquire()
    assert not b.acquire()          # burst drained
    assert b.retry_after_s() > 0
    clock[0] += 0.5                 # one token refilled
    assert b.acquire() and not b.acquire()
    # rate<=0 disables
    assert all(TokenBucket(0, 1).acquire() for _ in range(100))
    q = ClientQuotas(rate=1.0, burst=1.0, max_clients=2,
                     clock=lambda: clock[0])
    assert q.acquire("a") and not q.acquire("a")
    assert q.acquire("b")           # independent buckets


def test_out_keys_normalization_and_ladder():
    from raft_tpu.parallel.sweep import make_mesh
    from raft_tpu.serve import engine

    assert engine.normalize_out_keys(("PSD",)) == ("PSD", "status")
    assert engine.normalize_out_keys(("status", "X0")) == ("status", "X0")
    mesh = make_mesh(1)
    assert engine.batch_ladder(mesh, 8) == (1, 2, 4, 8)
    assert engine.batch_ladder(mesh, 5) == (1, 2, 4)
    assert engine.pick_padded(3, (1, 2, 4, 8)) == 4
    assert engine.pick_padded(1, (1, 2, 4, 8)) == 1


# ------------------------------------------------------ batcher core (jax)


@pytest.fixture(scope="module")
def serve_stack():
    """One spar+semi registry and a manual-tick batcher on a 1-device
    mesh (programs are process-cached on the bucket evaluators, so the
    module shares compiles across tests)."""
    from raft_tpu.parallel.sweep import make_mesh
    from raft_tpu.serve.batcher import Batcher
    from raft_tpu.serve.cache import ResultCache
    from raft_tpu.serve.engine import Registry
    from raft_tpu.serve.quota import ClientQuotas

    registry = Registry()
    registry.register("spar", os.path.join(DESIGNS, "spar_demo.yaml"))
    # the semi tenant is registered lazily by the (slow-tier) mixed-
    # bucket parity test — its host build + bucket compiles stay out of
    # the fast tier
    batcher = Batcher(
        registry, mesh=make_mesh(1), tick_ms=5, max_batch=2,
        cache=ResultCache(32 * 10**6, metrics_prefix="test_serve_cache"),
        quotas=ClientQuotas(rate=0.0, burst=1.0), queue_bound=64)
    return registry, batcher


def test_tick_coalescing_one_dispatch(serve_stack):
    from raft_tpu.obs import metrics

    _, batcher = serve_stack
    d0 = metrics.counter("serve_dispatches").value
    futs = [batcher.submit("spar", 4.0 + 0.25 * i, 9.0, 0.05 * i)
            for i in range(2)]
    assert all(not f.done() for f in futs)   # pending until the tick
    assert batcher.run_tick() == 2
    # 2 distinct spar cases coalesce into ONE padded dispatch
    assert metrics.counter("serve_dispatches").value - d0 == 1
    for f in futs:
        res = f.result(timeout=5)
        assert res["status_text"] == "ok" and not res["cache_hit"]
        assert set(res["outputs"]) == {"PSD", "X0", "status"}


def test_duplicate_inflight_requests_share_one_row(serve_stack):
    from raft_tpu.obs import metrics

    _, batcher = serve_stack
    c0 = metrics.counter("serve_coalesced").value
    d0 = metrics.counter("serve_dispatches").value
    futs = [batcher.submit("spar", 6.125, 11.0, 0.25) for _ in range(3)]
    futs += [batcher.submit("spar", 6.5, 11.5, 0.25) for _ in range(3)]
    batcher.run_tick()
    # 6 requests, 2 unique rows, one 2-row dispatch
    assert metrics.counter("serve_coalesced").value - c0 == 4
    assert metrics.counter("serve_dispatches").value - d0 == 1
    rows = [f.result(5)["outputs"]["PSD"] for f in futs[:3]]
    for r in rows[1:]:
        assert np.array_equal(np.asarray(rows[0]), np.asarray(r))


def test_tick_span_links_request_spans(serve_stack, tmp_path, monkeypatch):
    """One trace across the submit/tick thread boundary: the tick span
    records span-links to every coalesced request span, and the
    bucket dispatch span nests under the tick."""
    from raft_tpu.obs.report import collect_spans, read_events

    _, batcher = serve_stack
    log = str(tmp_path / "serve_events.jsonl")
    monkeypatch.setenv("RAFT_TPU_LOG", log)
    ctx_a = ("feed" * 4, "aaaa" * 4)
    ctx_b = ("feed" * 4, "bbbb" * 4)
    futs = [batcher.submit("spar", 5.0, 10.0, 0.0, trace_ctx=ctx_a),
            batcher.submit("spar", 5.0, 10.0, 0.0, trace_ctx=ctx_b),
            batcher.submit("spar", 5.5, 10.5, 0.1)]  # no client trace
    batcher.run_tick()
    for f in futs:
        f.result(timeout=30)
    evs, bad = read_events(log)
    assert bad == 0
    spans_, _ = collect_spans(evs)
    by_name = {s["name"]: s for s in spans_}
    tick = by_name["serve_tick"]
    # the 2 deduplicated traced requests are linked (3 submits, 2
    # unique rows, of which 2 carried a trace context)
    links = tick["attrs"]["links"]
    assert {(l["trace_id"], l["span_id"]) for l in links} == \
        {ctx_a, ctx_b}
    # the dispatch span is a tree CHILD of the tick span
    dispatch = by_name["sweep_dispatch"]
    assert dispatch["parent_id"] == tick["span_id"]
    assert dispatch["trace_id"] == tick["trace_id"]


def test_request_stage_attribution_sums_to_total(serve_stack, tmp_path,
                                                 monkeypatch):
    """Tail attribution: every dispatched request's latency decomposes
    into queue-wait / tick-wait / dispatch / solve / post stages that
    sum to its measured end-to-end latency, feed the serve_stage_*
    histograms, and render as the p50-vs-p95 table in obs report."""
    from raft_tpu.obs import metrics
    from raft_tpu.obs import report as obs_report
    from raft_tpu.obs.report import SERVE_STAGES

    _, batcher = serve_stack
    log = str(tmp_path / "stage_events.jsonl")
    monkeypatch.setenv("RAFT_TPU_LOG", log)
    c0 = {s: metrics.histogram(f"serve_stage_{s}_s").count
          for s in SERVE_STAGES}
    # 2 unique rows -> one dispatch through the module's already-warm
    # 2-row program (a 3rd unique would compile a 1-row program)
    futs = [batcher.submit("spar", 4.5 + 0.125 * i, 9.5, 0.02 * i)
            for i in range(2)]
    batcher.run_tick()
    for f in futs:
        f.result(timeout=60)
    for s in SERVE_STAGES:
        assert metrics.histogram(f"serve_stage_{s}_s").count - c0[s] == 2
    evs, bad = obs_report.read_events(log)
    assert bad == 0
    stage_evs = [e for e in evs if e["event"] == "serve_request_stages"]
    assert len(stage_evs) == 2
    for e in stage_evs:
        total = sum(e[f"{s}_s"] for s in SERVE_STAGES)
        # stages sum to the measured end-to-end latency (well inside
        # the 10% acceptance bound — equality up to rounding)
        assert total == pytest.approx(e["wall_s"], rel=0.01, abs=1e-4)
        assert e["solve_s"] > 0
    att = obs_report.report_data(evs)["serve_stages"]
    assert att["n_requests"] == 2
    for col in ("p50", "p95"):
        assert att[col]["stages_sum_s"] == pytest.approx(
            att[col]["total_s"], rel=0.01, abs=1e-4)
    # waste attribution fed by the serving dispatch: exact per-axis
    # counter pairs (strips are genuinely padded for the spar; the
    # rows axis only pads when a tick is short of its ladder rung)
    assert metrics.counter("pad_total_strips").value \
        > metrics.counter("pad_valid_strips").value > 0
    assert metrics.counter("pad_total_rows").value \
        >= metrics.counter("pad_valid_rows").value > 0
    # cache hits resolve at submit and carry no stage decomposition
    n0 = metrics.histogram("serve_stage_solve_s").count
    fut = batcher.submit("spar", 4.5, 9.5, 0.0)
    assert fut.result(timeout=5)["cache_hit"]
    assert metrics.histogram("serve_stage_solve_s").count == n0


def test_request_exemplars_name_the_actual_request(serve_stack, tmp_path,
                                                   monkeypatch):
    """Tail exemplars ride the real dispatch path: the latency
    histogram names the request (design hash, bucket signature, rows,
    ids, replica), /metrics renders it in OpenMetrics exemplar syntax,
    and ``obs report --tail`` joins it back to the stage breakdown by
    span_id."""
    from raft_tpu.obs import metrics
    from raft_tpu.obs import report as obs_report

    _, batcher = serve_stack
    log = str(tmp_path / "ex_events.jsonl")
    monkeypatch.setenv("RAFT_TPU_LOG", log)
    metrics.reset()       # empty exemplar slots: both requests admit
    ctx_a = ("feed" * 4, "cafe" * 4)
    ctx_b = ("feed" * 4, "beef" * 4)
    futs = [batcher.submit("spar", 3.75, 9.25, 0.05, trace_ctx=ctx_a),
            batcher.submit("spar", 3.85, 9.75, 0.0, trace_ctx=ctx_b)]
    batcher.run_tick()
    for f in futs:
        f.result(timeout=60)
    # the histogram exemplar carries the request's full identity (the
    # exporter keeps the best exemplar per bucket — both requests share
    # a latency bucket, so the slower of the two is the one named)
    ex = metrics.histogram("serve_request_s").exemplars()
    labels = [lab for _, _, lab in ex.values()]
    hit = next(lab for lab in labels if lab.get("trace_id") == "feed" * 4)
    assert hit["span_id"] in (ctx_a[1], ctx_b[1])
    assert hit["design"] and hit["sig"] and hit["rows"] == 2
    assert hit["cache_hit"] == 0 and hit["status"] == 0
    assert hit["replica"]
    # OpenMetrics exemplar clause on the scrape
    assert any("serve_request_s_bucket" in line and "# {" in line
               for line in metrics.to_prometheus().splitlines())
    # report --tail: the stages event carries the REQUEST's ids and
    # the exemplar_recorded event joins by span_id
    evs, bad = obs_report.read_events(log)
    assert bad == 0
    view = obs_report.tail_view(evs, rank=1.0)
    assert view["n_requests"] == 2
    assert view["span_id"] in (ctx_a[1], ctx_b[1])
    assert view["trace_id"] == "feed" * 4
    assert view["exemplar"]["span_id"] == view["span_id"]
    assert view["exemplar"]["design"] == hit["design"]
    assert view["stages"]["solve"] > 0
    txt = obs_report.render_tail(evs, rank=1.0, source=log)
    assert "design" in txt and "solve" in txt


def test_slo_breach_window_and_healthz(serve_stack, monkeypatch):
    from raft_tpu.obs import metrics
    from raft_tpu.serve.http import Server

    _, batcher = serve_stack
    # an absurdly tight SLO: every real dispatch breaches it
    monkeypatch.setenv("RAFT_TPU_SERVE_SLO_MS", "0.0001")
    b0 = metrics.counter("serve_slo_breaches").value
    w0 = metrics.window("serve_request_window_s").total
    fut = batcher.submit("spar", 7.0, 12.0, 0.125)
    batcher.run_tick()
    fut.result(timeout=30)
    assert metrics.counter("serve_slo_breaches").value > b0
    assert metrics.window("serve_request_window_s").total > w0
    code, body = Server(batcher)._healthz()
    assert code == 200
    # the sliding-window latency view + SLO accounting + cost ledger
    assert body["window"]["count"] >= 1 and body["window"]["p95"] > 0
    assert body["slo"]["slo_ms"] == 0.0001
    assert body["slo"]["breaches"] >= 1
    assert isinstance(body["cost_ledger"], list)
    # under RAFT_TPU_AOT=off there is nothing ledgered — but the key
    # exists so dashboards need no schema branch
    monkeypatch.setenv("RAFT_TPU_SERVE_SLO_MS", "0")
    code, body = Server(batcher)._healthz()
    assert body["slo"]["slo_ms"] is None


@pytest.mark.slow
def test_bucket_group_routing_parity_vs_solo(serve_stack):
    """Mixed spar+semi tick: one dispatch per bucket signature, every
    row within 1e-10 of the solo make_case_evaluator chain, int32
    status bit-equal.  (Slow tier: compiles the semi bucket + two solo
    jits; the fast tier keeps the spar-only batcher behavior tests and
    the bench load harness pins the parity gate end to end.)"""
    import jax

    from raft_tpu.api import make_case_evaluator
    from raft_tpu.obs import metrics

    registry, batcher = serve_stack
    spar = registry.get("spar")
    semi = (registry.get("semi")
            or registry.register("semi",
                                 os.path.join(DESIGNS, "semi_demo.yaml")))
    assert spar.sig != semi.sig
    cases = [(spar, 5.5, 10.0, 0.1), (semi, 5.5, 10.0, 0.1),
             (spar, 7.0, 12.0, -0.2), (semi, 3.0, 8.0, 0.3)]
    d0 = metrics.counter("serve_dispatches").value
    futs = [batcher.submit(e, h, t, b) for e, h, t, b in cases]
    batcher.run_tick()
    assert metrics.counter("serve_dispatches").value - d0 == 2  # per sig
    for (entry, h, t, b), fut in zip(cases, futs):
        res = fut.result(5)
        solo = jax.jit(make_case_evaluator(entry.model))(h, t, b)
        assert int(np.asarray(solo["status"])) == res["status"]
        for k in ("PSD", "X0"):
            np.testing.assert_allclose(
                np.asarray(res["outputs"][k]), np.asarray(solo[k]),
                rtol=0, atol=1e-10)


def test_cache_hit_skips_dispatch(serve_stack):
    from raft_tpu.obs import metrics

    _, batcher = serve_stack
    f1 = batcher.submit("spar", 4.75, 9.5, 0.0)
    batcher.submit("spar", 4.8, 9.5, 0.0)
    batcher.run_tick()
    r1 = f1.result(5)
    d0 = metrics.counter("serve_dispatches").value
    f2 = batcher.submit("spar", 4.75, 9.5, 0.0)
    assert f2.done()                       # resolved at submit time
    r2 = f2.result(0)
    assert r2["cache_hit"] and not r1["cache_hit"]
    assert metrics.counter("serve_dispatches").value == d0
    for k in r1["outputs"]:
        assert np.array_equal(np.asarray(r1["outputs"][k]),
                              np.asarray(r2["outputs"][k]))


def test_requested_out_keys_subset_and_unknown(serve_stack):
    _, batcher = serve_stack
    f = batcher.submit("spar", 5.0, 10.0, 0.0, out_keys=("X0",))
    batcher.submit("spar", 5.1, 10.0, 0.0)
    batcher.run_tick()
    assert set(f.result(5)["outputs"]) == {"X0"}
    with pytest.raises(ValueError, match="not served"):
        batcher.submit("spar", 5.0, 10.0, 0.0, out_keys=("Xi",))
    with pytest.raises(KeyError):
        batcher.submit("nope", 5.0, 10.0, 0.0)


def test_quota_and_queue_rejection(serve_stack):
    from raft_tpu.parallel.sweep import make_mesh
    from raft_tpu.serve.batcher import Batcher, QueueFull, QuotaExceeded
    from raft_tpu.serve.cache import ResultCache
    from raft_tpu.serve.quota import ClientQuotas

    registry, _ = serve_stack
    clock = [0.0]
    tight = Batcher(
        registry, mesh=make_mesh(1), tick_ms=5, max_batch=2,
        cache=ResultCache(10**6, metrics_prefix="test_serve_cache2"),
        quotas=ClientQuotas(rate=0.001, burst=2.0, clock=lambda: clock[0]),
        queue_bound=4)
    assert tight.submit("spar", 9.0, 10.0, 0.0, client="greedy") is not None
    assert tight.submit("spar", 9.1, 10.0, 0.0, client="greedy") is not None
    with pytest.raises(QuotaExceeded) as ei:
        tight.submit("spar", 9.2, 10.0, 0.0, client="greedy")
    assert ei.value.http_status == 429 and ei.value.retry_after_s > 0
    # other clients are unaffected by one client's dry bucket...
    assert tight.submit("spar", 9.3, 10.0, 0.0, client="polite") is not None
    assert tight.submit("spar", 9.4, 10.0, 0.0, client="other") is not None
    # ...until the shared admission queue hits its bound (503)
    with pytest.raises(QueueFull) as ei:
        tight.submit("spar", 9.5, 10.0, 0.0, client="other")
    assert ei.value.http_status == 503
    tight.drain()


def test_drain_finishes_pending_then_refuses(serve_stack):
    from raft_tpu.parallel.sweep import make_mesh
    from raft_tpu.serve.batcher import Batcher, Draining
    from raft_tpu.serve.cache import ResultCache

    registry, _ = serve_stack
    b = Batcher(registry, mesh=make_mesh(1), tick_ms=5, max_batch=2,
                cache=ResultCache(10**6,
                                  metrics_prefix="test_serve_cache3"),
                queue_bound=16)
    # submit BEFORE starting the tick thread: the backlog drains as one
    # deterministic 2-row tick (no 1-row straggler program)
    futs = [b.submit("spar", 3.0 + 0.5 * i, 10.5, 0.0) for i in range(2)]
    b.start()
    rep = b.drain(timeout=120)
    assert rep["completed"]
    for f in futs:                      # every accepted request resolved
        assert f.done() and f.result(0)["status_text"] == "ok"
    with pytest.raises(Draining):
        b.submit("spar", 3.0, 10.5, 0.0)


@pytest.mark.slow
def test_escalate_row_f64_smoke(serve_stack):
    """The per-request quarantine-style re-solve: dispatches solo under
    the f64_cpu rung flags and returns a healthy row for a healthy
    case (adoption-rule plumbing is in Batcher._finalize).  Slow tier:
    the rung's flag flip compiles its own program."""
    from raft_tpu.serve import engine

    registry, batcher = serve_stack
    row, status = engine.escalate_row(registry.get("spar"), 5.0, 10.0, 0.1,
                                      out_keys=batcher.out_keys,
                                      mesh=batcher.mesh)
    assert set(row) == set(batcher.out_keys)
    assert status == 0
    assert np.asarray(row["status"]).dtype == np.int32


def test_report_serve_section():
    from raft_tpu.obs.report import render_report

    events = [
        {"t": 0.1, "event": "serve_request", "pid": 1, "endpoint":
         "/evaluate", "method": "POST", "code": 200, "client": "a",
         "wall_s": 0.02, "cache_hit": False},
        {"t": 0.2, "event": "serve_request", "pid": 1, "endpoint":
         "/evaluate", "method": "POST", "code": 200, "client": "a",
         "wall_s": 0.001, "cache_hit": True},
        {"t": 0.3, "event": "serve_request", "pid": 1, "endpoint":
         "/healthz", "method": "GET", "code": 200, "client": "a",
         "wall_s": 0.0005, "cache_hit": False},
        {"t": 0.25, "event": "serve_tick", "pid": 1, "rows": 3,
         "unique": 2, "n_groups": 1, "dispatches": 1, "wall_s": 0.015},
    ]
    text = render_report(events, source="synthetic")
    assert "serve endpoints" in text
    assert "/evaluate" in text and "/healthz" in text
    assert "ticks: 1 (3 requests, 2 unique rows, 1 dispatches" in text


def test_registry_inline_cache_is_bounded(monkeypatch):
    """Tenant-supplied inline designs must recycle LRU slots, not grow
    the always-on server's RSS without bound."""
    from raft_tpu.aot.bank import content_fingerprint
    from raft_tpu.serve.engine import Registry

    built = []

    class _Dummy:
        def __init__(self, name, fp):
            self.name, self.fingerprint = name, fp

    reg = Registry(max_inline=2)
    monkeypatch.setattr(
        Registry, "_build",
        lambda self, name, design: built.append(name) or _Dummy(
            name, content_fingerprint(design)))
    a = reg.resolve_inline({"d": 1.0})
    assert reg.resolve_inline({"d": 1.0}) is a      # fingerprint hit
    reg.resolve_inline({"d": 2.0})
    reg.resolve_inline({"d": 1.0})                  # refresh a's recency
    reg.resolve_inline({"d": 3.0})                  # evicts d=2 (LRU)
    assert len(built) == 3
    reg.resolve_inline({"d": 2.0})          # rebuilt; evicts d=1 (LRU)
    assert len(built) == 4
    reg.resolve_inline({"d": 3.0})          # still cached, no rebuild
    assert len(built) == 4


def test_omdao_repeat_call_cache():
    """The optimizer repeat-call bugfix: identical iterates hit the
    result cache instead of re-dispatching the traced evaluator, and
    the counters surface on .diag."""
    from raft_tpu.omdao import DesignEvaluation

    ev = DesignEvaluation(os.path.join(DESIGNS, "spar_demo.yaml"))
    calls = []

    def fake_evaluate(case):
        calls.append(dict(case))
        return {"X0": np.arange(6.0), "Xi": np.zeros((2, 6, 4)),
                "S": np.ones(4), "zeta": np.ones((1, 4)),
                "unrelated": np.zeros(3)}

    case = {"Hs": np.asarray([6.0]), "Tp": np.asarray([11.0]),
            "wind_speed": 8.0}
    r1 = ev._evaluate_cached(fake_evaluate, case)
    r2 = ev._evaluate_cached(fake_evaluate, dict(case))
    assert len(calls) == 1                       # second iterate: cache
    assert set(r1) == {"X0", "Xi", "S", "zeta"}  # only the metric inputs
    assert np.array_equal(r1["X0"], r2["X0"])
    # a changed case bit is a different key
    ev._evaluate_cached(fake_evaluate, dict(case, wind_speed=8.0001))
    assert len(calls) == 2
    d = ev.diag
    assert d["cache_hits"] == 1 and d["cache_misses"] == 2
    assert d["cache_bytes"] > 0


# --------------------------------------------------------- subprocess e2e


def _wait_ready(proc, deadline_s):
    """Read server stdout until the ready line; returns the port."""
    t0 = time.monotonic()
    for line in proc.stdout:
        if "serving" in line and "http://" in line:
            return int(line.split("http://", 1)[1].split()[0]
                       .rsplit(":", 1)[1])
        if time.monotonic() - t0 > deadline_s:
            break
    raise AssertionError("server never printed its ready line")


def test_server_end_to_end_sigterm_drain(tmp_path):
    """Start a real server subprocess, hit it with concurrent clients,
    SIGTERM it mid-load: every accepted request gets its response, the
    server exits cleanly and flushes metrics."""
    from raft_tpu.serve.client import ServeClient

    metrics_path = tmp_path / "serve_metrics.prom"
    log_path = tmp_path / "serve_events.jsonl"
    stderr_path = tmp_path / "serve_stderr.txt"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        RAFT_TPU_SERVE_TICK_MS="10",
        # one padded program size (burst of 12 -> six 2-row dispatches)
        # keeps the cold-start compile bill minimal for CI
        RAFT_TPU_SERVE_MAX_BATCH="2",
        RAFT_TPU_METRICS=str(metrics_path),
        RAFT_TPU_LOG=str(log_path),
        RAFT_TPU_CACHE_DIR=str(tmp_path / "jax_cache"),
        # black-box flight recorder: periodic flush shards land here
        RAFT_TPU_FLIGHT_DIR=str(tmp_path / "flight"),
        RAFT_TPU_FLIGHT_FLUSH_S="0.5",
    )
    env.pop("RAFT_TPU_AOT", None)
    stderr_f = open(stderr_path, "w")
    proc = subprocess.Popen(
        [sys.executable, "-m", "raft_tpu.serve",
         "--designs", f"spar={os.path.join(DESIGNS, 'spar_demo.yaml')}",
         "--port", "0", "--no-warm"],
        cwd=ROOT, env=env, stdout=subprocess.PIPE, stderr=stderr_f,
        text=True)
    try:
        port = _wait_ready(proc, deadline_s=180)
        results, errors = [], []

        def client(i, n_req):
            c = ServeClient("127.0.0.1", port, client_id=f"c{i}",
                            timeout=300)
            try:
                for j in range(n_req):
                    code, body = c.evaluate("spar", 4.0 + (i % 5) * 0.5,
                                            9.0 + j, 0.1 * (i % 3))
                    results.append((i, j, code, body))
            except Exception as e:  # noqa: BLE001 — assert below
                errors.append((i, repr(e)))
            finally:
                c.close()

        threads = [threading.Thread(target=client, args=(i, 2))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not errors, errors
        assert len(results) == 12
        assert all(code == 200 for (_, _, code, _) in results), \
            [(c, b) for (_, _, c, b) in results if c != 200][:3]
        body = results[0][3]
        assert body["ok"] and body["status_text"] == "ok"
        assert "PSD" in body["outputs"] and "X0" in body["outputs"]

        c = ServeClient("127.0.0.1", port)
        # traceparent contract: a traced client's header is adopted
        # (response echoes a traceparent in the SAME trace), an
        # untraced client still gets a server-minted one
        tp_in = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
        code, _ = c.evaluate("spar", 4.25, 9.5, 0.0, traceparent=tp_in)
        assert code == 200
        tp_out = c.last_headers.get("traceparent")
        assert tp_out and tp_out.split("-")[1] == "ab" * 16
        assert tp_out != tp_in          # the span is the server's own
        code, _ = c.evaluate("spar", 4.25, 9.5, 0.0)
        assert code == 200 and c.last_headers.get("traceparent")
        code, health = c.healthz()
        assert code == 200 and health["ok"]
        assert health["serve_requests"] >= 12
        # the SLO/window + cost-ledger blocks are part of /healthz
        assert "window" in health and "slo" in health
        assert "cost_ledger" in health
        code, prom = c.metrics_text()
        assert code == 200
        assert "raft_tpu_serve_requests" in prom
        assert "raft_tpu_serve_batch_occupancy_bucket" in prom
        # OpenMetrics exemplars on the scrape: the latency buckets NAME
        # the actual requests that landed in them
        assert any("raft_tpu_serve_request_s_bucket" in line
                   and "# {" in line for line in prom.splitlines())
        # the loopback-gated flight-ring dump: a JSONL body whose first
        # line is the schema-versioned proc_start anchor
        code, box = c.request("GET", "/debug/flight")
        assert code == 200 and isinstance(box, str)
        first = json.loads(box.splitlines()[0])
        assert first["event"] == "proc_start"
        assert first["flight"]["trigger"] == "debug"
        code, designs = c.request("GET", "/designs")
        assert code == 200 and designs["designs"] == ["spar"]
        # unknown design -> 404, bad body -> 400
        assert c.evaluate("nope", 5, 10, 0)[0] == 404
        assert c.request("POST", "/evaluate", {"Hs": "x"})[0] == 400
        c.close()

        # ---- SIGTERM drain: fire a burst, kill mid-flight; every
        # accepted request must still get its full response
        drain_results, drain_errors = [], []

        def drain_client(i):
            dc = ServeClient("127.0.0.1", port, client_id=f"d{i}",
                             timeout=300)
            try:
                code, body = dc.evaluate("spar", 2.0 + 0.1 * i, 8.0, 0.0)
                drain_results.append((i, code, body))
            except (ConnectionError, OSError):
                # raced the socket close before ACCEPTANCE — a refused
                # connection is a clean reject, not a dropped response
                drain_results.append((i, "refused", None))
            except Exception as e:  # noqa: BLE001
                drain_errors.append((i, repr(e)))
            finally:
                dc.close()

        threads = [threading.Thread(target=drain_client, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.05)            # let the burst reach the queue
        proc.send_signal(signal.SIGTERM)
        for t in threads:
            t.join(timeout=300)
        rc = proc.wait(timeout=120)
        stderr_f.flush()
        assert rc == 0, stderr_path.read_text()[-2000:]
        # accepted requests (non-503) all resolved with full payloads
        assert not drain_errors, drain_errors
        accepted = [r for r in drain_results if r[1] == 200]
        assert accepted, drain_results
        for _, _, body in accepted:
            assert body["ok"] and "PSD" in body["outputs"]
        # metrics flushed on shutdown
        prom_text = metrics_path.read_text()
        assert "raft_tpu_serve_requests" in prom_text
        # drain events in the capture
        events = [json.loads(line)
                  for line in log_path.read_text().splitlines()]
        names = {e["event"] for e in events}
        assert {"serve_start", "serve_tick", "serve_request",
                "serve_drain", "serve_stop"} <= names
        # the flight recorder left its stable flush shard behind, and
        # it validates against the strict schema reader
        from raft_tpu.obs import flight

        shard = tmp_path / "flight" / f"flight-{proc.pid}.jsonl"
        assert shard.exists(), "no flight flush shard after shutdown"
        hdr, _recs = flight.read_shard(str(shard))
        assert hdr["flight"]["version"] == flight.SCHEMA_VERSION
        # report --tail on the capture: the slowest request joins its
        # exemplar identity AND its span tree (dispatched via HTTP, so
        # the serve_request span carries the stage events' ids)
        from raft_tpu.obs.report import tail_view

        view = tail_view(events, rank=1.0)
        assert view is not None and view["n_requests"] >= 12
        assert view["trace_id"] and view["span_id"]
        assert view["exemplar"] is not None
        assert view["exemplar"]["span_id"] == view["span_id"]
        assert view["exemplar"]["design"] and view["exemplar"]["replica"]
        assert view["spans"], "p100 request has no span tree"
        assert any(s["name"] == "serve_request" for s in view["spans"])
        assert view["stages"]["solve"] > 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
        stderr_f.close()


def test_free_port_helper_unused():
    """Guard: the e2e test relies on --port 0 ephemeral binding; keep a
    socket sanity check so a future refactor of the ready-line protocol
    fails here with a readable message."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    assert s.getsockname()[1] > 0
    s.close()
