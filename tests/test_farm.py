"""Multi-FOWT array (farm) tests: shared-mooring network equilibrium,
system eigenanalysis and the coupled dynamics solve.

Targets are the reference's hardcoded farm rows
(/root/reference/tests/test_model.py index 3: VolturnUS-S_farm).
Tolerances are slightly wider than single-FOWT parity because the
published equilibria embed MoorPy's free-point solver tolerance and the
early-stopped Newton trajectory (mm-level effects through 1.2 km of
shared line).
"""

import os

import numpy as np
import pytest
from numpy.testing import assert_allclose

from tests.conftest import ref_data

import raft_tpu

pytestmark = pytest.mark.slow

WAVE_CASE = {
    "wind_speed": 0, "wind_heading": 0, "turbulence": 0,
    "turbine_status": "operating", "yaw_misalign": 0,
    "wave_spectrum": "JONSWAP", "wave_period": 10, "wave_height": 4,
    "wave_heading": -30, "current_speed": 0, "current_heading": 0,
}
IDLE_CASE = dict(WAVE_CASE, turbine_status="idle", wave_height=0, wave_period=0)

X0_WAVE = [-3.28437405e-01, 1.37380291e-15, 8.59345726e-01, 6.09528763e-17,
           -2.31870486e-02, 9.89478513e-19, 1.60065726e+03, 9.12847486e-16,
           8.59907935e-01, 3.91868383e-17, -2.40815624e-02, -8.63499424e-19]
FNS_UNLOADED = [0.01074526, 0.00704213, 0.05083874, 0.03718830, 0.03746220,
                0.01573330, 0.00756069, 0.00716294, 0.05085846, 0.03718910,
                0.03751292, 0.01545850]


@pytest.fixture(scope="module")
def farm_model():
    path = ref_data("VolturnUS-S_farm.yaml")
    if not os.path.exists(path):
        pytest.skip("reference data unavailable")
    return raft_tpu.Model(path)


def test_farm_build(farm_model):
    m = farm_model
    assert m.nFOWT == 2 and m.nDOF == 12
    assert m.ms_array is not None
    assert len(m.ms_array.free_idx) == 2  # mid-line clump weights


def test_farm_statics_wave(farm_model):
    X = np.asarray(farm_model.solve_statics(WAVE_CASE))
    assert_allclose(X, X0_WAVE, atol=5e-3)  # mm-level solver-path effects


def test_farm_eigen_unloaded(farm_model):
    farm_model.solve_statics(IDLE_CASE)
    fns, modes = farm_model.solve_eigen()
    assert_allclose(fns, FNS_UNLOADED, rtol=5e-4, atol=1e-6)


def test_farm_dynamics_runs(farm_model):
    Xi, info = farm_model.solve_dynamics(WAVE_CASE)
    Xi = np.asarray(Xi)
    assert Xi.shape[1] == 12
    assert np.isfinite(Xi).all()
    # the two units see phase-shifted waves: responses similar magnitude,
    # not identical
    s0 = np.abs(Xi[0, 0, :]).max()
    s1 = np.abs(Xi[0, 6, :]).max()
    assert 0.5 < s0 / s1 < 2.0
    assert not np.allclose(Xi[0, 0, :], Xi[0, 6, :])


def test_bathymetry_grid(tmp_path):
    """MoorPy-style bathymetry grid: bilinear depth lookup and its
    effect on the anchor/grounding classification (the reference feeds
    the grid to MoorPy at array level, raft_model.py:87-91)."""
    from raft_tpu.physics.mooring import MooringNetwork, read_bathymetry

    bpath = tmp_path / "bath.txt"
    bpath.write_text(
        "--- MoorPy Bathymetry Input File ---\n"
        "nGridX 3\n"
        "nGridY 2\n"
        "      -1000.0 0.0 1000.0\n"
        "-1000.0  150.0 200.0 250.0\n"
        " 1000.0  250.0 300.0 350.0\n"
    )
    xg, yg, dg = read_bathymetry(str(bpath))
    assert xg.shape == (3,) and yg.shape == (2,) and dg.shape == (2, 3)

    net = MooringNetwork(200.0, bathymetry=(xg, yg, dg))
    assert net.depth_at(0.0, -1000.0) == pytest.approx(200.0)
    assert net.depth_at(1000.0, 1000.0) == pytest.approx(350.0)
    assert net.depth_at(0.0, 0.0) == pytest.approx(250.0)   # bilinear middle
    assert net.depth_at(500.0, -1000.0) == pytest.approx(225.0)

    # grounding classification uses the LOCAL depth: an anchor at
    # z=-200 sits on the seabed where depth=200 but hangs above it
    # where the seabed is at 350 m
    a1 = net.add_point(0, [0.0, -1000.0, -199.5])    # local depth 200
    a2 = net.add_point(0, [1000.0, 1000.0, -199.5])  # local depth 350
    f1 = net.add_point(1, [0.0, 0.0, 0.0], body=0)
    f2 = net.add_point(1, [10.0, 0.0, 0.0], body=0)
    net.add_line(a1, f1, 850.0, 1e3, 7e8)
    net.add_line(a2, f2, 850.0, 1e3, 7e8)
    net.finalize()
    assert bool(net.l_can_ground[0]) is True
    assert bool(net.l_can_ground[1]) is False
