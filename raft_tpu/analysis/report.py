"""Shared findings output for the analysis CLI engines.

Every engine (lint, concurrency, schemas, protocol) funnels its
findings through :func:`emit` so ``--json`` means the same thing
everywhere: a single JSON document on stdout with one record per
finding (``file``/``line``/``col``/``rule``/``message``) plus engine
metadata — stable keys for CI tooling to consume without scraping the
human text format.  Text mode is byte-identical to the historical
per-engine output.
"""

from __future__ import annotations

import json
import sys


def to_record(finding):
    """Normalize one finding into the machine-readable record shape.

    Accepts :class:`raft_tpu.analysis.lint.Finding` (and anything
    duck-typed to it), plain dicts, or bare strings (the schemas
    engine's violation lines, which carry no file position).
    """
    if isinstance(finding, str):
        return {"file": None, "line": None, "col": None,
                "rule": "schema-contract", "message": finding}
    if isinstance(finding, dict):
        rec = {"file": None, "line": None, "col": None, "rule": None,
               "message": None}
        rec.update(finding)
        return rec
    return {"file": finding.path, "line": finding.line,
            "col": finding.col, "rule": finding.rule,
            "message": finding.message}


def emit(engine, findings, as_json, clean_note=None, extra=None,
         stream=None):
    """Print findings in the selected format; return the exit code.

    Text mode preserves each engine's historical layout: one formatted
    finding per line on stdout, a count hint on stderr when dirty, the
    ``clean_note`` on stdout when clean.  JSON mode prints one document
    with ``engine``, ``findings`` and any ``extra`` metadata.
    """
    stream = stream or sys.stdout
    if as_json:
        doc = {"engine": engine, "clean": not findings,
               "findings": [to_record(f) for f in findings]}
        if extra:
            doc.update(extra)
        json.dump(doc, stream, indent=1, sort_keys=True)
        stream.write("\n")
        return 1 if findings else 0
    for f in findings:
        print(f if isinstance(f, str) else f.format(), file=stream)
    if findings:
        return 1
    if clean_note:
        print(clean_note, file=stream)
    return 0
