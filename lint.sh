#!/bin/sh
# CI lint gate: ruff (when installed) + the trace-hygiene linter.
#
# Runs next to the tier-1 suite (see README "Static analysis & trace
# hygiene"):
#     ./lint.sh && JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow'
#
# The checked-in tree lints CLEAN — exit 1 means a new finding.
# Suppress an audited exception inline with `# raft-lint: disable=<rule>`.
set -e
cd "$(dirname "$0")"

if command -v ruff >/dev/null 2>&1; then
    # error-class rules only (syntax errors, undefined names, misused
    # comparisons): meaningful everywhere, no style churn
    ruff check --quiet --select E9,F63,F7,F82 raft_tpu bench.py sweep_10k.py
else
    echo "lint.sh: ruff not installed; skipping ruff (custom linter still runs)"
fi

python -m raft_tpu.analysis lint

# concurrency invariants over the shared-state + serve modules:
# atomic-write / async-blocking / lock-discipline / thread-hygiene —
# the checked-in tree is CLEAN, and each seeded bad fixture must be
# caught with EXACTLY exit 1 (a crash/usage error is a broken engine,
# not a caught finding)
python -m raft_tpu.analysis concurrency
for fixture in bad_atomic bad_async bad_lock bad_thread; do
    conc_rc=0
    python -m raft_tpu.analysis concurrency \
        "tests/fixtures/lint/$fixture.py" > /dev/null 2>&1 || conc_rc=$?
    if [ "$conc_rc" -ne 1 ]; then
        echo "lint.sh: analysis concurrency exited $conc_rc on the" \
             "$fixture fixture (want 1: findings reported)" >&2
        exit 1
    fi
done

# cross-process schema contracts: writer/reader key sets of every
# record family (lease, done-record, worker status, fabric.json,
# manifest/fingerprint, quarantine v2, run-record v1, AOT sidecar)
# must match the checked-in analysis/schema_baseline.json with no
# reader-never-written / required-but-conditional drift; the seeded
# drifted-lease fixture must be caught with EXACTLY exit 1
python -m raft_tpu.analysis schemas
schema_rc=0
python -m raft_tpu.analysis schemas --fixture > /dev/null 2>&1 || schema_rc=$?
if [ "$schema_rc" -ne 1 ]; then
    echo "lint.sh: analysis schemas --fixture exited $schema_rc on the" \
         "drifted-lease fixture (want 1: drift caught)" >&2
    exit 1
fi

# protocol model checker: every shared-fs mutation site in the six
# protocol modules (fabric, fleet, release, rollout, router, canary)
# must match the checked-in analysis/protocol_baseline.json — no
# unmodeled raw writes, no unpinned sites — and the exhaustive
# interleaving + crash-injection explorer must find no invariant
# violation over the real protocol functions; each seeded historical
# race (pre-PR-13 claim live-twin, pre-PR-16 fleet-wide gate race,
# a raw-rename sidecar) must be caught with EXACTLY exit 1
python -m raft_tpu.analysis protocol check
for fixture in claim_hijack gate_fleetwide unmodeled_site; do
    proto_rc=0
    python -m raft_tpu.analysis protocol check \
        --fixture "tests/fixtures/protocol/$fixture.py" > /dev/null 2>&1 \
        || proto_rc=$?
    if [ "$proto_rc" -ne 1 ]; then
        echo "lint.sh: analysis protocol exited $proto_rc on the" \
             "$fixture fixture (want 1: seeded race caught)" >&2
        exit 1
    fi
done

# jaxpr contracts over the health-instrumented entry points
# (solve_dynamics_fowt, the design evaluator, the status fold): the
# status word must stay gather-free/callback-free and inside the
# checked-in primitive budgets (raft_tpu/analysis/primitive_baseline.json)
python -m raft_tpu.analysis contracts

# AOT program-bank integrity: entries parse, payload checksums/sizes
# match their metadata, no orphaned half-writes; stale entries (old
# jax or source fingerprints) are reported but don't fail — `python -m
# raft_tpu.aot gc` reclaims them.  Trivially clean on an empty bank.
python -m raft_tpu.aot verify

# release-manifest integrity: the checked-in good manifest fixture
# must verify clean (exit 0) and the tampered twin (one entry sha
# edited after the cut — signature + content address both break) must
# be caught with EXACTLY exit 1; pure file check, no bank and no jax
python -m raft_tpu.aot release verify \
    --manifest tests/fixtures/releases/good.json > /dev/null
release_rc=0
python -m raft_tpu.aot release verify \
    --manifest tests/fixtures/releases/tampered.json > /dev/null 2>&1 \
    || release_rc=$?
if [ "$release_rc" -ne 1 ]; then
    echo "lint.sh: aot release verify exited $release_rc on the tampered" \
         "manifest fixture (want 1: tamper caught)" >&2
    exit 1
fi

# cross-process trace assembly: the checked-in two-process capture
# (coordinator + fabric worker, per-process clock anchors) must merge
# onto one timeline with every span balanced and every parent id
# resolving (no orphan spans) — the distributed-tracing contract the
# fabric/serve propagation relies on
python -m raft_tpu.obs trace --merge tests/fixtures/obs \
    -o /tmp/raft_obs_merge_check.json --check > /dev/null

# serving-fleet trace assembly: the checked-in router + replica shards
# (a real kill/evict/drain session: router_request -> router_upstream
# spans in the router shard, the replica's serve_request spans
# adopting the router's forwarded traceparent as their remote parent)
# must merge with 0 orphan spans — the router propagation contract
python -m raft_tpu.obs trace --merge tests/fixtures/obs_router \
    -o /tmp/raft_obs_router_merge_check.json --check > /dev/null

# flight-recorder shards: the checked-in valid dump must pass `obs
# flight show` (exit 0: schema-versioned anchor, every record
# stamped) and the truncated twin — the torn write an atomic dumper
# can never produce — must be refused with EXACTLY exit 1 (trusting a
# damaged postmortem is worse than having none)
python -m raft_tpu.obs flight show tests/fixtures/flight/valid.jsonl \
    > /dev/null
flight_rc=0
python -m raft_tpu.obs flight show tests/fixtures/flight/truncated.jsonl \
    > /dev/null 2>&1 || flight_rc=$?
if [ "$flight_rc" -ne 1 ]; then
    echo "lint.sh: obs flight show exited $flight_rc on the truncated" \
         "shard fixture (want 1: damage refused)" >&2
    exit 1
fi

# alert-rule engine: the default rule pack (+ any RAFT_TPU_ALERT_RULES
# override) must validate, the clean run-record fixture must replay
# with no rule firing (exit 0), and the seeded alerting fixture (SLO
# breaches + breaker storm + canary parity split) must be caught with
# EXACTLY exit 1 — the `obs alerts eval --record` CI contract needs no
# live fleet and no jax import
python -m raft_tpu.obs alerts check > /dev/null
python -m raft_tpu.obs alerts eval --record tests/fixtures/runs/clean.json \
    > /dev/null
alerts_rc=0
python -m raft_tpu.obs alerts eval \
    --record tests/fixtures/runs/alerting.json > /dev/null 2>&1 \
    || alerts_rc=$?
if [ "$alerts_rc" -ne 1 ]; then
    echo "lint.sh: obs alerts eval exited $alerts_rc on the alerting" \
         "fixture (want 1: rules fired)" >&2
    exit 1
fi

# perf-regression sentinel: against the checked-in baseline record,
# the clean fixture run must PASS (exit 0) and the regressed fixture
# (5x shard wall, dropped throughput, doubled padding waste) must be
# CAUGHT (exit 1) — the `obs runs regress` CI contract every later
# perf PR gates through
python -m raft_tpu.obs runs regress tests/fixtures/runs/clean.json \
    --baseline tests/fixtures/runs/baseline.json --check > /dev/null
# must be EXACTLY exit 1 (regression caught) — a crash/usage error
# (exit 2) is a broken sentinel, not a caught regression
regress_rc=0
python -m raft_tpu.obs runs regress tests/fixtures/runs/regressed.json \
    --baseline tests/fixtures/runs/baseline.json --check \
    > /dev/null 2>&1 || regress_rc=$?
if [ "$regress_rc" -ne 1 ]; then
    echo "lint.sh: obs runs regress exited $regress_rc on the regressed" \
         "fixture (want 1: regression caught)" >&2
    exit 1
fi
