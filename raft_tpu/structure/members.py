"""Build-time member geometry (numpy).

Turns one member entry of the design schema into static arrays: station
data, the strip-theory discretisation, interpolated hydro coefficients,
end-cap/bulkhead geometry, and the per-section inertia elements.

This mirrors the geometry logic of the reference Member constructor
(``/root/reference/raft/raft_member.py``: strip discretisation :190-267,
station parsing :82-188, cap parsing :161-176) but factors out
everything that does not depend on the FOWT pose so the traced physics
kernels receive fixed-shape tensors.  Position-*dependent* quantities
(node positions, submergence masks, orientation under platform
rotation) are computed later in jax.

Inertia elements: for each section between stations (and each cap /
bulkhead) the mass, axial CG offset and principal moments of inertia
about the CG in member-local axes are closed-form in the geometry alone
(raft_member.py:412-541, 659-823), so they are precomputed here; the
jax statics kernel only rotates/translates them.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from raft_tpu.structure.schema import coerce


def _heading_rot(heading_deg):
    c, s = np.cos(np.deg2rad(heading_deg)), np.sin(np.deg2rad(heading_deg))
    return np.array([[c, -s, 0.0], [s, c, 0.0], [0.0, 0.0, 1.0]])


def _frustum_vcv(dA, dB, H):
    """numpy twin of ops.frustum.frustum_vcv_* for build-time use.

    dA/dB scalars (circular diameters) or length-2 arrays (side pairs);
    helpers.py:36-63."""
    dA = np.asarray(dA, dtype=float)
    dB = np.asarray(dB, dtype=float)
    if np.sum(dA) == 0 and np.sum(dB) == 0:
        return 0.0, 0.0
    if dA.ndim == 0:
        A1 = np.pi / 4 * dA**2
        A2 = np.pi / 4 * dB**2
        Am = np.pi / 4 * dA * dB
    else:
        A1 = dA[0] * dA[1]
        A2 = dB[0] * dB[1]
        Am = np.sqrt(A1 * A2)
    V = (A1 + A2 + Am) * H / 3.0
    hc = ((A1 + 2 * Am + 3 * A2) / (A1 + Am + A2)) * H / 4.0 if (A1 + Am + A2) != 0 else 0.0
    return V, hc


def _frustum_moi(dA, dB, H, rho):
    """helpers.py:65-83 (circular)."""
    if H == 0:
        return 0.0, 0.0
    r1, r2 = dA / 2.0, dB / 2.0
    if dA == dB:
        I_rad = (1 / 12) * (rho * H * np.pi * r1**2) * (3 * r1**2 + 4 * H**2)
        I_ax = 0.5 * rho * np.pi * H * r1**4
    else:
        I_rad = (1 / 20) * rho * np.pi * H * (r2**5 - r1**5) / (r2 - r1) + (
            1 / 30
        ) * rho * np.pi * H**3 * (r1**2 + 3 * r1 * r2 + 6 * r2**2)
        I_ax = (1 / 10) * rho * np.pi * H * (r2**5 - r1**5) / (r2 - r1)
    return I_rad, I_ax


def _rect_moi(La, Wa, Lb, Wb, H, rho):
    """helpers.py:85-146 (rectangular)."""
    if H == 0:
        return 0.0, 0.0, 0.0
    if La == Lb and Wa == Wb:
        M = rho * La * Wa * H
        return (
            (1 / 12) * M * (Wa**2 + 4 * H**2),
            (1 / 12) * M * (La**2 + 4 * H**2),
            (1 / 12) * M * (La**2 + Wa**2),
        )
    if La != Lb and Wa != Wb:
        x2 = (1 / 12) * rho * (
            (Lb - La) ** 3 * H * (Wb / 5 + Wa / 20)
            + (Lb - La) ** 2 * La * H * (3 * Wb / 4 + Wa / 4)
            + (Lb - La) * La**2 * H * (Wb + Wa / 2)
            + La**3 * H * (Wb / 2 + Wa / 2)
        )
        y2 = (1 / 12) * rho * (
            (Wb - Wa) ** 3 * H * (Lb / 5 + La / 20)
            + (Wb - Wa) ** 2 * Wa * H * (3 * Lb / 4 + La / 4)
            + (Wb - Wa) * Wa**2 * H * (Lb + La / 2)
            + Wa**3 * H * (Lb / 2 + La / 2)
        )
        z2 = rho * (Wb * Lb / 5 + Wa * Lb / 20 + La * Wb / 20 + Wa * La / 30) * H**3
    elif La == Lb:
        x2 = (1 / 24) * rho * (La**3) * H * (Wb + Wa)
        y2 = (1 / 48) * rho * La * H * (Wb**3 + Wa * Wb**2 + Wa**2 * Wb + Wa**3)
        z2 = (1 / 12) * rho * La * (H**3) * (3 * Wb + Wa)
    else:  # Wa == Wb
        x2 = (1 / 48) * rho * Wa * H * (Lb**3 + La * Lb**2 + La**2 * Lb + La**3)
        y2 = (1 / 24) * rho * (Wa**3) * H * (Lb + La)
        z2 = (1 / 12) * rho * Wa * (H**3) * (3 * Lb + La)
    return y2 + z2, x2 + z2, x2 + y2


@dataclass
class MemberGeometry:
    """Static geometry of one member (one heading copy)."""

    name: str
    part_of: str            # 'platform' | 'tower' | 'nacelle'
    mtype: str              # 'rigid' | 'beam'
    circular: bool
    potMod: bool
    MCF: bool
    rA0: np.ndarray         # (3,) end A wrt PRP, heading applied
    rB0: np.ndarray
    l: float
    gamma: float            # twist [deg] (incl. heading for vertical members)
    q0: np.ndarray          # member axes at reference pose (no platform rot)
    p10: np.ndarray
    p20: np.ndarray
    R0: np.ndarray          # (3,3), columns map local (x,y,z)->(p1,p2,q)

    stations: np.ndarray    # (n,) axial station positions 0..l
    d: np.ndarray           # (n,2) outer diameter pair (duplicated if circular)
    t: np.ndarray           # (n,) shell thickness
    rho_shell: float
    l_fill: np.ndarray      # (n-1,) ballast fill length per section [m]
    rho_fill: np.ndarray    # (n-1,) ballast density per section

    # strips (hydro nodes), raft_member.py:190-267
    ls: np.ndarray          # (ns,) node position along axis
    dls: np.ndarray         # (ns,) lumped strip length
    ds: np.ndarray          # (ns,2) strip mean diameter/side pair
    drs: np.ndarray         # (ns,2) strip radius/side-half change
    # strip coefficients interpolated at ls (raft_member.py:1315-1318 etc.)
    Cd_q: np.ndarray
    Cd_p1: np.ndarray
    Cd_p2: np.ndarray
    Cd_End: np.ndarray
    Ca_q: np.ndarray
    Ca_p1: np.ndarray
    Ca_p2: np.ndarray
    Ca_End: np.ndarray

    # inertia elements: sections + caps flattened (see module docstring)
    elem_mass: np.ndarray     # (ne,)
    elem_s: np.ndarray        # (ne,) axial CG offset from rA along axis
    elem_Ixx: np.ndarray      # (ne,) about CG, member-local axes (p1,p2,q)
    elem_Iyy: np.ndarray
    elem_Izz: np.ndarray

    # bookkeeping for reporting (mass of shell incl. caps, ballast lists)
    mshell: float = 0.0
    mfill: list = field(default_factory=list)
    pfill: list = field(default_factory=list)
    vfill: list = field(default_factory=list)

    # cap/bulkhead configuration (resolved axial positions; kept so the
    # traced-geometry twin can recompute cap inertias for scaled d/t)
    cap_L: np.ndarray | None = None       # (nc,) axial positions 0..l
    cap_t_arr: np.ndarray | None = None   # (nc,) cap thicknesses
    cap_d_in_arr: np.ndarray | None = None  # (nc,) or (nc,2) hole sizes

    # beam (flexible) member data
    E: float = 0.0
    G: float = 0.0
    dorsl_node_ext: np.ndarray | None = None  # (ns, 2) external d/side at strip nodes
    dorsl_node_int: np.ndarray | None = None  # (ns, 2) internal
    # per-node lumped ballast/cap data for beams (raft_member.py:550-657, 806-823)
    node_ballast_mass: np.ndarray | None = None    # (ns,)
    node_ballast_center: np.ndarray | None = None  # (ns, 3) wrt rA in member coords (global at ref pose)
    node_ballast_I: np.ndarray | None = None       # (ns, 3) local principal MoI about its CG
    node_cap_mass: np.ndarray | None = None
    node_cap_center: np.ndarray | None = None
    node_cap_I: np.ndarray | None = None

    @property
    def ns(self):
        return len(self.ls)


def build_member(mi, heading=0.0, part_of="platform", global_dlsMax=5.0):
    """Construct MemberGeometry from a member dict of the design schema.

    Mirrors Member.__init__ (raft_member.py:17-310) minus runtime state.
    """
    # normalise the member type: the current schema uses 'rigid'/'beam';
    # legacy designs carry numeric type codes (all rigid)
    mtype = "beam" if str(mi.get("type", "rigid")).lower() == "beam" else "rigid"
    rA0 = np.array(mi["rA"], dtype=float)
    rB0 = np.array(mi["rB"], dtype=float)
    shape = str(mi["shape"])
    circular = shape[0].lower() == "c"

    gamma = float(coerce(mi, "gamma", default=0.0))
    rAB = rB0 - rA0
    l = float(np.linalg.norm(rAB))

    if heading != 0.0:
        R_h = _heading_rot(heading)
        rA0 = R_h @ rA0
        rB0 = R_h @ rB0
        if rAB[0] == 0.0 and rAB[1] == 0.0:  # vertical: heading becomes twist
            gamma += heading

    st = np.array(mi["stations"], dtype=float)
    n = len(st)
    stations = (st - st[0]) / (st[-1] - st[0]) * l

    if circular:
        d1 = coerce(mi, "d", shape=n)
        d = np.stack([d1, d1], axis=1)
        gamma = 0.0  # twist irrelevant for circular (raft_member.py:104)
    else:
        d = coerce(mi, "d", shape=[n, 2])

    t = coerce(mi, "t", shape=n, default=0)
    rho_shell = float(coerce(mi, "rho_shell", shape=0, default=8500.0))

    st_fill = coerce(mi, "l_fill", shape=n - 1, default=0)
    l_fill = st_fill / (st[-1] - st[0]) * l
    rho_fill_in = coerce(mi, "rho_fill", shape=-1, default=1025)
    if np.isscalar(rho_fill_in):
        rho_fill = np.zeros(n - 1) + rho_fill_in
    else:
        rho_fill = np.array(rho_fill_in, dtype=float)

    # drag / added mass coefficients at stations (raft_member.py:179-188)
    Cd_q_st = coerce(mi, "Cd_q", shape=n, default=0.0)
    Cd_p1_st = coerce(mi, "Cd", shape=n, default=0.6, index=0)
    Cd_p2_st = coerce(mi, "Cd", shape=n, default=0.6, index=1)
    Cd_End_st = coerce(mi, "CdEnd", shape=n, default=0.6)
    Ca_q_st = coerce(mi, "Ca_q", shape=n, default=0.0)
    Ca_p1_st = coerce(mi, "Ca", shape=n, default=0.97, index=0)
    Ca_p2_st = coerce(mi, "Ca", shape=n, default=0.97, index=1)
    Ca_End_st = coerce(mi, "CaEnd", shape=n, default=0.6)

    # ----- strip discretisation (raft_member.py:190-254) -----
    dorsl = [d[i].copy() for i in range(n)]
    dorsl_int = [np.maximum(0.0, d[i] - 2 * t[i]) for i in range(n)]
    dlsMax = float(coerce(mi, "dlsMax", shape=0, default=global_dlsMax))

    ls = [0.0]
    dls = [0.0]
    ds = [0.5 * dorsl[0]]
    drs = [0.5 * dorsl[0]]
    d_node_ext = [dorsl[0]]
    d_node_int = [dorsl_int[0]]
    for i in range(1, n):
        lstrip = stations[i] - stations[i - 1]
        if lstrip > 0.0:
            ns_i = int(np.ceil(lstrip / dlsMax))
            dlstrip = lstrip / ns_i
            m = 0.5 * (dorsl[i] - dorsl[i - 1]) / lstrip
            m_int = 0.5 * (dorsl_int[i] - dorsl_int[i - 1]) / lstrip
            ls += [stations[i - 1] + dlstrip * (0.5 + j) for j in range(ns_i)]
            dls += [dlstrip] * ns_i
            ds += [dorsl[i - 1] + dlstrip * 2 * m * (0.5 + j) for j in range(ns_i)]
            drs += [dlstrip * m] * ns_i
            d_node_ext += [dorsl[i - 1] + dlstrip * 2 * m * (0.5 + j) for j in range(ns_i)]
            d_node_int += [dorsl_int[i - 1] + dlstrip * 2 * m_int * (0.5 + j) for j in range(ns_i)]
        elif lstrip == 0.0:
            ls += [stations[i - 1]]
            dls += [0.0]
            ds += [0.5 * (dorsl[i - 1] + dorsl[i])]
            drs += [0.5 * (dorsl[i] - dorsl[i - 1])]
            d_node_ext += [dorsl[i - 1]]
            d_node_int += [dorsl_int[i - 1]]
    # end B strip (raft_member.py:245-254)
    ls += [stations[-1]]
    dls += [0.0]
    ds += [0.5 * dorsl[-1]]
    drs += [-0.5 * dorsl[-1]]
    d_node_ext += [dorsl[-1]]
    d_node_int += [dorsl_int[-1]]

    ls = np.array(ls, dtype=float)
    dls = np.array(dls, dtype=float)
    ds = np.stack([np.broadcast_to(x, (2,)) for x in ds])
    drs = np.stack([np.broadcast_to(x, (2,)) for x in drs])
    d_node_ext = np.stack([np.broadcast_to(x, (2,)) for x in d_node_ext])
    d_node_int = np.stack([np.broadcast_to(x, (2,)) for x in d_node_int])

    # ----- member axes at reference pose (raft_member.py:312-345) -----
    q = (rB0 - rA0) / l
    beta_m = np.arctan2(q[1], q[0])
    phi_m = np.arctan2(np.sqrt(q[0] ** 2 + q[1] ** 2), q[2])
    s1, c1 = np.sin(beta_m), np.cos(beta_m)
    s2, c2 = np.sin(phi_m), np.cos(phi_m)
    s3, c3 = np.sin(np.deg2rad(gamma)), np.cos(np.deg2rad(gamma))
    R0 = np.array(
        [
            [c1 * c2 * c3 - s1 * s3, -c3 * s1 - c1 * c2 * s3, c1 * s2],
            [c1 * s3 + c2 * c3 * s1, c1 * c3 - c2 * s1 * s3, s1 * s2],
            [-c3 * s2, s2 * s3, c2],
        ]
    )
    p1 = R0 @ np.array([1.0, 0.0, 0.0])
    p2 = np.cross(q, p1)

    # ----- per-strip coefficients (np.interp over stations) -----
    def interp(c_st):
        return np.interp(ls, stations, c_st)

    geom = MemberGeometry(
        name=str(mi.get("name", "member")),
        part_of=part_of.lower(),
        mtype=mtype,
        circular=circular,
        potMod=bool(coerce(mi, "potMod", dtype=bool, default=False)),
        MCF=bool(coerce(mi, "MCF", dtype=bool, default=False)) and circular,
        rA0=rA0,
        rB0=rB0,
        l=l,
        gamma=gamma,
        q0=q,
        p10=p1,
        p20=p2,
        R0=R0,
        stations=stations,
        d=d,
        t=t,
        rho_shell=rho_shell,
        l_fill=l_fill,
        rho_fill=rho_fill,
        ls=ls,
        dls=dls,
        ds=ds,
        drs=drs,
        Cd_q=interp(Cd_q_st),
        Cd_p1=interp(Cd_p1_st),
        Cd_p2=interp(Cd_p2_st),
        Cd_End=interp(Cd_End_st),
        Ca_q=interp(Ca_q_st),
        Ca_p1=interp(Ca_p1_st),
        Ca_p2=interp(Ca_p2_st),
        Ca_End=interp(Ca_End_st),
        elem_mass=np.zeros(0),
        elem_s=np.zeros(0),
        elem_Ixx=np.zeros(0),
        elem_Iyy=np.zeros(0),
        elem_Izz=np.zeros(0),
        E=float(np.atleast_1d(mi.get("E", [0.0]))[0]) if "E" in mi else 0.0,
        G=float(np.atleast_1d(mi.get("G", [0.0]))[0]) if "G" in mi else 0.0,
        dorsl_node_ext=d_node_ext,
        dorsl_node_int=d_node_int,
    )
    _parse_caps(geom, mi)
    if mtype == "beam":
        _build_beam_node_data(geom, mi)
    else:
        _build_inertia_elements(geom, mi)
    return geom


def _parse_caps(g: MemberGeometry, mi):
    """Resolve the cap/bulkhead configuration onto the geometry object
    (axial positions scaled to member length, raft_member.py:161-176)."""
    cap_stations_in = coerce(mi, "cap_stations", shape=-1, default=[])
    if len(np.atleast_1d(cap_stations_in)) == 0:
        g.cap_L = np.zeros(0)
        g.cap_t_arr = np.zeros(0)
        g.cap_d_in_arr = np.zeros(0)
        return
    cap_st_in = np.atleast_1d(np.array(cap_stations_in, dtype=float))
    g.cap_t_arr = np.atleast_1d(coerce(mi, "cap_t", shape=cap_st_in.shape[0]))
    if g.circular:
        g.cap_d_in_arr = np.atleast_1d(
            coerce(mi, "cap_d_in", shape=cap_st_in.shape[0]))
    else:
        g.cap_d_in_arr = coerce(mi, "cap_d_in", shape=[cap_st_in.shape[0], 2])
    st0 = np.array(mi["stations"], dtype=float)
    g.cap_L = (cap_st_in - st0[0]) / (st0[-1] - st0[0]) * g.l


def _build_beam_node_data(g: MemberGeometry, mi):
    """Per-node lumped ballast and cap data for flexible members.

    Beam branch of Member.getInertia (raft_member.py:550-657): ballast
    in each section is split between nodes by their half-spacing zones;
    caps lump at the closest node (:806-823).
    """
    ns = g.ns
    nodes_s = g.ls.copy()  # node positions along axis (straight member)
    dist_p = np.diff(nodes_s, prepend=0)
    dist_n = np.diff(nodes_s, append=nodes_s[-1])

    mass_b = np.zeros(ns)
    center_b = np.zeros((ns, 3))
    I_b = np.zeros((ns, 3))
    n = len(g.stations)
    mfill, pfill, vfill = [], [], []
    for i in range(1, n):
        lsec = g.stations[i] - g.stations[i - 1]
        sec_mass = 0.0
        sec_v = 0.0
        rho_fill = g.rho_fill[i - 1] if lsec > 0 else 0.0
        if lsec > 0:
            l_fill = g.l_fill[i - 1]
            for inode in range(ns):
                s_lo = max(nodes_s[inode] - dist_p[inode] / 2, g.stations[i - 1])
                s_hi = min(nodes_s[inode] + dist_n[inode] / 2, g.stations[i - 1] + l_fill)
                l_node = s_hi - s_lo
                if l_node <= 0:
                    continue
                if g.circular:
                    dA_st = g.d[i - 1, 0] - 2 * g.t[i - 1]
                    dB_st = g.d[i, 0] - 2 * g.t[i]
                    dA = (dB_st - dA_st) * ((s_lo - g.stations[i - 1]) / lsec) + dA_st
                    dB = (dB_st - dA_st) * ((s_hi - g.stations[i - 1]) / lsec) + dA_st
                    v_n, hc_n = _frustum_vcv(dA, dB, l_node)
                    m_n = v_n * rho_fill
                    Ir_end, Ia = _frustum_moi(dA, dB, l_node, rho_fill)
                    Ir = Ir_end - m_n * hc_n**2
                    Ixx, Iyy, Izz = Ir, Ir, Ia
                else:
                    slA_st = g.d[i - 1] - 2 * g.t[i - 1]
                    slB_st = g.d[i] - 2 * g.t[i]
                    slA = (slB_st - slA_st) * ((s_lo - g.stations[i - 1]) / lsec) + slA_st
                    slB = (slB_st - slA_st) * ((s_hi - g.stations[i - 1]) / lsec) + slA_st
                    v_n, hc_n = _frustum_vcv(slA, slB, l_node)
                    m_n = v_n * rho_fill
                    Ix_e, Iy_e, Iz_e = _rect_moi(slA[0], slA[1], slB[0], slB[1], l_node, rho_fill)
                    Ixx = Ix_e - m_n * hc_n**2
                    Iyy = Iy_e - m_n * hc_n**2
                    Izz = Iz_e
                center = g.rA0 + g.q0 * (s_lo + hc_n)
                mass_b[inode] += m_n
                center_b[inode] += center * m_n
                I_b[inode] += np.array([Ixx, Iyy, Izz])
                sec_mass += m_n
                sec_v += v_n
        vfill.append(float(sec_v))
        mfill.append(float(sec_mass))
        pfill.append(float(rho_fill))
    nonzero = mass_b > 0
    center_b[nonzero] /= mass_b[nonzero, None]

    # caps lump at the closest node (raft_member.py:806-823)
    mass_c = np.zeros(ns)
    center_c = np.zeros((ns, 3))
    I_c = np.zeros((ns, 3))
    m_caps_total = 0.0
    for (m_cap, s_cg, Ix, Iy, Iz) in _cap_elements(g):
        center_cap = g.rA0 + g.q0 * s_cg
        inode = int(np.argmin(np.linalg.norm(
            (g.rA0[None, :] + g.q0[None, :] * nodes_s[:, None]) - center_cap[None, :],
            axis=1)))
        mass_c[inode] += m_cap
        center_c[inode] += center_cap * m_cap
        I_c[inode] += np.array([Ix, Iy, Iz])
        m_caps_total += m_cap
    nz = mass_c > 0
    center_c[nz] /= mass_c[nz, None]

    g.node_ballast_mass = mass_b
    g.node_ballast_center = center_b
    g.node_ballast_I = I_b
    g.node_cap_mass = mass_c
    g.node_cap_center = center_c
    g.node_cap_I = I_c
    g.mshell = m_caps_total  # shell mass itself comes from the FE matrix
    g.mfill = mfill
    g.pfill = pfill
    g.vfill = vfill


def _cap_elements(g: MemberGeometry):
    """Cap/bulkhead inertia elements (raft_member.py:659-823):
    list of (mass, axial CG offset, Ixx, Iyy, Izz about CG, local axes).
    Uses the cap configuration resolved by :func:`_parse_caps`."""
    out = []
    cap_L = g.cap_L
    cap_t = g.cap_t_arr
    cap_d_in = g.cap_d_in_arr
    if cap_L is not None and len(cap_L) > 0:

        for ic in range(len(cap_L)):
            L = cap_L[ic]
            h = cap_t[ic]
            rho_cap = g.rho_shell
            if g.circular:
                d_hole = cap_d_in[ic]
                d_in = g.d[:, 0] - 2 * g.t
                if L == g.stations[0]:
                    dA = d_in[0]
                    dB = np.interp(L + h, g.stations, d_in)
                    dAi = d_hole
                    dBi = dB * (dAi / dA) if dA != 0 else 0.0
                elif L == g.stations[-1]:
                    dA = np.interp(L - h, g.stations, d_in)
                    dB = d_in[-1]
                    dBi = d_hole
                    dAi = dA * (dBi / dB) if dB != 0 else 0.0
                elif ic < len(cap_L) - 1 and L == cap_L[ic + 1]:
                    # discontinuity station, lower-member end cap
                    # (raft_member.py:689-693; note d_in indexed by cap idx)
                    dA = np.interp(L - h, g.stations, d_in)
                    dB = d_in[ic]
                    dBi = d_hole
                    dAi = dA * (dBi / dB) if dB != 0 else 0.0
                elif ic > 0 and L == cap_L[ic - 1]:
                    # discontinuity station, upper-member end cap
                    # (raft_member.py:694-698)
                    dA = d_in[ic]
                    dB = np.interp(L + h, g.stations, d_in)
                    dAi = d_hole
                    dBi = dB * (dAi / dA) if dA != 0 else 0.0
                else:
                    dA = np.interp(L - h / 2, g.stations, d_in)
                    dB = np.interp(L + h / 2, g.stations, d_in)
                    dM = np.interp(L, g.stations, d_in)
                    dMi = d_hole
                    dAi = dA * (dMi / dM) if dM != 0 else 0.0
                    dBi = dB * (dMi / dM) if dM != 0 else 0.0
                V_o, hco = _frustum_vcv(dA, dB, h)
                V_i, hci = _frustum_vcv(dAi, dBi, h)
                v_cap = V_o - V_i
                m_cap = v_cap * rho_cap
                hc_cap = ((hco * V_o) - (hci * V_i)) / (V_o - V_i) if V_o != V_i else 0.0
                Ir_o, Ia_o = _frustum_moi(dA, dB, h, rho_cap)
                Ir_i, Ia_i = _frustum_moi(dAi, dBi, h, rho_cap)
                I_rad = (Ir_o - Ir_i) - m_cap * hc_cap**2
                I_ax = Ia_o - Ia_i
                Ixx, Iyy, Izz = I_rad, I_rad, I_ax
            else:
                sl_hole = cap_d_in[ic]
                sl_in = g.d - 2 * g.t[:, None]
                if L == g.stations[0]:
                    slA = sl_in[0]
                    slB = np.array(
                        [np.interp(L + h, g.stations, sl_in[:, 0]),
                         np.interp(L + h, g.stations, sl_in[:, 1])]
                    )
                    slAi = sl_hole
                    slBi = slB * (slAi / slA)
                elif L == g.stations[-1]:
                    slB = sl_in[-1]
                    slA = np.array(
                        [np.interp(L - h, g.stations, sl_in[:, 0]),
                         np.interp(L - h, g.stations, sl_in[:, 1])]
                    )
                    slBi = sl_hole
                    slAi = slA * (slBi / slB)
                elif ic < len(cap_L) - 1 and L == cap_L[ic + 1]:
                    slA = np.array(
                        [np.interp(L - h, g.stations, sl_in[:, 0]),
                         np.interp(L - h, g.stations, sl_in[:, 1])]
                    )
                    slB = sl_in[ic]
                    slBi = sl_hole
                    slAi = slA * (slBi / slB)
                elif ic > 0 and L == cap_L[ic - 1]:
                    slA = sl_in[ic]
                    slB = np.array(
                        [np.interp(L + h, g.stations, sl_in[:, 0]),
                         np.interp(L + h, g.stations, sl_in[:, 1])]
                    )
                    slAi = sl_hole
                    slBi = slB * (slAi / slA)
                else:
                    slA = np.array(
                        [np.interp(L - h / 2, g.stations, sl_in[:, 0]),
                         np.interp(L - h / 2, g.stations, sl_in[:, 1])]
                    )
                    slB = np.array(
                        [np.interp(L + h / 2, g.stations, sl_in[:, 0]),
                         np.interp(L + h / 2, g.stations, sl_in[:, 1])]
                    )
                    slM = np.array(
                        [np.interp(L, g.stations, sl_in[:, 0]),
                         np.interp(L, g.stations, sl_in[:, 1])]
                    )
                    slMi = sl_hole
                    slAi = slA * (slMi / slM)
                    slBi = slB * (slMi / slM)
                V_o, hco = _frustum_vcv(slA, slB, h)
                V_i, hci = _frustum_vcv(slAi, slBi, h)
                v_cap = V_o - V_i
                m_cap = v_cap * rho_cap
                hc_cap = ((hco * V_o) - (hci * V_i)) / (V_o - V_i) if V_o != V_i else 0.0
                Ix_o, Iy_o, Iz_o = _rect_moi(slA[0], slA[1], slB[0], slB[1], h, rho_cap)
                Ix_i, Iy_i, Iz_i = _rect_moi(slAi[0], slAi[1], slBi[0], slBi[1], h, rho_cap)
                Ixx = (Ix_o - Ix_i) - m_cap * hc_cap**2
                Iyy = (Iy_o - Iy_i) - m_cap * hc_cap**2
                Izz = Iz_o - Iz_i

            # cap CG axial position (raft_member.py:780-787)
            if L == g.stations[0]:
                s_cg = L + hc_cap
            elif L == g.stations[-1]:
                s_cg = L - (h - hc_cap)
            else:
                s_cg = L - (h / 2 - hc_cap)

            out.append((m_cap, s_cg, Ixx, Iyy, Izz))

    return out


def _build_inertia_elements(g: MemberGeometry, mi):
    """Precompute shell+ballast section and cap inertia elements.

    Rigid-member branch of Member.getInertia (raft_member.py:412-541)
    and the cap/bulkhead block (raft_member.py:659-823), reduced to
    (mass, axial CG offset, local principal MoI about CG) per element.
    """
    n = len(g.stations)
    masses, ss, Ixxs, Iyys, Izzs = [], [], [], [], []
    mshell = 0.0
    mfill, pfill, vfill = [], [], []

    for i in range(1, n):
        lsec = g.stations[i] - g.stations[i - 1]
        if lsec <= 0:
            # Reference quirk (replicated for parity): getInertia does not
            # reset Ixx/Iyy/Izz per iteration, so a zero-length section
            # re-adds the PREVIOUS section's CG inertia with zero mass
            # (raft_member.py:413-540: `if l > 0` skips the recompute but
            # the Mmat/I accumulation below it still runs).
            if masses:
                masses.append(0.0)
                ss.append(0.0)
                Ixxs.append(Ixxs[-1])
                Iyys.append(Iyys[-1])
                Izzs.append(Izzs[-1])
            vfill.append(0.0)
            mfill.append(0.0)
            pfill.append(0.0)
            continue
        l_fill = g.l_fill[i - 1] if np.ndim(g.l_fill) else g.l_fill
        rho_fill = g.rho_fill[i - 1] if np.ndim(g.rho_fill) else g.rho_fill

        if g.circular:
            dA, dB = g.d[i - 1, 0], g.d[i, 0]
            dAi = dA - 2 * g.t[i - 1]
            dBi = dB - 2 * g.t[i]
            V_o, hco = _frustum_vcv(dA, dB, lsec)
            V_i, hci = _frustum_vcv(dAi, dBi, lsec)
            v_shell = V_o - V_i
            m_shell = v_shell * g.rho_shell
            hc_shell = ((hco * V_o) - (hci * V_i)) / (V_o - V_i) if V_o != V_i else 0.0
            dBi_fill = (dBi - dAi) * (l_fill / lsec) + dAi
            v_fill, hc_fill = _frustum_vcv(dAi, dBi_fill, l_fill)
            m_fill = v_fill * rho_fill
            mass = m_shell + m_fill
            hc = ((hc_fill * m_fill) + (hc_shell * m_shell)) / mass if mass != 0 else 0.0
            Ir_o, Ia_o = _frustum_moi(dA, dB, lsec, g.rho_shell)
            Ir_i, Ia_i = _frustum_moi(dAi, dBi, lsec, g.rho_shell)
            Ir_f, Ia_f = _frustum_moi(dAi, dBi_fill, l_fill, rho_fill)
            I_rad_end = (Ir_o - Ir_i) + Ir_f
            I_rad = I_rad_end - mass * hc**2
            I_ax = (Ia_o - Ia_i) + Ia_f
            Ixx, Iyy, Izz = I_rad, I_rad, I_ax
        else:
            slA, slB = g.d[i - 1], g.d[i]
            slAi = slA - 2 * g.t[i - 1]
            slBi = slB - 2 * g.t[i]
            V_o, hco = _frustum_vcv(slA, slB, lsec)
            V_i, hci = _frustum_vcv(slAi, slBi, lsec)
            v_shell = V_o - V_i
            m_shell = v_shell * g.rho_shell
            hc_shell = ((hco * V_o) - (hci * V_i)) / (V_o - V_i) if V_o != V_i else 0.0
            slBi_fill = (slBi - slAi) * (l_fill / lsec) + slAi
            v_fill, hc_fill = _frustum_vcv(slAi, slBi_fill, l_fill)
            m_fill = v_fill * rho_fill
            mass = m_shell + m_fill
            hc = ((hc_fill * m_fill) + (hc_shell * m_shell)) / mass if mass != 0 else 0.0
            Ix_o, Iy_o, Iz_o = _rect_moi(slA[0], slA[1], slB[0], slB[1], lsec, g.rho_shell)
            Ix_i, Iy_i, Iz_i = _rect_moi(slAi[0], slAi[1], slBi[0], slBi[1], lsec, g.rho_shell)
            Ix_f, Iy_f, Iz_f = _rect_moi(
                slAi[0], slAi[1], slBi_fill[0], slBi_fill[1], l_fill, rho_fill
            )
            Ixx = (Ix_o - Ix_i) + Ix_f - mass * hc**2
            Iyy = (Iy_o - Iy_i) + Iy_f - mass * hc**2
            Izz = (Iz_o - Iz_i) + Iz_f

        masses.append(mass)
        ss.append(g.stations[i - 1] + hc)
        Ixxs.append(Ixx)
        Iyys.append(Iyy)
        Izzs.append(Izz)
        mshell += m_shell
        vfill.append(float(np.ravel(v_fill)[0]) if np.ndim(v_fill) else float(v_fill))
        mfill.append(float(m_fill))
        pfill.append(float(rho_fill))

    # ----- caps / bulkheads (shared helper) -----
    for (m_cap, s_cg, Ixx, Iyy, Izz) in _cap_elements(g):
        masses.append(m_cap)
        ss.append(s_cg)
        Ixxs.append(Ixx)
        Iyys.append(Iyy)
        Izzs.append(Izz)
        mshell += m_cap

    g.elem_mass = np.array(masses)
    g.elem_s = np.array(ss)
    g.elem_Ixx = np.array(Ixxs)
    g.elem_Iyy = np.array(Iyys)
    g.elem_Izz = np.array(Izzs)
    g.mshell = mshell
    g.mfill = mfill
    g.pfill = pfill
    g.vfill = vfill
