"""Offline tooling over captured telemetry JSONL streams.

Two consumers of a ``RAFT_TPU_LOG`` capture (pure stdlib, no jax):

* :func:`render_report` — the ``python -m raft_tpu.obs report`` view:
  per-stage wall-time tree built from the span hierarchy (count /
  total / p50 / p95), the counter table from the run's final metrics
  snapshot, per-event-name counts, and a reliability summary
  (retries, OOM splits, quarantine/escalation outcomes) — i.e. "where
  did the sweep spend its time and what fraction was retried /
  flagged / escalated" without re-running anything.
* :func:`chrome_trace` — the ``python -m raft_tpu.obs trace`` export:
  Chrome/Perfetto trace-event JSON (``chrome://tracing`` /
  https://ui.perfetto.dev) with one complete ("X") slice per matched
  span pair, instant events for everything else, and counter tracks
  from the heartbeat stream's device-memory samples.
"""

from __future__ import annotations

import json
import os
import re


def expand_captures(paths):
    """Flatten capture arguments: a directory expands to its sorted
    ``*.jsonl`` shards (the per-process ``RAFT_TPU_LOG=<dir>`` layout),
    a file stands for itself."""
    out = []
    for p in paths:
        if os.path.isdir(p):
            out += [os.path.join(p, n) for n in sorted(os.listdir(p))
                    if n.endswith(".jsonl")]
        else:
            out.append(p)
    return out


def merge_captures(paths):
    """Assemble several per-process captures into ONE event list on a
    shared wall-clock timeline.

    Every process's ``t`` is monotonic since ITS OWN start; the
    ``proc_start`` clock anchors (emitted as each sink's first record)
    carry ``unix_t``, so an anchored event maps to
    ``unix_t + (t - t_anchor)``.  Files without an anchor (captures
    predating the anchor, or truncated heads) are laid out sequentially
    AFTER the anchored window — visible, just not aligned.  Returns
    ``(events, n_bad, info)``; the returned events carry normalized
    ``t`` (seconds from the earliest anchored instant) and sort by it.
    """
    per_file = []
    n_bad = 0
    walls = []
    for path in expand_captures(paths):
        events, bad = read_events(path)
        n_bad += bad
        if not events:
            continue
        # segment by anchor: a pid-reused shard file can hold several
        # process lifetimes, each opening with its own proc_start
        anchor = None
        rows = []
        for ev in events:
            if ev["event"] == "proc_start" and "unix_t" in ev:
                anchor = (ev["t"], float(ev["unix_t"]))
            wall = (anchor[1] + (ev["t"] - anchor[0])
                    if anchor is not None else None)
            rows.append((wall, ev))
            if wall is not None:
                walls.append(wall)
        per_file.append((path, rows))
    t0 = min(walls) if walls else 0.0
    merged = []
    n_unanchored_files = 0
    cursor = (max(walls) - t0 + 1e-3) if walls else 0.0
    for path, rows in per_file:
        unanchored = [ev for wall, ev in rows if wall is None]
        if unanchored:
            n_unanchored_files += 1
            lo = min(ev["t"] for ev in unanchored)
            hi = max(ev["t"] for ev in unanchored)
            for ev in unanchored:
                ev = dict(ev)
                ev["t"] = round(cursor + (ev["t"] - lo), 6)
                merged.append(ev)
            cursor += (hi - lo) + 1e-3
        for wall, ev in rows:
            if wall is None:
                continue
            ev = dict(ev)
            ev["t"] = round(wall - t0, 6)
            merged.append(ev)
    merged.sort(key=lambda e: e["t"])
    info = {"files": len(per_file),
            "unanchored_files": n_unanchored_files,
            "t0_unix": round(t0, 6) if walls else None}
    return merged, n_bad, info


def read_events(path):
    """Parse one JSONL capture; returns ``(events, n_bad_lines)``.
    Damaged lines (a process killed mid-write pre-dates the sink lock)
    are counted, not fatal."""
    events, bad = [], 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                bad += 1
                continue
            if isinstance(ev, dict) and "event" in ev:
                events.append(ev)
            else:
                bad += 1
    return events, bad


def collect_spans(events):
    """Match ``span_begin``/``span_end`` pairs by span id.

    Returns ``(spans, unmatched_begins)``; each span dict carries
    name/t0/t1/wall_s/ok/ids/attrs.  Ends without a begin are dropped
    (a capture that starts mid-run)."""
    begins = {}
    spans = []
    for ev in events:
        kind = ev["event"]
        if kind == "span_begin" and "span_id" in ev:
            begins[ev["span_id"]] = ev
        elif kind == "span_end" and ev.get("span_id") in begins:
            b = begins.pop(ev["span_id"])
            attrs = {k: v for k, v in b.items()
                     if k not in ("t", "event", "pid", "run_id", "trace_id",
                                  "span_id", "name", "parent_id")}
            spans.append({
                "name": b.get("name", "?"),
                "t0": b["t"], "t1": ev["t"],
                "wall_s": ev.get("wall_s", round(ev["t"] - b["t"], 6)),
                "ok": ev.get("ok", True),
                "error": ev.get("error"),
                "span_id": b["span_id"],
                "parent_id": b.get("parent_id"),
                "trace_id": b.get("trace_id"),
                "pid": b.get("pid"),
                "run_id": b.get("run_id"),
                "attrs": attrs,
            })
    return spans, list(begins.values())


def _percentile(vals, p):
    vals = sorted(vals)
    if not vals:
        return None
    i = min(len(vals) - 1, max(0, round(p * (len(vals) - 1))))
    return vals[i]


def span_paths(spans):
    """Aggregate spans by their name *path* (root→leaf names following
    parent ids).  Returns ``{path_tuple: [wall_s, ...]}`` plus the
    per-path failure counts."""
    by_id = {s["span_id"]: s for s in spans}
    paths = {}
    fails = {}

    def path_of(s, _depth=0):
        if s["parent_id"] and s["parent_id"] in by_id and _depth < 64:
            return path_of(by_id[s["parent_id"]], _depth + 1) + (s["name"],)
        return (s["name"],)

    for s in spans:
        p = path_of(s)
        paths.setdefault(p, []).append(s["wall_s"])
        if not s.get("ok", True):
            fails[p] = fails.get(p, 0) + 1
    return paths, fails


def _fmt_s(v):
    return f"{v:9.3f}s" if v is not None else "        —"


#: the serve tail-attribution stage names, in pipeline order (the
#: batcher emits one ``serve_request_stages`` event per resolved
#: dispatched request with ``<stage>_s`` fields summing to wall_s)
SERVE_STAGES = ("queue_wait", "tick_wait", "dispatch", "solve", "post")


def serve_stage_attribution(events):
    """The p50-vs-p95 latency decomposition of the dispatched serve
    requests in a capture, or None when no ``serve_request_stages``
    events are present.

    Rather than reporting each stage's independent percentile (whose
    sum can exceed the total's percentile arbitrarily), the p50/p95
    columns show the stage breakdown of the *request at that rank of
    total latency* — stages then sum to that request's measured
    end-to-end latency by construction, so "p95 is 4.5x p50" reads
    directly as "the p95 request spent X ms in tick-wait"."""
    reqs = [e for e in events if e["event"] == "serve_request_stages"]
    if not reqs:
        return None

    def stages_of(e):
        return {s: float(e.get(f"{s}_s") or 0.0) for s in SERVE_STAGES}

    reqs.sort(key=lambda e: float(e.get("wall_s") or 0.0))

    def at_rank(p):
        i = min(len(reqs) - 1, max(0, round(p * (len(reqs) - 1))))
        e = reqs[i]
        st = stages_of(e)
        return {"total_s": round(float(e.get("wall_s") or 0.0), 6),
                "stages": {k: round(v, 6) for k, v in st.items()},
                "stages_sum_s": round(sum(st.values()), 6)}

    n = len(reqs)
    mean_stages = {s: round(sum(stages_of(e)[s] for e in reqs) / n, 6)
                   for s in SERVE_STAGES}
    return {
        "n_requests": n,
        "mean": {"total_s": round(sum(float(e.get("wall_s") or 0.0)
                                      for e in reqs) / n, 6),
                 "stages": mean_stages},
        "p50": at_rank(0.50),
        "p95": at_rank(0.95),
    }


def waste_axes_from_counters(counters):
    """``{axis: {valid, padded, waste_frac}}`` from the exact
    ``pad_valid_<axis>`` / ``pad_total_<axis>`` counter pairs a
    dispatch records — the single definition of the counter→waste
    derivation (the report table AND the run store's ``waste:*``
    regress metrics both go through here, so they cannot diverge)."""
    axes = {}
    for name, total in (counters or {}).items():
        m = re.fullmatch(r"pad_total_(\w+)", name)
        if m and total:
            axis = m.group(1)
            valid = counters.get(f"pad_valid_{axis}", 0)
            axes[axis] = {"valid": int(valid), "padded": int(total),
                          "waste_frac": round(1.0 - valid / total, 6)}
    return axes


def waste_attribution(events, snapshot=None):
    """Per-axis padding-waste decomposition, or None when the capture
    carries no waste instrumentation.

    Sources, in preference order: the final metrics snapshot's exact
    ``pad_valid_<axis>`` / ``pad_total_<axis>`` counter pairs (summed
    over every dispatched row — the strips axis reproduces the
    aggregate row-weighted ``padding_waste_frac`` bit-for-bit), else
    the ``bucket_sweep`` events' ``waste_by_axis`` payloads.  The
    per-row distribution (mean/p95 of each row's own pad fraction)
    joins from the ``pad_waste_<axis>`` histograms when present."""
    counters = (snapshot or {}).get("counters") or {}
    hists = (snapshot or {}).get("histograms") or {}
    axes = waste_axes_from_counters(counters)
    if not axes:
        for e in events:
            if e["event"] != "bucket_sweep" or not e.get("waste_by_axis"):
                continue
            for axis, rec in e["waste_by_axis"].items():
                a = axes.setdefault(axis, {"valid": 0, "padded": 0})
                a["valid"] += int(rec.get("valid") or 0)
                a["padded"] += int(rec.get("padded") or 0)
        for a in axes.values():
            a["waste_frac"] = (round(1.0 - a["valid"] / a["padded"], 6)
                               if a["padded"] else 0.0)
    if not axes:
        return None
    for axis, a in axes.items():
        h = hists.get(f"pad_waste_{axis}") or {}
        if h.get("count"):
            a["rows"] = h["count"]
            a["row_mean"] = h.get("mean")
            a["row_p95"] = h.get("p95")
    return {"axes": axes}


def report_data(events, n_bad=0, source="<events>"):
    """Machine-readable report: every section of :func:`render_report`
    as one JSON-ready dict (``obs report --format json``; embedded
    verbatim in run records by ``obs runs record --events`` instead of
    anyone re-parsing rendered text)."""
    run_ids = sorted({e.get("run_id") for e in events if e.get("run_id")})
    # per-pid windows summed: `t` is monotonic per process, so a
    # resume-appended capture spans several clocks
    pids = {}
    for e in events:
        lo, hi = pids.get(e.get("pid") or 1, (e["t"], e["t"]))
        pids[e.get("pid") or 1] = (min(lo, e["t"]), max(hi, e["t"]))
    window = sum(hi - lo for lo, hi in pids.values())

    spans, unmatched = collect_spans(events)
    paths, fails = span_paths(spans)
    # plain tuple sort = depth-first tree order (a child path sorts
    # immediately after its parent prefix)
    span_rows = []
    for p in sorted(paths):
        walls = paths[p]
        span_rows.append({
            "path": list(p), "count": len(walls),
            "total_s": round(sum(walls), 6),
            "p50_s": _percentile(walls, 0.50),
            "p95_s": _percentile(walls, 0.95),
            "max_s": max(walls),
            "failed": fails.get(p, 0)})

    # legacy flat stage timings (structlog.stage emits the stage name
    # as the event, with wall_s)
    legacy = {}
    for e in events:
        if "wall_s" in e and e["event"] not in (
                "span_end", "shard_done", "sweep_done",
                "serve_request_stages"):
            legacy.setdefault(e["event"], []).append(e["wall_s"])
    stage_rows = [
        {"name": name, "count": len(walls),
         "total_s": round(sum(walls), 6),
         "p50_s": _percentile(walls, 0.50),
         "p95_s": _percentile(walls, 0.95), "max_s": max(walls)}
        for name, walls in sorted(legacy.items())]

    snaps = [e for e in events if e["event"] == "metrics_snapshot"]
    snapshot = snaps[-1].get("snapshot", {}) if snaps else {}

    # fabric per-worker table: every record a worker emits is stamped
    # worker=<id> (RAFT_TPU_WORKER_ID via structlog), so one shared
    # capture splits cleanly into per-worker shard/latency rows
    workers = {}
    for e in events:
        w = e.get("worker")
        if not w:
            continue
        rec = workers.setdefault(
            w, {"walls": [], "claims": 0, "steals": 0, "resumes": 0})
        if e["event"] == "shard_done":
            rec["walls"].append(e.get("wall_s") or 0.0)
        elif e["event"] == "shard_claim":
            rec["claims"] += 1
        elif e["event"] == "shard_steal":
            rec["steals"] += 1
        elif e["event"] == "shard_resume":
            rec["resumes"] += 1
    worker_rows = [
        {"worker": w, "shards": len(r["walls"]), "claims": r["claims"],
         "steals": r["steals"], "resumes": r["resumes"],
         "total_s": round(sum(r["walls"]), 6) if r["walls"] else None,
         "p50_s": _percentile(r["walls"], 0.50),
         "p95_s": _percentile(r["walls"], 0.95)}
        for w, r in sorted(workers.items())
        if r["claims"] or r["walls"]]

    # evaluation-service table: per-endpoint request/latency rows from
    # serve_request events, batch occupancy from serve_tick events
    endpoints = {}
    for e in events:
        if e["event"] != "serve_request":
            continue
        key = (str(e.get("endpoint") or "?"), int(e.get("code") or 0))
        rec = endpoints.setdefault(key, {"walls": [], "hits": 0})
        rec["walls"].append(e.get("wall_s") or 0.0)
        if e.get("cache_hit"):
            rec["hits"] += 1
    endpoint_rows = [
        {"endpoint": ep, "code": code, "requests": len(rec["walls"]),
         "cache_hits": rec["hits"],
         "p50_s": _percentile(rec["walls"], 0.50),
         "p95_s": _percentile(rec["walls"], 0.95),
         "max_s": max(rec["walls"])}
        for (ep, code), rec in sorted(endpoints.items())]
    # fleet-router table: per-(answering replica, code) routed-request
    # rows from router_request events, plus the failover-ladder
    # summary (retries, hedges, breaker transitions, evictions) — the
    # kill-a-replica drill reads its "zero dropped responses" story
    # from here.  One pass: the ladder counters ride the same loop.
    routed = {}
    _ROUTER_COUNT_EVENTS = ("router_retry", "router_hedge",
                            "router_reject", "breaker_open",
                            "breaker_close", "replica_join",
                            "replica_drain", "replica_evict",
                            "router_ring_update")
    router_counts = dict.fromkeys(_ROUTER_COUNT_EVENTS, 0)
    prov_by_design = {}
    for e in events:
        if e["event"] in router_counts:
            router_counts[e["event"]] += 1
            continue
        if e["event"] != "router_request":
            continue
        key = (str(e.get("replica") or "-"), int(e.get("code") or 0))
        rec = routed.setdefault(key, {"walls": [], "attempts": 0,
                                      "hedged": 0})
        rec["walls"].append(e.get("wall_s") or 0.0)
        rec["attempts"] += int(e.get("attempts") or 1)
        if e.get("hedged"):
            rec["hedged"] += 1
        # per-replica provenance stamps (x-raft-provenance forwarded by
        # the router): the consistency line below checks that replicas
        # serving the SAME design agree on bank sha + code hash
        if e.get("provenance") and e.get("replica"):
            prov_by_design.setdefault(
                str(e.get("design") or "?"), {})[
                str(e["replica"])] = e["provenance"]
    router_rows = [
        {"replica": rid, "code": code, "requests": len(rec["walls"]),
         "attempts": rec["attempts"], "hedged": rec["hedged"],
         "p50_s": _percentile(rec["walls"], 0.50),
         "p95_s": _percentile(rec["walls"], 0.95),
         "max_s": max(rec["walls"])}
        for (rid, code), rec in sorted(routed.items())]
    provenance = None
    if prov_by_design:
        from raft_tpu.obs.alerts import (parse_provenance,
                                         provenance_consistency)

        parsed = {d: {rid: parse_provenance(p) for rid, p in m.items()}
                  for d, m in prov_by_design.items()}
        provenance = provenance_consistency(parsed)
        provenance["replicas"] = sorted(
            {rid for m in parsed.values() for rid in m})
    router_summary = None
    if router_rows or any(router_counts.values()):
        router_summary = {"replicas": router_rows,
                          "provenance": provenance, **router_counts}

    # alerting + canary section: alert_fire/alert_resolve lifecycles
    # and canary probe outcomes from the capture (the active layer
    # PR 14 added over these signals)
    alert_rules: dict = {}
    canary_checks = []
    canary_goldens = 0
    for e in events:
        if e["event"] == "alert_fire":
            r = alert_rules.setdefault(
                str(e.get("rule") or "?"),
                {"severity": e.get("severity"), "fires": 0, "resolves": 0})
            r["fires"] += 1
        elif e["event"] == "alert_resolve":
            r = alert_rules.setdefault(
                str(e.get("rule") or "?"),
                {"severity": e.get("severity"), "fires": 0, "resolves": 0})
            r["resolves"] += 1
        elif e["event"] == "canary_check":
            canary_checks.append(e)
        elif e["event"] == "canary_golden":
            canary_goldens += 1
    alerts_summary = None
    if alert_rules or canary_checks or canary_goldens:
        alerts_summary = {
            "rules": {n: dict(r) for n, r in sorted(alert_rules.items())},
            "active_at_end": sorted(
                n for n, r in alert_rules.items()
                if r["fires"] > r["resolves"]),
            "canary": ({
                "goldens": canary_goldens,
                "checks": len(canary_checks),
                "failed": sum(1 for e in canary_checks if not e.get("ok")),
                "provenance_failures": sum(
                    1 for e in canary_checks
                    if e.get("provenance_ok") is False),
            } if (canary_checks or canary_goldens) else None),
        }

    ticks = [e for e in events if e["event"] == "serve_tick"]
    tick_summary = None
    if ticks:
        rows = [e.get("rows") or 0 for e in ticks]
        walls = [e.get("wall_s") or 0.0 for e in ticks]
        tick_summary = {
            "ticks": len(ticks), "requests": sum(rows),
            "unique_rows": sum(e.get("unique") or 0 for e in ticks),
            "dispatches": sum(e.get("dispatches") or 0 for e in ticks),
            "mean_batch": round(sum(rows) / len(ticks), 2),
            "p95_s": _percentile(walls, 0.95)}
        # cost-driven ladder refinement, when the capture recorded one
        ladder_evs = [e for e in events if e["event"] == "serve_ladder"]
        if ladder_evs:
            tick_summary["ladder"] = {
                "candidates": ladder_evs[-1].get("candidates"),
                "sizes": ladder_evs[-1].get("sizes")}

    # device-cost ledger: one row per banked/compiled program, joined
    # from program_cost (flops, at load/store) and program_dispatch
    # (wall + achieved rate, per execution).  The "effective" column
    # adjusts achieved GFLOP/s for padding waste — flops spent on
    # masked pad rows are real device work but not useful evals — using
    # the capture's mean batch occupancy (serve) or 1 - padding waste
    # (bucketed sweeps) when either is present.
    progs = {}
    for e in events:
        if e["event"] == "program_cost" and e.get("key"):
            rec = progs.setdefault(e["key"], {"dispatches": 0,
                                              "wall_s": 0.0})
            rec["kind"] = e.get("kind")
            if e.get("flops") is not None:
                rec["flops"] = e["flops"]
        elif e["event"] == "program_dispatch" and e.get("key"):
            rec = progs.setdefault(e["key"], {"dispatches": 0,
                                              "wall_s": 0.0})
            rec.setdefault("kind", e.get("kind"))
            rec["dispatches"] += 1
            rec["wall_s"] += e.get("wall_s") or 0.0
    occupancy = None
    ledger_rows = []
    if progs:
        occ = (snapshot.get("histograms", {})
               .get("serve_batch_occupancy") or {})
        occupancy = occ.get("mean")
        if occupancy is None:
            wastes = [e["padding_waste_frac"] for e in events
                      if e["event"] == "bucket_sweep"
                      and e.get("padding_waste_frac") is not None]
            if wastes:
                occupancy = 1.0 - sum(wastes) / len(wastes)
        for key in sorted(progs):
            rec = progs[key]
            flops = rec.get("flops")
            gflops = (flops * rec["dispatches"] / rec["wall_s"] / 1e9
                      if flops and rec["wall_s"] > 0 and rec["dispatches"]
                      else None)
            eff = (gflops * occupancy
                   if gflops is not None and occupancy is not None else None)
            ledger_rows.append({
                "key": key, "kind": rec.get("kind"), "flops": flops,
                "dispatches": rec["dispatches"],
                "gflops_s": round(gflops, 4) if gflops is not None else None,
                "effective_gflops_s": (round(eff, 4)
                                       if eff is not None else None)})

    counts = {}
    for e in events:
        counts[e["event"]] = counts.get(e["event"], 0) + 1

    # reliability summary: the "what fraction was retried/flagged/
    # escalated" question, straight from the event stream
    retries = [e for e in events if e["event"] == "shard_retry"]
    ooms = [e for e in events if e["event"] == "shard_oom_split"]
    quar = [e for e in events if e["event"] == "shard_quarantine"]
    esc = [e for e in events if e["event"] == "shard_escalate"]
    done = [e for e in events if e["event"] == "sweep_done"]
    reliability = None
    if retries or ooms or quar or esc or done:
        reasons = {}
        for e in quar:
            r = str(e.get("reason") or "?")
            reasons[r] = reasons.get(r, 0) + 1
        reliability = {
            "retries": len(retries),
            "retry_shards": sorted({e.get("shard") for e in retries}),
            "oom_splits": len(ooms),
            "quarantine_judgements": len(quar),
            "quarantine_recovered": sum(1 for e in quar
                                        if e.get("recovered")),
            "quarantine_reasons": reasons,
            "escalation_rungs": len(esc),
            "escalations_resolved": sum(1 for e in esc
                                        if e.get("resolved")),
            "sweeps_done": [
                {"n_cases": e.get("n_cases"),
                 "n_quarantined": e.get("n_quarantined"),
                 "n_flagged": e.get("n_flagged"),
                 "wall_s": e.get("wall_s")} for e in done]}

    return {
        "source": source,
        "meta": {"events": len(events), "bad_lines": n_bad,
                 "window_s": round(window, 6), "processes": len(pids),
                 "run_ids": run_ids},
        "spans": {"unmatched": len(unmatched), "paths": span_rows},
        "stages": stage_rows,
        "snapshot": snapshot,
        "workers": worker_rows,
        "serve": ({"endpoints": endpoint_rows, "ticks": tick_summary}
                  if endpoint_rows or ticks else None),
        "router": router_summary,
        "alerts": alerts_summary,
        "serve_stages": serve_stage_attribution(events),
        "cost_ledger": ({"occupancy": occupancy, "programs": ledger_rows}
                        if ledger_rows else None),
        "waste": waste_attribution(events, snapshot),
        "event_counts": counts,
        "reliability": reliability,
    }


def render_report(events, n_bad=0, source="<events>"):
    """Human-readable report (string) over one capture — the text
    rendering of :func:`report_data`."""
    data = report_data(events, n_bad, source)
    meta = data["meta"]
    out = []
    out.append(f"telemetry report — {source}")
    out.append(f"  {meta['events']} events"
               + (f" ({n_bad} unparseable lines skipped)" if n_bad else "")
               + f", window {meta['window_s']:.3f}s"
               + (f" across {meta['processes']} process(es)"
                  if meta["processes"] > 1 else "")
               + f", run_id(s): {', '.join(meta['run_ids']) or '—'}")

    span_rows = data["spans"]["paths"]
    unmatched = data["spans"]["unmatched"]
    if span_rows or unmatched:
        out.append("")
        out.append("span wall-time tree"
                   + (f"  [{unmatched} unmatched begin(s) — "
                      "process died mid-span]" if unmatched else ""))
        out.append(f"  {'':38s} {'count':>6s} {'total':>10s} "
                   f"{'p50':>10s} {'p95':>10s} {'max':>10s}")
        for r in span_rows:
            label = "  " * (len(r["path"]) - 1) + r["path"][-1]
            out.append(
                f"  {label:38s} {r['count']:6d} {_fmt_s(r['total_s'])} "
                f"{_fmt_s(r['p50_s'])} {_fmt_s(r['p95_s'])} "
                f"{_fmt_s(r['max_s'])}"
                + (f"   [{r['failed']} failed]" if r["failed"] else ""))

    if data["stages"]:
        out.append("")
        out.append("flat stage timings (structlog.stage)")
        for r in data["stages"]:
            out.append(
                f"  {r['name']:38s} {r['count']:6d} {_fmt_s(r['total_s'])} "
                f"{_fmt_s(r['p50_s'])} {_fmt_s(r['p95_s'])} "
                f"{_fmt_s(r['max_s'])}")

    snap = data["snapshot"]
    counters = snap.get("counters", {})
    if counters:
        out.append("")
        out.append("counters (final metrics snapshot)")
        for name, v in sorted(counters.items()):
            out.append(f"  {name:38s} {v}")
    gauges = snap.get("gauges", {})
    if gauges:
        out.append("")
        out.append("gauges (value / high watermark)")
        for name, g in sorted(gauges.items()):
            out.append(f"  {name:38s} {g.get('value')} / {g.get('max')}")
    hists = {k: h for k, h in snap.get("histograms", {}).items()
             if h.get("count")}
    if hists:
        out.append("")
        out.append("histograms (count / mean / p50 / p95 / max)")
        for name, h in sorted(hists.items()):
            out.append(
                f"  {name:38s} {h['count']:6d}  {h.get('mean')}  "
                f"{h.get('p50')}  {h.get('p95')}  {h.get('max')}")

    if data["workers"]:
        out.append("")
        out.append("fabric workers (shards / claims / steals / resumes / "
                   "total / p50 / p95)")
        for r in data["workers"]:
            out.append(
                f"  {r['worker']:20s} {r['shards']:6d} {r['claims']:6d} "
                f"{r['steals']:6d} {r['resumes']:7d} "
                f"{_fmt_s(r['total_s'])} "
                f"{_fmt_s(r['p50_s'])} "
                f"{_fmt_s(r['p95_s'])}")

    serve = data["serve"]
    if serve:
        out.append("")
        out.append("serve endpoints (endpoint / code / requests / "
                   "cache hits / p50 / p95 / max)")
        for r in serve["endpoints"]:
            out.append(
                f"  {r['endpoint']:24s} {r['code']:4d} {r['requests']:8d} "
                f"{r['cache_hits']:8d} "
                f"{_fmt_s(r['p50_s'])} "
                f"{_fmt_s(r['p95_s'])} "
                f"{_fmt_s(r['max_s'])}")
        t = serve["ticks"]
        if t:
            # occupancy vs the padded program sizes lives in the
            # serve_batch_occupancy histogram (metrics snapshot above);
            # this line is the tick-level view of the same batching
            out.append(
                f"  ticks: {t['ticks']} ({t['requests']} requests, "
                f"{t['unique_rows']} unique rows, "
                f"{t['dispatches']} dispatches; "
                f"mean batch {t['mean_batch']:.1f}, "
                f"tick p95 {t['p95_s']:.3f}s)")
            if t.get("ladder"):
                out.append(
                    f"  batch ladder: {t['ladder']['sizes']} "
                    f"(cost-pruned from {t['ladder']['candidates']})")

    router = data["router"]
    if router:
        out.append("")
        out.append("fleet router (replica / code / requests / attempts "
                   "/ hedged / p50 / p95 / max)")
        for r in router["replicas"]:
            out.append(
                f"  {r['replica']:20s} {r['code']:4d} {r['requests']:8d} "
                f"{r['attempts']:8d} {r['hedged']:6d} "
                f"{_fmt_s(r['p50_s'])} "
                f"{_fmt_s(r['p95_s'])} "
                f"{_fmt_s(r['max_s'])}")
        out.append(
            f"  ladder: {router['router_retry']} retries, "
            f"{router['router_hedge']} hedges, "
            f"{router['router_reject']} rejects; breakers "
            f"{router['breaker_open']} opened / "
            f"{router['breaker_close']} closed; membership "
            f"{router['replica_join']} joins / "
            f"{router['replica_drain']} drains / "
            f"{router['replica_evict']} evictions "
            f"({router['router_ring_update']} ring updates)")
        prov = router.get("provenance")
        if prov:
            if prov["consistent"]:
                out.append(
                    "  provenance: consistent — bank sha + code hash "
                    f"agree across {len(prov['replicas'])} replica(s)")
            else:
                out.append("  provenance: INCONSISTENT —")
                for s in prov["splits"]:
                    out.append(
                        f"    design {s['design']}: {s['field']} "
                        + "  ".join(f"{rid}={v}"
                                    for rid, v in s["values"].items()))

    alerts_summary = data["alerts"]
    if alerts_summary:
        out.append("")
        out.append("alerts & canaries (rule / severity / fires / "
                   "resolves)")
        for name, r in alerts_summary["rules"].items():
            out.append(f"  {name:32s} {str(r.get('severity') or '?'):10s} "
                       f"{r['fires']:6d} {r['resolves']:8d}")
        if alerts_summary["active_at_end"]:
            out.append("  STILL FIRING at capture end: "
                       + ", ".join(alerts_summary["active_at_end"]))
        c = alerts_summary["canary"]
        if c:
            out.append(
                f"  canary: {c['goldens']} golden(s), {c['checks']} "
                f"check(s), {c['failed']} failed "
                f"({c['provenance_failures']} provenance split(s))")

    attrib = data["serve_stages"]
    if attrib:
        out.append("")
        out.append(f"serve tail attribution ({attrib['n_requests']} "
                   "dispatched requests; p50/p95 columns are the stage "
                   "breakdown of the request at that latency rank)")
        out.append(f"  {'stage':24s} {'p50':>10s} {'p95':>10s} "
                   f"{'mean':>10s}")
        for stage in SERVE_STAGES:
            out.append(
                f"  {stage:24s} "
                f"{_fmt_s(attrib['p50']['stages'].get(stage))} "
                f"{_fmt_s(attrib['p95']['stages'].get(stage))} "
                f"{_fmt_s(attrib['mean']['stages'].get(stage))}")
        out.append(
            f"  {'total (measured)':24s} "
            f"{_fmt_s(attrib['p50']['total_s'])} "
            f"{_fmt_s(attrib['p95']['total_s'])} "
            f"{_fmt_s(attrib['mean']['total_s'])}")

    waste = data["waste"]
    if waste:
        out.append("")
        out.append("padding waste by axis (valid / padded / waste "
                   "/ row mean / row p95)")
        for axis, a in sorted(waste["axes"].items()):
            out.append(
                f"  {axis:16s} {a['valid']:10d} {a['padded']:10d} "
                f"{a['waste_frac']:8.4f}"
                + (f" {a['row_mean']:9.4f}" if a.get("row_mean") is not None
                   else "         —")
                + (f" {a['row_p95']:9.4f}" if a.get("row_p95") is not None
                   else "         —"))

    ledger = data["cost_ledger"]
    if ledger:
        out.append("")
        out.append("program cost ledger (key / kind / GFLOP / dispatches "
                   "/ achieved GFLOP/s / effective)")
        for r in ledger["programs"]:
            flops = r["flops"]
            out.append(
                f"  {r['key']:26s} {str(r.get('kind') or '?'):12s} "
                + (f"{flops / 1e9:10.3f}" if flops else "         —")
                + f" {r['dispatches']:6d} "
                + (f"{r['gflops_s']:10.2f}" if r["gflops_s"] is not None
                   else "         —")
                + (f" {r['effective_gflops_s']:10.2f}"
                   if r["effective_gflops_s"] is not None else "          —"))
        if ledger["occupancy"] is not None:
            out.append(f"  (effective = achieved x mean batch occupancy "
                       f"{ledger['occupancy']:.3f})")

    out.append("")
    out.append("event counts")
    for name, n in sorted(data["event_counts"].items(),
                          key=lambda kv: (-kv[1], kv[0])):
        out.append(f"  {name:38s} {n:6d}")

    rel = data["reliability"]
    if rel:
        out.append("")
        out.append("reliability summary")
        if rel["retries"]:
            out.append(f"  retries: {rel['retries']} "
                       f"(shards {rel['retry_shards']})")
        if rel["oom_splits"]:
            out.append(f"  oom splits: {rel['oom_splits']}")
        if rel["quarantine_judgements"]:
            nq, nr = rel["quarantine_judgements"], rel["quarantine_recovered"]
            out.append(f"  quarantine judgements: {nq} "
                       f"({nr} recovered, {nq - nr} kept bad)")
            for r, n in sorted(rel["quarantine_reasons"].items(),
                               key=lambda kv: -kv[1]):
                out.append(f"    reason {r}: {n}")
        if rel["escalation_rungs"]:
            out.append(f"  escalation rungs: {rel['escalation_rungs']} "
                       f"({rel['escalations_resolved']} resolved)")
        for e in rel["sweeps_done"]:
            out.append(
                f"  sweep_done: {e.get('n_cases')} cases, "
                f"{e.get('n_quarantined')} quarantined, "
                f"{e.get('n_flagged')} flagged, wall {e.get('wall_s')}s")
    return "\n".join(out) + "\n"


# ------------------------------------------------------------- tail view


def tail_view(events, rank=0.95):
    """"The actual p99 request": the single served request at latency
    rank ``rank``, reconstructed from a capture (``obs report --tail``).

    Ranks the ``serve_request_stages`` events by measured wall, picks
    the one at ``rank``, joins the ``exemplar_recorded`` attrs stamped
    by the batcher (design content hash, bucket signature, dispatched
    rows, cache-hit bit, int32 status word, replica id) on ``span_id``,
    and pulls the request's full span tree out of the capture by
    ``trace_id`` — so the tail is a concrete request with an identity
    and a timeline, not a percentile in a histogram.  Returns None when
    the capture has no stage events."""
    reqs = [e for e in events if e["event"] == "serve_request_stages"]
    if not reqs:
        return None
    reqs.sort(key=lambda e: float(e.get("wall_s") or 0.0))
    i = min(len(reqs) - 1, max(0, round(rank * (len(reqs) - 1))))
    e = reqs[i]
    stages = {s: float(e.get(f"{s}_s") or 0.0) for s in SERVE_STAGES}
    exemplar = None
    if e.get("span_id"):
        for x in events:
            if x["event"] == "exemplar_recorded" \
                    and x.get("span_id") == e["span_id"]:
                exemplar = {k: v for k, v in x.items()
                            if k not in ("t", "event", "pid", "run_id",
                                         "metric", "value")}
                exemplar["metric"] = x.get("metric")
                break
    tree = []
    if e.get("trace_id"):
        spans, _unmatched = collect_spans(events)
        trace_spans = [s for s in spans if s["trace_id"] == e["trace_id"]]
        by_parent: dict = {}
        for s in trace_spans:
            by_parent.setdefault(s["parent_id"], []).append(s)
        ids = {s["span_id"] for s in trace_spans}
        roots = sorted((s for s in trace_spans
                        if s["parent_id"] not in ids),
                       key=lambda s: s["t0"])

        def walk(s, depth):
            tree.append({"name": s["name"], "depth": depth,
                         "t0": s["t0"], "wall_s": s["wall_s"],
                         "ok": s.get("ok", True),
                         "span_id": s["span_id"],
                         "attrs": s["attrs"]})
            for c in sorted(by_parent.get(s["span_id"], []),
                            key=lambda c: c["t0"]):
                walk(c, depth + 1)

        for r in roots:
            walk(r, 0)
    return {
        "rank": rank,
        "n_requests": len(reqs),
        "wall_s": round(float(e.get("wall_s") or 0.0), 6),
        "stages": {k: round(v, 6) for k, v in stages.items()},
        "stages_sum_s": round(sum(stages.values()), 6),
        "trace_id": e.get("trace_id"),
        "span_id": e.get("span_id"),
        "escalated": bool(e.get("escalated")),
        "exemplar": exemplar,
        "spans": tree,
    }


def render_tail(events, rank=0.95, source="<events>"):
    """Text rendering of :func:`tail_view` (``obs report --tail``)."""
    data = tail_view(events, rank)
    out = [f"tail exemplar — {source}"]
    if data is None:
        out.append("  no serve_request_stages events in this capture "
                   "(was a server handling dispatched requests?)")
        return "\n".join(out) + "\n"
    out.append(
        f"  the p{int(round(rank * 100))} request of "
        f"{data['n_requests']} dispatched: wall {data['wall_s']:.6f}s"
        + ("  [escalated]" if data["escalated"] else ""))
    ex = data["exemplar"]
    if ex:
        parts = [f"{k}={ex[k]}" for k in
                 ("design", "sig", "rows", "cache_hit", "status",
                  "replica") if ex.get(k) is not None]
        out.append("  identity: " + (", ".join(parts) or "—")
                   + (f"  (exemplar of {ex.get('metric')})"
                      if ex.get("metric") else ""))
    if data["trace_id"]:
        out.append(f"  trace {data['trace_id']}  span {data['span_id']}")
    out.append("")
    out.append(f"  {'stage':24s} {'wall':>10s}")
    for stage in SERVE_STAGES:
        out.append(f"  {stage:24s} {_fmt_s(data['stages'].get(stage))}")
    out.append(f"  {'sum of stages':24s} {_fmt_s(data['stages_sum_s'])}")
    out.append(f"  {'total (measured)':24s} {_fmt_s(data['wall_s'])}")
    if data["spans"]:
        out.append("")
        out.append("  span tree of this request's trace")
        for s in data["spans"]:
            label = "  " * s["depth"] + s["name"]
            attrs = ", ".join(f"{k}={v}" for k, v in sorted(
                s["attrs"].items()) if k not in ("remote_parent",
                                                 "boundary"))
            out.append(f"    {label:36s} {_fmt_s(s['wall_s'])}"
                       + (f"  [{attrs}]" if attrs else "")
                       + ("" if s["ok"] else "  FAILED"))
    elif data["span_id"]:
        out.append("")
        out.append("  (no spans for this trace in the capture — span "
                   "records need RAFT_TPU_LOG or a merged flight shard)")
    return "\n".join(out) + "\n"


# ----------------------------------------------------------- chrome trace


def _pid_time_offsets(events):
    """Per-pid timestamp offsets: ``t`` is monotonic within ONE
    process, so a capture appended across a resume (pinned
    ``RAFT_TPU_RUN_ID``) holds several pids whose clocks all start
    near zero.  Lay the processes out sequentially in file order (the
    real-world ordering of an append-mode capture) with a 1 ms gap."""
    bounds, order = {}, []
    for ev in events:
        pid = ev.get("pid") or 1
        b = bounds.get(pid)
        if b is None:
            bounds[pid] = [ev["t"], ev["t"]]
            order.append(pid)
        else:
            b[0] = min(b[0], ev["t"])
            b[1] = max(b[1], ev["t"])
    offsets, cursor = {}, 0.0
    for pid in order:
        lo, hi = bounds[pid]
        offsets[pid] = cursor - lo
        cursor += (hi - lo) + 1e-3
    return offsets


def chrome_trace(events, merged=False):
    """Chrome trace-event JSON (dict with ``traceEvents``) from one
    capture: matched spans as complete "X" slices, other events as
    instants, heartbeat memory samples as counter tracks.  Multi-pid
    captures (resume appends) render sequentially, one process track
    after the other — EXCEPT under ``merged=True``
    (:func:`merge_captures` already normalized every process onto one
    wall clock, so timestamps are used as-is and concurrent processes
    genuinely overlap on the timeline)."""
    spans, unmatched = collect_spans(events)
    offsets = {} if merged else _pid_time_offsets(events)
    tids = {}

    def tid_for(trace_id):
        if trace_id not in tids:
            tids[trace_id] = len(tids) + 1
        return tids[trace_id]

    def ts_of(t, pid):
        return round((t + offsets.get(pid or 1, 0.0)) * 1e6, 1)

    trace = []
    span_ids = set()
    for s in spans:
        span_ids.add(s["span_id"])
        args = dict(s["attrs"])
        args["span_id"] = s["span_id"]
        if s["parent_id"]:
            args["parent_id"] = s["parent_id"]
        if s["error"]:
            args["error"] = s["error"]
        trace.append({
            "name": s["name"], "cat": "span", "ph": "X",
            "ts": ts_of(s["t0"], s.get("pid")),
            "dur": round(max(s["t1"] - s["t0"], s["wall_s"] or 0.0) * 1e6, 1),
            "pid": s.get("pid") or 1,
            "tid": tid_for(s.get("trace_id")),
            "args": args,
        })
    for ev in events:
        kind = ev["event"]
        if kind in ("span_begin", "span_end"):
            continue
        pid = ev.get("pid") or 1
        tid = tid_for(ev.get("trace_id")) if ev.get("trace_id") else 0
        ts = ts_of(ev["t"], pid)
        if kind == "heartbeat":
            for d in ev.get("devices") or []:
                if "bytes_in_use" in d:
                    trace.append({
                        "name": f"device{d.get('id')} memory", "ph": "C",
                        "ts": ts, "pid": pid, "tid": 0,
                        "args": {"bytes_in_use": d["bytes_in_use"]}})
            if ev.get("live_arrays") is not None:
                trace.append({
                    "name": "live_arrays", "ph": "C", "ts": ts,
                    "pid": pid, "tid": 0,
                    "args": {"count": ev["live_arrays"]}})
            continue
        args = {k: v for k, v in ev.items()
                if k not in ("t", "event", "pid", "run_id",
                             "trace_id", "span_id")}
        trace.append({"name": kind, "cat": "event", "ph": "i", "s": "p",
                      "ts": ts, "pid": pid, "tid": tid, "args": args})
    # orphans: spans whose parent_id resolves to no span in the capture
    # — in a properly-propagated multi-process merge every worker root
    # chains to the coordinator's sweep span and every serve dispatch
    # to its tick, so the merged count must be 0 (the acceptance gate
    # `obs trace --merge --check` enforces).  Exceptions, both for
    # parents that legitimately live in an EXTERNAL tracer's telemetry:
    # a remote_parent span in a trace no other captured process
    # contributed to (a traced HTTP client hitting one server), and a
    # span stamped boundary="client" (the fleet router adopting a
    # client traceparent — its replicas' spans share the trace, but the
    # parent is still the client's).  Internally-propagated parents
    # (fabric coordinator -> workers, router -> replicas) get no
    # excuse: they must resolve in-capture.
    ids = {s["span_id"] for s in spans} | {b.get("span_id")
                                           for b in unmatched}
    pids_by_trace: dict = {}
    for s in spans:
        pids_by_trace.setdefault(s["trace_id"], set()).add(s.get("pid"))
    orphans = []
    for s in spans:
        if not s["parent_id"] or s["parent_id"] in ids:
            continue
        if s["attrs"].get("remote_parent") and (
                s["attrs"].get("boundary") == "client"
                or len(pids_by_trace.get(s["trace_id"], ())) <= 1):
            continue
        orphans.append(s)
    meta = {"spans_matched": len(spans),
            "spans_unmatched": len(unmatched),
            "spans_orphaned": len(orphans),
            "traces": len({s["trace_id"] for s in spans if s["trace_id"]}),
            "pids": len({e.get("pid") or 1 for e in events}),
            "run_ids": sorted({e.get("run_id") for e in events
                               if e.get("run_id")})}
    return {"traceEvents": trace, "displayTimeUnit": "ms",
            "otherData": meta}
