"""Concurrency-invariant analyzer for the multi-process runtime.

PRs 8-11 turned raft_tpu into a system of cooperating processes and
daemon threads whose correctness hangs on hand-maintained idioms: every
ledger/run-store/bank mutation must be an atomic filesystem operation,
the serve event loop must never block, the shared registries are only
safe under their locks, and every background thread needs a shutdown
path.  None of those invariants crash when violated — they corrupt
concurrent readers, stall the event loop, or tear a dict under a racing
thread, usually only under load.  This module makes them lintable.

Four rules, applied to the declared shared-state modules
(:data:`SHARED_STATE_MODULES`; ``async-blocking`` scans the
:data:`ASYNC_MODULES` event-loop code):

``atomic-write``
    A write-mode ``open()`` / ``os.fdopen()`` / ``np.save*`` landing in
    a ledger/out-dir/store path without the atomic idioms the
    checkpoint layer trusts: tmp + ``os.replace`` (the enclosing
    function must perform the replace), an ``O_CREAT|O_EXCL`` claim, or
    delegation to a sanctioned atomic writer
    (:data:`SANCTIONED_WRITERS`).  A torn plain write is silent data
    loss for every concurrent reader (``runs list``/``regress``,
    fabric lease scans, bank loads).  Append-mode sinks (worker logs,
    the structlog JSONL stream) are exempt: appends of one line are the
    audited exception.

``async-blocking``
    A blocking operation reachable from an ``async def`` in the serve
    event loop: ``time.sleep``, blocking file IO (``open``/
    ``os.fdopen``), ``subprocess``, ``Future.result()`` /
    ``Thread.join()``, or a lock ``.acquire()`` without a timeout.
    The check is taint-based: a package-internal call graph is built
    over the whole scan set and blocking-ness propagates through sync
    helpers, so ``shutdown() -> metrics.export() -> open()`` is caught
    even though ``shutdown`` itself never names ``open``.  Calls
    handed to ``run_in_executor`` (as arguments, not performed) are
    naturally exempt; :mod:`raft_tpu.utils.structlog` is allowlisted
    (bounded single-line append+flush under a lock — the audited
    telemetry exception, see :data:`NONBLOCKING_MODULES`).

``lock-discipline``
    A mutation of declared lock-guarded state lexically outside a
    ``with <lock>:`` block.  State declares its lock inline::

        _REGISTRY = {}  # raft-lint: guarded-by=_REGISTRY_LOCK
        self._entries = OrderedDict()  # raft-lint: guarded-by=self._lock

    and every assignment / augmented assignment / item-write / mutating
    method call (``append``/``pop``/``update``/...) on that name must
    then sit inside ``with <that lock>:``.  The annotation's own
    function (the constructor) and module-level initial bindings are
    exempt — state is not shared before it exists.  Reads are not
    checked (the registries deliberately allow brief stale reads).

``thread-hygiene``
    Every ``threading.Thread`` must be ``daemon=True`` (a forgotten
    non-daemon sampler wedges interpreter shutdown), carry a ``name``
    (an anonymous ``Thread-3`` in a hang dump is useless), and have a
    stop/join path: a ``Thread`` subclass must define a ``stop``/
    ``close``/``shutdown`` method that ``join``\\ s, and a plain
    ``Thread(target=...)`` construction must have a ``.join(`` call on
    its binding somewhere in the module.

Suppression uses the shared ``# raft-lint: disable=<rule>`` syntax
(:mod:`raft_tpu.analysis.lint`).  Pure stdlib ``ast`` — no jax import,
CI-safe.  Run ``python -m raft_tpu.analysis concurrency``.
"""

from __future__ import annotations

import ast
import os
import re

from raft_tpu.analysis.lint import (Finding, _Suppressions, _attr_root,
                                    default_paths, repo_root)

RULES = {
    "atomic-write": "non-atomic write into a shared ledger/store path",
    "async-blocking": "blocking call reachable from the serve event loop",
    "lock-discipline": "guarded state mutated outside its lock",
    "thread-hygiene": "thread without daemon/name/stop-join hygiene",
}

#: modules whose on-disk state is read concurrently by other processes
#: (ledgers, stores, banks) or mutated by daemon threads (registries,
#: sinks): atomic-write + lock-discipline + thread-hygiene apply here.
#: Paths are repo-relative '/'-separated prefixes, like
#: ``lint.TRACED_MODULES``.
SHARED_STATE_MODULES = (
    "raft_tpu/parallel/fabric.py",
    "raft_tpu/parallel/resilience.py",
    "raft_tpu/obs/runs.py",
    "raft_tpu/obs/metrics.py",
    "raft_tpu/obs/heartbeat.py",
    "raft_tpu/obs/alerts.py",
    "raft_tpu/aot/bank.py",
    "raft_tpu/serve/",
    "raft_tpu/utils/structlog.py",
)

#: modules whose ``async def`` functions run on the serve event loop
ASYNC_MODULES = ("raft_tpu/serve/",)

#: atomic-writer helpers: a write op inside an argument to (or the body
#: of) one of these is the sanctioned idiom, not a finding
SANCTIONED_WRITERS = frozenset(
    {"_atomic_write", "_atomic_json", "atomic_savez"})

#: modules whose functions are never treated as blocking for the
#: async-blocking taint: structlog's sink is a bounded single-line
#: append+flush under a lock (and the lazy sink open happens once) —
#: the audited telemetry exception every async handler relies on.
NONBLOCKING_MODULES = ("raft_tpu/utils/structlog.py",)

#: method names that mutate their receiver (dict/list/set/deque/
#: OrderedDict) for the lock-discipline rule
_MUTATORS = frozenset({
    "append", "appendleft", "add", "clear", "extend", "insert",
    "pop", "popitem", "popleft", "remove", "discard", "update",
    "setdefault", "sort", "move_to_end",
})

_GUARD_RE = re.compile(
    r"#\s*raft-lint:\s*guarded-by\s*=\s*(?P<lock>[A-Za-z_][\w.]*)")


def _unparse(node):
    try:
        return ast.unparse(node).strip()
    except Exception:  # very old ast nodes / synthetic trees
        return ""


def _in_modules(display_path, prefixes):
    norm = display_path.replace(os.sep, "/")
    return any(norm.startswith(p) or norm.endswith(p) for p in prefixes)


# ===================================================================== files


class _Func:
    """One function's concurrency-relevant facts (call graph node)."""

    __slots__ = ("module", "qualname", "node", "is_async", "lineno",
                 "calls", "primitives")

    def __init__(self, module, qualname, node):
        self.module = module
        self.qualname = qualname
        self.node = node
        self.is_async = isinstance(node, ast.AsyncFunctionDef)
        self.lineno = node.lineno
        #: [(lineno, target)] where target is ``(module, name)`` for a
        #: resolved package function, else None (unresolvable)
        self.calls = []
        #: [(lineno, description)] of directly-blocking operations
        self.primitives = []


class _ModuleInfo:
    """Parsed view of one file: functions, imports, classes, guards."""

    def __init__(self, path, display, source):
        self.path = path
        self.display = display.replace(os.sep, "/")
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.suppress = _Suppressions(source)
        #: alias -> module display path ("metrics" -> ".../metrics.py")
        self.module_aliases = {}
        #: alias -> (module display path, function name)
        self.func_aliases = {}
        #: qualname -> _Func
        self.functions = {}
        #: class name -> ClassDef
        self.classes = {}
        #: guarded state: name -> lock  (module scope) and
        #: (class, attr) -> lock  (instance scope)
        self.module_guards = {}
        self.instance_guards = {}
        self._collect_imports()
        self._collect_functions()
        _parse_guards(self)  # guarded-by annotations (lock-discipline)

    # ------------------------------------------------------------- imports

    @staticmethod
    def _module_display(dotted):
        if not dotted.startswith("raft_tpu"):
            return None
        return dotted.replace(".", "/") + ".py"

    def _collect_imports(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    disp = self._module_display(alias.name)
                    if disp:
                        self.module_aliases[
                            alias.asname or alias.name.split(".")[0]] = disp
            elif isinstance(node, ast.ImportFrom) and node.module:
                parent = self._module_display(node.module)
                for alias in node.names:
                    child = self._module_display(
                        node.module + "." + alias.name)
                    name = alias.asname or alias.name
                    # `from raft_tpu.obs import metrics` imports a
                    # MODULE; `from ...structlog import log_event`
                    # imports a function — disambiguated later against
                    # the parsed module set (both recorded here)
                    if child:
                        self.module_aliases.setdefault(name, child)
                    if parent:
                        self.func_aliases.setdefault(name, (parent,
                                                            alias.name))

    # ----------------------------------------------------------- functions

    def _collect_functions(self):
        pending = []  # register every def first: bare-name resolution
                      # must see functions defined later in the file

        def walk(node, prefix, class_name):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    qual = (prefix + "." if prefix else "") + child.name
                    fn = _Func(self.display, qual, child)
                    self.functions[qual] = fn
                    pending.append((fn, child, class_name))
                    walk(child, qual, class_name)
                elif isinstance(child, ast.ClassDef):
                    self.classes[child.name] = child
                    walk(child, child.name, child.name)
                else:
                    walk(child, prefix, class_name)

        walk(self.tree, "", None)
        for fn, node, class_name in pending:
            self._scan_body(fn, node, class_name)

    def _scan_body(self, fn, node, class_name):
        """Record calls + blocking primitives of ONE function body,
        without descending into nested defs/lambdas (they are separate
        scopes — passing a function is not calling it)."""
        def visit(n):
            for child in ast.iter_child_nodes(n):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue
                if isinstance(child, ast.Call):
                    self._record_call(fn, child, class_name)
                visit(child)

        visit(node)

    def _record_call(self, fn, call, class_name):
        f = call.func
        line = call.lineno
        prim = _blocking_primitive(call)
        if prim:
            fn.primitives.append((line, prim))
            return
        target = None
        if isinstance(f, ast.Name):
            # bare name: locally-defined function/class, else an import
            if f.id in self.functions or f.id in self.classes:
                target = (self.display, f.id)
            elif f.id in self.func_aliases:
                target = self.func_aliases[f.id]
        elif isinstance(f, ast.Attribute):
            v = f.value
            if isinstance(v, ast.Name):
                if v.id == "self" and class_name:
                    qual = f"{class_name}.{f.attr}"
                    if qual in self.functions:
                        target = (self.display, qual)
                elif v.id in self.module_aliases:
                    target = (self.module_aliases[v.id], f.attr)
        fn.calls.append((line, target))


def _blocking_primitive(call):
    """Description of a directly-blocking operation, or None.

    The event loop's own awaitables (``asyncio.sleep``, stream reads,
    executor dispatch) never match: only host-thread blockers do."""
    f = call.func
    if isinstance(f, ast.Name):
        if f.id == "open":
            return "open() — blocking file IO"
        return None
    if not isinstance(f, ast.Attribute):
        return None
    root = _attr_root(f)
    if root == "time" and f.attr == "sleep":
        return "time.sleep()"
    if root == "subprocess":
        return f"subprocess.{f.attr}()"
    if root == "os" and f.attr in ("fdopen", "system", "popen"):
        return f"os.{f.attr}() — blocking file IO"
    if f.attr == "result" and not call.args and not call.keywords:
        return ".result() — blocks until the future resolves"
    if f.attr == "acquire":
        bounded = any(kw.arg == "timeout" for kw in call.keywords) or \
            len(call.args) >= 2 or (
                call.args and isinstance(call.args[0], ast.Constant)
                and call.args[0].value is False)  # acquire(False): no wait
        if not bounded:
            return ".acquire() without timeout"
        return None
    if f.attr == "join":
        # distinguish Thread.join from str.join: a literal-str receiver
        # or a non-timeout argument (an iterable being joined) is
        # string work, not a blocking wait
        if isinstance(f.value, ast.Constant):
            return None
        if call.keywords and not any(kw.arg == "timeout"
                                     for kw in call.keywords):
            return None
        if call.args:
            a = call.args[0]
            timeoutish = (isinstance(a, ast.Constant)
                          and isinstance(a.value, (int, float))) or \
                (isinstance(a, (ast.Name, ast.Attribute))
                 and "timeout" in _unparse(a))
            if not timeoutish:
                return None
        return ".join() — blocks until the thread/process exits"
    return None


# ============================================================ blocking taint


def _propagate_blocking(modules):
    """Fixpoint: qualify every package function as blocking when it
    contains a blocking primitive or calls a blocking *sync* package
    function.  Returns ``{(module, qualname): witness}`` where witness
    is the human-readable chain to the primitive.

    Async callees never taint their callers: an async function that
    blocks is its own finding (awaiting it is not what blocks the
    loop — its body is)."""
    funcs = {}
    for m in modules.values():
        for fn in m.functions.values():
            funcs[(fn.module, fn.qualname)] = fn
    blocking = {}
    for key, fn in funcs.items():
        if fn.module in NONBLOCKING_MODULES:
            continue
        if fn.primitives:
            line, prim = fn.primitives[0]
            blocking[key] = f"{prim} ({fn.module}:{line})"
    changed = True
    while changed:
        changed = False
        for key, fn in funcs.items():
            if key in blocking or fn.module in NONBLOCKING_MODULES:
                continue
            for line, target in fn.calls:
                if target is None:
                    continue
                tgt = _resolve_target(funcs, modules, target)
                if tgt is None or tgt not in blocking:
                    continue
                if funcs[tgt].is_async:
                    continue  # awaited coroutine: reported at itself
                blocking[key] = (f"calls {tgt[0]}::{funcs[tgt].qualname} "
                                 f"-> {blocking[tgt]}")
                changed = True
                break
    return blocking, funcs


def _resolve_target(funcs, modules, target):
    """(module, name) -> the function-registry key, following class
    constructors to ``__init__``; None when the name is not a parsed
    package function."""
    module, name = target
    if (module, name) in funcs:
        return (module, name)
    m = modules.get(module)
    if m is not None and name in m.classes:
        init = f"{name}.__init__"
        if (module, init) in funcs:
            return (module, init)
    return None


# ================================================================= checks


class _FileChecker:
    """Per-file rule application (atomic-write, lock-discipline,
    thread-hygiene, and the per-async-function half of
    async-blocking)."""

    def __init__(self, info, rules, blocking=None, funcs=None,
                 modules=None, force=False):
        self.info = info
        self.rules = rules
        self.blocking = blocking or {}
        self.funcs = funcs or {}
        self.modules = modules or {}
        #: fixture mode: apply every rule regardless of the module sets
        self.force = force
        self.findings = []

    def _emit(self, rule, node, message):
        if rule not in self.rules:
            return
        if self.info.suppress.active(rule, node.lineno):
            return
        self.findings.append(Finding(
            self.info.display, node.lineno, node.col_offset + 1, rule,
            message))

    def run(self):
        self._check_atomic_writes()
        self._check_lock_discipline()
        self._check_thread_hygiene()
        self._check_async_blocking()
        return self.findings

    # --------------------------------------------------------- atomic-write

    def _check_atomic_writes(self):
        if "atomic-write" not in self.rules:
            return
        for fn in self.info.functions.values():
            self._atomic_in_scope(fn.node, fn.node.name)
        # module-level statements (rare, but a top-level open("w")
        # would otherwise be invisible)
        self._atomic_in_scope(self.info.tree, None, top_level=True)

    def _atomic_in_scope(self, scope, fname, top_level=False):
        if fname in SANCTIONED_WRITERS:
            return  # the atomic-writer helper IS the idiom
        writes = []

        def visit(n, in_sanctioned_arg):
            for child in ast.iter_child_nodes(n):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.ClassDef)):
                    continue  # nested scopes are checked on their own
                if isinstance(child, ast.Call):
                    self._note_write(child, writes, in_sanctioned_arg)
                    if self._is_sanctioned_writer_call(child):
                        # everything inside this call's arguments (the
                        # writer lambda) IS the atomic idiom
                        for a in list(child.args) + \
                                [kw.value for kw in child.keywords]:
                            visit(a, True)
                        continue
                visit(child, in_sanctioned_arg)

        visit(scope, False)
        if not writes:
            return
        if not top_level:
            # the idiom markers must live in THIS function's own body —
            # the same scope the writes were collected from.  Walking
            # into nested defs (or matching "O_EXCL" as a source
            # substring, where a comment counts) would let an unrelated
            # atomic helper excuse a torn write beside it.
            def scope_nodes(n):
                for child in ast.iter_child_nodes(n):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.ClassDef)):
                        continue
                    yield child
                    yield from scope_nodes(child)

            has_replace = has_excl = False
            for n in scope_nodes(scope):
                if isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Attribute) \
                        and n.func.attr in ("replace", "rename") \
                        and _attr_root(n.func) == "os":
                    has_replace = True
                elif isinstance(n, ast.Attribute) and n.attr == "O_EXCL":
                    has_excl = True
            if has_replace or has_excl:
                return
        for node, what in writes:
            self._emit(
                "atomic-write", node,
                f"{what} into a shared-state module without the atomic "
                "idiom (tmp + os.replace in this function, an "
                "O_CREAT|O_EXCL claim, or one of "
                f"{sorted(SANCTIONED_WRITERS)}) — a torn write is "
                "silent corruption for every concurrent reader")

    @staticmethod
    def _is_sanctioned_writer_call(call):
        f = call.func
        name = f.id if isinstance(f, ast.Name) else (
            f.attr if isinstance(f, ast.Attribute) else None)
        return name in SANCTIONED_WRITERS

    @staticmethod
    def _write_mode(call, arg_index):
        if len(call.args) > arg_index:
            m = call.args[arg_index]
            if isinstance(m, ast.Constant) and isinstance(m.value, str):
                return m.value
        for kw in call.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                return kw.value.value
        return None

    def _note_write(self, call, writes, in_sanctioned_arg):
        if in_sanctioned_arg:
            return
        f = call.func
        if isinstance(f, ast.Name) and f.id == "open":
            mode = self._write_mode(call, 1) or "r"
            if any(c in mode for c in "wx+"):
                writes.append((call, f"open(..., {mode!r})"))
        elif isinstance(f, ast.Attribute):
            root = _attr_root(f)
            if root == "os" and f.attr == "fdopen":
                mode = self._write_mode(call, 1) or "r"
                if any(c in mode for c in "wx+"):
                    writes.append((call, f"os.fdopen(..., {mode!r})"))
            elif root in ("np", "numpy") and f.attr in (
                    "save", "savez", "savez_compressed", "savetxt"):
                writes.append((call, f"np.{f.attr}(...)"))

    # ----------------------------------------------------- lock-discipline

    def _check_lock_discipline(self):
        if "lock-discipline" not in self.rules:
            return
        if not self.info.module_guards and not self.info.instance_guards:
            return

        def walk(node, locks, class_name, func_node):
            for child in ast.iter_child_nodes(node):
                child_locks = locks
                child_class = class_name
                child_func = func_node
                if isinstance(child, ast.ClassDef):
                    child_class = child.name
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef, ast.Lambda)):
                    child_func = child
                elif isinstance(child, (ast.With, ast.AsyncWith)):
                    held = {_unparse(item.context_expr)
                            for item in child.items}
                    child_locks = locks | held
                self._lock_check_node(child, locks, class_name, func_node)
                walk(child, child_locks, child_class, child_func)

        walk(self.info.tree, frozenset(), None, None)

    def _guard_for(self, expr, class_name):
        """``(lock, display name, declaration line)`` of the guarded
        state ``expr`` mutates, or ``(None, None, None)``."""
        if isinstance(expr, ast.Name):
            lock, line = self.info.module_guards.get(expr.id, (None, None))
            return lock, expr.id, line
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and class_name:
            lock, line = self.info.instance_guards.get(
                (class_name, expr.attr), (None, None))
            return lock, f"self.{expr.attr}", line
        return None, None, None

    def _state_expr(self, node, class_name):
        """Resolve a mutation target down to its guarded base: a bare
        name / self-attr, or the base of (possibly nested) subscripts
        on one."""
        while isinstance(node, ast.Subscript):
            node = node.value
        return self._guard_for(node, class_name)

    def _lock_check_node(self, node, locks, class_name, func_node):
        # definition-site exemptions, PER TARGET: a mutation is exempt
        # only inside the function that carries THAT state's own
        # guarded-by annotation (its constructor), or at module level
        # (initial binding) — an annotation for one name must not
        # excuse unlocked mutations of a different guarded name
        def exempt(decl_line):
            if func_node is None:
                return True  # module-level statement: initial binding
            if decl_line is None:
                return False
            return (func_node.lineno <= decl_line
                    <= getattr(func_node, "end_lineno", func_node.lineno))

        targets = []
        if isinstance(node, (ast.Assign,)):
            for t in node.targets:
                targets.extend(t.elts if isinstance(
                    t, (ast.Tuple, ast.List)) else [t])
        elif isinstance(node, ast.AugAssign):
            targets.append(node.target)
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets.append(node.target)
        elif isinstance(node, ast.Delete):
            targets.extend(node.targets)
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATORS:
            lock, name, decl = self._state_expr(node.func.value, class_name)
            if lock and lock not in locks and not exempt(decl):
                self._emit(
                    "lock-discipline", node,
                    f"{name}.{node.func.attr}(...) mutates state guarded "
                    f"by `{lock}` outside `with {lock}:`")
            return
        for t in targets:
            lock, name, decl = self._state_expr(t, class_name)
            if lock and lock not in locks and not exempt(decl):
                what = (_unparse(t) or name)
                self._emit(
                    "lock-discipline", node,
                    f"assignment to {what} mutates state guarded by "
                    f"`{lock}` outside `with {lock}:`")

    # ----------------------------------------------------- thread-hygiene

    def _thread_classes(self):
        out = set()
        for name, cls in self.info.classes.items():
            for base in cls.bases:
                b = _unparse(base)
                if b in ("threading.Thread", "Thread"):
                    out.add(name)
        return out

    def _check_thread_hygiene(self):
        if "thread-hygiene" not in self.rules:
            return
        thread_classes = self._thread_classes()
        for name in thread_classes:
            cls = self.info.classes[name]
            stop = next(
                (m for m in cls.body
                 if isinstance(m, ast.FunctionDef)
                 and m.name in ("stop", "close", "shutdown")), None)
            if stop is None or ".join(" not in (
                    ast.get_source_segment(self.info.source, stop) or ""):
                self._emit(
                    "thread-hygiene", cls,
                    f"Thread subclass {name!r} has no stop/join path "
                    "(define stop()/close()/shutdown() that joins) — an "
                    "unjoinable daemon can outlive the state it samples")
            init = next((m for m in cls.body
                         if isinstance(m, ast.FunctionDef)
                         and m.name == "__init__"), None)
            if init is not None:
                for n in ast.walk(init):
                    if isinstance(n, ast.Call) and _unparse(n.func) in (
                            "super().__init__", "threading.Thread.__init__"):
                        self._thread_ctor_kwargs(n, f"{name}.__init__")
        for fn in self.info.functions.values():
            for n in ast.walk(fn.node):
                if isinstance(n, ast.Call) and _unparse(n.func) in (
                        "threading.Thread", "Thread"):
                    self._thread_ctor_kwargs(n, fn.qualname)
                    self._thread_join_path(n, fn)

    def _thread_ctor_kwargs(self, call, where):
        kw = {k.arg: k.value for k in call.keywords}
        daemon = kw.get("daemon")
        if not (isinstance(daemon, ast.Constant) and daemon.value is True):
            self._emit(
                "thread-hygiene", call,
                f"thread constructed in {where} without daemon=True — a "
                "non-daemon background thread wedges interpreter "
                "shutdown when its owner forgets to stop it")
        if "name" not in kw:
            self._emit(
                "thread-hygiene", call,
                f"thread constructed in {where} without a name= — "
                "anonymous Thread-N in a hang dump is undebuggable")

    def _thread_join_path(self, call, fn):
        # the binding this construction lands in must be .join()ed
        # somewhere in the module (drain/stop paths live in the same
        # file for every runtime thread)
        parent = None
        for n in ast.walk(fn.node):
            if isinstance(n, ast.Assign) and any(
                    c is call for c in ast.walk(n.value)):
                parent = n
                break
        bound = None
        if parent is not None and parent.targets:
            t = parent.targets[0]
            if isinstance(t, ast.Name):
                bound = t.id
            elif isinstance(t, ast.Attribute):
                bound = t.attr
        if bound is None or f"{bound}.join(" not in self.info.source:
            self._emit(
                "thread-hygiene", call,
                "thread construction with no visible join path "
                f"({'unbound' if bound is None else bound + '.join(...) '}"
                "not found in this module) — every runtime thread needs "
                "a stop/join so shutdown is deterministic")

    # ----------------------------------------------------- async-blocking

    def _check_async_blocking(self):
        if "async-blocking" not in self.rules:
            return
        if not self.force and not _in_modules(self.info.display,
                                              ASYNC_MODULES):
            return
        for fn in self.info.functions.values():
            if not fn.is_async:
                continue
            for line, prim in fn.primitives:
                node = _NodeAt(line)
                self._emit(
                    "async-blocking", node,
                    f"async def {fn.qualname}: {prim} blocks the serve "
                    "event loop — await an async equivalent or push it "
                    "through loop.run_in_executor")
            for line, target in fn.calls:
                if target is None:
                    continue
                tgt = _resolve_target(self.funcs, self.modules, target)
                if tgt is None or tgt not in self.blocking:
                    continue
                if self.funcs[tgt].is_async:
                    continue
                self._emit(
                    "async-blocking", _NodeAt(line),
                    f"async def {fn.qualname} calls blocking "
                    f"{tgt[0]}::{self.funcs[tgt].qualname} "
                    f"[{self.blocking[tgt]}] — push it through "
                    "loop.run_in_executor")


class _NodeAt:
    """Minimal location carrier for findings derived from call-graph
    facts (only lineno/col are consumed by :class:`Finding`)."""

    __slots__ = ("lineno", "col_offset")

    def __init__(self, lineno):
        self.lineno = lineno
        self.col_offset = 0


# ================================================================== driver


def _parse_guards(info):
    """Attach ``guarded-by`` declarations to ``info``: maps of state
    name -> ``(lock, declaration line)`` — the line scopes the
    per-target constructor exemption in the lock-discipline check."""
    decls = {}
    for i, text in enumerate(info.source.splitlines(), start=1):
        m = _GUARD_RE.search(text)
        if m:
            decls[i] = m.group("lock")
    if not decls:
        return
    class_of_line = {}
    for name, cls in info.classes.items():
        for ln in range(cls.lineno, getattr(cls, "end_lineno",
                                            cls.lineno) + 1):
            class_of_line[ln] = name
    for node in ast.walk(info.tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        # the annotation may sit on any physical line of a multi-line
        # assignment (a wrapped AnnAssign puts it on the continuation)
        lock = next((decls[ln] for ln in
                     range(node.lineno,
                           getattr(node, "end_lineno", node.lineno) + 1)
                     if ln in decls), None)
        if lock is None:
            continue
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for t in targets:
            if isinstance(t, ast.Name):
                info.module_guards[t.id] = (lock, node.lineno)
            elif isinstance(t, ast.Attribute) \
                    and isinstance(t.value, ast.Name) \
                    and t.value.id == "self":
                cls = class_of_line.get(node.lineno)
                if cls:
                    info.instance_guards[(cls, t.attr)] = (lock,
                                                           node.lineno)


def _load_module(path, display=None, source=None):
    display = display or os.path.relpath(path, repo_root())
    if source is None:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    return _ModuleInfo(path, display, source)


def analyze_paths(paths=None, root=None, rules=None):
    """Run every concurrency rule; returns sorted :class:`Finding`\\ s.

    Default scan: the whole package scan set (the call graph needs it
    all) with per-module rule gating.  Explicit ``paths`` analyze just
    those files with EVERY rule forced on (the fixture/CI-negative
    mode) — their call graph is file-local."""
    forced = paths is not None
    scan = list(paths) if forced else default_paths(root)
    modules = {}
    for p in scan:
        try:
            info = _load_module(p)
        except SyntaxError as e:
            return [Finding(os.path.relpath(p, repo_root()), e.lineno or 1,
                            (e.offset or 0) + 1, "syntax",
                            f"cannot parse: {e.msg}")]
        modules[info.display] = info
    blocking, funcs = _propagate_blocking(modules)
    findings = []
    for info in modules.values():
        if forced:
            active = set(rules or RULES)
        else:
            active = set(RULES)
            if not _in_modules(info.display, SHARED_STATE_MODULES):
                active -= {"atomic-write", "lock-discipline",
                           "thread-hygiene"}
            if not _in_modules(info.display, ASYNC_MODULES):
                active.discard("async-blocking")
        if not active:
            continue
        checker = _FileChecker(info, active, blocking=blocking,
                               funcs=funcs, modules=modules, force=forced)
        findings.extend(checker.run())
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
