"""Traced geometry design axis (VERDICT r2 #2).

Validates that the in-trace geometry parameterisation
(:mod:`raft_tpu.structure.members_traced`) reproduces EXACTLY what a
Python rebuild of the design with scaled member diameters/thicknesses/
ballast/mooring would produce (the build-time/trace-time split of
SURVEY §7.1), and that response metrics are differentiable wrt the
geometry parameters (matching finite differences).

Reference touchpoints: parametersweep.py:56-100 (geometry DoE),
omdao_raft.py:26-343 (WEIS design variables member_d/member_t/ballast/
mooring), raft_member.py getInertia :412-541 + caps :659-823.
"""

import copy

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from numpy.testing import assert_allclose

from tests.conftest import ref_data

import raft_tpu
from raft_tpu.api import make_full_evaluator

PATH = ref_data("VolturnUS-S.yaml")

CASE = dict(wind_speed=10.0, Hs=6.0, Tp=12.0, beta_deg=20.0, TI=0.1)

D_S, T_S, F_S, L_S = 1.07, 0.92, 1.10, 1.02


@pytest.fixture(scope="module")
def model():
    import os

    if not os.path.exists(PATH):
        pytest.skip("reference data unavailable")
    return raft_tpu.Model(PATH)


def _scaled_design(design):
    """Rebuild the design dict with every member's d/t scaled and the
    mooring line lengths scaled — the ground truth the traced geometry
    axis must match."""
    d2 = copy.deepcopy(design)
    for mi in d2["platform"]["members"]:
        mi["d"] = (np.asarray(mi["d"], dtype=float) * D_S).tolist()
        mi["t"] = (np.asarray(mi["t"], dtype=float) * T_S).tolist()
        if "l_fill" in mi:
            mi["l_fill"] = (np.asarray(mi["l_fill"], dtype=float) * F_S).tolist()
        if "cap_d_in" in mi:
            # hole diameters follow the member scaling so the traced
            # twin (which scales d only) is compared consistently: the
            # traced path keeps cap_d_in fixed, so scale it here too? No:
            # the traced path treats cap_d_in as static — leave as is.
            pass
    tower = d2["turbine"]["tower"]
    towers = tower if isinstance(tower, list) else [tower]
    for mi in towers:
        mi["d"] = (np.asarray(mi["d"], dtype=float) * D_S).tolist()
        mi["t"] = (np.asarray(mi["t"], dtype=float) * T_S).tolist()
    for line in d2["mooring"]["lines"]:
        line["length"] = float(line["length"]) * L_S
    return d2


@pytest.mark.slow
def test_geometry_identity(model):
    """all-ones geometry params == the baked-constant evaluator."""
    ev0 = make_full_evaluator(model)
    evg = make_full_evaluator(model, geometry=True)
    out0 = jax.jit(ev0)(CASE)
    outg = jax.jit(evg)(dict(CASE, geom={}))
    assert_allclose(np.asarray(outg["PSD"]), np.asarray(out0["PSD"]),
                    rtol=1e-9, atol=1e-12)
    assert_allclose(np.asarray(outg["X0"]), np.asarray(out0["X0"]),
                    rtol=1e-9, atol=1e-12)


@pytest.mark.slow
def test_geometry_matches_rebuild(model):
    """Traced geometry scaling == Python rebuild of the scaled design.

    This is the core build-time/trace-time split guarantee: the traced
    member-element twin reproduces the numpy build path exactly, so a
    geometry DoE can run through ONE compiled evaluator."""
    evg = make_full_evaluator(model, geometry=True)
    geom = dict(d_scale=D_S, t_scale=T_S, fill_scale=F_S, L_moor_scale=L_S)
    outg = jax.jit(evg)(dict(CASE, geom=geom))

    model2 = raft_tpu.Model(_scaled_design(model.design))
    ev2 = make_full_evaluator(model2)
    out2 = jax.jit(ev2)(CASE)

    assert_allclose(np.asarray(outg["X0"]), np.asarray(out2["X0"]),
                    rtol=1e-7, atol=1e-10)
    psd_g = np.asarray(outg["PSD"])
    psd_2 = np.asarray(out2["PSD"])
    assert np.max(np.abs(psd_g - psd_2)) / (np.max(np.abs(psd_2)) + 1e-30) < 1e-7


def test_geometry_statics_elements_match_rebuild(model):
    """Element-level check: traced inertia elements at scaled d/t equal
    the numpy build of the scaled member."""
    from raft_tpu.structure.members import build_member
    from raft_tpu.structure.members_traced import traced_inertia_elements

    mi = dict(model.design["platform"]["members"][0])
    mem0 = build_member(mi, heading=0.0)
    mi2 = dict(mi)
    mi2["d"] = (np.asarray(mi["d"], dtype=float) * D_S).tolist()
    mi2["t"] = (np.asarray(mi["t"], dtype=float) * T_S).tolist()
    if "l_fill" in mi2:
        mi2["l_fill"] = (np.asarray(mi2["l_fill"], dtype=float) * F_S).tolist()
    mem2 = build_member(mi2, heading=0.0)

    lf = jnp.asarray(mem0.l_fill) * (F_S if "l_fill" in mi else 1.0)
    em, es, ex, ey, ez, mshell, mfill = traced_inertia_elements(
        mem0, jnp.asarray(mem0.d) * D_S, jnp.asarray(mem0.t) * T_S,
        lf, jnp.asarray(mem0.rho_fill))
    assert_allclose(np.asarray(em), mem2.elem_mass, rtol=1e-9, atol=1e-9)
    assert_allclose(np.asarray(es), mem2.elem_s, rtol=1e-9, atol=1e-9)
    assert_allclose(np.asarray(ex), mem2.elem_Ixx, rtol=1e-9, atol=1e-6)
    assert_allclose(np.asarray(ey), mem2.elem_Iyy, rtol=1e-9, atol=1e-6)
    assert_allclose(np.asarray(ez), mem2.elem_Izz, rtol=1e-9, atol=1e-6)
    assert_allclose(float(mshell), mem2.mshell, rtol=1e-9)


@pytest.mark.slow
def test_geometry_gradient_matches_fd(model):
    """jax.grad of a response metric wrt the member-diameter scale
    matches central finite differences (the optimization contract of
    the geometry axis)."""
    evg = make_full_evaluator(model, geometry=True)

    def metric(ds):
        out = evg(dict(CASE, geom=dict(d_scale=ds)))
        # pitch RMS-like scalar from the PSD
        return jnp.sqrt(jnp.sum(out["PSD"][4]))

    g = float(jax.jit(jax.grad(metric))(1.0))
    h = 1e-4
    m_p = float(jax.jit(metric)(1.0 + h))
    m_m = float(jax.jit(metric)(1.0 - h))
    fd = (m_p - m_m) / (2 * h)
    assert abs(g - fd) / (abs(fd) + 1e-12) < 5e-3, (g, fd)


@pytest.mark.slow
def test_geometry_bem_interpolation(tmp_path):
    """Geometry axis on a POTENTIAL-FLOW design (OC4semi,
    potModMaster=2): make_full_evaluator(geometry=True) samples the
    native BEM solver at three diameter scales and interpolates A/B/X
    quadratically in d_scale inside the trace.  Validity: the
    interpolated coefficients at an off-sample scale match a DIRECT
    native solve at that scale to <1%, and the full evaluator runs a
    traced case at the scaled geometry."""
    import os
    import shutil

    import raft_tpu
    from raft_tpu.api import make_full_evaluator
    from raft_tpu.structure.schema import load_design

    if shutil.which("g++") is None:
        pytest.skip("no C++ toolchain")
    prev_dir = os.environ.get("RAFT_TPU_BEM_DIR")
    os.environ["RAFT_TPU_BEM_DIR"] = str(tmp_path)
    try:
        design = load_design("/root/reference/designs/OC4semi.yaml")
        design["platform"]["potModMaster"] = 2
        design["settings"]["min_freq"] = 0.02
        design["settings"]["max_freq"] = 0.12
        design["settings"]["nAz_BEM"] = 8      # coarse for CI runtime
        design["settings"]["dz_BEM"] = 3.0
        model = raft_tpu.Model(design)
        assert model.bem is not None

        evaluate = make_full_evaluator(model, geometry=True)
        s_chk = 1.04
        gc = evaluate.geometry_constants(dict(d_scale=jnp.asarray(s_chk)))
        direct = model.run_bem(d_scale=s_chk)
        for key, got in (("A_BEM", gc["A_BEM6"]), ("B_BEM", gc["B_BEM6"]),
                         ("X_BEM", gc["X_BEM6"])):
            want = np.asarray(direct[key])
            dev = np.max(np.abs(np.asarray(got) - want)) / np.max(np.abs(want))
            assert dev < 1e-2, (key, dev)

        # end-to-end traced case at the scaled geometry
        out = jax.jit(lambda c: evaluate(c)["PSD"])(dict(
            wind_speed=0.0, Hs=jnp.asarray([4.0]), Tp=jnp.asarray([10.0]),
            gamma=jnp.asarray([0.0]), beta_deg=jnp.asarray([0.0]),
            geom=dict(d_scale=jnp.asarray(s_chk))))
        assert bool(jnp.all(jnp.isfinite(out)))
    finally:
        if prev_dir is None:
            os.environ.pop("RAFT_TPU_BEM_DIR", None)
        else:
            os.environ["RAFT_TPU_BEM_DIR"] = prev_dir
