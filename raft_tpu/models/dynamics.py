"""Frequency-domain dynamics solve (jax) — the framework's hot path.

Equivalent of ``Model.solveDynamics`` (``/root/reference/raft/
raft_model.py:966-1255``): iterative stochastic drag linearisation
around the response spectrum, then the complex impedance solve

    Z(w) xi(w) = F(w),   Z = -w^2 M(w) + i w B(w) + C

per frequency and excitation heading.

TPU-first design:
* the per-frequency dense solves are one batched ``jnp.linalg.solve``
  over the stacked (nw, nDOF, nDOF) tensor — no Python loop over
  frequencies (reference loops at raft_model.py:1084-1089);
* the fixed-point drag-linearisation iteration is a
  ``lax.while_loop`` with the reference's convergence test and 0.2/0.8
  under-relaxation (raft_model.py:1103-1133), so the whole solve jits
  and vmaps over load cases and designs;
* the system response for all headings is a single batched solve
  against the (nWaves, nDOF, nw) excitation tensor.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from raft_tpu.physics import morison


def impedance(w, M, B, C):
    """Z (nw, nDOF, nDOF) from M/B (nDOF, nDOF, nw) and C (nDOF, nDOF)."""
    Mw = jnp.moveaxis(M, -1, 0)
    Bw = jnp.moveaxis(B, -1, 0)
    return (-(w**2)[:, None, None] * Mw + 1j * w[:, None, None] * Bw + C[None, :, :])


def solve_dynamics_fowt(
    fs, ss, hc, u0, M_lin, B_lin, C_lin, F_lin, w, Tn, r_nodes,
    n_iter=15, Xi_start=0.1, tol=0.01, Z_extra=None, n_iter_extra=0,
):
    """Iterative linearised solve for one FOWT's impedance and response.

    M_lin/B_lin : (nDOF, nDOF, nw); C_lin : (nDOF, nDOF);
    F_lin : (nDOF, nw) complex (primary-heading excitation);
    u0 : (S, 3, nw) wave velocities at strips for the primary heading.
    Z_extra : optional (nw, nDOF, nDOF) complex impedance added to Z
    (e.g. the frequency-dependent lumped-mass mooring impedance of
    moorMod 2, replacing the constant C_moor in C_lin).

    Returns (Z (nw,nDOF,nDOF), Xi (nDOF,nw), Bmat (S,3,3),
    diag dict with drag_resid (scalar) / drag_converged (bool) — the
    stopping-rule residual of the returned linearisation point).
    """
    nDOF, nw = F_lin.shape
    S = ss.S
    if Z_extra is None:
        Z_extra = jnp.zeros((nw, nDOF, nDOF), dtype=complex)

    def linearize(XiLast):
        out = morison.hydro_linearization(fs, ss, hc, u0, XiLast, w, Tn, r_nodes)
        return out["B_hydro_drag"], out["Bmat"], out["F_hydro_drag"]

    def update(XiLast):
        """One full (un-relaxed) linearise-and-solve step."""
        B_drag, Bmat, F_drag = linearize(XiLast)
        Z = impedance(w, M_lin, B_lin + B_drag[:, :, None], C_lin) + Z_extra
        F = F_lin + F_drag
        Xi = jnp.linalg.solve(Z, jnp.moveaxis(F, -1, 0)[..., None])[..., 0]
        return jnp.moveaxis(Xi, 0, -1), Z, Bmat  # (nDOF, nw)

    # Iteration budget: the reference's cap is n_iter (break on
    # convergence, warn otherwise, raft_model.py:1133-1143).  The
    # default n_iter_extra=0 reproduces the reference EXACTLY, including
    # its cap-limited states — the flexible-model goldens correspond to
    # the capped fixed-point iterate (both cases of the flexible design,
    # measured: enabling extra iterations moves the no-wind case off its
    # 1e-10-level golden parity), so parity demands stopping where the
    # reference stops even when the stopping rule is unmet (the
    # flexible-tower wind case sits at residual ~1.03e-2 against tol
    # 1e-2).  Sweeps that prefer the true fixed point over golden
    # compatibility can grant n_iter_extra additional under-relaxed
    # iterations, taken ONLY when the reference cap strikes unconverged.
    cap = n_iter + 1 + max(int(n_iter_extra), 0)

    def body(carry):
        XiLast, it, _ = carry
        Xi, _, _ = update(XiLast)
        tolCheck = jnp.abs(Xi - XiLast) / (jnp.abs(Xi) + tol)
        done = jnp.all(tolCheck < tol)
        # keep the final LINEARISATION POINT: on convergence the
        # reference breaks before relaxing, and when the iteration cap
        # strikes it keeps the response computed at the last
        # linearisation — relaxing once more before the final solve
        # would be one extra iteration vs the reference (measured at
        # ~1e-3 in cap-limited resonance bands)
        last = it + 1 >= cap
        XiNext = jnp.where(done | last, XiLast, 0.2 * XiLast + 0.8 * Xi)
        return XiNext, it + 1, done

    def cond(carry):
        _, it, done = carry
        return (it < cap) & (~done)

    def run_fixed_point(f, Xinit):
        XiLast, _, _ = jax.lax.while_loop(cond, body, (Xinit, 0, jnp.asarray(False)))
        return XiLast

    def residual(X):
        Xi, _, _ = update(X)
        return X - Xi

    def tangent_solve(g, y):
        # g(x) = x - A x with A the (contractive) linearised drag
        # coupling — solve by Neumann iteration x <- y + (x - g(x)),
        # which converges at the same rate as the fixed point itself
        x = y
        for _ in range(10):
            x = y + (x - g(x))
        return x

    # implicit differentiation of the drag-linearisation fixed point
    # (lax.custom_root): forward value identical to the reference-style
    # under-relaxed iteration; jax.grad works through the converged
    # point instead of unrolling the while_loop (SURVEY.md §7.1)
    Xi0 = jnp.full((nDOF, nw), Xi_start, dtype=complex)
    XiLast = jax.lax.custom_root(residual, Xi0, run_fixed_point, tangent_solve)
    # final response/impedance at the converged linearisation (exactly
    # the quantities the while_loop's last iteration produced)
    Xi, Z, Bmat = update(XiLast)
    # convergence diagnostic: does the returned point satisfy the
    # stopping rule?  (the reference warns on non-convergence,
    # raft_model.py:1138-1140; sweeps use this to flag bad cases)
    tolCheck = jnp.max(jnp.abs(Xi - XiLast) / (jnp.abs(Xi) + tol))
    return Z, Xi, Bmat, dict(drag_resid=tolCheck, drag_converged=tolCheck < tol)


def system_response(Z_sys, F_waves):
    """Response for every excitation source.

    Z_sys : (nw, nDOF, nDOF); F_waves : (nH, nDOF, nw) ->
    Xi : (nH, nDOF, nw).  One batched solve replaces the reference's
    explicit inverse + per-(heading, frequency) matmuls
    (raft_model.py:1189-1236)."""
    F = jnp.moveaxis(F_waves, -1, 1)          # (nH, nw, nDOF)
    Xi = jnp.linalg.solve(Z_sys[None], F[..., None])[..., 0]
    return jnp.moveaxis(Xi, 1, -1)
