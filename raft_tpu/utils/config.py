"""Central registry for ``RAFT_TPU_*`` environment flags.

Every knob the framework reads from the environment is declared here
once, with a type, a default and a one-line description.  Call sites
go through :func:`get` (typed, validated) or — for the few modules
with bespoke parsing/caching semantics (dtype policy aliases, fault
re-arming, log-sink swapping) — :func:`raw`, which is the only
sanctioned way to read the raw string.

Motivation: the flags accreted one ``os.environ.get`` at a time across
the hot path, the sweep runtime and the bench; a typo'd name fails
silently (the default quietly wins) and there was no single place to
see what is tunable.  The registry makes unknown names loud
(:class:`KeyError` at the call site, not a silent default), keeps
parsing/validation in one place, and feeds the ``env-read`` rule of
the trace-hygiene linter (:mod:`raft_tpu.analysis.lint`), which flags
raw ``os.environ["RAFT_TPU_*"]`` reads anywhere else.

Flags are *re-read from the environment on every call* — the hot path
reads them at trace time (see e.g. :func:`raft_tpu.ops.linsolve.
solver_path`), and tests monkeypatch them mid-process.  Nothing here
imports jax, so the linter and CLI can load the registry without
touching a backend.

``python -m raft_tpu.analysis flags`` prints the full table.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

PREFIX = "RAFT_TPU_"


@dataclass(frozen=True)
class Flag:
    """One registered environment flag (name is the ``RAFT_TPU_``-less
    suffix; ``kind`` drives parsing in :func:`get`)."""

    name: str
    kind: str = "str"          # str | int | float | bool | choice | raw
    default: object = None     # value when unset (or factory, below)
    default_factory: object = None  # callable default (cwd-/home-relative)
    choices: tuple = ()        # for kind == "choice" (values lowercased)
    help: str = ""
    aliases: dict = field(default_factory=dict)  # normalisation map

    @property
    def env(self):
        return PREFIX + self.name


FLAGS: dict[str, Flag] = {}


def _register(*flags):
    for f in flags:
        FLAGS[f.name] = f


def env_name(name):
    """The full environment-variable name for a registered flag."""
    return FLAGS[name].env


def raw(name):
    """Raw string value of a registered flag (None when unset).

    For modules with bespoke parsing (dtype-policy aliases, fault-spec
    lists, log sinks) — everything else should use :func:`get`.
    Unknown names raise ``KeyError`` so typos fail loudly.
    """
    return os.environ.get(FLAGS[name].env)


def get(name):
    """Typed, validated value of a registered flag.

    Re-reads the environment on every call (trace-time semantics).
    Bad values raise ``ValueError`` naming the variable; unknown flag
    names raise ``KeyError``.
    """
    f = FLAGS[name]
    s = os.environ.get(f.env)
    if s is None or (s == "" and f.kind != "str"):
        if f.default_factory is not None:
            return f.default_factory()
        return f.default
    if f.kind in ("str", "raw"):
        return s
    if f.kind == "int":
        try:
            return int(s)
        except ValueError:
            raise ValueError(f"{f.env}={s!r}: expected an integer")
    if f.kind == "float":
        try:
            return float(s)
        except ValueError:
            raise ValueError(f"{f.env}={s!r}: expected a number")
    if f.kind == "bool":
        v = s.strip().lower()
        if v in ("1", "true", "yes", "on"):
            return True
        if v in ("0", "false", "no", "off"):
            return False
        raise ValueError(f"{f.env}={s!r}: expected a boolean (0/1)")
    if f.kind == "choice":
        v = s.strip().lower()
        v = f.aliases.get(v, v)
        if v not in f.choices:
            raise ValueError(
                f"{f.env}={s.strip().lower()!r}: expected one of "
                + "/".join(repr(c) for c in f.choices if c))
        return v
    raise AssertionError(f"unhandled flag kind {f.kind!r}")


def describe():
    """Yield ``(env_name, kind, default, help)`` rows for every flag,
    sorted by name (the ``flags`` CLI subcommand and the README table
    render from this)."""
    for f in sorted(FLAGS.values(), key=lambda f: f.name):
        default = ("<dynamic>" if f.default_factory is not None
                   else f.default)
        yield f.env, f.kind, default, f.help


# --------------------------------------------------------------- registry

# accepted spellings of the two dtype policies — the single source of
# truth for BOTH the env-var path (get("DTYPE")) and the explicit
# policy argument of raft_tpu.utils.dtypes.compute_dtypes
DTYPE_F32_NAMES = ("float32", "f32", "single", "complex64")
DTYPE_F64_NAMES = ("float64", "f64", "double", "complex128")

_F32_ALIASES = {a: "float32" for a in DTYPE_F32_NAMES}
_F64_ALIASES = {a: "float64" for a in DTYPE_F64_NAMES}

_register(
    # -- hot-path compute policy
    Flag("SOLVER", "choice", "native",
         choices=("native", "lapack", "pallas"),
         help="impedance-solve kernel: batched pivot-free native "
              "elimination, jnp.linalg.solve (golden-parity fallback), "
              "or the Pallas block-GE kernel prototype (interpret-mode "
              "on CPU hosts; see README 'Performance')"),
    Flag("FUSED", "choice", "on", choices=("on", "off"),
         help="fused case hot path: the rigid single-heading evaluators "
              "take the response straight from the drag fixed point's "
              "final solve (the separable drag-excitation fold) instead "
              "of re-staging drag_excitation + a second system solve; "
              "'off' restores the staged tail (the parity oracle). "
              "Trace-time; part of the sweep memo/bank key"),
    Flag("FIXED_POINT", "choice", "auto", choices=("auto", "scan", "while"),
         help="drag-linearisation loop driver ('auto': while on CPU, "
              "masked fixed-trip scan on accelerators)"),
    Flag("SCAN_CHUNK", "int", 4,
         help="masked-scan block size between early-exit checks"),
    Flag("DTYPE", "choice", "", choices=("", "float32", "float64"),
         aliases={**_F32_ALIASES, **_F64_ALIASES},
         help="compute-dtype policy for the dynamics hot path "
              "(default: derive from the inputs)"),
    # -- solver health (see raft_tpu.utils.health and README "Solver
    #    health"): all read at trace time, so they are part of the
    #    sweep memo key (raft_tpu.parallel.sweep._flags_key)
    Flag("COND_CHECK", "bool", False,
         help="fold a one-step Hager condition estimate of Z(w) into "
              "the solver-health status word (ILL_CONDITIONED_Z)"),
    Flag("COND_THRESHOLD", "float", 1e7,
         help="kappa_1(Z) estimate above which ILL_CONDITIONED_Z is "
              "set (only with RAFT_TPU_COND_CHECK)"),
    Flag("ITER_SCALE", "int", 1,
         help="iteration-budget multiplier for the statics Newton and "
              "the drag fixed point (1 = reference budgets; the "
              "escalation ladder sets this for re-solves)"),
    Flag("ESCALATE", "choice", "off",
         choices=("off", "retol", "f64_cpu"),
         help="escalation ladder for status-flagged sweep rows: 'retol' "
              "re-solves with ESCALATE_ITER_SCALE x the iteration "
              "budget, 'f64_cpu' additionally retries under float64 on "
              "the CPU backend"),
    Flag("ESCALATE_ITER_SCALE", "int", 4,
         help="RAFT_TPU_ITER_SCALE applied by the escalation rungs"),
    # -- runtime / caching
    Flag("CACHE_DIR", "str",
         default_factory=lambda: os.path.join(
             os.path.expanduser("~"), ".cache", "raft_tpu", "jax_cache"),
         help="persistent XLA compilation-cache directory"),
    Flag("CACHE_MIN_COMPILE_S", "float", 0.0,
         help="only XLA compilations at least this long persist to the "
              "disk cache.  0 (default) persists everything: on a CPU "
              "build host most programs compile in under 10s and a "
              "nonzero threshold silently disables cross-process cache "
              "hits; the cost is cache-directory growth (bound it with "
              "an external tmpwatch, or raise the threshold on hosts "
              "where only the multi-minute accelerator programs matter)"),
    # -- AOT program bank (see raft_tpu.aot and README "AOT program
    #    bank & warmup")
    Flag("AOT", "choice", "off", choices=("off", "load", "require"),
         help="ahead-of-time program bank: 'load' consults the bank "
              "before tracing and exports freshly-compiled sweep "
              "programs for the next process; 'require' additionally "
              "treats a bank miss per RAFT_TPU_AOT_MISS (serving mode: "
              "cold start must be trace- and compile-free)"),
    Flag("AOT_DIR", "str",
         default_factory=lambda: os.path.join(
             os.path.expanduser("~"), ".cache", "raft_tpu", "aot_bank"),
         help="AOT program-bank directory (versioned layout inside)"),
    Flag("AOT_MISS", "choice", "error", choices=("error", "compile"),
         help="what RAFT_TPU_AOT=require does on a bank miss: 'error' "
              "raises BankMissError (fail loudly before any XLA work); "
              "'compile' logs the miss and falls back to trace+compile"),
    Flag("COMPILE_BUDGET", "int", -1,
         help="hard ceiling on XLA backend compilations per process "
              "(-1 disables).  Enforced by the recompile sentinel "
              "listener: steady state stays 0, and a cold start with a "
              "warm AOT bank + XLA disk cache must also be 0"),
    Flag("COMPILE_BUDGET_ACTION", "choice", "error",
         choices=("error", "warn"),
         help="exceeding RAFT_TPU_COMPILE_BUDGET raises "
              "RecompilationError ('error') or only logs + counts "
              "('warn')"),
    Flag("BUCKET_ROWS", "int", 512,
         help="max rows per dispatched bucket program in "
              "sweep_heterogeneous (0 = unlimited): larger signature "
              "groups dispatch in fixed-size chunks of exactly this "
              "many rows (dp-rounded, last chunk padded with masked "
              "repeat rows), capping host/device memory for the packed "
              "design batch at chunk x design instead of rows x design "
              "while every chunk reuses ONE compiled program"),
    Flag("BUCKET_STEPS", "str",
         "strips=16,24,32,48,64,96,128;nodes=pow2;lines=pow2",
         help="per-axis shape-bucket pad ladders for the heterogeneous "
              "design buckets: ';'-separated axis=rungs entries where "
              "rungs is an ascending comma list (doubling continues "
              "past the last rung) or 'pow2' (classic power-of-two "
              "ceiling at the axis floor).  The default strips ladder "
              "adds midpoint rungs between the pow2 sizes — tuned from "
              "the PR-11 row-weighted waste_by_axis histograms, it cuts "
              "the bundled-trio row-weighted strip padding waste from "
              "0.35 to 0.15 (see README 'Performance').  Changing the "
              "ladder changes bucket signatures: re-run `python -m "
              "raft_tpu.aot warmup` so steady-state recompiles stay 0"),
    Flag("BEM_DIR", "str",
         default_factory=lambda: os.path.join(os.getcwd(), "_bem_cache"),
         help="panel-method BEM coefficient cache directory"),
    Flag("PROBE_S", "float", 300.0,
         help="accelerator health-probe timeout (seconds)"),
    Flag("CLI_PLATFORM", "str", "cpu",
         help="jax platform pin for `python -m raft_tpu` (cpu also "
              "enables x64 for the parity path)"),
    Flag("LOG", "raw", "",
         help="structured-log sink: '-' for stderr, a JSONL path, or a "
              "DIRECTORY (existing, or written with a trailing slash) — "
              "each process then appends to its own "
              "<dir>/trace-<pid>.jsonl shard, merged offline by "
              "`python -m raft_tpu.obs trace --merge <dir>`"),
    # -- telemetry (see raft_tpu.obs and README "Observability")
    Flag("RUN_ID", "raw", "",
         help="telemetry run id stamped on every structured-log record "
              "(default: a fresh uuid per process; pin it so a resumed "
              "sweep's events stay linkable to the original run; the "
              "fabric coordinator pins it into worker env automatically)"),
    Flag("TRACEPARENT", "raw", "",
         help="W3C traceparent (00-<trace>-<span>-01) inherited from a "
              "parent process: the first root span of this process "
              "joins that trace instead of minting a fresh trace_id "
              "(set by the fabric coordinator for spawned workers; "
              "accepted/emitted as the `traceparent` HTTP header by "
              "the evaluation service)"),
    Flag("HEARTBEAT_S", "float", 0.0,
         help="device-heartbeat sampling period in seconds (0 disables): "
              "a daemon thread emits per-device memory_stats, live-buffer "
              "counts and shard progress as 'heartbeat' events + gauges"),
    Flag("METRICS", "str", "",
         help="when set, the metrics registry is exported in Prometheus "
              "text format to this path at sweep_done (scrape target "
              "for long runs)"),
    # -- black-box flight recorder (see raft_tpu.obs.flight and README
    #    "Flight recorder & exemplars")
    Flag("FLIGHT_RING", "int", 4096,
         help="flight-recorder ring capacity in records (spans, events, "
              "metric deltas; ~200B each in memory).  Always on — every "
              "process keeps its last N records for postmortem dumps "
              "even with RAFT_TPU_LOG unset; 0 disables the recorder"),
    Flag("FLIGHT_DIR", "str", "",
         help="flight-dump shard directory: when set, the ring is "
              "flushed atomically to <dir>/flight-<pid>.jsonl every "
              "RAFT_TPU_FLIGHT_FLUSH_S (what a SIGKILLed process "
              "leaves behind) and trigger dumps (alert_fire, SEVERE "
              "quarantine, compile-budget breach, crash/SIGTERM) land "
              "as trigger-named siblings.  Unset: ring only (still "
              "dumpable via GET /debug/flight and `obs flight dump -o`)"),
    Flag("FLIGHT_FLUSH_S", "float", 2.0,
         help="period of the background flight-ring flush to "
              "RAFT_TPU_FLIGHT_DIR — the upper bound on history lost "
              "to an uncatchable SIGKILL"),
    Flag("FLIGHT_SNAP_S", "float", 10.0,
         help="period of the flight ring's metric-snapshot delta "
              "records (counter movement between snapshots — rate "
              "context for a postmortem)"),
    Flag("EXEMPLAR_K", "int", 2,
         help="exemplars kept per histogram log-bucket (top-K by "
              "value): the trace/span ids + caller attrs of the actual "
              "slowest requests, exported in OpenMetrics exemplar "
              "syntax on /metrics and joined by `obs report --tail`. "
              "0 disables exemplar capture"),
    Flag("EXEMPLAR_MIN_S", "float", 0.0,
         help="minimum observed value for exemplar admission (mute "
              "exemplar bookkeeping + exemplar_recorded events for "
              "uninteresting fast observations)"),
    # -- longitudinal run-record store (see raft_tpu.obs.runs and
    #    README "Performance regression tracking")
    Flag("RUNS_DIR", "str", "",
         help="append-only run-record store directory: when set, every "
              "bench / checkpointed-sweep / serve session ends by "
              "writing a schema-versioned run record (env fingerprint, "
              "metrics snapshot, cost ledger, compile counts) there; "
              "`python -m raft_tpu.obs runs {list,compare,regress}` "
              "read it.  Unset (default) disables recording"),
    Flag("RUNS_REL_TOL", "float", 0.5,
         help="when SET, overrides every watch rule's relative "
              "worsening tolerance in `obs runs regress` (the "
              "noisier-host loosening knob; a watched metric "
              "regresses only past max(rel_tol x |baseline|, abs "
              "floor)).  Unset, the per-rule tolerances apply: "
              "latency-histogram rules use 1.0 (their percentiles "
              "move in log-bucket quantization steps of ~1.78x), "
              "throughput rules 0.5.  `--rel-tol` outranks both"),
    Flag("RUNS_ABS_FLOOR", "float", 1.0,
         help="global multiplier on the per-rule minimum-absolute-"
              "delta floors of `obs runs regress` (raise it to mute "
              "sub-floor jitter on noisier hosts; the floors keep "
              "tiny-relative-but-huge-percentage changes on "
              "near-zero baselines from failing CI)"),
    Flag("FAULTS", "raw", "",
         help="deterministic fault injection: comma list of "
              "kind:site[:count] specs (see raft_tpu.utils.faults)"),
    # -- elastic sweep fabric (see raft_tpu.parallel.fabric and README
    #    "Elastic sweep fabric")
    Flag("FABRIC_WORKERS", "int", 0,
         help="route checkpointed sweeps through N local fabric worker "
              "subprocesses claiming shards from the lease ledger "
              "(0/1 = serial in-process path; needs a fabric entry "
              "spec on the evaluator — see README)"),
    Flag("FABRIC_TTL_S", "float", 30.0,
         help="shard lease time-to-live: a lease not renewed within "
              "this window is expired and the shard is stealable "
              "(a dead worker is just an expired lease)"),
    Flag("FABRIC_STEAL_MULT", "float", 4.0,
         help="straggler steal threshold: a lease older than this "
              "multiple of the pooled shard_wall_s p95 is stealable "
              "even while still being renewed"),
    Flag("FABRIC_POLL_S", "float", 0.5,
         help="fabric ledger poll period for idle workers and the "
              "coordinator wait loop"),
    Flag("FABRIC_FAULT_WORKER", "int", 0,
         help="index of the ONE spawned worker that receives the "
              "worker-targeted RAFT_TPU_FAULTS kinds (worker_kill, "
              "lease_expire); other workers get them stripped so the "
              "kill-a-worker test is deterministic"),
    Flag("WORKER_ID", "raw", "",
         help="fabric worker id stamped as 'worker' on every "
              "structured-log record (set by the coordinator for "
              "spawned workers; per-worker event streams stay "
              "separable in one shared RAFT_TPU_LOG capture)"),
    # -- evaluation service (see raft_tpu.serve and README "Evaluation
    #    service")
    Flag("SERVE_TICK_MS", "float", 20.0,
         help="continuous-batching tick CEILING: pending requests "
              "coalesce into one bucketed dispatch per (signature, "
              "tick).  Under RAFT_TPU_SERVE_TICK_MODE=adaptive this is "
              "the window under sustained load; light load shrinks the "
              "window toward RAFT_TPU_SERVE_TICK_MIN_MS"),
    Flag("SERVE_TICK_MIN_MS", "float", 1.0,
         help="adaptive-tick floor: with a near-empty queue the "
              "coalescing window shrinks to this, so a lone light-load "
              "request waits ~this long instead of the full tick "
              "(adaptive mode only)"),
    Flag("SERVE_TICK_MODE", "choice", "adaptive",
         choices=("adaptive", "fixed"),
         help="serve tick policy: 'adaptive' scales the coalescing "
              "window between SERVE_TICK_MIN_MS and SERVE_TICK_MS with "
              "the recent per-tick row load and dispatches speculatively "
              "early when a bucket group fills a top ladder rung; "
              "'fixed' restores the constant SERVE_TICK_MS window"),
    Flag("SERVE_LADDER", "str", "cost",
         help="serve batch-ladder policy: 'pow2' (dp,2dp,... up to "
              "SERVE_MAX_BATCH), 'cost' (pow2 candidates warmed, then "
              "rungs whose measured dispatch wall is flat vs the next "
              "rung are pruned after warmup — fewer programs where "
              "padding is free, finer rungs where it costs), or an "
              "explicit ascending comma list of rung sizes"),
    Flag("SERVE_LADDER_TOL", "float", 1.15,
         help="cost-ladder flatness tolerance: rung r is pruned when "
              "the next rung's measured per-dispatch wall is within "
              "this factor of r's (dispatching padded to the bigger "
              "rung costs ~nothing, so the extra program buys nothing)"),
    Flag("SERVE_MAX_BATCH", "int", 64,
         help="largest padded batch one serving dispatch holds; the "
              "batch ladder is dp,2*dp,... up to this (programs are "
              "compiled/banked per ladder size — warm with the SAME "
              "value: python -m raft_tpu.aot warmup --kinds serve)"),
    Flag("SERVE_CACHE_MB", "float", 64.0,
         help="byte budget of the content-addressed result cache "
              "(design hash + case + out_keys -> outputs, LRU)"),
    Flag("SERVE_QUEUE", "int", 1024,
         help="admission-queue bound: requests past this many pending "
              "get 503 (backpressure) instead of an unbounded backlog"),
    Flag("SERVE_QPS", "float", 0.0,
         help="per-client token-bucket sustained rate (requests/s); "
              "0 disables quotas.  An over-quota client gets 429 with "
              "Retry-After"),
    Flag("SERVE_BURST", "float", 32.0,
         help="per-client token-bucket burst capacity"),
    Flag("SERVE_TIMEOUT_S", "float", 300.0,
         help="per-request evaluation timeout at the HTTP layer (408)"),
    Flag("SERVE_DRAIN_S", "float", 120.0,
         help="graceful-shutdown budget: SIGTERM finishes in-flight "
              "ticks and open responses within this window"),
    Flag("SERVE_SLO_MS", "float", 0.0,
         help="per-request latency SLO in milliseconds (0 disables): a "
              "request resolving slower than this increments the "
              "serve_slo_breaches counter and emits an slo_breach "
              "event; /healthz reports breaches next to the sliding-"
              "window p50/p95"),
    Flag("SERVE_WINDOW_S", "float", 60.0,
         help="sliding-window length (seconds) of the serve latency "
              "time-series: /healthz p50/p95/rate are computed over "
              "the last this-many seconds, not process lifetime"),
    Flag("SERVE_CLIENT_RETRIES", "int", 0,
         help="serve-client retry budget for clean 429/503 rejections "
              "(capped exponential backoff honoring Retry-After; 0 "
              "disables — rejections return to the caller as-is)"),
    # -- live fleet health: alert rules + golden canaries (see
    #    raft_tpu.obs.alerts / raft_tpu.serve.canary and README
    #    "Alerting & canaries")
    Flag("ALERT_EVAL_S", "float", 0.0,
         help="alert-rule evaluation period in seconds (0 disables — "
              "no thread, no state): a named daemon evaluates the rule "
              "pack against the live metrics registry, emitting "
              "alert_fire/alert_resolve events, the alerts_active "
              "gauge and the RAFT_TPU_ALERTS sink; state is served at "
              "GET /alerts on replicas and the router"),
    Flag("ALERT_RULES", "str", "",
         help="YAML/JSON rule file loaded over the default alert pack "
              "(same-name rules replace, 'disabled: true' removes, "
              "top-level 'default_pack: false' starts empty); validate "
              "with `python -m raft_tpu.obs alerts check`"),
    Flag("ALERTS", "str", "",
         help="JSONL sink path for alert fire/resolve records (one "
              "appended line per transition; unset = no sink)"),
    Flag("CANARY_S", "float", 0.0,
         help="golden-answer canary period in seconds (0 disables): on "
              "the router, a daemon probes every (replica, design) "
              "pair with a synthetic /evaluate and compares against "
              "content-addressed goldens (bit-for-status, tolerance-"
              "for-floats) + cross-replica provenance consistency; on "
              "a replica, golden rows are captured at warmup"),
    Flag("CANARY_OUT_KEYS", "str", "X0,status",
         help="out_keys the canary probes request and compares "
              "(status is always included; keep these small — X0 is "
              "6 floats, PSD is a full grid)"),
    Flag("CANARY_RTOL", "float", 1e-5,
         help="relative tolerance of the canary's float-output "
              "comparison against the golden row (status bits are "
              "always compared exactly)"),
    Flag("CANARY_ATOL", "float", 1e-8,
         help="absolute tolerance of the canary's float-output "
              "comparison against the golden row"),
    # -- serving fleet: replica membership ledger (see raft_tpu.serve.
    #    fleet and README "Serving fleet")
    Flag("FLEET_DIR", "str", "",
         help="default fleet deploy directory (the _fleet/ membership "
              "ledger root) for `python -m raft_tpu.serve "
              "{--fleet-dir,fleet,router}` when the flag is not passed "
              "explicitly"),
    Flag("FLEET_TTL_S", "float", 10.0,
         help="replica membership-lease time-to-live: a lease not "
              "renewed within this window is a dead replica — the "
              "router evicts it from the hash ring (renewals run every "
              "ttl/3 from a daemon thread)"),
    Flag("FLEET_FAULT_REPLICA", "int", 0,
         help="index of the ONE spawned fleet replica that receives "
              "the replica-targeted RAFT_TPU_FAULTS kinds "
              "(replica_kill, replica_hang, replica_5xx); other "
              "replicas get them stripped so the kill-a-replica drill "
              "is deterministic"),
    # -- serving fleet: failover router (see raft_tpu.serve.router)
    Flag("ROUTER_PROBE_S", "float", 1.0,
         help="router membership-prober period: lease-ledger scan, "
              "joiner /healthz admission probe, expired-lease "
              "eviction, breaker-open recovery probe, router.json "
              "publication"),
    Flag("ROUTER_VNODES", "int", 64,
         help="virtual nodes per replica on the consistent-hash ring "
              "(more = smoother key distribution, larger ring)"),
    Flag("ROUTER_RETRIES", "int", 3,
         help="failover retry budget per proxied request: a connect "
              "failure, dropped response, per-attempt timeout or "
              "retryable 5xx moves the request to the next ring "
              "replica up to this many extra attempts"),
    Flag("ROUTER_BACKOFF_MS", "float", 50.0,
         help="base delay of the router's capped exponential failover "
              "backoff (doubles per retry; shared schedule with the "
              "serve client's 429/503 retries)"),
    Flag("ROUTER_BACKOFF_CAP_MS", "float", 2000.0,
         help="upper bound of the router failover backoff (an "
              "upstream Retry-After may exceed it — the server's "
              "window wins)"),
    Flag("ROUTER_TIMEOUT_S", "float", 300.0,
         help="per-attempt upstream timeout of one proxied request "
              "(connect + response); a timed-out attempt counts "
              "against the replica's breaker and fails over"),
    Flag("ROUTER_BREAKER_FAILS", "int", 3,
         help="consecutive upstream failures that open a replica's "
              "circuit breaker (no traffic until half-open)"),
    Flag("ROUTER_BREAKER_COOLDOWN_S", "float", 5.0,
         help="open-breaker cooldown before ONE half-open trial "
              "request (or prober /healthz success) may close it"),
    Flag("ROUTER_HEDGE_MS", "float", 0.0,
         help="hedged-request delay for p99 stragglers: a first "
              "attempt still unanswered after this many ms fires a "
              "second copy at the next ring replica and the first "
              "good response wins (0 disables; duplicate dispatch is "
              "benign — content-addressed result caches)"),
    # -- zero-downtime releases: canary-gated rolling upgrades (see
    #    raft_tpu.serve.rollout and README "Releases & rollouts")
    Flag("ROLLOUT_HEALTH_TIMEOUT_S", "float", 180.0,
         help="per-replica rollout step budget: the upgraded replica "
              "must bind, join the fleet ledger and clear its canary "
              "gate within this window, or the rollout aborts and "
              "rolls back automatically"),
    Flag("ROLLOUT_CANARY_PROBES", "int", 2,
         help="green canary passes required after each replica "
              "replacement before the rollout promotes to the next "
              "replica (0 skips the canary gate — testing only)"),
    Flag("ROLLOUT_POLL_S", "float", 0.5,
         help="rollout driver poll period while waiting on lease "
              "joins and canary verdicts"),
    # -- SLO-driven autoscaler (see raft_tpu.serve.autoscale)
    Flag("AUTOSCALE_EVAL_S", "float", 0.0,
         help="autoscaler evaluation period in seconds (0 disables — "
              "no thread, no state): a router-side daemon scales the "
              "replica fleet out on sustained slo-breach/breaker-"
              "storm alert state and in on low cost-ledger occupancy"),
    Flag("AUTOSCALE_MIN", "int", 1,
         help="autoscaler floor: scale-in never drops the fleet below "
              "this many live replicas"),
    Flag("AUTOSCALE_MAX", "int", 4,
         help="autoscaler ceiling: scale-out never grows the fleet "
              "past this many live replicas"),
    Flag("AUTOSCALE_OUT_FOR_S", "float", 3.0,
         help="sustain window of the scale-out signal: the hot "
              "condition (slo-breach/breaker-storm firing) must hold "
              "this long before a replica is added (the alert "
              "engine's for-duration state machine)"),
    Flag("AUTOSCALE_IN_FOR_S", "float", 15.0,
         help="sustain window of the scale-in signal: cost-ledger "
              "occupancy must stay under AUTOSCALE_LOW_OCC this long "
              "before a replica is drained (hysteresis against "
              "flapping — deliberately longer than the out window)"),
    Flag("AUTOSCALE_COOLDOWN_S", "float", 30.0,
         help="minimum seconds between ANY two autoscaler actions "
              "(out or in): a scale-out's warmup/join transient must "
              "never read as the next scale signal"),
    Flag("AUTOSCALE_LOW_OCC", "float", 0.1,
         help="scale-in occupancy threshold: fleet-mean busy fraction "
              "(cost-ledger busy seconds per wall second per replica) "
              "under this is a shrink candidate"),
    # -- multi-host distributed runtime (dryrun-tested on CPU; wired
    #    into resilience.resolve_mesh for real pods)
    Flag("DIST", "bool", False,
         help="call jax.distributed.initialize before mesh "
              "construction: the mesh spans every process's devices "
              "(multi-host pmap/shard_map pods)"),
    Flag("DIST_COORDINATOR", "str", "localhost:12765",
         help="jax.distributed coordinator address host:port"),
    Flag("DIST_PROCESS_ID", "int", 0,
         help="this process's index in the distributed job"),
    Flag("DIST_NUM_PROCESSES", "int", 1,
         help="total process count in the distributed job"),
    Flag("PROFILE", "str", "",
         help="when set, the bench AND any checkpointed sweep capture a "
              "jax profiler trace into this directory; telemetry spans "
              "mirror onto the profiler timeline as TraceAnnotations"),
    # -- bench harness
    Flag("PEAK_TFLOPS", "float", 90.0,
         help="assumed peak TF/s for the bench MFU estimate"),
    Flag("BENCH_PLATFORM", "str", "",
         help="jax platform pin for bench attempts (unset: ambient)"),
    Flag("BENCH_MODE", "str", "",
         help="bench child-process mode ('flat'/'geom'; internal)"),
    Flag("BENCH_BUDGET_S", "float", 1350.0,
         help="total bench wall-clock budget (seconds)"),
    Flag("BENCH_DEADLINE_S", "float", None,
         help="per-attempt deadline handed to bench children (internal)"),
    Flag("BENCH_PROBE_S", "float", 300.0,
         help="bench backend health-probe timeout (seconds)"),
    Flag("BENCH_BREAKDOWN", "bool", True,
         help="stage-attribution timing in the bench breakdown"),
    Flag("BENCH_DESIGNS", "int", 16,
         help="bench batch size (distinct design geometries)"),
    Flag("BENCH_REPS", "int", 3,
         help="bench steady-state timing repetitions"),
    Flag("BENCH_NBASE", "int", 1,
         help="cases measured for the serial NumPy baseline"),
    Flag("BENCH_BASE_EVAL_S", "float", None,
         help="pre-resolved NumPy-baseline seconds/design-eval "
              "(internal, parent -> child)"),
    Flag("BENCH_BASE_HOST", "str", "",
         help="host fingerprint of the NumPy baseline (internal)"),
    Flag("BENCH_FABRIC", "bool", True,
         help="append the fabric scaling block (same sweep at 1/2/4 "
              "workers) to the bench result when budget remains"),
    Flag("BENCH_FABRIC_N", "int", 1024,
         help="designs in the bench fabric scaling sweep"),
    Flag("BENCH_FABRIC_SHARD", "int", 64,
         help="shard size of the bench fabric scaling sweep"),
    Flag("BENCH_FABRIC_WORKERS", "str", "1,2,4",
         help="comma list of worker counts the bench fabric block "
              "measures"),
    Flag("BENCH_SERVE_CLIENTS", "int", 200,
         help="concurrent synthetic clients in the serve load test "
              "(RAFT_TPU_BENCH_MODE=serve)"),
    Flag("BENCH_SERVE_REQS", "int", 4,
         help="requests each synthetic serve-bench client issues"),
    Flag("BENCH_SERVE_POOL", "int", 48,
         help="distinct (Hs,Tp,beta) cases the serve-bench clients "
              "draw from (smaller pool = more duplicate corners = "
              "higher cache/coalescing hit rates)"),
)
