"""Seeded violations for the env-read rule: raw flag reads that bypass
the raft_tpu.utils.config registry."""

import os


def read_flags():
    a = os.environ.get("RAFT_TPU_SOLVER", "native")   # line 8
    b = os.environ["RAFT_TPU_DTYPE"]                  # line 9
    c = os.getenv("RAFT_TPU_SCAN_CHUNK", "4")         # line 10
    d = os.environ.get("XLA_FLAGS", "")               # not RAFT_TPU_: fine
    return a, b, c, d
