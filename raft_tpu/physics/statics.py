"""Statics: mass/inertia, weight, and hydrostatics of a FOWT (jax).

Pure-function twin of the reference statics stage
(``/root/reference/raft/raft_fowt.py`` ``calcStatics`` :811-1285,
``/root/reference/raft/raft_member.py`` ``getInertia`` :380-836,
``getHydrostatics`` :838-1156, ``getWeight`` :1158-1259), re-designed
for tracing:

* member *geometry* integrals (section masses, local MoI) were already
  reduced to per-element constants at build time
  (:mod:`raft_tpu.structure.members`);
* everything pose-dependent here is ``jax.numpy`` on those constants,
  with the waterplane-crossing branches expressed as ``where`` masks,
  so ``calc_statics`` jits and vmaps over mean-offset and design axes;
* DOF reduction is applied node-block-wise with the rigid-body
  transformation ``T_n = [[I, H(r_n - r_root)], [0, I]]`` (equivalent
  to the reference's assembled-T congruence, raft_fowt.py:1118-1128)
  and the geometric-stiffness correction from the T-derivative
  (raft_fowt.py:1182-1194) in closed form.

Supported round-1 scope: rigid members (single structural node each);
flexible beams to follow.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from raft_tpu.ops import transforms as tf
from raft_tpu.ops import frustum as fr


# ---------------------------------------------------------------- kinematics

def platform_kinematics(fs, Xi0):
    """Displaced node positions and platform rotation for a single-rigid-body
    FOWT (nonlinear rigid kinematics; raft_fowt.py:669-752).

    Returns (r_nodes (N,3), R_ptfm (3,3), r_root (3,)).
    """
    Xi0 = jnp.asarray(Xi0)
    R = tf.rotation_matrix(Xi0[3], Xi0[4], Xi0[5])
    r0 = jnp.asarray(fs.node_r0)
    r_root0 = r0[fs.root_id]
    d = r0 - r_root0
    r_nodes = r0 + Xi0[:3] + (d @ R.T - d)  # (R - I) @ d, batched
    return r_nodes, R, r_nodes[fs.root_id]


def node_T(r_nodes, r_root):
    """Per-node reduction matrix [[I, H(d)],[0, I]], d = r_n - r_root.

    Matches the assembled T of topology.reduce for a single rigid body
    (chained H blocks are additive)."""
    d = r_nodes - r_root
    H = tf.skew(d)
    N = d.shape[0]
    I3 = jnp.broadcast_to(jnp.eye(3, dtype=H.dtype), (N, 3, 3))
    Z3 = jnp.zeros_like(I3)
    top = jnp.concatenate([I3, H], axis=-1)
    bot = jnp.concatenate([Z3, I3], axis=-1)
    return jnp.concatenate([top, bot], axis=-2)


# ------------------------------------------------------------ member pieces

def member_inertia(mem, R_mem, q):
    """Member mass matrix (6x6 about its node), weight & weight-stiffness.

    Uses the precomputed inertia elements (mass, axial CG offset s,
    local principal MoI); raft_member.py:412-541 + getWeight :1179-1181.

    Returns (M6, W6, C6, mass, s_bar) with s_bar the axial CG offset.
    """
    m_e = jnp.asarray(mem.elem_mass)
    s_e = jnp.asarray(mem.elem_s)
    I_loc = jnp.zeros((len(mem.elem_mass), 3, 3))
    I_loc = I_loc.at[:, 0, 0].set(jnp.asarray(mem.elem_Ixx))
    I_loc = I_loc.at[:, 1, 1].set(jnp.asarray(mem.elem_Iyy))
    I_loc = I_loc.at[:, 2, 2].set(jnp.asarray(mem.elem_Izz))
    I_rot = R_mem @ I_loc @ R_mem.T  # (ne,3,3)

    M6_e = jnp.zeros((len(mem.elem_mass), 6, 6))
    M6_e = M6_e.at[:, 0, 0].set(m_e)
    M6_e = M6_e.at[:, 1, 1].set(m_e)
    M6_e = M6_e.at[:, 2, 2].set(m_e)
    M6_e = M6_e.at[:, 3:, 3:].set(I_rot)

    r_e = q[None, :] * s_e[:, None]  # element CG relative to member node
    M6 = jnp.sum(tf.translate_matrix_6to6(M6_e, r_e), axis=0)

    mass = jnp.sum(m_e)
    s_bar = jnp.where(mass > 0, jnp.sum(m_e * s_e) / jnp.where(mass > 0, mass, 1.0), 0.0)
    return M6, mass, s_bar, M6_e  # W/C computed by caller with g


def member_hydrostatics(mem, q, p1, p2, R_mem, r_node, rho, g):
    """Buoyancy force/stiffness of one rigid member about its node.

    raft_member.py:838-1156 (rigid branch), vectorised over sections
    with crossing/submerged where-masks.

    Returns dict with Fvec(6), Cmat(6,6), V_UW, r_centerV(3 — global
    center*V sum), AWP, IWP, xWP, yWP (last-crossing values, member
    convention), where positions are global.
    """
    st = jnp.asarray(mem.stations)
    n = len(mem.stations)
    circ = mem.circular

    beta = jnp.arctan2(q[1], q[0])
    phi = jnp.arctan2(jnp.sqrt(q[0] ** 2 + q[1] ** 2), q[2])
    cosPhi, sinPhi, tanPhi = jnp.cos(phi), jnp.sin(phi), jnp.tan(phi)
    cosBeta, sinBeta = jnp.cos(beta), jnp.sin(beta)

    Fvec = jnp.zeros(6)
    Cmat = jnp.zeros((6, 6))
    V_UW = jnp.asarray(0.0)
    r_centerV = jnp.zeros(3)
    AWP = jnp.asarray(0.0)
    IWP = jnp.asarray(0.0)
    xWPr = jnp.asarray(0.0)
    yWPr = jnp.asarray(0.0)

    for i in range(1, n):
        rA = r_node + q * st[i - 1]
        rB = r_node + q * st[i]
        crossing = rA[2] * rB[2] <= 0
        submerged = (~crossing) & (rA[2] <= 0) & (rB[2] <= 0)

        dz = rB[2] - rA[2]
        dz_safe = jnp.where(dz == 0, 1.0, dz)
        frac0 = (0.0 - rA[2]) / dz_safe  # waterplane crossing fraction

        # geometry at the waterplane — NOTE the reference interpolates
        # with the diameter endpoints swapped (raft_member.py:902,908);
        # reproduced verbatim for parity.
        if circ:
            dA_o, dB_o = mem.d[i - 1, 0], mem.d[i, 0]
            dWP = dB_o + frac0 * (dA_o - dB_o)
            AWP_i = 0.25 * jnp.pi * dWP**2
            IWP_i = (jnp.pi / 64.0) * dWP**4
            IxWP_i = IWP_i
            IyWP_i = IWP_i
        else:
            slA_o = jnp.asarray(mem.d[i - 1])
            slB_o = jnp.asarray(mem.d[i])
            slWP = slB_o + frac0 * (slA_o - slB_o)
            AWP_i = slWP[0] * slWP[1]
            Ix_loc = (1.0 / 12.0) * slWP[0] * slWP[1] ** 3
            Iy_loc = (1.0 / 12.0) * slWP[0] ** 3 * slWP[1]
            I_loc = jnp.diag(jnp.stack([Ix_loc, Iy_loc, jnp.asarray(0.0)]))
            I_rot = R_mem @ I_loc @ R_mem.T
            IxWP_i = I_rot[0, 0]
            IyWP_i = I_rot[1, 1]
            IWP_i = Ix_loc  # reference returns the scalar IWP only for circ;
            # for rect it returns the pre-loop IWP (stays 0/prev) — see below

        cosPhi_safe = jnp.where(cosPhi == 0, 1.0, cosPhi)
        LWP = jnp.abs(rA[2] / cosPhi_safe)

        # ---- crossing branch (partially submerged) raft_member.py:895-977
        if circ:
            V_c, hc_c = fr.frustum_vcv_circ(mem.d[i - 1, 0], dWP, LWP)
        else:
            V_c, hc_c = fr.frustum_vcv_rect(jnp.asarray(mem.d[i - 1]), slWP, LWP)
        r_center_c = rA + q * hc_c

        Fz_c = rho * g * V_c
        if circ:
            M_c = -rho * g * jnp.pi * (
                dWP**2 / 32.0 * (2.0 + tanPhi**2) + 0.5 * (rA[2] / cosPhi_safe) ** 2
            ) * sinPhi
        else:
            M_c = jnp.asarray(0.0)

        F_c = tf.translate_force_3to6(jnp.stack([0.0 * Fz_c, 0.0 * Fz_c, Fz_c]), rA - r_node)
        F_c = F_c.at[3].add(M_c * (-sinBeta))
        F_c = F_c.at[4].add(M_c * cosBeta)

        xWP_c = rA[0] + frac0 * (rB[0] - rA[0]) - r_node[0]
        yWP_c = rA[1] + frac0 * (rB[1] - rA[1]) - r_node[1]
        r_rel_c = r_center_c - r_node
        C_c = jnp.zeros((6, 6))
        C_c = C_c.at[2, 2].add(rho * g * AWP_i / cosPhi_safe)
        C_c = C_c.at[2, 3].add(rho * g * (-AWP_i * yWP_c))
        C_c = C_c.at[2, 4].add(rho * g * (AWP_i * xWP_c))
        C_c = C_c.at[3, 2].add(rho * g * (-AWP_i * yWP_c))
        C_c = C_c.at[3, 3].add(rho * g * (IxWP_i + AWP_i * yWP_c**2))
        C_c = C_c.at[3, 4].add(rho * g * (AWP_i * xWP_c * yWP_c))
        C_c = C_c.at[4, 2].add(rho * g * (AWP_i * xWP_c))
        C_c = C_c.at[4, 3].add(rho * g * (AWP_i * xWP_c * yWP_c))
        C_c = C_c.at[4, 4].add(rho * g * (IyWP_i + AWP_i * xWP_c**2))
        C_c = C_c.at[3, 3].add(rho * g * V_c * r_rel_c[2])
        C_c = C_c.at[4, 4].add(rho * g * V_c * r_rel_c[2])
        C_c = C_c.at[3, 5].add(-rho * g * V_c * r_rel_c[0])
        C_c = C_c.at[4, 5].add(-rho * g * V_c * r_rel_c[1])

        # ---- fully submerged branch raft_member.py:979-1001
        if circ:
            V_s, hc_s = fr.frustum_vcv_circ(mem.d[i - 1, 0], mem.d[i, 0], st[i] - st[i - 1])
        else:
            V_s, hc_s = fr.frustum_vcv_rect(
                jnp.asarray(mem.d[i - 1]), jnp.asarray(mem.d[i]), st[i] - st[i - 1]
            )
        r_center_s = rA + q * hc_s
        r_rel_s = r_center_s - r_node
        F_s = tf.translate_force_3to6(
            jnp.stack([0.0 * V_s, 0.0 * V_s, rho * g * V_s]), r_rel_s
        )
        C_s = jnp.zeros((6, 6))
        C_s = C_s.at[3, 3].add(rho * g * V_s * r_rel_s[2])
        C_s = C_s.at[4, 4].add(rho * g * V_s * r_rel_s[2])
        C_s = C_s.at[3, 5].add(-rho * g * V_s * r_rel_s[0])
        C_s = C_s.at[4, 5].add(-rho * g * V_s * r_rel_s[1])

        # ---- select by mask and accumulate
        c = crossing
        s = submerged
        Fvec = Fvec + jnp.where(c, F_c, 0.0) + jnp.where(s, F_s, 0.0)
        Cmat = Cmat + jnp.where(c, C_c, 0.0) + jnp.where(s, C_s, 0.0)
        V_i = jnp.where(c, V_c, jnp.where(s, V_s, 0.0))
        r_center_i = jnp.where(c, r_center_c, jnp.where(s, r_center_s, 0.0))
        V_UW = V_UW + V_i
        r_centerV = r_centerV + r_center_i * V_i
        # member-level waterplane values keep the LAST crossing section
        AWP = jnp.where(c, AWP_i, AWP)
        if circ:
            IWP = jnp.where(c, IWP_i, IWP)
        xWPr = jnp.where(c, xWP_c + r_node[0], xWPr)  # global (pre -rRP value)
        yWPr = jnp.where(c, yWP_c + r_node[1], yWPr)

    return dict(
        Fvec=Fvec, Cmat=Cmat, V_UW=V_UW, r_centerV=r_centerV,
        AWP=AWP, IWP=IWP, xWP=xWPr, yWP=yWPr,
    )


# ------------------------------------------------------------ FOWT assembly

def calc_statics(fs, Xi0=None):
    """Full FOWT statics about the root node in reduced DOFs.

    Equivalent of FOWT.calcStatics (raft_fowt.py:811-1285) for rigid
    FOWTs.  Returns a dict of reduced matrices and summary properties.
    """
    rho, g = fs.rho_water, fs.g
    nDOF = fs.nDOF
    if not fs.is_single_body:
        # mixed rigid/flexible structures use the general numpy path,
        # with nonlinear rigid-link/beam kinematics at displaced poses
        # (see physics/statics_general.py)
        from raft_tpu.physics.statics_general import calc_statics_general

        return calc_statics_general(fs, Xi0=Xi0)
    if Xi0 is None:
        Xi0 = jnp.zeros(nDOF)

    r_nodes, R_ptfm, r_root = platform_kinematics(fs, Xi0)
    Tn = node_T(r_nodes, r_root)  # (N, 6, 6)

    # per-node 6x6 blocks / 6-vectors in full DOFs
    N = fs.n_nodes
    M_blocks = jnp.zeros((N, 6, 6))
    Msub_blocks = jnp.zeros((N, 6, 6))
    Cs_blocks = jnp.zeros((N, 6, 6))
    Cssub_blocks = jnp.zeros((N, 6, 6))
    Ch_blocks = jnp.zeros((N, 6, 6))
    W_blocks = jnp.zeros((N, 6))
    Wsub_blocks = jnp.zeros((N, 6))
    Wh_blocks = jnp.zeros((N, 6))
    f0_blocks = jnp.zeros((N, 6))

    m_center_sum = jnp.zeros(3)
    m_sub_sum = jnp.zeros(3)
    m_sub = jnp.asarray(0.0)
    VTOT = jnp.asarray(0.0)
    AWP_TOT = jnp.asarray(0.0)
    IWPx_TOT = jnp.asarray(0.0)
    IWPy_TOT = jnp.asarray(0.0)
    Sum_V_rCB = jnp.zeros(3)
    mtower = []
    rCG_tow = []

    # ---------------- members (inertia loop excludes nacelles,
    # raft_fowt.py:876-935; hydrostatics of members named 'nacelle'
    # added separately :1007-1030)
    for im, mem in enumerate(fs.members):
        node = int(fs.member_node[im])
        r_node = r_nodes[node]
        R_mem = R_ptfm @ jnp.asarray(mem.R0)
        q = R_ptfm @ jnp.asarray(mem.q0)
        p1 = R_ptfm @ jnp.asarray(mem.p10)
        p2 = R_ptfm @ jnp.asarray(mem.p20)

        if mem.part_of != "nacelle":
            M6, mass, s_bar, _ = member_inertia(mem, R_mem, q)
            W6, C6 = tf.weight_of_point_mass(mass, q * s_bar, g=g)
            M_blocks = M_blocks.at[node].add(M6)
            W_blocks = W_blocks.at[node].add(W6)
            Cs_blocks = Cs_blocks.at[node].add(C6)
            center = q * s_bar + jnp.asarray(fs.node_r0[node])  # ref: uses r0 (raft_fowt.py:900)
            m_center_sum = m_center_sum + center * mass
            if mem.part_of == "tower":
                mtower.append(mass)
                rCG_tow.append(center)
            else:
                Msub_blocks = Msub_blocks.at[node].add(M6)
                Cssub_blocks = Cssub_blocks.at[node].add(C6)
                Wsub_blocks = Wsub_blocks.at[node].add(W6)
                m_sub = m_sub + mass
                m_sub_sum = m_sub_sum + center * mass

            hs = member_hydrostatics(mem, q, p1, p2, R_mem, r_node, rho, g)
        elif mem.name == "nacelle":
            hs = member_hydrostatics(mem, q, p1, p2, R_mem, r_node, rho, g)
        else:
            continue

        Wh_blocks = Wh_blocks.at[node].add(hs["Fvec"])
        Ch_blocks = Ch_blocks.at[node].add(hs["Cmat"])
        # totals about the PRP (raft_fowt.py:926-935) — xWP/yWP made
        # global by adding the member's undisplaced node position
        xWP = hs["xWP"] - r_node[0] + jnp.asarray(fs.node_r0[node][0])
        yWP = hs["yWP"] - r_node[1] + jnp.asarray(fs.node_r0[node][1])
        VTOT = VTOT + hs["V_UW"]
        AWP_TOT = AWP_TOT + hs["AWP"]
        IWPx_TOT = IWPx_TOT + hs["IWP"] + hs["AWP"] * yWP**2
        IWPy_TOT = IWPy_TOT + hs["IWP"] + hs["AWP"] * xWP**2
        V = hs["V_UW"]
        rCB_m = jnp.where(
            V > 0, hs["r_centerV"] / jnp.where(V > 0, V, 1.0) - r_node, jnp.zeros(3)
        )
        Sum_V_rCB = Sum_V_rCB + (rCB_m + jnp.asarray(fs.node_r0[node])) * V

    # ---------------- RNA inertia (raft_fowt.py:1033-1052)
    for ir, rot in enumerate(fs.rotors):
        node = int(fs.rotor_node[ir])
        q_rot = R_ptfm @ jnp.asarray(rot.q_rel)
        R_q = jnp.asarray(rot.R_q0) @ R_ptfm  # reference order, raft_rotor.py:467
        Mmat = jnp.diag(jnp.asarray([rot.mRNA, rot.mRNA, rot.mRNA,
                                     rot.IxRNA, rot.IrRNA, rot.IrRNA]))
        Mmat = tf.rotate_matrix_6(Mmat, R_q)
        dCG = q_rot * rot.xCG_RNA  # r_CG_rel - r_RRP_rel
        W6, C6 = tf.weight_of_point_mass(rot.mRNA, dCG, g=g)
        W_blocks = W_blocks.at[node].add(W6)
        M_blocks = M_blocks.at[node].add(tf.translate_matrix_6to6(Mmat, dCG))
        Cs_blocks = Cs_blocks.at[node].add(C6)
        r_CG_rel = R_ptfm @ jnp.asarray(rot.r_rel) + dCG
        m_center_sum = m_center_sum + r_CG_rel * rot.mRNA

        # submerged rotor blade buoyancy (raft_fowt.py:937-1005)
        if rot.hydro is not None:
            Wh_blocks = Wh_blocks.at[node].add(jnp.asarray(rot.hydro["Fvec"]))
            Ch_blocks = Ch_blocks.at[node].add(jnp.asarray(rot.hydro["Cmat"]))
            V_rot = float(rot.hydro["V"])
            VTOT = VTOT + V_rot
            Sum_V_rCB = Sum_V_rCB + jnp.asarray(fs.node_r0[node]) * V_rot

    # ---------------- point inertias (raft_fowt.py:1054-1072)
    for pi in fs.pointInertias:
        node = int(
            np.argmin(np.linalg.norm(fs.node_r0 - np.asarray(pi["r"]), axis=1))
        )
        dR = jnp.asarray(pi["r"] - fs.node_r0[node])
        W6, C6 = tf.weight_of_point_mass(pi["m"], dR, g=g)
        M6 = tf.translate_matrix_6to6(jnp.asarray(pi["inertia"]), dR)
        W_blocks = W_blocks.at[node].add(W6)
        M_blocks = M_blocks.at[node].add(M6)
        Cs_blocks = Cs_blocks.at[node].add(C6)
        Msub_blocks = Msub_blocks.at[node].add(M6)
        Cssub_blocks = Cssub_blocks.at[node].add(C6)
        Wsub_blocks = Wsub_blocks.at[node].add(W6)
        m_sub = m_sub + pi["m"]
        m_sub_sum = m_sub_sum + jnp.asarray(pi["r"]) * pi["m"]
        m_center_sum = m_center_sum + jnp.asarray(pi["r"]) * pi["m"]

    # ---------------- user point loads (raft_fowt.py:1074-1080)
    for pl in fs.pointLoads:
        node = int(
            np.argmin(np.linalg.norm(fs.node_r0 - np.asarray(pl["r"]), axis=1))
        )
        f6 = tf.transform_force_6(jnp.asarray(pl["f"]),
                                  jnp.asarray(pl["r"] - fs.node_r0[node]))
        f0_blocks = f0_blocks.at[node].add(f6)

    # ---------------- reduce to the structure DOFs (raft_fowt.py:1118-1128)
    def reduce_mat(blocks):
        return jnp.einsum("nia,nij,njb->ab", Tn, blocks, Tn)

    def reduce_vec(blocks):
        return jnp.einsum("nia,ni->a", Tn, blocks)

    M_struc = reduce_mat(M_blocks)
    M_struc_sub = reduce_mat(Msub_blocks)
    C_struc = reduce_mat(Cs_blocks)
    C_struc_sub = reduce_mat(Cssub_blocks)
    C_hydro = reduce_mat(Ch_blocks)
    W_struc = reduce_vec(W_blocks)
    W_hydro = reduce_vec(Wh_blocks)
    f0_additional = reduce_vec(f0_blocks)

    # ---------------- geometric stiffness from dT (raft_fowt.py:1182-1194)
    # C_geom[3+i, 3+j] = -sum_n cross(cross(e_j, d_n), F_n)[i]
    d_n = r_nodes - r_root
    eye3 = jnp.eye(3)

    def c_geom(F_blocks):
        F = F_blocks[:, :3]
        cj = jnp.cross(eye3[None, :, :], d_n[:, None, :])     # (N, 3j, 3)
        contrib = jnp.cross(cj, F[:, None, :])                 # (N, 3j, 3i)
        block = -jnp.sum(contrib, axis=0).T                    # (3i, 3j)
        C = jnp.zeros((6, 6))
        return C.at[3:, 3:].set(block)

    C_hydro = C_hydro + c_geom(Wh_blocks)
    C_struc = C_struc + c_geom(W_blocks)
    C_struc_sub = C_struc_sub + c_geom(Wsub_blocks)

    # symmetrise (raft_fowt.py:1197-1204)
    sym = lambda A: 0.5 * (A + A.T)
    M_struc, M_struc_sub = sym(M_struc), sym(M_struc_sub)
    C_hydro, C_struc, C_struc_sub = sym(C_hydro), sym(C_struc), sym(C_struc_sub)

    # ---------------- totals (raft_fowt.py:1206-1285)
    m_all = M_struc[0, 0]
    rCG = m_center_sum / m_all
    rCG_sub = m_sub_sum / jnp.where(m_sub > 0, m_sub, 1.0)
    M_sub6 = tf.translate_matrix_6to6(M_struc_sub[:6, :6], -rCG_sub)
    M_all6 = tf.translate_matrix_6to6(M_struc[:6, :6], -rCG)

    rCB = Sum_V_rCB / jnp.where(VTOT > 0, VTOT, 1.0)
    zMeta = jnp.where(VTOT > 0, rCB[2] + IWPx_TOT / jnp.where(VTOT > 0, VTOT, 1.0), 0.0)

    # ballast bookkeeping (static; raft_fowt.py:1231-1242)
    pb = []
    for mem in fs.members:
        if mem.part_of == "nacelle":
            continue
        for p in mem.pfill:
            if p != 0 and p not in pb:
                pb.append(p)
    # accumulate as jax scalars: mfill may be traced (geometry axis)
    m_ballast_l = [jnp.asarray(0.0)] * len(pb)
    for mem in fs.members:
        if mem.part_of == "nacelle":
            continue
        for mf, p in zip(mem.mfill, mem.pfill):
            if p != 0:
                i = pb.index(p)
                m_ballast_l[i] = m_ballast_l[i] + mf
    m_ballast = jnp.stack(m_ballast_l) if pb else jnp.zeros(0)

    return dict(
        M_struc=M_struc,
        M_struc_sub=M_struc_sub,
        C_struc=C_struc,
        C_struc_sub=C_struc_sub,
        C_hydro=C_hydro,
        C_elast=jnp.zeros((nDOF, nDOF)),
        W_struc=W_struc,
        W_hydro=W_hydro,
        f0_additional=f0_additional,
        rCG=rCG,
        rCG_sub=rCG_sub,
        rCB=rCB,
        m=m_all,
        m_sub=m_sub,
        V=VTOT,
        AWP=AWP_TOT,
        rM=jnp.stack([rCB[0], rCB[1], zMeta]),
        m_ballast=m_ballast,
        pb=pb,
        mtower=mtower,
        rCG_tow=rCG_tow,
        M_all6=M_all6,
        M_sub6=M_sub6,
        r_nodes=r_nodes,
        R_ptfm=R_ptfm,
        Tn=Tn,
    )
