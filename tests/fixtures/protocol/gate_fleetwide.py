"""Seeded protocol bug: the pre-PR-16 rollout-gate fleet-wide-pass race.

Before the gate was keyed to the replaced replica's post-seize
observation run, it counted *fleet-wide* fresh canary passes: probes
of neighbor replicas (or of the candidate at its pre-seize endpoint)
could satisfy ``need`` and turn the gate green before the upgraded
process had ever answered a probe.

The model checker must catch this through the gate-candidate-probed
invariant in the rollout-gate scenario.  ``python -m raft_tpu.analysis
protocol check --fixture <this file>`` must exit 1.
"""


def gate_decision(payload, baseline, need, replica=None, endpoint=None):
    # the historical gate: replica/endpoint accepted but IGNORED —
    # any fresh pass anywhere in the fleet counts toward `need`.
    can = (payload or {}).get("canary")
    if not can:
        return "pending", "no-canary"
    fails = int(can.get("fails") or 0) - baseline["fails"]
    if fails > 0:
        return "red", "canary-fail"
    if not can.get("parity_ok", True):
        return "red", "canary-parity"
    active = (payload or {}).get("active") or []
    if active:
        names = sorted(a.get("rule") or "?" for a in active)
        return "red", "alert:" + ",".join(names)
    fresh = int(can.get("passes") or 0) - baseline["passes"]
    if fresh >= need:
        return "green", f"canary-green({fresh})"
    return "pending", "waiting"


PATCHES = {
    "raft_tpu.serve.rollout:gate_decision": gate_decision,
}

SCENARIOS = ("rollout-gate",)
