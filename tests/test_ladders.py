"""Cost-driven serve batch ladder + adaptive tick (ROADMAP item 5a/5b).

Socket-free and COMPILE-free: the batcher runs against a faked
``engine.dispatch`` and a hand-built design entry, so these cover the
scheduling/ladder logic (window scaling, full-rung early dispatch,
rung pruning, stage-sum accounting) without building a model or
touching XLA.  The real-dispatch twins live in tests/test_serve.py
and the serve bench.
"""

import json
import os
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _obs_helpers import read_events  # noqa: E402

from raft_tpu.parallel.sweep import make_mesh  # noqa: E402
from raft_tpu.serve import engine  # noqa: E402
from raft_tpu.serve.batcher import Batcher  # noqa: E402
from raft_tpu.serve.cache import ResultCache  # noqa: E402
from raft_tpu.serve.quota import ClientQuotas  # noqa: E402


# ------------------------------------------------------------ ladder math

def test_batch_ladder_policies():
    mesh = make_mesh(1)
    # 'cost' and 'pow2' share the candidate generator (pruning is a
    # separate post-warmup step)
    assert engine.batch_ladder(mesh, 8, policy="pow2") == (1, 2, 4, 8)
    assert engine.batch_ladder(mesh, 8, policy="cost") == (1, 2, 4, 8)
    # explicit rung lists are used verbatim
    assert engine.batch_ladder(mesh, 64, policy="1,4,16,64") == (1, 4, 16, 64)
    for bad in ("4,2", "0,4", "a,b", ""):
        with pytest.raises(ValueError):
            engine.batch_ladder(mesh, 64, policy=bad)


def test_prune_ladder_drops_flat_rungs():
    sizes = (1, 2, 4, 8)
    # walls flat through 1/2/4 (fixed dispatch-overhead floor), real
    # growth only at 8: the flat rungs buy nothing -> pruned
    walls = {1: 0.010, 2: 0.0101, 4: 0.011, 8: 0.020}
    assert engine.prune_ladder(sizes, walls, tol=1.15) == (4, 8)
    # strictly scaling walls (compute-bound): every rung saves time
    walls = {1: 0.01, 2: 0.02, 4: 0.04, 8: 0.08}
    assert engine.prune_ladder(sizes, walls, tol=1.15) == sizes
    # missing measurements are kept, never pruned on ignorance
    assert engine.prune_ladder(sizes, {}, tol=1.15) == sizes
    # the top rung (the tick's chunk cap) always survives
    assert engine.prune_ladder(sizes, {s: 0.01 for s in sizes},
                               tol=1.15) == (8,)


def test_refine_ladder_policies(monkeypatch):
    mesh = make_mesh(1)
    sizes = (1, 2, 4, 8)
    # non-cost policies come back unchanged without measuring anything
    monkeypatch.setenv("RAFT_TPU_SERVE_LADDER", "pow2")
    assert engine.refine_ladder([], sizes, mesh=mesh) == sizes
    # cost policy prunes per measured walls (stubbed here; the real
    # walls come from the AOT cost ledger after warmup)
    monkeypatch.setenv("RAFT_TPU_SERVE_LADDER", "cost")
    monkeypatch.setattr(engine, "ladder_walls",
                        lambda *a, **k: {1: 0.010, 2: 0.0101,
                                         4: 0.011, 8: 0.020})
    assert engine.refine_ladder([], sizes, mesh=mesh) == (4, 8)
    # no measurements (e.g. RAFT_TPU_AOT=off): candidates unchanged
    monkeypatch.setattr(engine, "ladder_walls", lambda *a, **k: {})
    assert engine.refine_ladder([], sizes, mesh=mesh) == sizes


# ------------------------------------------------ faked-dispatch batcher

def _toy_entry(sig="toy-sig", fingerprint="toy-fp"):
    e = object.__new__(engine.DesignEntry)
    e.name = "toy"
    e.model = None
    e.sig = sig
    e.packed = {}
    e.fingerprint = fingerprint
    e.axes = {"strips": (4, 8), "nodes": (2, 2), "lines": (0, 0)}
    return e


class _ToyRegistry:
    def __init__(self, entry):
        self._entry = entry

    def get(self, name):
        return self._entry

    def names(self):
        return ["toy"]


def _fake_dispatch(solve_sleep_s=0.0):
    def dispatch(entries, Hs, Tp, beta, out_keys=("PSD", "X0", "status"),
                 mesh=None, padded=None, record_metrics=True,
                 timings=None):
        if solve_sleep_s:
            time.sleep(solve_sleep_s)
        n = len(entries)
        out = {}
        for k in out_keys:
            if k == "status":
                out[k] = np.zeros(n, dtype=np.int32)
            else:
                out[k] = np.stack([np.full(3, h) for h in Hs])
        if timings is not None:
            timings["solve_s"] = solve_sleep_s
        return out

    return dispatch


def _make_batcher(monkeypatch, tick_ms=200.0, max_batch=4,
                  solve_sleep_s=0.0, mode=None, floor_ms=None):
    if mode is not None:
        monkeypatch.setenv("RAFT_TPU_SERVE_TICK_MODE", mode)
    if floor_ms is not None:
        monkeypatch.setenv("RAFT_TPU_SERVE_TICK_MIN_MS", str(floor_ms))
    monkeypatch.setattr(engine, "dispatch", _fake_dispatch(solve_sleep_s))
    entry = _toy_entry()
    b = Batcher(_ToyRegistry(entry), out_keys=("PSD", "status"),
                mesh=make_mesh(1), tick_ms=tick_ms, max_batch=max_batch,
                cache=ResultCache(10**6, metrics_prefix="test_ladders"),
                quotas=ClientQuotas(rate=0.0, burst=1.0), queue_bound=64)
    return b, entry


def test_adaptive_wake_window(monkeypatch):
    b, entry = _make_batcher(monkeypatch, tick_ms=200.0, floor_ms=2.0)
    t0 = time.perf_counter()
    # idle queue parks on the ceiling
    with b._cond:
        assert b._wake_in(t0) == pytest.approx(0.2, abs=0.05)
    # one pending request + zero load EMA: the window is ~the floor,
    # anchored on the request's submit time
    b.submit(entry, 4.0, 9.0, 0.0)
    with b._cond:
        assert b._wake_in(t0) < 0.01
    # a full top ladder rung dispatches NOW
    for i in range(b.sizes[-1]):
        b.submit(entry, 5.0 + i, 9.0, 0.0)
    with b._cond:
        assert b._wake_in(t0) == 0.0
    b.run_tick()
    # sustained load (EMA ~ top rung) widens the window to the ceiling
    with b._cond:
        b._load_ema = float(b.sizes[-1])
        b._first_pending_t = time.perf_counter()
        b._pending.append(object())  # sentinel: non-empty queue
        w = b._wake_in(time.perf_counter())
        b._pending.pop()
    assert w == pytest.approx(0.2, abs=0.05)


def test_full_rung_trigger_counts_unique_rows(monkeypatch):
    """A same-case burst dedups to ONE dispatched row, so it must NOT
    fire the full-rung early dispatch (that would collapse the
    coalescing window for a 1-row batch)."""
    b, entry = _make_batcher(monkeypatch, tick_ms=200.0, floor_ms=50.0)
    t0 = time.perf_counter()
    for _ in range(b.sizes[-1] + 2):      # duplicates of one corner
        b.submit(entry, 4.0, 9.0, 0.0)
    with b._cond:
        assert b._wake_in(t0) > 0.0       # window intact
    b.submit(entry, 99.0, 9.0, 0.0)       # distinct rows DO count
    for i in range(b.sizes[-1] - 3):      # the dup corner is 1 unique
        b.submit(entry, 50.0 + i, 9.0, 0.0)
    with b._cond:
        assert b._wake_in(t0) > 0.0       # one short of the rung
    b.submit(entry, 98.0, 9.0, 0.0)
    with b._cond:
        assert b._wake_in(t0) == 0.0      # full rung of UNIQUE rows
    b.run_tick()


def test_fixed_mode_keeps_cadence(monkeypatch):
    b, entry = _make_batcher(monkeypatch, tick_ms=100.0, mode="fixed")
    assert b.tick_mode == "fixed"
    t0 = time.perf_counter()
    b.submit(entry, 4.0, 9.0, 0.0)
    with b._cond:
        # pending or not, fixed mode sleeps out the cadence
        assert b._wake_in(t0) == pytest.approx(0.1, abs=0.03)
    b.run_tick()


def test_adaptive_thread_light_load_latency(monkeypatch):
    """A lone request against an idle adaptive batcher resolves in ~the
    tick floor, not the (deliberately huge) tick ceiling — the
    light-load acceptance mechanic."""
    b, entry = _make_batcher(monkeypatch, tick_ms=500.0, floor_ms=2.0)
    b.start()
    try:
        t0 = time.perf_counter()
        fut = b.submit(entry, 4.0, 9.0, 0.0)
        res = fut.result(timeout=10)
        wall = time.perf_counter() - t0
        assert res["status"] == 0
        # floor(2ms) + scheduling slack << the 500ms ceiling
        assert wall < 0.25
    finally:
        b.drain(timeout=10)


def test_full_rung_early_dispatch_thread(monkeypatch):
    """A burst filling the top ladder rung dispatches without waiting
    out the window."""
    b, entry = _make_batcher(monkeypatch, tick_ms=500.0, floor_ms=400.0)
    b.start()
    try:
        t0 = time.perf_counter()
        futs = [b.submit(entry, 4.0 + 0.1 * i, 9.0, 0.0)
                for i in range(b.sizes[-1])]
        for f in futs:
            f.result(timeout=10)
        # the 400ms floor window would apply to a PARTIAL batch; a full
        # rung must go out immediately
        assert time.perf_counter() - t0 < 0.3
    finally:
        b.drain(timeout=10)


def test_stage_sum_invariant_at_p50_and_p95(monkeypatch, tmp_path):
    """Adaptive-tick tail attribution: every resolved request's stage
    decomposition sums to its measured wall — asserted at the p50 and
    p95 latency ranks specifically (the report's stage table is the
    per-request breakdown AT those ranks)."""
    log = str(tmp_path / "ev.jsonl")
    monkeypatch.setenv("RAFT_TPU_LOG", log)
    b, entry = _make_batcher(monkeypatch, tick_ms=50.0,
                             solve_sleep_s=0.002)
    futs = [b.submit(entry, 3.0 + 0.01 * i, 9.0, 0.0) for i in range(12)]
    b.run_tick()
    for f in futs:
        f.result(timeout=10)
    evs = [e for e in read_events(log)
           if e["event"] == "serve_request_stages"]
    assert len(evs) == 12
    stages = ("queue_wait_s", "tick_wait_s", "dispatch_s", "solve_s",
              "post_s")
    by_wall = sorted(evs, key=lambda e: e["wall_s"])
    for rank in (len(evs) // 2, int(len(evs) * 0.95)):
        e = by_wall[min(rank, len(evs) - 1)]
        # stage values are rounded to 1e-6 in the event payload
        assert sum(e[s] for s in stages) == pytest.approx(
            e["wall_s"], abs=5e-5)
    for e in evs:  # and the invariant holds for every request
        assert sum(e[s] for s in stages) == pytest.approx(
            e["wall_s"], abs=5e-5)


def test_set_sizes_swaps_ladder(monkeypatch):
    b, entry = _make_batcher(monkeypatch, max_batch=8)
    assert b.sizes == (1, 2, 4, 8)
    assert b.set_sizes((4, 8)) == (4, 8)
    assert b.stats()["batch_sizes"] == [4, 8]
    with pytest.raises(ValueError):
        b.set_sizes(())


def test_cross_tick_inflight_join(monkeypatch):
    """A duplicate case submitted while its row is MID-SOLVE joins the
    solving tick instead of queueing a redundant dispatch; later
    submits hit the cache."""
    import threading

    from raft_tpu.obs import metrics

    gate = threading.Event()
    dispatched = []

    def blocking_dispatch(entries, Hs, Tp, beta, out_keys=("PSD", "status"),
                          mesh=None, padded=None, record_metrics=True,
                          timings=None):
        dispatched.append(len(entries))
        gate.wait(timeout=10)
        n = len(entries)
        out = {"PSD": np.stack([np.full(3, h) for h in Hs]),
               "status": np.zeros(n, dtype=np.int32)}
        if timings is not None:
            timings["solve_s"] = 0.0
        return out

    monkeypatch.setattr(engine, "dispatch", _fake_dispatch())
    entry = _toy_entry()
    b = Batcher(_ToyRegistry(entry), out_keys=("PSD", "status"),
                mesh=make_mesh(1), tick_ms=50, max_batch=4,
                cache=ResultCache(10**6, metrics_prefix="test_join"),
                quotas=ClientQuotas(rate=0.0, burst=1.0), queue_bound=64)
    monkeypatch.setattr(engine, "dispatch", blocking_dispatch)
    f1 = b.submit(entry, 4.0, 9.0, 0.0)
    t = threading.Thread(target=b.run_tick, daemon=True, name="tick")
    t.start()
    for _ in range(100):          # wait until the dispatch is in flight
        if dispatched:
            break
        time.sleep(0.01)
    assert dispatched == [1]
    j0 = metrics.counter("serve_inflight_joins").value
    f2 = b.submit(entry, 4.0, 9.0, 0.0)   # duplicate, mid-solve: joins
    assert metrics.counter("serve_inflight_joins").value == j0 + 1
    assert len(b._pending) == 0           # never queued a second row
    gate.set()
    t.join(timeout=10)
    r1, r2 = f1.result(timeout=10), f2.result(timeout=10)
    assert not r1["cache_hit"] and not r2["cache_hit"]
    np.testing.assert_array_equal(r1["outputs"]["PSD"],
                                  r2["outputs"]["PSD"])
    assert dispatched == [1]              # ONE dispatch served both
    # the row is cached now: a third submit resolves without queueing
    f3 = b.submit(entry, 4.0, 9.0, 0.0)
    assert f3.result(timeout=1)["cache_hit"]
    assert b.stats()["inflight_rows"] == 0


# -------------------------------------------------- fused-path plumbing

def test_fused_flag_in_memo_key(monkeypatch):
    from raft_tpu.models.dynamics import fused_response_enabled
    from raft_tpu.parallel.sweep import _flags_key

    monkeypatch.delenv("RAFT_TPU_FUSED", raising=False)
    assert fused_response_enabled()
    k_on = _flags_key()
    monkeypatch.setenv("RAFT_TPU_FUSED", "off")
    assert not fused_response_enabled()
    k_off = _flags_key()
    # the fused/staged programs must never share a memo/bank key
    assert k_on != k_off and "on" in k_on and "off" in k_off
