"""Seeded negatives for the ``atomic-write`` concurrency rule."""

import json

import numpy as np


def write_ledger_record(path, rec):
    with open(path, "w") as f:          # torn-readable plain write
        json.dump(rec, f)


def save_shard(path, arr):
    np.save(path, arr)                  # non-atomic array checkpoint


def rewrite_binary(path, payload):
    f = open(path, "wb")                # same class, expression form
    f.write(payload)
    f.close()
