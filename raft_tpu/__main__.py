"""CLI: ``python -m raft_tpu design.yaml [--csv out.csv]``."""

import argparse


def main():
    p = argparse.ArgumentParser(
        description="raft_tpu: TPU-native frequency-domain FOWT analysis")
    p.add_argument("design", help="design YAML (RAFT-compatible schema)")
    p.add_argument("--csv", default=None, help="write channel statistics CSV")
    args = p.parse_args()

    from raft_tpu.drivers import run

    model = run(args.design, save_csv=args.csv)
    for iCase, per_fowt in model.results["case_metrics"].items():
        for ifowt, m in per_fowt.items():
            print(f"case {iCase} fowt {ifowt}: "
                  f"surge {float(m['surge_avg']):+.2f}±{float(m['surge_std']):.2f} m, "
                  f"heave {float(m['heave_avg']):+.2f}±{float(m['heave_std']):.2f} m, "
                  f"pitch {float(m['pitch_avg']):+.2f}±{float(m['pitch_std']):.2f} deg")


if __name__ == "__main__":
    main()
