"""Seeded negatives for the ``async-blocking`` concurrency rule
(analyzed with rules forced on, as if it lived under serve/)."""

import asyncio
import subprocess
import time


async def blocking_tick(lock, fut, thread):
    time.sleep(0.1)                     # host sleep on the loop
    subprocess.run(["true"])            # subprocess on the loop
    open("/tmp/raft_fixture", "w")      # blocking file IO  # raft-lint: disable=atomic-write
    fut.result()                        # blocks until resolution
    thread.join()                       # blocks until thread exit
    lock.acquire()                      # unbounded lock wait


def _blocking_helper():
    time.sleep(1.0)


async def transitive():
    _blocking_helper()                  # taints through the sync helper


async def clean(lock, loop, fn, reader):
    await asyncio.sleep(0)              # loop-native sleep: fine
    lock.acquire(timeout=1.0)           # bounded wait: fine
    if lock.acquire(False):             # non-blocking probe: fine
        lock.release()
    ",".join(["a", "b"])                # str.join, not Thread.join
    await loop.run_in_executor(None, _blocking_helper)  # pushed off-loop
    await reader.readline()
