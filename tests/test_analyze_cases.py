"""End-to-end analyzeCases parity vs reference goldens.

Exercises the full chain: statics -> mooring equilibrium -> (aero-servo
constants) -> wave excitation -> iterative drag linearisation ->
impedance solve -> response statistics, against
*_true_analyzeCases.pkl.

Tolerances: the no-wind case matches at golden tolerance (1e-5); the
WIND case carries the ~1% BEMT-vs-CCBlade load/derivative deviation
through the aero damping and mean thrust, so motion PSDs are gated at
1.5e-2 relative to the spectral peak.

Known golden anomalies (measured, documented rather than hidden):

* The OC3 wind-case ``Tmoor_PSD`` golden has high-frequency content
  that cannot be reproduced from the reference's own documented
  moorMod-0 algorithm (tension Jacobian x motion amplitudes,
  raft_fowt.py:2364-2368) using the golden's own stored motion RAs —
  we match those RAs to 0.5% and the mean tensions to 1e-4, yet the
  slack-line tension std differs ~30%, with the discrepancy growing
  with frequency like a line-inertia term.  Tension spectra are
  therefore gated loosely for the wind case.
* RESOLVED (round 4): the VolturnUS-S goldens' ~1.2e5 N mean surge
  force in the no-wind case is the slender-body-QTF mean drift fed back
  into the equilibrium — the reference re-runs solveStatics with
  Fhydro_2nd_mean for ANY potSecOrder > 0 (raft_model.py:316-328), and
  with the same feedback our means match at ~1%
  (test_analyze_cases_volturn_meandrift).  The VolturnUS WIND case
  remains off in the low-frequency 2nd-order band (motion-dependent
  QTF terms with wind-included RAOs; deviations up to ~0.9 of the tiny
  yaw channel) and stays out of the gated set for now.
"""

import os
import pickle

import numpy as np
import pytest
from numpy.testing import assert_allclose

from tests.conftest import ref_data

import raft_tpu

pytestmark = pytest.mark.slow

METRICS = [
    "wave_PSD", "surge_PSD", "sway_PSD", "heave_PSD", "roll_PSD",
    "pitch_PSD", "yaw_PSD", "AxRNA_PSD", "Mbase_PSD", "Tmoor_PSD",
]


def test_analyze_cases_oc3_nowind():
    path = ref_data("OC3spar.yaml")
    if not os.path.exists(path):
        pytest.skip("reference data unavailable")
    model = raft_tpu.Model(path)
    res = model.analyze_cases()
    with open(path.replace(".yaml", "_true_analyzeCases.pkl"), "rb") as f:
        true = pickle.load(f)

    # case 0 has wind_speed == 0 (no aero); golden-tolerance parity
    iCase = 0
    assert model.cases[iCase]["wind_speed"] == 0
    for metric in METRICS:
        a = np.asarray(res["case_metrics"][iCase][0][metric])
        b = np.asarray(true["case_metrics"][iCase][0][metric])
        if metric == "Tmoor_PSD":
            # the reference's tension spectra inherit MoorPy's coarse
            # 0.1-step finite-difference tension Jacobian (including a
            # 0.1 *rad* rotational step); we replicate the secant but
            # small catenary-model differences remain visible at ~3e-5
            assert_allclose(a, b, rtol=3e-5, atol=1e-3, err_msg=metric)
        else:
            assert_allclose(a, b, rtol=1e-5, atol=1e-3, err_msg=metric)

    # ---- WIND case (case 1, 10 m/s operating): full aero-servo chain.
    iCase = 1
    assert model.cases[iCase]["wind_speed"] > 0
    mc = res["case_metrics"][iCase][0]
    gc = true["case_metrics"][iCase][0]
    # mean offsets carry the mean rotor thrust through the equilibrium;
    # gate covers the reference's own 0.05 m solveStatics tolerance on
    # the ~28 m offset (turbine constants at the case-start zero pose,
    # raft_model.py:602, shift the converged mean by ~7 mm)
    assert_allclose(float(np.asarray(mc["surge_avg"])),
                    float(np.asarray(gc["surge_avg"])), rtol=2e-3)
    assert_allclose(float(np.asarray(mc["pitch_avg"])),
                    float(np.asarray(gc["pitch_avg"])), rtol=2e-3)
    # motion spectra: aero damping folds the ~1% BEMT derivative
    # deviation into the response peaks
    for metric in ("wave_PSD", "surge_PSD", "heave_PSD", "pitch_PSD",
                   "yaw_PSD", "AxRNA_PSD", "Mbase_PSD"):
        a = np.asarray(mc[metric])
        b = np.asarray(gc[metric])
        scale = np.max(np.abs(b)) + 1e-12
        assert np.max(np.abs(a - b)) / scale < 1.5e-2, metric
    # mean tensions at the wind-loaded offset
    assert_allclose(np.asarray(mc["Tmoor_avg"]), np.asarray(gc["Tmoor_avg"]),
                    rtol=1e-3)
    # tension spectra: loose gate only (see module docstring)
    a = np.asarray(mc["Tmoor_PSD"])
    b = np.asarray(gc["Tmoor_PSD"])
    assert np.max(np.abs(a - b)) / (np.max(np.abs(b)) + 1e-12) < 0.5


def test_analyze_cases_flexible_wind():
    """VolturnUS-S-flexible analyzeCases parity — BOTH cases, including
    the 10 m/s operating-turbine case through the aero-servo chain on a
    flexible-tower (multibody) model.

    Measured deviations (f64 CPU): case 0 motion PSDs ~2e-10 (golden
    level), Tmoor 1.2e-4; case 1 motion PSDs 4-5e-3 (the ~1% BEMT
    derivative deviation through the aero damping), AxRNA 1.1e-2,
    Tmoor 2e-2.  Gates at ~1.5x measured.  Mbase (FE tower-base moment)
    is gated loosely: the load recovery -Kf @ Xi is a near-cancellation
    that amplifies the small flexible-DOF response deviations (case 0
    3.4e-2 with motions at 1e-10; case 1 ~0.53 via the wind-band
    flexible response — the aero damping's effect on the tower-mode
    rows, invisible in the platform-motion channels).
    """
    path = ref_data("VolturnUS-S-flexible.yaml")
    if not os.path.exists(path):
        pytest.skip("reference data unavailable")
    model = raft_tpu.Model(path)
    res = model.analyze_cases()
    with open(path.replace(".yaml", "_true_analyzeCases.pkl"), "rb") as f:
        true = pickle.load(f)

    mc = res["case_metrics"][0][0]
    gc = true["case_metrics"][0][0]
    for metric in ("surge_PSD", "heave_PSD", "pitch_PSD", "yaw_PSD",
                   "AxRNA_PSD"):
        a, b = np.asarray(mc[metric]), np.asarray(gc[metric])
        assert np.max(np.abs(a - b)) / (np.max(np.abs(b)) + 1e-12) < 1e-8, metric
    a, b = np.asarray(mc["Tmoor_PSD"]), np.asarray(gc["Tmoor_PSD"])
    assert np.max(np.abs(a - b)) / np.max(np.abs(b)) < 1e-3
    a, b = np.asarray(mc["Mbase_PSD"]), np.asarray(gc["Mbase_PSD"])
    assert np.max(np.abs(a - b)) / np.max(np.abs(b)) < 8e-2

    mc = res["case_metrics"][1][0]
    gc = true["case_metrics"][1][0]
    assert model.cases[1]["wind_speed"] > 0
    assert_allclose(float(np.asarray(mc["surge_avg"])),
                    float(np.asarray(gc["surge_avg"])), rtol=1e-2)
    assert_allclose(float(np.asarray(mc["pitch_avg"])),
                    float(np.asarray(gc["pitch_avg"])), rtol=5e-2)
    for metric, gate in (("surge_PSD", 1e-2), ("heave_PSD", 1e-2),
                         ("pitch_PSD", 1e-2), ("AxRNA_PSD", 2e-2),
                         ("Tmoor_PSD", 3e-2), ("Mbase_PSD", 0.6)):
        a, b = np.asarray(mc[metric]), np.asarray(gc[metric])
        assert np.max(np.abs(a - b)) / (np.max(np.abs(b)) + 1e-12) < gate, metric


def test_analyze_cases_farm_wind():
    """2-unit VolturnUS-S farm analyzeCases parity at 10.5 m/s operating
    wind — the coupled array chain (shared-mooring equilibrium, per-unit
    aero + excitation, block system impedance) against the farm golden.

    Measured deviations (f64 CPU): motion PSDs 1e-4..1.6e-2 per unit,
    Mbase 2.1-3.8e-2, surge_avg 4e-3.  Gates at ~1.5x measured.
    """
    path = ref_data("VolturnUS-S_farm.yaml")
    if not os.path.exists(path):
        pytest.skip("reference data unavailable")
    model = raft_tpu.Model(path)
    res = model.analyze_cases()
    with open(path.replace(".yaml", "_true_analyzeCases.pkl"), "rb") as f:
        true = pickle.load(f)
    assert np.asarray(model.cases[0]["wind_speed"]).max() > 0
    for ifowt in range(2):
        mc = res["case_metrics"][0][ifowt]
        gc = true["case_metrics"][0][ifowt]
        assert_allclose(float(np.asarray(mc["surge_avg"])),
                        float(np.asarray(gc["surge_avg"])), rtol=1e-2)
        for metric, gate in (("surge_PSD", 3e-3), ("heave_PSD", 1e-3),
                             ("pitch_PSD", 2.5e-2), ("AxRNA_PSD", 2e-2),
                             ("Mbase_PSD", 6e-2)):
            a, b = np.asarray(mc[metric]), np.asarray(gc[metric])
            assert np.max(np.abs(a - b)) / (np.max(np.abs(b)) + 1e-12) < gate, \
                (ifowt, metric)


def test_analyze_cases_volturn_meandrift():
    """VolturnUS-S analyzeCases no-wind case with the slender-QTF mean
    drift fed back into the equilibrium (raft_model.py:316-328): the
    golden's 1.61 m mean surge — formerly documented as an anomaly — is
    the drift-included pose.  Motion/tension PSDs include the 2nd-order
    response realisation (measured 1.2-2.6e-2)."""
    path = ref_data("VolturnUS-S.yaml")
    if not os.path.exists(path):
        pytest.skip("reference data unavailable")
    model = raft_tpu.Model(path)
    res = model.analyze_cases()
    with open(path.replace(".yaml", "_true_analyzeCases.pkl"), "rb") as f:
        true = pickle.load(f)
    mc = res["case_metrics"][0][0]
    gc = true["case_metrics"][0][0]
    assert model.cases[0]["wind_speed"] == 0
    assert_allclose(float(np.asarray(mc["surge_avg"])),
                    float(np.asarray(gc["surge_avg"])), rtol=2e-2)
    assert_allclose(float(np.asarray(mc["pitch_avg"])),
                    float(np.asarray(gc["pitch_avg"])), rtol=1e-2)
    for metric, gate in (("surge_PSD", 2e-2), ("heave_PSD", 2e-2),
                         ("pitch_PSD", 4e-2), ("AxRNA_PSD", 2e-2),
                         ("Mbase_PSD", 3e-2), ("Tmoor_PSD", 2e-2)):
        a, b = np.asarray(mc[metric]), np.asarray(gc[metric])
        assert np.max(np.abs(a - b)) / (np.max(np.abs(b)) + 1e-12) < gate, metric
