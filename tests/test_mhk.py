"""MHK (underwater-rotor) design smoke tests: the RM1 floating tidal
turbine builds, reaches a current-loaded equilibrium, and solves
dynamics with the current-driven rotor providing mean thrust."""

import os

import numpy as np
import pytest

from tests.conftest import REFERENCE_DIR

import raft_tpu

pytestmark = pytest.mark.slow

PATH = os.path.join(REFERENCE_DIR, "designs", "RM1_Floating.yaml")


@pytest.fixture(scope="module")
def model():
    if not os.path.exists(PATH):
        pytest.skip("reference design unavailable")
    return raft_tpu.Model(PATH)


def test_mhk_builds(model):
    fs = model.fowtList[0]
    assert fs.nrotors == 1
    assert fs.rotors[0].Zhub < 0  # submerged rotor


def test_mhk_current_equilibrium(model):
    case = dict(zip(model.design["cases"]["keys"], model.design["cases"]["data"][0]))
    assert case["current_speed"] > 0
    X = np.asarray(model.solve_statics(case))
    # current thrust pushes the platform downstream
    assert 0.5 < X[0] < 30.0
    assert np.all(np.isfinite(X))
    # rotor thrust from the water flow is substantial
    F = np.asarray(model.aero_mean_force(case, 0))
    assert F[0] > 1e4


def test_mhk_dynamics(model):
    case = dict(zip(model.design["cases"]["keys"], model.design["cases"]["data"][0]))
    Xi, info = model.solve_dynamics(case)
    assert np.isfinite(np.asarray(Xi)).all()


def test_mhk_rotor_blade_hydro(model):
    """Submerged rotor blade-member hydro (raft_rotor.py:604-656):
    added mass, inertial excitation and buoyancy about the rotor node."""
    fs = model.fowtList[0]
    rh = fs.rotors[0].hydro
    assert rh is not None
    assert rh["V"] > 0.0                       # displaced blade volume
    A = np.asarray(rh["A_hydro"])
    assert np.allclose(A, A.T, atol=1e-6 * np.max(np.abs(A)))
    assert np.all(np.linalg.eigvalsh(A[:3, :3]) >= -1e-9)
    # buoyancy is upward
    assert rh["Fvec"][2] > 0
    # inertial excitation exceeds added mass (Cm = 1 + Ca)
    I3 = np.asarray(rh["I_hydro"])[:3, :3]
    assert np.trace(I3) > np.trace(A[:3, :3])

    # the FOWT-level added-mass matrix includes the rotor contribution
    A_tot = np.asarray(model.hydro[0].hc0["A_hydro"])
    assert np.all(np.isfinite(A_tot))


def test_mhk_cavitation(model):
    """Cavitation margins computed from the BEMT relative velocities and
    cpmin polars (raft_rotor.py:657-716); positive margin = no
    cavitation at the RM1 design point."""
    case = dict(zip(model.design["cases"]["keys"], model.design["cases"]["data"][0]))
    tc = model.turbine_constants(case, 0)
    cav = tc["rotor_info"][0].get("cavitation")
    assert cav is not None
    assert cav.shape[1] == len(model.rotor_aero[0].r)
    assert np.all(np.isfinite(cav))
    # margins positive across the blade at the design flow speed
    assert np.all(cav > 0)

    # and the channel lands in the case metrics
    results = model.analyze_cases()
    assert "cavitation" in results["case_metrics"][0][0]
