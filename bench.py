"""Benchmark: frequency-domain design evaluations per second per chip.

Workload (the reference's headline loop, SURVEY.md §6 / BASELINE.md):
one full design evaluation = static equilibrium (catenary mooring
Newton) + strip-theory wave excitation + iterative stochastic drag
linearisation + per-frequency 6-DOF complex impedance solves + response
spectra, on a spar design with ~80 Morison strips x 40 frequencies and
10 linearisation iterations.

* raft_tpu path: the jitted, vmapped evaluator from raft_tpu.api,
  batched over sea states (the per-chip shard of a design sweep).
* baseline: a straight serial NumPy implementation of the same math,
  looping members/strips and frequencies the way the reference does
  (raft_model.py:1084-1089, raft_member.py:1965-2124) — measured here
  because the reference itself publishes no numbers and cannot run in
  this image (its moorpy/ccblade deps are absent; see BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import time

import numpy as np


def build():
    import raft_tpu
    from raft_tpu.api import make_case_evaluator

    here = os.path.dirname(os.path.abspath(__file__))
    model = raft_tpu.Model(os.path.join(here, "raft_tpu", "designs", "spar_demo.yaml"))
    return model, make_case_evaluator(model)


# --------------------------------------------------------------- baseline

def numpy_eval_case(model, Hs, Tp, beta):
    """Serial NumPy twin of one design evaluation (reference-style loops)."""
    fs = model.fowtList[0]
    fh = model.hydro[0]
    ss = fh.strips
    w = model.w
    k = model.k
    nw = len(w)
    dw = w[1] - w[0]
    rho, g, depth = fs.rho_water, fs.g, fs.depth

    stat = model.statics()
    K_h = np.asarray(stat["C_struc"] + stat["C_hydro"])
    F_und = np.asarray(stat["W_struc"] + stat["W_hydro"])
    M = np.asarray(stat["M_struc"]) + np.asarray(fh.hc0["A_hydro"])
    Imat = np.asarray(fh.hc0["Imat"])  # (S,3,3,nw)
    a_i = np.asarray(fh.hc0["a_i"])
    ms = model.ms

    # --- catenary (serial per line, Newton)
    def line_force(r6):
        from numpy import hypot

        R = _rotmat(r6[3], r6[4], r6[5])
        F = np.zeros(6)
        K = np.zeros((6, 6))
        for iL in range(ms.n_lines):
            rf = r6[:3] + R @ ms.r_fair0[iL]
            dv = rf - ms.r_anchor[iL]
            XF, ZF = hypot(dv[0], dv[1]), dv[2]
            HF, VF = _catenary_np(XF, ZF, ms.L[iL], ms.w[iL], ms.EA[iL])
            uh = dv[:2] / max(XF, 1e-9)
            f3 = np.array([-HF * uh[0], -HF * uh[1], -VF])
            F[:3] += f3
            F[3:] += np.cross(rf - r6[:3], f3)
        return F

    def line_stiffness(r6, dx=1e-4):
        K = np.zeros((6, 6))
        for j in range(6):
            e = np.zeros(6)
            e[j] = dx
            K[:, j] = -(line_force(r6 + e) - line_force(r6 - e)) / (2 * dx)
        return K

    # --- static equilibrium (Newton, reference stopping rule)
    X = np.zeros(6)
    tols = np.array([0.05, 0.05, 0.05, 0.005, 0.005, 0.005])
    for _ in range(30):
        F = F_und - K_h @ X + line_force(X)
        K = K_h + line_stiffness(X)
        dX = np.linalg.solve(K, F)
        if np.all(np.abs(dX) < tols):
            break
        X += dX

    # --- strip frames at mean offset
    Rp = _rotmat(X[3], X[4], X[5])
    r0n = fs.node_r0
    d = r0n - r0n[fs.root_id]
    r_nodes = r0n + X[:3] + (d @ Rp.T - d)
    q = ss.q0 @ Rp.T
    p1 = ss.p10 @ Rp.T
    p2 = ss.p20 @ Rp.T
    r = r_nodes[ss.node] + q * ss.ls[:, None]
    sub = r[:, 2] < 0
    active = sub & ss.active

    # --- sea state + per-strip wave kinematics & excitation (strip loop)
    S = _jonswap_np(w, Hs, Tp)
    zeta = np.sqrt(2 * S * dw).astype(complex)
    Fexc = np.zeros((6, nw), dtype=complex)
    u_all = np.zeros((ss.S, 3, nw), dtype=complex)
    for s in range(ss.S):
        u, ud, pd = _wavekin_np(zeta, beta, w, k, depth, r[s], rho, g)
        u_all[s] = u
        if not active[s]:
            continue
        F3 = np.einsum("ijw,jw->iw", Imat[s], ud) + pd[None, :] * (a_i[s] * q[s])[:, None]
        lever = r[s] - r_nodes[ss.node[s]] + (r_nodes[ss.node[s]] - r_nodes[fs.root_id])
        Fexc[:3] += F3
        Fexc[3:] += np.cross(np.broadcast_to(lever[:, None], F3.shape), F3, axis=0)

    C = K_h + line_stiffness(X)

    # --- drag linearisation iterations + per-frequency solves
    a_q = np.where(ss.circ, np.pi * ss.ds[:, 0] * ss.dls, 2 * (ss.ds[:, 0] + ss.ds[:, 0]) * ss.dls)
    a_p1 = np.where(ss.circ, ss.ds[:, 0] * ss.dls, ss.ds[:, 0] * ss.dls)
    a_p2 = np.where(ss.circ, ss.ds[:, 0] * ss.dls, ss.ds[:, 1] * ss.dls)
    a_end = np.abs(np.where(
        ss.circ, np.pi * ss.ds[:, 0] * ss.drs[:, 0],
        (ss.ds[:, 0] + ss.drs[:, 0]) * (ss.ds[:, 1] + ss.drs[:, 1])
        - (ss.ds[:, 0] - ss.drs[:, 0]) * (ss.ds[:, 1] - ss.drs[:, 1])))

    XiLast = np.zeros((6, nw), dtype=complex)
    Xi = XiLast
    for _ in range(model.nIter + 1):
        B6 = np.zeros((6, 6))
        Fdrag = np.zeros((6, nw), dtype=complex)
        for s in range(ss.S):  # strip loop, as the reference does
            if not sub[s]:
                continue
            lever = r[s] - r_nodes[fs.root_id]
            th = XiLast[3:]
            vnode = 1j * w * (XiLast[:3] + np.cross(th, np.broadcast_to(lever[:, None], th.shape), axis=0))
            vrel = u_all[s] - vnode
            vq = q[s] @ vrel
            vp1 = p1[s] @ vrel
            vp2 = p2[s] @ vrel
            vrel_p = vrel - vq[None, :] * q[s][:, None]
            rms = lambda x: np.sqrt(0.5 * np.sum(np.abs(x) ** 2))
            vq_r = rms(vq)
            vp_r = rms(vrel_p)
            c = np.sqrt(8 / np.pi) * 0.5 * rho
            Bq = c * vq_r * a_q[s] * ss.Cd_q[s] + c * vq_r * a_end[s] * ss.Cd_End[s]
            Bp1 = c * (vp_r if ss.circ[s] else rms(vp1)) * a_p1[s] * ss.Cd_p1[s]
            Bp2 = c * (vp_r if ss.circ[s] else rms(vp2)) * a_p2[s] * ss.Cd_p2[s]
            Bm = (Bq * np.outer(q[s], q[s]) + Bp1 * np.outer(p1[s], p1[s])
                  + Bp2 * np.outer(p2[s], p2[s]))
            H = _skew(lever)
            B6[:3, :3] += Bm
            B6[:3, 3:] += Bm @ H
            B6[3:, :3] += (Bm @ H).T
            B6[3:, 3:] += H @ Bm @ H.T
            F3 = Bm @ u_all[s]
            Fdrag[:3] += F3
            Fdrag[3:] += np.cross(np.broadcast_to(lever[:, None], F3.shape), F3, axis=0)

        Xi = np.zeros((6, nw), dtype=complex)
        for i in range(nw):  # frequency loop, as the reference does
            Z = -w[i] ** 2 * M + 1j * w[i] * B6 + C
            Xi[:, i] = np.linalg.solve(Z, Fexc[:, i] + Fdrag[:, i])
        tolCheck = np.abs(Xi - XiLast) / (np.abs(Xi) + 0.01)
        if np.all(tolCheck < 0.01):
            break
        XiLast = 0.2 * XiLast + 0.8 * Xi

    return 0.5 * np.abs(Xi) ** 2 / dw


def _rotmat(x3, x2, x1):
    s1, c1, s2, c2, s3, c3 = np.sin(x1), np.cos(x1), np.sin(x2), np.cos(x2), np.sin(x3), np.cos(x3)
    return np.array([
        [c1 * c2, c1 * s2 * s3 - c3 * s1, s1 * s3 + c1 * c3 * s2],
        [c2 * s1, c1 * c3 + s1 * s2 * s3, c3 * s1 * s2 - c1 * s3],
        [-s2, c2 * s3, c2 * c3]])


def _skew(r):
    return np.array([[0, r[2], -r[1]], [-r[2], 0, r[0]], [r[1], -r[0], 0]])


def _jonswap_np(ws, Hs, Tp):
    TpOvrSqrtHs = Tp / np.sqrt(Hs)
    gamma = 5.0 if TpOvrSqrtHs <= 3.6 else 1.0 if TpOvrSqrtHs >= 5.0 else np.exp(5.75 - 1.15 * TpOvrSqrtHs)
    f = 0.5 / np.pi * ws
    fp4 = (Tp * f) ** -4.0
    C = 1.0 - 0.287 * np.log(gamma)
    sig = np.where(f <= 1.0 / Tp, 0.07, 0.09)
    alpha = np.exp(-0.5 * ((f * Tp - 1.0) / sig) ** 2)
    return 0.5 / np.pi * C * 0.3125 * Hs * Hs * fp4 / f * np.exp(-1.25 * fp4) * gamma**alpha


def _wavekin_np(zeta, beta, w, k, h, r, rho, g):
    x, y, z = r
    ze = zeta * np.exp(-1j * k * (np.cos(beta) * x + np.sin(beta) * y))
    if z > 0:
        nw = len(w)
        return (np.zeros((3, nw), complex), np.zeros((3, nw), complex), np.zeros(nw, complex))
    kh = k * h
    deep = kh > 89.4
    with np.errstate(over="ignore"):
        SINH = np.where(deep, np.exp(k * z), np.sinh(np.where(deep, 0, k * (z + h))) / np.sinh(np.where(deep, 1, kh)))
        COSHs = np.where(deep, np.exp(k * z), np.cosh(np.where(deep, 0, k * (z + h))) / np.sinh(np.where(deep, 1, kh)))
        COSHc = np.where(deep, np.exp(k * z), np.cosh(np.where(deep, 0, k * (z + h))) / np.cosh(np.where(deep, 1, kh)))
    u = np.stack([w * ze * COSHs * np.cos(beta), w * ze * COSHs * np.sin(beta), 1j * w * ze * SINH])
    return u, 1j * w * u, rho * g * ze * COSHc


def _catenary_np(XF, ZF, L, w_line, EA, n_iter=60):
    lr = np.hypot(XF, ZF)
    lam = 0.2 if L <= lr else np.sqrt(max(3 * ((L**2 - ZF**2) / XF**2 - 1), 1e-12))
    HF = max(abs(0.5 * w_line * XF / lam), 1e-3)
    VF = 0.5 * w_line * (ZF / np.tanh(lam) + L)
    for _ in range(n_iter):
        def prof(HF, VF):
            t1 = VF / HF
            s1 = np.sqrt(1 + t1 * t1)
            if VF < w_line * L:  # grounded
                LB = L - VF / w_line
                X = LB + HF / w_line * np.log(t1 + s1) + HF * L / EA
                Z = HF / w_line * (s1 - 1) + VF**2 / (2 * EA * w_line)
            else:
                VA = VF - w_line * L
                t2 = VA / HF
                s2 = np.sqrt(1 + t2 * t2)
                X = HF / w_line * (np.log(t1 + s1) - np.log(t2 + s2)) + HF * L / EA
                Z = HF / w_line * (s1 - s2) + (VF * L - 0.5 * w_line * L**2) / EA
            return X, Z
        X0, Z0 = prof(HF, VF)
        dh = max(1e-4 * HF, 1.0)
        dv = max(1e-4 * abs(VF), 1.0)
        Xh, Zh = prof(HF + dh, VF)
        Xv, Zv = prof(HF, VF + dv)
        J = np.array([[(Xh - X0) / dh, (Xv - X0) / dv], [(Zh - Z0) / dh, (Zv - Z0) / dv]])
        rvec = np.array([X0 - XF, Z0 - ZF])
        try:
            dHV = np.linalg.solve(J, -rvec)
        except np.linalg.LinAlgError:
            break
        HF = max(HF + np.clip(dHV[0], -0.5 * (abs(HF) + abs(VF) + 1), 0.5 * (abs(HF) + abs(VF) + 1)), 1e-6)
        VF = VF + np.clip(dHV[1], -0.5 * (abs(HF) + abs(VF) + 1), 0.5 * (abs(HF) + abs(VF) + 1))
        if np.hypot(*rvec) < 1e-8 * max(XF, 1.0):
            break
    return HF, VF


# ------------------------------------------------------------------- main

def main():
    import jax
    import jax.numpy as jnp

    model, evaluate = build()

    # --- accelerator path: batched sweep on this chip
    fn = jax.jit(jax.vmap(lambda h, t, b: evaluate(h, t, b)["PSD"]))
    B = int(os.environ.get("RAFT_TPU_BENCH_BATCH", "512"))
    rng = np.random.default_rng(0)
    Hs = jnp.asarray(2.0 + 6.0 * rng.random(B), dtype=jnp.float32)
    Tp = jnp.asarray(8.0 + 8.0 * rng.random(B), dtype=jnp.float32)
    beta = jnp.asarray(2 * np.pi * rng.random(B), dtype=jnp.float32)
    jax.block_until_ready(fn(Hs, Tp, beta))  # compile
    reps = 5
    t0 = time.perf_counter()
    for _ in range(reps):
        jax.block_until_ready(fn(Hs, Tp, beta))
    dt = (time.perf_counter() - t0) / reps
    evals_per_sec = B / dt

    # --- NumPy baseline (serial loops, reference structure)
    n_base = 5
    t0 = time.perf_counter()
    for i in range(n_base):
        numpy_eval_case(model, float(Hs[i]), float(Tp[i]), float(beta[i]))
    base_dt = (time.perf_counter() - t0) / n_base
    base_evals_per_sec = 1.0 / base_dt

    print(json.dumps({
        "metric": "design-evals/sec/chip (full freq-domain case evaluation)",
        "value": round(evals_per_sec, 2),
        "unit": "evals/s",
        "vs_baseline": round(evals_per_sec / base_evals_per_sec, 2),
    }))


if __name__ == "__main__":
    main()
