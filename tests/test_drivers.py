"""Driver-layer tests: run(), CSV output, power/thrust curve, IEC wind."""

import os

import numpy as np
import pytest

from tests.conftest import ref_data


pytestmark = pytest.mark.slow

def test_run_and_csv(tmp_path):
    from raft_tpu.drivers import run

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "raft_tpu", "designs", "spar_demo.yaml")
    csv = tmp_path / "out.csv"
    model = run(path, save_csv=str(csv))
    assert 0 in model.results["case_metrics"]
    text = csv.read_text()
    assert "surge" in text and "Tmoor0" in text


def test_power_thrust_curve():
    import raft_tpu
    from raft_tpu.drivers import power_thrust_curve

    path = ref_data("OC3spar.yaml")
    if not os.path.exists(path):
        pytest.skip("reference data unavailable")
    model = raft_tpu.Model(path)
    out = power_thrust_curve(model, np.linspace(5, 20, 6))
    assert np.all(out["thrust"] > 0)
    assert np.all(out["power"] > 0)
    # rated power of the 5MW class machine within a sensible band
    assert 3e6 < out["power"].max() < 9e6


def test_iec_wind_events():
    from raft_tpu.physics.iec_wind import IECWindExtreme, write_wnd

    iec = IECWindExtreme(turbine_class="I", turbulence_class="B",
                         z_hub=90.0, D=126.0)
    assert np.isclose(iec.NTM(10.0), 0.14 * (0.75 * 10 + 5.6))
    eog = iec.EOG(11.4)
    # the EOG dips then rises; peak-to-peak bounded by the gust magnitude
    assert eog["V_gust"].min() < 0 < eog["V_gust"].max()
    edc = iec.EDC(11.4)
    assert 0 < edc["theta_e"] <= 180
    assert np.isclose(edc["theta_pos"][-1], edc["theta_e"])
    ecd = iec.ECD(11.4)
    assert np.isclose(ecd["V"][-1] - ecd["V"][0], 15.0)
    ews = iec.EWS(11.4)
    assert ews["shear_lin"].max() > 0


def test_wnd_writer(tmp_path):
    from raft_tpu.physics.iec_wind import IECWindExtreme, write_wnd

    iec = IECWindExtreme()
    eog = iec.EOG(11.4)
    t = eog["t"]
    z = np.zeros_like(t)
    p = tmp_path / "eog.wnd"
    write_wnd(p, (t, eog["V"], z, z, z, z + 0.2, z, eog["V_gust"], z),
              header_lines=["! EOG"])
    assert p.read_text().startswith("! EOG")


def test_adjust_ballast():
    from raft_tpu.drivers import adjust_ballast

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "raft_tpu", "designs", "spar_demo.yaml")
    model, scale = adjust_ballast(path, target_heave=0.0, heave_tol=0.05)
    X = np.asarray(model.solve_statics(None))
    assert abs(X[2]) < 0.05
    assert scale != 1.0
