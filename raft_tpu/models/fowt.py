"""FOWT structure: build-time assembly of one floating unit.

Parses the platform/turbine sections of a design dict into
``MemberGeometry`` objects, rotor properties, point inertias/loads and
the joint topology, and exposes the statically-shaped inputs the traced
physics kernels consume.

Mirrors the construction logic of the reference FOWT
(``/root/reference/raft/raft_fowt.py`` ``__init__`` :36-437, joint
wiring :439-551) minus all runtime state: this object is immutable
after construction and safe to close over in ``jit``.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from raft_tpu.structure.members import MemberGeometry, build_member
from raft_tpu.structure.schema import coerce
from raft_tpu.structure.topology import Topology


def _rotmat_np(x3, x2, x1):
    s1, c1 = np.sin(x1), np.cos(x1)
    s2, c2 = np.sin(x2), np.cos(x2)
    s3, c3 = np.sin(x3), np.cos(x3)
    return np.array(
        [
            [c1 * c2, c1 * s2 * s3 - c3 * s1, s1 * s3 + c1 * c3 * s2],
            [c2 * s1, c1 * c3 + s1 * s2 * s3, c3 * s1 * s2 - c1 * s3],
            [-s2, c2 * s3, c2 * c3],
        ]
    )


@dataclass
class RotorProps:
    """RNA mass/geometry needed for statics & dynamics assembly.

    From Rotor.__init__ / setPosition / setYaw
    (/root/reference/raft/raft_rotor.py:38-135, 390-478)."""

    mRNA: float
    IxRNA: float
    IrRNA: float
    xCG_RNA: float
    overhang: float
    shaft_tilt: float       # [rad]
    shaft_toe: float        # [rad]
    precone: float          # [rad]
    nBlades: int
    r_rel: np.ndarray       # RNA reference point wrt PRP (3,)
    q_rel: np.ndarray       # rotor axis unit vector at zero pose
    R_q0: np.ndarray        # rotation matrix local->global at zero pose
    Zhub: float
    I_drivetrain: float = 0.0
    aeroServoMod: int = 1
    yaw_mode: int = 0
    # submerged (MHK) rotor blade hydro summary about the rotor node:
    # dict(A_hydro, I_hydro, Fvec, Cmat, V) or None (raft_rotor.py:604-656)
    hydro: dict | None = None


class FOWTStructure:
    """Immutable build-time description of one FOWT."""

    def __init__(self, design, depth=600.0, x_ref=0.0, y_ref=0.0, heading_adjust=0.0):
        self.design = design
        self.depth = float(depth)
        self.x_ref = x_ref
        self.y_ref = y_ref
        self.heading_adjust = heading_adjust

        site = design.get("site", {})
        self.rho_water = float(coerce(site, "rho_water", default=1025.0))
        self.g = float(coerce(site, "g", default=9.81))
        self.shearExp_water = float(coerce(site, "shearExp_water", default=0.12))

        platform = design["platform"]
        self.potModMaster = int(coerce(platform, "potModMaster", dtype=int, default=0))
        dlsMax = float(coerce(platform, "dlsMax", default=5.0))
        self.yaw_stiffness = float(platform.get("yaw_stiffness", 0.0))
        self.potFirstOrder = int(coerce(platform, "potFirstOrder", dtype=int, default=0))
        self.potSecOrder = int(coerce(platform, "potSecOrder", dtype=int, default=0))
        self.hydroPath = platform.get("hydroPath", None)

        # ---- members: platform (with heading copies), tower, nacelle ----
        self.members: list[MemberGeometry] = []
        for mi in platform["members"]:
            mi = dict(mi)
            if self.potModMaster in (1,):
                mi["potMod"] = False
            elif self.potModMaster in (2, 3):
                mi["potMod"] = True
            if "dlsMax" not in mi:
                mi["dlsMax"] = dlsMax
            headings = coerce(mi, "heading", shape=-1, default=0.0)
            headings = [headings] if np.isscalar(headings) else list(headings)
            for h in headings:
                self.members.append(
                    build_member(mi, heading=h + heading_adjust, part_of="platform")
                )

        self.nrotors = 0
        self.ntowers = 0
        turbine = design.get("turbine", None)
        if turbine is not None:
            self.nrotors = int(coerce(turbine, "nrotors", dtype=int, shape=0, default=1))
            turbine.setdefault("nrotors", self.nrotors)
            towers = turbine.get("tower", None)
            if towers is not None:
                if isinstance(towers, dict):
                    towers = [towers] * self.nrotors
                self.ntowers = len(towers)
                for mem in towers:
                    self.members.append(build_member(mem, part_of="tower"))
            nacelles = turbine.get("nacelle", None)
            if nacelles is not None:
                if isinstance(nacelles, dict):
                    nacelles = [nacelles] * self.nrotors
                for mem in nacelles:
                    self.members.append(build_member(mem, part_of="nacelle"))

        self.nplatmems = sum(1 for m in self.members if m.part_of == "platform")

        # ---- rotors ----
        self.rotors: list[RotorProps] = []
        for ir in range(self.nrotors):
            self.rotors.append(self._build_rotor(turbine, ir))

        # ---- point inertias / mean loads (raft_fowt.py:96-120) ----
        self.pointInertias = []
        self.pointLoads = []
        for eff in platform.get("additional_effects", []) or []:
            if eff["type"] == "point_inertia":
                m = coerce(eff, "mass", shape=0, default=0)
                J = coerce(eff, "moments_of_inertia", shape=6, default=[0, 0, 0])
                M = np.diag([m, m, m, J[0], J[1], J[2]])
                M[3, 4] = M[4, 3] = J[3]
                M[3, 5] = M[5, 3] = J[4]
                M[4, 5] = M[5, 4] = J[5]
                self.pointInertias.append(
                    {"m": m, "inertia": M, "r": coerce(eff, "location", shape=3, default=[0, 0, 0])}
                )
            elif eff["type"] == "mean_load":
                self.pointLoads.append(
                    {
                        "f": coerce(eff, "load", shape=6, default=np.zeros(6)),
                        "r": coerce(eff, "location", shape=3, default=[0, 0, 0]),
                    }
                )

        # ---- topology: nodes, joints, DOF reduction ----
        self._build_topology(design)

    # ------------------------------------------------------------------
    def _build_rotor(self, turbine, ir):
        nrotors = turbine["nrotors"]
        if "rRNA" in turbine:
            r_rel = coerce(turbine, "rRNA", shape=[nrotors, 3])[ir].astype(float)
        else:
            r_rel = np.zeros(3)
        overhang = coerce(turbine, "overhang", shape=nrotors)[ir]
        shaft_tilt = coerce(turbine, "shaft_tilt", shape=nrotors)[ir] * np.pi / 180
        shaft_toe = coerce(turbine, "shaft_toe", shape=nrotors, default=0)[ir] * np.pi / 180
        precone = coerce(turbine, "precone", shape=nrotors, default=0)[ir] * np.pi / 180
        q_rel = _rotmat_np(0.0, -shaft_tilt, shaft_toe) @ np.array([1.0, 0.0, 0.0])
        if "hHub" in turbine:
            hHub = coerce(turbine, "hHub", shape=nrotors)[ir]
            r_rel = r_rel.copy()
            r_rel[2] = hHub - q_rel[2] * overhang
        R_q0 = _rotmat_np(0.0, -shaft_tilt, shaft_toe)  # yaw = 0 at build
        Zhub = r_rel[2] + q_rel[2] * overhang
        rotor_hydro = None
        if Zhub < 0 and "blade" in turbine and "airfoils" in turbine:
            from raft_tpu.physics.aero import blade_hydro

            props = RotorProps(
                mRNA=0, IxRNA=0, IrRNA=0, xCG_RNA=0, overhang=overhang,
                shaft_tilt=shaft_tilt, shaft_toe=shaft_toe, precone=precone,
                nBlades=int(coerce(turbine, "nBlades", shape=nrotors,
                                   dtype=int, default=3)[ir]),
                r_rel=r_rel, q_rel=q_rel, R_q0=R_q0, Zhub=Zhub)
            rotor_hydro = blade_hydro(
                turbine, ir, props, rho_water=self.rho_water, g=self.g)
        return RotorProps(
            mRNA=coerce(turbine, "mRNA", shape=nrotors)[ir],
            IxRNA=coerce(turbine, "IxRNA", shape=nrotors)[ir],
            IrRNA=coerce(turbine, "IrRNA", shape=nrotors)[ir],
            xCG_RNA=coerce(turbine, "xCG_RNA", shape=nrotors, default=0)[ir],
            overhang=overhang,
            shaft_tilt=shaft_tilt,
            shaft_toe=shaft_toe,
            precone=precone,
            nBlades=int(coerce(turbine, "nBlades", shape=nrotors, dtype=int, default=3)[ir]),
            r_rel=r_rel,
            q_rel=q_rel,
            R_q0=R_q0,
            Zhub=r_rel[2] + q_rel[2] * overhang,
            I_drivetrain=float(coerce(turbine, "I_drivetrain", shape=nrotors, default=0.0)[ir]),
            aeroServoMod=int(coerce(turbine, "aeroServoMod", shape=nrotors, dtype=int, default=1)[ir]),
            yaw_mode=int(coerce(turbine, "yaw_mode", shape=nrotors, dtype=int, default=0)[ir]),
            hydro=rotor_hydro,
        )

    # ------------------------------------------------------------------
    def _build_topology(self, design):
        """Joints + nodes + reduction; raft_fowt.py:183-339."""
        topo = Topology()

        # nodes per member: rigid members have a single node at rA0;
        # beams get one node per strip (raft_member.py:273-287)
        member_nodes = []
        for im, mem in enumerate(self.members):
            if mem.mtype == "rigid":
                member_nodes.append(topo.add_node(mem.rA0, "member", owner=im).id)
            else:
                r = mem.rA0[None, :] + mem.q0[None, :] * mem.ls[:, None]
                ids = []
                for i in range(mem.ns):
                    end = i == 0 or i == mem.ns - 1
                    ids.append(topo.add_node(r[i], "member", owner=im,
                                             end_node=end).id)
                topo.add_chain(ids)
                member_nodes.append(ids[0])
        rotor_nodes = []
        for ir, rot in enumerate(self.rotors):
            rotor_nodes.append(topo.add_node(rot.r_rel, "rotor", owner=ir).id)

        # joint data (raft_fowt.py:188-212): explicit or the virtual
        # origin joint connecting all platform members + towers
        turbine = design.get("turbine", {}) or {}
        tower_names = []
        if "tower" in turbine:
            tw = turbine["tower"]
            tw = [tw] if isinstance(tw, dict) else tw
            tower_names = [m["name"] for m in tw]

        joint_data = design.get("joints", None)
        if joint_data is None:
            names = [m["name"] for m in design["platform"]["members"]] + tower_names
            joint_data = [
                {"name": "origin_joint", "type": "cantilever", "location": [0, 0, 0],
                 "members": names}
            ]

        from raft_tpu.structure.members import _heading_rot

        for j_data in joint_data:
            j_headings = coerce(j_data, "heading", shape=-1, default=0.0)
            j_headings = [j_headings] if np.isscalar(j_headings) else list(j_headings)
            for count_heading, j_heading in enumerate(j_headings):
                r_j = np.array(j_data["location"], dtype=float)
                if j_heading != 0.0:
                    r_j = _heading_rot(j_heading) @ r_j
                joint = topo.add_joint(r_j, j_data["type"], j_data["name"])
                for member_name in j_data["members"]:
                    idxs = [i for i, m in enumerate(self.members) if m.name == member_name]
                    if not idxs:
                        raise ValueError(f"joint references unknown member {member_name!r}")
                    if len(idxs) == 1 or len(j_headings) == 1:
                        chosen = idxs
                    else:
                        chosen = [idxs[count_heading]]
                    for im in chosen:
                        topo.attach_node_to_joint(
                            self._closest_end_node(topo, member_nodes, im, joint),
                            joint,
                        )

        # rotor-to-tower joints (raft_fowt.py:303-312)
        tower_member_idx = [i for i, m in enumerate(self.members) if m.part_of == "tower"]
        nacelle_member_idx = [i for i, m in enumerate(self.members) if m.part_of == "nacelle"]
        for ir, rot in enumerate(self.rotors):
            joint = topo.add_joint(rot.r_rel, "cantilever", "tower2rotor")
            topo.attach_node_to_joint(
                self._closest_end_node(topo, member_nodes, tower_member_idx[ir], joint),
                joint)
            topo.attach_node_to_joint(topo.nodes[rotor_nodes[ir]], joint)
            # nacelle members ride the tower top (the reference leaves
            # them unjoined, which breaks its own DOF reduction on the
            # MHK designs; rigid attachment to the RNA joint is the
            # physically intended configuration)
            if ir < len(nacelle_member_idx):
                topo.attach_node_to_joint(
                    topo.nodes[member_nodes[nacelle_member_idx[ir]]], joint)

        T, dT, reducedDOF, root_id = topo.reduce_with_derivative()
        self.topology = topo
        self.T = T
        self.dT = dT
        self.reducedDOF = reducedDOF
        self.root_id = root_id
        self.member_node = np.array(member_nodes)
        self.rotor_node = np.array(rotor_nodes)
        self.n_nodes = len(topo.nodes)
        self.node_r0 = np.array([n.r0 for n in topo.nodes])
        self.nFullDOF = 6 * self.n_nodes
        self.nDOF = len(reducedDOF)
        self.is_single_body = self.nDOF == 6 and all(
            d[0] == root_id for d in reducedDOF
        )

    @staticmethod
    def _closest_end_node(topo, member_nodes, im, joint):
        """The member end node closest to the joint (raft_fowt.py:498-511)."""
        first = member_nodes[im]
        n0 = topo.nodes[first]
        # find the member's last node (same owner, contiguous ids)
        last = first
        while (last + 1 < len(topo.nodes)
               and topo.nodes[last + 1].kind == "member"
               and topo.nodes[last + 1].owner == n0.owner):
            last += 1
        if last == first:
            return n0
        n1 = topo.nodes[last]
        dA = np.linalg.norm(n0.r0 - joint["r"])
        dB = np.linalg.norm(n1.r0 - joint["r"])
        return n0 if dA < dB else n1
