"""Elastic sweep-fabric tests (:mod:`raft_tpu.parallel.fabric`).

Ledger mechanics (claim exclusivity, expiry, stealing, pooled
straggler thresholds) are unit-tested in-process; the acceptance
scenarios — 2-worker sweep bit-identical to serial, kill-a-worker
(SIGKILL mid-shard -> lease expires -> shard stolen -> sweep completes
with no duplicate/missing rows), mid-sweep worker join — run REAL
worker subprocesses against toy entries in tests/_fabric_entry.py.
"""

import json
import os
import sys
import time

import numpy as np
import pytest

from raft_tpu.obs import metrics
from raft_tpu.parallel import fabric, resilience
from raft_tpu.parallel.sweep import (
    ensure_distributed, make_mesh, run_sweep_checkpointed_full)
from raft_tpu.utils import faults

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _fabric_entry  # noqa: E402

ENTRY_FILE = os.path.abspath(_fabric_entry.__file__)


def _cases(n, seed=0):
    rng = np.random.default_rng(seed)
    return dict(Hs=2.0 + 6.0 * rng.random(n), Tp=8.0 + 8.0 * rng.random(n))


def _events(path, name=None):
    with open(path) as f:
        evs = [json.loads(line) for line in f if line.strip()]
    return [e for e in evs if name is None or e["event"] == name]


@pytest.fixture
def log_path(tmp_path, monkeypatch):
    p = str(tmp_path / "events.jsonl")
    monkeypatch.setenv("RAFT_TPU_LOG", p)
    return p


@pytest.fixture
def fabric_env(monkeypatch):
    """Worker subprocesses must land on CPU with a short lease TTL and
    a snappy poll, whatever environment pytest itself runs under."""
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("RAFT_TPU_FABRIC_TTL_S", "2.0")
    monkeypatch.setenv("RAFT_TPU_FABRIC_POLL_S", "0.1")


MESH = None


def mesh2():
    global MESH
    if MESH is None:
        MESH = make_mesh(2)
    return MESH


# ------------------------------------------------------------------ ledger


def test_claim_is_exclusive_release_reopens(tmp_path):
    led_a = fabric.Ledger(str(tmp_path), 4, worker_id="a")
    led_b = fabric.Ledger(str(tmp_path), 4, worker_id="b")
    assert led_a.claim(0)
    assert not led_b.claim(0)          # O_EXCL: one claimant wins
    rec, _ = led_b.read_lease(0)
    assert rec["worker"] == "a" and rec["attempt"] == 1
    assert led_b.claim(1)              # other shards stay claimable
    led_b.release(0)                   # not b's lease: must be a no-op
    assert led_a.read_lease(0)[0]["worker"] == "a"
    led_a.release(0)
    assert led_a.read_lease(0) == (None, None)
    assert led_b.claim(0)              # released -> claimable again


def test_expired_lease_is_stolen_exactly_once(tmp_path, log_path,
                                              monkeypatch):
    monkeypatch.setenv("RAFT_TPU_FABRIC_TTL_S", "0.2")
    led_a = fabric.Ledger(str(tmp_path), 2, worker_id="a")
    led_b = fabric.Ledger(str(tmp_path), 2, worker_id="b")
    led_c = fabric.Ledger(str(tmp_path), 2, worker_id="c")
    assert led_a.claim(0)
    assert led_b.stealable(0)[0] is None    # fresh lease: not stealable
    time.sleep(0.3)
    reason, age, holder, attempt = led_b.stealable(0)
    assert reason == "expired" and holder == "a" and attempt == 1
    # renewal refreshes the clock
    assert led_a.renew(0)
    assert led_b.stealable(0)[0] is None
    time.sleep(0.3)
    reason, age, holder, attempt = led_b.stealable(0)
    assert reason == "expired"
    # exactly one stealer wins the rename
    won_b = led_b.steal(0, reason, age, holder)
    won_c = led_c.steal(0, reason, age, holder)
    assert won_b and not won_c
    assert led_c.claim(0, attempt=attempt + 1)
    assert led_c.read_lease(0)[0]["attempt"] == 2
    # the loser's renew must now fail (lease is c's)
    assert not led_a.renew(0)
    evs = _events(log_path, "shard_steal")
    assert len(evs) == 1 and evs[0]["from_worker"] == "a" \
        and evs[0]["reason"] == "expired"


def test_holder_stale_status_file_makes_lease_stealable(tmp_path,
                                                        monkeypatch):
    monkeypatch.setenv("RAFT_TPU_FABRIC_TTL_S", "0.3")
    led_a = fabric.Ledger(str(tmp_path), 2, worker_id="a")
    led_b = fabric.Ledger(str(tmp_path), 2, worker_id="b")
    assert led_a.claim(0)
    led_a.write_worker_status("running", held=[0])
    old = time.time() - 10.0
    os.utime(fabric._worker_path(str(tmp_path), "a"), (old, old))
    # lease still fresh (just claimed) but the holder's heartbeat file
    # went stale -> stealable without waiting out the lease TTL
    reason, _, holder, _ = led_b.stealable(0)
    assert reason == "holder_stale" and holder == "a"


def test_straggler_steal_uses_pooled_wall_p95(tmp_path, monkeypatch):
    # drop the process registry: earlier suite sweeps already observed
    # shard_wall_s, which would pre-arm the straggler threshold
    metrics.reset()
    monkeypatch.setenv("RAFT_TPU_FABRIC_TTL_S", "60")
    monkeypatch.setenv("RAFT_TPU_FABRIC_STEAL_MULT", "4.0")
    led_a = fabric.Ledger(str(tmp_path), 2, worker_id="a")
    led_b = fabric.Ledger(str(tmp_path), 2, worker_id="b")
    assert led_a.claim(0)
    led_a.write_worker_status("running", held=[0])  # fresh heartbeat
    # backdate the claim so its age dwarfs the typical shard wall
    path = fabric._lease_path(str(tmp_path), 0)
    with open(path) as f:
        rec = json.load(f)
    rec["claimed_t"] = time.time() - 5.0
    rec["renewed_t"] = time.time()          # still renewing: alive
    with open(path, "w") as f:
        json.dump(rec, f)
    # below MIN_WALL_SAMPLES pooled observations: no straggler verdict
    assert led_b.stealable(0)[0] is None
    h = metrics.Histogram("shard_wall_s")
    for _ in range(8):
        h.observe(0.05)                      # typical shard: 50 ms
    led_b_state = h.state()
    with open(fabric._worker_path(str(tmp_path), "b"), "w") as f:
        json.dump({"worker": "b", "shard_wall_s": led_b_state}, f)
    reason, age, holder, _ = led_b.stealable(0)
    assert reason == "straggler" and holder == "a" and age > 4.0


def test_histogram_state_roundtrip_and_merge():
    a = metrics.Histogram("a")
    b = metrics.Histogram("b")
    for v in (0.1, 0.2, 0.3):
        a.observe(v)
    for v in (10.0, 20.0):
        b.observe(v)
    pooled = metrics.merge_states([a.state(), b.state()])
    assert pooled.count == 5
    assert pooled.min == pytest.approx(0.1) and pooled.max == 20.0
    assert pooled.sum == pytest.approx(30.6)
    assert pooled.percentile(0.95) >= 10.0
    # garbled states are ignored, not fatal
    pooled.merge_state({"count": "nan?"})
    pooled.merge_state(None)
    assert pooled.count == 5


# ------------------------------------------------------------- entry specs


def test_resolve_entry_module_and_file_forms():
    res = fabric.resolve_entry(f"{ENTRY_FILE}:toy_with_cases_entry",
                               {"n": 6})
    assert callable(res["compute"]) and len(res["cases"]["Hs"]) == 6
    res2 = fabric.resolve_entry(f"{ENTRY_FILE}:toy_entry")
    assert callable(res2["compute"])
    with pytest.raises(ValueError, match="module:callable"):
        fabric.resolve_entry("no_colon_here")
    with pytest.raises(ValueError, match="compute"):
        fabric.resolve_entry(f"{ENTRY_FILE}:not_an_entry")


def test_distributed_dryrun_config(monkeypatch, log_path):
    assert ensure_distributed(dryrun=True) is None   # off by default
    monkeypatch.setenv("RAFT_TPU_DIST", "1")
    monkeypatch.setenv("RAFT_TPU_DIST_COORDINATOR", "10.0.0.1:8476")
    monkeypatch.setenv("RAFT_TPU_DIST_NUM_PROCESSES", "4")
    monkeypatch.setenv("RAFT_TPU_DIST_PROCESS_ID", "2")
    cfg = ensure_distributed(dryrun=True)
    assert cfg == {"coordinator": "10.0.0.1:8476", "process_id": 2,
                   "num_processes": 4}
    (ev,) = _events(log_path, "distributed_init")
    assert ev["dryrun"] is True and ev["num_processes"] == 4
    monkeypatch.setenv("RAFT_TPU_DIST_PROCESS_ID", "4")
    with pytest.raises(ValueError, match="out of range"):
        ensure_distributed(dryrun=True)
    monkeypatch.setenv("RAFT_TPU_DIST_PROCESS_ID", "0")
    monkeypatch.setenv("RAFT_TPU_DIST_COORDINATOR", "noport")
    with pytest.raises(ValueError, match="host:port"):
        ensure_distributed(dryrun=True)


def test_lease_expire_fault_silences_renewer(tmp_path, monkeypatch):
    monkeypatch.setenv("RAFT_TPU_FABRIC_TTL_S", "0.3")
    led = fabric.Ledger(str(tmp_path), 1, worker_id="a")
    led.write_worker_status("running")
    assert led.claim(0)
    silenced = [False]
    renewer = fabric._Renewer(led, 0, silenced)
    with faults.inject("lease_expire:lease_renew:1"):
        renewer.start()
        time.sleep(0.8)
        renewer.stop()
    assert silenced[0]
    # renewals stopped: the lease aged past its TTL while "held"
    reason, _, _, _ = fabric.Ledger(str(tmp_path), 1,
                                    worker_id="b").stealable(0)
    assert reason in ("expired", "holder_stale")


# ------------------------------------------------- subprocess acceptance


def test_two_worker_sweep_bit_identical_to_serial(tmp_path, log_path,
                                                  fabric_env):
    cases = _cases(24, seed=1)
    serial = run_sweep_checkpointed_full(
        _fabric_entry._toy_full, cases, str(tmp_path / "serial"),
        shard_size=4, mesh=mesh2())

    out_dir = str(tmp_path / "fab")
    out = fabric.run_fabric(
        out_dir, workers=2, entry=f"{ENTRY_FILE}:slow_toy_entry",
        entry_kwargs={"delay_s": 0.25}, cases=cases,
        out_keys=("PSD", "X0"), shard_size=4,
        worker_env={"RAFT_TPU_HEARTBEAT_S": "0.2"})
    for k in serial:
        assert np.array_equal(np.asarray(serial[k]), out[k]), k

    # both workers actually participated (slow shards interleave them)
    claimants = {e["worker"] for e in _events(log_path, "shard_claim")}
    assert claimants == {"w0", "w1"}
    starts = _events(log_path, "fabric_worker_start")
    assert {e["worker"] for e in starts} == {"w0", "w1"}
    # worker cold-start provenance is reported per worker (AOT off in
    # this toy run: nothing loaded, nothing banked)
    assert all("programs_loaded" in e and "programs_compiled" in e
               for e in starts)
    # worker heartbeats carry the worker id and its held leases
    beats = [e for e in _events(log_path, "heartbeat")
             if e.get("worker_id")]
    assert beats and all(isinstance(e.get("leases"), list) for e in beats)
    assert any(e["leases"] for e in beats)
    # the manifest records every shard done with its computing worker
    with open(os.path.join(out_dir, "manifest.json")) as f:
        man = json.load(f)
    assert all(man["shards"][str(s)]["status"] == "done" for s in range(6))
    assert {man["shards"][str(s)]["worker"] for s in range(6)} \
        <= {"w0", "w1"}
    assert man["metrics"]["counters"].get("shards_done") == 6
    # per-worker table renders from the shared capture
    from raft_tpu.obs.report import collect_spans, render_report

    txt = render_report(_events(log_path))
    assert "fabric workers" in txt and "w0" in txt and "w1" in txt

    # --- telemetry linkage (the 5-unlinked-timelines bug): the
    # coordinator pins its run id into worker env, so EVERY record —
    # coordinator, w0, w1 — shares one run_id instead of 3 uuids
    evs = _events(log_path)
    assert len({e["run_id"] for e in evs}) == 1
    # ...and the workers' shard spans join the coordinator's trace:
    # remote-parented onto the sweep span via RAFT_TPU_TRACEPARENT
    spans_, _ = collect_spans(evs)
    sweep = [s for s in spans_ if s["name"] == "sweep"][-1]
    shard_spans = [s for s in spans_ if s["name"] == "shard"
                   and s["pid"] != os.getpid()]
    assert shard_spans, "worker shard spans missing from the capture"
    assert {s["trace_id"] for s in shard_spans} == {sweep["trace_id"]}
    assert {s["parent_id"] for s in shard_spans} == {sweep["span_id"]}
    # lease bookkeeping carries the trace context too: done records
    # written by workers stamp (trace_id, parent_span_id)
    ledger = fabric.Ledger(out_dir, 6)
    recs = [ledger.read_done(s) for s in range(6)]
    assert all(r.get("trace_id") == sweep["trace_id"] for r in recs)
    assert all(r.get("parent_span_id") == sweep["span_id"] for r in recs)


def test_kill_a_worker_completes_bit_identical(tmp_path, log_path,
                                               fabric_env, monkeypatch):
    """The acceptance scenario: SIGKILL one worker mid-shard -> its
    lease expires -> the shard is stolen -> the sweep completes with
    results bit-identical to a fault-free serial run (no duplicate or
    missing rows), manifest consistent."""
    cases = _cases(24, seed=2)
    serial = run_sweep_checkpointed_full(
        _fabric_entry._toy_full, cases, str(tmp_path / "serial"),
        shard_size=4, mesh=mesh2())

    # worker_kill goes to worker index RAFT_TPU_FABRIC_FAULT_WORKER
    # (default 0) ONLY; w1 survives and steals
    monkeypatch.setenv("RAFT_TPU_FAULTS", "worker_kill:worker_shard:1")
    out_dir = str(tmp_path / "fab")
    out = fabric.run_fabric(
        out_dir, workers=2, entry=f"{ENTRY_FILE}:slow_toy_entry",
        entry_kwargs={"delay_s": 0.25}, cases=cases,
        out_keys=("PSD", "X0"), shard_size=4)
    for k in serial:
        assert np.array_equal(np.asarray(serial[k]), out[k]), k
    assert len(out["X0"]) == 24                     # no dup/missing rows

    steals = _events(log_path, "shard_steal")
    # whichever rule notices the dead worker first wins: TTL expiry, or
    # the straggler threshold once enough shard walls pooled (the
    # survivor's fast shards can arm p95 * FABRIC_STEAL_MULT below the
    # 2s test TTL)
    assert steals and steals[0]["from_worker"] == "w0" \
        and steals[0]["reason"] in ("expired", "straggler", "holder_stale")
    # the whole drill — SIGKILL, steal, re-execution — happened under
    # ONE run_id: the killed worker, the stealer and the coordinator
    # all carry the pinned id, so the recovery story reads as one run
    assert len({e["run_id"] for e in _events(log_path)}) == 1
    exits = {e["worker"]: e["returncode"]
             for e in _events(log_path, "fabric_worker_exit")}
    assert exits["w0"] != 0 and exits["w1"] == 0    # SIGKILL really hit
    with open(os.path.join(out_dir, "manifest.json")) as f:
        man = json.load(f)
    assert all(man["shards"][str(s)]["status"] == "done"
               for s in range(6))
    stolen = steals[0]["shard"]
    assert man["shards"][str(stolen)]["worker"] == "w1"
    assert man["shards"][str(stolen)]["attempt"] == 2


def test_midsweep_join_picks_up_remaining_shards(tmp_path, log_path,
                                                 fabric_env):
    cases = _cases(24, seed=3)
    out_dir = str(tmp_path / "fab")
    fabric.init_sweep(out_dir, f"{ENTRY_FILE}:slow_toy_entry", cases,
                      ("PSD", "X0"), 4, entry_kwargs={"delay_s": 0.3})
    p0, w0 = fabric.spawn_worker(out_dir, index=0)
    # join mid-sweep: by the time a fresh process is up (~seconds of
    # jax import) the first worker is partway through the 6 shards
    time.sleep(1.0)
    p1, w1 = fabric.spawn_worker(out_dir, index=1)
    assert p0.wait(timeout=120) == 0 and p1.wait(timeout=120) == 0

    out = fabric.assemble(out_dir)
    np.testing.assert_array_equal(out["X0"], cases["Hs"] - cases["Tp"])
    ledger = fabric.Ledger(out_dir, 6)
    by_worker = {}
    for s in range(6):
        rec = ledger.read_done(s)
        by_worker.setdefault(rec["worker"], []).append(s)
    assert set(by_worker) == {"w0", "w1"}           # the joiner got work
    starts = _events(log_path, "fabric_worker_start")
    assert {e["worker"] for e in starts} == {"w0", "w1"}


def test_fabric_workers_env_routes_checkpointed_sweep(tmp_path, log_path,
                                                      fabric_env,
                                                      monkeypatch):
    """RAFT_TPU_FABRIC_WORKERS=2 + a stamped evaluator: the standard
    checkpointed driver runs N-way with zero caller changes."""
    cases = _cases(12, seed=4)
    serial = run_sweep_checkpointed_full(
        _fabric_entry._toy_full, cases, str(tmp_path / "serial"),
        shard_size=4, mesh=mesh2())
    monkeypatch.setenv("RAFT_TPU_FABRIC_WORKERS", "2")
    out = run_sweep_checkpointed_full(
        _fabric_entry.stamped_toy_evaluator(), cases,
        str(tmp_path / "fab"), shard_size=4, mesh=mesh2())
    for k in serial:
        assert np.array_equal(np.asarray(serial[k]), np.asarray(out[k])), k
    assert _events(log_path, "fabric_worker_spawn")

    # an unstamped closure cannot ship to workers: loud event, serial
    # fallback, same results
    out2 = run_sweep_checkpointed_full(
        _fabric_entry._toy_full, cases, str(tmp_path / "fallback"),
        shard_size=4, mesh=mesh2())
    for k in serial:
        assert np.array_equal(np.asarray(serial[k]), np.asarray(out2[k]))
    assert _events(log_path, "fabric_unavailable")


def test_all_workers_dead_raises_fabric_error(tmp_path, fabric_env,
                                              monkeypatch):
    cases = _cases(8, seed=5)
    # kill-fault forwarded to BOTH workers via FABRIC_FAULT_WORKER
    # pinning each index in turn is overkill — simply give each worker
    # enough kill shots by targeting index 0 with a 1-worker fleet
    monkeypatch.setenv("RAFT_TPU_FAULTS", "worker_kill:worker_shard:1")
    with pytest.raises(fabric.FabricError, match="workers exited"):
        fabric.run_fabric(
            str(tmp_path / "fab"), workers=1,
            entry=f"{ENTRY_FILE}:toy_entry", cases=cases,
            out_keys=("PSD", "X0"), shard_size=4)


def test_resume_after_serial_run_skips_done_shards(tmp_path, log_path,
                                                   fabric_env):
    """A fabric run over an out_dir holding valid serial shards resumes
    them (manifest-validated) instead of recomputing."""
    cases = _cases(8, seed=6)
    out_dir = str(tmp_path / "fab")
    serial = run_sweep_checkpointed_full(
        _fabric_entry._toy_full, cases, out_dir, shard_size=4,
        mesh=mesh2())
    out = fabric.run_fabric(
        out_dir, workers=1, entry=f"{ENTRY_FILE}:toy_entry",
        cases=cases, out_keys=("PSD", "X0"), shard_size=4)
    for k in serial:
        assert np.array_equal(np.asarray(serial[k]), out[k])
    resumes = _events(log_path, "shard_resume")
    assert sorted(e["shard"] for e in resumes) == [0, 1]
    # changed inputs against the same ledger fail loudly in the worker
    with pytest.raises(resilience.ManifestMismatchError):
        fabric.init_sweep(out_dir, f"{ENTRY_FILE}:toy_entry",
                          dict(cases, Hs=cases["Hs"] + 1.0),
                          ("PSD", "X0"), 4)


def test_fabric_resume_preserves_quarantine_audit(tmp_path, fabric_env):
    """Adopting (resuming) shards must NOT re-judge quarantine.json:
    a prior run's audit entries survive a fabric resume even though
    the resumed done records carry no entries themselves."""
    cases = _cases(8, seed=7)
    out_dir = str(tmp_path / "fab")
    with faults.inject("nan:shard_result:1"):
        run_sweep_checkpointed_full(
            _fabric_entry._toy_full, cases, out_dir, shard_size=4,
            mesh=mesh2(), quarantine_retry=False)
    before = resilience.load_quarantine(out_dir)
    assert [e["index"] for e in before] == [0]

    out = fabric.run_fabric(
        out_dir, workers=1, entry=f"{ENTRY_FILE}:toy_entry",
        cases=cases, out_keys=("PSD", "X0"), shard_size=4)
    assert np.isnan(out["X0"][0])            # the bad row is still bad
    after = resilience.load_quarantine(out_dir)
    assert after == before                    # audit intact
