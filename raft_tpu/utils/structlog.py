"""Structured (JSONL) event logging for long-running analyses.

SURVEY §5.1: the reference's only instrumentation is a wall-clock print
around the QTF loop (raft_model.py:1122-1126).  Here every analysis
stage can emit machine-readable events — stage name, wall time,
convergence diagnostics — as one JSON object per line.

Off by default (zero overhead beyond an env check).  Enable with

    RAFT_TPU_LOG=-            # JSONL to stderr
    RAFT_TPU_LOG=/path/f.jsonl  # JSONL appended to a file

Every record carries a monotonic ``t`` (seconds since process start),
an ``event`` name, the emitting ``pid`` and the process ``run_id``
(``RAFT_TPU_RUN_ID``, else a fresh uuid per process — pin it to keep a
resumed sweep's events linkable to the original run); records emitted
inside an :func:`raft_tpu.obs.span` additionally carry ``trace_id``/
``span_id``, so free-form events nest under the span that produced
them.  Everything else is free-form numeric/str payload.

The sink is shared by the main thread and the telemetry threads
(heartbeat sampler, :mod:`raft_tpu.obs.heartbeat`), so writes are
serialized by a lock — interleaved half-lines would corrupt the JSONL
stream for every downstream consumer (``python -m raft_tpu.obs
report``/``trace``).

Event *names* are registered centrally in :mod:`raft_tpu.obs.events`
and lint-enforced (``event-name`` rule): a typo'd name silently splits
an event stream, which is worse than a crash.
"""

from __future__ import annotations

import atexit
import contextvars
import json
import os
import sys
import threading
import time
import uuid

from raft_tpu.utils import config

_T0 = time.perf_counter()
_SINK = None  # raft-lint: guarded-by=_LOCK
_DEST = None  # raft-lint: guarded-by=_LOCK
# RLock: log_event re-resolves the sink while holding the lock (the
# handle must not be swapped/closed between resolution and write by a
# concurrent retarget), and _sink() itself locks the swap
_LOCK = threading.RLock()
_RUN_ID = None  # raft-lint: guarded-by=_LOCK

#: (trace_id, span_id) of the innermost active telemetry span in this
#: task/thread; managed by :class:`raft_tpu.obs.spans.span`.
SPAN_CTX: contextvars.ContextVar = contextvars.ContextVar(
    "raft_tpu_span_ctx", default=None)

#: Flight-recorder tap (:mod:`raft_tpu.obs.flight` installs itself here
#: at import).  Called as ``tap(event, payload)`` for EVERY log_event —
#: before the sink check, so the black-box ring keeps recording when
#: logging is off.  structlog deliberately does not import flight (the
#: dependency points the other way); the slot keeps this module
#: importable standalone.
_FLIGHT_TAP = None


def set_flight_tap(fn):
    """Install (or clear, with None) the flight-recorder tap."""
    global _FLIGHT_TAP
    _FLIGHT_TAP = fn


def run_id():
    """The telemetry run id stamped on every record: ``RAFT_TPU_RUN_ID``
    when set (re-read per call so a resuming orchestrator can pin it),
    else one fresh uuid12 per process."""
    rid = config.raw("RUN_ID")
    if rid:
        return rid
    global _RUN_ID
    if _RUN_ID is None:
        # locked: the heartbeat thread's first beat can race the main
        # thread's first event — one process must get ONE run id
        with _LOCK:
            if _RUN_ID is None:
                _RUN_ID = uuid.uuid4().hex[:12]
    return _RUN_ID


def _sink_path(dest):
    """The actual file path for a non-stderr dest: a directory (an
    existing one, or any path spelled with a trailing separator) holds
    one ``trace-<pid>.jsonl`` shard PER PROCESS — the cross-process
    capture layout `python -m raft_tpu.obs trace --merge` assembles
    (fabric coordinator + workers + server each own their shard, no
    cross-process write interleaving)."""
    if dest.endswith(os.sep) or dest.endswith("/") or os.path.isdir(dest):
        os.makedirs(dest, exist_ok=True)
        return os.path.join(dest, f"trace-{os.getpid()}.jsonl")
    return dest


def _sink():
    """Resolve the sink from RAFT_TPU_LOG, re-reading the env var on
    every call so setting/changing/unsetting it mid-process takes
    effect (file handles are swapped and closed at interpreter exit).
    The unset fast path is one dict lookup."""
    global _SINK, _DEST
    dest = config.raw("LOG") or ""
    if dest != _DEST:
        with _LOCK:
            if dest != _DEST:
                if _SINK is not None and _SINK is not sys.stderr:
                    try:
                        _SINK.close()
                    except Exception:
                        pass
                if dest == "-":
                    _SINK = sys.stderr
                elif dest:
                    _SINK = open(_sink_path(dest), "a")
                    atexit.register(_SINK.close)
                else:
                    _SINK = None
                _DEST = dest
    return _SINK


def enabled():
    return _sink() is not None


#: dests this process has written its clock anchor to (the merge
#: tooling needs one ``proc_start`` per (process, sink) to map the
#: monotonic ``t`` column onto a shared wall clock)
_ANCHORED: set = set()  # raft-lint: guarded-by=_LOCK


def _anchor_record():
    """The clock-anchor record: ``unix_t`` is the wall-clock time at
    which this record's monotonic ``t`` was read, so a merge can
    normalize every process's ``t`` onto one timeline
    (``wall = unix_t + (t - t_anchor)``)."""
    now = time.perf_counter() - _T0
    rec = {"t": round(now, 6), "event": "proc_start",
           "pid": os.getpid(), "run_id": run_id(),
           "unix_t": round(time.time(), 6),
           "argv0": os.path.basename(sys.argv[0] or "python")}
    wid = config.raw("WORKER_ID")
    if wid:
        rec["worker"] = wid
    return rec


def log_event(event, **payload):
    """Emit one JSONL event (no-op unless RAFT_TPU_LOG is set; the
    flight-recorder ring captures it either way)."""
    tap = _FLIGHT_TAP
    if tap is not None:
        tap(event, payload)
    s = _sink()
    if s is None:
        return
    rec = {"t": round(time.perf_counter() - _T0, 6), "event": event,
           "pid": os.getpid(), "run_id": run_id()}
    # fabric worker stamp: one shared RAFT_TPU_LOG capture holds every
    # worker's stream; the per-record worker id keeps them separable
    # (per-worker tables in `python -m raft_tpu.obs report`)
    wid = config.raw("WORKER_ID")
    if wid:
        rec["worker"] = wid
    ctx = SPAN_CTX.get()
    if ctx is not None:
        rec["trace_id"], rec["span_id"] = ctx
    for k, v in payload.items():
        if hasattr(v, "item"):
            try:
                v = v.item()
            except Exception:
                v = str(v)
        rec[k] = v
    # default=str: a non-JSON-serializable payload value (Path, dtype,
    # exception, device object) must never take down the analysis that
    # was merely trying to log it
    line = json.dumps(rec, default=str) + "\n"
    # one lock around resolve+write+flush: the heartbeat thread shares
    # the sink, and a concurrent RAFT_TPU_LOG retarget closes the old
    # handle — re-resolving under the lock keeps the write off a handle
    # another thread just closed
    with _LOCK:
        s = _sink()
        if s is None:
            return
        if _DEST not in _ANCHORED:
            # first record to this sink: lead with the clock anchor so
            # the merge tooling can place this process on a shared
            # wall-clock timeline
            _ANCHORED.add(_DEST)
            s.write(json.dumps(_anchor_record(), default=str) + "\n")
        s.write(line)
        s.flush()


class stage:
    """Context manager timing one analysis stage:

    with stage("solve_dynamics", case=2): ...
    emits {"event": "solve_dynamics", "wall_s": ..., **kw} on exit;
    a failing stage carries ok=False plus a truncated error=repr(exc).

    Prefer :func:`raft_tpu.obs.span` for new instrumentation — spans
    add trace/parent linkage and feed the metrics registry; ``stage``
    stays for flat one-shot timings and backward compatibility."""

    def __init__(self, name, **kw):
        self.name = name
        self.kw = kw

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if enabled():
            kw = dict(self.kw)
            if exc[0] is not None:
                kw["error"] = repr(exc[1])[:200]
            log_event(self.name, wall_s=round(time.perf_counter() - self.t0, 6),
                      ok=exc[0] is None, **kw)
        return False
