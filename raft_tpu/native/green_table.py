"""Free-surface Green-function wave-term tables.

The infinite-depth wave Green function (source + its free-surface wave
part; Wehausen & Laitone eq. 13.17, the same kernel the reference's
external HAMS solver evaluates in Fortran) is

    G = 1/r + 1/r1 + 2K L(R, Z) + i 2 pi K e^Z J0(R)

with nondimensional horizontal distance R = K R_h and vertical
Z = K (z + zeta) <= 0 (field + source depth), K = w^2/g, r1 the
distance to the image point, and the principal-value integral

    L(R, Z) = PV int_0^inf  e^{mu Z} J0(mu R) / (mu - 1)  d mu .

The gradient needs the companion J1 kernel

    M(R, Z) = PV int_0^inf  e^{mu Z} J1(mu R) / (mu - 1)  d mu

through the exact relations (all derived by mu/(mu-1) = 1 + 1/(mu-1)):

    dL/dZ = L + 1/d,          d = sqrt(R^2 + Z^2)
    dL/dR = -( (d - |Z|) / (R d)  +  M )

This module tabulates L and M once per process on a (ln d, alpha=R/d)
grid — the coordinates in which the d -> 0 log singularity is linear —
using scipy quadrature:

* [0, 2]: QAWC Cauchy-weight quadrature (exact PV handling);
* [2, inf): block integration between Bessel zeros with repeated
  averaging (Euler transform) of the alternating partial sums, which
  converges for the conditionally-convergent Z -> 0 tails.

The table is cached to disk next to this file; the C++ panel kernel
receives the raw arrays and interpolates bilinearly (the grid is dense
enough that bilinear error is ~1e-4 relative, far below panel
discretisation error).
"""

from __future__ import annotations

import os

import numpy as np

_CACHE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "_green_table_v1.npz")

# grid: ln d in [ln 1e-5, ln 700], alpha = R/d in [0, 1]
ND, NA = 280, 72
LND = np.linspace(np.log(1e-5), np.log(700.0), ND)
ALPHA = np.linspace(0.0, 1.0, NA)

_tables = None


def _tail_blocks(order, R, Z, a0, n_blocks=80, tol=1e-11):
    """int_a0^inf e^{mu Z} J_order(mu R)/(mu-1) dmu by Bessel-zero blocks
    with repeated averaging of the alternating partial sums."""
    from scipy.special import j0, j1, jn_zeros, exp1

    if R < 1e-12:
        if order == 1:
            return 0.0
        # J0 -> 1: exact via exponential integral (Z < 0 strictly)
        if Z > -1e-300:
            Z = -1e-300
        return np.exp(Z) * exp1((a0 - 1.0) * (-Z))

    jfun = j0 if order == 0 else j1
    zeros = jn_zeros(order, n_blocks + 2) / R
    bounds = [a0] + [z for z in zeros if z > a0]
    if len(bounds) < 3:
        # oscillation slower than any decay window: direct quad
        from scipy.integrate import quad

        val, _ = quad(lambda mu: np.exp(mu * Z) * jfun(mu * R) / (mu - 1.0),
                      a0, a0 + max(60.0 / max(-Z, 1e-3), 10 * np.pi / R),
                      limit=400)
        return val

    # integrate each block with fixed Gauss-Legendre
    gx, gw = np.polynomial.legendre.leggauss(12)
    vals = []
    for i in range(len(bounds) - 1):
        a, b = bounds[i], bounds[i + 1]
        mu = 0.5 * (a + b) + 0.5 * (b - a) * gx
        f = np.exp(mu * Z) * jfun(mu * R) / (mu - 1.0)
        vals.append(0.5 * (b - a) * np.dot(gw, f))
        if abs(vals[-1]) < tol and i > 2:
            break
    partial = np.cumsum(vals)
    # repeated averaging (Euler transform) for the alternating tail
    s = partial[max(0, len(partial) - 24):].astype(float)
    while len(s) > 1:
        s = 0.5 * (s[1:] + s[:-1])
    return float(s[0])


def _pv_node(order, R, Z):
    """PV int_0^inf e^{mu Z} J_order(mu R)/(mu-1) dmu."""
    from scipy.integrate import quad
    from scipy.special import j0, j1

    jfun = j0 if order == 0 else j1

    def f(mu):
        return np.exp(mu * Z) * jfun(mu * R)

    # PV over [0, 2] via Cauchy-weight quadrature
    I1, _ = quad(f, 0.0, 2.0, weight="cauchy", wvar=1.0, limit=200)
    return I1 + _tail_blocks(order, R, Z, 2.0)


def build_tables(verbose=False):
    """Build (or load cached) L and M tables.  Returns dict with
    lnd, alpha, L, M arrays (L/M shaped (ND, NA))."""
    global _tables
    if _tables is not None:
        return _tables
    if os.path.exists(_CACHE):
        d = np.load(_CACHE)
        if (len(d["lnd"]) == ND and len(d["alpha"]) == NA):
            _tables = dict(lnd=d["lnd"], alpha=d["alpha"], L=d["L"], M=d["M"])
            return _tables

    L = np.zeros((ND, NA))
    M = np.zeros((ND, NA))
    for i, ld in enumerate(LND):
        d = np.exp(ld)
        for j, a in enumerate(ALPHA):
            R = d * a
            Z = -d * np.sqrt(max(0.0, 1.0 - a * a))
            L[i, j] = _pv_node(0, R, Z)
            M[i, j] = _pv_node(1, R, Z)
        if verbose and i % 20 == 0:
            print(f"green table row {i}/{ND}")
    _tables = dict(lnd=LND, alpha=ALPHA, L=L, M=M)
    try:
        np.savez_compressed(_CACHE, **_tables)
    except OSError:
        pass
    return _tables


def interp_L(R, Z):
    """Reference (numpy) bilinear interpolation — the same scheme the
    C++ kernel uses; exposed for table self-tests."""
    t = build_tables()
    d = np.sqrt(R**2 + Z**2)
    d = np.clip(d, np.exp(t["lnd"][0]), np.exp(t["lnd"][-1]))
    a = np.clip(R / d, 0.0, 1.0)
    x = np.log(d)
    i = np.clip(np.searchsorted(t["lnd"], x) - 1, 0, ND - 2)
    j = np.clip(np.searchsorted(t["alpha"], a) - 1, 0, NA - 2)
    fx = (x - t["lnd"][i]) / (t["lnd"][i + 1] - t["lnd"][i])
    fa = (a - t["alpha"][j]) / (t["alpha"][j + 1] - t["alpha"][j])
    T = t["L"]
    return ((1 - fx) * (1 - fa) * T[i, j] + fx * (1 - fa) * T[i + 1, j]
            + (1 - fx) * fa * T[i, j + 1] + fx * fa * T[i + 1, j + 1])
