"""raft_tpu — a TPU-native (JAX/XLA) frequency-domain dynamics framework for
floating offshore wind (and marine hydrokinetic) turbines.

This is a ground-up re-design of the capabilities of WISDEM/RAFT (the
reference implementation lives at /root/reference; see SURVEY.md for the
layer map) built TPU-first:

* All physics kernels are pure ``jax.numpy`` functions over pytrees of
  statically-shaped arrays, so they ``jit``/``vmap`` over frequency,
  wave heading, load case and *design* axes, and ``shard_map`` over a
  ``jax.sharding.Mesh`` for pod-scale design sweeps.
* Model *structure* (member strip discretisation, joint/DOF-reduction
  topology) is resolved once at build time in Python/numpy, producing the
  padded tensors and transformation matrices the kernels consume — the
  moral equivalent of tracing: topology is static, parameters are traced.

Package layout
--------------
``raft_tpu.ops``        low-level math kernels (transforms, frustum
                        integrals, wave kinematics, spectra).
``raft_tpu.structure``  build-time geometry + topology (schema parsing,
                        strip discretisation, DOF reduction).
``raft_tpu.physics``    statics, Morison hydrodynamics, mooring, aero.
``raft_tpu.models``     FOWT / Model assembly and the dynamics solver.
``raft_tpu.parallel``   device-mesh sweep drivers (vmap/shard_map).
"""

__version__ = "0.1.0"


def __getattr__(name):  # lazy: keep `import raft_tpu.ops` light
    if name == "Model":
        from raft_tpu.models.model import Model

        return Model
    raise AttributeError(name)
