"""Recompilation sentinel: count XLA backend compiles, assert budgets.

A recompilation storm is the quiet failure mode of a jit-heavy
pipeline: a shape that varies per call, a config arg traced instead of
static, a closure rebuilt per invocation — each turns a cached-in-
microseconds dispatch into seconds of XLA work, silently.  The
sentinel makes the count observable and assertable:

    from raft_tpu.analysis import recompile

    with recompile.count_compilations() as log:
        run_sweep(...)
    print(log.count)

    # steady state must be compile-free: second identical run => 0
    run_sweep(...)                       # warm (compiles, fills caches)
    with recompile.assert_compile_budget(0):
        run_sweep(...)                   # identical -> raises if any

Counting hooks jax's own monitoring stream (the
``/jax/core/compile/backend_compile_duration`` event fires once per
actual backend compilation, cache hits don't emit it), so eager-op
compiles are counted too — exactly the ones that sneak past
jit-centric reasoning.  ``bench.py`` reports the steady-state count in
its breakdown (``steady_state_recompiles``), and
``tests/test_trace_contracts.py`` asserts the zero-budget invariant on
a repeated sweep invocation in the tier-1 suite.
"""

from __future__ import annotations

import contextlib

COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


class RecompilationError(AssertionError):
    """More backend compilations than the declared budget."""


class CompileLog:
    """Mutable counter the listener writes into (exposed by the
    context managers)."""

    def __init__(self):
        self.count = 0
        self.seconds = []

    @property
    def total_seconds(self):
        return sum(self.seconds)

    def __repr__(self):
        return (f"CompileLog(count={self.count}, "
                f"total_seconds={self.total_seconds:.3f})")


# ONE process-wide listener dispatching to the currently-active logs:
# jax's public monitoring API has no unregister, so per-use listeners
# would accumulate forever in a long-running process (one sentinel
# scope per sweep iteration is the advertised pattern).  The single
# listener costs a string compare per event when no scope is active.
_ACTIVE_LOGS: list = []
_registered = False


def _listener(event, duration_secs, **kwargs):
    if event == COMPILE_EVENT:
        # feed the telemetry registry unconditionally: total compile
        # count + time are part of every metrics snapshot
        # (raft_tpu.obs.metrics), not just of sentinel scopes
        from raft_tpu.obs import metrics

        metrics.counter("xla_compiles").inc()
        metrics.histogram("xla_compile_s").observe(duration_secs)
        for log in _ACTIVE_LOGS:
            log.count += 1
            log.seconds.append(duration_secs)


def install():
    """Register the process-wide compile listener (idempotent) so the
    ``xla_compiles`` counter / ``xla_compile_s`` histogram count every
    backend compilation from now on — called by
    :func:`raft_tpu.utils.devices.enable_compile_cache`, i.e. by every
    driver/sweep/bench entry point."""
    import jax.monitoring

    global _registered
    if not _registered:
        jax.monitoring.register_event_duration_secs_listener(_listener)
        _registered = True


@contextlib.contextmanager
def count_compilations():
    """Context manager yielding a :class:`CompileLog` that counts every
    XLA backend compilation inside the block (nesting-safe)."""
    install()
    log = CompileLog()
    _ACTIVE_LOGS.append(log)
    try:
        yield log
    finally:
        _ACTIVE_LOGS.remove(log)


@contextlib.contextmanager
def assert_compile_budget(budget=0, what="this block"):
    """Assert at most ``budget`` backend compilations happen inside the
    block (default 0: the steady-state invariant — a second identical
    driver/sweep run must be compile-free)."""
    with count_compilations() as log:
        yield log
    if log.count > budget:
        raise RecompilationError(
            f"{log.count} backend compilation(s) in {what} "
            f"(budget {budget}, {log.total_seconds:.2f}s of XLA work) — "
            "a shape/config/closure is varying between calls that "
            "should hit the jit cache")
