"""Batched small-N complex linear solves, accelerator-native.

The per-frequency impedance solve ``Z(w) xi(w) = F(w)`` is the
framework's hot path: tiny (nDOF x nDOF, nDOF <= 12 for rigid bodies)
*complex* systems batched over (frequency x case x design).  The
generic ``jnp.linalg.solve`` route lowers to a pivoted LU — on TPU a
poor fit for small batched matrices (the complex arithmetic lowers to
real pairs, but the LU itself is an opaque kernel that neither fuses
with the surrounding program nor vectorises well at N=6), and on CPU a
per-matrix LAPACK dispatch.

``solve`` instead embeds each complex system in its real 2N x 2N block
form

    [[Ar, -Ai],      [[xr],     [[br],
     [Ai,  Ar]]  @    [xi]]  =   [bi]]

and eliminates it with *pivot-free blocked Gaussian elimination*: the
elimination proceeds in 2x2 blocks whose pivots are the embedded
complex diagonal entries ``[[ar, -ai], [ai, ar]]``, inverted in closed
form with determinant ``ar^2 + ai^2 = |z|^2``.  Block-wise elimination
of the embedding is algebraically exact complex Gaussian elimination
without pivoting — safe for impedance matrices, whose diagonal
``-w^2 M_ii + C_ii + i w B_ii`` never vanishes (the damping term keeps
``|z| > 0`` through resonance crossings where the real part changes
sign, exactly where a *real* pivot-free elimination would die).  The
whole solve is unrolled over the static N (specialised for N <= 12)
into plain mul/add/div ops over the batch — one fusable XLA loop nest,
no pivot permutations, no LAPACK round trips.

Flag-gating: ``RAFT_TPU_SOLVER=native`` (default), ``lapack``
(golden-parity fallback through ``jnp.linalg.solve``), or ``pallas``
(the single-kernel Pallas prototype of the same block elimination —
see :func:`_pallas_solve`).  Read at trace time.  Systems larger than
``MAX_NATIVE_N`` always take the lapack path (e.g. the 150-DOF
flexible tower), so goldens of large reduced models are
solver-flag independent.

The Pallas path lays the batch out as the LANE axis — (real, imag)
planes of shape ``(N, N, block)`` per grid step, every elimination op
an elementwise ``(N-ish, block)`` vector op — so on TPU the whole
unrolled solve is ONE kernel over VMEM-resident tiles instead of an
XLA loop nest.  On this CPU build host the kernel runs in Pallas
INTERPRET mode: numerics/shape semantics are validated end to end
(parity vs native <=1e-12, tests/test_linsolve.py), the TPU lowering
itself is not exercised — keep ``native`` the default and treat the
achieved-GFLOP/s ledger column as the honest before/after when a TPU
host measures the compiled kernel.  The kernel has no autodiff rule:
``jax.grad`` through a ``SOLVER=pallas`` evaluator is unsupported
(the drag fixed point's ``custom_root`` tangent solve calls back into
:func:`solve`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.utils import config

# beyond this the O(N^3) unrolled elimination stops paying for itself
# (and pivot-free growth becomes a real concern) — generic LU takes over
MAX_NATIVE_N = 12


def solver_path(n=None):
    """Resolve the active solver for size-``n`` systems.

    Returns ``'native'``, ``'lapack'`` or ``'pallas'``; raises on an
    unknown ``RAFT_TPU_SOLVER`` value so typos fail loudly, not
    silently slow.  Oversized systems always fall back to lapack.
    """
    mode = config.get("SOLVER")
    if n is not None and n > MAX_NATIVE_N:
        return "lapack"
    return mode


def solve(Z, F, path=None):
    """Solve ``Z x = F`` for batched small complex systems.

    Z : (..., N, N) complex; F : (..., N) vector right-hand sides.
    Batch dims broadcast (e.g. Z (nw, N, N) against F (nH, nw, N)).
    ``path`` overrides the ``RAFT_TPU_SOLVER`` flag
    ('native'/'lapack'/'pallas').
    """
    N = Z.shape[-1]
    if path is None:
        path = solver_path(N)
    elif path not in ("native", "lapack", "pallas"):
        raise ValueError(
            f"path={path!r}: expected 'native', 'lapack' or 'pallas'")
    elif N > MAX_NATIVE_N:
        path = "lapack"
    if path == "lapack":
        return jnp.linalg.solve(Z, F[..., None])[..., 0]
    if path == "pallas":
        return _pallas_solve(Z, F)
    return _native_solve(Z, F)


def cond_estimate(Z, path=None):
    """Cheap 1-norm condition estimate of batched small systems.

    ``kappa_1(Z) = ||Z||_1 ||Z^-1||_1`` with ``||Z||_1`` exact (max
    column abs sum) and ``||Z^-1||_1`` lower-bounded by one Hager
    step: ``x = Z^-1 e`` with ``e = ones/N`` (so ``||e||_1 = 1``)
    gives ``||x||_1 <= ||Z^-1||_1``.  One extra batched solve of the
    system being health-checked — the same kernel, so the estimate
    rides the native path and fuses with it.  Being a lower bound it
    can under-flag a pathological matrix, never false-positive a
    healthy one; the solver-health layer (``RAFT_TPU_COND_CHECK``)
    compares it against ``RAFT_TPU_COND_THRESHOLD``.

    Z : (..., N, N) complex -> (...) real estimate.
    """
    N = Z.shape[-1]
    norm1 = jnp.max(jnp.sum(jnp.abs(Z), axis=-2), axis=-1)
    e = jnp.full(Z.shape[:-2] + (N,), 1.0 / N, dtype=Z.dtype)
    inv_lb = jnp.sum(jnp.abs(solve(Z, e, path=path)), axis=-1)
    return norm1 * inv_lb


def _native_solve(Z, F):
    """Pivot-free blocked elimination of the real 2N x 2N embedding.

    Carried as explicit (real, imag) pairs — the 2x2 block structure of
    the embedding never needs materialising, and every op is real
    mul/add/div that XLA fuses across the batch.
    """
    N = Z.shape[-1]
    Ar, Ai = jnp.real(Z), jnp.imag(Z)
    br, bi = jnp.real(F), jnp.imag(F)
    # broadcast the RHS batch against the matrix batch up front so the
    # row updates see consistent shapes either way round
    bshape = jnp.broadcast_shapes(Ar.shape[:-2], br.shape[:-1])
    # SSA row lists (each (..., N)) instead of in-place .at[] updates:
    # the elimination becomes a pure elementwise dataflow graph XLA
    # fuses across the batch, with no dynamic-update-slice chains
    rows = [(jnp.broadcast_to(Ar[..., i, :], bshape + (N,)),
             jnp.broadcast_to(Ai[..., i, :], bshape + (N,)))
            for i in range(N)]
    rhs = [(jnp.broadcast_to(br[..., i], bshape),
            jnp.broadcast_to(bi[..., i], bshape)) for i in range(N)]

    # forward elimination, unrolled over the static N: eliminate the
    # 2x2 pivot block [[ar,-ai],[ai,ar]] (det = |z|^2) at step k
    for kk in range(N - 1):
        pkr, pki = rows[kk]
        fr, fi = rhs[kk]
        pr, pi = pkr[..., kk], pki[..., kk]
        d = pr * pr + pi * pi
        ivr, ivi = pr / d, -pi / d                       # 1/z_kk
        for ii in range(kk + 1, N):
            air, aii = rows[ii]
            cr, ci = air[..., kk], aii[..., kk]
            mr = cr * ivr - ci * ivi                     # multiplier
            mi = cr * ivi + ci * ivr
            rows[ii] = (air - (mr[..., None] * pkr - mi[..., None] * pki),
                        aii - (mr[..., None] * pki + mi[..., None] * pkr))
            gr, gi = rhs[ii]
            rhs[ii] = (gr - (mr * fr - mi * fi), gi - (mr * fi + mi * fr))

    # back substitution (unrolled, complex arithmetic as pairs)
    xr = [None] * N
    xi = [None] * N
    for kk in range(N - 1, -1, -1):
        sr, si = rhs[kk]
        akr, aki = rows[kk]
        for jj in range(kk + 1, N):
            ar, ai = akr[..., jj], aki[..., jj]
            sr = sr - (ar * xr[jj] - ai * xi[jj])
            si = si - (ar * xi[jj] + ai * xr[jj])
        pr, pi = akr[..., kk], aki[..., kk]
        d = pr * pr + pi * pi
        xr[kk] = (sr * pr + si * pi) / d
        xi[kk] = (si * pr - sr * pi) / d
    return jax.lax.complex(jnp.stack(xr, axis=-1), jnp.stack(xi, axis=-1))


# ----------------------------------------------------- pallas prototype

#: batch rows per kernel instance: the LANE axis of every elimination
#: op (TPU vector registers are 128 lanes wide; interpret mode is
#: shape-agnostic but keeps the same blocking so the validated program
#: is the one a TPU would compile)
PALLAS_BLOCK = 128


def _ge_kernel(N):
    """Pallas kernel body: pivot-free blocked GE of one batch block.

    Refs are (real, imag) planes laid out batch-LAST — Z as
    ``(N, N, bs)``, F/x as ``(N, bs)`` — so every elimination update is
    an elementwise op over the ``bs`` lane axis (VPU-shaped on TPU);
    the whole unrolled solve is straight-line code inside ONE kernel,
    no XLA loop nest, no pivot permutations.  Algebra is identical to
    :func:`_native_solve` (same SSA row elimination), so interpret-mode
    parity on CPU validates exactly the program a TPU would compile.
    """

    def kernel(zr_ref, zi_ref, fr_ref, fi_ref, xr_ref, xi_ref):
        rows = [(zr_ref[i], zi_ref[i]) for i in range(N)]   # (N, bs) each
        rhs = [(fr_ref[i], fi_ref[i]) for i in range(N)]    # (bs,) each
        for kk in range(N - 1):
            pkr, pki = rows[kk]
            fr, fi = rhs[kk]
            pr, pi = pkr[kk], pki[kk]                       # (bs,)
            d = pr * pr + pi * pi
            ivr, ivi = pr / d, -pi / d                      # 1/z_kk
            for ii in range(kk + 1, N):
                air, aii = rows[ii]
                cr, ci = air[kk], aii[kk]
                mr = cr * ivr - ci * ivi                    # multiplier
                mi = cr * ivi + ci * ivr
                rows[ii] = (
                    air - (mr[None, :] * pkr - mi[None, :] * pki),
                    aii - (mr[None, :] * pki + mi[None, :] * pkr))
                gr, gi = rhs[ii]
                rhs[ii] = (gr - (mr * fr - mi * fi),
                           gi - (mr * fi + mi * fr))
        xr = [None] * N
        xi = [None] * N
        for kk in range(N - 1, -1, -1):
            sr, si = rhs[kk]
            akr, aki = rows[kk]
            for jj in range(kk + 1, N):
                ar, ai = akr[jj], aki[jj]
                sr = sr - (ar * xr[jj] - ai * xi[jj])
                si = si - (ar * xi[jj] + ai * xr[jj])
            pr, pi = akr[kk], aki[kk]
            d = pr * pr + pi * pi
            xr[kk] = (sr * pr + si * pi) / d
            xi[kk] = (si * pr - sr * pi) / d
        for kk in range(N):
            xr_ref[kk] = xr[kk]
            xi_ref[kk] = xi[kk]

    return kernel


def _pallas_solve(Z, F, block=None, interpret=None):
    """Batched small-N complex solve as ONE Pallas kernel.

    The broadcast batch flattens and transposes to the trailing (lane)
    axis, padded by edge replication to a ``block`` multiple (padded
    lanes solve a copy of the last real system — benign, dropped on
    reshape; zero-padding would divide by zero in the pivot inverse).
    ``interpret`` defaults to True off-TPU: on this CPU host the
    kernel runs under the Pallas interpreter (parity validation), on a
    TPU backend it compiles for real.
    """
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    import math

    N = Z.shape[-1]
    bshape = np.broadcast_shapes(Z.shape[:-2], F.shape[:-1])
    B = math.prod(bshape) if bshape else 1
    bs = min(PALLAS_BLOCK, max(B, 1)) if block is None else int(block)
    pad = (-B) % bs
    # batch-last planes: (N, N, B) / (N, B)
    Zb = jnp.moveaxis(
        jnp.broadcast_to(Z, bshape + (N, N)).reshape(-1, N, N), 0, -1)
    Fb = jnp.moveaxis(
        jnp.broadcast_to(F, bshape + (N,)).reshape(-1, N), 0, -1)
    if pad:
        Zb = jnp.concatenate([Zb, jnp.repeat(Zb[..., -1:], pad, -1)], -1)
        Fb = jnp.concatenate([Fb, jnp.repeat(Fb[..., -1:], pad, -1)], -1)
    nblk = (B + pad) // bs
    rdt = jnp.real(Zb).dtype
    mat_spec = pl.BlockSpec((N, N, bs), lambda i: (0, 0, i))
    vec_spec = pl.BlockSpec((N, bs), lambda i: (0, i))
    out = pl.pallas_call(
        _ge_kernel(N),
        grid=(nblk,),
        in_specs=[mat_spec, mat_spec, vec_spec, vec_spec],
        out_specs=[vec_spec, vec_spec],
        out_shape=[jax.ShapeDtypeStruct((N, B + pad), rdt)] * 2,
        interpret=interpret,
    )(jnp.real(Zb), jnp.imag(Zb), jnp.real(Fb), jnp.imag(Fb))
    x = jax.lax.complex(out[0], out[1])[:, :B]          # (N, B)
    return jnp.moveaxis(x, 0, -1).reshape(bshape + (N,))
