"""Frustum (tapered-cylinder / tapered-cuboid) geometry kernels.

jax.numpy, fully batched re-derivations of the closed-form volume /
centroid / moment-of-inertia formulas the reference uses for member
sections (``/root/reference/raft/helpers.py``: ``FrustumVCV`` :36,
``FrustumMOI`` :65, ``RectangularFrustumMOI`` :85).

All functions are safe under ``vmap``/``jit``: degenerate inputs
(zero height, zero taper, zero area) are handled with ``jnp.where``
guards instead of Python branches, with the divide-by-zero operands
sanitised *before* the division so no NaNs leak through gradients.
"""

from __future__ import annotations

import jax.numpy as jnp


def _safe_div(num, den, fallback=0.0):
    """num/den with den==0 mapped to ``fallback`` (NaN-safe under grad)."""
    den_ok = den != 0
    den_safe = jnp.where(den_ok, den, 1.0)
    return jnp.where(den_ok, num / den_safe, fallback)


def frustum_vcv_circ(dA, dB, H):
    """Volume and axial centroid (from the dA end) of a circular frustum.

    helpers.py:36-63 (scalar-diameter branch). Returns (V, hc).
    """
    A1 = 0.25 * jnp.pi * dA**2
    A2 = 0.25 * jnp.pi * dB**2
    Am = 0.25 * jnp.pi * dA * dB
    V = (A1 + A2 + Am) * H / 3.0
    hc = _safe_div(A1 + 2.0 * Am + 3.0 * A2, A1 + Am + A2) * H / 4.0
    return V, hc


def frustum_vcv_rect(slA, slB, H):
    """Volume and axial centroid of a rectangular frustum.

    helpers.py:48-56 (side-length-pair branch). slA/slB: (..., 2).
    """
    A1 = slA[..., 0] * slA[..., 1]
    A2 = slB[..., 0] * slB[..., 1]
    Am = jnp.sqrt(A1 * A2)
    V = (A1 + A2 + Am) * H / 3.0
    hc = _safe_div(A1 + 2.0 * Am + 3.0 * A2, A1 + Am + A2) * H / 4.0
    return V, hc


def frustum_moi_circ(dA, dB, H, rho):
    """Radial and axial mass moments of inertia of a (possibly tapered)
    circular frustum about its dA-end node.  helpers.py:65-83.

    Returns (I_rad, I_ax).  The cylinder limit (dA == dB) is handled by
    an explicit where-guard matching the reference's dedicated formula
    (the tapered formula is 0/0 there).
    """
    r1 = dA / 2.0
    r2 = dB / 2.0
    # cylinder branch (helpers.py:72-76)
    I_rad_cyl = (1.0 / 12.0) * (rho * H * jnp.pi * r1**2) * (3.0 * r1**2 + 4.0 * H**2)
    I_ax_cyl = 0.5 * rho * jnp.pi * H * r1**4
    # tapered branch (helpers.py:77-81)
    dr = r2 - r1
    dr_safe = jnp.where(dr == 0, 1.0, dr)
    r5 = (r2**5 - r1**5) / dr_safe
    I_rad_tap = (1.0 / 20.0) * rho * jnp.pi * H * r5 + (1.0 / 30.0) * rho * jnp.pi * H**3 * (
        r1**2 + 3.0 * r1 * r2 + 6.0 * r2**2
    )
    I_ax_tap = (1.0 / 10.0) * rho * jnp.pi * H * r5
    is_cyl = dr == 0
    I_rad = jnp.where(is_cyl, I_rad_cyl, I_rad_tap)
    I_ax = jnp.where(is_cyl, I_ax_cyl, I_ax_tap)
    zero = H == 0
    return jnp.where(zero, 0.0, I_rad), jnp.where(zero, 0.0, I_ax)


def frustum_moi_rect(slA, slB, H, rho):
    """Moments of inertia (Ixx, Iyy, Izz) of a tapered cuboid about its
    slA-end node.  helpers.py:85-146.

    slA/slB: (..., 2) as (L, W) pairs.  The reference has four explicit
    branches (cuboid / double-taper / single-taper in L or W); here the
    double-taper ("truncated pyramid") closed form is evaluated with the
    degenerate differences guarded, and the special cases are recovered
    by where-selection so values match the reference bit-for-bit in each
    regime.
    """
    La, Wa = slA[..., 0], slA[..., 1]
    Lb, Wb = slB[..., 0], slB[..., 1]

    # --- cuboid branch (La==Lb and Wa==Wb), helpers.py:98-105
    M = rho * La * Wa * H
    Ixx_c = (1.0 / 12.0) * M * (Wa**2 + 4.0 * H**2)
    Iyy_c = (1.0 / 12.0) * M * (La**2 + 4.0 * H**2)
    Izz_c = (1.0 / 12.0) * M * (La**2 + Wa**2)

    # --- full double-taper branch, helpers.py:107-119
    dL = Lb - La
    dW = Wb - Wa
    x2 = (1.0 / 12.0) * rho * (
        dL**3 * H * (Wb / 5.0 + Wa / 20.0)
        + dL**2 * La * H * (3.0 * Wb / 4.0 + Wa / 4.0)
        + dL * La**2 * H * (Wb + Wa / 2.0)
        + La**3 * H * (Wb / 2.0 + Wa / 2.0)
    )
    y2 = (1.0 / 12.0) * rho * (
        dW**3 * H * (Lb / 5.0 + La / 20.0)
        + dW**2 * Wa * H * (3.0 * Lb / 4.0 + La / 4.0)
        + dW * Wa**2 * H * (Lb + La / 2.0)
        + Wa**3 * H * (Lb / 2.0 + La / 2.0)
    )
    z2 = rho * (Wb * Lb / 5.0 + Wa * Lb / 20.0 + La * Wb / 20.0 + Wa * La / 30.0) * H**3
    Ixx_t = y2 + z2
    Iyy_t = x2 + z2
    Izz_t = x2 + y2

    # --- single-taper branches, helpers.py:121-141
    # La==Lb, Wa!=Wb (taper only in W)
    x2_w = (1.0 / 24.0) * rho * (La**3) * H * (Wb + Wa)
    y2_w = (1.0 / 48.0) * rho * La * H * (Wb**3 + Wa * Wb**2 + Wa**2 * Wb + Wa**3)
    z2_w = (1.0 / 12.0) * rho * La * (H**3) * (3.0 * Wb + Wa)
    # Wa==Wb, La!=Lb (taper only in L)
    x2_l = (1.0 / 48.0) * rho * Wa * H * (Lb**3 + La * Lb**2 + La**2 * Lb + La**3)
    y2_l = (1.0 / 24.0) * rho * (Wa**3) * H * (Lb + La)
    z2_l = (1.0 / 12.0) * rho * Wa * (H**3) * (3.0 * Lb + La)

    sameL = dL == 0
    sameW = dW == 0
    x2s = jnp.where(sameL, jnp.where(sameW, 0.0, x2_w), jnp.where(sameW, x2_l, x2))
    y2s = jnp.where(sameL, jnp.where(sameW, 0.0, y2_w), jnp.where(sameW, y2_l, y2))
    z2s = jnp.where(sameL, jnp.where(sameW, 0.0, z2_w), jnp.where(sameW, z2_l, z2))

    both_same = sameL & sameW
    Ixx = jnp.where(both_same, Ixx_c, y2s + z2s)
    Iyy = jnp.where(both_same, Iyy_c, x2s + z2s)
    Izz = jnp.where(both_same, Izz_c, x2s + y2s)

    zero = H == 0
    return (
        jnp.where(zero, 0.0, Ixx),
        jnp.where(zero, 0.0, Iyy),
        jnp.where(zero, 0.0, Izz),
    )
