"""Release-manifest tests (fast, jax-free paths): cut / verify /
promote / rollback over a tmp releases dir — atomic current flips,
tampered-manifest rejection, parent-chain walks, the rollout-marker
parity window, and the mismatch classifier that names why a bank went
cold."""

import json
import os

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def rel_env(tmp_path, monkeypatch):
    """A release module pointed at an empty tmp AOT dir (every path in
    release.py resolves through RAFT_TPU_AOT_DIR on each call)."""
    from raft_tpu.aot import release

    monkeypatch.setenv("RAFT_TPU_AOT_DIR", str(tmp_path))
    # the parity cache is keyed by aot_dir but ~1s fresh — reset it so
    # parallel tmp dirs never serve each other's view
    release._PARITY_CACHE[:] = []
    return release


def _entries(n=2, sha_char="a"):
    return {f"k{i}": {"payload_sha256": sha_char * 64, "kind": "serve"}
            for i in range(n)}


def _cut(release, entries=None, flags="f" * 12, label=None, parent=None,
         promote_after=False):
    """A jax-free cut: build + write the manifest exactly like
    release.cut but with an injected flags fingerprint and entry set
    (no bank, no jax)."""
    from raft_tpu.aot import bank

    man = release.build_manifest(entries if entries is not None
                                 else _entries(),
                                 bank.code_fingerprint(), flags,
                                 parent=parent, label=label)
    os.makedirs(release.releases_dir(), exist_ok=True)
    bank._atomic_write(
        release.manifest_path(man["release"]),
        (json.dumps(man, indent=1, sort_keys=True) + "\n").encode())
    if promote_after:
        release.promote(man["release"])
    return man


# ------------------------------------------------------- identity & sign


def test_release_id_is_content_addressed(rel_env):
    release = rel_env
    a = _cut(release, flags="f1")
    b = _cut(release, flags="f1")
    c = _cut(release, flags="f2")
    # same content = same release (idempotent cut), different flags =
    # different id
    assert a["release"] == b["release"]
    assert a["release"] != c["release"]
    assert len(a["release"]) == 12
    # created/label are provenance, not identity
    d = _cut(release, flags="f1", label="relabeled")
    assert d["release"] == a["release"]


def test_verify_manifest_clean_and_tampered(rel_env):
    release = rel_env
    man = _cut(release)
    assert release.verify_manifest(man) == []
    # tamper one entry sha after the cut: signature AND content
    # address both break
    bad = json.loads(json.dumps(man))
    next(iter(bad["entries"].values()))["payload_sha256"] = "e" * 64
    problems = release.verify_manifest(bad)
    assert any("manifest_sha256" in p for p in problems)
    assert any("does not match its content" in p for p in problems)
    # a re-signed tamper still fails the content address
    resigned = release.sign_manifest(dict(bad))
    problems = release.verify_manifest(resigned)
    assert problems and all("manifest_sha256" not in p for p in problems)
    # swapped parent breaks the id too
    swapped = dict(man)
    swapped["parent"] = "fff000fff000"
    assert release.verify_manifest(release.sign_manifest(dict(swapped)))
    # not-a-manifest
    assert release.verify_manifest({"schema": "nope"})
    assert release.verify_manifest(None)


def test_checked_in_lint_fixtures_verify_as_expected(rel_env):
    """The lint.sh gate's fixture pair must keep meaning what the gate
    says: good verifies clean, tampered is caught."""
    release = rel_env
    fx = os.path.join(ROOT, "tests", "fixtures", "releases")
    with open(os.path.join(fx, "good.json"), encoding="utf-8") as f:
        good = json.load(f)
    with open(os.path.join(fx, "tampered.json"), encoding="utf-8") as f:
        tampered = json.load(f)
    assert release.verify_manifest(good) == []
    assert release.verify_manifest(tampered)


# ------------------------------------------------------ pointer lifecycle


def test_promote_flips_current_atomically(rel_env, tmp_path):
    release = rel_env
    a = _cut(release, flags="fa")
    b = _cut(release, flags="fb")
    assert release.current_release() is None
    assert release.promote(a["release"]) is None
    assert release.current_release() == a["release"]
    # promote returns the PREVIOUS id (the rollout driver logs it)
    assert release.promote(b["release"]) == a["release"]
    rid, man = release.resolve()
    assert rid == b["release"] and man["release"] == b["release"]
    # the pointer is one small json file written via atomic rename —
    # no tmp litter left beside it
    names = os.listdir(release.releases_dir())
    assert "current.json" in names
    assert not [n for n in names if n.endswith(".tmp")]


def test_promote_refuses_missing_or_tampered(rel_env):
    release = rel_env
    with pytest.raises(FileNotFoundError):
        release.promote("000000000000")
    man = _cut(release)
    # corrupt the stored manifest in place: promote must refuse
    path = release.manifest_path(man["release"])
    bad = json.loads(open(path, encoding="utf-8").read())
    bad["entries"]["k0"]["payload_sha256"] = "e" * 64
    with open(path, "w", encoding="utf-8") as f:
        json.dump(bad, f)
    with pytest.raises(ValueError, match="refusing to promote"):
        release.promote(man["release"])


def test_rollback_walks_to_parent(rel_env):
    release = rel_env
    a = _cut(release, flags="fa", promote_after=True)
    b = _cut(release, flags="fb", parent=a["release"],
             promote_after=True)
    assert release.current_release() == b["release"]
    assert release.rollback() == (b["release"], a["release"])
    assert release.current_release() == a["release"]
    # the root release has nothing to roll back to
    with pytest.raises(ValueError, match="no parent"):
        release.rollback()


def test_walk_parents_chain_and_cycle_guard(rel_env):
    release = rel_env
    a = _cut(release, flags="fa")
    b = _cut(release, flags="fb", parent=a["release"])
    c = _cut(release, flags="fc", parent=b["release"])
    chain = release.walk_parents(c["release"])
    assert [m["release"] for m in chain] == [c["release"], b["release"],
                                             a["release"]]
    # a manufactured parent cycle ends the walk instead of spinning
    a2 = json.loads(open(release.manifest_path(a["release"]),
                         encoding="utf-8").read())
    a2["parent"] = c["release"]
    with open(release.manifest_path(a["release"]), "w",
              encoding="utf-8") as f:
        json.dump(a2, f)
    chain = release.walk_parents(c["release"])
    assert len(chain) == 3


def test_list_releases_newest_first_skips_pointers(rel_env):
    release = rel_env
    assert release.list_releases() == []
    a = _cut(release, flags="fa", promote_after=True)
    b = _cut(release, flags="fb", parent=a["release"])
    release.write_rollout_marker(a["release"], b["release"])
    ids = [m["release"] for m in release.list_releases()]
    assert set(ids) == {a["release"], b["release"]}
    assert ids[0] == b["release"]  # newest first
    # a foreign json in the dir is ignored, never crashed on
    with open(os.path.join(release.releases_dir(), "junk.json"),
              "w", encoding="utf-8") as f:
        f.write("{not json")
    assert len(release.list_releases()) == 2


# ---------------------------------------------------- parity window view


def test_parity_context_rollout_window(rel_env):
    release = rel_env
    # no release infrastructure: None (legacy canary behavior)
    assert release.parity_context(now=0.0) is None
    a = _cut(release, entries=_entries(sha_char="a"), flags="fa",
             promote_after=True)
    ctx = release.parity_context(now=10.0)
    assert ctx["allowed"] == [a["release"]]
    assert ctx["entries"][a["release"]] == ["a" * 16]
    # mid-rollout BOTH ids are allowed, each with its own sha set
    b = _cut(release, entries=_entries(sha_char="b"), flags="fb",
             parent=a["release"])
    release.promote(b["release"])
    release.write_rollout_marker(a["release"], b["release"])
    ctx = release.parity_context(now=20.0)
    assert ctx["allowed"] == sorted([a["release"], b["release"]])
    assert ctx["entries"][b["release"]] == ["b" * 16]
    # the cache serves the stale view inside ttl, recomputes after
    release.clear_rollout_marker()
    assert release.parity_context(now=20.5)["allowed"] == ctx["allowed"]
    assert release.parity_context(now=30.0)["allowed"] == [b["release"]]


def test_version_aware_provenance_consistency(rel_env):
    """The canary contract: a mixed-version fleet mid-rollout is
    consistent; a replica on a release outside the window, or a sha
    outside its release's manifest, still splits."""
    from raft_tpu.obs.alerts import provenance_consistency

    releases = {"allowed": ["relA", "relB"],
                "entries": {"relA": ["a" * 16], "relB": ["b" * 16]}}
    # the stamp's bank_sha is the 16-char payload-sha prefix (see
    # serve.engine.build_provenance)
    prov = lambda rel, sha: {"release": rel, "bank_sha": sha,  # noqa: E731
                             "bank_key": "k", "code": "c", "flags": "f"}
    # mixed versions, each sha shipped by its release: expected state
    view = {"d": {"r0": prov("relA", "a" * 16),
                  "r1": prov("relB", "b" * 16)}}
    assert provenance_consistency(view, releases=releases)["consistent"]
    # same view WITHOUT the release context: the legacy check splits
    legacy = provenance_consistency(view)
    assert not legacy["consistent"]
    # a lone replica whose sha its release never shipped: genuine skew
    view = {"d": {"r0": prov("relA", "a" * 16),
                  "r1": prov("relB", "skew" + "e" * 12)}}
    res = provenance_consistency(view, releases=releases)
    assert not res["consistent"]
    assert any(s["field"] == "bank_sha" for s in res["splits"])
    # a release id outside the rollout window: split on "release"
    view = {"d": {"r0": prov("relZ", "a" * 16)}}
    res = provenance_consistency(view, releases=releases)
    assert any(s["field"] == "release" for s in res["splits"])


# ------------------------------------------------------------- diagnosis


def test_classify_mismatch_precedence(rel_env, monkeypatch):
    release = rel_env
    ladder = release.ladder_state()
    man = {"code": "c1", "flags": "f1", "ladder": dict(ladder)}
    assert release.classify_mismatch(man, "c2", "f1", ladder) == "code"
    assert release.classify_mismatch(man, "c1", "f2", ladder) == "flags"
    retuned = dict(ladder, SERVE_MAX_BATCH=999)
    assert release.classify_mismatch(man, "c1", "f1", retuned) == "ladder"
    assert release.classify_mismatch(man, "c1", "f1", ladder) == "avals"


def test_format_diagnosis_names_reason_and_fix(rel_env):
    release = rel_env
    report = {"release": "abc123abc123", "total": 4, "warmed": 2,
              "unwarmed": [{"design": "spar", "rows": 8, "key": "k1",
                            "reason": "ladder"},
                           {"design": "spar", "rows": 16, "key": "k2",
                            "reason": "bank-missing"}],
              "reason": "ladder"}
    lines = release.format_diagnosis(report,
                                     design_paths=["designs/spar.yaml"])
    text = "\n".join(lines)
    assert "2/4" in text and "UNWARMED" in text
    assert "why [ladder]" in text and "why [bank-missing]" in text
    # the printed fix is the exact runbook: warmup then cut --promote
    assert "python -m raft_tpu.aot warmup --kinds serve" in text
    assert "--design designs/spar.yaml" in text
    assert "release cut --promote" in text


def test_capture_env_only_set_flags(rel_env, monkeypatch):
    release = rel_env
    monkeypatch.delenv("RAFT_TPU_SERVE_MAX_BATCH", raising=False)
    monkeypatch.setenv("RAFT_TPU_BUCKET_STEPS", "strips=16,32")
    env = release.capture_env()
    assert env.get("RAFT_TPU_BUCKET_STEPS") == "strips=16,32"
    assert "RAFT_TPU_SERVE_MAX_BATCH" not in env


def test_release_cli_verify_manifest_paths(rel_env, tmp_path):
    """The CLI surface lint.sh gates on, exercised in-process."""
    from raft_tpu.aot.__main__ import main

    man = _cut(rel_env)
    path = rel_env.manifest_path(man["release"])
    assert main(["release", "verify", "--manifest", path]) == 0
    bad = json.loads(open(path, encoding="utf-8").read())
    bad["flags"] = "tampered"
    bad_path = str(tmp_path / "bad.json")
    with open(bad_path, "w", encoding="utf-8") as f:
        json.dump(bad, f)
    assert main(["release", "verify", "--manifest", bad_path]) == 1
    # list + promote + rollback round-trip through the CLI
    b = _cut(rel_env, flags="fb", parent=man["release"])
    assert main(["release", "promote", man["release"]]) == 0
    assert main(["release", "promote", b["release"]]) == 0
    assert main(["release", "list"]) == 0
    assert main(["release", "rollback"]) == 0
    assert rel_env.current_release() == man["release"]
