"""Shared helpers for tests that read structured-log captures.

Not a pytest module (underscore name).  Since PR 10 every structlog
sink opens with a ``proc_start`` clock-anchor record, so every test
that used to assert on ``lines[0]`` (or count events) needs the anchor
skipped — this helper centralizes that instead of each test hand-
rolling its own ``proc_start`` filtering.
"""

from __future__ import annotations


def read_events(path, skip_anchor=True, name=None):
    """Parsed events of one JSONL capture, asserting zero damaged
    lines.

    skip_anchor : drop the ``proc_start`` clock-anchor record(s) each
        sink opens with (pass ``False`` to assert on them).
    name : keep only events with this name.
    """
    from raft_tpu.obs import report

    events, bad = report.read_events(str(path))
    assert bad == 0, f"{bad} unparseable lines in {path}"
    if skip_anchor:
        events = [e for e in events if e["event"] != "proc_start"]
    if name is not None:
        events = [e for e in events if e["event"] == name]
    return events
