"""Device-placement helpers.

Build-time model assembly does small *eager* jax computations (statics
matrices, strip constants).  On TPU images those would otherwise land
on the accelerator and then need device-to-host pulls when embedded as
jit constants — and the axon TPU tunnel in this environment only
implements f32 transfers.  ``on_cpu()`` pins eager build work to the
host CPU backend; jitted hot-path programs still run wherever the
caller places them.
"""

from __future__ import annotations

import contextlib

import jax

from raft_tpu.utils import config


@contextlib.contextmanager
def on_cpu():
    try:
        cpu = jax.local_devices(backend="cpu")[0]
    except RuntimeError:
        yield
        return
    with jax.default_device(cpu):
        yield


def to_host(tree):
    """Pull a pytree of arrays to host numpy."""
    import numpy as np

    return jax.tree_util.tree_map(
        lambda x: np.asarray(x) if hasattr(x, "dtype") else x, tree
    )


def enable_compile_cache(cache_dir=None, platform=None,
                         min_compile_secs=None):
    """Enable the persistent XLA compilation cache (idempotent).

    Promoted from the ad-hoc ``_enable_compile_cache`` in ``bench.py``
    (mirroring the PR-1 ``probe_backend`` promotion) so library users —
    the drivers in :mod:`raft_tpu.drivers` and the sweep runtimes in
    :mod:`raft_tpu.parallel.sweep` — get cache hits across processes,
    not just the bench.  Repeated driver retries / sweep resumes then
    skip recompilation entirely.

    This is also the single funnel every entry point passes through on
    the way to a sweep, so the cold-start machinery is armed here: the
    recompile sentinel/telemetry listener (``xla_compiles``) and the
    AOT program-bank counters the sweep dispatcher and the bench
    report from (:mod:`raft_tpu.aot.bank` — the bank itself is
    consulted lazily per dispatch, gated by ``RAFT_TPU_AOT``).

    cache_dir : cache location; default ``RAFT_TPU_CACHE_DIR``, else
        ``~/.cache/raft_tpu/jax_cache``.
    platform : optional platform pin (e.g. ``"cpu"``) — the axon TPU
        plugin in this image overrides ``JAX_PLATFORMS`` at import
        time, so an explicit platform request must go through the
        config, not the env var.
    min_compile_secs : only compilations at least this long persist;
        default from ``RAFT_TPU_CACHE_MIN_COMPILE_S`` (0.0: persist
        everything).  The old hard-coded 10.0 silently disabled the
        disk cache for every sub-10s program — which on a CPU build
        host is nearly all of them, so each fresh process re-compiled
        from scratch.  The trade-off of 0 is cache-directory growth;
        raise the flag on hosts where only multi-minute accelerator
        compilations are worth persisting.

    Returns the cache directory in use (None when the cache could not
    be enabled — e.g. jax already finalised its config).
    """
    import jax

    # every entry point that wants compile caching also wants compile
    # *counting*: arm the telemetry feed (xla_compiles counter) here so
    # drivers/sweeps/bench all get it without a separate call
    from raft_tpu.analysis.recompile import install as _install_sentinel
    from raft_tpu.obs import metrics

    _install_sentinel()
    # pre-register the bank counters so sweep manifests / metrics.json
    # state "0 loads" explicitly instead of omitting the story
    for name in ("aot_programs_loaded", "aot_programs_compiled",
                 "aot_bank_misses"):
        metrics.counter(name)
    if platform:
        jax.config.update("jax_platforms", platform)
    if cache_dir is None:
        cache_dir = config.get("CACHE_DIR")
    if min_compile_secs is None:
        min_compile_secs = config.get("CACHE_MIN_COMPILE_S")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_secs))
    except Exception:
        return None
    return cache_dir


def probe_backend(platform=None, timeout_s=None):
    """Health-probe an accelerator backend without risking this process.

    A dead accelerator tunnel (observed with the axon TPU plugin) hangs
    jax backend *initialization* until killed, so the probe runs one tiny
    matmul in a **subprocess** with a timeout: the parent never touches
    the suspect backend.  Promoted from the ad-hoc probe in ``bench.py``
    so sweeps and benches share one health check.

    platform : optional JAX platform name to pin in the child (e.g.
        ``"tpu"``); default lets the child use its ambient default.
    timeout_s : seconds before the backend is declared dead (default
        from ``RAFT_TPU_PROBE_S``, else 300 — first contact with a cold
        TPU tunnel is legitimately slow).

    Returns True when the backend answered, False on timeout/error.
    """
    import os
    import subprocess
    import sys

    from raft_tpu.utils import faults

    if faults.take("unhealthy", "backend_probe"):
        return False
    if timeout_s is None:
        timeout_s = config.get("PROBE_S")
    env = dict(os.environ)
    if platform:
        env["JAX_PLATFORMS"] = platform
    try:
        p = subprocess.run(
            [sys.executable, "-c",
             "import jax, jax.numpy as jnp; "
             "x = jnp.ones((128, 128)); (x @ x).block_until_ready(); "
             "print('ok', jax.devices()[0].device_kind)"],
            timeout=timeout_s, capture_output=True, text=True, env=env)
        return p.returncode == 0
    except (subprocess.TimeoutExpired, OSError):
        return False
