"""Asyncio HTTP front end of the evaluation service (stdlib only).

A deliberately small HTTP/1.1 implementation over ``asyncio.
start_server`` — request line + headers + Content-Length body,
keep-alive connections, JSON in/out.  No framework, no new deps.

Routes
------
``POST /evaluate``  body::

        {"design": "spar",            # registered name, or
         "design_inline": {...},      # inline design dict (YAML-as-JSON)
         "Hs": 6.0, "Tp": 11.0, "beta": 0.0,
         "out_keys": ["PSD", "X0", "status"],   # optional subset
         "escalate_f64": false}                 # quarantine-style re-solve

    → 200 with ``{"ok": true, "status": <int32 word>, "status_text",
    "cache_hit", "escalated", "outputs": {...}}``; a result carrying
    SEVERE health bits returns **422** with the same body plus the
    ``describe()`` error text (numbers included — suspect, not absent);
    backpressure returns **429** (per-client quota, with Retry-After)
    or **503** (admission queue full / draining).

``GET /healthz``    liveness + warmup provenance (programs loaded vs
                    compiled, real XLA compiles, cache + batcher stats)
``GET /metrics``    the process metrics registry in Prometheus text
                    exposition format (the ``RAFT_TPU_METRICS`` file
                    exporter's live HTTP twin)
``GET /designs``    registered design names
``POST /drain``     begin a graceful drain (202) — loopback peers only
                    (the fleet router drains a replica it is evicting;
                    a tenant must never be able to drain the service)

Shutdown: SIGTERM/SIGINT (or ``POST /drain``) triggers a graceful
drain — release the fleet membership lease FIRST (``on_drain_start``,
so the router stops routing new work here while accepted work
finishes), stop accepting, finish in-flight ticks (every accepted
request gets its response), flush metrics (``RAFT_TPU_METRICS`` path
when set), then exit.

Fault injection (:mod:`raft_tpu.utils.faults`): the three
``replica_*`` kinds consult the ``serve_evaluate`` site here —
``replica_kill`` SIGKILLs the process on the next /evaluate,
``replica_hang`` parks it past every timeout, ``replica_5xx`` returns
a 500 — driving the router's kill-a-replica / breaker drills
deterministically.
"""

from __future__ import annotations

import asyncio
import json
import os
import signal
import time

import numpy as np

from raft_tpu.obs import metrics
from raft_tpu.obs.spans import (current_ids, format_traceparent,
                                parse_traceparent, span)
from raft_tpu.serve import batcher as batcher_mod
from raft_tpu.serve import wire
from raft_tpu.utils import config, faults
from raft_tpu.utils.structlog import log_event

_T0 = time.perf_counter()

#: kept as module aliases — the wire module is the single definition
#: shared with the fleet router
MAX_BODY_BYTES = wire.MAX_BODY_BYTES


def _json_value(v):
    """JSON-encode one output leaf: numpy arrays to nested lists,
    complex values split into real/imag."""
    a = np.asarray(v)
    if np.iscomplexobj(a):
        return {"real": a.real.tolist(), "imag": a.imag.tolist()}
    return a.tolist()


def encode_result(result):
    """The JSON body of one evaluation result payload."""
    return {
        "ok": not result["severe"],
        "status": result["status"],
        "status_text": result["status_text"],
        "cache_hit": result["cache_hit"],
        "escalated": result["escalated"],
        "outputs": {k: _json_value(v)
                    for k, v in result["outputs"].items()},
    }


class Server:
    """One service instance: batcher + asyncio HTTP endpoint."""

    def __init__(self, batcher, host="127.0.0.1", port=8787,
                 on_drain_start=None, provenance=None):
        self.batcher = batcher
        self.host = host
        self.port = int(port)
        self.timeout_s = float(config.get("SERVE_TIMEOUT_S"))
        #: per-design provenance stamps ({design: prov dict} plus the
        #: "*" base for inline designs — engine.build_provenance);
        #: precomputed ONCE into header strings so the per-request cost
        #: is a dict lookup (the zero-overhead contract)
        from raft_tpu.obs.alerts import format_provenance

        self._prov_headers = {k: format_provenance(v)
                              for k, v in (provenance or {}).items()}
        #: called (in an executor — it does file IO) at the very START
        #: of the graceful drain, before any in-flight work finishes:
        #: the fleet replica releases its membership lease here, so the
        #: router stops routing new requests to a draining replica
        #: while it completes the accepted ones
        self.on_drain_start = on_drain_start
        self._server = None
        self._stop = None
        self._handlers = set()

    # ------------------------------------------------------------ routes

    async def _evaluate(self, body, client, traceparent=None):
        """One /evaluate request under its ``serve_request`` span: the
        span adopts the client's ``traceparent`` (when sent) so the
        request joins the caller's trace, and its ids ride into the
        batcher so the tick span can link back — one trace from HTTP
        accept through coalescing to the banked-program dispatch.
        Returns ``(status, payload, extra_headers)``."""
        req_span = span("serve_request", endpoint="/evaluate",
                        remote=parse_traceparent(traceparent),
                        client=str(client))
        with req_span:
            status, payload, design = await self._evaluate_inner(body,
                                                                 client)
        hdrs = {}
        tp = format_traceparent(req_span.trace_id, req_span.span_id) \
            if req_span.span_id else None
        if tp:
            hdrs["traceparent"] = tp
        # provenance stamp: WHAT produced these numbers — bank key +
        # sidecar sha, code hash, flags key, replica id (precomputed at
        # startup; the canary cross-checks it across replicas)
        prov = (self._prov_headers.get(design)
                or self._prov_headers.get("*"))
        if prov:
            hdrs["x-raft-provenance"] = prov
        return status, payload, hdrs

    async def _evaluate_inner(self, body, client):
        """Returns ``(status, payload, design_key)`` — the design key
        picks the provenance stamp (``"*"`` = base stamp: inline or
        unresolved designs)."""
        try:
            payload = json.loads(body or b"{}")
        except (ValueError, UnicodeDecodeError) as e:
            return 400, {"ok": False, "error": f"bad JSON body: {e}"}, "*"
        if not isinstance(payload, dict):
            return (400, {"ok": False, "error": "body must be a JSON object"},
                    "*")
        client = payload.get("client") or client
        loop = asyncio.get_running_loop()
        entry = None
        if payload.get("design_inline") is not None:
            # building an inline design is host work (YAML schema +
            # model build) — keep it off the event loop
            try:
                entry = await loop.run_in_executor(
                    None, self.batcher.registry.resolve_inline,
                    payload["design_inline"])
            except Exception as e:  # noqa: BLE001 — tenant input
                return (400, {"ok": False,
                              "error": f"inline design rejected: {e!r}"},
                        "*")
        else:
            name = payload.get("design")
            if not name:
                return (400, {"ok": False,
                              "error": "missing 'design' "
                                       "(or 'design_inline')"}, "*")
            entry = self.batcher.registry.get(name)
            if entry is None:
                return (404, {"ok": False,
                              "error": f"unknown design {name!r}"}, "*")
        design = entry.name
        # the case scalars are REQUIRED: silently defaulting a missing
        # (or misspelled) Hs/Tp/beta would evaluate the wrong sea state
        # and return it as ok:true — in a parity-gated service, wrong
        # numbers must never be quieter than a 400
        missing = [k for k in ("Hs", "Tp", "beta") if k not in payload]
        if missing:
            return (400, {"ok": False,
                          "error": f"missing case scalar(s) {missing}"},
                    design)
        try:
            case = {k: float(payload[k]) for k in ("Hs", "Tp", "beta")}
        except (TypeError, ValueError):
            return (400, {"ok": False,
                          "error": "Hs/Tp/beta must be numbers"}, design)
        out_keys = payload.get("out_keys")
        if out_keys is not None and not (
                isinstance(out_keys, list)
                and all(isinstance(k, str) for k in out_keys)):
            return (400, {"ok": False,
                          "error": "out_keys must be a string list"}, design)
        try:
            fut = self.batcher.submit(
                entry, case["Hs"], case["Tp"], case["beta"],
                out_keys=tuple(out_keys) if out_keys else None,
                escalate_f64=bool(payload.get("escalate_f64")),
                client=client, trace_ctx=current_ids())
        except batcher_mod.QuotaExceeded as e:
            return (429, {"ok": False, "error": "client quota exceeded",
                          "retry_after_s": round(e.retry_after_s, 3)},
                    design)
        except batcher_mod.RejectError as e:
            return (503, {"ok": False, "error": str(e),
                          "reason": e.reason}, design)
        except ValueError as e:
            return 400, {"ok": False, "error": str(e)}, design
        try:
            result = await asyncio.wait_for(asyncio.wrap_future(fut),
                                            timeout=self.timeout_s)
        except asyncio.TimeoutError:
            fut.cancel()
            return (408, {"ok": False,
                          "error": f"evaluation exceeded {self.timeout_s}s"},
                    design)
        except Exception as e:  # noqa: BLE001 — dispatch failure
            return 500, {"ok": False, "error": repr(e)[:300]}, design
        return (422 if result["severe"] else 200), encode_result(result), \
            design

    def _healthz(self):
        from raft_tpu.analysis.recompile import PROCESS_LOG
        from raft_tpu.aot import bank

        snap = {c: metrics.counter(c).value for c in
                ("aot_programs_loaded", "aot_programs_compiled",
                 "serve_requests", "serve_dispatches",
                 "serve_rows_dispatched", "serve_coalesced",
                 "serve_rejected_quota", "serve_rejected_queue",
                 "serve_errors", "serve_escalations")}
        from raft_tpu.obs.report import SERVE_STAGES

        occ = metrics.histogram("serve_batch_occupancy").snapshot()
        lat = metrics.histogram("serve_request_s").snapshot()
        # tail attribution: per-stage latency histograms of every
        # dispatched request (the capture-level p50-vs-p95 stage table
        # lives in `obs report`; this is the live operator view)
        stages = {s: metrics.histogram(f"serve_stage_{s}_s").snapshot()
                  for s in SERVE_STAGES}
        window_s = float(config.get("SERVE_WINDOW_S"))
        win = metrics.window("serve_request_window_s").snapshot(window_s)
        slo_ms = float(config.get("SERVE_SLO_MS") or 0)
        return 200, {
            "ok": True,
            "draining": self.batcher.draining,
            "uptime_s": round(time.perf_counter() - _T0, 3),
            "xla_compiles": PROCESS_LOG.count,
            "xla_real_compiles": PROCESS_LOG.real_count,
            "batch_occupancy": occ,
            "request_latency_s": lat,
            # the sliding view an operator actually pages on: p50/p95
            # over the last RAFT_TPU_SERVE_WINDOW_S seconds + SLO state
            "window": win,
            "request_stages": stages,
            "slo": {"slo_ms": slo_ms or None,
                    "breaches": metrics.counter("serve_slo_breaches").value},
            # device-cost ledger: per-program flops / dispatches /
            # achieved GFLOP/s (populated when the AOT bank is armed)
            "cost_ledger": bank.ledger_summary(),
            **self.batcher.stats(),
            **snap,
        }

    async def _route(self, method, path, body, client, headers,
                     peer_host="?"):
        """Returns ``(status, payload)`` or ``(status, payload,
        extra_response_headers)``."""
        if path == "/evaluate":
            if method != "POST":
                return 405, {"ok": False, "error": "POST required"}
            # deterministic replica failure modes for the fleet drills
            # (raft_tpu.utils.faults): kill = SIGKILL mid-load, hang =
            # park past every timeout (wedged-but-alive), 5xx = error
            # response — the router must retry/break around all three
            if faults.take("replica_kill", "serve_evaluate"):
                os.kill(os.getpid(), signal.SIGKILL)
            if faults.take("replica_hang", "serve_evaluate"):
                await asyncio.sleep(2 * self.timeout_s)
                return 503, {"ok": False, "error": "hang fault elapsed"}
            if faults.take("replica_5xx", "serve_evaluate"):
                return 500, {"ok": False, "error": "injected 5xx fault"}
            if self.batcher.draining:
                return 503, {"ok": False, "error": "service is draining",
                             "reason": "draining"}
            return await self._evaluate(body, client,
                                        traceparent=headers.get("traceparent"))
        if path == "/drain":
            if method != "POST":
                return 405, {"ok": False, "error": "POST required"}
            # admin-gated: only loopback peers (the operator or a
            # co-hosted router evicting this replica) may drain
            if peer_host not in wire.LOOPBACK_HOSTS:
                return 403, {"ok": False,
                             "error": "drain is loopback-only"}
            if self._stop is None:
                return 503, {"ok": False,
                             "error": "server not accepting signals yet"}
            already = self.batcher.draining or self._stop.is_set()
            self._stop.set()  # same path as SIGTERM: shutdown() runs
            #                   after this response is written
            return 202, {"ok": True, "draining": True,
                         "already_draining": bool(already)}
        if method != "GET":
            return 405, {"ok": False, "error": "GET required"}
        if path == "/healthz":
            return self._healthz()
        if path == "/alerts":
            # live alert-engine state (+ the replica's golden-canary
            # summary when the canary path is enabled) — pure in-memory
            # reads, safe on the event loop
            from raft_tpu.obs import alerts as alerts_mod
            from raft_tpu.serve import canary as canary_mod

            payload = alerts_mod.endpoint_payload()
            payload["canary"] = canary_mod.replica_summary()
            return 200, payload
        if path == "/metrics":
            return 200, metrics.to_prometheus()  # text, not JSON
        if path == "/debug/flight":
            # the live flight ring as a JSONL shard — postmortem-grade
            # history (spans/events/metric deltas with logging off)
            # without restarting anything.  Loopback-gated like /drain:
            # ring payloads carry design hashes and client ids, which a
            # tenant must not be able to read
            if peer_host not in wire.LOOPBACK_HOSTS:
                return 403, {"ok": False,
                             "error": "/debug/flight is loopback-only"}
            from raft_tpu.obs import flight

            return 200, flight.serialize_text(trigger="debug")  # text
        if path == "/designs":
            return 200, {"ok": True, "designs": self.batcher.registry.names()}
        return 404, {"ok": False, "error": f"no route {path}"}

    # -------------------------------------------------------- connection

    # request parsing + response formatting live in raft_tpu.serve.wire
    # (shared with the fleet router)

    async def _read_request(self, reader):
        return await wire.read_request(reader)

    @staticmethod
    def _response_bytes(status, payload, keep_alive, extra_headers=None):
        return wire.response_bytes(status, payload, keep_alive,
                                   extra_headers)

    async def _handle(self, reader, writer):
        task = asyncio.current_task()
        self._handlers.add(task)
        peer = writer.get_extra_info("peername")
        peer_host = peer[0] if isinstance(peer, tuple) else "?"
        try:
            while True:
                try:
                    req = await self._read_request(reader)
                except (ValueError, asyncio.IncompleteReadError) as e:
                    writer.write(self._response_bytes(
                        400, {"ok": False, "error": str(e)[:200]}, False))
                    await writer.drain()
                    break
                if req is None:
                    break
                method, path, headers, body = req
                client = headers.get("x-client") or peer_host
                t0 = time.perf_counter()
                extra = None
                try:
                    routed = await self._route(method, path, body,
                                               client, headers,
                                               peer_host=peer_host)
                    status, payload = routed[0], routed[1]
                    extra = routed[2] if len(routed) > 2 else None
                except Exception as e:  # noqa: BLE001 — keep serving
                    status, payload = 500, {"ok": False,
                                            "error": repr(e)[:300]}
                keep = (headers.get("connection", "keep-alive").lower()
                        != "close") and not self.batcher.draining \
                    and not (self._stop is not None and self._stop.is_set())
                writer.write(self._response_bytes(status, payload, keep,
                                                  extra))
                await writer.drain()
                log_event("serve_request", endpoint=path, method=method,
                          code=status, client=str(client),
                          wall_s=round(time.perf_counter() - t0, 6),
                          cache_hit=bool(payload.get("cache_hit"))
                          if isinstance(payload, dict) else False)
                metrics.counter("serve_http_requests").inc()
                if not keep:
                    break
        finally:
            self._handlers.discard(task)
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    # ------------------------------------------------------------- serve

    async def start(self):
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        self._stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                loop.add_signal_handler(sig, self._stop.set)
            except (NotImplementedError, RuntimeError):
                pass
        self.batcher.start()
        # arm the flight recorder's periodic flush + crash hooks (no-op
        # without RAFT_TPU_FLIGHT_DIR): a SIGKILLed replica must leave
        # its last seconds behind for the kill-a-replica postmortem
        from raft_tpu.obs import flight

        flight.maybe_start()
        log_event("serve_start", host=self.host, port=self.port,
                  designs=self.batcher.registry.names(),
                  tick_ms=self.batcher.tick_s * 1e3,
                  batch_sizes=list(self.batcher.sizes))
        return self

    async def serve_until_stopped(self):
        """Block until SIGTERM/SIGINT, then drain gracefully."""
        await self._stop.wait()
        await self.shutdown()

    async def shutdown(self):
        """Graceful drain: refuse new work, finish in-flight requests,
        flush metrics."""
        t0 = time.perf_counter()
        loop = asyncio.get_running_loop()
        # 0. release fleet membership FIRST (file IO — executor): the
        #    router must stop routing NEW work here before we spend the
        #    drain window finishing the accepted work; a lease released
        #    at process exit instead would keep attracting traffic for
        #    the whole drain
        if self.on_drain_start is not None:
            try:
                await loop.run_in_executor(None, self.on_drain_start)
            except Exception as e:  # noqa: BLE001 — drain must proceed
                log_event("serve_error", error=repr(e)[:300], rows=0)
        # 1. stop accepting new connections; mark draining so keep-alive
        #    connections get 503 for new requests
        self._server.close()
        # 2. finish every accepted request (the batcher resolves all
        #    pending futures before drain() returns)
        drain_s = float(config.get("SERVE_DRAIN_S"))
        await loop.run_in_executor(None, self.batcher.drain, drain_s)
        # 3. let the open handlers write their final responses
        handlers = {t for t in self._handlers if not t.done()}
        if handlers:
            await asyncio.wait(handlers, timeout=drain_s)
        for t in list(self._handlers):
            t.cancel()
        await self._server.wait_closed()
        # 4. flush metrics for the scrape-at-exit consumers — file IO,
        #    so off the loop: open handlers are still writing their
        #    final responses while this runs (async-blocking lint)
        path = config.get("METRICS")
        if path:
            await loop.run_in_executor(None, metrics.export, path)
        # 5. append the session's run record (RAFT_TPU_RUNS_DIR): the
        #    metrics registry at drain carries the whole serving story
        #    — request/stage/occupancy histograms, waste counters,
        #    cost ledger — so the longitudinal store sees every session.
        #    Executor too: the record write is file IO plus a
        #    `git rev-parse` subprocess (obs.runs.git_sha)
        from raft_tpu.obs import runs as obs_runs

        wall_s = time.perf_counter() - _T0
        requests = metrics.counter("serve_requests").value
        await loop.run_in_executor(
            None, lambda: obs_runs.maybe_record(
                "serve", wall_s=wall_s, extra={"requests": requests}))
        log_event("serve_stop",
                  requests=metrics.counter("serve_requests").value,
                  wall_s=round(time.perf_counter() - t0, 3))


async def run_server(batcher, host="127.0.0.1", port=8787, ready=None,
                     on_drain_start=None, provenance=None):
    """Start + block until signalled.  ``ready(server)`` runs after the
    socket binds (the CLI prints its ready line there; the fleet
    replica claims its membership lease there too)."""
    server = await Server(batcher, host, port,
                          on_drain_start=on_drain_start,
                          provenance=provenance).start()
    if ready is not None:
        ready(server)
    await server.serve_until_stopped()
    return server
