"""Instrumentable filesystem seam for the coordination protocols.

Every shared-filesystem mutation the fleet's state machines perform —
lease claims (``O_CREAT|O_EXCL``), tmp+``os.replace`` rewrites, rename
steals/evictions, pointer flips, lease removes — routes through this
module instead of calling ``os`` directly.  At runtime it is a
passthrough: ``_FS`` is ``None`` and every helper is one attribute
check away from the bare ``os`` call.

The indirection exists for ``analysis/mcheck.py``: the protocol model
checker installs an in-memory virtual filesystem here (``install``)
that implements exactly the atomicity the protocols assume — atomic
create-exclusive, atomic rename, atomic replace; everything else
interruptible — and then drives the *real* protocol functions through
every interleaving of 2–3 actors with crash injection at the
tmp-write → replace boundaries.

The seam is also what ``analysis/protocol.py`` keys its static
extraction on: a raw ``os.replace``/``os.rename``/``os.unlink`` in a
protocol module is an unmodeled mutation site and fails
``analysis protocol check``.
"""
from __future__ import annotations

import itertools
import os

#: Installed virtual filesystem (``analysis/mcheck.py``) or ``None``
#: for the real ``os``-backed implementation.  Never mutated at
#: runtime outside the model checker and its tests.
_FS = None

_TMP_COUNTER = itertools.count()


def install(fs):
    """Substitute ``fs`` for the real filesystem.  Checker/test only."""
    global _FS
    _FS = fs


def uninstall():
    global _FS
    _FS = None


def installed():
    return _FS


# ------------------------------------------------------------ mutations


def create_exclusive(path, text):
    """Atomically create ``path`` with ``text``.

    Raises :class:`FileExistsError` if the path already exists — the
    lease-claim primitive: exactly one creator wins.
    """
    if _FS is not None:
        return _FS.create_exclusive(path, text)
    fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    with os.fdopen(fd, "w") as f:
        f.write(text)
    return None


def write_text(path, text):
    """Plain (interruptible) write — the tmp half of a rewrite."""
    if _FS is not None:
        return _FS.write_text(path, text)
    with open(path, "w") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    return None


def replace(src, dst):
    """Atomic replace: readers of ``dst`` see old-or-new, never torn."""
    if _FS is not None:
        return _FS.replace(src, dst)
    return os.replace(src, dst)


def rename(src, dst):
    """Atomic rename; raises :class:`OSError` if ``src`` is absent —
    the steal/evict primitive: exactly one renamer wins."""
    if _FS is not None:
        return _FS.rename(src, dst)
    return os.rename(src, dst)


def unlink(path):
    if _FS is not None:
        return _FS.unlink(path)
    return os.unlink(path)


def tmp_name(path):
    """Unique sibling tmp path for ``path``.  Never ends in the final
    path's suffix, so directory scans (``*.json`` filters) can never
    mistake a tmp for live state."""
    if _FS is not None:
        return _FS.tmp_name(path)
    return f"{path}.tmp.{os.getpid()}.{next(_TMP_COUNTER)}"


def grave_name(path, tag):
    """Unique grave path for an atomic remove-via-rename of ``path``."""
    if _FS is not None:
        return _FS.grave_name(path, tag)
    import uuid

    return f"{path}.{tag}.{uuid.uuid4().hex[:8]}"


def write_atomic(path, text):
    """tmp write + atomic replace, composed from the two seam ops so
    the model checker sees (and can crash between) both halves."""
    tmp = tmp_name(path)
    try:
        write_text(tmp, text)
        replace(tmp, path)
    except BaseException:
        try:
            unlink(tmp)
        except OSError:
            pass
        raise


def makedirs(path, exist_ok=True):
    if _FS is not None:
        return _FS.makedirs(path, exist_ok=exist_ok)
    return os.makedirs(path, exist_ok=exist_ok)


def utime(path):
    if _FS is not None:
        return _FS.utime(path)
    return os.utime(path, None)


# ------------------------------------------------------------ reads


def read_text(path):
    """Read ``path``; raises :class:`OSError` when absent (like open)."""
    if _FS is not None:
        return _FS.read_text(path)
    with open(path) as f:
        return f.read()


def exists(path):
    if _FS is not None:
        return _FS.exists(path)
    return os.path.exists(path)


def listdir(path):
    if _FS is not None:
        return _FS.listdir(path)
    return os.listdir(path)


def getmtime(path):
    if _FS is not None:
        return _FS.getmtime(path)
    return os.path.getmtime(path)
