"""Lumped-mass mooring dynamics (moorMod 1/2) tests.

MoorPy (the reference's backend for ``line.dynamicSolve`` /
``getCoupledDynamicMatrices``) is not in this image, so validation is
physics-based:

* the quasi-static limit: at vanishing frequency the dynamic tension
  and the condensed fairlead impedance must reduce to the catenary's
  static tension Jacobian / stiffness;
* inertia: at high frequency the dynamic tension exceeds quasi-static
  (added-mass + drag reaction of the line), the hallmark the lumped-
  mass model exists to capture;
* end-to-end: a VolturnUS-S-style model runs moorMod 1 and 2 and the
  dynamic tension statistics differ from the quasi-static ones.
"""

import numpy as np
import pytest

import raft_tpu
from raft_tpu.physics.mooring import (MooringSystem, mooring_force,
                                      solve_catenary)
from raft_tpu.physics.mooring_dynamics import (fowt_mooring_impedance,
                                               line_dynamics,
                                               line_static_shape)

pytestmark = pytest.mark.slow

# one VolturnUS-S-style chain line
ANCHOR = np.array([-837.6, 0.0, -200.0])
FAIR = np.array([-58.0, 0.0, -14.0])
L, W_LIN, EA = 850.0, (685.0 - 1025 * np.pi / 4 * 0.333**2) * 9.81, 3.27e9
M_LIN, D_VOL = 685.0, 0.333


def quasi_static_jacobian(dr=0.05):
    """dT_fair/dr_fair and dF/dr_fair by central differences."""
    def tens(rf):
        dv = rf - ANCHOR
        XF, ZF = np.hypot(dv[0], dv[1]), dv[2]
        HF, VF, _, _ = solve_catenary(XF, ZF, L, W_LIN, EA)
        return float(np.hypot(HF, VF))

    J = np.zeros(3)
    for j in range(3):
        e = np.zeros(3)
        e[j] = dr
        J[j] = (tens(FAIR + e) - tens(FAIR - e)) / (2 * dr)
    return J


def run_line(w_arr, rao_dir, amp=1.0, with_waves=False):
    r_nodes, T_nodes, grounded, s_arc = line_static_shape(ANCHOR, FAIR, L, W_LIN, EA)
    nw = len(w_arr)
    k_arr = np.asarray(w_arr) ** 2 / 9.81
    zeta = (np.full(nw, 1.0 + 0j) if with_waves else np.zeros(nw, complex))
    RAO_B = np.zeros((3, nw), complex)
    RAO_B[rao_dir] = amp
    return line_dynamics(r_nodes, T_nodes, grounded, L, EA, M_LIN, D_VOL,
                         np.asarray(w_arr), k_arr, zeta, 0.0, 200.0,
                         RAO_B=RAO_B, s_arc=s_arc)


def test_quasi_static_tension_limit():
    """w -> 0: fairlead dynamic tension == static tension Jacobian."""
    J = quasi_static_jacobian()
    w_arr = np.array([0.02, 0.05])
    for j, direction in enumerate(["surge", "heave"]):
        res = run_line(w_arr, rao_dir=0 if direction == "surge" else 2)
        T_dyn = float(np.abs(np.asarray(res["T_amp"])[-1, 0]))
        T_qs = abs(J[0 if direction == "surge" else 2])
        assert T_dyn == pytest.approx(T_qs, rel=0.05), direction


def test_quasi_static_impedance_limit():
    """w -> 0: Re(Z_fair) == static line stiffness at the fairlead."""
    def force(rf):
        dv = rf - ANCHOR
        XF, ZF = np.hypot(dv[0], dv[1]), dv[2]
        HF, VF, _, _ = solve_catenary(XF, ZF, L, W_LIN, EA)
        uh = dv[:2] / max(XF, 1e-9)
        return np.array([-HF * uh[0], -HF * uh[1], -VF])

    K_qs = np.zeros((3, 3))
    for j in range(3):
        e = np.zeros(3)
        e[j] = 0.05
        K_qs[:, j] = -(force(FAIR + e) - force(FAIR - e)) / 0.1

    res = run_line(np.array([0.02]), rao_dir=0)
    Z0 = np.asarray(res["Z_fair"])[0].real
    # compare the dominant surge-surge and heave-heave terms
    assert Z0[0, 0] == pytest.approx(K_qs[0, 0], rel=0.08)
    assert Z0[2, 2] == pytest.approx(K_qs[2, 2], rel=0.08)


def test_dynamic_amplification():
    """High-frequency axial tension exceeds quasi-static (line inertia
    and drag resist the fairlead motion)."""
    J = quasi_static_jacobian()
    res = run_line(np.array([0.05, 1.5, 2.5]), rao_dir=0)
    T = np.abs(np.asarray(res["T_amp"])[-1])
    assert T[0] == pytest.approx(abs(J[0]), rel=0.06)
    assert T[2] > 1.5 * T[0]  # strong dynamic amplification at 2.5 rad/s


def test_moormod_impedance_6dof():
    ms = MooringSystem(
        r_anchor=ANCHOR[None, :], r_fair0=FAIR[None, :],
        L=np.array([L]), w=np.array([W_LIN]), EA=np.array([EA]), depth=200.0,
        m_lin=np.array([M_LIN]), d_vol=np.array([D_VOL]),
        Cd=np.array([1.2]), Ca=np.array([1.0]),
        CdAx=np.array([0.05]), CaAx=np.array([0.0]), moorMod=2)
    w_arr = np.arange(0.05, 1.55, 0.25)
    S = np.ones(len(w_arr)) * 1.0
    Z = np.asarray(fowt_mooring_impedance(
        ms, np.zeros(6), w_arr, w_arr**2 / 9.81, S, 0.0, 200.0))
    assert Z.shape == (len(w_arr), 6, 6)
    # low-frequency real part ~ quasi-static coupled stiffness
    from raft_tpu.physics.mooring import mooring_stiffness
    import jax.numpy as jnp

    C_qs = np.asarray(mooring_stiffness(ms, jnp.zeros(6)))
    assert Z[0, 0, 0].real == pytest.approx(C_qs[0, 0], rel=0.1)
    assert Z[0, 2, 2].real == pytest.approx(C_qs[2, 2], rel=0.1)
    # damping (positive imaginary part) appears at wave frequencies
    assert Z[4, 0, 0].imag > 0


def test_model_moormod_end_to_end():
    """VolturnUS-S with moorMod 1 (dynamic tensions) and 2 (dynamic
    impedance): both run end to end; tension std differs from
    quasi-static; moorMod 2 shifts the surge response."""
    from raft_tpu.structure.schema import load_design

    base = load_design("/root/reference/designs/VolturnUS-S.yaml")
    base["settings"]["min_freq"] = 0.005
    base["settings"]["max_freq"] = 0.12
    base["cases"]["data"] = [
        [0.0, 0, 0, "operating", 0, "JONSWAP", 10.0, 5.0, 0]]

    stds = {}
    surge_std = {}
    for mod in (0, 1, 2):
        import copy

        design = copy.deepcopy(base)
        design["mooring"]["moorMod"] = mod
        model = raft_tpu.Model(design)
        results = model.analyze_cases()
        m = results["case_metrics"][0][0]
        stds[mod] = np.asarray(m["Tmoor_std"])
        surge_std[mod] = float(np.asarray(m["surge_std"]))
        assert np.all(np.isfinite(stds[mod]))

    # dynamic tensions differ from (and are generally larger than)
    # quasi-static at the fairlead ends
    nL = 3
    fair = slice(nL, 2 * nL)
    assert not np.allclose(stds[1][fair], stds[0][fair], rtol=0.02)
    assert np.all(stds[1][fair] > 0)
    # moorMod 2 changes the platform response (mooring inertia/damping)
    assert surge_std[2] != pytest.approx(surge_std[0], rel=1e-3)
    # moorMod 1 and 2 tension magnitudes are in the same ballpark
    assert np.all(stds[2][fair] < 10 * stds[1][fair] + 1e3)
    assert np.all(stds[1][fair] < 5 * stds[0][fair] + 1e3)
