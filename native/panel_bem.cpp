// raft_tpu native panel-method kernel.
//
// First-order potential-flow boundary-element solver core: constant-
// strength source panels (Hess & Smith) with a flat free surface
// handled by the method of images.  This is the native-code foundation
// of the HAMS-equivalent solver the reference delegates to an external
// Fortran package (pyHAMS; /root/reference/raft/raft_fowt.py:1288-1442)
// — here the influence-matrix assembly and dense solve live in C++
// behind a C ABI consumed through ctypes.
//
// Scope:
//   * frequency-limit radiation problems:
//       mirror = -1 : high-frequency free-surface condition (phi = 0
//                     on z = 0, negative image)  -> A(w -> inf)
//       mirror = +1 : rigid-lid condition (dphi/dz = 0, positive
//                     image) -> A(w -> 0)
//   * finite-frequency radiation/diffraction with the wave Green
//     function: infinite depth via the tabulated Telste-Noblesse-style
//     kernel (wave_term() below), finite depth via John's
//     eigenfunction series with adaptive evanescent cutoff
//     (fd_wave_term(), dispatched for Kh <= 6).
//
// Numerics: panel integrals by centroid collocation with 2x2 Gauss
// refinement for near-field pairs and an analytic equivalent-disk self
// term; dense partial-pivot LU for the source strengths.

#include <cmath>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

struct V3 {
  double x, y, z;
};

inline V3 sub(const V3& a, const V3& b) { return {a.x - b.x, a.y - b.y, a.z - b.z}; }
inline double dot(const V3& a, const V3& b) { return a.x * b.x + a.y * b.y + a.z * b.z; }
inline double norm(const V3& a) { return std::sqrt(dot(a, a)); }

// potential at p of a unit-strength source distribution (1/(4 pi r)
// kernel) over a quad panel given by 4 vertices, by Gauss quadrature
double quad_potential(const V3* verts, double area, const V3& p) {
  // bilinear map with 2x2 Gauss points
  static const double gp[2] = {-0.5773502691896257, 0.5773502691896257};
  double phi = 0.0;
  for (int iu = 0; iu < 2; ++iu) {
    for (int iv = 0; iv < 2; ++iv) {
      double u = 0.5 * (1 + gp[iu]);
      double v = 0.5 * (1 + gp[iv]);
      V3 q{
          (1 - u) * (1 - v) * verts[0].x + u * (1 - v) * verts[1].x +
              u * v * verts[2].x + (1 - u) * v * verts[3].x,
          (1 - u) * (1 - v) * verts[0].y + u * (1 - v) * verts[1].y +
              u * v * verts[2].y + (1 - u) * v * verts[3].y,
          (1 - u) * (1 - v) * verts[0].z + u * (1 - v) * verts[1].z +
              u * v * verts[2].z + (1 - u) * v * verts[3].z,
      };
      double r = norm(sub(p, q));
      phi += 0.25 * area / (4.0 * M_PI * (r > 1e-12 ? r : 1e-12));
    }
  }
  return phi;
}

// velocity (gradient of potential) at p from a quad source panel
V3 quad_velocity(const V3* verts, double area, const V3& p) {
  static const double gp[2] = {-0.5773502691896257, 0.5773502691896257};
  V3 vel{0, 0, 0};
  for (int iu = 0; iu < 2; ++iu) {
    for (int iv = 0; iv < 2; ++iv) {
      double u = 0.5 * (1 + gp[iu]);
      double v = 0.5 * (1 + gp[iv]);
      V3 q{
          (1 - u) * (1 - v) * verts[0].x + u * (1 - v) * verts[1].x +
              u * v * verts[2].x + (1 - u) * v * verts[3].x,
          (1 - u) * (1 - v) * verts[0].y + u * (1 - v) * verts[1].y +
              u * v * verts[2].y + (1 - u) * v * verts[3].y,
          (1 - u) * (1 - v) * verts[0].z + u * (1 - v) * verts[1].z +
              u * v * verts[2].z + (1 - u) * v * verts[3].z,
      };
      V3 d = sub(p, q);
      double r = norm(d);
      double r3 = (r > 1e-9 ? r * r * r : 1e-27);
      double c = 0.25 * area / (4.0 * M_PI * r3);
      vel.x += c * d.x;
      vel.y += c * d.y;
      vel.z += c * d.z;
    }
  }
  return vel;
}

// dense partial-pivot LU solve: A (n x n, row major) x = b, overwrites
int lu_solve(std::vector<double>& A, std::vector<double>& b, int n) {
  std::vector<int> piv(n);
  for (int i = 0; i < n; ++i) piv[i] = i;
  for (int k = 0; k < n; ++k) {
    int pk = k;
    double amax = std::fabs(A[k * n + k]);
    for (int i = k + 1; i < n; ++i) {
      double a = std::fabs(A[i * n + k]);
      if (a > amax) {
        amax = a;
        pk = i;
      }
    }
    if (amax < 1e-30) return 1;
    if (pk != k) {
      for (int j = 0; j < n; ++j) std::swap(A[k * n + j], A[pk * n + j]);
      std::swap(b[k], b[pk]);
    }
    double inv = 1.0 / A[k * n + k];
    for (int i = k + 1; i < n; ++i) {
      double f = A[i * n + k] * inv;
      if (f == 0.0) continue;
      A[i * n + k] = f;
      for (int j = k + 1; j < n; ++j) A[i * n + j] -= f * A[k * n + j];
      b[i] -= f * b[k];
    }
  }
  for (int i = n - 1; i >= 0; --i) {
    double s = b[i];
    for (int j = i + 1; j < n; ++j) s -= A[i * n + j] * b[j];
    b[i] = s / A[i * n + i];
  }
  return 0;
}

}  // namespace

// ---------------------------------------------------------------------------
// finite-frequency machinery
// ---------------------------------------------------------------------------

#include <complex>

namespace {

using cd = std::complex<double>;

// complex dense partial-pivot LU with multiple right-hand sides.
// A (n x n row major) is destroyed; B is (nrhs x n) row-per-RHS.
int lu_solve_cplx(std::vector<cd>& A, std::vector<cd>& B, int n, int nrhs) {
  for (int k = 0; k < n; ++k) {
    int pk = k;
    double amax = std::abs(A[static_cast<size_t>(k) * n + k]);
    for (int i = k + 1; i < n; ++i) {
      double a = std::abs(A[static_cast<size_t>(i) * n + k]);
      if (a > amax) {
        amax = a;
        pk = i;
      }
    }
    if (amax < 1e-30) return 1;
    if (pk != k) {
      for (int j = 0; j < n; ++j)
        std::swap(A[static_cast<size_t>(k) * n + j], A[static_cast<size_t>(pk) * n + j]);
      for (int r = 0; r < nrhs; ++r)
        std::swap(B[static_cast<size_t>(r) * n + k], B[static_cast<size_t>(r) * n + pk]);
    }
    cd inv = 1.0 / A[static_cast<size_t>(k) * n + k];
    for (int i = k + 1; i < n; ++i) {
      cd f = A[static_cast<size_t>(i) * n + k] * inv;
      if (f == cd(0.0, 0.0)) continue;
      A[static_cast<size_t>(i) * n + k] = f;
      for (int j = k + 1; j < n; ++j)
        A[static_cast<size_t>(i) * n + j] -= f * A[static_cast<size_t>(k) * n + j];
      for (int r = 0; r < nrhs; ++r)
        B[static_cast<size_t>(r) * n + i] -= f * B[static_cast<size_t>(r) * n + k];
    }
  }
  for (int r = 0; r < nrhs; ++r) {
    for (int i = n - 1; i >= 0; --i) {
      cd s = B[static_cast<size_t>(r) * n + i];
      for (int j = i + 1; j < n; ++j)
        s -= A[static_cast<size_t>(i) * n + j] * B[static_cast<size_t>(r) * n + j];
      B[static_cast<size_t>(r) * n + i] = s / A[static_cast<size_t>(i) * n + i];
    }
  }
  return 0;
}

// bilinear lookup in the (ln d, alpha = R/d) wave-term tables
struct GreenTab {
  int nd, na;
  const double *lnd, *alpha, *L, *M;
};

inline double tab_interp(const GreenTab& t, const double* T, double x, double a) {
  if (x < t.lnd[0]) x = t.lnd[0];
  if (x > t.lnd[t.nd - 1]) x = t.lnd[t.nd - 1];
  if (a < 0) a = 0;
  if (a > 1) a = 1;
  // uniform grids
  double fx = (x - t.lnd[0]) / (t.lnd[t.nd - 1] - t.lnd[0]) * (t.nd - 1);
  double fa = (a - t.alpha[0]) / (t.alpha[t.na - 1] - t.alpha[0]) * (t.na - 1);
  int i = static_cast<int>(fx);
  int j = static_cast<int>(fa);
  if (i > t.nd - 2) i = t.nd - 2;
  if (j > t.na - 2) j = t.na - 2;
  fx -= i;
  fa -= j;
  const double* row0 = T + static_cast<size_t>(i) * t.na;
  const double* row1 = row0 + t.na;
  return (1 - fx) * ((1 - fa) * row0[j] + fa * row0[j + 1]) +
         fx * ((1 - fa) * row1[j] + fa * row1[j + 1]);
}

// wave part of the Green function (kernel normalisation 1/(4 pi r)):
// potential and gradient at field p due to a unit source at q, both z<0.
// K: wavenumber.  Uses G_w = (1/4pi)[2K L + i 2 pi K e^Z J0].
struct WaveEval {
  cd pot;
  cd grad[3];  // d/dx, d/dy, d/dz at the field point
};

inline WaveEval wave_term(const GreenTab& t, double K, const V3& p, const V3& q) {
  double dx = p.x - q.x, dy = p.y - q.y;
  double Rh = std::sqrt(dx * dx + dy * dy);
  double R = K * Rh;
  double Z = K * (p.z + q.z);
  if (Z > -1e-12) Z = -1e-12;
  double d = std::sqrt(R * R + Z * Z);
  double x = std::log(d > 1e-300 ? d : 1e-300);
  double a = (d > 0 ? R / d : 0.0);
  double L = tab_interp(t, t.L, x, a);
  double M = tab_interp(t, t.M, x, a);

  double eZ = std::exp(Z);
  double J0 = j0(R);
  double J1 = j1(R);

  const double c = 1.0 / (4.0 * M_PI);
  WaveEval w;
  w.pot = c * cd(2.0 * K * L, 2.0 * M_PI * K * eZ * J0);

  // dL/dR = -((d - |Z|)/(R d) + M); dL/dZ = L + 1/d
  double dLdR = (R > 1e-12) ? -((d + Z) / (R * d) + M) : 0.0;  // Z<0: |Z|=-Z
  double dLdZ = L + 1.0 / d;
  double dRe_dRh = c * 2.0 * K * K * dLdR;
  double dIm_dRh = -c * 2.0 * M_PI * K * K * eZ * J1;
  double dRe_dz = c * 2.0 * K * K * dLdZ;
  double dIm_dz = c * 2.0 * M_PI * K * K * eZ * J0;

  double ux = (Rh > 1e-12) ? dx / Rh : 0.0;
  double uy = (Rh > 1e-12) ? dy / Rh : 0.0;
  w.grad[0] = cd(dRe_dRh * ux, dIm_dRh * ux);
  w.grad[1] = cd(dRe_dRh * uy, dIm_dRh * uy);
  w.grad[2] = cd(dRe_dz, dIm_dz);
  return w;
}

// wave term integrated over source panel j by its 2x2 Gauss points
inline WaveEval wave_panel(const GreenTab& t, double K, const V3& p,
                           const V3* verts, double area) {
  static const double gp[2] = {-0.5773502691896257, 0.5773502691896257};
  WaveEval acc;
  acc.pot = 0;
  acc.grad[0] = acc.grad[1] = acc.grad[2] = 0;
  for (int iu = 0; iu < 2; ++iu) {
    for (int iv = 0; iv < 2; ++iv) {
      double u = 0.5 * (1 + gp[iu]);
      double v = 0.5 * (1 + gp[iv]);
      V3 q{
          (1 - u) * (1 - v) * verts[0].x + u * (1 - v) * verts[1].x +
              u * v * verts[2].x + (1 - u) * v * verts[3].x,
          (1 - u) * (1 - v) * verts[0].y + u * (1 - v) * verts[1].y +
              u * v * verts[2].y + (1 - u) * v * verts[3].y,
          (1 - u) * (1 - v) * verts[0].z + u * (1 - v) * verts[1].z +
              u * v * verts[2].z + (1 - u) * v * verts[3].z,
      };
      WaveEval w = wave_term(t, K, p, q);
      acc.pot += 0.25 * area * w.pot;
      for (int k = 0; k < 3; ++k) acc.grad[k] += 0.25 * area * w.grad[k];
    }
  }
  return acc;
}

}  // namespace

// ---------------------------------------------------------------------------
// finite-depth wave kernel: John's eigenfunction series
// (raft_tpu/native/green_fd.py is the validated host-side prototype;
// constants/roots are solved there and passed in)
// ---------------------------------------------------------------------------

#include <mutex>

struct FDGreen {
  int n_modes;      // evanescent modes available in km/Cm
  double K;         // omega^2 / g
  double h;         // water depth
  double k0;        // propagating wavenumber: k0 tanh k0 h = K
  double den0;      // h k0^2 sech^2(k0 h) + K  (stable C0 denominator)
  const double* km;  // (n_modes,)
  const double* Cm;  // (n_modes,) (km^2+K^2)/(h(km^2+K^2)-K)
};

namespace {

// K0/K1 lookup tables on a log grid (cyl_bessel_k is far too slow to
// call n^2 * n_modes times); ~1e-7 relative interpolation error.
constexpr int kBesselN = 1 << 14;
constexpr double kBesselXmin = 1e-7, kBesselXmax = 700.0;
double kK0tab[kBesselN], kK1tab[kBesselN];
std::once_flag kBesselOnce;

void build_bessel_tables() {
  double lmin = std::log(kBesselXmin), lmax = std::log(kBesselXmax);
  for (int i = 0; i < kBesselN; ++i) {
    double x = std::exp(lmin + (lmax - lmin) * i / (kBesselN - 1));
    kK0tab[i] = std::cyl_bessel_k(0.0, x);
    kK1tab[i] = std::cyl_bessel_k(1.0, x);
  }
}

inline void bessel_k01(double x, double* K0v, double* K1v) {
  // beyond the table the terms are below 1e-300; below it use the
  // small-x forms K0 ~ -ln(x/2)-gamma, K1 ~ 1/x
  if (x >= kBesselXmax) {
    *K0v = 0.0;
    *K1v = 0.0;
    return;
  }
  if (x <= kBesselXmin) {
    *K0v = -std::log(0.5 * x) - 0.5772156649015329;
    *K1v = 1.0 / x;
    return;
  }
  double lmin = std::log(kBesselXmin), lmax = std::log(kBesselXmax);
  double f = (std::log(x) - lmin) / (lmax - lmin) * (kBesselN - 1);
  int i = static_cast<int>(f);
  if (i > kBesselN - 2) i = kBesselN - 2;
  f -= i;
  *K0v = (1 - f) * kK0tab[i] + f * kK0tab[i + 1];
  *K1v = (1 - f) * kK1tab[i] + f * kK1tab[i + 1];
}

// stable cosh k0(z+h) cosh k0(zeta+h) / cosh^2(k0 h): all exponents <= 0
inline double prop_profile(double k0, double h, double z, double zeta,
                           double* dprof_dz) {
  double a = k0 * (z + h), b = k0 * (zeta + h), c = k0 * h;
  double f = std::exp(a + b - 2 * c) * (1 + std::exp(-2 * a)) *
             (1 + std::exp(-2 * b)) /
             ((1 + std::exp(-2 * c)) * (1 + std::exp(-2 * c)));
  // d/dz: factor tanh(k0 (z+h)) * k0
  double th = std::tanh(a);
  *dprof_dz = k0 * th * f;
  return f;
}

// finite-depth wave term at a point pair: the full eigen-series G minus
// the 1/r and 1/r1 (surface image) Rankine parts the assembly adds
// separately.  Kernel normalisation 1/(4 pi r), like wave_term().
WaveEval fd_wave_point(const FDGreen& fd, double Rh, double zf, double zq) {
  const double c4 = 1.0 / (4.0 * M_PI);
  double dp;
  double prof = prop_profile(fd.k0, fd.h, zf, zq, &dp);
  double A0 = fd.k0 * fd.k0 * prof / fd.den0;
  double dA0_dz = fd.k0 * fd.k0 * dp / fd.den0;

  double x = fd.k0 * Rh;
  double J0 = j0(x), J1 = j1(x), Y0 = y0(x), Y1 = y1(x);
  // G_prop = 2 pi A0 (-Y0 + i J0)(k0 R)
  cd pot = 2.0 * M_PI * cd(-A0 * Y0, A0 * J0);
  double dRe_dR = 2.0 * M_PI * A0 * fd.k0 * Y1;
  double dIm_dR = -2.0 * M_PI * A0 * fd.k0 * J1;
  double dRe_dz = 2.0 * M_PI * (-dA0_dz * Y0);
  double dIm_dz = 2.0 * M_PI * (dA0_dz * J0);

  // evanescent sum: 4 sum Cm cos km(z+h) cos km(zeta+h) K0(km R);
  // adaptive cutoff from the e^{-km R} decay of K0
  int M = fd.n_modes;
  if (Rh * fd.km[0] > 1e-12) {
    double need = 36.0 / Rh;  // km beyond this: K0 < ~2e-16
    int Mneed = static_cast<int>(need * fd.h / M_PI) + 2;
    if (Mneed < M) M = Mneed;
  }
  double zfh = zf + fd.h, zqh = zq + fd.h;
  double sum = 0, dsum_dR = 0, dsum_dz = 0;
  for (int m = 0; m < M; ++m) {
    double kmv = fd.km[m];
    double K0v, K1v;
    bessel_k01(kmv * Rh, &K0v, &K1v);
    if (K0v == 0.0 && K1v == 0.0) break;
    double ca = std::cos(kmv * zfh), cb = std::cos(kmv * zqh);
    double sa = std::sin(kmv * zfh);
    double t = 4.0 * fd.Cm[m] * cb;
    sum += t * ca * K0v;
    dsum_dR += -t * ca * kmv * K1v;
    dsum_dz += -t * sa * kmv * K0v;
  }
  pot += sum;
  dRe_dR += dsum_dR;
  dRe_dz += dsum_dz;

  // subtract the Rankine parts the assembly adds explicitly
  double dz1 = zf - zq, dz2 = zf + zq;
  double r = std::sqrt(Rh * Rh + dz1 * dz1);
  double r1 = std::sqrt(Rh * Rh + dz2 * dz2);
  if (r > 1e-12) {
    pot -= 1.0 / r;
    dRe_dR += Rh / (r * r * r);
    dRe_dz += dz1 / (r * r * r);
  }
  if (r1 > 1e-12) {
    pot -= 1.0 / r1;
    dRe_dR += Rh / (r1 * r1 * r1);
    dRe_dz += dz2 / (r1 * r1 * r1);
  }

  WaveEval w;
  w.pot = c4 * pot;
  w.grad[0] = c4 * cd(dRe_dR, dIm_dR);   // d/dRh (direction applied by caller)
  w.grad[1] = 0;
  w.grad[2] = c4 * cd(dRe_dz, dIm_dz);
  return w;
}

// finite-depth wave term with small-R treatment: the truncated
// evanescent series (minus Rankine parts) loses accuracy for
// R << h / n_modes, but the remainder is smooth and even in R there,
// so extrapolate quadratically in R^2 from three well-converged radii.
WaveEval fd_wave_term(const FDGreen& fd, const V3& p, const V3& q) {
  double dx = p.x - q.x, dy = p.y - q.y;
  double Rh = std::sqrt(dx * dx + dy * dy);
  double zf = (p.z < -1e-9 ? p.z : -1e-9);
  double zq = (q.z < -1e-9 ? q.z : -1e-9);
  // radius below which n_modes no longer resolves the series
  double Rc = 40.0 * fd.h / (M_PI * fd.n_modes);

  WaveEval w;
  if (Rh >= Rc) {
    w = fd_wave_point(fd, Rh, zf, zq);
  } else {
    // three-point fit f(R^2) = a + b R^2 + c R^4 on {Rc, sqrt2 Rc, 2 Rc}
    WaveEval w1 = fd_wave_point(fd, Rc, zf, zq);
    WaveEval w2 = fd_wave_point(fd, Rc * 1.4142135623730951, zf, zq);
    WaveEval w3 = fd_wave_point(fd, 2.0 * Rc, zf, zq);
    double s = Rc * Rc;
    double t = Rh * Rh / s;  // in units of Rc^2: nodes at 1, 2, 4
    // Lagrange weights for nodes {1, 2, 4} in t
    double l1 = (t - 2) * (t - 4) / ((1 - 2) * (1 - 4));
    double l2 = (t - 1) * (t - 4) / ((2 - 1) * (2 - 4));
    double l3 = (t - 1) * (t - 2) / ((4 - 1) * (4 - 2));
    w.pot = l1 * w1.pot + l2 * w2.pot + l3 * w3.pot;
    w.grad[2] = l1 * w1.grad[2] + l2 * w2.grad[2] + l3 * w3.grad[2];
    // df/dR = df/dt * dt/dR = (sum dl/dt f) * 2R/s
    double d1 = ((t - 2) + (t - 4)) / 3.0;
    double d2 = ((t - 1) + (t - 4)) / -2.0;
    double d3 = ((t - 1) + (t - 2)) / 6.0;
    w.grad[0] = (d1 * w1.pot + d2 * w2.pot + d3 * w3.pot) * (2.0 * Rh / s);
    w.grad[1] = 0;
  }
  double ux = (Rh > 1e-12) ? dx / Rh : 0.0;
  double uy = (Rh > 1e-12) ? dy / Rh : 0.0;
  cd dR = w.grad[0];
  w.grad[0] = dR * ux;
  w.grad[1] = dR * uy;
  return w;
}

// finite-depth wave term integrated over source panel j (2x2 Gauss)
inline WaveEval fd_wave_panel(const FDGreen& fd, const V3& p, const V3* verts,
                              double area) {
  static const double gp[2] = {-0.5773502691896257, 0.5773502691896257};
  WaveEval acc;
  acc.pot = 0;
  acc.grad[0] = acc.grad[1] = acc.grad[2] = 0;
  for (int iu = 0; iu < 2; ++iu) {
    for (int iv = 0; iv < 2; ++iv) {
      double u = 0.5 * (1 + gp[iu]);
      double v = 0.5 * (1 + gp[iv]);
      V3 q{
          (1 - u) * (1 - v) * verts[0].x + u * (1 - v) * verts[1].x +
              u * v * verts[2].x + (1 - u) * v * verts[3].x,
          (1 - u) * (1 - v) * verts[0].y + u * (1 - v) * verts[1].y +
              u * v * verts[2].y + (1 - u) * v * verts[3].y,
          (1 - u) * (1 - v) * verts[0].z + u * (1 - v) * verts[1].z +
              u * v * verts[2].z + (1 - u) * v * verts[3].z,
      };
      WaveEval w = fd_wave_term(fd, p, q);
      acc.pot += 0.25 * area * w.pot;
      for (int k = 0; k < 3; ++k) acc.grad[k] += 0.25 * area * w.grad[k];
    }
  }
  return acc;
}

}  // namespace

extern "C" {

// Solve radiation (6 modes) + diffraction (nh headings) at ONE frequency.
//
// Geometry as in panel_radiation_added_mass.  K is the (finite-depth
// mapped) wavenumber, omega the angular frequency, rho/g fluid
// properties, ref the reference point for rotational modes.
// Wave tables are passed from Python (see raft_tpu/native/green_table.py).
//
// Outputs: A_out/B_out (6x6) added mass / radiation damping;
// X_out (nh x 6 x 2): excitation force complex amplitudes per unit wave
// amplitude (WAMIT heading convention: beta measured from +x).
int panel_solve_frequency(int n, const double* vertices, const double* centroid,
                          const double* normal, const double* area, double K,
                          double omega, double rho, double g, const double* ref,
                          int nh, const double* headings, int nd, int na,
                          const double* lnd_grid, const double* alpha_grid,
                          const double* Ltab, const double* Mtab, double* A_out,
                          double* B_out, double* X_out) {
  const V3* verts = reinterpret_cast<const V3*>(vertices);
  const V3* cen = reinterpret_cast<const V3*>(centroid);
  const V3* nor = reinterpret_cast<const V3*>(normal);
  const V3 r0{ref[0], ref[1], ref[2]};
  GreenTab tab{nd, na, lnd_grid, alpha_grid, Ltab, Mtab};

  // ---- influence matrices: normal velocity G_v and potential P at
  // centroid i from unit source on panel j (Rankine + positive image +
  // wave term)
  std::vector<cd> Gv(static_cast<size_t>(n) * n);
  std::vector<cd> P(static_cast<size_t>(n) * n);
  for (int i = 0; i < n; ++i) {
    for (int j = 0; j < n; ++j) {
      double g_re, p_re;
      if (i == j) {
        g_re = 0.5;
        double a_eq = std::sqrt(area[j] / M_PI);
        p_re = 0.5 * a_eq;
      } else {
        V3 vel = quad_velocity(&verts[4 * j], area[j], cen[i]);
        g_re = dot(vel, nor[i]);
        p_re = quad_potential(&verts[4 * j], area[j], cen[i]);
      }
      // positive image above z = 0 (the 1/r1 term of the wave G)
      V3 iv[4];
      for (int k = 0; k < 4; ++k) {
        iv[k] = verts[4 * j + k];
        iv[k].z = -iv[k].z;
      }
      V3 velm = quad_velocity(iv, area[j], cen[i]);
      double phim = quad_potential(iv, area[j], cen[i]);
      g_re += dot(velm, nor[i]);
      p_re += phim;
      // wave term (smooth; 2x2 Gauss over the source panel).
      //
      // Sign convention: the Rankine blocks above follow the legacy
      // rows (g_re = -(true gradient) . n, +0.5 diagonal), i.e. the
      // assembled system solves  -dphi/dn = rhs.  The wave gradient is
      // the TRUE field-point gradient, so it enters with a minus; the
      // potential matrix is negated wholesale so that phi = P sigma
      // recovers the true potential for the sign-flipped sigma.
      WaveEval w = wave_panel(tab, K, cen[i], &verts[4 * j], area[j]);
      cd gn = w.grad[0] * nor[i].x + w.grad[1] * nor[i].y + w.grad[2] * nor[i].z;
      Gv[static_cast<size_t>(i) * n + j] = cd(g_re, 0.0) - gn;
      P[static_cast<size_t>(i) * n + j] = -(cd(p_re, 0.0) + w.pot);
    }
  }

  // ---- right-hand sides: 6 radiation modes + nh diffraction headings
  int nrhs = 6 + nh;
  std::vector<cd> rhs(static_cast<size_t>(nrhs) * n);
  std::vector<double> nmode(static_cast<size_t>(6) * n);
  for (int i = 0; i < n; ++i) {
    V3 rr = sub(cen[i], r0);
    double nm[6] = {nor[i].x,
                    nor[i].y,
                    nor[i].z,
                    rr.y * nor[i].z - rr.z * nor[i].y,
                    rr.z * nor[i].x - rr.x * nor[i].z,
                    rr.x * nor[i].y - rr.y * nor[i].x};
    for (int m = 0; m < 6; ++m) {
      nmode[static_cast<size_t>(m) * n + i] = nm[m];
      rhs[static_cast<size_t>(m) * n + i] = nm[m];
    }
  }
  // incident potential for UNIT POSITIVE elevation amplitude travelling
  // toward heading beta (e^{-i omega t} convention; elevation
  // zeta = (i omega / g) phi_I at z=0):
  //   phi_I = -(i g / omega) e^{Kz} e^{+i K (x cosb + y sinb)}
  // diffraction BC: d(phi_S)/dn = -d(phi_I)/dn
  std::vector<cd> phiI(static_cast<size_t>(nh) * n);
  for (int h = 0; h < nh; ++h) {
    double cb = std::cos(headings[h]);
    double sb = std::sin(headings[h]);
    for (int i = 0; i < n; ++i) {
      cd e = std::exp(cd(K * cen[i].z, K * (cen[i].x * cb + cen[i].y * sb)));
      cd pI = cd(0.0, -g / omega) * e;
      phiI[static_cast<size_t>(h) * n + i] = pI;
      cd dpx = pI * cd(0.0, K * cb);
      cd dpy = pI * cd(0.0, K * sb);
      cd dpz = pI * K;
      // the assembled system solves -dphi/dn = rhs and phi is read back
      // through the negated potential matrix, so the scattering BC
      // dphi_S/dn = -dphi_I/dn enters with rhs = -dphi_I/dn (the double
      // sign flip cancels; radiation absorbs it in the A/B formulas)
      rhs[static_cast<size_t>(6 + h) * n + i] =
          -(dpx * nor[i].x + dpy * nor[i].y + dpz * nor[i].z);
    }
  }

  std::vector<cd> Gc(Gv);
  if (lu_solve_cplx(Gc, rhs, n, nrhs)) return 1;

  // ---- potentials on the body per RHS
  std::vector<cd> phi(static_cast<size_t>(nrhs) * n);
  for (int r = 0; r < nrhs; ++r) {
    for (int i = 0; i < n; ++i) {
      cd s = 0;
      for (int j = 0; j < n; ++j)
        s += P[static_cast<size_t>(i) * n + j] * rhs[static_cast<size_t>(r) * n + j];
      phi[static_cast<size_t>(r) * n + i] = s;
    }
  }

  // ---- radiation: with true potentials (e^{-i omega t} convention)
  // rho int phi_m n_k dS = -A_km - (i/omega) B_km
  for (int k = 0; k < 6; ++k) {
    for (int m = 0; m < 6; ++m) {
      cd s = 0;
      for (int i = 0; i < n; ++i)
        s += phi[static_cast<size_t>(m) * n + i] *
             nmode[static_cast<size_t>(k) * n + i] * area[i];
      A_out[k * 6 + m] = -rho * s.real();
      B_out[k * 6 + m] = -rho * omega * s.imag();
    }
  }

  // ---- excitation: X_k = -i omega rho int (phi_I + phi_S) n_k dS
  for (int h = 0; h < nh; ++h) {
    for (int k = 0; k < 6; ++k) {
      cd s = 0;
      for (int i = 0; i < n; ++i)
        s += (phiI[static_cast<size_t>(h) * n + i] +
              phi[static_cast<size_t>(6 + h) * n + i]) *
             nmode[static_cast<size_t>(k) * n + i] * area[i];
      cd X = cd(0.0, -omega) * rho * s;
      // conjugate: the WAMIT-format files the reference pipeline
      // consumes (and the HAMS outputs validated against) carry the
      // e^{+i omega t} phase convention
      X_out[(h * 6 + k) * 2] = X.real();
      X_out[(h * 6 + k) * 2 + 1] = -X.imag();
    }
  }
  return 0;
}

// Finite-depth variant of panel_solve_frequency: the wave term is
// John's eigenfunction series (see green_fd.py for the validated
// prototype and the root solve), the incident wave uses the
// cosh-profile, and the dispersion data (k0, evanescent km, Cm) comes
// precomputed from Python.
//
// NOTE: the assembly/solve/output blocks mirror panel_solve_frequency
// line for line (only the wave kernel and incident wave differ).  Any
// fix to the sign-convention logic (negated P matrix, conjugated X
// output, self terms) MUST be applied to both functions.
int panel_solve_frequency_fd(
    int n, const double* vertices, const double* centroid,
    const double* normal, const double* area, double omega, double rho,
    double g, double depth, const double* ref, int nh,
    const double* headings, int n_modes, double k0_in, const double* km,
    const double* Cm, double* A_out, double* B_out, double* X_out) {
  const V3* verts = reinterpret_cast<const V3*>(vertices);
  const V3* cen = reinterpret_cast<const V3*>(centroid);
  const V3* nor = reinterpret_cast<const V3*>(normal);
  const V3 r0{ref[0], ref[1], ref[2]};

  std::call_once(kBesselOnce, build_bessel_tables);

  double K = omega * omega / g;
  double c0h = k0_in * depth;
  double sech2 = (c0h < 350.0)
                     ? 1.0 / (std::cosh(c0h) * std::cosh(c0h))
                     : 4.0 * std::exp(-2.0 * c0h);
  FDGreen fd{n_modes, K, depth, k0_in,
             depth * k0_in * k0_in * sech2 + K, km, Cm};

  std::vector<cd> Gv(static_cast<size_t>(n) * n);
  std::vector<cd> P(static_cast<size_t>(n) * n);
  for (int i = 0; i < n; ++i) {
    for (int j = 0; j < n; ++j) {
      double g_re, p_re;
      if (i == j) {
        g_re = 0.5;
        double a_eq = std::sqrt(area[j] / M_PI);
        p_re = 0.5 * a_eq;
      } else {
        V3 vel = quad_velocity(&verts[4 * j], area[j], cen[i]);
        g_re = dot(vel, nor[i]);
        p_re = quad_potential(&verts[4 * j], area[j], cen[i]);
      }
      // positive surface image (the fd wave term subtracts it)
      V3 iv[4];
      for (int k = 0; k < 4; ++k) {
        iv[k] = verts[4 * j + k];
        iv[k].z = -iv[k].z;
      }
      V3 velm = quad_velocity(iv, area[j], cen[i]);
      double phim = quad_potential(iv, area[j], cen[i]);
      g_re += dot(velm, nor[i]);
      p_re += phim;
      WaveEval w = fd_wave_panel(fd, cen[i], &verts[4 * j], area[j]);
      cd gn = w.grad[0] * nor[i].x + w.grad[1] * nor[i].y + w.grad[2] * nor[i].z;
      Gv[static_cast<size_t>(i) * n + j] = cd(g_re, 0.0) - gn;
      P[static_cast<size_t>(i) * n + j] = -(cd(p_re, 0.0) + w.pot);
    }
  }

  int nrhs = 6 + nh;
  std::vector<cd> rhs(static_cast<size_t>(nrhs) * n);
  std::vector<double> nmode(static_cast<size_t>(6) * n);
  for (int i = 0; i < n; ++i) {
    V3 rr = sub(cen[i], r0);
    double nm[6] = {nor[i].x,
                    nor[i].y,
                    nor[i].z,
                    rr.y * nor[i].z - rr.z * nor[i].y,
                    rr.z * nor[i].x - rr.x * nor[i].z,
                    rr.x * nor[i].y - rr.y * nor[i].x};
    for (int m = 0; m < 6; ++m) {
      nmode[static_cast<size_t>(m) * n + i] = nm[m];
      rhs[static_cast<size_t>(m) * n + i] = nm[m];
    }
  }
  // finite-depth incident wave, unit positive elevation amplitude:
  //   phi_I = -(i g / omega) (cosh k0(z+h)/cosh k0 h) e^{+i k0 (x cb + y sb)}
  std::vector<cd> phiI(static_cast<size_t>(nh) * n);
  for (int h = 0; h < nh; ++h) {
    double cb = std::cos(headings[h]);
    double sb = std::sin(headings[h]);
    for (int i = 0; i < n; ++i) {
      double a = k0_in * (cen[i].z + depth);
      double prof = std::exp(a - c0h) * (1 + std::exp(-2 * a)) /
                    (1 + std::exp(-2 * c0h));
      cd e = prof *
             std::exp(cd(0.0, k0_in * (cen[i].x * cb + cen[i].y * sb)));
      cd pI = cd(0.0, -g / omega) * e;
      phiI[static_cast<size_t>(h) * n + i] = pI;
      cd dpx = pI * cd(0.0, k0_in * cb);
      cd dpy = pI * cd(0.0, k0_in * sb);
      cd dpz = pI * (k0_in * std::tanh(a));
      rhs[static_cast<size_t>(6 + h) * n + i] =
          -(dpx * nor[i].x + dpy * nor[i].y + dpz * nor[i].z);
    }
  }

  std::vector<cd> Gc(Gv);
  if (lu_solve_cplx(Gc, rhs, n, nrhs)) return 1;

  std::vector<cd> phi(static_cast<size_t>(nrhs) * n);
  for (int r = 0; r < nrhs; ++r) {
    for (int i = 0; i < n; ++i) {
      cd s = 0;
      for (int j = 0; j < n; ++j)
        s += P[static_cast<size_t>(i) * n + j] *
             rhs[static_cast<size_t>(r) * n + j];
      phi[static_cast<size_t>(r) * n + i] = s;
    }
  }

  for (int k = 0; k < 6; ++k) {
    for (int m = 0; m < 6; ++m) {
      cd s = 0;
      for (int i = 0; i < n; ++i)
        s += phi[static_cast<size_t>(m) * n + i] *
             nmode[static_cast<size_t>(k) * n + i] * area[i];
      A_out[k * 6 + m] = -rho * s.real();
      B_out[k * 6 + m] = -rho * omega * s.imag();
    }
  }

  for (int h = 0; h < nh; ++h) {
    for (int k = 0; k < 6; ++k) {
      cd s = 0;
      for (int i = 0; i < n; ++i)
        s += (phiI[static_cast<size_t>(h) * n + i] +
              phi[static_cast<size_t>(6 + h) * n + i]) *
             nmode[static_cast<size_t>(k) * n + i] * area[i];
      cd X = cd(0.0, -omega) * rho * s;
      X_out[(h * 6 + k) * 2] = X.real();
      X_out[(h * 6 + k) * 2 + 1] = -X.imag();
    }
  }
  return 0;
}

}  // extern "C"

extern "C" {

// Solve the radiation problem for all 6 rigid-body modes.
//
// vertices : (n, 4, 3) panel corner coordinates (below the waterline)
// centroid : (n, 3); normal : (n, 3) body-outward unit normals;
// area     : (n,)
// mirror   : -1 (phi=0 free surface, w->inf) or +1 (rigid lid, w->0)
// rho      : fluid density
// ref      : (3,) reference point for the rotational modes
// A_out    : (6, 6) added-mass matrix, row major
//
// Returns 0 on success.
int panel_radiation_added_mass(int n, const double* vertices,
                               const double* centroid, const double* normal,
                               const double* area, int mirror, double rho,
                               const double* ref, double* A_out) {
  const V3* verts = reinterpret_cast<const V3*>(vertices);
  const V3* cen = reinterpret_cast<const V3*>(centroid);
  const V3* nor = reinterpret_cast<const V3*>(normal);
  const V3 r0{ref[0], ref[1], ref[2]};

  // ---- influence matrix: normal velocity at panel i from unit source
  // on panel j (+ mirrored image panel)
  std::vector<double> G(static_cast<size_t>(n) * n);
  std::vector<double> P(static_cast<size_t>(n) * n);  // potentials
  for (int i = 0; i < n; ++i) {
    for (int j = 0; j < n; ++j) {
      if (i == j) {
        // analytic self terms: half-space velocity jump + equivalent disk
        G[i * n + j] = 0.5;  // (sigma/2) outward normal velocity
        double a_eq = std::sqrt(area[j] / M_PI);
        P[i * n + j] = 0.5 * a_eq;  // disk potential a/2 for 1/(4 pi r)
      } else {
        V3 vel = quad_velocity(&verts[4 * j], area[j], cen[i]);
        G[i * n + j] = dot(vel, nor[i]);
        P[i * n + j] = quad_potential(&verts[4 * j], area[j], cen[i]);
      }
      // mirrored image above z = 0
      V3 iv[4];
      for (int k = 0; k < 4; ++k) {
        iv[k] = verts[4 * j + k];
        iv[k].z = -iv[k].z;
      }
      V3 velm = quad_velocity(iv, area[j], cen[i]);
      double phim = quad_potential(iv, area[j], cen[i]);
      G[i * n + j] += mirror * dot(velm, nor[i]);
      P[i * n + j] += mirror * phim;
    }
  }

  // ---- modes: rigid-body normal velocities
  // translations: n_k ; rotations: ((r - r0) x n)_k
  std::vector<double> phi(static_cast<size_t>(6) * n);  // panel potentials per mode
  std::vector<double> nmode(static_cast<size_t>(6) * n);
  for (int i = 0; i < n; ++i) {
    V3 rr = sub(cen[i], r0);
    double nm[6] = {nor[i].x,
                    nor[i].y,
                    nor[i].z,
                    rr.y * nor[i].z - rr.z * nor[i].y,
                    rr.z * nor[i].x - rr.x * nor[i].z,
                    rr.x * nor[i].y - rr.y * nor[i].x};
    for (int m = 0; m < 6; ++m) nmode[m * n + i] = nm[m];
  }

  for (int m = 0; m < 6; ++m) {
    std::vector<double> Gc(G);  // LU destroys the matrix
    std::vector<double> rhs(nmode.begin() + m * n, nmode.begin() + (m + 1) * n);
    if (lu_solve(Gc, rhs, n)) return 1;
    // potentials phi_m(i) = sum_j P(i,j) sigma_j
    for (int i = 0; i < n; ++i) {
      double s = 0.0;
      for (int j = 0; j < n; ++j) s += P[i * n + j] * rhs[j];
      phi[m * n + i] = s;
    }
  }

  // ---- added mass A_km = rho * sum_i phi_m(i) n_k(i) dS_i
  for (int k = 0; k < 6; ++k) {
    for (int m = 0; m < 6; ++m) {
      double s = 0.0;
      for (int i = 0; i < n; ++i) s += phi[m * n + i] * nmode[k * n + i] * area[i];
      A_out[k * 6 + m] = rho * s;
    }
  }
  return 0;
}

}  // extern "C"
