"""Traced flexible-FOWT evaluator parity vs the orchestrated path
(VERDICT r2 #3): ``api.make_flexible_evaluator`` runs the 150-DOF
VolturnUS-S-flexible chain — equilibrium, traced nonlinear
displaced-pose kinematics + position-dependent T
(structure/topology_traced.py), N-DOF excitation and drag-linearised
impedance solves — as one jit, matching ``Model.solve_dynamics`` at
1e-9 (which itself matches the reference analyzeCases golden at ~1e-9,
tests/test_flexible.py).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.conftest import ref_data

import raft_tpu
from raft_tpu.api import make_flexible_evaluator

pytestmark = pytest.mark.slow

PATH = ref_data("VolturnUS-S-flexible.yaml")


@pytest.fixture(scope="module")
def model():
    if not os.path.exists(PATH):
        pytest.skip("reference data unavailable")
    return raft_tpu.Model(PATH)


def test_flexible_evaluator_parity(model):
    case = dict(zip(model.design["cases"]["keys"],
                    model.design["cases"]["data"][0]))
    X0_o = model.solve_statics(case)
    Xi_o, info = model.solve_dynamics(case, X0=X0_o)

    evaluate = jax.jit(make_flexible_evaluator(model))
    out = evaluate(dict(
        wind_speed=float(case["wind_speed"]),
        Hs=float(case["wave_height"]), Tp=float(case["wave_period"]),
        beta_deg=float(case["wave_heading"])))

    scale_X = np.max(np.abs(np.asarray(X0_o)))
    np.testing.assert_allclose(np.asarray(out["X0"]), np.asarray(X0_o),
                               atol=1e-9 * scale_X, rtol=0)
    Xi_o = np.asarray(Xi_o)
    Xi_t = np.asarray(out["Xi"])
    scale = np.max(np.abs(Xi_o))
    np.testing.assert_allclose(Xi_t, Xi_o, atol=1e-9 * scale, rtol=0)
    assert Xi_t.shape[1] == 150


def test_flexible_evaluator_vmaps(model):
    """The 150-DOF evaluator vmaps over a sea-state batch."""
    evaluate = make_flexible_evaluator(model)
    fn = jax.jit(jax.vmap(lambda h, t: evaluate(dict(Hs=h, Tp=t))["PSD"]))
    B = 2
    out = fn(jnp.asarray([3.0, 5.0]), jnp.asarray([9.0, 12.0]))
    assert out.shape == (B, 150, model.nw)
    assert bool(jnp.all(jnp.isfinite(out)))
