"""High-level drivers: the ``runRAFT``-style entry point and utilities.

Equivalents of the reference driver layer (``/root/reference/raft/
raft_model.py``: ``runRAFT`` :2247-2285, ``saveResponses`` :1400-1462,
``powerThrustCurve`` :1877-1955) plus a module CLI
(``python -m raft_tpu design.yaml``).
"""

from __future__ import annotations

import numpy as np


def run(input_file, save_csv=None):
    """Load a design, analyze all load cases, return the Model.

    runRAFT equivalent: YAML -> Model -> analyze_cases (-> CSV)."""
    import raft_tpu
    from raft_tpu.obs import span
    from raft_tpu.utils.devices import enable_compile_cache

    enable_compile_cache()
    with span("driver.run", input=str(input_file)):
        model = raft_tpu.Model(input_file)
        model.analyze_cases()
        if save_csv:
            save_responses(model, save_csv)
    return model


def run_farm(input_file, save_csv=None):
    """One-call farm driver (runRAFTFarm equivalent,
    raft_model.py:2287-2310).  The reference's farm entry skips
    ``analyzeUnloaded`` and ``calcOutputs`` (unsupported for arrays
    there); here ``analyze_cases`` already covers the array path, so
    this is the same one-call convenience with the farm-safe scope:
    case metrics only, no single-FOWT property/eigen outputs.
    Returns the Model."""
    import raft_tpu
    from raft_tpu.obs import span
    from raft_tpu.utils.devices import enable_compile_cache

    enable_compile_cache()
    with span("driver.run_farm", input=str(input_file)):
        model = raft_tpu.Model(input_file)
        model.analyze_cases()
        if save_csv:
            save_responses(model, save_csv)
    return model


def warmup(input_file=None, sizes=(8,), kinds=("cases", "full", "design"),
           out_keys=("PSD", "X0", "status")):
    """Warm the AOT program bank for a design before serving it.

    The driver-level face of ``python -m raft_tpu.aot warmup``: builds
    the model once and pushes every requested sweep kind through the
    production dispatch funnel under ``RAFT_TPU_AOT=load``, so each
    program is lowered, compiled and exported to the bank
    (``RAFT_TPU_AOT_DIR``).  A subsequent fresh process — a worker
    joining mid-sweep, a serving replica, the next bench round — then
    answers its first sweep from deserialized executables with zero
    backend compilations (run it under ``RAFT_TPU_AOT=require`` +
    ``RAFT_TPU_COMPILE_BUDGET=0`` to make that an enforced invariant).

    sizes : batch sizes to warm, one program each (warm the shard
        sizes you will dispatch; tail shards pad to the device count).
    Returns the per-program warmup reports (kind, rows,
    loaded/compiled, seconds)."""
    from raft_tpu.aot.warmup import warmup_model

    return warmup_model(design=input_file, sizes=sizes, kinds=kinds,
                        out_keys=out_keys)


def save_responses(model, path):
    """Write per-case channel statistics to CSV (saveResponses analog)."""
    rows = ["case,fowt,channel,avg,std,max,min"]
    for iCase, per_fowt in model.results["case_metrics"].items():
        for ifowt, metrics in per_fowt.items():
            for ch in ("surge", "sway", "heave", "roll", "pitch", "yaw"):
                rows.append(
                    f"{iCase},{ifowt},{ch},"
                    f"{float(metrics[ch + '_avg']):.6e},"
                    f"{float(metrics[ch + '_std']):.6e},"
                    f"{float(metrics[ch + '_max']):.6e},"
                    f"{float(metrics[ch + '_min']):.6e}"
                )
            if "Tmoor_avg" in metrics:
                T = np.asarray(metrics["Tmoor_avg"])
                Ts = np.asarray(metrics["Tmoor_std"])
                for iT in range(len(T)):
                    rows.append(
                        f"{iCase},{ifowt},Tmoor{iT},"
                        f"{T[iT]:.6e},{Ts[iT]:.6e},"
                        f"{T[iT] + 3 * Ts[iT]:.6e},{T[iT] - 3 * Ts[iT]:.6e}"
                    )
    with open(path, "w") as f:
        f.write("\n".join(rows) + "\n")


def power_thrust_curve(model, speeds, ifowt=0, ir=0):
    """Steady power/thrust curve over wind speeds via the jax BEMT
    (powerThrustCurve equivalent) — one vmapped rotor evaluation.

    Returns dict(speeds, thrust [N], torque [Nm], power [W],
    Omega_rpm, pitch_deg)."""
    import jax
    import jax.numpy as jnp

    from raft_tpu.physics.aero import operating_point, rotor_loads

    rot = model.rotor_aero[ir]
    rprops = model.fowtList[ifowt].rotors[ir]
    tilt = -np.arctan2(rprops.q_rel[2], np.hypot(rprops.q_rel[0], rprops.q_rel[1]))

    def one(U):
        Om, pit = operating_point(rot, U)
        loads = rotor_loads(rot, U, Om, pit, tilt, 0.0)
        return loads[0], loads[3], loads[3] * Om * jnp.pi / 30.0, Om, pit

    T, Q, P, Om, pit = jax.vmap(one)(jnp.asarray(speeds, dtype=float))
    return dict(
        speeds=np.asarray(speeds), thrust=np.asarray(T), torque=np.asarray(Q),
        power=np.asarray(P), Omega_rpm=np.asarray(Om), pitch_deg=np.asarray(pit),
    )


def adjust_ballast(base_design, target_heave=0.0, heave_tol=0.05, max_iter=12):
    """Tune ballast fill levels to reach a target unloaded mean heave.

    Equivalent of Model.adjustBallast (raft_model.py:1633-1770): secant
    iteration on a global scale factor applied to every ballasted
    section's fill length, re-solving the unloaded equilibrium each
    step.  Returns (model, scale) with the adjusted design built in.
    """
    import copy

    import numpy as np

    import raft_tpu
    from raft_tpu.structure.schema import load_design

    base = load_design(base_design)

    def heave_at(scale):
        d = copy.deepcopy(base)
        members = d["platform"]["members"]
        for mi in members:
            if "l_fill" in mi and np.any(np.asarray(mi["l_fill"], dtype=float) > 0):
                lf = np.atleast_1d(np.asarray(mi["l_fill"], dtype=float)) * scale
                st = np.asarray(mi["stations"], dtype=float)
                lf = np.minimum(lf, np.diff(st))  # can't overfill a section
                mi["l_fill"] = lf.tolist() if lf.size > 1 else float(lf[0])
        model = raft_tpu.Model(d)
        X = np.asarray(model.solve_statics(None))
        return float(X[2]), model

    s0, s1 = 1.0, 1.05
    h0, model = heave_at(s0)
    if abs(h0 - target_heave) < heave_tol:
        return model, s0
    h1, model = heave_at(s1)
    for _ in range(max_iter):
        if abs(h1 - h0) < 1e-12:
            break
        s2 = s1 - (h1 - target_heave) * (s1 - s0) / (h1 - h0)
        s2 = float(np.clip(s2, 0.0, 3.0))
        h2, model = heave_at(s2)
        s0, h0, s1, h1 = s1, h1, s2, h2
        if abs(h1 - target_heave) < heave_tol:
            break
    return model, s1


def adjust_ballast_density(base_design):
    """Uniformly shift ballast fill densities to zero the unloaded
    heave (Model.adjustBallastDensity equivalent, raft_model.py:1772).

    One closed-form step: delta_rho = sumFz / (g * V_ballast), applied
    to every section with nonzero fill, then the model is rebuilt.
    Returns (model, delta_rho).
    """
    import copy

    import numpy as np

    import raft_tpu
    from raft_tpu.structure.schema import load_design

    base = load_design(base_design)
    model = raft_tpu.Model(copy.deepcopy(base))
    fs = model.fowtList[0]
    stat = model.statics(0)
    g = fs.g
    X0 = np.asarray(model.solve_statics(None))
    from raft_tpu.physics.mooring import mooring_force
    import jax.numpy as jnp

    Fm = np.zeros(6)
    if model.ms is not None:
        Fm = np.asarray(mooring_force(model.ms, jnp.asarray(X0[:6]))[0])
    sumFz = (-float(np.asarray(stat["M_struc"])[0, 0]) * g
             + float(stat["V"]) * fs.rho_water * g + Fm[2])

    V_ballast = float(sum(sum(m.vfill) for m in fs.members))
    if V_ballast <= 0:
        raise ValueError("adjust_ballast_density needs nonzero ballast volume")
    delta_rho = sumFz / g / V_ballast

    d = copy.deepcopy(base)
    for mi in d["platform"]["members"]:
        if "rho_fill" in mi and "l_fill" in mi:
            lf = np.atleast_1d(np.asarray(mi["l_fill"], dtype=float))
            rf = np.atleast_1d(np.asarray(mi["rho_fill"], dtype=float))
            rf = np.where(lf > 0, rf + delta_rho, rf)
            mi["rho_fill"] = rf.tolist() if rf.size > 1 else float(rf[0])
    return raft_tpu.Model(d), float(delta_rho)


def adjust_wisdem(model, old_wisdem_file, new_wisdem_file):
    """Write RAFT-adjusted ballast fill volumes back into a WISDEM
    geometry YAML (Model.adjustWISDEM equivalent, raft_model.py:1830):
    WISDEM members are matched to RAFT members by bottom-joint elevation
    and base diameter, and their first ballast volume is updated from
    the RAFT member's fill level."""
    import numpy as np
    import yaml

    with open(old_wisdem_file, encoding="utf-8") as f:
        wisdem_design = yaml.safe_load(f)

    fs = model.fowtList[0]
    members_w = wisdem_design["components"]["floating_platform"]["members"]
    joints_w = wisdem_design["components"]["floating_platform"]["joints"]
    for wm in members_w:
        if "ballasts" not in wm.get("internal_structure", {}):
            continue
        for rm in fs.members:
            matched = False
            for joint in joints_w:
                if wm["joint1"] != joint["name"]:
                    continue
                same_z = str(joint["location"][2])[0:5] == str(rm.rA0[2])[0:5]
                same_d = (wm["outer_shape"]["outer_diameter"]["values"][0]
                          == rm.d[0, 0])
                if same_z and same_d:
                    area = np.pi * ((rm.d[0, 0] - 2 * rm.t[0]) / 2) ** 2
                    lf = np.atleast_1d(np.asarray(rm.l_fill, dtype=float))
                    wm["internal_structure"]["ballasts"][0]["volume"] = \
                        float(area * lf[0])
                    matched = True
                break
            if matched:
                break

    with open(new_wisdem_file, "w", encoding="utf-8") as f:
        yaml.safe_dump(wisdem_design, f, default_flow_style=None, sort_keys=False)
    return wisdem_design
