"""Quasi-static catenary mooring (jax).

A TPU-native replacement for the MoorPy dependency the reference uses
for mooring reactions (imported at ``/root/reference/raft/raft_model.py:17``
and ``raft_fowt.py:13``; RAFT consumes ``ms.solveEquilibrium`` +
``getCoupledStiffnessA(lines_only=True)`` + body forces,
``raft_fowt.py:797-808``).

Design:
* the classic elastic catenary with flat-seabed contact is solved per
  line by a fixed-iteration damped Newton on (HF, VF) — shape-static,
  so the whole mooring system evaluates as one fused expression and
  ``vmap``s over bodies/designs;
* the 6-DOF mooring force on the platform is a pure function of the
  platform pose, and the coupled stiffness matrix is its exact
  (auto-diff) Jacobian — equivalent to MoorPy's analytic
  ``getCoupledStiffnessA`` in the quasi-static limit;
* the same solve yields fairlead/anchor tensions for output metrics.

Catenary formulation (suspended + grounded regimes, no seabed
friction), e.g. Jonkman (2007) mooring appendix — the same model MoorPy
implements.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.ops import transforms as tf
from raft_tpu.structure.schema import coerce


# ----------------------------------------------------------------- build

@dataclass
class MooringSystem:
    """Static description of one body's mooring system."""

    r_anchor: np.ndarray    # (nL, 3) fixed anchor coordinates
    r_fair0: np.ndarray     # (nL, 3) fairlead coordinates at zero pose
    L: np.ndarray           # (nL,) unstretched lengths
    w: np.ndarray           # (nL,) submerged weight per length [N/m]
    EA: np.ndarray          # (nL,) axial stiffness [N]
    depth: float
    # line-dynamics properties (lumped-mass moorMod 1/2); MoorDyn-style
    # defaults filled by build_mooring when the design omits them
    m_lin: np.ndarray | None = None   # (nL,) structural mass per length
    d_vol: np.ndarray | None = None   # (nL,) volume-equivalent diameter
    Cd: np.ndarray | None = None      # transverse drag
    Ca: np.ndarray | None = None      # transverse added mass
    CdAx: np.ndarray | None = None    # tangential drag
    CaAx: np.ndarray | None = None    # tangential added mass
    BA: np.ndarray | None = None      # internal damping [N-s], <0 = -zeta
    moorMod: int = 0

    @property
    def n_lines(self):
        return len(self.L)


def build_mooring(mooring, rho_water=1025.0, g=9.81, x_ref=0.0, y_ref=0.0,
                  heading_adjust=0.0):
    """Parse the design's ``mooring`` section (MoorPy-compatible schema:
    points / lines / line_types) into a MooringSystem.

    Submerged weight per length w = (m' - rho pi/4 d^2) g with d the
    volume-equivalent diameter (MoorPy convention).  ``x_ref/y_ref`` and
    ``heading_adjust`` transform the whole system to the FOWT's array
    position (raft_fowt.py:367 ms.transform)."""
    depth = float(coerce(mooring, "water_depth", default=600.0))
    types = {lt["name"]: lt for lt in mooring["line_types"]}
    points = {p["name"]: p for p in mooring["points"]}

    r_anchor, r_fair, L, w, EA = [], [], [], [], []
    m_lin_l, d_l, Cd_l, Ca_l, CdAx_l, CaAx_l = [], [], [], [], [], []
    BA_sch = []
    for line in mooring["lines"]:
        pA = points[line["endA"]]
        pB = points[line["endB"]]
        # orient so end A is the fixed anchor
        if pA["type"] == "fixed":
            anchor, fair = pA, pB
        else:
            anchor, fair = pB, pA
        lt = types[line["type"]]
        d = float(lt["diameter"])
        m_lin = float(lt["mass_density"])
        r_anchor.append(np.array(anchor["location"], dtype=float))
        r_fair.append(np.array(fair["location"], dtype=float))
        L.append(float(line["length"]))
        w.append((m_lin - rho_water * np.pi / 4 * d**2) * g)
        EA.append(float(lt["stiffness"]))
        m_lin_l.append(m_lin)
        d_l.append(d)
        Cd_l.append(float(coerce(lt, "transverse_drag", default=1.2)))
        Ca_l.append(float(coerce(lt, "transverse_added_mass", default=1.0)))
        CdAx_l.append(float(coerce(lt, "tangential_drag", default=0.05)))
        CaAx_l.append(float(coerce(lt, "tangential_added_mass", default=0.0)))
        BA_sch.append(float(coerce(lt, "damping", default=0.0)))

    r_anchor = np.array(r_anchor)
    r_fair = np.array(r_fair)
    if heading_adjust != 0.0:
        c, s = np.cos(np.deg2rad(heading_adjust)), np.sin(np.deg2rad(heading_adjust))
        Rz = np.array([[c, -s, 0.0], [s, c, 0.0], [0.0, 0.0, 1.0]])
        r_anchor = r_anchor @ Rz.T
        r_fair = r_fair @ Rz.T
    r_anchor = r_anchor + np.array([x_ref, y_ref, 0.0])
    # fairleads stay body-local (the body pose carries x_ref/y_ref)

    return MooringSystem(
        r_anchor=np.array(r_anchor),
        r_fair0=np.array(r_fair),
        L=np.array(L),
        w=np.array(w),
        EA=np.array(EA),
        depth=depth,
        m_lin=np.array(m_lin_l),
        d_vol=np.array(d_l),
        Cd=np.array(Cd_l),
        Ca=np.array(Ca_l),
        CdAx=np.array(CdAx_l),
        CaAx=np.array(CaAx_l),
        BA=np.array(BA_sch),
        moorMod=int(coerce(mooring, "moorMod", default=0, dtype=int)),
    )


# --------------------------------------------------------------- catenary

def _profile(HF, VF, L, w, EA, can_ground=True):
    """Horizontal/vertical fairlead-anchor spans (XF, ZF) of an elastic
    catenary with fairlead loads (HF, VF); flat frictionless seabed.

    Grounded when VF < w L and the lower end rests on the seabed
    (``can_ground`` — True for anchor lines, False for suspended /
    shared lines between floating bodies)."""
    HF = jnp.maximum(HF, 1e-8)
    t1 = VF / HF
    s1 = jnp.sqrt(1.0 + t1 * t1)
    asinh1 = jnp.arcsinh(t1)  # stable for negative arguments

    # grounded regime
    LB = L - VF / w
    XF_g = LB + (HF / w) * asinh1 + HF * L / EA
    ZF_g = (HF / w) * (s1 - 1.0) + VF**2 / (2.0 * EA * w)

    # fully suspended regime
    VA = VF - w * L
    t2 = VA / HF
    s2 = jnp.sqrt(1.0 + t2 * t2)
    asinh2 = jnp.arcsinh(t2)
    XF_s = (HF / w) * (asinh1 - asinh2) + HF * L / EA
    ZF_s = (HF / w) * (s1 - s2) + (VF * L - 0.5 * w * L**2) / EA

    grounded = (VF < w * L) & can_ground
    return jnp.where(grounded, XF_g, XF_s), jnp.where(grounded, ZF_g, ZF_s)


def solve_catenary(XF, ZF, L, w, EA, n_iter=60, can_ground=True):
    """Solve (HF, VF) such that the catenary spans (XF, ZF).

    Damped Newton with the MoorPy-style initial guess; fixed iteration
    count for trace-static shapes (fully converged for physical inputs).
    Returns (HF, VF, HA, VA)."""
    XF = jnp.maximum(XF, 1e-6)
    lr = jnp.sqrt(XF**2 + ZF**2)
    taut = L <= lr
    # slack seed: MoorPy-style sag parameter; taut seed: elastic estimate
    arg = jnp.maximum(3.0 * ((L**2 - ZF**2) / XF**2 - 1.0), 1e-12)
    lam = jnp.sqrt(arg)
    HF_slack = jnp.maximum(jnp.abs(0.5 * w * XF / lam), 1e-3)
    VF_slack = 0.5 * w * (ZF / jnp.tanh(lam) + L)
    T0 = jnp.maximum(EA * (lr - L) / L, w * L)
    HF_taut = T0 * XF / lr
    VF_taut = T0 * ZF / lr + 0.5 * w * L
    HF = jnp.where(taut, HF_taut, HF_slack)
    VF = jnp.where(taut, VF_taut, VF_slack)

    def res(hv):
        x, z = _profile(hv[0], hv[1], L, w, EA, can_ground=can_ground)
        return jnp.stack([x - XF, z - ZF])

    def body(carry, _):
        HF, VF = carry
        hv = jnp.stack([HF, VF])
        r = res(hv)
        J = jax.jacfwd(res)(hv)
        det = J[0, 0] * J[1, 1] - J[0, 1] * J[1, 0]
        det = jnp.where(jnp.abs(det) < 1e-30, 1e-30, det)
        dH = -(r[0] * J[1, 1] - r[1] * J[0, 1]) / det
        dV = -(J[0, 0] * r[1] - J[1, 0] * r[0]) / det
        # backtracking: halve the step until the residual norm decreases
        rn0 = jnp.linalg.norm(r)

        def try_step(alpha):
            hv2 = jnp.stack([jnp.maximum(HF + alpha * dH, 1e-6), VF + alpha * dV])
            return jnp.linalg.norm(res(hv2))

        alpha = jnp.asarray(1.0)
        for _ in range(4):
            worse = try_step(alpha) > rn0
            alpha = jnp.where(worse, 0.5 * alpha, alpha)
        HF2 = jnp.maximum(HF + alpha * dH, 1e-6)
        VF2 = VF + alpha * dV
        # reject non-finite steps outright
        ok = jnp.isfinite(HF2) & jnp.isfinite(VF2)
        return (jnp.where(ok, HF2, HF), jnp.where(ok, VF2, VF)), None

    (HF, VF), _ = jax.lax.scan(body, (HF, VF), None, length=n_iter)
    HA = HF  # no seabed friction
    grounded = (VF < w * L) & can_ground
    VA = jnp.where(grounded, 0.0, VF - w * L)
    return HF, VF, HA, VA


# ------------------------------------------------------------ body level

def catenary_line_forces(r_fair0, r_anchor, L, w, EA, r6):
    """Per-line 6-DOF fairlead force contributions about the body
    origin at pose ``r6`` (catenary lines, no seabed friction), plus
    per-line tension components.  Single source of the line-force
    body: :func:`mooring_force` sums all lines, the shape-bucketed
    masked closures (:mod:`raft_tpu.structure.bucketing`) sum under a
    validity mask — both MUST trace identical per-line physics or the
    bucketed solo-parity contract breaks."""
    R = tf.rotation_matrix(r6[3], r6[4], r6[5])
    r_fair = r6[:3] + jnp.asarray(r_fair0) @ R.T  # (nL, 3)
    dvec = r_fair - jnp.asarray(r_anchor)
    XF = jnp.sqrt(dvec[:, 0] ** 2 + dvec[:, 1] ** 2)
    ZF = dvec[:, 2]
    XF_safe = jnp.maximum(XF, 1e-8)
    u_h = dvec[:, :2] / XF_safe[:, None]

    HF, VF, HA, VA = jax.vmap(solve_catenary)(
        XF, ZF, jnp.asarray(L), jnp.asarray(w), jnp.asarray(EA)
    )
    F_fair = jnp.concatenate([-HF[:, None] * u_h, -VF[:, None]], axis=1)  # (nL,3)
    F6 = tf.translate_force_3to6(F_fair, r_fair - r6[:3])
    return F6, dict(HF=HF, VF=VF, HA=HA, VA=VA)


def mooring_force(ms, r6):
    """Net 6-DOF mooring force on the body at pose ``r6`` about the body
    origin (line forces only).  Accepts a MooringSystem or a one-body
    MooringNetwork (MoorDyn-file moorings with free points)."""
    if isinstance(ms, MooringNetwork):
        F, info = ms.body_forces(jnp.asarray(r6)[None, :])
        t = info["tensions"]  # (nL, 2) anchor/fairlead magnitudes
        return F[0], dict(HF=t[:, 1], VF=jnp.zeros_like(t[:, 1]),
                          HA=t[:, 0], VA=jnp.zeros_like(t[:, 0]))
    F6, info = catenary_line_forces(ms.r_fair0, ms.r_anchor, ms.L, ms.w,
                                    ms.EA, r6)
    return jnp.sum(F6, axis=0), info


def mooring_stiffness(ms, r6):
    """Coupled 6x6 mooring stiffness C = -dF/dr6 at pose r6 (exact
    Jacobian; MoorPy getCoupledStiffnessA equivalent)."""
    if isinstance(ms, MooringNetwork):
        return ms.stiffness(jnp.asarray(r6)[None, :])
    f = lambda x: mooring_force(ms, x)[0]
    return -jax.jacfwd(f)(jnp.asarray(r6, dtype=float))


def mooring_tensions(ms: MooringSystem, r6):
    """Fairlead and anchor tensions per line (for output metrics)."""
    _, info = mooring_force(ms, r6)
    T_fair = jnp.sqrt(info["HF"] ** 2 + info["VF"] ** 2)
    T_anch = jnp.sqrt(info["HA"] ** 2 + info["VA"] ** 2)
    return T_fair, T_anch


# ------------------------------------------------------------- networks

class MooringNetwork:
    """General quasi-static mooring network: lines between fixed
    anchors, body-attached fairleads and *free* points (e.g. mid-line
    clump weights in shared-mooring farms).

    Equivalent of an array-level MoorPy system loaded from a MoorDyn
    file (raft_model.py:84-106).  Free-point equilibrium is an inner
    damped-Newton solve (MoorPy's solveEquilibrium analog) and the
    coupled force on each body is a pure function of all body poses, so
    stiffness blocks (including body-body coupling through shared
    lines) come from ``jax.jacfwd``.
    """

    def __init__(self, depth, g=9.81, rho=1025.0, bathymetry=None):
        self.depth = float(depth)
        self.g = g
        self.rho = rho
        # optional (x_grid, y_grid, depth_grid) bathymetry; when set,
        # the local seabed depth at each point's (x, y) replaces the
        # uniform depth in the anchor/grounding classification (the
        # functional effect of the reference's MoorPy bathymetry at the
        # quasi-static fidelity, raft_model.py:87-91)
        self.bathymetry = bathymetry
        # points
        self.p_kind = []     # 0 fixed, 1 body-attached, 2 free
        self.p_body = []     # body index for kind 1
        self.p_r = []        # fixed/initial position or body-local position
        self.p_mass = []
        self.p_vol = []
        # lines
        self.l_ends = []     # (ptA, ptB)
        self.l_L = []
        self.l_w = []
        self.l_EA = []

    # ------------------------------------------------------------ build
    def add_point(self, kind, r, body=-1, mass=0.0, vol=0.0):
        self.p_kind.append(kind)
        self.p_body.append(body)
        self.p_r.append(np.asarray(r, dtype=float))
        self.p_mass.append(mass)
        self.p_vol.append(vol)
        return len(self.p_kind) - 1

    def add_line(self, pA, pB, L, w, EA):
        self.l_ends.append((pA, pB))
        self.l_L.append(L)
        self.l_w.append(w)
        self.l_EA.append(EA)

    def finalize(self):
        self.p_kind = np.asarray(self.p_kind)
        self.p_body = np.asarray(self.p_body)
        self.p_r = np.asarray(self.p_r)
        self.p_mass = np.asarray(self.p_mass)
        self.p_vol = np.asarray(self.p_vol)
        self.free_idx = np.where(self.p_kind == 2)[0]
        self.n_bodies = int(self.p_body.max()) + 1 if len(self.p_body) else 0
        # a line end can rest on the seabed only if its lower end is a
        # fixed point at the seabed (local bathymetry depth when a grid
        # is attached)
        self.l_can_ground = []
        for (a, b) in self.l_ends:
            ground = False
            for p in (a, b):
                if self.p_kind[p] == 0 and \
                        self.p_r[p][2] <= -self.depth_at(*self.p_r[p][:2]) + 1.0:
                    ground = True
            self.l_can_ground.append(ground)
        self.l_can_ground = np.asarray(self.l_can_ground)
        return self

    def depth_at(self, x, y):
        """Local seabed depth [m, positive down] at (x, y): bilinear on
        the bathymetry grid when present, else the uniform depth."""
        if self.bathymetry is None:
            return self.depth
        xg, yg, dg = self.bathymetry
        ix = int(np.clip(np.searchsorted(xg, x) - 1, 0, len(xg) - 2))
        iy = int(np.clip(np.searchsorted(yg, y) - 1, 0, len(yg) - 2))
        fx = np.clip((x - xg[ix]) / (xg[ix + 1] - xg[ix]), 0.0, 1.0)
        fy = np.clip((y - yg[iy]) / (yg[iy + 1] - yg[iy]), 0.0, 1.0)
        return float(
            dg[iy, ix] * (1 - fx) * (1 - fy)
            + dg[iy, ix + 1] * fx * (1 - fy)
            + dg[iy + 1, ix] * (1 - fx) * fy
            + dg[iy + 1, ix + 1] * fx * fy)

    # ---------------------------------------------------------- physics
    def _point_positions(self, r6_bodies, r_free):
        """Positions of all points given body poses and free positions."""
        pos = []
        i_free = 0
        for i in range(len(self.p_kind)):
            k = self.p_kind[i]
            if k == 0:
                pos.append(jnp.asarray(self.p_r[i]))
            elif k == 1:
                r6 = r6_bodies[self.p_body[i]]
                R = tf.rotation_matrix(r6[3], r6[4], r6[5])
                pos.append(r6[:3] + R @ jnp.asarray(self.p_r[i]))
            else:
                pos.append(r_free[i_free])
                i_free += 1
        return jnp.stack(pos)

    def _line_end_forces(self, pos):
        """Per-line forces on end A and end B attachments.

        Each line is canonicalised with the lower end as the catenary
        'anchor' side.  Returns (FA (nL,3), FB (nL,3), HF, VF, HA, VA)
        with A/B in the line's stored order."""
        FA, FB, tens = [], [], []
        for il, (a, b) in enumerate(self.l_ends):
            ra, rb = pos[a], pos[b]
            flip = ra[2] > rb[2]
            rlo = jnp.where(flip, rb, ra)
            rhi = jnp.where(flip, ra, rb)
            dvec = rhi - rlo
            XF = jnp.sqrt(dvec[0] ** 2 + dvec[1] ** 2)
            ZF = dvec[2]
            XF_safe = jnp.maximum(XF, 1e-8)
            uh = dvec[:2] / XF_safe
            HF, VF, HA, VA = solve_catenary(
                XF, ZF, self.l_L[il], self.l_w[il], self.l_EA[il],
                can_ground=bool(self.l_can_ground[il]),
            )
            F_hi = jnp.concatenate([-HF * uh, jnp.asarray([-VF])])
            F_lo = jnp.concatenate([HF * uh, jnp.asarray([VA])])
            Fa = jnp.where(flip, F_hi, F_lo)
            Fb = jnp.where(flip, F_lo, F_hi)
            FA.append(Fa)
            FB.append(Fb)
            tens.append(jnp.stack([jnp.hypot(HA, VA), jnp.hypot(HF, VF)]))
        return jnp.stack(FA), jnp.stack(FB), jnp.stack(tens)

    def _free_net_force(self, r6_bodies, r_free):
        pos = self._point_positions(r6_bodies, r_free)
        FA, FB, _ = self._line_end_forces(pos)
        F = jnp.zeros((len(self.free_idx), 3))
        for il, (a, b) in enumerate(self.l_ends):
            for p, Fp in ((a, FA[il]), (b, FB[il])):
                if self.p_kind[p] == 2:
                    slot = int(np.where(self.free_idx == p)[0][0])
                    F = F.at[slot].add(Fp)
        for s, p in enumerate(self.free_idx):
            Fz = -self.p_mass[p] * self.g + self.rho * self.g * self.p_vol[p]
            F = F.at[s, 2].add(Fz)
        return F

    def solve_free_points(self, r6_bodies, n_iter=25):
        """Inner equilibrium of free points (damped Newton, fixed count)."""
        if len(self.free_idx) == 0:
            return jnp.zeros((0, 3))
        r0 = jnp.asarray(self.p_r[self.free_idx])

        def body(r_free, _):
            F = self._free_net_force(r6_bodies, r_free).reshape(-1)
            J = jax.jacfwd(
                lambda rf: self._free_net_force(r6_bodies, rf.reshape(-1, 3)).reshape(-1)
            )(r_free.reshape(-1))
            dX = jnp.linalg.solve(
                J - 1e-6 * jnp.eye(J.shape[0]), -F
            )
            dX = jnp.clip(dX, -50.0, 50.0)
            return (r_free.reshape(-1) + dX).reshape(-1, 3), None

        r_free, _ = jax.lax.scan(body, r0, None, length=n_iter)
        return r_free

    def body_forces(self, r6_all):
        """Net 6-DOF mooring force on every body.

        r6_all : (n_bodies, 6) poses.  Returns (F (n_bodies, 6), info).
        """
        r6_all = jnp.asarray(r6_all).reshape(-1, 6)
        r_free = self.solve_free_points(r6_all)
        pos = self._point_positions(r6_all, r_free)
        FA, FB, tens = self._line_end_forces(pos)
        F = jnp.zeros((r6_all.shape[0], 6))
        for il, (a, b) in enumerate(self.l_ends):
            for p, Fp in ((a, FA[il]), (b, FB[il])):
                if self.p_kind[p] == 1:
                    bi = int(self.p_body[p])
                    lever = pos[p] - r6_all[bi, :3]
                    F = F.at[bi, :3].add(Fp)
                    F = F.at[bi, 3:].add(jnp.cross(lever, Fp))
        return F, dict(tensions=tens, r_free=r_free)

    def stiffness(self, r6_all):
        """Full coupled stiffness (6 n_bodies x 6 n_bodies): exact
        Jacobian -dF/dX through the free-point equilibrium."""

        def f(x):
            return self.body_forces(x.reshape(-1, 6))[0].reshape(-1)

        return -jax.jacfwd(f)(jnp.asarray(r6_all).reshape(-1))


def read_bathymetry(path):
    """Read a MoorPy-style bathymetry grid file
    (``--- MoorPy Bathymetry Input File ---`` header, nGridX/nGridY,
    x row, then ``y d d d ...`` rows).  Returns (x (nx,), y (ny,),
    depth (ny, nx)) with depth positive-down [m]."""
    rows = []
    xg = yg = None
    nx = ny = None
    with open(path) as f:
        for raw in f:
            line = raw.strip()
            if not line or line.startswith("---"):
                continue
            toks = line.split()
            key = toks[0].lower()
            if key == "ngridx":
                nx = int(toks[1])
            elif key == "ngridy":
                ny = int(toks[1])
            elif xg is None:
                xg = np.asarray(toks, dtype=float)
            else:
                yg_row = float(toks[0])
                rows.append((yg_row, np.asarray(toks[1:], dtype=float)))
    yg = np.asarray([r[0] for r in rows])
    dg = np.stack([r[1] for r in rows])
    if nx is not None and (len(xg) != nx or dg.shape != (ny, nx)):
        raise ValueError(
            f"bathymetry grid shape {dg.shape} does not match declared "
            f"nGridX={nx} nGridY={ny} in {path}")
    return xg, yg, dg


def parse_moordyn_system(path, depth, rho=1025.0, g=9.81, moorMod=0):
    """Parse a SIMPLE MoorDyn file — every line connecting one Vessel
    point to one Fixed point, no free/shared connections — into a
    :class:`MooringSystem` with full line-dynamics properties (Diam /
    MassDen / Cd / Ca / CdAx / CaAx columns), so file-based moorings
    support moorMod 1/2 exactly like schema-based ones
    (raft_fowt.py:359-370 MoorPy load + lines2ss).

    Raises ValueError when the file needs the network treatment
    (free points, shared lines) — callers fall back to
    :func:`parse_moordyn`.
    """
    types = {}
    points = {}
    lines = []
    section = None
    with open(path) as f:
        for raw in f:
            line = raw.strip()
            if not line:
                continue
            up = line.upper()
            if up.startswith("---"):
                # keep the section matchers IDENTICAL to parse_moordyn's
                # so the two treatments of the same file never diverge
                if "LINE TYPE" in up:
                    section = "types"
                elif "POINT" in up or "CONNECTION" in up:
                    section = "points"
                elif up.startswith("---------------------- LINES") \
                        or "- LINES -" in up or up.strip("- ").startswith("LINES"):
                    section = "lines"
                else:
                    section = None
                continue
            toks = line.split()
            if section == "types" and len(toks) >= 4:
                try:
                    d = float(toks[1])
                except ValueError:
                    continue
                # MoorDyn v2 line-type row has 10 columns
                #   Name Diam Mass/m EA BA/-zeta EI Cd Ca CdAx CaAx
                # MoorDyn v1 has 9, with the hydro coefficients in a
                # DIFFERENT order (added mass first, normal/tangential):
                #   Name Diam MassDen EA BA/-zeta Can Cat Cdn Cdt
                # Distinguish by token count; mapping v1 rows through the
                # v2 positions silently swaps Cd<->Ca (the moorMod 1/2
                # dynamic-tension/impedance paths read them).
                if len(toks) >= 10:      # v2: EI at 5, drag-first at 6+
                    hydro = dict(
                        Cd=float(toks[6]), Ca=float(toks[7]),
                        CdAx=float(toks[8]), CaAx=float(toks[9]))
                elif len(toks) == 9:     # v1: Can Cat Cdn Cdt at 5..8
                    hydro = dict(
                        Ca=float(toks[5]), CaAx=float(toks[6]),
                        Cd=float(toks[7]), CdAx=float(toks[8]))
                elif len(toks) <= 5:     # quasi-static-only row
                    hydro = dict(Cd=1.2, Ca=1.0, CdAx=0.05, CaAx=0.0)
                else:
                    raise ValueError(
                        f"ambiguous line-type row ({len(toks)} columns) in "
                        f"{path}: expected 9 (MoorDyn v1) or >=10 (v2) "
                        f"columns; got {line!r}")
                types[toks[0]] = dict(
                    d=d, m=float(toks[2]), EA=float(toks[3]),
                    BA=float(toks[4]) if len(toks) > 4 else 0.0,
                    **hydro)
            elif section == "points" and len(toks) >= 5:
                try:
                    pid = int(toks[0])
                except ValueError:
                    continue
                points[pid] = (toks[1].lower(),
                               np.array([float(toks[2]), float(toks[3]),
                                         float(toks[4])]))
            elif section == "lines" and len(toks) >= 5:
                try:
                    int(toks[0])
                except ValueError:
                    continue
                lines.append((toks[1], int(toks[2]), int(toks[3]),
                              float(toks[4])))

    r_anchor, r_fair, L = [], [], []
    w_l, EA, m_l, d_l, Cd_l, Ca_l, CdAx_l, CaAx_l, BA_l = \
        [], [], [], [], [], [], [], [], []
    for (tname, a, b, length) in lines:
        ka, ra = points[a]
        kb, rb = points[b]

        def kind(att):
            if att.startswith(("fix", "anch")):
                return "fixed"
            if att.startswith(("vessel", "coupled", "body", "turbine")):
                return "vessel"
            return "other"

        if kind(ka) == "fixed" and kind(kb) == "vessel":
            anc, fair = ra, rb
        elif kind(kb) == "fixed" and kind(ka) == "vessel":
            anc, fair = rb, ra
        else:
            raise ValueError(
                f"line {tname} connects {ka}-{kb}: needs the network "
                "treatment (free/shared points)")
        lt = types[tname]
        r_anchor.append(anc)
        r_fair.append(fair)
        L.append(length)
        w_l.append((lt["m"] - rho * np.pi / 4 * lt["d"] ** 2) * g)
        EA.append(lt["EA"])
        m_l.append(lt["m"])
        d_l.append(lt["d"])
        Cd_l.append(lt["Cd"])
        Ca_l.append(lt["Ca"])
        CdAx_l.append(lt["CdAx"])
        CaAx_l.append(lt["CaAx"])
        BA_l.append(lt["BA"])
    if not lines:
        raise ValueError("no lines found")
    return MooringSystem(
        r_anchor=np.array(r_anchor), r_fair0=np.array(r_fair),
        L=np.array(L), w=np.array(w_l), EA=np.array(EA), depth=float(depth),
        m_lin=np.array(m_l), d_vol=np.array(d_l), Cd=np.array(Cd_l),
        Ca=np.array(Ca_l), CdAx=np.array(CdAx_l), CaAx=np.array(CaAx_l),
        BA=np.array(BA_l), moorMod=int(moorMod),
    )


def parse_moordyn(path, depth, rho=1025.0, g=9.81, bathymetry=None):
    """Parse a MoorDyn v1/v2 input file into a MooringNetwork.

    Supports LINE TYPES / POINTS / LINES sections with Fixed, Free,
    Vessel, Coupled, Turbine<N> and Body<N> attachments (the subset the
    reference consumes through MoorPy's System.load,
    raft_model.py:98-100).  ``bathymetry``: optional path to a
    MoorPy-style grid file (raft_model.py:87-91)."""
    bath = read_bathymetry(bathymetry) if isinstance(bathymetry, str) \
        else bathymetry
    net = MooringNetwork(depth, g=g, rho=rho, bathymetry=bath)
    types = {}
    section = None
    point_ids = {}
    with open(path) as f:
        for raw in f:
            line = raw.strip()
            if not line:
                continue
            up = line.upper()
            if up.startswith("---"):
                if "LINE TYPE" in up:
                    section = "types"
                elif "POINT" in up or "CONNECTION" in up:
                    section = "points"
                elif up.startswith("---------------------- LINES") or "- LINES -" in up or up.strip("- ").startswith("LINES"):
                    section = "lines"
                else:
                    section = None
                continue
            toks = line.split()
            if section == "types" and len(toks) >= 4 and toks[0] not in ("Name", "TypeName", "(-)", "(name)"):
                try:
                    d = float(toks[1])
                except ValueError:
                    continue
                m = float(toks[2])
                EA = float(toks[3])
                types[toks[0]] = dict(w=(m - rho * np.pi / 4 * d**2) * g, EA=EA)
            elif section == "points" and len(toks) >= 5:
                try:
                    pid = int(toks[0])
                except ValueError:
                    continue
                att = toks[1].lower()
                r = np.array([float(toks[2]), float(toks[3]), float(toks[4])])
                mass = float(toks[5]) if len(toks) > 5 else 0.0
                vol = float(toks[6]) if len(toks) > 6 else 0.0
                if att.startswith("fix") or att.startswith("anch"):
                    point_ids[pid] = net.add_point(0, r)
                elif att.startswith("free") or att.startswith("connect"):
                    point_ids[pid] = net.add_point(2, r, mass=mass, vol=vol)
                else:
                    # Vessel / Coupled / Turbine<N> / Body<N>
                    body = 0
                    digits = "".join(ch for ch in att if ch.isdigit())
                    if digits:
                        body = int(digits) - 1
                    point_ids[pid] = net.add_point(1, r, body=body)
            elif section == "lines" and len(toks) >= 5:
                try:
                    int(toks[0])
                except ValueError:
                    continue
                lt = types[toks[1]]
                a = point_ids[int(toks[2])]
                b = point_ids[int(toks[3])]
                net.add_line(a, b, float(toks[4]), lt["w"], lt["EA"])
    return net.finalize()
