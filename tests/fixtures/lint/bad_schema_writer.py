"""Deliberately drifted lease writer/reader pair for the schema-contract
engine (`python -m raft_tpu.analysis schemas --fixture` must exit 1).

Two seeded drifts, one per violation class:

* the writer emits ``renewd_t`` (typo) while the reader dereferences
  ``renewed_t`` — ``read-never-written``;
* the writer emits ``ttl_s`` only for named workers while the reader
  hard-subscripts it — ``required-but-conditional``.
"""

import json
import os
import time


def write_lease(path, worker, token):
    rec = {
        "worker": worker,
        "claimed_t": time.time(),
        "renewd_t": time.time(),   # typo: readers want "renewed_t"
        "token": token,
    }
    if worker:
        rec["ttl_s"] = 30.0        # conditional: anonymous leases lack it
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(rec, f)
    os.replace(tmp, path)


def read_lease(path, now):
    with open(path) as f:
        rec = json.load(f)
    age = now - rec["renewed_t"]       # never written (writer typo'd it)
    expired = age > rec["ttl_s"]       # required, but only conditionally written
    return expired, rec.get("worker")
