"""Device-mesh sweep drivers and the fault-tolerant sweep runtime.

``raft_tpu.parallel.sweep``       GSPMD sweep drivers (vmap + shardings)
``raft_tpu.parallel.resilience``  atomic checkpoints, manifest-validated
                                  resume, retry/backoff, NaN quarantine
``raft_tpu.parallel.fabric``      elastic multi-worker sweep fabric:
                                  lease-based shard ledger, work
                                  stealing, coordinator/worker CLI
"""

from raft_tpu.parallel.resilience import (  # noqa: F401
    ManifestMismatchError, ShardCorruptError, load_quarantine)
from raft_tpu.parallel.sweep import (  # noqa: F401
    case_compute, full_compute, make_mesh, run_sweep_checkpointed,
    run_sweep_checkpointed_full, sweep_cases, sweep_cases_full)
