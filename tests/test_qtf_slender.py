"""Slender-body QTF parity vs reference golden values.

Mirrors test_calcQTF_slenderBody (/root/reference/tests/test_fowt.py:
192-216): fixed-body QTFs for the designs with potSecOrder == 1,
compared at the reference's tolerance (rtol 1e-5, atol 1e-3).
"""

import os
import pickle

import numpy as np
import pytest
from numpy.testing import assert_allclose

from tests.conftest import ref_data

import raft_tpu
from raft_tpu.physics.qtf_slender import fowt_qtf_slender

pytestmark = pytest.mark.slow

DESIGNS = ["VolturnUS-S.yaml", "VolturnUS-S-pointInertia.yaml"]


@pytest.mark.parametrize("design", DESIGNS, ids=[d.split(".")[0] for d in DESIGNS])
def test_qtf_slender_fixed_body(design):
    path = ref_data(design)
    golden = path.replace(".yaml", "_true_calcQTF_slenderBody.pkl")
    if not (os.path.exists(path) and os.path.exists(golden)):
        pytest.skip("reference data unavailable")
    model = raft_tpu.Model(path)
    assert model.fowtList[0].potSecOrder == 1
    fh = model.hydro[0]
    fh.hydro_excitation({"wave_heading": 30, "wave_period": 12, "wave_height": 6})
    qtf = fowt_qtf_slender(model, 0, Xi0=None)
    with open(golden, "rb") as f:
        true = pickle.load(f)
    assert_allclose(qtf, np.asarray(true["qtf"]), rtol=1e-5, atol=1e-3)


def test_second_order_in_dynamics():
    """potSecOrder==1 end-to-end: 2nd-order forces enter the response."""
    path = ref_data("VolturnUS-S.yaml")
    if not os.path.exists(path):
        pytest.skip("reference data unavailable")
    model = raft_tpu.Model(path)
    case = {"wind_speed": 0, "wind_heading": 0, "turbulence": 0,
            "turbine_status": "idle", "yaw_misalign": 0,
            "wave_spectrum": "JONSWAP", "wave_period": 12, "wave_height": 6,
            "wave_heading": 0, "current_speed": 0, "current_heading": 0}
    Xi, info = model.solve_dynamics(case)
    assert np.isfinite(np.asarray(Xi)).all()
    # mean drift force present and pushing downwave
    assert model._last_drift_mean[0, 0] > 0


def test_qtf_checkpoint_roundtrip(tmp_path):
    """outFolderQTF (raft_fowt.py:434-436, 2027-2078): solve_dynamics
    persists the slender-body QTF as WAMIT .12d and the motion RAOs as
    .4, and reading the .12d back reproduces the in-memory QTF — the
    reference's checkpoint pattern for expensive 2nd-order results."""
    import glob

    from raft_tpu.physics.secondorder import read_qtf_12d

    path = ref_data("VolturnUS-S.yaml")
    if not os.path.exists(path):
        pytest.skip("reference data unavailable")
    from raft_tpu.structure.schema import load_design

    design = load_design(path)
    design["platform"]["outFolderQTF"] = str(tmp_path)
    model = raft_tpu.Model(design)
    case = {"wind_speed": 0, "wind_heading": 0, "turbulence": 0,
            "turbine_status": "idle", "yaw_misalign": 0,
            "wave_spectrum": "JONSWAP", "wave_period": 12, "wave_height": 6,
            "wave_heading": 0, "current_speed": 0, "current_heading": 0}
    model.solve_dynamics(case)

    f12d = glob.glob(str(tmp_path / "qtf-slender_body-total_*.12d"))
    f4 = glob.glob(str(tmp_path / "raos-slender_body_*.4"))
    assert len(f12d) == 1 and len(f4) == 1
    fs = model.fowtList[0]
    back = read_qtf_12d(f12d[0], rho=fs.rho_water, g=fs.g)
    np.testing.assert_allclose(back["w_2nd"], model.w1_2nd, rtol=1e-4)
    # the solve's stored mean-drift force was computed from the same
    # QTF that was written: re-deriving it from the FILE must match,
    # closing the write->read->use loop
    from raft_tpu.physics.secondorder import hydro_force_2nd

    fh = model.hydro[0]
    fm_back, _ = hydro_force_2nd(back, fh.beta[0], fh.S[0], model.w)
    drift = np.asarray(model._last_drift_mean)[0, :6]
    scale = max(np.abs(drift).max(), 1.0)
    np.testing.assert_allclose(fm_back[:6], drift, atol=2e-4 * scale)


def test_pinkster_iv_vectorized_matches_loop_and_scales():
    """The blocked-broadcast Pinkster-IV term equals the reference-style
    scalar double loop bitwise-compatibly, and handles a large
    (>=800-bin) min_freq2nd-class grid in well under a second (the loop
    it replaced was O(nw2^2) Python — minutes at this size)."""
    import time

    from raft_tpu.physics.qtf_slender import pinkster_iv

    rng = np.random.default_rng(3)
    nw2 = 160
    Xi = rng.standard_normal((6, nw2)) + 1j * rng.standard_normal((6, nw2))
    F1 = rng.standard_normal((6, nw2)) + 1j * rng.standard_normal((6, nw2))

    ref = np.zeros((nw2, nw2, 6), dtype=complex)
    for i1 in range(nw2):
        for i2 in range(i1, nw2):
            ref[i1, i2, :3] = 0.25 * (np.cross(Xi[3:6, i1], np.conj(F1[:3, i2]))
                                      + np.cross(np.conj(Xi[3:6, i2]), F1[:3, i1]))
            ref[i1, i2, 3:] = 0.25 * (np.cross(Xi[3:6, i1], np.conj(F1[3:6, i2]))
                                      + np.cross(np.conj(Xi[3:6, i2]), F1[3:6, i1]))
    got = pinkster_iv(Xi, F1, block=64)
    assert_allclose(got, ref, rtol=0, atol=1e-14 * np.abs(ref).max())

    nw2 = 800
    Xi = rng.standard_normal((6, nw2)) + 1j * rng.standard_normal((6, nw2))
    F1 = rng.standard_normal((6, nw2)) + 1j * rng.standard_normal((6, nw2))
    t0 = time.perf_counter()
    out = pinkster_iv(Xi, F1)
    dt = time.perf_counter() - t0
    assert out.shape == (800, 800, 6)
    assert dt < 5.0  # generous CI bound; measured ~0.1 s


def test_qtf_dispatcher_sharded_in_dynamics():
    """solve_dynamics' potSecOrder==1 flow routes through the SHARDED
    pair-axis path when the mesh has >1 device (the 8-device CPU mesh
    of conftest), with the same response as the host path."""
    import jax

    path = ref_data("VolturnUS-S.yaml")
    if not os.path.exists(path):
        pytest.skip("reference data unavailable")
    if len(jax.devices()) < 2:
        pytest.skip("needs the virtual multi-device mesh")
    case = {"wind_speed": 0, "wind_heading": 0, "turbulence": 0,
            "turbine_status": "idle", "yaw_misalign": 0,
            "wave_spectrum": "JONSWAP", "wave_period": 12, "wave_height": 6,
            "wave_heading": 0, "current_speed": 0, "current_heading": 0}
    model = raft_tpu.Model(path)
    Xi_sharded, _ = model.solve_dynamics(case)

    from raft_tpu.physics.qtf_slender import fowt_qtf_slender
    model2 = raft_tpu.Model(path)
    model2.qtf_slender = lambda ih=0, Xi0=None, ifowt=0: fowt_qtf_slender(
        model2, ih, Xi0=Xi0, ifowt=ifowt)
    Xi_host, _ = model2.solve_dynamics(case)
    assert_allclose(np.asarray(Xi_sharded), np.asarray(Xi_host),
                    rtol=0, atol=1e-9 * np.abs(np.asarray(Xi_host)).max())
