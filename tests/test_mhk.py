"""MHK (underwater-rotor) design smoke tests: the RM1 floating tidal
turbine builds, reaches a current-loaded equilibrium, and solves
dynamics with the current-driven rotor providing mean thrust."""

import os

import numpy as np
import pytest

from tests.conftest import REFERENCE_DIR

import raft_tpu

pytestmark = pytest.mark.slow

PATH = os.path.join(REFERENCE_DIR, "designs", "RM1_Floating.yaml")


@pytest.fixture(scope="module")
def model():
    if not os.path.exists(PATH):
        pytest.skip("reference design unavailable")
    return raft_tpu.Model(PATH)


def test_mhk_builds(model):
    fs = model.fowtList[0]
    assert fs.nrotors == 1
    assert fs.rotors[0].Zhub < 0  # submerged rotor


def test_mhk_current_equilibrium(model):
    case = dict(zip(model.design["cases"]["keys"], model.design["cases"]["data"][0]))
    assert case["current_speed"] > 0
    X = np.asarray(model.solve_statics(case))
    # current thrust pushes the platform downstream
    assert 0.5 < X[0] < 30.0
    assert np.all(np.isfinite(X))
    # rotor thrust from the water flow is substantial
    F = np.asarray(model.aero_mean_force(case, 0))
    assert F[0] > 1e4


def test_mhk_dynamics(model):
    case = dict(zip(model.design["cases"]["keys"], model.design["cases"]["data"][0]))
    Xi, info = model.solve_dynamics(case)
    assert np.isfinite(np.asarray(Xi)).all()
