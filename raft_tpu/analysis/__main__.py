"""CLI for the trace-hygiene + concurrency-invariant suite.

    python -m raft_tpu.analysis lint [--json] [paths...]
    python -m raft_tpu.analysis concurrency [--json] [paths...]
    python -m raft_tpu.analysis schemas [--json] [--write | --fixture]
    python -m raft_tpu.analysis protocol {check,extract,baseline}
        [--json] [--write] [--fixture PATH] [--static-only]
    python -m raft_tpu.analysis contracts [--design YAML] [--modes ...]
    python -m raft_tpu.analysis baseline --write [--design YAML]
    python -m raft_tpu.analysis flags

Exit codes: 0 clean, 1 findings/violations, 2 usage error.  ``lint``,
``concurrency``, ``schemas``, ``protocol`` and ``flags`` are jax-free;
``contracts``/``baseline`` trace the entry points and pin the CPU
backend first (accelerator plugins in this image can hang backend init
— the lint gate must never).  ``--json`` swaps the human text for one
machine-readable document (see :mod:`raft_tpu.analysis.report`).
"""

from __future__ import annotations

import argparse
import sys


def _cmd_lint(args):
    from raft_tpu.analysis import lint, report

    findings = lint.lint_paths(args.paths or None)
    if not args.paths:
        # the dead-entry audit only makes sense over the full scan set
        # (a partial path list would flag every registration as dead)
        findings.extend(lint.registered_unused())
    rc = report.emit(
        "lint", findings, args.json,
        clean_note="lint clean "
        f"({len(args.paths) or len(lint.default_paths())} files).")
    if rc and not args.json:
        print(f"{len(findings)} finding(s). Suppress intentional ones with "
              "`# raft-lint: disable=<rule>`.", file=sys.stderr)
    return rc


def _cmd_concurrency(args):
    from raft_tpu.analysis import concurrency, report

    findings = concurrency.analyze_paths(args.paths or None)
    scope = (f"{len(args.paths)} file(s)" if args.paths
             else "shared-state + serve modules")
    rc = report.emit("concurrency", findings, args.json,
                     clean_note=f"concurrency invariants clean ({scope}).")
    if rc and not args.json:
        print(f"{len(findings)} finding(s). Suppress audited exceptions "
              "with `# raft-lint: disable=<rule>`.", file=sys.stderr)
    return rc


def _cmd_schemas(args):
    from raft_tpu.analysis import report, schemas

    if args.fixture:
        violations, _ = schemas.run_fixture_checks()
        if not violations:
            print("schema drift fixture produced NO violations — the "
                  "engine is broken", file=sys.stderr)
            return 2
        rc = report.emit("schemas", violations, args.json,
                         extra={"fixture": True})
        if not args.json:
            print(f"{len(violations)} violation(s) (seeded fixture drill).",
                  file=sys.stderr)
        return rc
    if args.write:
        contracts = schemas.extract_all()
        drift = []
        for name, contract in contracts.items():
            drift.extend(schemas.drift_violations(name, contract))
        if drift:
            # never bake live writer/reader drift into the baseline
            for v in drift:
                print(v, file=sys.stderr)
            print("refusing to write a baseline over live drift",
                  file=sys.stderr)
            return 1
        path = schemas.write_baseline(contracts)
        print(f"schema baseline written: {path} "
              f"({len(contracts)} families)")
        return 0
    violations, contracts = schemas.run_checks()
    n_keys = sum(len(c["written"]) + len(c["read"])
                 for c in contracts.values())
    rc = report.emit(
        "schemas", violations, args.json,
        clean_note=f"schema contracts clean ({len(contracts)} families, "
                   f"{n_keys} keys).",
        extra={"families": len(contracts), "keys": n_keys})
    if rc and not args.json:
        print(f"{len(violations)} schema-contract violation(s). "
              "Intentional evolution: `python -m raft_tpu.analysis "
              "schemas --write` and commit the diff.", file=sys.stderr)
    return rc


def _cmd_protocol(args):
    from raft_tpu.analysis import protocol, report

    if args.mode == "extract":
        sites, unmodeled = protocol.extract_all()
        if args.json:
            findings = [
                {"file": s.path, "line": s.line, "col": s.col,
                 "rule": ("protocol-unmodeled" if not s.modeled
                          else "protocol-site"),
                 "message": s.key, "action": s.action}
                for s in sites]
            report.emit("protocol", findings, True,
                        extra={"mode": "extract",
                               "unmodeled": len(unmodeled)})
        else:
            for s in sites:
                mark = "!" if not s.modeled else " "
                print(f"{mark} {s.key:58s} {s.action or 'UNMODELED':10s} "
                      f"{s.path}:{s.line}")
            print(f"{len(sites)} mutation site(s), "
                  f"{len(unmodeled)} unmodeled.", file=sys.stderr)
        return 1 if unmodeled else 0

    if args.mode == "baseline":
        if not args.write:
            print("baseline is checked in; pass --write to re-pin "
                  "(after an intentional protocol change)",
                  file=sys.stderr)
            return 2
        try:
            data = protocol.write_baseline()
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 1
        print(f"protocol baseline written: {protocol.BASELINE_PATH} "
              f"({len(data['sites'])} sites, "
              f"{len(data['invariants'])} invariants)")
        return 0

    # mode == "check"
    if args.fixture:
        findings, stats = protocol.run_fixture(args.fixture)
        if not findings:
            print("protocol fixture produced NO findings — the engine "
                  "is broken", file=sys.stderr)
            return 2
        rc = report.emit("protocol", findings, args.json,
                         extra={"fixture": args.fixture, "stats": stats})
        if not args.json:
            print(f"{len(findings)} finding(s) (seeded fixture drill).",
                  file=sys.stderr)
        return rc
    findings, stats = protocol.check(explore=not args.static_only)
    rc = report.emit(
        "protocol", findings, args.json,
        clean_note="protocol model clean"
        + ("" if args.static_only else
           " (%d runs, %d states explored)" % (
               sum(s.get("runs", 0) for s in stats.values()),
               sum(s.get("states", 0) for s in stats.values()))) + ".",
        extra={"stats": stats})
    if rc and not args.json:
        print(f"{len(findings)} protocol finding(s). Intentional "
              "surface change: extend the mcheck model, then "
              "`python -m raft_tpu.analysis protocol baseline --write`.",
              file=sys.stderr)
    return rc


def _pin_cpu():
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)


def _cmd_contracts(args, update_baseline=False):
    _pin_cpu()
    from raft_tpu.analysis import jaxpr_contracts as jc

    modes = [m.strip() for m in args.modes.split(",") if m.strip()]
    report = jc.run_checks(design=args.design, dtype_modes=modes,
                           update_baseline=update_baseline)
    for line in report["log"]:
        print(line)
    if report["violations"]:
        print(f"{len(report['violations'])} contract violation(s):",
              file=sys.stderr)
        for v in report["violations"]:
            print("  " + v, file=sys.stderr)
        return 1
    if update_baseline:
        print(f"baseline written: {jc.baseline_path()}")
    print("jaxpr contracts clean.")
    return 0


def _cmd_baseline(args):
    if not args.write:
        print("baseline is checked in; pass --write to regenerate "
              "(after an intentional hot-path change)", file=sys.stderr)
        return 2
    return _cmd_contracts(args, update_baseline=True)


def _cmd_flags(_args):
    from raft_tpu.utils import config

    rows = list(config.describe())
    w = max(len(r[0]) for r in rows)
    for env, kind, default, help_ in rows:
        print(f"{env:<{w}}  {kind:<6}  default={default!r}  {help_}")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(prog="python -m raft_tpu.analysis")
    sub = ap.add_subparsers(dest="cmd", required=True)

    def _json_flag(parser):
        parser.add_argument(
            "--json", action="store_true",
            help="emit one machine-readable JSON document instead of "
                 "the human text format")
        return parser

    p = _json_flag(sub.add_parser(
        "lint", help="run the trace-hygiene AST linter"))
    p.add_argument("paths", nargs="*", help="files to lint "
                   "(default: raft_tpu/ + bench.py + sweep_10k.py)")

    p = _json_flag(sub.add_parser(
        "concurrency",
        help="concurrency invariants: atomic-write, async-blocking, "
             "lock-discipline, thread-hygiene"))
    p.add_argument("paths", nargs="*",
                   help="files to analyze with every rule forced on "
                        "(default: the audited shared-state + serve "
                        "modules with per-module rule gating)")

    p = _json_flag(sub.add_parser(
        "schemas",
        help="cross-process writer/reader schema contracts vs the "
             "checked-in analysis/schema_baseline.json"))
    p.add_argument("--write", action="store_true",
                   help="regenerate the baseline (intentional schema "
                        "evolution; refuses over live drift)")
    p.add_argument("--fixture", action="store_true",
                   help="run the seeded drifted-lease fixture drill "
                        "(must exit 1 — the CI negative)")

    p = _json_flag(sub.add_parser(
        "protocol",
        help="protocol model checker: static mutation-site extraction "
             "vs analysis/protocol_baseline.json + exhaustive "
             "interleaving/crash exploration of the fs state machines"))
    p.add_argument("mode", choices=("check", "extract", "baseline"),
                   help="check: diff sites vs baseline and explore; "
                        "extract: list every mutation site; "
                        "baseline: re-pin the site model (--write)")
    p.add_argument("--write", action="store_true",
                   help="with `baseline`: re-pin protocol_baseline.json "
                        "(refuses over unmodeled sites)")
    p.add_argument("--fixture", metavar="PATH",
                   help="with `check`: drive the engines against a "
                        "seeded-bug fixture module (must exit 1 — the "
                        "CI negative)")
    p.add_argument("--static-only", action="store_true",
                   help="with `check`: skip the interleaving explorer "
                        "(extraction diff only)")

    for name in ("contracts", "baseline"):
        p = sub.add_parser(
            name, help=("check jaxpr contracts + primitive budgets"
                        if name == "contracts"
                        else "regenerate the primitive-count baseline"))
        p.add_argument("--design", default=None,
                       help="design YAML (default: bundled spar_demo)")
        p.add_argument("--modes", default="float64,float32",
                       help="comma list of RAFT_TPU_DTYPE modes to trace")
        if name == "baseline":
            p.add_argument("--write", action="store_true")

    sub.add_parser("flags", help="list the RAFT_TPU_* flag registry")

    args = ap.parse_args(argv)
    cmd = {"lint": _cmd_lint, "concurrency": _cmd_concurrency,
           "schemas": _cmd_schemas, "protocol": _cmd_protocol,
           "contracts": _cmd_contracts, "baseline": _cmd_baseline,
           "flags": _cmd_flags}[args.cmd]
    return cmd(args)


if __name__ == "__main__":
    sys.exit(main())
