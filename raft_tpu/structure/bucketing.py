"""Shape-bucketed heterogeneous-design batching (SURVEY §7.3 hard part 2).

The geometry design axis (:mod:`raft_tpu.structure.members_traced`)
traces d/t/ballast *scales* over a fixed member layout, so a DoE that
mixes topologies (spar + semi + MHK variants) compiles one program per
member layout — exactly the per-design recompilation the "one jit/vmap
compilation serves all 10k designs" claim (SURVEY §7.1) exists to kill.

This module makes the *design itself* a traced input.  Every per-design
quantity the rigid-body case-evaluation chain consumes is extracted
into a flat pytree of fixed-shape arrays, padded up to per-family
**shape buckets** (per-axis pad ladders over the strip / node /
mooring-line axes — measured-waste-tuned by default, configurable via
``RAFT_TPU_BUCKET_STEPS``; see :func:`pad_ladder`) with explicit
validity masks:

* padded STRIPS carry zero areas, zero drag/added-mass coefficients and
  a False entry in ``strip_mask``/``active``, so they contribute
  exactly zero to added mass, hydrostatic reductions, excitation and
  drag tensors (the submergence/strip-activity where-mask machinery in
  :mod:`raft_tpu.physics.morison` is the template — ``sub`` is simply
  extended by the validity mask);
* padded NODES receive no strip contributions (every padded strip
  points at node 0 with a zero force), so their ``T`` rows multiply
  exact zeros in every reduction;
* padded MOORING LINES replicate line 0 (keeping the catenary Newton
  solve on benign inputs — a degenerate L=w=EA=0 line would divide by
  zero) and are masked out of the force sum, so force AND the autodiff
  stiffness of padded lines are exactly zero.

A **bucket signature** is the full static shape of the compiled
program: padded axis sizes, the frequency grid (embedded verbatim — two
designs with different grids are different programs), and the
fixed-point iteration budget.  :func:`make_bucket_evaluator` builds the
evaluator for a signature with NO model closure at all — its program
identity is the signature itself, which makes the compiled/banked
program shareable across every design that packs into the bucket.
The auto-binning dispatcher lives in
:func:`raft_tpu.parallel.sweep.sweep_heterogeneous`.

Scope: rigid single-body (6-DOF) FOWTs through the sea-state case chain
(statics equilibrium, strip excitation, drag-linearised impedance solve
— the :func:`raft_tpu.api.make_case_evaluator` physics).  Designs with
potential-flow coefficients, external QTFs, network moorings or
flexible topologies raise :class:`UnbucketableDesignError` and fall
back to their per-design traced evaluators.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.ops import transforms as tf
from raft_tpu.ops import waves as wv
from raft_tpu.physics import morison
from raft_tpu.physics.mooring import MooringSystem, catenary_line_forces

BUCKET_VERSION = 1

#: minimum bucket sizes: small designs share one family instead of
#: minting near-empty micro-buckets (the floors of the pow2 policy and
#: of every ladder's doubling continuation — see :func:`pad_ladder`)
STRIP_FLOOR = 16
NODE_FLOOR = 2
LINE_FLOOR = 2


class UnbucketableDesignError(ValueError):
    """The design needs physics the bucketed chain does not trace."""


def _ceil_pow2(n, floor=1):
    n = max(int(n), int(floor))
    return 1 << (n - 1).bit_length()


# ------------------------------------------------------------ pad ladders

#: per-axis floor of the 'pow2' ladder policy (and of any explicit
#: ladder's doubling continuation)
AXIS_FLOORS = {"strips": STRIP_FLOOR, "nodes": NODE_FLOOR,
               "lines": LINE_FLOOR}


def pad_ladder(spec=None):
    """Parse the ``RAFT_TPU_BUCKET_STEPS`` pad-ladder spec.

    ``spec`` is ``';'``-separated ``axis=rungs`` entries (axes
    ``strips``/``nodes``/``lines``); ``rungs`` is either the literal
    ``pow2`` (classic power-of-two ceiling at the axis floor) or an
    ascending comma list of explicit rung sizes — beyond the last rung
    the ladder continues by doubling, so no design is ever too big.
    Returns ``{axis: tuple(rungs) | None}`` (``None`` = pow2).

    The default ladder is measured-waste-tuned (ROADMAP item 5a): the
    PR-11 row-weighted ``waste_by_axis`` histograms put essentially the
    whole pad budget on the STRIPS axis (each strip row drags a
    ``(S, 3, 3, nw)`` complex ``Imat`` through the whole case chain),
    with per-row pad fractions clustered just under the pow2 ceilings
    — so strips get midpoint rungs between the pow2 sizes (worst-case
    waste 1/3 instead of 1/2; bundled-trio row-weighted waste 0.35 →
    0.15), while the cheap nodes/lines axes keep coarse pow2 rungs
    (fewer distinct signatures = more program sharing).
    """
    from raft_tpu.utils import config

    spec = config.get("BUCKET_STEPS") if spec is None else spec
    ladders = dict.fromkeys(AXIS_FLOORS)
    if not spec or spec.strip().lower() == "pow2":
        return ladders
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        axis, sep, rungs = part.partition("=")
        axis = axis.strip().lower()
        if not sep or axis not in AXIS_FLOORS:
            raise ValueError(
                f"RAFT_TPU_BUCKET_STEPS entry {part!r}: expected "
                f"axis=rungs with axis one of {sorted(AXIS_FLOORS)}")
        rungs = rungs.strip().lower()
        if rungs == "pow2":
            ladders[axis] = None
            continue
        try:
            sizes = tuple(int(r) for r in rungs.split(",") if r.strip())
        except ValueError:
            raise ValueError(
                f"RAFT_TPU_BUCKET_STEPS {axis} rungs {rungs!r}: expected "
                "'pow2' or a comma list of integers")
        if not sizes or any(s <= 0 for s in sizes) or \
                any(b <= a for a, b in zip(sizes, sizes[1:])):
            raise ValueError(
                f"RAFT_TPU_BUCKET_STEPS {axis} rungs {rungs!r}: rungs "
                "must be positive and strictly ascending")
        ladders[axis] = sizes
    return ladders


def _axis_pad(n, axis, ladders=None):
    """Padded size of ``n`` real rows on ``axis`` under the active
    ladder: the smallest rung holding ``n`` (doubling past the last
    explicit rung; pow2-at-floor when the axis has no explicit rungs).
    ``n == 0`` stays 0 (axis absent, e.g. a moorings-free design)."""
    n = int(n)
    if n <= 0:
        return 0
    ladders = pad_ladder() if ladders is None else ladders
    rungs = ladders.get(axis)
    if rungs is None:
        return _ceil_pow2(n, AXIS_FLOORS[axis])
    for r in rungs:
        if r >= n:
            return r
    r = rungs[-1]
    while r < n:
        r *= 2
    return r


def tuned_rungs(observed_sizes, max_waste=0.2, floor=None):
    """Seed a ladder from measured axis occupancy (the README
    ladder-tuning recipe): given the REAL per-row axis sizes a workload
    dispatched (e.g. read off the ``pad_waste_<axis>`` histogram /
    ``axis_counts`` rows of a recorded run), return the minimal
    ascending rung list under which every observed size pads with at
    most ``max_waste`` — walk the sizes descending and keep a rung
    whenever the next-larger kept rung would waste more than the
    budget.  Feed the result into ``RAFT_TPU_BUCKET_STEPS``."""
    sizes = sorted({int(s) for s in observed_sizes if int(s) > 0})
    if not sizes:
        return ()
    floor = int(floor if floor is not None else 1)
    rungs = []
    last = None
    for s in reversed(sizes):
        s = max(s, floor)
        if last is None or 1.0 - s / last > max_waste:
            rungs.append(s)
            last = s
    return tuple(sorted(set(rungs)))


# ------------------------------------------------------------- signature

def bucket_signature(model):
    """Hashable static-shape signature of the compiled bucket program.

    Two models with equal signatures evaluate through ONE compiled
    (and AOT-bankable) program.  The signature carries everything the
    trace specializes on: padded axis sizes, the frequency grid
    (verbatim — it is baked into the program as constants), and the
    drag-fixed-point iteration budget.
    """
    if model.nFOWT != 1:
        raise UnbucketableDesignError("bucketing covers single-FOWT models")
    fs = model.fowtList[0]
    if not fs.is_single_body:
        raise UnbucketableDesignError(
            "bucketing covers rigid single-body (6-DOF) FOWTs; flexible "
            "topologies keep their per-design traced evaluators")
    # gate on the design FLAGS, not the lazy model.bem_list/model.qtf
    # properties — touching those would run the native panel solver /
    # QTF file load just to reject the design
    if (fs.potFirstOrder == 1 and fs.hydroPath) or any(
            m.potMod for m in fs.members):
        raise UnbucketableDesignError(
            "potential-flow coefficients are design-shaped host data; "
            "potMod designs keep their per-design evaluators")
    if fs.potSecOrder == 2 and fs.hydroPath:
        raise UnbucketableDesignError("external QTFs are not bucketed")
    if fs.x_ref or fs.y_ref:
        raise UnbucketableDesignError("array-positioned units not bucketed")
    ms = model.ms
    if ms is not None and not isinstance(ms, MooringSystem):
        raise UnbucketableDesignError(
            "network/file moorings with free points are not bucketed")
    if ms is not None and int(getattr(ms, "moorMod", 0) or 0) != 0:
        raise UnbucketableDesignError("moorMod 1/2 line dynamics not bucketed")
    ss = model.hydro[0].strips
    # padded sizes come from the ACTIVE pad ladder (RAFT_TPU_BUCKET_STEPS,
    # default measured-waste-tuned — see pad_ladder): the signature IS
    # the padded shape, so every downstream consumer (pack_design,
    # axis_counts/waste_by_axis, the bank key, warmup) sees the tuned
    # sizes, never an assumed pow2
    ladders = pad_ladder()
    L = 0 if ms is None else _axis_pad(ms.n_lines, "lines", ladders)
    return (
        "rigid6", BUCKET_VERSION,
        _axis_pad(ss.S, "strips", ladders),
        _axis_pad(fs.n_nodes, "nodes", ladders),
        L,
        tuple(float(x) for x in np.asarray(model.w)),
        int(model.nIter), float(model.XiStart), int(model.nIterExtra),
    )


def signature_meta(sig):
    """Named view of a signature tuple."""
    kind, ver, S, N, L, w, nIter, XiStart, nIterExtra = sig
    if kind != "rigid6" or ver != BUCKET_VERSION:
        raise ValueError(f"unknown bucket signature {kind!r} v{ver}")
    return dict(S=S, N=N, L=L, w=np.asarray(w, dtype=float),
                nw=len(w), nIter=nIter, XiStart=XiStart,
                nIterExtra=nIterExtra)


def signature_fingerprint(sig):
    """Short stable hash of a signature (for keys / filenames / logs)."""
    h = hashlib.sha256(repr(sig).encode())
    return h.hexdigest()[:12]


# --------------------------------------------------------------- packing

def _pad_rows(a, n, fill=0.0):
    """Pad array ``a`` along axis 0 up to ``n`` rows with ``fill``."""
    a = np.asarray(a)
    pad = n - a.shape[0]
    if pad < 0:
        raise ValueError(f"array of {a.shape[0]} rows exceeds bucket {n}")
    if pad == 0:
        return a.copy()
    tail = np.full((pad,) + a.shape[1:], fill, dtype=a.dtype)
    return np.concatenate([a, tail], axis=0)


def _pad_axis_rows(a, n, axis0_fill):
    """Pad with a given per-row fill vector (axis vectors need a unit
    entry, not zeros, so downstream rotations stay well-defined)."""
    a = np.asarray(a)
    pad = n - a.shape[0]
    if pad < 0:
        raise ValueError(f"array of {a.shape[0]} rows exceeds bucket {n}")
    if pad == 0:
        return a.copy()
    tail = np.tile(np.asarray(axis0_fill, dtype=a.dtype), (pad, 1))
    return np.concatenate([a, tail], axis=0)


def pack_design(model, sig=None):
    """Extract one model into the bucket's padded design pytree.

    Every leaf is a plain numpy array of the bucket's static shape;
    stacking the pytrees of all designs in a bucket along a new leading
    axis yields the batch the bucket evaluator vmaps over.  All values
    are the HOST-built constants of the per-design build (statics
    matrices, zero-pose hydro-constant tensors), so a bucketed
    evaluation reproduces the solo per-design evaluation exactly —
    padding only ever appends exact zeros to reductions.
    """
    sig = sig or bucket_signature(model)
    meta = signature_meta(sig)
    S, N, L = meta["S"], meta["N"], meta["L"]
    fs = model.fowtList[0]
    fh = model.hydro[0]
    ss = fh.strips
    stat = model.statics()
    ms = model.ms
    if not np.array_equal(np.asarray(model.w, dtype=float), meta["w"]):
        raise ValueError("model frequency grid does not match the signature")
    if (ms is None) != (L == 0):
        raise ValueError("mooring presence does not match the signature")

    d = dict(
        # ---- strip axis (padded strips: zero areas/coefficients,
        # active/strip_mask False — exact zero contributions)
        node=_pad_rows(np.asarray(ss.node, dtype=np.int32), S, 0),
        ls=_pad_rows(np.asarray(ss.ls, dtype=float), S),
        dls=_pad_rows(np.asarray(ss.dls, dtype=float), S),
        ds=_pad_rows(np.asarray(ss.ds, dtype=float), S),
        drs=_pad_rows(np.asarray(ss.drs, dtype=float), S),
        circ=_pad_rows(np.asarray(ss.circ, dtype=bool), S, False),
        active=_pad_rows(np.asarray(ss.active, dtype=bool), S, False),
        q0=_pad_axis_rows(np.asarray(ss.q0, dtype=float), S, (0.0, 0.0, 1.0)),
        p10=_pad_axis_rows(np.asarray(ss.p10, dtype=float), S, (1.0, 0.0, 0.0)),
        p20=_pad_axis_rows(np.asarray(ss.p20, dtype=float), S, (0.0, 1.0, 0.0)),
        Cd_q=_pad_rows(np.asarray(ss.Cd_q, dtype=float), S),
        Cd_p1=_pad_rows(np.asarray(ss.Cd_p1, dtype=float), S),
        Cd_p2=_pad_rows(np.asarray(ss.Cd_p2, dtype=float), S),
        Cd_End=_pad_rows(np.asarray(ss.Cd_End, dtype=float), S),
        strip_mask=(np.arange(S) < ss.S),
        # ---- zero-pose hydro constants (host-built, reference-flow
        # semantics: calcHydroConstants at the reference position)
        Imat=_pad_rows(np.asarray(fh.hc0["Imat"], dtype=np.complex128), S),
        a_i=_pad_rows(np.asarray(fh.hc0["a_i"], dtype=float), S),
        A_hydro=np.asarray(fh.hc0["A_hydro"], dtype=float),
        # ---- node axis
        node_r0=_pad_rows(np.asarray(fs.node_r0, dtype=float), N),
        root=np.int32(fs.root_id),
        # ---- statics matrices (6-DOF, host-built)
        K_h=np.asarray(stat["C_struc"] + stat["C_hydro"], dtype=float),
        F_und=np.asarray(stat["W_struc"] + stat["W_hydro"]
                         + stat["f0_additional"], dtype=float),
        M_struc=np.asarray(stat["M_struc"], dtype=float),
        # ---- site scalars + dispersion (depth-dependent)
        depth=np.float64(fs.depth),
        rho_water=np.float64(fs.rho_water),
        g=np.float64(fs.g),
        k=np.asarray(model.k, dtype=float),
    )
    if L:
        if ms.n_lines > L:
            raise ValueError(
                f"mooring system of {ms.n_lines} lines exceeds bucket {L}")

        # padded lines replicate line 0 (benign catenary inputs) and are
        # masked out of the force sum — zero force AND zero stiffness
        def padL(a):
            a = np.asarray(a, dtype=float)
            reps = np.repeat(a[:1], L - a.shape[0], axis=0)
            return np.concatenate([a, reps], axis=0)

        d.update(
            moor_anchor=padL(ms.r_anchor), moor_fair0=padL(ms.r_fair0),
            moor_L=padL(ms.L), moor_w=padL(ms.w), moor_EA=padL(ms.EA),
            line_mask=(np.arange(L) < ms.n_lines),
        )
    return d


def stack_packed(packed_list, rows=None):
    """Stack packed-design pytrees into the batch a bucket evaluator
    vmaps over — the request→packed-row adapter of the serving batcher
    (:mod:`raft_tpu.serve`) and of any caller that already holds
    :func:`pack_design` outputs.

    ``packed_list`` : per-row packed pytrees of ONE bucket signature
        (row i of the batch evaluates design i).
    ``rows`` : pad the batch up to this many rows by repeating the last
        entry (masked repeat rows, dropped again by the caller on
        fan-out) — the serving tick pads to its fixed program sizes so
        every occupancy shares one compiled program.

    Returns the stacked dict of numpy leaves (leading axis = rows).
    """
    if not packed_list:
        raise ValueError("stack_packed: empty packed-design batch")
    n = len(packed_list)
    rows = n if rows is None else int(rows)
    if rows < n:
        raise ValueError(
            f"stack_packed: {n} rows exceed the requested batch {rows}")
    take = list(range(n)) + [n - 1] * (rows - n)
    return {k: np.stack([packed_list[i][k] for i in take])
            for k in packed_list[0]}


def padding_waste_frac(packed_list):
    """Fraction of padded strip rows that carry no real strip, over a
    batch of packed designs: ``1 - sum(valid) / sum(padded)`` — the
    compute the bucket spends keeping its program shape static."""
    valid = sum(int(np.asarray(p["strip_mask"]).sum()) for p in packed_list)
    total = sum(int(np.asarray(p["strip_mask"]).size) for p in packed_list)
    return 1.0 - valid / total if total else 0.0


def axis_counts(model, sig=None):
    """``{axis: (real, padded)}`` per padded design axis of one model
    in its bucket — the waste-attribution unit ROADMAP item 5a tunes
    bucket ladders from.  The strips axis reproduces the aggregate
    :func:`padding_waste_frac` when summed over a batch (both are
    ``1 - sum(real)/sum(padded)``); nodes and mooring lines get the
    same treatment so the waste table names WHICH axis the pad budget
    goes to, not just that 35% of strip rows are masked."""
    sig = sig or bucket_signature(model)
    meta = signature_meta(sig)
    fs = model.fowtList[0]
    ms = model.ms
    return {
        "strips": (int(model.hydro[0].strips.S), int(meta["S"])),
        "nodes": (int(fs.n_nodes), int(meta["N"])),
        "lines": (0 if ms is None else int(ms.n_lines), int(meta["L"])),
    }


def waste_by_axis(axis_counts_list):
    """Row-weighted per-axis padding waste over a batch (one
    ``axis_counts`` dict per dispatched row): ``{axis: {valid, padded,
    waste_frac}}`` with ``waste_frac = 1 - sum(valid)/sum(padded)`` —
    the exact row-weighted aggregate, not a mean of per-row fractions
    (990 floor-bucket rows + 10 big-semi rows must not report the
    unweighted 2-design mean)."""
    agg: dict = {}
    for axes in axis_counts_list:
        for name, (real, padded) in axes.items():
            v, t = agg.get(name, (0, 0))
            agg[name] = (v + int(real), t + int(padded))
    return {name: {"valid": v, "padded": t,
                   "waste_frac": round(1.0 - v / t, 6) if t else 0.0}
            for name, (v, t) in agg.items()}


def observe_axis_waste(axis_counts_list, rows_valid=None, rows_padded=None):
    """Feed the per-axis waste metrics for one dispatched batch: exact
    ``pad_valid_<axis>``/``pad_total_<axis>`` counter pairs (their
    ratio IS the row-weighted aggregate, summable across dispatches
    and processes) plus a ``pad_waste_<axis>`` histogram of each row's
    own pad fraction (the distribution view: a bimodal histogram says
    "split the bucket", a uniform one says "shrink the floor").  The
    optional ``rows_valid``/``rows_padded`` pair records the BATCH-row
    axis (masked repeat rows added for dp-divisibility / ladder
    padding) the same way."""
    from raft_tpu.obs import metrics

    for axes in axis_counts_list:
        for name, (real, padded) in axes.items():
            if not padded:
                continue
            metrics.counter(f"pad_valid_{name}").inc(int(real))
            metrics.counter(f"pad_total_{name}").inc(int(padded))
            metrics.histogram(f"pad_waste_{name}").observe(
                1.0 - real / padded)
    if rows_padded:
        metrics.counter("pad_valid_rows").inc(int(rows_valid))
        metrics.counter("pad_total_rows").inc(int(rows_padded))
        metrics.histogram("pad_waste_rows").observe(
            1.0 - rows_valid / rows_padded)


# ------------------------------------------------------------- evaluator

@dataclass
class _BucketFOWT:
    """The minimal FOWT facade the strip physics consumes: site scalars
    (traced, per design) + the static padded node count."""

    rho_water: object
    depth: object
    g: object
    n_nodes: int


def _masked_moor_closures(d):
    """Force/stiffness closures of the PADDED mooring system: the exact
    per-line catenary of :func:`raft_tpu.physics.mooring.mooring_force`
    (shared through
    :func:`~raft_tpu.physics.mooring.catenary_line_forces`) with padded
    lines masked out of the sum (their autodiff stiffness vanishes with
    them — the mask multiplies the primal)."""
    mask = jnp.asarray(d["line_mask"])

    def force(X):
        F6, _ = catenary_line_forces(
            d["moor_fair0"], d["moor_anchor"], d["moor_L"], d["moor_w"],
            d["moor_EA"], X)
        return jnp.sum(jnp.where(mask[:, None], F6, 0.0), axis=0)

    def stiff(X):
        return -jax.jacfwd(force)(X)

    return force, stiff


# 6-DOF rigid-body solver tolerances/caps (make_tolerances for a single
# root-node body; x_ref/y_ref are 0 by the signature gate)
_TOL6 = (0.05, 0.05, 0.05, 0.005, 0.005, 0.005)
_CAP6 = (30.0, 30.0, 5.0, 0.1, 0.1, 0.1)


def make_bucket_evaluator(sig):
    """Build ``evaluate(case) -> outputs`` for one bucket signature.

    ``case`` carries the packed design pytree under ``case["design"]``
    plus the scalar sea state (``Hs``/``Tp``/``beta``); the function is
    pure jax with NO model closure, so one trace serves every design
    that packs into the bucket — vmap the whole case dict (including
    the design subtree) to batch heterogeneous designs.

    Outputs match :func:`raft_tpu.api.make_case_evaluator` key for key
    (X0, Xi, RAO, PSD, S, drag diagnostics, ``status``).
    """
    from raft_tpu.api import _case_status, _policy_cdt
    from raft_tpu.models.dynamics import (fused_response_enabled,
                                          solve_dynamics_fowt,
                                          system_response)
    from raft_tpu.models.statics_solve import solve_equilibrium_general
    from raft_tpu.physics.statics import node_T

    meta = signature_meta(sig)
    S, N, L, nw = meta["S"], meta["N"], meta["L"], meta["nw"]
    w_np = meta["w"]
    dw = float(w_np[1] - w_np[0])
    n_iter, Xi_start = meta["nIter"], meta["XiStart"]
    n_iter_extra = meta["nIterExtra"]
    # numpy trace-time constants: an eager ``jnp.zeros``/``jnp.asarray``
    # at trace time compiles a tiny one-off program per shape — enough
    # to break the "a mixed sweep costs exactly n_buckets backend
    # compiles" contract (host numpy enters the trace through a
    # compile-free device_put)
    tol_vec = np.asarray(_TOL6)
    caps = np.asarray(_CAP6)
    refs = np.zeros(6)

    def evaluate(case):
        d = case["design"]
        Hs, Tp, beta = case["Hs"], case["Tp"], case["beta"]
        w = jnp.asarray(w_np)
        k = jnp.asarray(d["k"])
        mask = jnp.asarray(d["strip_mask"])
        fsb = _BucketFOWT(rho_water=d["rho_water"], depth=d["depth"],
                          g=d["g"], n_nodes=N)
        # StripSet over traced per-design leaves; fields the case chain
        # never reads (Ca_*/Cm_* feed the host-built Imat/A_hydro,
        # mcf/mnode0 the geometry axis) are inert placeholders
        zS = np.zeros(S)
        ss = morison.StripSet(
            node=jnp.asarray(d["node"]), mnode0=jnp.asarray(d["node"]),
            ls=jnp.asarray(d["ls"]), dls=jnp.asarray(d["dls"]),
            ds=jnp.asarray(d["ds"]), drs=jnp.asarray(d["drs"]),
            circ=jnp.asarray(d["circ"]), active=jnp.asarray(d["active"]),
            mcf=np.zeros(S, dtype=bool),
            q0=jnp.asarray(d["q0"]), p10=jnp.asarray(d["p10"]),
            p20=jnp.asarray(d["p20"]),
            Cd_q=jnp.asarray(d["Cd_q"]), Cd_p1=jnp.asarray(d["Cd_p1"]),
            Cd_p2=jnp.asarray(d["Cd_p2"]), Cd_End=jnp.asarray(d["Cd_End"]),
            Ca_q=zS, Ca_p1=zS, Ca_p2=zS, Ca_End=zS,
            Cm_p1_w=np.zeros((S, nw), dtype=np.complex128),
            Cm_p2_w=np.zeros((S, nw), dtype=np.complex128),
        )

        # ---- mean-offset equilibrium (zero mean environmental load)
        if L:
            force, stiff = _masked_moor_closures(d)
        else:
            force = lambda X: np.zeros(6)
            stiff = lambda X: np.zeros((6, 6))
        K_h = jnp.asarray(d["K_h"])
        X0, _, _, _, st_status = solve_equilibrium_general(
            K_h, jnp.asarray(d["F_und"]), np.zeros(6), force, stiff,
            jnp.asarray(tol_vec), jnp.asarray(caps), jnp.asarray(refs))

        # ---- rigid kinematics with a TRACED root index (node order is
        # per design; physics/statics.platform_kinematics with the
        # static fs.root_id gather made dynamic)
        R_ptfm = tf.rotation_matrix(X0[3], X0[4], X0[5])
        r0 = jnp.asarray(d["node_r0"])
        r_root0 = jnp.take(r0, d["root"], axis=0)
        dvec = r0 - r_root0
        r_nodes = r0 + X0[:3] + (dvec @ R_ptfm.T - dvec)
        r_root = jnp.take(r_nodes, d["root"], axis=0)
        Tn = node_T(r_nodes, r_root)

        # ---- pose-dependent strip frames; the validity mask extends
        # the submergence mask, so every ``sub``-gated reduction in the
        # excitation/drag chain drops padded strips too
        r, q, p1, p2 = morison.strip_frames(ss, R_ptfm, r_nodes)
        sub = (r[:, 2] < 0) & mask
        hc = dict(Imat=jnp.asarray(d["Imat"]), a_i=jnp.asarray(d["a_i"]),
                  r=r, q=q, p1=p1, p2=p2, sub=sub,
                  active=sub & jnp.asarray(d["active"]))

        # ---- sea state + excitation
        S_spec = wv.jonswap(w, Hs, Tp)
        zeta = jnp.sqrt(2.0 * S_spec * dw).astype(_policy_cdt())
        exc = morison.hydro_excitation(
            fsb, ss, hc, zeta[None, :], jnp.asarray([beta]), w, k,
            Tn, r_nodes)

        # ---- linear system + drag-linearised impedance solve
        C_moor = stiff(X0) if L else np.zeros((6, 6))
        M_lin = jnp.broadcast_to(
            (jnp.asarray(d["M_struc"]) + jnp.asarray(d["A_hydro"]))
            [:, :, None], (6, 6, nw))
        B_lin = np.zeros((6, 6, nw))
        C_lin = K_h + C_moor
        F_lin = exc["F_hydro_iner"][0]
        Z, Xi_fused, Bmat, dyn_diag = solve_dynamics_fowt(
            fsb, ss, hc, exc["u"][0], M_lin, B_lin, C_lin, F_lin,
            w, Tn, r_nodes, n_iter=n_iter, Xi_start=Xi_start,
            n_iter_extra=n_iter_extra)
        if fused_response_enabled():
            # fused hot path (ROADMAP item 5c): the solve's own final
            # response already IS F_lin + the separable drag-excitation
            # fold — re-staging drag_excitation + a second system solve
            # recomputes the identical quantity (parity gated <=1e-10,
            # tests/test_fused.py)
            Xi = Xi_fused
        else:
            F_wave = exc["F_hydro_iner"][0] + morison.drag_excitation(
                fsb, ss, hc, Bmat, exc["u"][0], Tn, r_nodes)
            Xi = system_response(Z, F_wave[None])[0]

        return dict(
            X0=X0, Xi=Xi, RAO=wv.get_rao(Xi, zeta),
            PSD=0.5 * jnp.abs(Xi) ** 2 / dw, S=S_spec,
            drag_resid=dyn_diag["drag_resid"],
            drag_converged=dyn_diag["drag_converged"],
            n_iter_drag=dyn_diag["n_iter_drag"],
            status=_case_status(st_status, dyn_diag, X0, Xi),
        )

    # AOT-bank identity: the signature IS the program (no closure over
    # any model), so every design in the bucket shares the banked entry
    from raft_tpu.aot.bank import content_fingerprint

    evaluate._raft_program_key = ("bucket_evaluator",
                                  content_fingerprint(list(sig)))
    evaluate._raft_bucket_sig = sig
    return evaluate


# module-level evaluator cache: bucket evaluators close over nothing
# but the signature, so caching them per process is free and lets the
# sweep memo (which lives on the evaluator's attribute dict) persist
# across sweeps — the steady-state zero-compile contract
_EVALUATORS: dict = {}


def get_bucket_evaluator(sig):
    """Process-cached :func:`make_bucket_evaluator` (per signature)."""
    ev = _EVALUATORS.get(sig)
    if ev is None:
        ev = _EVALUATORS[sig] = make_bucket_evaluator(sig)
    return ev
