"""Content-addressed result cache for evaluation serving.

Sweeps, DoEs and optimizer loops are full of duplicate corners: the
WEIS per-iterate pattern re-evaluates near-identical designs, DoE
generators repeat corner cases, and hundreds of concurrent synthetic
clients hammer the same (design, sea-state) pairs.  The cache keys one
evaluation by CONTENT — the design-pytree hash, the exact case floats
and the dispatched out_keys — so a hit is bit-identical to the dispatch
that produced it, by construction.

LRU with a byte budget (numpy ``nbytes`` accounting): serving holds
full per-case output rows (PSD/X0/... arrays), so an entry count alone
would let a few wide-grid designs blow the RSS.  Thread-safe — the
asyncio loop (submit-time lookups) and the dispatcher thread
(post-tick inserts) share one instance.

Pure stdlib + numpy; no jax import, usable host-side everywhere
(:class:`raft_tpu.omdao.DesignEvaluation` reuses it for the optimizer
repeat-call path).
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict

import numpy as np

from raft_tpu.obs import metrics


def _value_token(v):
    """Exact content token of one case value: scalar floats by their
    IEEE bits (never a rounded rendering), arrays by a hash of their
    raw bytes + dtype + shape."""
    a = np.asarray(v)
    if a.dtype == object:
        return repr(v)
    if a.size == 1 and np.issubdtype(a.dtype, np.floating):
        return float(a.reshape(-1)[0]).hex()
    return hashlib.sha256(
        a.tobytes() + str(a.dtype).encode() + repr(a.shape).encode()
    ).hexdigest()


def result_cache_key(design_fingerprint, case, out_keys, extra=()):
    """Stable content key of one evaluation.

    design_fingerprint : the design-pytree hash
        (:func:`raft_tpu.aot.bank.content_fingerprint` of the design —
        :func:`raft_tpu.api.pack_for_serving` returns it)
    case : mapping of case values (``Hs``/``Tp``/``beta`` scalars for
        the single-case chain; the omdao repeat-call path keys its full
        traced case dict, arrays included) — keyed by exact content
        bits, never a rounded rendering
    out_keys : the DISPATCHED out_keys tuple (a served subset of a
        wider dispatch shares the wider entry — key on what was
        computed, not what was asked)
    extra : anything else that shapes the numbers (trace-time flag
        key, x64 mode) — the server folds its flag state in here
    """
    case_items = tuple(sorted((str(k), _value_token(v))
                              for k, v in dict(case).items()))
    blob = repr((str(design_fingerprint), case_items,
                 tuple(out_keys), tuple(extra)))
    return hashlib.sha256(blob.encode()).hexdigest()


def _entry_bytes(row):
    return sum(np.asarray(v).nbytes for v in row.values())


class ResultCache:
    """Byte-budgeted LRU of evaluation rows.

    ``get``/``put`` take/return ``{out_key: numpy array}`` rows (one
    request's outputs).  Eviction is LRU by access order; an entry
    larger than the whole budget is simply not cached.  Hit/miss/evict
    totals feed the metrics registry under ``<prefix>_hits`` /
    ``_misses`` / ``_evictions`` plus a ``<prefix>_bytes`` gauge, so
    ``/metrics`` and the bench report the hit rate without touching
    the instance.
    """

    def __init__(self, max_bytes, metrics_prefix="serve_cache"):
        self.max_bytes = int(max_bytes)
        self._prefix = metrics_prefix
        self._lock = threading.Lock()
        self._entries: OrderedDict[str, tuple[dict, int]] = \
            OrderedDict()  # raft-lint: guarded-by=self._lock
        self._bytes = 0  # raft-lint: guarded-by=self._lock
        self.hits = 0  # raft-lint: guarded-by=self._lock
        self.misses = 0  # raft-lint: guarded-by=self._lock
        self.evictions = 0  # raft-lint: guarded-by=self._lock

    def get(self, key):
        """The cached row for ``key`` (a shallow copy — callers slice
        out_key subsets freely) or ``None``."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                self.misses += 1
                metrics.counter(self._prefix + "_misses").inc()
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            metrics.counter(self._prefix + "_hits").inc()
            return dict(ent[0])

    def put(self, key, row):
        """Insert one output row (values coerced to host numpy).  A
        re-insert under the same key refreshes recency and swaps the
        payload."""
        # np.array COPIES: the batcher hands in row-slice VIEWS of the
        # whole padded dispatch batch — retaining the view would pin
        # the full batch while charging one row against the budget
        row = {k: np.array(v) for k, v in row.items()}
        nbytes = _entry_bytes(row)
        if nbytes > self.max_bytes:
            return False
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._entries[key] = (row, nbytes)
            self._bytes += nbytes
            while self._bytes > self.max_bytes and self._entries:
                _, (_, freed) = self._entries.popitem(last=False)
                self._bytes -= freed
                self.evictions += 1
                metrics.counter(self._prefix + "_evictions").inc()
            metrics.gauge(self._prefix + "_bytes").set(self._bytes)
        return True

    def __len__(self):
        with self._lock:
            return len(self._entries)

    @property
    def bytes(self):
        with self._lock:
            return self._bytes

    def stats(self):
        with self._lock:
            total = self.hits + self.misses
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "max_bytes": self.max_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "hit_rate": round(self.hits / total, 4) if total else None,
            }
