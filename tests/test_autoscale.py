"""Autoscaler tests (fast, socket-free): the control loop against a
faked backend and clock — for-duration hysteresis both ways, the
shared cooldown, min/max bounds, hot-beats-cold, and the occupancy
estimator's two-sample rule."""

import pytest


class FakeBackend:
    """Scriptable stand-in for autoscale.FleetBackend: tests set
    ``press``/``occ``/``n`` directly and read the action log."""

    def __init__(self, n=2):
        self.n = n
        self.press = 0.0
        self.occ = 0.5
        self.actions = []
        self._next = 0

    def n_replicas(self):
        return self.n

    def pressure(self):
        return self.press

    def occupancy(self):
        return self.occ

    def scale_out(self):
        self.n += 1
        self._next += 1
        rid = f"as-{self._next}"
        self.actions.append(("out", rid))
        return rid

    def scale_in(self):
        self.n -= 1
        rid = f"r{self.n}"
        self.actions.append(("in", rid))
        return rid


def _mk(monkeypatch, n=2, out_for=3.0, in_for=15.0, cooldown=30.0,
        minimum=1, maximum=4, low_occ=0.1):
    from raft_tpu.serve.autoscale import Autoscaler

    monkeypatch.setenv("RAFT_TPU_AUTOSCALE_OUT_FOR_S", str(out_for))
    monkeypatch.setenv("RAFT_TPU_AUTOSCALE_IN_FOR_S", str(in_for))
    monkeypatch.setenv("RAFT_TPU_AUTOSCALE_LOW_OCC", str(low_occ))
    clock = [0.0]
    backend = FakeBackend(n=n)
    scaler = Autoscaler(backend=backend, clock=lambda: clock[0],
                        interval_s=1.0, minimum=minimum, maximum=maximum,
                        cooldown_s=cooldown)
    return scaler, backend, clock


def _tick(scaler, clock, t):
    clock[0] = t
    return scaler.step(now=t)


def test_scale_out_needs_sustained_pressure(monkeypatch):
    scaler, backend, clock = _mk(monkeypatch, out_for=3.0)
    backend.press = 1.0
    # pressure below the for-duration: pending, no action
    assert _tick(scaler, clock, 0.0) is None
    assert _tick(scaler, clock, 2.0) is None
    # a blip that clears re-arms the for-duration from scratch
    backend.press = 0.0
    assert _tick(scaler, clock, 2.5) is None
    backend.press = 1.0
    assert _tick(scaler, clock, 3.0) is None
    assert _tick(scaler, clock, 5.0) is None  # only 2s sustained again
    act = _tick(scaler, clock, 6.5)
    assert act is not None and act[0] == "out"
    assert backend.n == 3


def test_cooldown_gates_both_directions(monkeypatch):
    scaler, backend, clock = _mk(monkeypatch, out_for=1.0, in_for=1.0,
                                 cooldown=30.0)
    backend.press = 1.0
    assert _tick(scaler, clock, 0.0) is None
    assert _tick(scaler, clock, 1.5) == ("out", "as-1")
    # still hot, but cooling: no second spawn (the join transient must
    # not read as the next signal)
    assert _tick(scaler, clock, 2.5) is None
    assert _tick(scaler, clock, 20.0) is None
    # pressure resolved + occupancy collapsed: scale-in ALSO waits out
    # the same cooldown, then its own for-duration
    backend.press, backend.occ = 0.0, 0.0
    assert _tick(scaler, clock, 25.0) is None   # cooling
    act = None
    for t in (32.0, 33.5):
        act = _tick(scaler, clock, t) or act
    assert act == ("in", "r2")
    assert [a[0] for a in backend.actions] == ["out", "in"]


def test_bounds_are_hard(monkeypatch):
    scaler, backend, clock = _mk(monkeypatch, out_for=1.0, in_for=1.0,
                                 cooldown=0.0, minimum=2, maximum=3)
    backend.press = 1.0
    assert _tick(scaler, clock, 0.0) is None
    assert _tick(scaler, clock, 1.5) == ("out", "as-1")
    # at the ceiling: sustained pressure scales nothing
    for t in (3.0, 4.5, 6.0):
        assert _tick(scaler, clock, t) is None
    assert backend.n == 3
    backend.press, backend.occ = 0.0, 0.0
    _tick(scaler, clock, 7.0)
    act = _tick(scaler, clock, 8.5)
    assert act is not None and act[0] == "in" and backend.n == 2
    # at the floor: sustained cold scales nothing
    for t in (10.0, 11.5, 13.0):
        assert _tick(scaler, clock, t) is None
    assert backend.n == 2


def test_hot_beats_cold_no_flap(monkeypatch):
    """Contradictory signals (pressure firing while occupancy reads
    low — exactly the scale-out warm-up window) must never shrink."""
    scaler, backend, clock = _mk(monkeypatch, out_for=1.0, in_for=1.0,
                                 cooldown=0.0, maximum=3)
    backend.press, backend.occ = 1.0, 0.0
    assert _tick(scaler, clock, 0.0) is None
    act = _tick(scaler, clock, 1.5)
    assert act is not None and act[0] == "out"
    # both rules stay active; at the ceiling the answer is "hold", not
    # "in" — hot gates cold
    for t in (3.0, 4.5, 6.0, 7.5):
        a = _tick(scaler, clock, t)
        assert a is None or a[0] == "out"
    assert [a[0] for a in backend.actions].count("in") == 0


def test_one_action_per_tick(monkeypatch):
    scaler, backend, clock = _mk(monkeypatch, out_for=1.0, in_for=1.0,
                                 cooldown=0.0, maximum=8)
    backend.press = 1.0
    _tick(scaler, clock, 0.0)
    assert _tick(scaler, clock, 1.5) == ("out", "as-1")
    # even with cooldown 0 a single tick only ever takes one action
    assert len(backend.actions) == 1


def test_scaling_rules_read_flags(monkeypatch):
    from raft_tpu.serve.autoscale import scaling_rules

    monkeypatch.setenv("RAFT_TPU_AUTOSCALE_OUT_FOR_S", "7")
    monkeypatch.setenv("RAFT_TPU_AUTOSCALE_IN_FOR_S", "21")
    monkeypatch.setenv("RAFT_TPU_AUTOSCALE_LOW_OCC", "0.25")
    hot, cold = scaling_rules()
    assert hot.name == "autoscale-hot" and hot.for_s == 7.0
    assert cold.name == "autoscale-cold" and cold.for_s == 21.0
    assert cold.threshold == 0.25
    # in deliberately slower than out (shrink is the careful direction)
    assert cold.for_s > hot.for_s


def test_occupancy_two_sample_rule(tmp_path, monkeypatch):
    """The real backend's occupancy: 0.0 until two lease samples, then
    the busy_s delta rate, clamped to [0, 1], dead rids pruned."""
    import json
    import os

    import time

    from raft_tpu.serve.autoscale import FleetBackend
    from raft_tpu.serve.fleet import _replicas_dir

    clock = [100.0]
    backend = FleetBackend(str(tmp_path), clock=lambda: clock[0])
    rep_dir = _replicas_dir(str(tmp_path))

    def lease(rid, busy_s):
        # renewed far in the (real) future so the lease stays live no
        # matter how long this test takes
        rec = {"replica": rid, "pid": 1, "host": "h", "addr": "127.0.0.1",
               "port": 1, "claimed_t": 1.0,
               "renewed_t": time.time() + 3600.0,
               "ttl_s": 10.0, "designs": {}, "buckets": [],
               "out_keys": [], "healthz": {"busy_s": busy_s},
               "token": rid}
        with open(os.path.join(rep_dir, f"{rid}.json"), "w",
                  encoding="utf-8") as f:
            json.dump(rec, f)

    os.makedirs(rep_dir, exist_ok=True)
    lease("r0", 0.0)
    lease("r1", 0.0)
    assert backend.occupancy() == 0.0  # first sample: no rate yet
    clock[0] = 110.0
    lease("r0", 5.0)   # 5 busy seconds over 10s wall = 0.5
    lease("r1", 20.0)  # faster than wall: clamps to 1.0
    assert backend.occupancy() == pytest.approx(0.75)
    # a vanished replica is pruned, not a crash or a stale rate
    os.remove(os.path.join(rep_dir, "r1.json"))
    clock[0] = 120.0
    lease("r0", 5.0)   # idle decade
    assert backend.occupancy() == pytest.approx(0.0)
    assert "r1" not in backend._busy
