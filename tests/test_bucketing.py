"""Shape-bucketed heterogeneous-design batching tests.

The acceptance contract of the bucketing layer
(:mod:`raft_tpu.structure.bucketing` +
:func:`raft_tpu.parallel.sweep.sweep_heterogeneous`):

* a mixed sweep over >=3 DISTINCT member layouts dispatches at most
  ``n_buckets`` backend compilations (recompile-sentinel-asserted) and
  a second identical sweep compiles nothing;
* every row matches the solo per-design evaluation
  (:func:`raft_tpu.api.make_case_evaluator`) to <=1e-10, INCLUDING the
  int32 solver-health ``status`` word — padded strips/nodes/lines never
  flip health bits, and dp-padding rows are dropped before any
  quarantine logic can see them;
* ragged batches auto-pad to dp-divisibility with masked rows (dropped
  on gather) instead of raising, keeping a ``dp_autopad`` event.
"""

import copy
import json
import os

import jax
import numpy as np
import pytest

import raft_tpu
from raft_tpu.analysis.recompile import count_compilations
from raft_tpu.api import make_case_evaluator
from raft_tpu.parallel.sweep import (
    make_mesh, sweep_cases, sweep_cases_full, sweep_heterogeneous)
from raft_tpu.structure import bucketing
from raft_tpu.structure.schema import load_design

HERE = os.path.dirname(os.path.abspath(__file__))
DESIGNS = os.path.join(HERE, "..", "raft_tpu", "designs")


@pytest.fixture(autouse=True, scope="module")
def _pow2_ladders():
    """Pin the LEGACY pow2 pad policy for this module: the sharing /
    parity / chunking contracts here predate the tuned default ladder
    (RAFT_TPU_BUCKET_STEPS) and deliberately exercise the pow2 path —
    the spar VARIANT (53 strips) only shares the spar's bucket under a
    64-strip pow2 ceiling.  The tuned-ladder signatures get their own
    tests below (test_tuned_ladder_*), which drop the pin."""
    env = "RAFT_TPU_BUCKET_STEPS"
    old = os.environ.get(env)
    os.environ[env] = "pow2"
    yield
    if old is None:
        os.environ.pop(env, None)
    else:
        os.environ[env] = old


def _spar_variant_design():
    """A spar with a DIFFERENT member layout (extra station, different
    diameter schedule) that still packs into the spar's bucket."""
    d = copy.deepcopy(load_design(os.path.join(DESIGNS, "spar_demo.yaml")))
    mem = d["platform"]["members"][0]
    mem["stations"] = [-120, -60, -12, -4, 10]
    mem["d"] = [9.4, 9.4, 9.4, 6.5, 6.5]
    mem["l_fill"] = [52.0, 0.0, 0.0, 0.0]
    mem["rho_fill"] = [1850.0, 0.0, 0.0, 0.0]
    mem["dlsMax"] = 4.0   # finer strips: different strip COUNT too
    return d


@pytest.fixture(scope="module")
def trio():
    """spar + spar-variant + MHK: three distinct member layouts, two
    bucket signatures.  Packing here also forces the lazy host-side
    hydro/statics builds, so the sweep tests count DISPATCH compiles
    only (build-time eager ops are not sweep compiles)."""
    models = [
        raft_tpu.Model(os.path.join(DESIGNS, "spar_demo.yaml")),
        raft_tpu.Model(_spar_variant_design()),
        raft_tpu.Model(os.path.join(DESIGNS, "mhk_demo.yaml")),
    ]
    sigs = [bucketing.bucket_signature(m) for m in models]
    for m, s in zip(models, sigs):
        bucketing.pack_design(m, s)
    return models, sigs


# ------------------------------------------------------------ unit layer

def test_ceil_pow2():
    assert bucketing._ceil_pow2(1) == 1
    assert bucketing._ceil_pow2(3) == 4
    assert bucketing._ceil_pow2(16) == 16
    assert bucketing._ceil_pow2(17) == 32
    assert bucketing._ceil_pow2(3, floor=16) == 16


def test_signature_and_shapes(trio):
    models, sigs = trio
    spar, spar2, mhk = models
    # distinct layouts, shared bucket for the two spar variants
    assert spar.hydro[0].strips.S != spar2.hydro[0].strips.S
    assert sigs[0] == sigs[1]
    assert sigs[2] != sigs[0]
    meta = bucketing.signature_meta(sigs[0])
    assert meta["S"] >= spar2.hydro[0].strips.S
    assert meta["S"] & (meta["S"] - 1) == 0  # power of two
    packed = bucketing.pack_design(spar, sigs[0])
    assert packed["ds"].shape == (meta["S"], 2)
    assert packed["Imat"].shape == (meta["S"], 3, 3, meta["nw"])
    assert packed["node_r0"].shape == (meta["N"], 3)
    assert packed["moor_L"].shape == (meta["L"],)
    # masks mark exactly the real rows
    assert packed["strip_mask"].sum() == spar.hydro[0].strips.S
    assert packed["line_mask"].sum() == spar.ms.n_lines
    # padded strips contribute nothing: zero coefficients and areas
    pad = ~packed["strip_mask"]
    assert not packed["active"][pad].any()
    assert np.all(packed["ds"][pad] == 0)
    assert np.all(packed["Cd_q"][pad] == 0)


def test_padding_waste_frac(trio):
    models, sigs = trio
    packed = [bucketing.pack_design(m, s) for m, s in zip(models, sigs)]
    w = bucketing.padding_waste_frac(packed)
    assert 0.0 < w < 1.0
    assert bucketing.padding_waste_frac([]) == 0.0


def test_axis_counts_reproduce_aggregate_waste(trio):
    """Per-axis waste attribution: the strips axis of waste_by_axis is
    the SAME row-weighted aggregate padding_waste_frac reports (both
    are 1 - sum(real)/sum(padded)), and nodes/lines decompose the rest
    of the pad budget."""
    models, sigs = trio
    axes = [bucketing.axis_counts(m, s) for m, s in zip(models, sigs)]
    for m, s, a in zip(models, sigs, axes):
        meta = bucketing.signature_meta(s)
        assert a["strips"] == (m.hydro[0].strips.S, meta["S"])
        assert a["nodes"] == (m.fowtList[0].n_nodes, meta["N"])
        assert a["lines"][1] == meta["L"]
    by_axis = bucketing.waste_by_axis(axes)
    packed = [bucketing.pack_design(m, s) for m, s in zip(models, sigs)]
    # waste_frac is rounded to 6 decimals for the event payload
    assert by_axis["strips"]["waste_frac"] == pytest.approx(
        bucketing.padding_waste_frac(packed), abs=1e-6)
    for axis in ("strips", "nodes", "lines"):
        rec = by_axis[axis]
        assert 0.0 <= rec["waste_frac"] < 1.0
        assert rec["valid"] <= rec["padded"]


def test_unbucketable_gates(trio):
    models, _ = trio
    spar = models[0]
    from raft_tpu.physics.mooring import MooringNetwork

    old = spar.ms_list[0]
    try:
        net = MooringNetwork(320.0).finalize()
        spar.ms_list[0] = net
        spar.ms = net
        with pytest.raises(bucketing.UnbucketableDesignError):
            bucketing.bucket_signature(spar)
    finally:
        spar.ms_list[0] = old
        spar.ms = old


def test_evaluator_is_stamped(trio):
    _, sigs = trio
    ev = bucketing.get_bucket_evaluator(sigs[0])
    assert ev._raft_program_key[0] == "bucket_evaluator"
    # process cache returns the same object (memoized sweep programs)
    assert bucketing.get_bucket_evaluator(sigs[0]) is ev


# --------------------------------------------- the acceptance invariant

def test_mixed_sweep_parity_and_compile_budget(trio):
    """Sweep over 3 distinct member layouts: <= n_buckets compiles,
    zero on repeat, rows bit-compatible with solo evals including the
    health status word."""
    models, sigs = trio
    n_buckets = len(set(sigs))
    assert n_buckets == 2 < len(models)

    rows = [models[i % 3] for i in range(5)]  # ragged on the dp=8 mesh
    rng = np.random.default_rng(11)
    n = len(rows)
    Hs = 3.0 + 4.0 * rng.random(n)
    Tp = 8.0 + 6.0 * rng.random(n)
    beta = 0.5 * rng.random(n)
    mesh = make_mesh(8)
    keys = ("PSD", "X0", "Xi", "status")

    from raft_tpu.obs import metrics as obs_metrics

    pad0 = {k: obs_metrics.counter(k).value
            for k in ("pad_valid_strips", "pad_total_strips",
                      "pad_valid_rows", "pad_total_rows")}
    with count_compilations() as clog:
        out = sweep_heterogeneous(rows, Hs, Tp, beta, mesh=mesh,
                                  out_keys=keys)
    assert clog.real_count <= n_buckets
    # waste attribution: the per-axis counters reproduce the aggregate
    # row-weighted strips waste exactly, and the batch-rows axis
    # records the dp autopadding (5 rows padded onto the dp=8 mesh)
    dv = obs_metrics.counter("pad_valid_strips").value \
        - pad0["pad_valid_strips"]
    dt = obs_metrics.counter("pad_total_strips").value \
        - pad0["pad_total_strips"]
    agg = bucketing.padding_waste_frac(
        [bucketing.pack_design(m) for m in rows])
    assert dt > 0 and 1.0 - dv / dt == pytest.approx(agg, abs=1e-9)
    assert obs_metrics.counter("pad_valid_rows").value \
        - pad0["pad_valid_rows"] == n
    # 2 bucket groups (4 + 1 rows), each dp-autopadded to the dp=8 mesh
    assert obs_metrics.counter("pad_total_rows").value \
        - pad0["pad_total_rows"] == 16

    with count_compilations() as clog2:
        out2 = sweep_heterogeneous(rows, Hs, Tp, beta, mesh=mesh,
                                   out_keys=keys)
    assert clog2.count == 0  # steady state: no backend events at all
    for k in keys:
        np.testing.assert_array_equal(out[k], out2[k])

    # row-for-row parity vs the solo per-design evaluators
    solo = {id(m): jax.jit(make_case_evaluator(m)) for m in set(rows)}
    for i, m in enumerate(rows):
        ref = solo[id(m)](Hs[i], Tp[i], beta[i])
        for k in ("PSD", "X0", "Xi"):
            np.testing.assert_allclose(
                out[k][i], np.asarray(ref[k]), rtol=1e-10, atol=1e-12,
                err_msg=f"row {i} key {k}")
        # status words EXACTLY equal: padded strips/lines/rows never
        # flip a health bit
        assert int(out["status"][i]) == int(np.asarray(ref["status"]))
    assert out["status"].dtype == np.int32


@pytest.mark.slow
def test_semi_joins_the_mix(trio):
    """The bundled multi-column semi (8 members, its own bucket) rides
    the same dispatcher and matches its solo evaluation."""
    models, sigs = trio
    semi = raft_tpu.Model(os.path.join(DESIGNS, "semi_demo.yaml"))
    sig = bucketing.bucket_signature(semi)
    assert sig not in set(sigs)
    rows = [models[0], semi, models[2]]
    Hs, Tp, beta = np.r_[5.0, 6.0, 3.0], np.r_[10.0, 12.0, 9.0], \
        np.r_[0.0, 0.2, 0.4]
    out = sweep_heterogeneous(rows, Hs, Tp, beta, mesh=make_mesh(8),
                              out_keys=("X0", "PSD", "status"))
    ref = jax.jit(make_case_evaluator(semi))(Hs[1], Tp[1], beta[1])
    np.testing.assert_allclose(out["X0"][1], np.asarray(ref["X0"]),
                               rtol=1e-10, atol=1e-12)
    np.testing.assert_allclose(out["PSD"][1], np.asarray(ref["PSD"]),
                               rtol=1e-10, atol=1e-12)
    assert int(out["status"][1]) == int(np.asarray(ref["status"]))


def test_mixed_frequency_grids_rejected(trio):
    models, _ = trio
    d = copy.deepcopy(load_design(os.path.join(DESIGNS, "spar_demo.yaml")))
    d["settings"]["max_freq"] = 0.15
    other = raft_tpu.Model(d)
    with pytest.raises(ValueError, match="frequency grids"):
        sweep_heterogeneous([models[0], other], [5.0, 5.0], [10.0, 10.0],
                            [0.0, 0.0], mesh=make_mesh(8))


def test_bucket_rows_chunked_dispatch(trio, tmp_path, monkeypatch):
    """RAFT_TPU_BUCKET_ROWS caps the materialized design batch: a
    signature group larger than the cap dispatches in fixed-size
    chunks (last chunk padded) that all share one program, and rows
    still match the unchunked sweep."""
    models, sigs = trio
    rows = [models[i % 3] for i in range(20)]
    rng = np.random.default_rng(7)
    n = len(rows)
    Hs = 3.0 + 4.0 * rng.random(n)
    Tp = 8.0 + 6.0 * rng.random(n)
    beta = 0.5 * rng.random(n)
    mesh = make_mesh(8)
    keys = ("X0", "PSD", "status")
    ref = sweep_heterogeneous(rows, Hs, Tp, beta, mesh=mesh, out_keys=keys)
    log = str(tmp_path / "ev.jsonl")
    monkeypatch.setenv("RAFT_TPU_LOG", log)
    monkeypatch.setenv("RAFT_TPU_BUCKET_ROWS", "8")
    out = sweep_heterogeneous(rows, Hs, Tp, beta, mesh=mesh, out_keys=keys)
    for k in ("X0", "PSD"):
        np.testing.assert_allclose(np.asarray(out[k]), np.asarray(ref[k]),
                                   rtol=1e-10, atol=1e-12)
    np.testing.assert_array_equal(out["status"], ref["status"])
    with open(log) as f:
        evs = [json.loads(x) for x in f if x.strip()]
    disp = [e for e in evs if e["event"] == "span_begin"
            and e.get("name") == "sweep_dispatch"]
    # 14 spar-family rows -> chunks of [8, 6->8]; 6 MHK rows -> one
    # dispatch under the cap
    assert len(disp) == 3


# ------------------------------------------- cost-driven pad ladders

def test_pad_ladder_parse_and_validation(monkeypatch):
    # default: tuned strips rungs, pow2 nodes/lines
    monkeypatch.delenv("RAFT_TPU_BUCKET_STEPS", raising=False)
    lad = bucketing.pad_ladder()
    assert lad["strips"] == (16, 24, 32, 48, 64, 96, 128)
    assert lad["nodes"] is None and lad["lines"] is None
    # explicit spec + pow2 literal
    assert bucketing.pad_ladder("pow2") == dict.fromkeys(
        ("strips", "nodes", "lines"))
    lad = bucketing.pad_ladder("strips=10,30;nodes=pow2")
    assert lad["strips"] == (10, 30) and lad["nodes"] is None
    for bad in ("strips", "bogus=1,2", "strips=3,2", "strips=0",
                "strips=a,b"):
        with pytest.raises(ValueError):
            bucketing.pad_ladder(bad)


def test_axis_pad_floor_and_continuation(monkeypatch):
    monkeypatch.delenv("RAFT_TPU_BUCKET_STEPS", raising=False)
    # single-design bucket at the floor: anything under the first rung
    # pads to the floor rung, never below it
    assert bucketing._axis_pad(1, "strips") == 16
    assert bucketing._axis_pad(16, "strips") == 16
    # midpoint rungs between the pow2 sizes
    assert bucketing._axis_pad(47, "strips") == 48
    assert bucketing._axis_pad(49, "strips") == 64
    assert bucketing._axis_pad(73, "strips") == 96
    # doubling continuation past the last explicit rung
    assert bucketing._axis_pad(130, "strips") == 256
    # pow2 axes keep the classic ceiling-at-floor
    assert bucketing._axis_pad(5, "nodes") == 8
    assert bucketing._axis_pad(1, "nodes") == 2
    assert bucketing._axis_pad(0, "lines") == 0  # moorings-free design
    # custom rungs drive both the pad and the continuation
    monkeypatch.setenv("RAFT_TPU_BUCKET_STEPS", "strips=10,30")
    assert bucketing._axis_pad(25, "strips") == 30
    assert bucketing._axis_pad(31, "strips") == 60


def test_tuned_rungs_recipe():
    """The ladder-seeding recipe: minimal rung set under which every
    observed axis size pads within the waste budget."""
    rungs = bucketing.tuned_rungs([14, 47, 53, 73], max_waste=0.2,
                                  floor=16)
    assert rungs == (16, 53, 73)
    for s in (14, 47, 53, 73):
        pad = min(r for r in rungs if r >= max(s, 0))
        assert 1.0 - max(s, 16) / pad <= 0.2 + 1e-12
    assert bucketing.tuned_rungs([]) == ()
    # a tight budget keeps every distinct size as its own rung
    assert bucketing.tuned_rungs([20, 40], max_waste=0.0) == (20, 40)


def test_tuned_ladder_signatures(trio, monkeypatch):
    """Under the DEFAULT tuned ladder the padded shapes shrink (spar
    47->48 instead of 64) and every waste-attribution consumer reports
    the ACTUAL padded sizes, not an assumed pow2."""
    monkeypatch.delenv("RAFT_TPU_BUCKET_STEPS", raising=False)
    models, pow2_sigs = trio
    spar, spar2, mhk = models
    sigs = [bucketing.bucket_signature(m) for m in models]
    assert bucketing.signature_meta(sigs[0])["S"] == 48   # 47 strips
    assert bucketing.signature_meta(sigs[1])["S"] == 64   # 53 strips
    # the small MHK sits at the ladder floor: its own micro-bucket
    # never shrinks below the floor rung
    assert bucketing.signature_meta(sigs[2])["S"] == 16   # 14 strips
    # the spar variant no longer shares the spar's bucket (48 vs 64) —
    # the tuned ladder trades that sharing for 25% less strip padding
    assert sigs[0] != sigs[1]
    # row-weighted strips waste strictly improves vs the pow2 policy
    def strip_waste(sig_list):
        real = sum(m.hydro[0].strips.S for m in models)
        padded = sum(bucketing.signature_meta(s)["S"] for s in sig_list)
        return 1.0 - real / padded
    assert strip_waste(sigs) < strip_waste(pow2_sigs)
    # axis_counts / waste_by_axis reflect the tuned padded shapes
    axes = [bucketing.axis_counts(m, s) for m, s in zip(models, sigs)]
    assert axes[0]["strips"] == (47, 48)
    by_axis = bucketing.waste_by_axis(axes)
    assert by_axis["strips"]["padded"] == 48 + 64 + 16
    # and pack_design pads to the tuned (non-pow2) size
    packed = bucketing.pack_design(spar, sigs[0])
    assert packed["ds"].shape[0] == 48
    assert packed["strip_mask"].sum() == 47


@pytest.mark.slow
def test_chunked_dispatch_under_non_pow2_steps(trio, tmp_path,
                                               monkeypatch):
    """RAFT_TPU_BUCKET_ROWS chunking under a NON-pow2 strip ladder:
    chunks share one (48-strip) program, results match the solo
    evaluations, and the bucket_sweep event reports the tuned padded
    shapes (the waste table fix — actual sizes, never assumed pow2)."""
    models, _ = trio
    spar = models[0]
    monkeypatch.delenv("RAFT_TPU_BUCKET_STEPS", raising=False)
    rows = [spar] * 10
    rng = np.random.default_rng(3)
    Hs = 3.0 + 4.0 * rng.random(10)
    Tp = 8.0 + 6.0 * rng.random(10)
    beta = 0.5 * rng.random(10)
    mesh = make_mesh(1)
    log = str(tmp_path / "ev.jsonl")
    monkeypatch.setenv("RAFT_TPU_LOG", log)
    monkeypatch.setenv("RAFT_TPU_BUCKET_ROWS", "4")
    with count_compilations() as clog:
        out = sweep_heterogeneous(rows, Hs, Tp, beta, mesh=mesh,
                                  out_keys=("X0", "PSD", "status"))
    # 10 rows -> chunks of 4/4/2->4, ONE compiled 48-strip program
    assert clog.real_count <= 1
    with open(log) as f:
        evs = [json.loads(x) for x in f if x.strip()]
    disp = [e for e in evs if e["event"] == "span_begin"
            and e.get("name") == "sweep_dispatch"]
    assert len(disp) == 3
    sweep_ev = [e for e in evs if e["event"] == "bucket_sweep"][-1]
    assert sweep_ev["waste_by_axis"]["strips"]["padded"] == 10 * 48
    assert sweep_ev["waste_by_axis"]["strips"]["valid"] == 10 * 47
    solo = jax.jit(make_case_evaluator(spar))
    for i in range(10):
        ref = solo(Hs[i], Tp[i], beta[i])
        np.testing.assert_allclose(out["PSD"][i], np.asarray(ref["PSD"]),
                                   rtol=1e-10, atol=1e-12)
        assert int(out["status"][i]) == int(np.asarray(ref["status"]))


# --------------------------------------------------- dp auto-pad (toys)

def _toy_case(h, t, b):
    import jax.numpy as jnp

    return {"PSD": jnp.stack([h, t, b]), "X0": h + t + b}


def _toy_full(c):
    import jax.numpy as jnp

    return {"PSD": jnp.stack([c["Hs"], c["Tp"], c["Hs"] * c["Tp"]]),
            "X0": c["Hs"] - c["Tp"]}


def test_sweep_cases_autopads_ragged_batch(tmp_path, monkeypatch):
    log = str(tmp_path / "ev.jsonl")
    monkeypatch.setenv("RAFT_TPU_LOG", log)
    mesh = make_mesh(8)
    n = 5  # not divisible by dp=8
    Hs = np.linspace(2.0, 4.0, n)
    Tp = np.linspace(8.0, 10.0, n)
    beta = np.zeros(n)
    out = sweep_cases(_toy_case, Hs, Tp, beta, mesh=mesh,
                      out_keys=("PSD", "X0"))
    assert np.asarray(out["X0"]).shape == (n,)
    np.testing.assert_allclose(np.asarray(out["X0"]), Hs + Tp + beta)
    with open(log) as f:
        evs = [json.loads(x) for x in f if x.strip()]
    pads = [e for e in evs if e["event"] == "dp_autopad"]
    assert pads and pads[0]["rows"] == n and pads[0]["pad"] == 3


def test_sweep_cases_full_autopads_ragged_batch():
    mesh = make_mesh(8)
    n = 6
    cases = dict(Hs=np.linspace(2.0, 4.0, n), Tp=np.linspace(8.0, 10.0, n))
    out = sweep_cases_full(_toy_full, cases, mesh=mesh,
                           out_keys=("PSD", "X0"))
    assert np.asarray(out["PSD"]).shape == (n, 3)
    np.testing.assert_allclose(np.asarray(out["X0"]),
                               cases["Hs"] - cases["Tp"])


def test_ragged_dict_and_empty_batch_still_rejected():
    mesh = make_mesh(8)
    with pytest.raises(ValueError, match="ragged"):
        sweep_cases_full(_toy_full, dict(Hs=np.ones(4), Tp=np.ones(3)),
                         mesh=mesh)
    with pytest.raises(ValueError, match="empty"):
        sweep_cases(_toy_case, np.zeros(0), np.zeros(0), np.zeros(0),
                    mesh=mesh)
