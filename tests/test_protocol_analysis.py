"""Unit tests for the protocol model checker: the static
mutation-site extraction (:mod:`raft_tpu.analysis.protocol`), the
interleaving explorer (:mod:`raft_tpu.analysis.mcheck`), the seeded
historical-race fixtures, the ``--json`` CLI surface, and direct
crash-window tests of the two atomic flips everything else leans on
(fabric lease rewrite, release pointer promote).

The explorer subsets used here are the cheap ones (the full five-
scenario sweep runs in lint.sh via ``protocol check``); the fixture
drills stop at the first violation and finish in well under a second.
"""

import json
import os
import subprocess
import sys

import pytest

from raft_tpu.analysis import mcheck, protocol
from raft_tpu.utils import fsops

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
FIXTURES = os.path.join(HERE, "fixtures", "protocol")


def fixture(name):
    return os.path.join(FIXTURES, name)


# ------------------------------------------------------- static extraction


def test_extraction_covers_protocol_surface():
    sites, unmodeled = protocol.extract_all()
    assert unmodeled == []
    keys = {s.key for s in sites}
    # the load-bearing actions must be modeled exactly where they live
    assert "fabric::lease_claim::fsops.create_exclusive" in keys
    assert "fabric::lease_rewrite::fsops.write_atomic" in keys
    assert "fleet::FleetLedger.seize::lease_rewrite" in keys
    assert "release::promote::fsops.write_atomic" in keys
    assert "release::clear_rollout_marker::fsops.unlink" in keys
    # rollout/router/canary coordinate through fleet/release/fsops —
    # they must own NO direct mutation sites of their own
    assert not any(s.module in ("rollout", "router", "canary")
                   for s in sites)


def test_action_classification():
    sites, _ = protocol.extract_all()
    by_key = {s.key: s.action for s in sites}
    assert by_key["fabric::Ledger.claim::lease_claim"] == "claim"
    assert by_key["fabric::Ledger.steal::lease_remove"] == "steal"
    assert by_key["fleet::FleetLedger.seize::lease_rewrite"] == "seize"
    assert by_key["fleet::FleetLedger.evict::lease_remove"] == "evict"
    assert by_key["release::promote::fsops.write_atomic"] == "promote"
    assert by_key["fabric::Ledger.touch_worker::fsops.utime"] \
        == "heartbeat"
    assert by_key["fabric::spawn_worker::open[ab]"] == "append-log"


def test_baseline_roundtrip_clean():
    """The checked-in baseline matches a fresh extraction exactly."""
    sites, unmodeled = protocol.extract_all()
    baseline = protocol.load_baseline()
    assert protocol.sites_to_model(sites) == baseline["sites"]
    assert sorted(baseline["invariants"]) == sorted(mcheck.INVARIANTS)
    assert protocol.diff_against_baseline(sites, unmodeled,
                                          baseline) == []


def test_static_check_clean():
    findings, _ = protocol.check(explore=False)
    assert findings == []


def test_drift_detected():
    sites, unmodeled = protocol.extract_all()
    baseline = protocol.load_baseline()
    mutated = {"schema": baseline["schema"],
               "invariants": baseline["invariants"],
               "sites": dict(baseline["sites"])}
    (dropped, ent) = sorted(mutated["sites"].items())[0]
    del mutated["sites"][dropped]
    mutated["sites"]["fabric::ghost::fsops.unlink"] = {
        "action": "release", "count": 1}
    found = protocol.diff_against_baseline(sites, unmodeled, mutated)
    msgs = [f.message for f in found]
    assert all(f.rule == "protocol-drift" for f in found)
    assert any(dropped in m and "not in baseline" in m for m in msgs)
    assert any("fabric::ghost::fsops.unlink" in m and "vanished" in m
               for m in msgs)


# ----------------------------------------------------- seeded race drills


def test_unmodeled_fixture_caught():
    findings, _ = protocol.run_fixture(fixture("unmodeled_site.py"))
    assert findings
    assert {f.rule for f in findings} == {"protocol-unmodeled"}
    assert any("os.rename" in f.message for f in findings)


def test_claim_hijack_fixture_caught():
    """The pre-PR-13 exists-then-write claim is a single-holder
    violation on its very first interleaving."""
    findings, _ = protocol.run_fixture(fixture("claim_hijack.py"))
    assert any(f.rule == "protocol-single-holder" for f in findings)


def test_gate_fleetwide_fixture_caught():
    """The pre-PR-16 fleet-wide gate goes green off neighbor probes."""
    findings, _ = protocol.run_fixture(fixture("gate_fleetwide.py"))
    assert any(f.rule == "protocol-gate-candidate-probed"
               for f in findings)


def test_release_pointer_scenario_clean():
    violations, stats = mcheck.run_all(
        scenarios=[mcheck.ReleasePointerScenario])
    assert violations == []
    assert stats["release-pointer"]["runs"] > 0


# ------------------------------------------------- crash-window contracts


def _crashing_replace(monkeypatch):
    def boom(src, dst):
        raise OSError("injected crash before pointer flip")
    monkeypatch.setattr(fsops, "replace", boom)


def test_lease_rewrite_crash_window(tmp_path, monkeypatch):
    """A renewer dying between tmp-write and replace must leave the
    prior lease record fully readable and no tmp debris behind."""
    from raft_tpu.parallel import fabric

    path = str(tmp_path / "lease.json")
    assert fabric.lease_claim(path, {"worker": "w1", "token": "t1"})
    _crashing_replace(monkeypatch)
    with pytest.raises(OSError):
        fabric.lease_rewrite(path, {"worker": "w1", "token": "t2"})
    rec, mtime = fabric.lease_read(path)
    assert rec == {"worker": "w1", "token": "t1"}
    assert mtime is not None
    assert [n for n in os.listdir(tmp_path) if ".tmp." in n] == []


def test_promote_crash_window(tmp_path, monkeypatch):
    """A promoter dying at the pointer flip must leave ``current``
    resolving to the previous verified release."""
    from raft_tpu.aot import release

    aot = str(tmp_path)
    man1 = release.build_manifest({}, "code", "flags")
    man2 = release.build_manifest({}, "code", "flags",
                                  parent=man1["release"])
    fsops.makedirs(release.releases_dir(aot))
    for man in (man1, man2):
        fsops.write_atomic(release.manifest_path(man["release"], aot),
                           json.dumps(man, sort_keys=True))
    release.promote(man1["release"], aot)

    _crashing_replace(monkeypatch)
    with pytest.raises(OSError):
        release.promote(man2["release"], aot)
    rid, man = release.resolve(aot)
    assert rid == man1["release"]
    assert man is not None and release.verify_manifest(man) == []
    assert [n for n in os.listdir(release.releases_dir(aot))
            if ".tmp." in n] == []


def test_tmp_and_grave_leftovers_never_live(tmp_path):
    """Stray tmp/grave debris in the replicas dir (a crashed renewer
    or loser of a steal race) must never surface as membership."""
    from raft_tpu.serve import fleet

    root = str(tmp_path)
    led = fleet.FleetLedger(root, replica_id="r0")
    assert led.claim(7001)
    lease = os.path.join(root, "_fleet", "replicas", "r0.json")
    with open(lease + ".tmp.x.1", "w") as f:
        f.write("{torn")
    with open(lease + ".stolen.x.2", "w") as f:
        f.write("{}")
    assert set(led.replicas()) == {"r0"}
    assert set(led.live()) == {"r0"}


# ----------------------------------------------------------- CLI surface


def test_cli_static_json_clean():
    out = subprocess.run(
        [sys.executable, "-m", "raft_tpu.analysis", "protocol",
         "check", "--static-only", "--json"],
        cwd=REPO, capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    doc = json.loads(out.stdout)
    assert doc["engine"] == "protocol"
    assert doc["clean"] is True and doc["findings"] == []


def test_cli_fixture_exit_code_and_records():
    out = subprocess.run(
        [sys.executable, "-m", "raft_tpu.analysis", "protocol",
         "check", "--fixture", fixture("unmodeled_site.py"), "--json"],
        cwd=REPO, capture_output=True, text=True)
    assert out.returncode == 1, out.stderr
    doc = json.loads(out.stdout)
    recs = doc["findings"]
    assert recs and all(
        set(r) >= {"file", "line", "col", "rule", "message"}
        for r in recs)


def test_explorer_is_jax_free():
    """The model checker must stay importable and runnable without jax
    (it is a pre-commit gate; backend init can hang under plugins)."""
    code = (
        "import sys\n"
        "from raft_tpu.analysis import mcheck\n"
        "v, s = mcheck.run_all("
        "scenarios=[mcheck.ReleasePointerScenario])\n"
        "assert not v, v\n"
        "assert 'jax' not in sys.modules, 'jax leaked into explorer'\n")
    out = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                         capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
