"""Quasi-static catenary mooring (jax).

A TPU-native replacement for the MoorPy dependency the reference uses
for mooring reactions (imported at ``/root/reference/raft/raft_model.py:17``
and ``raft_fowt.py:13``; RAFT consumes ``ms.solveEquilibrium`` +
``getCoupledStiffnessA(lines_only=True)`` + body forces,
``raft_fowt.py:797-808``).

Design:
* the classic elastic catenary with flat-seabed contact is solved per
  line by a fixed-iteration damped Newton on (HF, VF) — shape-static,
  so the whole mooring system evaluates as one fused expression and
  ``vmap``s over bodies/designs;
* the 6-DOF mooring force on the platform is a pure function of the
  platform pose, and the coupled stiffness matrix is its exact
  (auto-diff) Jacobian — equivalent to MoorPy's analytic
  ``getCoupledStiffnessA`` in the quasi-static limit;
* the same solve yields fairlead/anchor tensions for output metrics.

Catenary formulation (suspended + grounded regimes, no seabed
friction), e.g. Jonkman (2007) mooring appendix — the same model MoorPy
implements.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.ops import transforms as tf
from raft_tpu.structure.schema import coerce


# ----------------------------------------------------------------- build

@dataclass
class MooringSystem:
    """Static description of one body's mooring system."""

    r_anchor: np.ndarray    # (nL, 3) fixed anchor coordinates
    r_fair0: np.ndarray     # (nL, 3) fairlead coordinates at zero pose
    L: np.ndarray           # (nL,) unstretched lengths
    w: np.ndarray           # (nL,) submerged weight per length [N/m]
    EA: np.ndarray          # (nL,) axial stiffness [N]
    depth: float

    @property
    def n_lines(self):
        return len(self.L)


def build_mooring(mooring, rho_water=1025.0, g=9.81):
    """Parse the design's ``mooring`` section (MoorPy-compatible schema:
    points / lines / line_types) into a MooringSystem.

    Submerged weight per length w = (m' - rho pi/4 d^2) g with d the
    volume-equivalent diameter (MoorPy convention)."""
    depth = float(coerce(mooring, "water_depth", default=600.0))
    types = {lt["name"]: lt for lt in mooring["line_types"]}
    points = {p["name"]: p for p in mooring["points"]}

    r_anchor, r_fair, L, w, EA = [], [], [], [], []
    for line in mooring["lines"]:
        pA = points[line["endA"]]
        pB = points[line["endB"]]
        # orient so end A is the fixed anchor
        if pA["type"] == "fixed":
            anchor, fair = pA, pB
        else:
            anchor, fair = pB, pA
        lt = types[line["type"]]
        d = float(lt["diameter"])
        m_lin = float(lt["mass_density"])
        r_anchor.append(np.array(anchor["location"], dtype=float))
        r_fair.append(np.array(fair["location"], dtype=float))
        L.append(float(line["length"]))
        w.append((m_lin - rho_water * np.pi / 4 * d**2) * g)
        EA.append(float(lt["stiffness"]))

    return MooringSystem(
        r_anchor=np.array(r_anchor),
        r_fair0=np.array(r_fair),
        L=np.array(L),
        w=np.array(w),
        EA=np.array(EA),
        depth=depth,
    )


# --------------------------------------------------------------- catenary

def _profile(HF, VF, L, w, EA):
    """Horizontal/vertical fairlead-anchor spans (XF, ZF) of an elastic
    catenary with fairlead loads (HF, VF); flat frictionless seabed.

    Grounded when VF < w L (part of the line rests on the seabed)."""
    HF = jnp.maximum(HF, 1e-8)
    t1 = VF / HF
    s1 = jnp.sqrt(1.0 + t1 * t1)
    asinh1 = jnp.log(t1 + s1)

    # grounded regime
    LB = L - VF / w
    XF_g = LB + (HF / w) * asinh1 + HF * L / EA
    ZF_g = (HF / w) * (s1 - 1.0) + VF**2 / (2.0 * EA * w)

    # fully suspended regime
    VA = VF - w * L
    t2 = VA / HF
    s2 = jnp.sqrt(1.0 + t2 * t2)
    asinh2 = jnp.log(t2 + s2)
    XF_s = (HF / w) * (asinh1 - asinh2) + HF * L / EA
    ZF_s = (HF / w) * (s1 - s2) + (VF * L - 0.5 * w * L**2) / EA

    grounded = VF < w * L
    return jnp.where(grounded, XF_g, XF_s), jnp.where(grounded, ZF_g, ZF_s)


def solve_catenary(XF, ZF, L, w, EA, n_iter=60):
    """Solve (HF, VF) such that the catenary spans (XF, ZF).

    Damped Newton with the MoorPy-style initial guess; fixed iteration
    count for trace-static shapes (fully converged for physical inputs).
    Returns (HF, VF, HA, VA)."""
    XF = jnp.maximum(XF, 1e-6)
    lr = jnp.sqrt(XF**2 + ZF**2)
    taut = L <= lr
    arg = jnp.maximum(3.0 * ((L**2 - ZF**2) / XF**2 - 1.0), 1e-12)
    lam = jnp.where(taut, 0.2, jnp.sqrt(arg))
    HF = jnp.maximum(jnp.abs(0.5 * w * XF / lam), 1e-3)
    VF = 0.5 * w * (ZF / jnp.tanh(lam) + L)

    def body(carry, _):
        HF, VF = carry

        def res(hv):
            x, z = _profile(hv[0], hv[1], L, w, EA)
            return jnp.stack([x - XF, z - ZF])

        hv = jnp.stack([HF, VF])
        r = res(hv)
        J = jax.jacfwd(res)(hv)
        # guarded 2x2 solve
        det = J[0, 0] * J[1, 1] - J[0, 1] * J[1, 0]
        det = jnp.where(jnp.abs(det) < 1e-30, 1e-30, det)
        dH = -(r[0] * J[1, 1] - r[1] * J[0, 1]) / det
        dV = -(J[0, 0] * r[1] - J[1, 0] * r[0]) / det
        # damp: cap the step to a fraction of current magnitude scale
        scale = jnp.maximum(jnp.abs(HF) + jnp.abs(VF), 1.0)
        cap = 0.5 * scale
        dH = jnp.clip(dH, -cap, cap)
        dV = jnp.clip(dV, -cap, cap)
        HF2 = jnp.maximum(HF + dH, 1e-6)
        VF2 = VF + dV
        return (HF2, VF2), None

    (HF, VF), _ = jax.lax.scan(body, (HF, VF), None, length=n_iter)
    HA = HF  # no seabed friction
    VA = jnp.maximum(VF - w * L, 0.0)
    return HF, VF, HA, VA


# ------------------------------------------------------------ body level

def mooring_force(ms: MooringSystem, r6):
    """Net 6-DOF mooring force on the body at pose ``r6`` about the body
    origin (line forces only)."""
    R = tf.rotation_matrix(r6[3], r6[4], r6[5])
    r_fair = r6[:3] + jnp.asarray(ms.r_fair0) @ R.T  # (nL, 3)
    dvec = r_fair - jnp.asarray(ms.r_anchor)
    XF = jnp.sqrt(dvec[:, 0] ** 2 + dvec[:, 1] ** 2)
    ZF = dvec[:, 2]
    XF_safe = jnp.maximum(XF, 1e-8)
    u_h = dvec[:, :2] / XF_safe[:, None]

    HF, VF, HA, VA = jax.vmap(solve_catenary)(
        XF, ZF, jnp.asarray(ms.L), jnp.asarray(ms.w), jnp.asarray(ms.EA)
    )
    F_fair = jnp.concatenate([-HF[:, None] * u_h, -VF[:, None]], axis=1)  # (nL,3)
    F6 = tf.translate_force_3to6(F_fair, r_fair - r6[:3])
    return jnp.sum(F6, axis=0), dict(HF=HF, VF=VF, HA=HA, VA=VA)


def mooring_stiffness(ms: MooringSystem, r6):
    """Coupled 6x6 mooring stiffness C = -dF/dr6 at pose r6 (exact
    Jacobian; MoorPy getCoupledStiffnessA equivalent)."""
    f = lambda x: mooring_force(ms, x)[0]
    return -jax.jacfwd(f)(jnp.asarray(r6, dtype=float))


def mooring_tensions(ms: MooringSystem, r6):
    """Fairlead and anchor tensions per line (for output metrics)."""
    _, info = mooring_force(ms, r6)
    T_fair = jnp.sqrt(info["HF"] ** 2 + info["VF"] ** 2)
    T_anch = jnp.sqrt(info["HA"] ** 2 + info["VA"] ** 2)
    return T_fair, T_anch
