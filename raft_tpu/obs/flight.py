"""Black-box flight recorder: always-on, fixed-memory postmortem ring.

The observability stack so far is *opt-in*: with ``RAFT_TPU_LOG`` unset
a crashed replica leaves no trace to merge, and a SIGKILL leaves
nothing at all.  This module keeps the last N span/event records of
every process in a bounded in-memory ring — a deque append per record,
cheap enough to stay on unconditionally — and persists them as
schema-versioned JSONL shards that ``python -m raft_tpu.obs trace
--merge`` assembles onto the same wall-clock timeline as live
``RAFT_TPU_LOG`` shards (the dump leads with its own ``proc_start``
clock anchor).

Capture sources (no JSON, no id minting, no contextvar mutation on the
hot path — the zero-overhead span contract in :mod:`raft_tpu.obs.spans`
holds with the recorder on):

* every :func:`raft_tpu.utils.structlog.log_event` call, *before* the
  sink check — events are captured even when logging is off;
* span begin/end on the logging-off fast path (:class:`raft_tpu.obs.
  spans.span` calls :func:`capture_span_begin`/:func:`capture_span_end`
  directly) — trace/span/parent ids are synthesized **at dump time**
  from per-thread nesting stacks, deterministically (derived from the
  record's own clock reading), so repeated dumps of one ring agree and
  a merged dump contributes 0 orphan spans by construction;
* periodic metric-snapshot deltas (``RAFT_TPU_FLIGHT_SNAP_S``): the
  counter movement since the previous snapshot rides in the ring as
  ``flight_metrics`` records, so a postmortem shows *rates*, not just
  the final totals.

Dump triggers: ``alert_fire`` (the alert engine names the triggering
rule in the filename), SEVERE-status quarantine, a compile-budget
breach, an unhandled exception / SIGTERM at exit, on demand via the
loopback-gated ``GET /debug/flight`` and ``python -m raft_tpu.obs
flight dump`` — plus a periodic background flush to a stable
``flight-<pid>.jsonl`` (``RAFT_TPU_FLIGHT_FLUSH_S``) so even an
uncatchable SIGKILL leaves the last flush interval's worth of history.
All shard writes route through the :mod:`raft_tpu.utils.fsops` seam
(tmp + atomic replace): a scraper or merge never reads a torn shard.

Merge discipline: merge at most ONE flight shard per process next to
the live shards.  Span records a dump shares with a live shard carry
the same ids, so ``collect_spans`` collapses them; two *differently
triggered* dumps of the same ring would duplicate instant events.

Pure stdlib, no jax import.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque

from raft_tpu.obs import metrics
from raft_tpu.utils import config, fsops, structlog

#: bumped when the dump-shard layout changes; readers refuse shards
#: from a NEWER writer (``flight show`` exits 1) instead of guessing
SCHEMA_VERSION = 1


class FlightError(ValueError):
    """A flight shard failed strict validation (truncated/damaged/
    newer schema)."""


# ring state: None = not yet configured, False = disabled
# (RAFT_TPU_FLIGHT_RING=0), else the deque.  deque.append is
# GIL-atomic, so the capture hot path takes no lock.
_RING = None  # raft-lint: guarded-by=_STATE_LOCK
_STATE_LOCK = threading.Lock()
_N_CAPTURED = [0]          # approximate (unlocked += is fine for a gauge)
_NEXT_SNAP = [float("inf")]  # raft-lint: guarded-by=_STATE_LOCK
_LAST_COUNTERS: dict = {}  # raft-lint: guarded-by=_STATE_LOCK
_FLUSHER = [None]  # raft-lint: guarded-by=_STATE_LOCK
_HOOKS_INSTALLED = [False]  # raft-lint: guarded-by=_STATE_LOCK


def _configure():
    """First-capture lazy init: size the ring from RAFT_TPU_FLIGHT_RING
    and arm the periodic machinery.  Cached — tests changing the flags
    mid-process call :func:`reset`."""
    global _RING
    with _STATE_LOCK:
        if _RING is not None:
            return _RING
        try:
            n = int(config.get("FLIGHT_RING"))
        except ValueError:
            n = 0
        if n <= 0:
            _RING = False
            return _RING
        _RING = deque(maxlen=n)
        _NEXT_SNAP[0] = time.perf_counter()
    maybe_start()
    return _RING


def reset():
    """Drop the ring and re-read the flags on next capture (tests)."""
    global _RING
    with _STATE_LOCK:
        _RING = None
        _N_CAPTURED[0] = 0
        _NEXT_SNAP[0] = float("inf")
        _LAST_COUNTERS.clear()


def ring_records():
    """Current ring contents (raw tuples, oldest first) — tests."""
    ring = _RING
    return list(ring) if ring else []


# ------------------------------------------------------------ capture

def capture_event(event, payload):
    """Ring-append one structured-log event (the :func:`structlog.
    log_event` tap — fires whether or not a sink is live)."""
    ring = _RING
    if ring is None:
        ring = _configure()
    if ring is False:
        return
    now = time.perf_counter()
    ring.append(("ev", now, event, structlog.SPAN_CTX.get(), payload))
    _N_CAPTURED[0] += 1
    if now >= _NEXT_SNAP[0]:
        _snap_metrics(now)


def capture_span_begin(name, attrs):
    """Ring-append a fast-path (logging-off) span begin.  No ids — the
    dump synthesizes them from the per-thread nesting order."""
    ring = _RING
    if ring is None:
        ring = _configure()
    if ring is False:
        return
    now = time.perf_counter()
    ring.append(("sb", now, name, threading.get_ident(),
                 attrs if attrs else None))
    _N_CAPTURED[0] += 1
    if now >= _NEXT_SNAP[0]:
        _snap_metrics(now)


def capture_span_end(name, wall_s, ok):
    """Ring-append a fast-path (logging-off) span end."""
    ring = _RING
    if ring is None:
        ring = _configure()
    if ring is False:
        return
    now = time.perf_counter()
    ring.append(("se", now, name, threading.get_ident(), wall_s, ok))
    _N_CAPTURED[0] += 1
    if now >= _NEXT_SNAP[0]:
        _snap_metrics(now)


def _snap_metrics(now):
    """Append the counter movement since the last snapshot as one
    ``flight_metrics`` ring record (rate context for a postmortem).
    Runs at most once per RAFT_TPU_FLIGHT_SNAP_S; the odd hot-path
    caller that lands on the boundary pays ~a registry snapshot."""
    with _STATE_LOCK:
        if now < _NEXT_SNAP[0]:
            return
        try:
            period = max(0.5, float(config.get("FLIGHT_SNAP_S")))
        except ValueError:
            period = 10.0
        _NEXT_SNAP[0] = now + period
        counters = metrics.snapshot().get("counters") or {}
        delta = {k: v - _LAST_COUNTERS.get(k, 0)
                 for k, v in counters.items()
                 if v != _LAST_COUNTERS.get(k, 0)}
        _LAST_COUNTERS.clear()
        _LAST_COUNTERS.update(counters)
        ring = _RING
    if ring and delta:
        ring.append(("mx", now, delta))


# ------------------------------------------------------------ serialize

def _synth_id(t, tid):
    """Deterministic synthesized span id for a fast-path record: the
    record's own nanosecond clock reading + thread tag.  Two dumps of
    one ring mint identical ids, so overlapping shards collapse in
    ``collect_spans`` instead of double-counting."""
    return f"fl{int(t * 1e9) & 0xFFFFFFFFFFFF:012x}{tid & 0xFF:02x}"


def _header_record(trigger, n_records):
    """The dump shard's first line: a ``proc_start`` clock anchor (so
    ``obs trace --merge`` places the shard on the shared wall-clock
    timeline) carrying the flight metadata block — the ``flight-dump``
    record family of :mod:`raft_tpu.analysis.schemas`."""
    ring = _RING
    rec = {
        "t": round(time.perf_counter() - structlog._T0, 6),
        "event": "proc_start",
        "pid": os.getpid(),
        "run_id": structlog.run_id(),
        "unix_t": round(time.time(), 6),
        "argv0": os.path.basename(sys.argv[0] or "python"),
        "flight": {
            "version": SCHEMA_VERSION,
            "trigger": str(trigger),
            "ring": (ring.maxlen if ring else 0),
            "records": n_records,
            "captured": _N_CAPTURED[0],
        },
    }
    wid = config.raw("WORKER_ID")
    if wid:
        rec["worker"] = wid
    return rec


def serialize_records(trigger="manual"):
    """The ring as JSON-ready record dicts, header first, on the same
    monotonic ``t`` scale as the live ``RAFT_TPU_LOG`` shards."""
    raw = ring_records()
    t0 = structlog._T0
    base_pid = os.getpid()
    base_rid = structlog.run_id()
    wid = config.raw("WORKER_ID")
    out = [_header_record(trigger, len(raw))]
    stacks: dict = {}  # thread ident -> [(name, span_id, trace_id), ...]
    for item in raw:
        kind = item[0]
        rec = {"t": round(item[1] - t0, 6), "pid": base_pid,
               "run_id": base_rid}
        if wid:
            rec["worker"] = wid
        if kind == "ev":
            _, _t, event, ctx, payload = item
            rec["event"] = event
            if ctx is not None:
                rec["trace_id"], rec["span_id"] = ctx
            if payload:
                for k, v in payload.items():
                    rec[k] = v
        elif kind == "sb":
            _, t, name, tid, attrs = item
            stack = stacks.setdefault(tid, [])
            sid = _synth_id(t, tid)
            trace = stack[-1][2] if stack else sid
            parent = stack[-1][1] if stack else None
            stack.append((name, sid, trace))
            rec.update(event="span_begin", trace_id=trace, span_id=sid,
                       name=name, parent_id=parent)
            if attrs:
                for k, v in attrs.items():
                    rec.setdefault(k, v)
        elif kind == "se":
            _, t, name, tid, wall_s, ok = item
            stack = stacks.get(tid) or []
            sid = trace = None
            for j in range(len(stack) - 1, -1, -1):
                if stack[j][0] == name:
                    _n, sid, trace = stack[j]
                    del stack[j:]
                    break
            rec.update(event="span_end", name=name,
                       wall_s=round(float(wall_s), 6), ok=bool(ok))
            if sid is not None:
                rec["trace_id"], rec["span_id"] = trace, sid
        else:  # "mx"
            _, _t, delta = item
            rec.update({"event": "flight_metrics", "counters": delta})
        out.append(rec)
    return out


def serialize_text(trigger="manual"):
    """The ring as one JSONL string (the ``GET /debug/flight`` body)."""
    return "".join(json.dumps(r, default=str) + "\n"
                   for r in serialize_records(trigger))


# ------------------------------------------------------------ dumps

def _slug(name):
    s = "".join(c if c.isalnum() or c in "-_" else "-"
                for c in str(name).lower())
    return s.strip("-")[:48] or "dump"


def dump_path(trigger="manual", directory=None):
    """Where a dump for ``trigger`` lands: the stable per-process
    ``flight-<pid>.jsonl`` for the periodic flush (latest state wins —
    this is the shard a SIGKILL leaves behind), a trigger-named sibling
    for everything else (an alert dump never clobbers a crash dump)."""
    d = directory if directory is not None else config.raw("FLIGHT_DIR")
    if not d:
        return None
    if trigger == "flush":
        name = f"flight-{os.getpid()}.jsonl"
    else:
        name = f"flight-{os.getpid()}-{_slug(trigger)}.jsonl"
    return os.path.join(d, name)


def dump(trigger="manual", path=None, quiet=False):
    """Atomically persist the ring as one JSONL shard.

    ``path`` overrides the ``RAFT_TPU_FLIGHT_DIR`` layout (the CLI's
    ``-o``).  Returns the written path, or None when there is nowhere
    to write (no dir configured) or nothing recorded.  Best-effort by
    design: a failing dump must never take down the process it is
    trying to explain."""
    if _RING is None:
        _configure()
    if _RING is False:
        return None
    if path is None:
        path = dump_path(trigger)
        if path is None:
            return None
    text = serialize_text(trigger)
    try:
        d = os.path.dirname(path)
        if d:
            fsops.makedirs(d)
        fsops.write_atomic(path, text)
    except OSError:
        return None
    if not quiet:
        structlog.log_event("flight_dump", trigger=str(trigger), path=path,
                            records=max(text.count("\n") - 1, 0))
    return path


# ----------------------------------------------- background persistence

def maybe_start():
    """Arm the periodic flusher + crash hooks when RAFT_TPU_FLIGHT_DIR
    is set (idempotent; called lazily at first capture and explicitly
    by the serve/router/fabric entry points).  Without a dump dir the
    ring still records — ``GET /debug/flight`` and ``obs flight dump
    -o`` remain available."""
    if not config.raw("FLIGHT_DIR"):
        return False
    with _STATE_LOCK:
        if not _HOOKS_INSTALLED[0]:
            _HOOKS_INSTALLED[0] = True
            _install_crash_hooks()
        if _FLUSHER[0] is None or not _FLUSHER[0].is_alive():
            t = threading.Thread(target=_flush_loop, daemon=True,
                                 name="raft-flight-flush")
            _FLUSHER[0] = t
            t.start()
    return True


def _flush_loop():
    while True:
        try:
            period = max(0.2, float(config.get("FLIGHT_FLUSH_S")))
        except ValueError:
            period = 2.0
        time.sleep(period)
        try:
            if config.raw("FLIGHT_DIR"):
                dump(trigger="flush", quiet=True)
        except Exception:  # noqa: BLE001 — the flusher must survive
            pass


def _install_crash_hooks():
    """Unhandled-exception + SIGTERM + exit dumps.  SIGKILL is
    uncatchable by definition — that case is covered by the periodic
    flush shard, which is the whole reason it exists."""
    import atexit
    import signal

    prev_hook = sys.excepthook

    def _flight_excepthook(exc_type, exc, tb):
        try:
            dump(trigger=f"crash-{exc_type.__name__}", quiet=True)
        except Exception:  # noqa: BLE001
            pass
        prev_hook(exc_type, exc, tb)

    sys.excepthook = _flight_excepthook
    atexit.register(lambda: dump(trigger="flush", quiet=True))
    try:
        if (threading.current_thread() is threading.main_thread()
                and signal.getsignal(signal.SIGTERM) == signal.SIG_DFL):
            def _on_term(signum, frame):
                dump(trigger="sigterm", quiet=True)
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

            signal.signal(signal.SIGTERM, _on_term)
    except (ValueError, OSError):
        pass  # non-main thread / exotic platform: exception+exit only


# ------------------------------------------------------------ readers

def read_shard(path):
    """Strictly parse one dump shard; returns ``(header, records)``.

    Unlike :func:`raft_tpu.obs.report.read_events` (which tolerates
    damaged lines), a *flight shard* is written atomically — any
    unparseable line, missing stamp or absent/newer header means the
    artifact is not trustworthy, and trusting a damaged postmortem is
    worse than having none.  Raises :class:`FlightError`."""
    try:
        text = fsops.read_text(path)
    except OSError as e:
        raise FlightError(f"{path}: unreadable ({e})")
    records = []
    for i, line in enumerate(text.splitlines()):
        if not line.strip():
            raise FlightError(f"{path}: blank line {i + 1}")
        try:
            rec = json.loads(line)
        except ValueError:
            raise FlightError(f"{path}: line {i + 1} unparseable "
                              "(truncated dump?)")
        if not isinstance(rec, dict) or "event" not in rec \
                or "t" not in rec or "pid" not in rec:
            raise FlightError(f"{path}: line {i + 1} missing the "
                              "t/event/pid stamps")
        records.append(rec)
    if not records:
        raise FlightError(f"{path}: empty shard")
    hdr = records[0]
    meta = hdr.get("flight")
    if hdr["event"] != "proc_start" or not isinstance(meta, dict):
        raise FlightError(
            f"{path}: first record is not a flight proc_start anchor")
    if "unix_t" not in hdr:
        raise FlightError(f"{path}: anchor has no unix_t (unmergeable)")
    try:
        version = int(meta["version"])
        trigger = str(meta["trigger"])
        ring = int(meta["ring"])
    except (KeyError, TypeError, ValueError):
        raise FlightError(f"{path}: flight header missing "
                          "version/trigger/ring")
    if version > SCHEMA_VERSION:
        raise FlightError(
            f"{path}: schema v{version} is newer than this reader "
            f"(v{SCHEMA_VERSION})")
    del trigger, ring
    return hdr, records


def show(path, out=None):
    """Human summary of one dump shard (``obs flight show``); returns
    0, or 1 after printing the validation failure — the lint.sh gate."""
    out = out if out is not None else sys.stdout
    try:
        hdr, records = read_shard(path)
    except FlightError as e:
        print(f"flight show FAILED: {e}", file=sys.stderr)
        return 1
    from raft_tpu.obs import report

    meta = hdr["flight"]
    spans, unmatched = report.collect_spans(records)
    counts: dict = {}
    for r in records[1:]:
        counts[r["event"]] = counts.get(r["event"], 0) + 1
    ts = [r["t"] for r in records]
    print(f"{path}: flight shard v{meta['version']} "
          f"(trigger={meta['trigger']}, ring={meta['ring']})", file=out)
    print(f"  pid {hdr['pid']}, run_id {hdr.get('run_id')}, "
          f"{len(records) - 1} record(s) of {meta.get('captured', '?')} "
          f"captured, window {max(ts) - min(ts):.3f}s", file=out)
    print(f"  spans: {len(spans)} matched, {len(unmatched)} still open "
          "at dump", file=out)
    for name, n in sorted(counts.items(), key=lambda kv: (-kv[1], kv[0])):
        print(f"  {name:38s} {n:6d}", file=out)
    return 0


# Self-install: importing the obs package is what turns the recorder
# on (structlog stays import-cycle-free by never importing flight).
structlog.set_flight_tap(capture_event)
