"""Traced flexible-FOWT evaluator parity vs the orchestrated path
(VERDICT r2 #3): ``api.make_flexible_evaluator`` runs the 150-DOF
VolturnUS-S-flexible chain — equilibrium, traced nonlinear
displaced-pose kinematics + position-dependent T
(structure/topology_traced.py), N-DOF excitation and drag-linearised
impedance solves — as one jit, matching ``Model.solve_dynamics`` at
1e-9 (which itself matches the reference analyzeCases golden at ~1e-9,
tests/test_flexible.py).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.conftest import ref_data

import raft_tpu
from raft_tpu.api import make_flexible_evaluator

pytestmark = pytest.mark.slow

PATH = ref_data("VolturnUS-S-flexible.yaml")


@pytest.fixture(scope="module")
def model():
    if not os.path.exists(PATH):
        pytest.skip("reference data unavailable")
    return raft_tpu.Model(PATH)


def test_flexible_evaluator_parity(model):
    case = dict(zip(model.design["cases"]["keys"],
                    model.design["cases"]["data"][0]))
    X0_o = model.solve_statics(case)
    Xi_o, info = model.solve_dynamics(case, X0=X0_o)

    evaluate = jax.jit(make_flexible_evaluator(model))
    out = evaluate(dict(
        wind_speed=float(case["wind_speed"]),
        Hs=float(case["wave_height"]), Tp=float(case["wave_period"]),
        beta_deg=float(case["wave_heading"])))

    scale_X = np.max(np.abs(np.asarray(X0_o)))
    np.testing.assert_allclose(np.asarray(out["X0"]), np.asarray(X0_o),
                               atol=1e-9 * scale_X, rtol=0)
    Xi_o = np.asarray(Xi_o)
    Xi_t = np.asarray(out["Xi"])
    scale = np.max(np.abs(Xi_o))
    np.testing.assert_allclose(Xi_t, Xi_o, atol=1e-9 * scale, rtol=0)
    assert Xi_t.shape[1] == 150


def test_flexible_evaluator_vmaps(model):
    """The 150-DOF evaluator vmaps over a sea-state batch."""
    evaluate = make_flexible_evaluator(model)
    fn = jax.jit(jax.vmap(lambda h, t: evaluate(dict(Hs=h, Tp=t))["PSD"]))
    B = 2
    out = fn(jnp.asarray([3.0, 5.0]), jnp.asarray([9.0, 12.0]))
    assert out.shape == (B, 150, model.nw)
    assert bool(jnp.all(jnp.isfinite(out)))


def _scaled_flexible_design(scale_d, scale_t):
    from raft_tpu.structure.schema import load_design

    design = load_design(ref_data("VolturnUS-S-flexible.yaml"))
    for m in design["platform"]["members"]:
        d = np.asarray(m["d"], dtype=float) * scale_d
        m["d"] = d.tolist() if d.ndim else float(d)
        t = np.asarray(m["t"], dtype=float) * scale_t
        m["t"] = t.tolist() if t.ndim else float(t)
    return design


def test_flexible_geometry_params_axis(model):
    """Flexible GEOMETRY design axis (VERDICT r3 #6): one compiled
    150-DOF evaluator serves scaled-member designs through the
    struct_params pytree (host-rebuilt per design — exact build parity,
    incl. the FE-beam C_elast that the rigid traced axis cannot
    re-derive).  Parity: the parametrised evaluator fed a scaled
    design's params equals that design's own BAKED evaluator at 1e-12;
    and a 2-design DoE runs through one vmapped compilation."""
    from raft_tpu.api import flexible_struct_params

    evp = make_flexible_evaluator(model, geometry=True)
    case = dict(Hs=3.5, Tp=10.0, beta_deg=20.0)

    design1 = _scaled_flexible_design(1.03, 1.05)
    model1 = raft_tpu.Model(design1)
    sp1 = flexible_struct_params(model1)
    out_p = jax.jit(lambda c: evp(c))(dict(case, struct_params=sp1))

    ev1 = make_flexible_evaluator(model1)
    out_b = jax.jit(lambda c: ev1(c))(case)
    scale = float(np.max(np.abs(np.asarray(out_b["Xi"]))))
    np.testing.assert_allclose(np.asarray(out_p["X0"]), np.asarray(out_b["X0"]),
                               atol=1e-12 * np.max(np.abs(np.asarray(out_b["X0"]))), rtol=0)
    np.testing.assert_allclose(np.asarray(out_p["Xi"]), np.asarray(out_b["Xi"]),
                               atol=1e-12 * scale, rtol=0)

    # the geometry must actually matter (scaled vs baseline responses differ)
    sp0 = flexible_struct_params(model)
    out_0 = jax.jit(lambda c: evp(c))(dict(case, struct_params=sp0))
    assert float(np.max(np.abs(np.asarray(out_0["X0"])
                               - np.asarray(out_p["X0"])))) > 1e-4

    # one-compile DoE: vmap over the stacked parameter pytrees
    stacked = jax.tree.map(lambda a, b: jnp.stack([jnp.asarray(a),
                                                   jnp.asarray(b)]), sp0, sp1)
    fn = jax.jit(jax.vmap(lambda p: evp(dict(case, struct_params=p))["PSD"]))
    out = fn(stacked)
    assert out.shape[0] == 2
    assert bool(jnp.all(jnp.isfinite(out)))
