"""Design-dictionary schema utilities (build time, numpy).

A tolerant reader for the RAFT-compatible YAML design schema
(documented in the reference at ``docs/usage.rst:100-520``).  The
framework keeps full input-file compatibility with the reference so
existing designs run unmodified; ``coerce`` mirrors the semantics of
the reference's ``getFromDict`` (``/root/reference/raft/helpers.py:828``):
scalars broadcast to requested shapes, lists are length-checked, and
missing keys either raise or take defaults.

This layer runs once per design at build time and produces plain numpy;
nothing here is traced.
"""

from __future__ import annotations

import numpy as np
import yaml


def coerce(d, key, shape=0, dtype=float, default=None, index=None):
    """Fetch ``d[key]`` coerced to ``dtype`` and ``shape``.

    shape semantics (matching helpers.py:828-906):
      0   scalar expected;
      -1  any shape accepted (scalar stays scalar);
      n   1-D array of length n (scalars tile; ``index`` selects a column
          of 2-D input / tiles an element of 1-D input);
      [m, n]  2-D array (1-D rows tile m times).
    """
    if key in d:
        val = d[key]
        if shape == 0:
            if np.isscalar(val):
                return dtype(val)
            raise ValueError(f"'{key}' expected scalar, got {val!r}")
        if shape == -1:
            return dtype(val) if np.isscalar(val) else np.array(val, dtype=dtype)
        if np.isscalar(val):
            return np.tile(dtype(val), shape)
        if np.isscalar(shape):
            if len(val) != shape:
                raise ValueError(f"'{key}' expected length {shape}, got {val!r}")
            if index is None:
                return np.array([dtype(v) for v in val])
            arr = np.array(val)
            if arr.ndim == 1:
                return np.tile(arr[index], shape)
            return np.array([v[index] for v in val])
        arr = np.array(val, dtype=dtype)
        if list(arr.shape) == list(shape):
            return arr
        if arr.ndim == 1 and len(arr) == shape[1]:
            return np.tile(arr, [shape[0], 1])
        raise ValueError(f"'{key}' incompatible with shape {shape}: {val!r}")
    if default is None:
        raise ValueError(f"Key '{key}' not found in design input")
    if shape in (0, -1):
        return default
    if np.isscalar(default):
        return np.tile(default, shape)
    return np.tile(default, [shape, 1])


def load_design(path_or_dict):
    """Load a design from a YAML path or pass a dict through."""
    if isinstance(path_or_dict, dict):
        return path_or_dict
    with open(path_or_dict) as f:
        return yaml.load(f, Loader=yaml.FullLoader)


def parse_cases(design):
    """The load-case table as a list of dicts (docs/usage.rst:167)."""
    if "cases" not in design:
        return []
    keys = design["cases"]["keys"]
    return [dict(zip(keys, row)) for row in design["cases"]["data"]]


def frequency_grid(design):
    """Angular frequency grid from the settings section
    (raft_model.py:46-58): min_freq doubles as the bin width."""
    settings = design.get("settings", {}) or {}
    min_freq = coerce(settings, "min_freq", default=0.01)
    max_freq = coerce(settings, "max_freq", default=1.00)
    w = np.arange(min_freq, max_freq + 0.5 * min_freq, min_freq) * 2 * np.pi
    return w
