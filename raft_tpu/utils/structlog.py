"""Structured (JSONL) event logging for long-running analyses.

SURVEY §5.1: the reference's only instrumentation is a wall-clock print
around the QTF loop (raft_model.py:1122-1126).  Here every analysis
stage can emit machine-readable events — stage name, wall time,
convergence diagnostics — as one JSON object per line.

Off by default (zero overhead beyond an env check).  Enable with

    RAFT_TPU_LOG=-            # JSONL to stderr
    RAFT_TPU_LOG=/path/f.jsonl  # JSONL appended to a file

Events carry a monotonic ``t`` (seconds since process start) and a
``event`` name; everything else is free-form numeric/str payload.
"""

from __future__ import annotations

import atexit
import json
import sys
import time

from raft_tpu.utils import config

_T0 = time.perf_counter()
_SINK = None
_DEST = None


def _sink():
    """Resolve the sink from RAFT_TPU_LOG, re-reading the env var on
    every call so setting/changing/unsetting it mid-process takes
    effect (file handles are swapped and closed at interpreter exit).
    The unset fast path is one dict lookup."""
    global _SINK, _DEST
    dest = config.raw("LOG") or ""
    if dest != _DEST:
        if _SINK is not None and _SINK is not sys.stderr:
            try:
                _SINK.close()
            except Exception:
                pass
        _DEST = dest
        if dest == "-":
            _SINK = sys.stderr
        elif dest:
            _SINK = open(dest, "a")
            atexit.register(_SINK.close)
        else:
            _SINK = None
    return _SINK


def enabled():
    return _sink() is not None


def log_event(event, **payload):
    """Emit one JSONL event (no-op unless RAFT_TPU_LOG is set)."""
    s = _sink()
    if s is None:
        return
    rec = {"t": round(time.perf_counter() - _T0, 6), "event": event}
    for k, v in payload.items():
        if hasattr(v, "item"):
            try:
                v = v.item()
            except Exception:
                v = str(v)
        rec[k] = v
    # default=str: a non-JSON-serializable payload value (Path, dtype,
    # exception, device object) must never take down the analysis that
    # was merely trying to log it
    s.write(json.dumps(rec, default=str) + "\n")
    s.flush()


class stage:
    """Context manager timing one analysis stage:

    with stage("solve_dynamics", case=2): ...
    emits {"event": "solve_dynamics", "wall_s": ..., **kw} on exit."""

    def __init__(self, name, **kw):
        self.name = name
        self.kw = kw

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if enabled():
            log_event(self.name, wall_s=round(time.perf_counter() - self.t0, 6),
                      ok=exc[0] is None, **self.kw)
        return False
