"""Custom AST linter for trace hygiene.

Four rules, each targeting a bug class that has actually bitten this
codebase (or was fixed by hand in PR 2 and must stay fixed):

``dtype-literal``
    Hard-coded complex/float64 dtype literals.  ``dtype=complex`` is
    complex128 under ``jax_enable_x64`` *regardless of the input
    dtypes* — the silent-upcast class that turned f32 pipelines into
    complex128 ones.  On ``jnp`` calls any 64-bit or bare literal is
    flagged (use :mod:`raft_tpu.utils.dtypes` to derive from inputs or
    the policy); on host (numpy) calls only the width-ambiguous bare
    ``complex`` is flagged (write ``np.complex128`` when double
    precision is the audited intent).

``host-coercion``
    ``float()``/``int()``/``bool()``/``.item()``/``np.asarray()``
    applied to values that dataflow from a ``jnp`` expression inside
    the same function: under ``jit`` these raise ``TracerError`` or —
    worse, outside jit — silently pull the value to host and block
    async dispatch.  Applies only to the declared ``TRACED_MODULES``
    (host-orchestration modules pull eager results to numpy on
    purpose); shape/len metadata access is exempt.

``env-read``
    Raw ``os.environ``/``os.getenv`` reads of ``RAFT_TPU_*`` names
    anywhere except the central registry
    (:mod:`raft_tpu.utils.config`): unregistered reads are exactly how
    flag typos fail silently.

``jit-static``
    ``jax.jit`` call sites whose wrapped function takes config-like
    parameters (``mode``, ``n_*``, ``*_path``, ``out_keys``, ...)
    without declaring ``static_argnames``/``static_argnums`` — traced
    config args either crash at trace time or recompile per value.

``event-name``
    ``log_event("<name>", ...)`` calls whose literal event name is not
    registered in :mod:`raft_tpu.obs.events`: a typo'd name does not
    crash anything, it silently splits an event stream in two and every
    consumer (``python -m raft_tpu.obs report``/``trace``, quarantine
    forensics) sees only half the story.

``span-name``
    ``span("<name>", ...)`` calls whose literal span name is not
    registered in the ``SPANS`` table of :mod:`raft_tpu.obs.events` —
    the same typo class as ``event-name``, for the wall-time tree: an
    unregistered name silently forks the span hierarchy and mints a
    stray ``span_<name>_s`` histogram nobody is reading.

``registered-unused`` (whole-scan audit, not a per-file rule)
    Dead registry entries: events/spans in :mod:`raft_tpu.obs.events`
    that nothing emits, ``RAFT_TPU_*`` flags in
    :mod:`raft_tpu.utils.config` that nothing reads, and registered
    flags missing from the README flag tables.  Runs when the CLI
    lints the DEFAULT scan set (a partial path list would flag every
    registration as dead); see :func:`registered_unused`.

Suppression: append ``# raft-lint: disable=<rule>[,<rule>]`` to the
offending line (or put it alone on the line above); a file-level
``# raft-lint: disable-file=<rule>`` comment disables a rule for the
whole file.  Suppressing ``all`` disables every rule.

The linter is pure stdlib ``ast`` — no jax import — so it runs in CI
without touching a backend.  Run ``python -m raft_tpu.analysis lint``.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass

RULES = {
    "dtype-literal": "hard-coded complex/float64 dtype literal",
    "host-coercion": "host-Python coercion of a traced value",
    "env-read": "raw RAFT_TPU_* env read outside raft_tpu.utils.config",
    "jit-static": "jax.jit of config-like args without static_argnames",
    "event-name": "log_event() with an unregistered event name",
    "span-name": "obs.span() with an unregistered span name",
}

_EVENT_NAMES = None
_SPAN_NAMES = None


def _event_names():
    """Registered event names (lazy: the registry lives in
    :mod:`raft_tpu.obs.events`, itself jax-free).  An unloadable
    registry disables the rule rather than flagging everything."""
    global _EVENT_NAMES
    if _EVENT_NAMES is None:
        try:
            from raft_tpu.obs.events import EVENTS

            _EVENT_NAMES = frozenset(EVENTS)
        except Exception:
            _EVENT_NAMES = frozenset()
    return _EVENT_NAMES


def _span_names():
    """Registered span names (same lazy/fail-open contract as
    :func:`_event_names`)."""
    global _SPAN_NAMES
    if _SPAN_NAMES is None:
        try:
            from raft_tpu.obs.events import SPANS

            _SPAN_NAMES = frozenset(SPANS)
        except Exception:
            _SPAN_NAMES = frozenset()
    return _SPAN_NAMES

# modules whose code runs under jax tracing: the host-coercion rule
# only applies here.  Host-orchestration modules (drivers, outputs,
# plotting, the float64 parity path in models/model.py) legitimately
# pull eager jax values to numpy; the traced modules must never.
# Paths are repo-relative '/'-separated prefixes.
TRACED_MODULES = (
    "raft_tpu/ops/",
    "raft_tpu/models/dynamics.py",
    "raft_tpu/physics/morison.py",
    "raft_tpu/api.py",
    "raft_tpu/structure/members_traced.py",
    "raft_tpu/structure/topology_traced.py",
)

_SUPPRESS_RE = re.compile(
    r"#\s*raft-lint:\s*disable(?P<file>-file)?\s*=\s*"
    r"(?P<rules>[a-z\-,\s]+)")

_CONFIG_PARAM_RE = re.compile(
    r"^(n_|num_)"
    # NB: bare `key`/`keys` are NOT config-like — a PRNG `key` param is
    # idiomatic jax and must stay traced (making it static would force
    # a compile per key, the storm this suite exists to prevent)
    r"|^(mode|modes|path|paths|policy|dtype|static|config|cfg|flag|flags"
    r"|method|kind|option|options|out_keys|nWaves|chunk)$"
    r"|(_mode|_path|_dir|_flag|_keys|_name|_names|_kind)$")

# dtype literals that hard-code a 64-bit (or width-ambiguous) choice
_BAD_DTYPE_STRINGS = ("complex", "complex128", "float64")
_BAD_DTYPE_ATTRS = ("complex128", "float64", "complex_", "float_")


@dataclass(frozen=True)
class Finding:
    path: str
    line: int
    col: int
    rule: str
    message: str

    def format(self):
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"


def _attr_root(node):
    """Leftmost name of a dotted expression ('jnp' for jnp.zeros,
    'np' for np.ctypeslib.ndpointer), or None."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _is_jnp_root(root):
    return root in ("jnp", "jax")


def _is_np_root(root):
    return root in ("np", "numpy")


class _Suppressions:
    """Per-file suppression table parsed from comments."""

    def __init__(self, source):
        self.by_line = {}
        self.file_level = set()
        for i, text in enumerate(source.splitlines(), start=1):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            rules = {r.strip() for r in m.group("rules").split(",") if r.strip()}
            if m.group("file"):
                self.file_level |= rules
            else:
                self.by_line.setdefault(i, set()).update(rules)
                # a standalone suppression comment covers the next line
                if text.lstrip().startswith("#"):
                    self.by_line.setdefault(i + 1, set()).update(rules)

    def active(self, rule, line):
        for scope in (self.file_level, self.by_line.get(line, ())):
            if rule in scope or "all" in scope:
                return True
        return False


class _TaintScope:
    """Names in the current function known to flow from jnp expressions."""

    def __init__(self, parent=None):
        self.names = set(parent.names) if parent else set()

    def expr_tainted(self, node):
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and (
                    sub.id in self.names or sub.id == "jnp"):
                return True
        return False


def _coercion_arg_is_hostlike(node):
    """Shape/size/len() accesses are host metadata even on tracers —
    coercing them is fine and extremely common."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in (
                "shape", "ndim", "size", "dtype"):
            return True
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name) \
                and sub.func.id == "len":
            return True
    return False


class _Linter(ast.NodeVisitor):
    def __init__(self, path, display_path, source, rules):
        self.path = path
        self.display = display_path
        self.rules = rules
        self.suppress = _Suppressions(source)
        self.findings = []
        self.scopes = [_TaintScope()]
        # all named function defs, innermost visible wins (for the
        # jit-static rule's call-target resolution)
        self.defs = {}
        tree = ast.parse(source, filename=path)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs.setdefault(node.name, node)
        self.visit(tree)

    # ------------------------------------------------------------- helpers

    def _emit(self, rule, node, message):
        if rule not in self.rules:
            return
        if self.suppress.active(rule, node.lineno):
            return
        self.findings.append(Finding(
            self.display, node.lineno, node.col_offset + 1, rule, message))

    # ------------------------------------------------------------- scoping

    def visit_FunctionDef(self, node):
        self.scopes.append(_TaintScope(self.scopes[-1]))
        self.generic_visit(node)
        self.scopes.pop()

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def visit_Assign(self, node):
        if self.scopes[-1].expr_tainted(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.scopes[-1].names.add(t.id)
                elif isinstance(t, (ast.Tuple, ast.List)):
                    for e in t.elts:
                        if isinstance(e, ast.Name):
                            self.scopes[-1].names.add(e.id)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        if isinstance(node.target, ast.Name) and \
                self.scopes[-1].expr_tainted(node.value):
            self.scopes[-1].names.add(node.target.id)
        self.generic_visit(node)

    # --------------------------------------------------------------- rules

    def visit_Call(self, node):
        self._check_dtype_literal(node)
        self._check_host_coercion(node)
        self._check_env_read(node)
        self._check_jit_static(node)
        self._check_event_name(node)
        self._check_span_name(node)
        self.generic_visit(node)

    def visit_Subscript(self, node):
        # os.environ["RAFT_TPU_X"]
        if isinstance(node.value, ast.Attribute) \
                and node.value.attr == "environ" \
                and _attr_root(node.value) == "os":
            key = node.slice
            if isinstance(key, ast.Constant) and isinstance(key.value, str) \
                    and key.value.startswith("RAFT_TPU_"):
                self._emit("env-read", node,
                           f"os.environ[{key.value!r}] outside the flag "
                           "registry; use raft_tpu.utils.config")
        self.generic_visit(node)

    # positional index of the dtype arg on the common constructors, so
    # `jnp.zeros((6, nw), complex)` is caught as well as the kwarg form
    _DTYPE_ARG_POS = {"zeros": 1, "ones": 1, "empty": 1, "full": 2,
                      "asarray": 1, "array": 1}

    def _check_dtype_literal(self, node):
        root = _attr_root(node.func) if isinstance(
            node.func, (ast.Attribute, ast.Name)) else None
        jnp_call = _is_jnp_root(root)
        values = [kw.value for kw in node.keywords if kw.arg == "dtype"]
        if isinstance(node.func, ast.Attribute):
            # x.astype(complex) — positional dtype
            if node.func.attr == "astype" and node.args:
                values.append(node.args[0])
            pos = self._DTYPE_ARG_POS.get(node.func.attr)
            if pos is not None and len(node.args) > pos:
                values.append(node.args[pos])
        for v in values:
            if isinstance(v, ast.Name) and v.id == "complex":
                self._emit(
                    "dtype-literal", v,
                    "bare `complex` dtype is complex128 under x64 (silent "
                    "upcast); derive from inputs via "
                    "raft_tpu.utils.dtypes.compute_dtypes, or write "
                    "np.complex128 for audited host-side precision")
            elif jnp_call and isinstance(v, ast.Constant) \
                    and isinstance(v.value, str) \
                    and v.value.lower() in _BAD_DTYPE_STRINGS:
                self._emit(
                    "dtype-literal", v,
                    f"hard-coded dtype {v.value!r} on a jnp call pins a "
                    "64-bit width; derive from inputs or the "
                    "RAFT_TPU_DTYPE policy")
            elif jnp_call and isinstance(v, ast.Attribute) \
                    and v.attr in _BAD_DTYPE_ATTRS:
                self._emit(
                    "dtype-literal", v,
                    f"hard-coded dtype .{v.attr} on a jnp call pins a "
                    "64-bit width; derive from inputs or the "
                    "RAFT_TPU_DTYPE policy")

    def _check_host_coercion(self, node):
        scope = self.scopes[-1]
        # float(x) / int(x) / bool(x) / complex(x)
        if isinstance(node.func, ast.Name) \
                and node.func.id in ("float", "int", "bool", "complex") \
                and len(node.args) == 1:
            arg = node.args[0]
            if scope.expr_tainted(arg) and not _coercion_arg_is_hostlike(arg):
                self._emit(
                    "host-coercion", node,
                    f"{node.func.id}() on a traced (jnp-derived) value "
                    "breaks tracing / forces a host sync; keep it as an "
                    "array op (jnp.asarray / astype)")
        # x.item() / x.tolist()
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("item", "tolist") and not node.args:
            if scope.expr_tainted(node.func.value):
                self._emit(
                    "host-coercion", node,
                    f".{node.func.attr}() on a traced (jnp-derived) value "
                    "forces a device->host transfer inside the hot path")
        # np.asarray(x) / np.array(x) on a traced value
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("asarray", "array") \
                and _is_np_root(_attr_root(node.func)) and node.args:
            arg = node.args[0]
            if scope.expr_tainted(arg) and not _coercion_arg_is_hostlike(arg):
                self._emit(
                    "host-coercion", node,
                    "np.asarray/np.array on a jnp value pulls it to host "
                    "(blocks async dispatch); use jnp.asarray or move the "
                    "pull out of the traced path")

    def _check_env_read(self, node):
        if not isinstance(node.func, ast.Attribute):
            return
        is_environ_get = (node.func.attr in ("get", "setdefault")
                          and isinstance(node.func.value, ast.Attribute)
                          and node.func.value.attr == "environ"
                          and _attr_root(node.func.value) == "os")
        is_getenv = (node.func.attr == "getenv"
                     and _attr_root(node.func) == "os")
        if not (is_environ_get or is_getenv) or not node.args:
            return
        key = node.args[0]
        if isinstance(key, ast.Constant) and isinstance(key.value, str) \
                and key.value.startswith("RAFT_TPU_"):
            self._emit(
                "env-read", node,
                f"raw read of {key.value!r} outside the flag registry; "
                "register it in raft_tpu/utils/config.py and use "
                "config.get/config.raw")

    def _check_event_name(self, node):
        # log_event("name", ...) / structlog.log_event("name", ...);
        # dynamic first args (stage's self.name) are not checkable
        fn = node.func
        is_log_event = ((isinstance(fn, ast.Name) and fn.id == "log_event")
                        or (isinstance(fn, ast.Attribute)
                            and fn.attr == "log_event"))
        if not is_log_event or not node.args:
            return
        name = node.args[0]
        if not (isinstance(name, ast.Constant)
                and isinstance(name.value, str)):
            return
        registry = _event_names()
        if registry and name.value not in registry:
            self._emit(
                "event-name", node,
                f"log_event({name.value!r}): event name not registered "
                "in raft_tpu/obs/events.py — a typo'd name silently "
                "splits the event stream for every consumer")

    def _check_span_name(self, node):
        # span("name", ...) / obs.span("name", ...) / spans.span(...);
        # dynamic first args (a variable name) are not checkable
        fn = node.func
        is_span = ((isinstance(fn, ast.Name) and fn.id == "span")
                   or (isinstance(fn, ast.Attribute) and fn.attr == "span"
                       and _attr_root(fn) in ("obs", "spans")))
        if not is_span or not node.args:
            return
        name = node.args[0]
        if not (isinstance(name, ast.Constant)
                and isinstance(name.value, str)):
            return
        registry = _span_names()
        if registry and name.value not in registry:
            self._emit(
                "span-name", node,
                f"span({name.value!r}): span name not registered in the "
                "SPANS table of raft_tpu/obs/events.py — a typo'd name "
                "silently forks the wall-time tree for every consumer")

    def _check_jit_static(self, node):
        if not (isinstance(node.func, ast.Attribute)
                and node.func.attr == "jit"
                and _attr_root(node.func) == "jax"):
            return
        kwarg_names = {kw.arg for kw in node.keywords}
        if kwarg_names & {"static_argnames", "static_argnums"}:
            return
        if not node.args:
            return
        target = node.args[0]
        if isinstance(target, ast.Name):
            target = self.defs.get(target.id)
        if not isinstance(target, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.Lambda)):
            return  # jax.jit(vmap(...)) etc.: not resolvable statically
        args = target.args
        params = [a.arg for a in
                  args.posonlyargs + args.args + args.kwonlyargs]
        suspicious = [p for p in params if _CONFIG_PARAM_RE.search(p)]
        if suspicious:
            self._emit(
                "jit-static", node,
                "jax.jit wraps config-like parameter(s) "
                f"{', '.join(repr(p) for p in suspicious)} without "
                "static_argnames — traced config args fail at trace time "
                "or recompile per value")


# ----------------------------------------------------------------- driver

def repo_root():
    """The repository root (two levels above this package)."""
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def default_paths(root=None):
    """The default lint scan set: the whole ``raft_tpu`` package plus
    the repo-level bench/sweep scripts (tests and fixtures excluded)."""
    root = root or repo_root()
    paths = []
    pkg = os.path.join(root, "raft_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                paths.append(os.path.join(dirpath, fn))
    for fn in ("bench.py", "sweep_10k.py"):
        p = os.path.join(root, fn)
        if os.path.exists(p):
            paths.append(p)
    return paths


def _rules_for(display_path):
    """Rule set by file role: the registry itself is exempt from
    env-read (it IS the sanctioned reader), and host-coercion only
    applies to the declared traced modules."""
    rules = set(RULES)
    norm = display_path.replace(os.sep, "/")
    if norm.endswith("raft_tpu/utils/config.py"):
        rules.discard("env-read")
    if not any(norm.startswith(p) or norm.endswith(p)
               for p in TRACED_MODULES):
        rules.discard("host-coercion")
    return rules


def lint_file(path, display_path=None, source=None, rules=None):
    """Lint one file; returns a list of :class:`Finding`.

    ``rules`` overrides the path-based rule selection (the fixture
    tests force every rule on regardless of location)."""
    display = display_path or os.path.relpath(path, repo_root())
    if source is None:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    try:
        return _Linter(path, display, source,
                       rules or _rules_for(display)).findings
    except SyntaxError as e:
        return [Finding(display, e.lineno or 1, (e.offset or 0) + 1,
                        "syntax", f"cannot parse: {e.msg}")]


def lint_paths(paths=None, root=None):
    """Lint many files (default: :func:`default_paths`); directory
    paths are walked for ``*.py``; findings are sorted by path/line for
    stable CI output."""
    expanded = []
    for p in (paths or default_paths(root)):
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                expanded += [os.path.join(dirpath, fn)
                             for fn in sorted(filenames)
                             if fn.endswith(".py")]
        else:
            expanded.append(p)
    findings = []
    for p in expanded:
        findings.extend(lint_file(p))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


# ----------------------------------------------- registered-unused audit


class _UsageCollector(ast.NodeVisitor):
    """Literal usages of registered names across one file: event names
    (``log_event("x", ...)`` and ``{"event": "x"}`` dict records), span
    names (``span("x", ...)``), and flag names (``config.get/raw/
    env_name("X")`` plus bare ``get/raw/env_name`` inside the registry
    module itself)."""

    def __init__(self):
        self.events = set()
        self.spans = set()
        self.flags = set()

    @staticmethod
    def _str_arg(node):
        if node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            return node.args[0].value
        return None

    def visit_Call(self, node):
        fn = node.func
        name = (fn.id if isinstance(fn, ast.Name)
                else fn.attr if isinstance(fn, ast.Attribute) else None)
        arg = self._str_arg(node)
        if arg is not None:
            if name == "log_event":
                self.events.add(arg)
            elif name == "span":
                self.spans.add(arg)
            elif name in ("get", "raw", "env_name") \
                    and isinstance(fn, ast.Attribute) \
                    and isinstance(fn.value, ast.Name) \
                    and fn.value.id == "config":
                # receiver-checked: `anydict.get("X")` must not mark a
                # flag as read — only the registry module's accessors do
                self.flags.add(arg)
        self.generic_visit(node)

    def visit_Dict(self, node):
        # hand-built records ({"event": "proc_start", ...}) emit events
        # without going through log_event (the structlog clock anchor)
        for k, v in zip(node.keys, node.values):
            if isinstance(k, ast.Constant) and k.value == "event" \
                    and isinstance(v, ast.Constant) \
                    and isinstance(v.value, str):
                self.events.add(v.value)
        self.generic_visit(node)


def _registration_line(lines, needle):
    """1-based line of the first occurrence of ``needle`` in the
    preloaded source ``lines`` (for pointing a dead-entry finding at
    its registration; each registry file is read once, not per name)."""
    for i, text in enumerate(lines, start=1):
        if needle in text:
            return i
    return 1


def _source_lines(path):
    try:
        with open(path, encoding="utf-8") as f:
            return f.read().splitlines()
    except OSError:
        return []


def registered_unused(root=None):
    """Dead-entry audit over the full scan set: events/spans registered
    in :mod:`raft_tpu.obs.events` that no scanned file ever emits, and
    ``RAFT_TPU_*`` flags registered in :mod:`raft_tpu.utils.config`
    that nothing reads — plus README flag-table completeness (every
    registered flag must appear in the README; an undocumented knob is
    indistinguishable from a typo'd one).  Returns :class:`Finding`
    rows anchored at the dead registration.  Only meaningful over the
    DEFAULT scan set — partial path lists would flag everything."""
    root = root or repo_root()
    used = _UsageCollector()
    for p in default_paths(root):
        try:
            with open(p, encoding="utf-8") as f:
                used.visit(ast.parse(f.read(), filename=p))
        except (OSError, SyntaxError):
            continue
    findings = []
    events_lines = _source_lines(
        os.path.join(root, "raft_tpu", "obs", "events.py"))
    events_disp = "raft_tpu/obs/events.py"
    try:
        from raft_tpu.obs.events import EVENTS, SPANS
    except Exception:
        EVENTS, SPANS = {}, {}
    for name in sorted(set(EVENTS) - used.events):
        findings.append(Finding(
            events_disp, _registration_line(events_lines, f'"{name}"'), 1,
            "registered-unused",
            f"event {name!r} is registered but no scanned file ever "
            "emits it — emit it or prune the registration"))
    for name in sorted(set(SPANS) - used.spans):
        findings.append(Finding(
            events_disp, _registration_line(events_lines, f'"{name}"'), 1,
            "registered-unused",
            f"span {name!r} is registered in SPANS but no scanned file "
            "ever opens it — open it or prune the registration"))
    config_lines = _source_lines(
        os.path.join(root, "raft_tpu", "utils", "config.py"))
    config_disp = "raft_tpu/utils/config.py"
    try:
        from raft_tpu.utils.config import FLAGS
    except Exception:
        FLAGS = {}
    readme = "\n".join(_source_lines(os.path.join(root, "README.md")))
    for name in sorted(FLAGS):
        if name not in used.flags:
            findings.append(Finding(
                config_disp,
                _registration_line(config_lines, f'Flag("{name}"'), 1,
                "registered-unused",
                f"flag RAFT_TPU_{name} is registered but nothing reads "
                "it (config.get/raw/env_name) — read it or prune the "
                "registration"))
        if readme and f"RAFT_TPU_{name}" not in readme:
            findings.append(Finding(
                config_disp,
                _registration_line(config_lines, f'Flag("{name}"'), 1,
                "registered-unused",
                f"flag RAFT_TPU_{name} is registered but undocumented "
                "in README.md — every knob must appear in a flag table"))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
