"""Hot-path regression tests for the drag-linearisation overhaul:

* scan-vs-while fixed-point bit-compatibility (the masked fixed-trip
  ``lax.scan`` must reproduce the legacy ``lax.while_loop`` driver
  bit for bit, including the cap-limited flexible-tower golden case
  documented in models/dynamics.py);
* a tier-1-safe micro-regression guard asserting the jaxpr of ONE drag
  iteration contains no re-gathered geometry constants (the
  loop-invariant hoisting of ``drag_lin_precompute``);
* the explicit dtype-policy float32 path (runs, stays finite, lands
  within loose tolerance of the float64 result).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import raft_tpu
from raft_tpu.physics import morison
from tests.conftest import ref_data

SPAR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "raft_tpu", "designs", "spar_demo.yaml")

SPAR_CASE = {
    "wind_speed": 0, "wind_heading": 0, "turbulence": 0,
    "turbine_status": "operating", "yaw_misalign": 0,
    "wave_spectrum": "JONSWAP", "wave_period": 12, "wave_height": 6,
    "wave_heading": 0, "current_speed": 0, "current_heading": 0,
}


def _solve(model, case, monkeypatch, mode):
    monkeypatch.setenv("RAFT_TPU_FIXED_POINT", mode)
    Xi, info = model.solve_dynamics(case)
    return (np.asarray(Xi), np.asarray(info["Z"]),
            info["infos"][0]["dyn_diag"])


def test_scan_vs_while_bitcompat_spar(monkeypatch):
    """The fixed-trip masked scan and the legacy while_loop produce the
    SAME bits (the masked body is idempotent at the converged state),
    and agree on the realized iteration count."""
    model = raft_tpu.Model(SPAR)
    Xi_s, Z_s, d_s = _solve(model, SPAR_CASE, monkeypatch, "scan")
    Xi_w, Z_w, d_w = _solve(model, SPAR_CASE, monkeypatch, "while")
    assert np.array_equal(Xi_s, Xi_w)
    assert np.array_equal(Z_s, Z_w)
    assert int(d_s["n_iter_drag"]) == int(d_w["n_iter_drag"])
    # the spar sea state converges well before the reference cap
    assert bool(d_s["drag_converged"])
    assert 1 <= int(d_s["n_iter_drag"]) <= model.nIter


@pytest.mark.slow
def test_scan_vs_while_bitcompat_flexible_golden(monkeypatch):
    """The cap-limited flexible-tower golden (models/dynamics.py:
    iteration-budget note): nIter=4, the stopping rule never fires, so
    the scan must stop exactly where the while_loop (and the reference)
    stops — keeping the capped linearisation point bit for bit."""
    path = ref_data("VolturnUS-S-flexible.yaml")
    if not os.path.exists(path):
        pytest.skip("reference data unavailable")
    model = raft_tpu.Model(path)
    case = dict(zip(model.design["cases"]["keys"],
                    model.design["cases"]["data"][0]))
    Xi_s, Z_s, d_s = _solve(model, case, monkeypatch, "scan")
    Xi_w, Z_w, d_w = _solve(model, case, monkeypatch, "while")
    assert np.array_equal(Xi_s, Xi_w)
    assert np.array_equal(Z_s, Z_w)
    # cap-limited: all nIter+1 trips do real work, rule unmet
    assert int(d_s["n_iter_drag"]) == int(d_w["n_iter_drag"]) == model.nIter + 1
    assert not bool(d_s["drag_converged"])


def test_fixed_point_flag_validation(monkeypatch):
    from raft_tpu.models import dynamics

    monkeypatch.delenv("RAFT_TPU_FIXED_POINT", raising=False)
    # 'auto' on the CPU test backend resolves to the while driver
    assert dynamics.fixed_point_mode() == "while"
    monkeypatch.setenv("RAFT_TPU_FIXED_POINT", "scan")
    assert dynamics.fixed_point_mode() == "scan"
    monkeypatch.setenv("RAFT_TPU_FIXED_POINT", "unroll")
    with pytest.raises(ValueError):
        dynamics.fixed_point_mode()


def test_drag_iteration_jaxpr_gathers_no_geometry():
    """Regression guard for the loop-invariant hoisting, now expressed
    through the shared contract engine (raft_tpu.analysis.
    jaxpr_contracts): the declarative ``drag_lin_iter`` contract allows
    at most ONE gather — the (iteration-dependent) node RESPONSE
    lookup — and no dynamic_slice; geometry constants (strip positions,
    lever arms, frames, areas) are gathered once in
    drag_lin_precompute.  Reintroducing an ``r_nodes[node_idx]``-style
    lookup into the iteration body fails this."""
    from raft_tpu.analysis import jaxpr_contracts as jc

    tracer = jc.EntryPointTracer(SPAR)
    jaxpr = tracer.trace("drag_lin_iter", "float64")
    assert jc.check_structure("drag_lin_iter", "float64", jaxpr) == []

    # sanity: the one-shot wrapper (precompute included) carries the
    # geometry gathers — the contract's gather cap is not vacuous
    fs, fh, model = tracer.fs, tracer.fh, tracer.model
    Xi0 = jnp.full((fs.nDOF, model.nw), 0.1 + 0j)
    full = jax.make_jaxpr(
        lambda Xi: morison.hydro_linearization(
            fs, fh.strips, fh.hc, fh.u[0], Xi, jnp.asarray(model.w),
            fh.Tn, fh.r_nodes))(Xi0)
    assert jc.count_primitives(full)["gather"] >= 2


def test_dtype_policy_float32_smoke(monkeypatch):
    """RAFT_TPU_DTYPE=float32 routes the drag solve through the
    f32/complex64 pair path: it must run, stay finite, and land within
    loose tolerance of the float64 result."""
    model = raft_tpu.Model(SPAR)
    Xi64, info64 = model.solve_dynamics(SPAR_CASE)
    monkeypatch.setenv("RAFT_TPU_DTYPE", "float32")
    Xi32, info32 = model.solve_dynamics(SPAR_CASE)
    assert np.asarray(info32["Z"]).dtype == np.complex64
    a, b = np.abs(np.asarray(Xi32)), np.abs(np.asarray(Xi64))
    assert np.all(np.isfinite(a))
    scale = np.max(b)
    assert np.max(np.abs(a - b)) < 5e-3 * scale


def test_dtype_policy_helper(monkeypatch):
    from raft_tpu.utils.dtypes import compute_dtypes, policy_name

    monkeypatch.delenv("RAFT_TPU_DTYPE", raising=False)
    assert policy_name() == ""
    rdt, cdt = compute_dtypes(jnp.zeros(3, dtype=jnp.float64))
    assert (rdt, cdt) == (jnp.dtype(jnp.float64), jnp.dtype(jnp.complex128))
    rdt, cdt = compute_dtypes(jnp.zeros(3, dtype=jnp.complex64))
    assert (rdt, cdt) == (jnp.dtype(jnp.float32), jnp.dtype(jnp.complex64))
    monkeypatch.setenv("RAFT_TPU_DTYPE", "float32")
    rdt, cdt = compute_dtypes(jnp.zeros(3, dtype=jnp.float64))
    assert (rdt, cdt) == (jnp.dtype(jnp.float32), jnp.dtype(jnp.complex64))
    monkeypatch.setenv("RAFT_TPU_DTYPE", "half")
    with pytest.raises(ValueError):
        policy_name()
