"""Farm wake coupling + AEP and ballast-density trimming tests.

The FLORIS-coupling capability (raft_model.py:1956-2245) with the
built-in Gaussian wake model: waked rotor speeds feed back into the
array equilibrium (per-FOWT wind speeds), powers and platform positions
converge, and a wind rose integrates to AEP.
"""

import numpy as np
import pytest

import raft_tpu
from raft_tpu.physics.wake import farm_velocities, gaussian_deficit

pytestmark = pytest.mark.slow

FARM = "/root/reference/tests/test_data/VolturnUS-S_farm.yaml"


def test_gaussian_deficit_physics():
    D, Ct, TI = 240.0, 0.8, 0.06
    # deficit decays downstream and crosswind; zero upstream
    d5 = gaussian_deficit(5 * D, 0.0, D, Ct, TI)
    d10 = gaussian_deficit(10 * D, 0.0, D, Ct, TI)
    assert 0 < d10 < d5 < 1
    assert gaussian_deficit(5 * D, 3 * D, D, Ct, TI) < 0.2 * d5
    assert gaussian_deficit(-2 * D, 0.0, D, Ct, TI) == 0.0


def test_farm_velocities_ordering():
    """Downstream turbine sees a slower waked flow; crosswind neighbour
    is nearly unaffected."""
    xy = np.array([[0.0, 0.0], [1200.0, 0.0], [0.0, 1500.0]])
    D = np.array([240.0] * 3)
    ct = [lambda U: 0.8] * 3
    U, Ct = farm_velocities(xy, D, ct, 10.0, 0.0, 0.06)
    assert U[0] == pytest.approx(10.0)
    assert U[1] < 9.5            # waked
    assert U[2] == pytest.approx(10.0, abs=0.05)


@pytest.fixture(scope="module")
def farm_model():
    import os

    if not os.path.exists(FARM):
        pytest.skip("reference farm design unavailable")
    return raft_tpu.Model(FARM)


def test_wake_equilibrium_and_aep(farm_model):
    model = farm_model
    wake = model.wake_coupling(u_grid=np.arange(4.0, 25.0, 1.0))
    keys = model.design["cases"]["keys"]
    case = dict(zip(keys, [10.0, 0.0, 0.06, "operating", 0,
                           "JONSWAP", 8.0, 2.0, 0]))
    winds, xs, ys, powers = wake.find_equilibrium(case, n_iter=4)
    assert winds.shape[1] == model.nFOWT
    # all turbines see at most the free stream; at least one is waked
    # or all free depending on layout vs wind direction
    assert np.all(winds[-1] <= 10.0 + 1e-6)
    assert np.all(powers[-1] >= 0)
    assert np.all(np.isfinite(xs)) and np.all(np.isfinite(ys))

    # a 2-state wind rose integrates to a positive AEP
    p, aep, total = wake.calc_aep([8.0, 30.0], [0.0, 90.0], [0.7, 0.3],
                                  cutin=3.0, cutout=25.0, TI=0.06, n_iter=3)
    assert p.shape == (2, model.nFOWT)
    assert np.all(p[1] == 0)     # above cutout
    assert total > 0


def test_adjust_ballast_density():
    from raft_tpu.drivers import adjust_ballast_density

    model, d_rho = adjust_ballast_density(
        "/root/reference/designs/VolturnUS-S.yaml")
    X = np.asarray(model.solve_statics(None))
    assert abs(X[2]) < 0.05      # trimmed heave
    assert abs(d_rho) < 500.0    # sane density shift
