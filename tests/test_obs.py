"""Telemetry-subsystem tests (:mod:`raft_tpu.obs`).

Fast tier, toy evaluators on a small CPU mesh (no model build):

* span nesting / parent-id propagation, including across the
  checkpointed-sweep path with a resume (pinned ``RAFT_TPU_RUN_ID``
  keeps both runs' events linkable);
* the zero-overhead fast path with ``RAFT_TPU_LOG`` unset;
* metrics-registry thread safety and histogram percentile estimates;
* the metrics snapshot landing in ``metrics.json`` + the sweep
  manifest, and the Prometheus text export;
* Chrome-trace export round-trip (valid JSON, balanced spans) and the
  report CLI on a capture with injected faults;
* the device heartbeat sampler.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu.obs import current_ids, metrics, span
from raft_tpu.obs import report as obs_report
from raft_tpu.obs.heartbeat import Heartbeat
from raft_tpu.parallel.sweep import make_mesh, run_sweep_checkpointed_full
from raft_tpu.utils import faults, structlog

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def toy_full(c):
    return {"PSD": jnp.stack([c["Hs"], c["Tp"], c["Hs"] * c["Tp"]]),
            "X0": c["Hs"] - c["Tp"]}


def _cases(n, seed=0):
    rng = np.random.default_rng(seed)
    return dict(Hs=2.0 + 6.0 * rng.random(n), Tp=8.0 + 8.0 * rng.random(n))


def _events(path, name=None):
    evs, bad = obs_report.read_events(path)
    assert bad == 0
    return [e for e in evs if name is None or e["event"] == name]


MESH = None


def mesh2():
    global MESH
    if MESH is None:
        MESH = make_mesh(2)
    return MESH


@pytest.fixture
def log_path(tmp_path, monkeypatch):
    p = str(tmp_path / "events.jsonl")
    monkeypatch.setenv("RAFT_TPU_LOG", p)
    return p


@pytest.fixture(autouse=True)
def fresh_metrics():
    metrics.reset()
    yield
    metrics.reset()


# ------------------------------------------------------------------ spans


def test_span_nesting_and_parent_ids(log_path):
    with span("outer", job=1) as outer:
        with span("inner") as inner:
            structlog.log_event("drag_linearisation", case=0, fowt=0,
                                resid=1e-3, converged=True, n_iter=3,
                                status=0, reason="")
        with span("inner") as inner2:
            pass
    begins = {e["span_id"]: e for e in _events(log_path, "span_begin")}
    assert len(begins) == 3
    bo = begins[outer.span_id]
    bi, bi2 = begins[inner.span_id], begins[inner2.span_id]
    assert bo["parent_id"] is None and bo["name"] == "outer" and bo["job"] == 1
    assert bi["parent_id"] == outer.span_id
    assert bi2["parent_id"] == outer.span_id
    # one trace id for the whole tree, stamped on every record inside
    assert bo["trace_id"] == bi["trace_id"] == bi2["trace_id"]
    (free_ev,) = _events(log_path, "drag_linearisation")
    assert free_ev["span_id"] == inner.span_id
    assert free_ev["trace_id"] == outer.trace_id
    ends = _events(log_path, "span_end")
    assert len(ends) == 3 and all(e["ok"] and "wall_s" in e for e in ends)
    # pid + run_id are stamped on every record
    for e in _events(log_path):
        assert e["pid"] == os.getpid() and e["run_id"]
    # the context is fully unwound
    assert current_ids() is None


def test_span_failure_records_error_and_reraises(log_path):
    with pytest.raises(ValueError, match="boom"):
        with span("failing"):
            raise ValueError("boom")
    (end,) = _events(log_path, "span_end")
    assert end["ok"] is False and "ValueError" in end["error"]
    assert current_ids() is None


def test_zero_overhead_fast_path_when_log_unset(monkeypatch):
    monkeypatch.delenv("RAFT_TPU_LOG", raising=False)
    monkeypatch.delenv("RAFT_TPU_PROFILE", raising=False)
    with span("quiet", x=1) as s:
        # no ids generated, no contextvar touched, nothing emitted
        assert s.span_id is None and current_ids() is None
    assert not structlog.enabled()
    # the wall-time histogram still feeds (metrics are independent of
    # the event stream) — but no event was produced anywhere
    assert metrics.histogram("span_quiet_s").count == 1


def test_sweep_spans_and_run_id_survive_resume(tmp_path, monkeypatch):
    p = str(tmp_path / "events.jsonl")
    monkeypatch.setenv("RAFT_TPU_LOG", p)
    monkeypatch.setenv("RAFT_TPU_RUN_ID", "linkage01")
    cases = _cases(8, seed=1)
    out_dir = str(tmp_path / "sweep")
    out1 = run_sweep_checkpointed_full(toy_full, cases, out_dir,
                                       shard_size=4, mesh=mesh2())
    faults.truncate_file(os.path.join(out_dir, "shard_0001.npz"))
    out2 = run_sweep_checkpointed_full(toy_full, cases, out_dir,
                                       shard_size=4, mesh=mesh2())
    for k in out1:
        assert np.array_equal(out1[k], out2[k])
    evs = _events(p)
    # both runs share the pinned run id on EVERY record
    assert {e["run_id"] for e in evs} == {"linkage01"}
    spans, unmatched = obs_report.collect_spans(evs)
    assert unmatched == []
    paths, _ = obs_report.span_paths(spans)
    # two sweep roots (run + resume), shards + attempts nested beneath
    assert len(paths[("sweep",)]) == 2
    assert len(paths[("sweep", "shard")]) == 3  # 2 fresh + 1 recomputed
    assert ("sweep", "shard", "shard_attempt") in paths
    # shard events carry the enclosing shard span's ids
    by_id = {s["span_id"]: s for s in spans}
    for e in _events(p, "shard_done"):
        assert by_id[e["span_id"]]["name"] == "shard"


# ---------------------------------------------------------------- metrics


def test_metrics_registry_thread_safety():
    c = metrics.counter("t_conc")
    h = metrics.histogram("t_conc_h")

    def work():
        for i in range(2000):
            c.inc()
            h.observe(i % 7 + 0.5)

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 8 * 2000
    assert h.count == 8 * 2000
    assert h.min == 0.5 and h.max == 6.5


def test_histogram_percentiles_and_snapshot():
    h = metrics.histogram("t_hist")
    for v in [0.01] * 50 + [0.1] * 45 + [10.0] * 5:
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 100
    assert snap["min"] == 0.01 and snap["max"] == 10.0
    # log-bucket estimates: p50 lands in the 0.01 bucket, p95 well
    # below the 10.0 outliers' bucket ceiling
    assert snap["p50"] <= 0.02
    assert 0.05 <= snap["p95"] <= 0.2
    assert metrics.histogram("t_empty").snapshot() == {"count": 0}
    assert metrics.histogram("t_empty").percentile(0.5) is None


def test_kind_collision_is_loud():
    metrics.counter("t_kind")
    with pytest.raises(TypeError, match="already registered"):
        metrics.gauge("t_kind")


def test_prometheus_export(tmp_path):
    metrics.counter("t_prom").inc(4)
    metrics.gauge("t_gauge").set(7.0)
    metrics.gauge("t_gauge").set(3.0)
    metrics.histogram("t_ph").observe(0.5)
    text = metrics.to_prometheus()
    assert "# TYPE raft_tpu_t_prom counter\nraft_tpu_t_prom 4" in text
    assert "raft_tpu_t_gauge 3.0" in text and "raft_tpu_t_gauge_max 7.0" in text
    assert 'raft_tpu_t_ph_bucket{le="+Inf"} 1' in text
    assert "raft_tpu_t_ph_count 1" in text
    path = tmp_path / "m.prom"
    assert metrics.export(str(path))
    assert path.read_text() == text


def test_sweep_dumps_metrics_snapshot(tmp_path, log_path, monkeypatch):
    prom = str(tmp_path / "scrape.prom")
    monkeypatch.setenv("RAFT_TPU_METRICS", prom)
    cases = _cases(8, seed=2)
    out_dir = str(tmp_path / "sweep")
    with faults.inject("transient:shard_eval:1"):
        run_sweep_checkpointed_full(toy_full, cases, out_dir,
                                    shard_size=4, mesh=mesh2(),
                                    backoff_s=0.01)
    with open(os.path.join(out_dir, "metrics.json")) as f:
        snap = json.load(f)
    assert snap["counters"]["shards_done"] == 2
    assert snap["counters"]["shard_retries"] == 1
    assert snap["counters"]["rows_evaluated"] == 8
    # the same snapshot is embedded in the manifest...
    with open(os.path.join(out_dir, "manifest.json")) as f:
        manifest = json.load(f)
    assert manifest["metrics"]["counters"] == snap["counters"]
    # ...emitted as an event...
    (ev,) = _events(log_path, "metrics_snapshot")
    assert ev["snapshot"]["counters"]["shards_done"] == 2
    # ...and exported as Prometheus text
    with open(prom) as f:
        text = f.read()
    assert "raft_tpu_shards_done 2" in text
    assert "raft_tpu_shard_retries 1" in text


def test_resumed_quarantined_rows_counted(tmp_path, log_path):
    """A resumed run must not report rows_quarantined=0 while the
    resumed shards still carry NaN-poisoned rows."""
    def toy_nan(c):
        bad = c["Hs"] < 0
        return {"PSD": jnp.where(bad, jnp.nan,
                                 jnp.stack([c["Hs"], c["Tp"], c["Hs"]])),
                "X0": jnp.where(bad, jnp.nan, c["Hs"] - c["Tp"])}

    cases = _cases(8, seed=6)
    cases["Hs"][5] = -1.0
    out_dir = str(tmp_path / "sweep")
    run_sweep_checkpointed_full(toy_nan, cases, out_dir, shard_size=4,
                                mesh=mesh2(), quarantine_retry=False)
    assert metrics.counter("rows_quarantined").value == 1
    metrics.reset()
    # full resume: every shard loads from disk, the poison persists
    run_sweep_checkpointed_full(toy_nan, cases, out_dir, shard_size=4,
                                mesh=mesh2(), quarantine_retry=False)
    assert metrics.counter("rows_quarantined").value == 1
    done = _events(log_path, "sweep_done")
    assert [e["n_quarantined"] for e in done] == [1, 1]


# ------------------------------------------------------------- CLI tooling


def _run_faulty_sweep(tmp_path, log_path):
    """One checkpointed sweep with a retried transient fault AND a
    quarantined NaN row — the acceptance capture."""
    cases = _cases(8, seed=3)
    out_dir = str(tmp_path / "sweep")
    with faults.inject("transient:shard_eval:1", "nan:shard_result:1"):
        run_sweep_checkpointed_full(toy_full, cases, out_dir,
                                    shard_size=4, mesh=mesh2(),
                                    backoff_s=0.01, quarantine_retry=False)
    assert len(_events(log_path, "shard_retry")) == 1
    assert len(_events(log_path, "shard_quarantine")) == 1
    return out_dir


def test_chrome_trace_roundtrip(tmp_path, log_path):
    _run_faulty_sweep(tmp_path, log_path)
    out = str(tmp_path / "trace.json")
    p = subprocess.run(
        [sys.executable, "-m", "raft_tpu.obs", "trace", log_path, "-o", out],
        capture_output=True, text=True, cwd=REPO)
    assert p.returncode == 0, p.stdout + p.stderr
    with open(out) as f:
        trace = json.load(f)  # valid JSON round-trip
    evs = trace["traceEvents"]
    slices = [e for e in evs if e["ph"] == "X"]
    # every span begin matched an end (balanced), none dropped
    assert trace["otherData"]["spans_unmatched"] == 0
    assert len(slices) == trace["otherData"]["spans_matched"] > 0
    assert {s["name"] for s in slices} >= {"sweep", "shard", "shard_attempt"}
    for e in evs:
        assert {"name", "ph", "ts", "pid", "tid"} <= set(e)
    for s in slices:
        assert s["dur"] >= 0
    # the failed attempt slice carries the error
    fails = [s for s in slices if s["args"].get("error")]
    assert len(fails) == 1 and "Transient" in fails[0]["args"]["error"]
    # instant events for the non-span stream
    assert any(e["ph"] == "i" and e["name"] == "shard_retry" for e in evs)


def test_report_cli_smoke(tmp_path, log_path):
    _run_faulty_sweep(tmp_path, log_path)
    p = subprocess.run(
        [sys.executable, "-m", "raft_tpu.obs", "report", log_path],
        capture_output=True, text=True, cwd=REPO)
    assert p.returncode == 0, p.stdout + p.stderr
    out = p.stdout
    assert "span wall-time tree" in out
    assert "sweep" in out and "shard_attempt" in out
    assert "counters (final metrics snapshot)" in out
    assert "shard_retries" in out
    assert "reliability summary" in out
    assert "retries: 1" in out
    assert "quarantine judgements: 1" in out
    # empty/garbage input exits 2, not a traceback
    bad = tmp_path / "empty.jsonl"
    bad.write_text("not json\n")
    p = subprocess.run(
        [sys.executable, "-m", "raft_tpu.obs", "report", str(bad)],
        capture_output=True, text=True, cwd=REPO)
    assert p.returncode == 2


def test_events_cli_lists_registry():
    p = subprocess.run(
        [sys.executable, "-m", "raft_tpu.obs", "events"],
        capture_output=True, text=True, cwd=REPO)
    assert p.returncode == 0
    assert "span_begin" in p.stdout and "heartbeat" in p.stdout


# -------------------------------------------------------------- heartbeat


def test_heartbeat_samples_devices_and_progress(log_path):
    progress = {"shards_done": 0, "n_shards": 2}
    hb = Heartbeat(0.02, progress=progress)
    hb.beat()  # deterministic single sample (no thread timing)
    progress["shards_done"] = 1
    hb.beat()
    evs = _events(log_path, "heartbeat")
    assert len(evs) == 2
    assert evs[0]["devices"] and "kind" in evs[0]["devices"][0]
    assert evs[0]["live_arrays"] is not None
    assert [e["progress"]["shards_done"] for e in evs] == [0, 1]
    assert metrics.gauge("live_arrays").value is not None


def test_heartbeat_thread_lifecycle(log_path, monkeypatch):
    monkeypatch.setenv("RAFT_TPU_HEARTBEAT_S", "0.02")
    from raft_tpu.obs.heartbeat import maybe_heartbeat

    with maybe_heartbeat(progress={"stage": "x"}) as hb:
        assert hb is not None and hb.is_alive()
        time.sleep(0.1)
    assert not hb.is_alive()
    # sampled while running, plus the final beat on stop
    assert len(_events(log_path, "heartbeat")) >= 2


def test_heartbeat_disabled_by_default(monkeypatch):
    monkeypatch.delenv("RAFT_TPU_HEARTBEAT_S", raising=False)
    from raft_tpu.obs.heartbeat import maybe_heartbeat

    with maybe_heartbeat() as hb:
        assert hb is None


# -------------------------------------------------------------- structlog


def test_stage_failure_includes_error(log_path):
    with pytest.raises(RuntimeError):
        with structlog.stage("doomed_stage", case=7):
            raise RuntimeError("kaput")
    (ev,) = _events(log_path, "doomed_stage")
    assert ev["ok"] is False and "kaput" in ev["error"] and ev["case"] == 7


def test_run_id_defaults_to_process_uuid(log_path, monkeypatch):
    monkeypatch.delenv("RAFT_TPU_RUN_ID", raising=False)
    rid = structlog.run_id()
    assert rid and rid == structlog.run_id()  # stable within the process
    monkeypatch.setenv("RAFT_TPU_RUN_ID", "pinned42")
    assert structlog.run_id() == "pinned42"
