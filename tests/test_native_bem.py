"""Native C++ panel-method kernel tests.

Physics checks against closed-form potential-flow results:
* surge added mass of a deeply-drafted circular spar ~ rho pi a^2 T
  (2-D cylinder slice value Ca = 1, with 3-D end-effect reduction);
* symmetry of the added-mass matrix.
"""

import shutil

import numpy as np
import pytest

from raft_tpu.io.panels import mesh_cylinder, write_pnl


@pytest.fixture(scope="module")
def spar_mesh():
    if shutil.which("g++") is None:
        pytest.skip("no C++ toolchain")
    # vertical cylinder: radius 5 m, draft 60 m
    return mesh_cylinder(
        stations=[0.0, 60.0], diameters=[10.0, 10.0],
        rA=np.array([0.0, 0.0, -60.0]), q=np.array([0.0, 0.0, 1.0]),
        n_az=24, dz_max=2.5,
    )


def test_mesh_properties(spar_mesh):
    verts, cents, norms, areas = spar_mesh
    assert np.all(cents[:, 2] <= 0)
    # total side area ~ 2 pi a T; cap area ~ pi a^2
    assert abs(areas.sum() - (2 * np.pi * 5 * 60 + np.pi * 25)) / areas.sum() < 0.05
    # normals unit length
    assert np.allclose(np.linalg.norm(norms, axis=1), 1.0, atol=1e-9)


def test_radiation_added_mass(spar_mesh):
    from raft_tpu.native import radiation_added_mass

    verts, cents, norms, areas = spar_mesh
    rho = 1025.0
    A = radiation_added_mass(verts, cents, norms, areas, mirror=-1, rho=rho)
    a, T = 5.0, 60.0
    A11_strip = rho * np.pi * a**2 * T  # 2-D slice estimate
    # 3-D + discretisation effects: expect within ~20% of the strip value
    assert 0.75 * A11_strip < A[0, 0] < 1.15 * A11_strip
    assert np.isclose(A[0, 0], A[1, 1], rtol=1e-6)   # x/y symmetry
    assert abs(A[0, 1]) < 0.01 * A[0, 0]
    # matrix symmetry (Green's identity)
    assert np.allclose(A, A.T, rtol=5e-2, atol=1e-3 * A[0, 0])
    # heave added mass positive and much smaller than surge for a spar
    assert 0 < A[2, 2] < 0.5 * A[0, 0]


def test_pnl_writer(tmp_path, spar_mesh):
    verts, *_ = spar_mesh
    p = tmp_path / "mesh.pnl"
    write_pnl(p, verts)
    lines = p.read_text().splitlines()
    assert str(len(verts)) in lines[2]
