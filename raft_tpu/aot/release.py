"""Immutable, content-addressed releases of the AOT program bank.

A warmed bank directory is the deploy artifact — but a directory is
not a *version*: nothing names the exact entry set a fleet was warmed
from, so "roll back to yesterday's programs" and "are these two
replicas serving the same release?" have no answer.  A **release**
fixes that: a signed manifest snapshotting the bank — every entry key
with its payload sha, the code fingerprint, the trace-flags
fingerprint and the batch-ladder state that shaped the keys, plus the
parent release — written under ``RAFT_TPU_AOT_DIR/releases/`` and
addressed by a hash of its own content:

* the release id is ``sha256(format, parent, code, flags, ladder,
  entries)[:12]`` — two cuts of the same bank state under the same
  flags are the SAME release;
* the manifest is signed by ``manifest_sha256`` over its canonical
  JSON body, so any post-cut tamper (edited entry sha, swapped
  parent) is detected by ``release verify``;
* the bank directory stays the single content-addressed object store
  (entries are immutable and shared across releases, like git objects
  behind refs) — a release is a *view*, so cutting one is a metadata
  write, never a copy;
* ``releases/current.json`` is the pointer replicas resolve at warmup
  (flipped by atomic rename: ``promote``/``rollback``), and the
  resolved id is stamped into every ``x-raft-provenance`` header —
  the rolling-upgrade canary distinguishes "mixed-version fleet
  mid-rollout" from "genuinely skewed replica" by exactly this stamp
  (``releases/rollout.json`` marks the in-progress window).

CLI: ``python -m raft_tpu.aot release {cut,list,verify,promote,
rollback}``.  ``verify --manifest`` is a pure integrity check (no
bank, no jax — the lint.sh fixture gate); ``verify
--against-designs`` additionally diffs the live designs' program
identities against the manifest and names the mismatch class (code |
flags | ladder | avals) — the diagnosis a require-mode replica
prints before dying on a cold bank.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time

from raft_tpu.aot import bank
from raft_tpu.utils import config, fsops
from raft_tpu.utils.structlog import log_event

RELEASES_DIRNAME = "releases"
MANIFEST_SCHEMA = "release-manifest-v1"

#: the flags whose values shape bank keys (trace-time program flags +
#: the batch-ladder geometry): captured into the manifest's ``env``
#: block so a rollout can spawn candidate replicas under EXACTLY the
#: environment the release was warmed with
TRACE_FLAG_NAMES = ("SOLVER", "FIXED_POINT", "SCAN_CHUNK", "DTYPE",
                    "COND_CHECK", "COND_THRESHOLD", "ITER_SCALE", "FUSED")
LADDER_FLAG_NAMES = ("SERVE_LADDER", "SERVE_MAX_BATCH",
                     "BUCKET_STEPS", "BUCKET_ROWS")


def releases_dir(aot_dir=None):
    return os.path.join(aot_dir or config.get("AOT_DIR"), RELEASES_DIRNAME)


def manifest_path(release_id, aot_dir=None):
    return os.path.join(releases_dir(aot_dir), f"{release_id}.json")


def current_path(aot_dir=None):
    return os.path.join(releases_dir(aot_dir), "current.json")


def rollout_marker_path(aot_dir=None):
    return os.path.join(releases_dir(aot_dir), "rollout.json")


def _canonical(obj):
    """Canonical JSON bytes — the signing/addressing domain."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":"),
                      default=str).encode()


# ----------------------------------------------------------------- build


def ladder_state():
    """The batch-ladder flag values that shape the serve bank keys —
    part of the release identity: PR-15's gotcha was exactly a ladder
    retune silently re-keying the bank under a warmed fleet."""
    return {k: config.get(k) for k in LADDER_FLAG_NAMES}


def capture_env():
    """The explicitly-SET ``RAFT_TPU_*`` environment of the key-shaping
    flags (unset flags stay unset — the candidate replica then sees
    the same defaults).  The rollout driver applies this verbatim when
    spawning replicas of the release."""
    env = {}
    for k in TRACE_FLAG_NAMES + LADDER_FLAG_NAMES:
        name = config.env_name(k)
        if name in os.environ:
            env[name] = os.environ[name]
    return env


def snapshot_entries():
    """``{entry_key: {payload_sha256, kind}}`` of every bank entry the
    CURRENT code would load (other source states are dead weight, not
    release content; foreign environments — other platform/topology —
    are legitimate coexisting variants and stay in)."""
    code = bank.code_fingerprint()
    out = {}
    for key, meta, _mp, bin_path in bank.scan():
        if meta is None or not os.path.exists(bin_path):
            continue
        if meta.get("format") != bank.BANK_FORMAT:
            continue
        if (meta.get("version") or {}).get("code") != code:
            continue
        out[key] = {"payload_sha256": meta.get("payload_sha256") or "",
                    "kind": meta.get("kind") or "?"}
    return out


def compute_release_id(parent, code, flags, ladder, entries):
    """Content address over everything that makes the release what it
    is (created/label/env are provenance, not identity)."""
    ident = {"format": bank.BANK_FORMAT, "parent": parent, "code": code,
             "flags": flags, "ladder": ladder, "entries": entries}
    return hashlib.sha256(_canonical(ident)).hexdigest()[:12]


def sign_manifest(man):
    """``manifest_sha256`` over the canonical body minus the signature
    itself; returns the signed manifest."""
    body = {k: v for k, v in man.items() if k != "manifest_sha256"}
    man["manifest_sha256"] = hashlib.sha256(_canonical(body)).hexdigest()
    return man


def build_manifest(entries, code, flags, parent=None, label=None):
    """The release-manifest record (schema family
    ``release-manifest``)."""
    ladder = ladder_state()
    man = {
        "schema": MANIFEST_SCHEMA,
        "release": compute_release_id(parent, code, flags, ladder,
                                      entries),
        "created": time.time(),
        "label": str(label or ""),
        "parent": parent,
        "bank_format": bank.BANK_FORMAT,
        "code": code,
        "flags": flags,
        "ladder": ladder,
        "env": capture_env(),
        "entries": dict(entries),
        "n_entries": len(entries),
        "manifest_sha256": "",  # filled by sign_manifest below
    }
    return sign_manifest(man)


def cut(label=None, flags_fp=None, promote_after=False):
    """Cut a release from the current bank snapshot; returns the
    written manifest.  ``flags_fp`` defaults to the live serving
    flags fingerprint (:func:`raft_tpu.serve.engine.
    flags_fingerprint` — imports jax; pass one explicitly to stay
    jax-free).  Cutting an identical state twice is idempotent: same
    id, same file."""
    if flags_fp is None:
        from raft_tpu.serve import engine

        flags_fp = engine.flags_fingerprint()
    entries = snapshot_entries()
    man = build_manifest(entries, bank.code_fingerprint(), str(flags_fp),
                         parent=current_release(), label=label)
    fsops.makedirs(releases_dir(), exist_ok=True)
    fsops.write_atomic(
        manifest_path(man["release"]),
        json.dumps(man, indent=1, sort_keys=True) + "\n")
    log_event("release_cut", release=man["release"], parent=man["parent"],
              entries=man["n_entries"], label=man["label"] or None)
    if promote_after:
        promote(man["release"])
    return man


# ------------------------------------------------------------------ load


def load_manifest(path):
    """Parse one manifest file; None when missing/garbled (a reader
    must never crash on a foreign file)."""
    try:
        man = json.loads(fsops.read_text(path))
    except (OSError, ValueError):
        return None
    return man if isinstance(man, dict) else None


def load_release(release_id, aot_dir=None):
    return load_manifest(manifest_path(release_id, aot_dir))


def list_releases(aot_dir=None):
    """Every readable manifest under releases/, newest first."""
    d = releases_dir(aot_dir)
    out = []
    try:
        names = sorted(fsops.listdir(d))
    except OSError:
        return out
    for name in names:
        if not name.endswith(".json") or name in ("current.json",
                                                  "rollout.json"):
            continue
        man = load_manifest(os.path.join(d, name))
        if man is not None and man.get("release") == name[:-5]:
            out.append(man)
    out.sort(key=lambda m: m.get("created") or 0, reverse=True)
    return out


def current_release(aot_dir=None):
    """The id the ``current`` pointer names, or None."""
    try:
        rec = json.loads(fsops.read_text(current_path(aot_dir)))
        return str(rec["release"]) if isinstance(rec, dict) else None
    except (OSError, ValueError, KeyError):
        return None


def resolve(aot_dir=None):
    """``(release_id, manifest)`` through the current pointer —
    what a replica resolves at warmup — or ``(None, None)`` when no
    release infrastructure is in use (pointer-less banks keep
    working: releases are opt-in)."""
    rid = current_release(aot_dir)
    if rid is None:
        return None, None
    return rid, load_release(rid, aot_dir)


# ---------------------------------------------------------------- verify


def verify_manifest(man):
    """Pure integrity problems of one manifest (no bank access): the
    schema, the self-signature, and the content address must all
    hold.  The lint.sh fixture gate runs exactly this."""
    problems = []
    if not isinstance(man, dict) or man.get("schema") != MANIFEST_SCHEMA:
        return [f"not a {MANIFEST_SCHEMA} manifest"]
    for k in ("release", "code", "flags", "ladder", "entries",
              "manifest_sha256", "bank_format"):
        if k not in man:
            problems.append(f"missing required key {k!r}")
    if problems:
        return problems
    body = {k: v for k, v in man.items() if k != "manifest_sha256"}
    want = hashlib.sha256(_canonical(body)).hexdigest()
    if man["manifest_sha256"] != want:
        problems.append("manifest_sha256 mismatch (tampered or "
                        "hand-edited manifest)")
    rid = compute_release_id(man.get("parent"), man["code"], man["flags"],
                             man["ladder"], man["entries"])
    if man["release"] != rid:
        problems.append(f"release id {man['release']} does not match "
                        f"its content (expect {rid})")
    if man["bank_format"] != bank.BANK_FORMAT:
        problems.append(f"bank format {man['bank_format']} != "
                        f"{bank.BANK_FORMAT} (foreign toolchain)")
    return problems


def verify_against_bank(man):
    """Problems of a release vs the live bank directory: every
    manifest entry must exist with its exact payload sha (a release
    whose objects were gc'd or rewritten cannot be served)."""
    problems = []
    for key, ent in sorted((man.get("entries") or {}).items()):
        meta = bank.read_meta(key)
        if meta is None:
            problems.append(f"{key}: bank entry missing/unreadable "
                            "(gc'd from under the release?)")
            continue
        if meta.get("payload_sha256") != ent.get("payload_sha256"):
            problems.append(f"{key}: bank payload sha differs from the "
                            "manifest (entry rewritten after the cut)")
    return problems


def walk_parents(release_id, aot_dir=None, max_depth=64):
    """The parent chain starting at ``release_id`` (inclusive), oldest
    last; cycles/missing parents just end the walk."""
    chain, seen = [], set()
    rid = release_id
    while rid and rid not in seen and len(chain) < max_depth:
        seen.add(rid)
        man = load_release(rid, aot_dir)
        if man is None:
            break
        chain.append(man)
        rid = man.get("parent")
    return chain


# --------------------------------------------------------------- pointer


def promote(release_id, aot_dir=None):
    """Flip ``current`` to ``release_id`` (atomic rename — a reader
    sees the old pointer or the new one, never a torn write).
    Returns the previous id.  The manifest must exist and verify."""
    man = load_release(release_id, aot_dir)
    if man is None:
        raise FileNotFoundError(
            f"no release {release_id!r} under {releases_dir(aot_dir)} "
            "(cut it first: python -m raft_tpu.aot release cut)")
    problems = verify_manifest(man)
    if problems:
        raise ValueError(f"refusing to promote {release_id}: "
                         + "; ".join(problems))
    previous = current_release(aot_dir)
    fsops.makedirs(releases_dir(aot_dir), exist_ok=True)
    fsops.write_atomic(
        current_path(aot_dir),
        json.dumps({"release": str(release_id), "t": time.time()}) + "\n")
    log_event("release_promote", release=str(release_id),
              previous=previous)
    return previous


def rollback(aot_dir=None):
    """Re-point ``current`` at the current release's parent.  Returns
    ``(from_id, to_id)``."""
    rid = current_release(aot_dir)
    if rid is None:
        raise FileNotFoundError("no current release to roll back from")
    man = load_release(rid, aot_dir)
    parent = (man or {}).get("parent")
    if not parent:
        raise ValueError(f"release {rid} has no parent to roll back to")
    promote(parent, aot_dir)
    log_event("release_rollback", release=rid, to=parent)
    return rid, parent


# --------------------------------------------------------- rollout marker


def write_rollout_marker(from_id, to_id, aot_dir=None):
    """Mark a rolling upgrade in progress: BOTH releases are
    legitimate fleet members until the marker clears — the canary's
    provenance-consistency check reads this window."""
    fsops.makedirs(releases_dir(aot_dir), exist_ok=True)
    fsops.write_atomic(
        rollout_marker_path(aot_dir),
        json.dumps({"from": from_id, "to": to_id, "t": time.time()}) + "\n")


def read_rollout_marker(aot_dir=None):
    try:
        rec = json.loads(fsops.read_text(rollout_marker_path(aot_dir)))
        return rec if isinstance(rec, dict) else None
    except (OSError, ValueError):
        return None


def clear_rollout_marker(aot_dir=None):
    try:
        fsops.unlink(rollout_marker_path(aot_dir))
        return True
    except OSError:
        return False


_PARITY_LOCK = threading.Lock()
#: (aot_dir, computed_t, value) — the canary calls parity_context per
#: probe observation; 1s of staleness is fine, per-probe file IO is not
_PARITY_CACHE: list = []  # raft-lint: guarded-by=_PARITY_LOCK


def parity_context(aot_dir=None, ttl_s=1.0, now=None):
    """The release view the provenance-consistency check needs:
    ``{"allowed": [release ids legitimately in the fleet], "entries":
    {release_id: [16-char payload sha prefixes]}}`` — or None when no
    release infrastructure is present (the pre-release behavior).
    Mid-rollout the marker's from/to are BOTH allowed; otherwise only
    ``current`` is.  Cached ~1s: called per canary observation."""
    aot_dir = aot_dir or config.get("AOT_DIR")
    now = time.monotonic() if now is None else now
    with _PARITY_LOCK:
        if _PARITY_CACHE and _PARITY_CACHE[0] == aot_dir \
                and now - _PARITY_CACHE[1] < ttl_s:
            return _PARITY_CACHE[2]
    rid = current_release(aot_dir)
    value = None
    if rid is not None:
        allowed = {rid}
        marker = read_rollout_marker(aot_dir)
        if marker:
            allowed |= {str(v) for v in (marker.get("from"),
                                         marker.get("to")) if v}
        entries = {}
        for r in sorted(allowed):
            man = load_release(r, aot_dir)
            if man is not None:
                entries[r] = sorted(
                    {str(e.get("payload_sha256") or "")[:16]
                     for e in (man.get("entries") or {}).values()})
        value = {"allowed": sorted(allowed), "entries": entries}
    with _PARITY_LOCK:
        _PARITY_CACHE[:] = [aot_dir, now, value]
    return value


# ------------------------------------------------------------- diagnosis


def classify_mismatch(man, code, flags, ladder):
    """WHY a live process misses a release's bank entries, in key-
    component precedence order: a code edit re-keys everything (check
    first), then a trace-flag flip, then a ladder retune; ``avals``
    is the remainder (design set / out_keys / batch-shape drift)."""
    if man.get("code") != code:
        return "code"
    if man.get("flags") != flags:
        return "flags"
    if {k: man.get("ladder", {}).get(k) for k in LADDER_FLAG_NAMES} \
            != {k: ladder.get(k) for k in LADDER_FLAG_NAMES}:
        return "ladder"
    return "avals"


def diagnose(entries, mesh=None, out_keys=None, sizes=None,
             manifest=None):
    """Bank-warmth report of the live design set vs a release: for
    every (design x ladder rung) program, is it banked — and when not,
    WHICH key component drifted from the manifest.  Imports jax (the
    program identities are real bank keys).  Returns ``{"release",
    "total", "warmed", "unwarmed": [{design, rows, key, reason}],
    "reason"}``."""
    from raft_tpu.parallel.sweep import make_mesh
    from raft_tpu.serve import engine

    if mesh is None:
        mesh = make_mesh()
    out_keys = engine.normalize_out_keys(out_keys)
    sizes = tuple(sizes) if sizes else engine.batch_ladder(mesh)
    man = manifest or {}
    reason = classify_mismatch(man, bank.code_fingerprint(),
                               engine.flags_fingerprint(),
                               ladder_state()) if man else None
    unwarmed, total = [], 0
    for entry in entries:
        for rows in sizes:
            total += 1
            try:
                key, side = engine.program_identity(
                    entry, mesh=mesh, out_keys=out_keys, rows=rows)
            except Exception:  # noqa: BLE001 — diagnosis is telemetry
                key, side = None, None
            if side is not None:
                continue
            why = reason or "avals"
            if man and key and key in (man.get("entries") or {}):
                why = "bank-missing"  # manifest promises it; bank lost it
            unwarmed.append({"design": entry.name, "rows": int(rows),
                             "key": key, "reason": why})
    report = {"release": man.get("release"), "total": total,
              "warmed": total - len(unwarmed), "unwarmed": unwarmed,
              "reason": (reason if unwarmed else None)}
    log_event("release_preflight", release=report["release"],
              unwarmed=len(unwarmed), total=total,
              reason=report["reason"])
    return report


def warmup_command(design_paths, x64=False):
    """The exact re-warm command a failed preflight prints."""
    cmd = "python -m raft_tpu.aot warmup --kinds serve"
    for p in design_paths:
        cmd += f" --design {p}"
    if x64:
        cmd += " --x64"
    return cmd


_REASON_HELP = {
    "code": "the raft_tpu source changed since the release was cut "
            "(every bank key embeds the code fingerprint)",
    "flags": "trace-time RAFT_TPU_* flags differ from the release "
             "(SOLVER/DTYPE/ITER_SCALE/... are part of every key)",
    "ladder": "the batch ladder changed (SERVE_LADDER/SERVE_MAX_BATCH/"
              "BUCKET_* retune re-keys the serve programs — cut a new "
              "release and roll it out instead of re-warming by hand)",
    "avals": "the design set / out_keys / batch shapes differ from "
             "what was warmed",
    "bank-missing": "the manifest promises this entry but the bank "
                    "directory lost it (gc'd or deleted?)",
}


def format_diagnosis(report, design_paths=(), x64=False):
    """Human lines for a failed preflight: which programs are cold,
    why, and the exact command that fixes it."""
    lines = []
    rel = report.get("release")
    head = (f"release {rel}" if rel else "bank (no release manifest)")
    lines.append(f"bank preflight vs {head}: "
                 f"{len(report['unwarmed'])}/{report['total']} serve "
                 "program(s) UNWARMED")
    for row in report["unwarmed"]:
        lines.append(f"  {row['design']} x rows={row['rows']}: "
                     f"{row['reason']} (key {row['key']})")
    reasons = {row["reason"] for row in report["unwarmed"]}
    for r in sorted(reasons):
        if r in _REASON_HELP:
            lines.append(f"  why [{r}]: {_REASON_HELP[r]}")
    if design_paths:
        lines.append("warm the bank, then cut + promote a release:")
        lines.append(f"  {warmup_command(design_paths, x64=x64)}")
        lines.append("  python -m raft_tpu.aot release cut --promote")
    return lines
