"""MoorDyn file-format parsing: v1 vs v2 line-type column order.

MoorDyn v2 line-type rows carry 10 columns
(Name Diam Mass/m EA BA/-zeta EI Cd Ca CdAx CaAx); v1 rows carry 9 with
the hydro coefficients added-mass-first (Name Diam MassDen EA BA/-zeta
Can Cat Cdn Cdt).  Mapping v1 rows through the v2 positions silently
swaps Cd<->Ca in the moorMod 1/2 dynamic-tension/impedance paths, so
the parser must detect the format by column count (reference consumes
these via MoorPy System.load, raft_fowt.py:359-370).
"""

import numpy as np
import pytest

from raft_tpu.physics.mooring import parse_moordyn_system

HEADER = """--------------------- MoorDyn Input File -------------------
---------------------- LINE TYPES -----------------------------
Name     Diam    MassDen   EA        BA/-zeta  {typecols}
(name)   (m)     (kg/m)    (N)       (N-s/-)   {typeunits}
{typerow}
---------------------- POINTS ---------------------------------
ID  Attachment  X       Y      Z     M  V  CdA Ca
(#) (-)         (m)     (m)    (m)  (kg) (m3) (m2) (-)
1   Fixed      -837.6   0.0   -200.0  0  0  0  0
2   Vessel     -58.0    0.0   -14.0   0  0  0  0
---------------------- LINES ----------------------------------
ID  LineType  AttachA  AttachB  UnstrLen  NumSegs Outputs
(#) (name)    (#)      (#)      (m)       (-)     (-)
1   chain     1        2        850.0     40      -
---------------------- OPTIONS --------------------------------
0.001  dtM
"""

V2 = HEADER.format(
    typecols="EI     Cd    Ca    CdAx   CaAx",
    typeunits="(N-m^2) (-)  (-)   (-)    (-)",
    typerow="chain   0.333   685.0   3.27e9    -1.0      0.0    1.1   0.82  0.21   0.27")

V1 = HEADER.format(
    typecols="Can   Cat    Cdn   Cdt",
    typeunits="(-)   (-)    (-)   (-)",
    typerow="chain   0.333   685.0   3.27e9    -1.0      0.82   0.27  1.1   0.21")

AMBIG = HEADER.format(
    typecols="Cd    Ca",
    typeunits="(-)   (-)",
    typerow="chain   0.333   685.0   3.27e9    -1.0      1.1    0.82")


def _write(tmp_path, name, text):
    p = tmp_path / name
    p.write_text(text)
    return str(p)


def test_v2_columns(tmp_path):
    ms = parse_moordyn_system(_write(tmp_path, "v2.dat", V2), depth=200.0)
    assert np.allclose(ms.Cd, 1.1)
    assert np.allclose(ms.Ca, 0.82)
    assert np.allclose(ms.CdAx, 0.21)
    assert np.allclose(ms.CaAx, 0.27)


def test_v1_columns_same_physics(tmp_path):
    """The v1 file above carries the SAME physical coefficients as the
    v2 one (Can=Ca, Cat=CaAx, Cdn=Cd, Cdt=CdAx) — the parsed system
    must be identical."""
    ms1 = parse_moordyn_system(_write(tmp_path, "v1.dat", V1), depth=200.0)
    ms2 = parse_moordyn_system(_write(tmp_path, "v2.dat", V2), depth=200.0)
    for attr in ("Cd", "Ca", "CdAx", "CaAx", "L", "w", "EA", "m_lin",
                 "d_vol"):
        np.testing.assert_allclose(getattr(ms1, attr), getattr(ms2, attr),
                                   err_msg=attr)


def test_ambiguous_column_count_raises(tmp_path):
    with pytest.raises(ValueError, match="ambiguous line-type row"):
        parse_moordyn_system(_write(tmp_path, "amb.dat", AMBIG), depth=200.0)
