"""CLI for the always-on evaluation service + the serving fleet.

Single server (a fleet **replica** when ``--fleet-dir`` is set)::

    python -m raft_tpu.serve --designs spar=raft_tpu/designs/spar_demo.yaml \
        [--designs semi=...] [--host 127.0.0.1] [--port 8787] \
        [--out-keys PSD,X0,status] [--no-warm] [--platform cpu] [--x64] \
        [--fleet-dir DEPLOY_DIR] [--replica-id r0]

Startup order is the serving contract: build + pack every registered
design, WARM every (bucket x batch-ladder) program through the AOT
bank (:func:`raft_tpu.serve.engine.warm`), and only then bind the
socket — a client can never reach a server that would trace on its
request.  Under ``RAFT_TPU_AOT=require`` a cold bank fails here, at
startup, not mid-request; fill it first with

    python -m raft_tpu.aot warmup --kinds serve --design <yaml>

With ``--fleet-dir`` the server additionally JOINS the serving fleet:
after the socket binds it claims a membership lease in the
``_fleet/`` ledger (port + bucket signatures + health snapshot in the
lease body), renews it from a daemon thread, and releases it at drain
START — see :mod:`raft_tpu.serve.fleet`.

Fleet coordinator (N replicas warmed from the SAME bank)::

    python -m raft_tpu.serve fleet --replicas 2 --fleet-dir DEPLOY_DIR \
        --designs spar=... [--warm-bank] [--no-warm] [--status]

Failover router (the one endpoint clients talk to)::

    python -m raft_tpu.serve router --fleet-dir DEPLOY_DIR --port 8788

Canary-gated rolling upgrade to a cut release (automatic rollback on
a red canary or firing alert — see :mod:`raft_tpu.serve.rollout`)::

    python -m raft_tpu.serve rollout --fleet-dir DEPLOY_DIR \
        --to RELEASE_ID --designs spar=... \
        [--router-url http://127.0.0.1:8788]

``--port 0`` binds an ephemeral port; the ready line on stdout
(``serving N design(s) on http://host:port ...`` / ``routing N
replica(s) ...``) reports the actual one (load harnesses parse it).
SIGTERM/SIGINT drains gracefully: in-flight requests finish, new work
gets 503, metrics flush to ``RAFT_TPU_METRICS``.

Tuning flags (see ``python -m raft_tpu.analysis flags``):
``RAFT_TPU_SERVE_*`` for replicas, ``RAFT_TPU_ROUTER_*`` for the
failover ladder, ``RAFT_TPU_FLEET_*`` for membership leases.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import uuid


def _parse_designs(specs):
    """``name=path`` (or bare path — name = file stem) from repeated /
    comma-separated ``--designs`` values."""
    out = {}
    for spec in specs:
        for item in spec.split(","):
            item = item.strip()
            if not item:
                continue
            if "=" in item:
                name, path = item.split("=", 1)
            else:
                name = os.path.splitext(os.path.basename(item))[0]
                path = item
            out[name.strip()] = path.strip()
    return out


def _default_fleet_dir(value):
    from raft_tpu.utils import config

    return value if value is not None else (config.get("FLEET_DIR") or None)


def _serve_main(argv):
    ap = argparse.ArgumentParser(prog="python -m raft_tpu.serve")
    ap.add_argument("--designs", action="append", required=True,
                    help="name=design.yaml (repeatable / comma list)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8787,
                    help="0 binds an ephemeral port (see the ready line)")
    ap.add_argument("--out-keys", default=",".join(
        ("PSD", "X0", "status")),
        help="out_keys this server dispatches (requests may ask for "
             "subsets; 'status' is always included)")
    ap.add_argument("--no-warm", action="store_true",
                    help="skip the pre-bind warmup (first requests pay "
                         "the trace/compile; testing only)")
    ap.add_argument("--platform", default=None,
                    help="jax platform pin (default: RAFT_TPU_CLI_PLATFORM)")
    ap.add_argument("--x64", action="store_true",
                    help="serve under jax_enable_x64 (warm the bank with "
                         "--x64 too — x64 is part of the bank key)")
    ap.add_argument("--fleet-dir", default=None,
                    help="join the serving fleet whose _fleet/ ledger "
                         "lives under this directory (default: "
                         "RAFT_TPU_FLEET_DIR when set)")
    ap.add_argument("--replica-id", default=None,
                    help="fleet replica id (default: a fresh unique id)")
    ap.add_argument("--takeover", action="store_true",
                    help="SEIZE the replica id's existing fleet lease "
                         "after warmup+bind instead of claiming fresh "
                         "(the rolling-upgrade replacement path: same "
                         "rid keeps the same ring vnodes; the previous "
                         "owner is then drained by the rollout driver)")
    args = ap.parse_args(argv)

    from raft_tpu.utils import config

    platform = (args.platform if args.platform is not None
                else config.get("CLI_PLATFORM"))
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    if args.x64:
        jax.config.update("jax_enable_x64", True)

    from raft_tpu.aot import bank as bank_mod
    from raft_tpu.aot import release as release_mod
    from raft_tpu.serve import engine
    from raft_tpu.serve import fleet as fleet_mod
    from raft_tpu.serve.batcher import Batcher
    from raft_tpu.serve.http import run_server
    from raft_tpu.structure.bucketing import signature_fingerprint
    from raft_tpu.utils.devices import enable_compile_cache
    from raft_tpu.utils.structlog import log_event

    enable_compile_cache()
    registry = engine.Registry()
    designs = _parse_designs(args.designs)
    if not designs:
        print("no designs registered (--designs name=path)", file=sys.stderr)
        return 2
    for name, path in designs.items():
        entry = registry.register(name, path)
        print(f"registered {name}: bucket "
              f"{signature_fingerprint(entry.sig)}", flush=True)

    # resolve the bank through the release pointer FIRST: the resolved
    # id is stamped into every provenance header, and a warmup miss is
    # diagnosed against this release's manifest (releases are opt-in —
    # a pointer-less bank serves exactly as before)
    cur_release, cur_manifest = release_mod.resolve()
    if cur_release:
        log_event("release_resolve", release=cur_release,
                  root=release_mod.releases_dir())
        print(f"release: {cur_release}", flush=True)

    # the replica id is fixed BEFORE anything starts: the provenance
    # stamp, the fleet lease, and the latency exemplars on /metrics
    # must all name the same replica
    rid = args.replica_id or f"replica-{uuid.uuid4().hex[:8]}"

    out_keys = tuple(k.strip() for k in args.out_keys.split(",") if k.strip())
    batcher = Batcher(registry, out_keys=out_keys, replica_id=rid)
    if not args.no_warm:
        try:
            reports = engine.warm(
                [registry.get(n) for n in registry.names()],
                mesh=batcher.mesh, out_keys=batcher.out_keys,
                sizes=batcher.sizes)
        except bank_mod.BankMissError:
            # RAFT_TPU_AOT=require on a cold/stale bank: die with the
            # full preflight diagnosis (which programs, which key
            # component drifted, the exact re-warm command) instead of
            # one opaque bank key
            report = release_mod.diagnose(
                [registry.get(n) for n in registry.names()],
                mesh=batcher.mesh, out_keys=batcher.out_keys,
                sizes=batcher.sizes, manifest=cur_manifest)
            for line in release_mod.format_diagnosis(
                    report, sorted(designs.values()), x64=args.x64):
                print(line, file=sys.stderr)
            return 3
        loaded = sum(r["loaded"] for r in reports)
        compiled = sum(r["compiled"] for r in reports)
        wall = sum(r["wall_s"] for r in reports)
        print(f"warmup: {len(reports)} program(s) "
              f"({loaded} bank-loaded, {compiled} compiled) in {wall:.1f}s",
              flush=True)
        # cost-driven ladder refinement (RAFT_TPU_SERVE_LADDER=cost):
        # the warmup dispatches just measured every candidate rung's
        # wall through the cost ledger — prune the flat rungs so the
        # serving ladder only keeps rungs that buy latency (every kept
        # rung was warmed above; a no-warm server keeps the candidates)
        refined = engine.refine_ladder(
            [registry.get(n) for n in registry.names()],
            batcher.sizes, mesh=batcher.mesh, out_keys=batcher.out_keys)
        if tuple(refined) != tuple(batcher.sizes):
            print(f"batch ladder refined {list(batcher.sizes)} -> "
                  f"{list(refined)} (cost-flat rungs pruned)", flush=True)
            batcher.set_sizes(refined)

    # provenance stamps (x-raft-provenance on every /evaluate
    # response): bank key + sidecar sha per design, code hash, flags
    # key, replica id — computed once here, a dict lookup per request
    provenance = engine.build_provenance(
        registry, mesh=batcher.mesh, out_keys=batcher.out_keys,
        sizes=batcher.sizes, replica_id=rid)
    if float(config.get("CANARY_S") or 0) > 0:
        # golden capture at warmup: one banked dispatch per design at
        # the canary case (programs are already warm) — the replica's
        # own golden rows, reported at GET /alerts
        from raft_tpu.serve import canary as canary_mod

        state = canary_mod.capture_goldens(
            [registry.get(n) for n in registry.names()],
            mesh=batcher.mesh, out_keys=batcher.out_keys)
        print(f"canary: captured {state.summary()['goldens']} golden "
              "row(s)", flush=True)
    # in-process alert evaluator (RAFT_TPU_ALERT_EVAL_S > 0; served at
    # GET /alerts) — no flag, no thread
    from raft_tpu.obs import alerts as alerts_mod

    alerts_mod.maybe_start()

    fleet_root = _default_fleet_dir(args.fleet_dir)
    fleet_state = {}

    def ready(server):
        print(f"serving {len(registry)} design(s) on "
              f"http://{server.host}:{server.port} "
              f"(tick {batcher.tick_s * 1e3:.0f}ms, "
              f"batch ladder {list(batcher.sizes)})", flush=True)
        if not fleet_root:
            return
        # join the fleet only AFTER warmup + bind: the router must
        # never route to a replica that would trace on the request
        # (rid was fixed above, shared with the provenance stamp)
        ledger = fleet_mod.FleetLedger(fleet_root, replica_id=rid)
        meta = {}
        for name in registry.names():
            e = registry.get(name)
            meta[name] = {"sig": signature_fingerprint(e.sig),
                          "fingerprint": e.fingerprint}

        def healthz():
            s = batcher.stats()
            # busy_s: cumulative on-device wall across every banked
            # program — the autoscaler derives fleet occupancy from
            # lease-to-lease deltas of this
            busy = sum(float(r.get("wall_s") or 0)
                       for r in bank_mod.ledger_summary())
            return {"draining": bool(s["draining"]),
                    "pending": int(s["pending"]),
                    "cache": s["cache"],
                    "busy_s": round(busy, 4)}

        buckets = sorted({m["sig"] for m in meta.values()})
        served_keys = list(batcher.out_keys)
        if args.takeover:
            # rolling-upgrade replacement: unconditionally take the
            # lease over (same rid = same ring vnodes — zero key
            # movement); the rollout driver drains the previous owner
            # only after this succeeds, so membership never gaps
            ledger.seize(server.port, host=server.host, designs=meta,
                         buckets=buckets, healthz=healthz(),
                         out_keys=served_keys)
        elif not ledger.claim(server.port, host=server.host, designs=meta,
                              buckets=buckets, healthz=healthz(),
                              out_keys=served_keys):
            # a lease already exists under this forced id.  Only a
            # crashed predecessor's EXPIRED lease may be evicted — a
            # live one means another replica is serving under this id
            # right now, and hijacking it would silently knock that
            # replica out of the ring (its renewer fails token checks
            # and never re-claims)
            rec, mtime = ledger.read(rid)
            ttl = float((rec or {}).get("ttl_s")
                        or config.get("FLEET_TTL_S"))
            age = (fleet_mod.FleetLedger.lease_age(rec, mtime)
                   if rec is not None else float("inf"))
            if rec is None or age > ttl:
                ledger.evict(rid, reason="stale_self", age_s=age)
                if not ledger.claim(server.port, host=server.host,
                                    designs=meta, buckets=buckets,
                                    healthz=healthz(),
                                    out_keys=served_keys):
                    # lost the re-claim race to a same-id twin: joining
                    # anyway would start a renewer that no-ops forever
                    print(f"fleet: NOT joining {fleet_root} — lost the "
                          f"claim race for {rid!r} (serving standalone)",
                          file=sys.stderr)
                    return
            else:
                print(f"fleet: NOT joining {fleet_root} — the lease for "
                      f"{rid!r} is LIVE (age {age:.1f}s <= ttl {ttl:.1f}s); "
                      "another replica is serving under this id.  Pick a "
                      "different --replica-id (serving standalone).",
                      file=sys.stderr)
                return
        renewer = fleet_mod.LeaseRenewer(ledger, healthz=healthz)
        renewer.start()
        fleet_state.update(ledger=ledger, renewer=renewer)
        print(f"fleet: joined {fleet_root} as {rid}", flush=True)

    def on_drain_start():
        # release the membership lease at drain START (executor
        # thread): the router stops routing here while accepted work
        # finishes — the whole point of drain = release
        renewer = fleet_state.get("renewer")
        if renewer is not None:
            renewer.stop()
        ledger = fleet_state.get("ledger")
        if ledger is not None:
            ledger.release(reason="drain")

    asyncio.run(run_server(batcher, host=args.host, port=args.port,
                           ready=ready, on_drain_start=on_drain_start,
                           provenance=provenance))
    alerts_mod.stop()
    return 0


def _fleet_main(argv):
    ap = argparse.ArgumentParser(prog="python -m raft_tpu.serve fleet")
    ap.add_argument("--fleet-dir", default=None, required=False,
                    help="fleet deploy directory (the _fleet/ ledger "
                         "root; default: RAFT_TPU_FLEET_DIR)")
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--designs", action="append", default=[],
                    help="name=design.yaml, forwarded to every replica")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--out-keys", default=None,
                    help="forwarded to every replica")
    ap.add_argument("--no-warm", action="store_true",
                    help="replicas skip their pre-bind warmup")
    ap.add_argument("--warm-bank", action="store_true",
                    help="warm the shared AOT bank ONCE in this process "
                         "before spawning (pay the compile bill once; "
                         "replicas then start under RAFT_TPU_AOT=require "
                         "with zero backend compiles)")
    ap.add_argument("--status", action="store_true",
                    help="print the ledger summary as JSON and exit")
    args = ap.parse_args(argv)

    from raft_tpu.serve import fleet as fleet_mod

    root = _default_fleet_dir(args.fleet_dir)
    if not root:
        print("--fleet-dir (or RAFT_TPU_FLEET_DIR) is required",
              file=sys.stderr)
        return 2
    if args.status:
        print(json.dumps(fleet_mod.FleetLedger(root).summary(), indent=1,
                         default=str))
        return 0
    if not args.designs:
        print("no designs (--designs name=path)", file=sys.stderr)
        return 2
    extra = []
    if args.no_warm:
        extra.append("--no-warm")
    if args.out_keys:
        extra += ["--out-keys", args.out_keys]

    def on_ready(ports):
        print(f"fleet ready: {len(ports)} replica(s) at "
              + " ".join(f"{rid}=http://{args.host}:{p}"
                         for rid, p in ports.items()), flush=True)

    return fleet_mod.run_fleet(root, args.replicas, args.designs,
                               host=args.host, extra_args=extra,
                               warm_bank=args.warm_bank,
                               on_ready=on_ready)


def _router_main(argv):
    ap = argparse.ArgumentParser(prog="python -m raft_tpu.serve router")
    ap.add_argument("--fleet-dir", default=None,
                    help="fleet deploy directory (default: "
                         "RAFT_TPU_FLEET_DIR)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8788,
                    help="0 binds an ephemeral port (see the ready line)")
    ap.add_argument("--designs", action="append", default=[],
                    help="name=design.yaml forwarded to replicas the "
                         "AUTOSCALER spawns (RAFT_TPU_AUTOSCALE_EVAL_S "
                         "> 0 enables the scaling daemon; without "
                         "designs it can only scale in)")
    args = ap.parse_args(argv)

    from raft_tpu.obs import alerts as alerts_mod
    from raft_tpu.serve.router import run_router
    from raft_tpu.utils import config

    root = _default_fleet_dir(args.fleet_dir)
    if not root:
        print("--fleet-dir (or RAFT_TPU_FLEET_DIR) is required",
              file=sys.stderr)
        return 2
    # the router runs the fleet-level alert evaluator: its registry
    # carries the ladder/breaker/membership/canary counters the
    # default rule pack watches (RAFT_TPU_ALERT_EVAL_S > 0; served at
    # GET /alerts)
    alerts_mod.maybe_start()
    scaler = None
    if float(config.get("AUTOSCALE_EVAL_S") or 0) > 0:
        from raft_tpu.serve import autoscale as autoscale_mod

        scaler = autoscale_mod.Autoscaler(root, args.designs)
        scaler.start()
        print(f"autoscale: [{scaler.minimum}, {scaler.maximum}] "
              f"replicas every {scaler.interval_s}s", flush=True)

    def ready(router):
        snap = router.state.snapshot()
        print(f"routing {snap['n_replicas']} replica(s) on "
              f"http://{router.host}:{router.port} "
              f"(fleet {root})", flush=True)

    asyncio.run(run_router(root, host=args.host, port=args.port,
                           ready=ready))
    if scaler is not None:
        scaler.stop()
    alerts_mod.stop()
    return 0


def _rollout_main(argv):
    ap = argparse.ArgumentParser(prog="python -m raft_tpu.serve rollout")
    ap.add_argument("--fleet-dir", default=None,
                    help="fleet deploy directory (default: "
                         "RAFT_TPU_FLEET_DIR)")
    ap.add_argument("--to", required=True,
                    help="candidate release id (cut + verified; the "
                         "driver promotes it, then surf-replaces the "
                         "fleet replica by replica)")
    ap.add_argument("--designs", action="append", default=[],
                    help="name=design.yaml forwarded to the upgraded "
                         "replicas")
    ap.add_argument("--router-url", default=None,
                    help="router base URL whose GET /alerts gates each "
                         "step (canary verdicts + active alerts); "
                         "omitting it skips the canary gate — testing "
                         "only")
    args = ap.parse_args(argv)

    from raft_tpu.serve import rollout as rollout_mod

    root = _default_fleet_dir(args.fleet_dir)
    if not root:
        print("--fleet-dir (or RAFT_TPU_FLEET_DIR) is required",
              file=sys.stderr)
        return 2
    if not args.designs:
        print("no designs (--designs name=path)", file=sys.stderr)
        return 2
    record = rollout_mod.run_rollout(root, args.to, args.designs,
                                     router_url=args.router_url)
    print(json.dumps(record, indent=1, default=str))
    print(rollout_mod.summarize_record(record), flush=True)
    return 0 if record.get("ok") else 1


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "fleet":
        return _fleet_main(argv[1:])
    if argv and argv[0] == "router":
        return _router_main(argv[1:])
    if argv and argv[0] == "rollout":
        return _rollout_main(argv[1:])
    return _serve_main(argv)


if __name__ == "__main__":
    sys.exit(main())
