"""Registry of every structured-log event name the framework emits.

A typo'd event name does not crash anything — it silently splits an
event stream in two, and every consumer (the report CLI, the Chrome
trace exporter, a grep) sees only half the story.  This module is the
single source of truth: every ``log_event("<name>", ...)`` call site in
the package must use a name registered here, enforced by the
``event-name`` rule of the trace-hygiene linter
(:mod:`raft_tpu.analysis.lint`) and gated by ``lint.sh``.

Each entry maps the event name to its schema: the payload fields the
emitter promises (beyond the universal stamps ``t``/``event``/``pid``/
``run_id`` and, inside a span, ``trace_id``/``span_id`` — see
:mod:`raft_tpu.utils.structlog`) and a one-line description.  The
README "Observability" event table renders from :func:`describe`.

Pure stdlib — the linter and the report/trace CLIs import this without
touching a jax backend.
"""

from __future__ import annotations

#: name -> (fields tuple, help).  Fields are the emitter's documented
#: payload keys; optional keys are suffixed with ``?``.
EVENTS: dict[str, tuple[tuple[str, ...], str]] = {
    # ------------------------------------------------------------ telemetry
    "proc_start": (
        ("unix_t", "argv0"),
        "per-(process, sink) clock anchor: unix_t is the wall-clock "
        "instant of this record's monotonic t, letting `obs trace "
        "--merge` normalize every process onto one shared timeline"),
    "span_begin": (
        ("name", "parent_id", "remote_parent?", "links?"),
        "a telemetry span opened (obs.span); attrs ride along verbatim "
        "(remote_parent marks a root adopted from traceparent "
        "propagation; links name coalesced request spans)"),
    "span_end": (
        ("name", "wall_s", "ok", "error?"),
        "the matching span closed; error carries repr(exc) on failure"),
    "heartbeat": (
        ("devices", "live_arrays", "progress?", "worker_id?", "leases?",
         "windows?", "host_rss_bytes?", "host_rss_peak_bytes?"),
        "periodic device sampler: per-device memory_stats, live-buffer "
        "count, sweep shard progress (RAFT_TPU_HEARTBEAT_S); fabric "
        "workers add their id and currently-held shard leases; serving "
        "processes add the sliding-window latency snapshots; on Linux "
        "each beat also carries the host process RSS/high-watermark "
        "(/proc/self/status, no psutil)"),
    "metrics_snapshot": (
        ("snapshot",),
        "full metrics-registry snapshot (emitted at sweep_done; also "
        "written to <out_dir>/metrics.json)"),
    "profile_start": (
        ("dir",),
        "jax profiler capture started for a checkpointed sweep "
        "(RAFT_TPU_PROFILE)"),
    "profile_stop": (("dir",), "jax profiler capture finished"),
    "profile_failed": (
        ("error",),
        "jax profiler capture could not start/stop (logged, not fatal)"),
    # -------------------------------------------------------- sweep runtime
    "dp_autopad": (
        ("rows", "pad", "dp"),
        "a ragged batch was padded to dp-divisibility with masked "
        "repeat rows (dropped again on gather) — warning-level: the "
        "caller is paying for rows it did not ask for"),
    "bucket_sweep": (
        ("rows", "n_buckets", "n_designs", "padding_waste_frac",
         "waste_by_axis?"),
        "heterogeneous sweep dispatched: designs auto-binned into "
        "shape buckets, one compiled program per bucket; waste_by_axis "
        "decomposes the row-weighted padding waste per padded axis "
        "(strips/nodes/lines/rows)"),
    "sweep_start": (
        ("out_dir", "n_cases", "n_shards", "shard_size", "out_keys",
         "mesh_shape"),
        "checkpointed sweep began"),
    "sweep_done": (
        ("out_dir", "n_cases", "n_quarantined", "n_flagged", "wall_s"),
        "checkpointed sweep finished"),
    "shard_start": (("shard", "rows"), "shard evaluation began"),
    "shard_done": (("shard", "rows", "wall_s"), "shard written"),
    "shard_resume": (
        ("shard", "rows"), "shard loaded from a valid checkpoint file"),
    "shard_corrupt": (
        ("shard", "error"),
        "checkpoint shard failed validation and was re-queued"),
    "shard_retry": (
        ("shard", "attempt", "max_retries", "delay_s", "error"),
        "transient shard failure; retrying with backoff"),
    "shard_oom_split": (
        ("shard", "rows", "split", "error"),
        "device OOM; shard batch halved and re-evaluated"),
    "shard_quarantine": (
        ("shard", "index", "keys", "recovered", "status", "reason"),
        "a non-finite or status-flagged row was judged"),
    "shard_quarantine_retry_failed": (
        ("shard", "index", "error"),
        "the solo CPU re-evaluation of a quarantined row raised"),
    "shard_escalate": (
        ("shard", "index", "rung", "status_before", "status_after",
         "resolved"),
        "one escalation-ladder rung re-solved a flagged row"),
    "shard_escalate_failed": (
        ("shard", "index", "rung", "error"),
        "an escalation rung raised instead of returning a result"),
    # ------------------------------------------------------- sweep fabric
    "fabric_init": (
        ("out_dir", "n_cases", "n_shards", "shard_size", "entry"),
        "fabric sweep spec + case arrays + lease ledger initialized "
        "under <out_dir>/_fabric (raft_tpu.parallel.fabric)"),
    "fabric_worker_spawn": (
        ("out_dir", "worker", "pid"),
        "coordinator spawned one worker subprocess"),
    "fabric_worker_start": (
        ("out_dir", "worker", "n_shards", "programs_loaded",
         "programs_compiled", "warmup_s?"),
        "a fabric worker is ready to claim shards (after jax init, "
        "entry build and optional AOT-bank warmup; a mid-sweep joiner "
        "on a warmed bank must report programs_compiled=0)"),
    "fabric_worker_done": (
        ("out_dir", "worker", "shards_done", "shards_resumed", "rows",
         "wall_s", "programs_loaded", "programs_compiled"),
        "a fabric worker found the ledger drained and exited cleanly"),
    "fabric_worker_exit": (
        ("out_dir", "worker", "returncode"),
        "a spawned worker subprocess exited (nonzero returncode with "
        "the sweep incomplete means its leases will expire and be "
        "stolen)"),
    "shard_claim": (
        ("shard", "worker", "attempt"),
        "a worker claimed one shard lease (O_CREAT|O_EXCL on the "
        "lease file: exactly one claimant wins)"),
    "shard_steal": (
        ("shard", "worker", "from_worker", "reason", "age_s"),
        "an expired/stale/straggling lease was atomically removed so "
        "the shard can be re-claimed (reason: expired | holder_stale "
        "| straggler)"),
    "fabric_assemble": (
        ("out_dir", "n_shards", "n_workers", "n_quarantined",
         "n_flagged", "wall_s"),
        "coordinator validated every shard, merged worker quarantine "
        "records and wrote the final manifest/metrics"),
    "fabric_unavailable": (
        ("out_dir", "reason"),
        "RAFT_TPU_FABRIC_WORKERS requested but the sweep cannot run "
        "on the fabric (no entry spec on the evaluator); falling back "
        "to the serial in-process path"),
    "distributed_init": (
        ("coordinator", "process_id", "num_processes", "dryrun"),
        "jax.distributed.initialize wiring for multi-host meshes "
        "(RAFT_TPU_DIST; dryrun validates the config without touching "
        "a backend)"),
    "backend_fallback": (
        ("from_platform", "to_platform", "forced_by_fault"),
        "accelerator unhealthy; sweep pinned to the CPU backend"),
    "backend_fallback_failed": (
        ("from_platform", "reason"),
        "CPU pin attempted after a backend was already initialized"),
    "manifest_mismatch": (
        ("out_dir", "fields", "fatal"),
        "resume fingerprint differs from manifest.json"),
    "quarantine_corrupt": (
        ("out_dir", "error"),
        "quarantine.json was unreadable (externally damaged)"),
    # ------------------------------------------------------------- solvers
    "statics_unconverged": (
        ("n_iter", "status", "reason"),
        "statics Newton hit its budget with the step rule unmet"),
    "drag_linearisation": (
        ("case", "fowt", "resid", "converged", "n_iter", "status",
         "reason"),
        "per-case drag-linearisation convergence diagnostics"),
    # ---------------------------------------------------------- sweep trace
    "sweep_program_built": (
        ("kind", "out_keys"),
        "a sweep jit wrapper was built fresh (first call for this memo "
        "key; the next dispatch loads from the AOT bank or "
        "traces + compiles)"),
    # ------------------------------------------------ evaluation service
    "serve_start": (
        ("host", "port", "designs", "tick_ms", "batch_sizes"),
        "the evaluation service bound its socket (after design "
        "registration and AOT warmup — raft_tpu.serve)"),
    "serve_request": (
        ("endpoint", "method", "code", "client", "wall_s", "cache_hit"),
        "one HTTP request served (any endpoint; wall_s includes "
        "queueing + batching + dispatch for /evaluate)"),
    "serve_tick": (
        ("rows", "unique", "n_groups", "dispatches", "wall_s"),
        "one non-empty batcher tick: pending requests deduplicated, "
        "grouped by bucket signature and dispatched"),
    "serve_reject": (
        ("reason", "client"),
        "a request was refused at admission (reason: quota -> 429 | "
        "queue_full -> 503)"),
    "serve_escalate": (
        ("status_before", "status_after", "resolved"),
        "a SEVERE-flagged request opted into the f64_cpu re-solve; "
        "only a healthy re-solve is adopted"),
    "serve_error": (
        ("error", "rows"),
        "a serving dispatch raised; every coalesced requester got the "
        "exception (HTTP 500)"),
    "serve_drain": (
        ("pending", "wall_s", "completed"),
        "graceful drain: new work refused, pending ticks finished"),
    "slo_breach": (
        ("wall_s", "slo_ms", "client?", "cache_hit?"),
        "one request resolved slower than RAFT_TPU_SERVE_SLO_MS "
        "(counted in serve_slo_breaches; /healthz reports both next "
        "to the sliding-window p50/p95)"),
    "serve_request_stages": (
        ("wall_s", "queue_wait_s", "tick_wait_s", "dispatch_s",
         "solve_s", "post_s", "escalated?"),
        "per-resolved-request latency decomposition into named stages "
        "(admission-queue wait, in-tick wait behind earlier groups, "
        "dispatch overhead, compiled-program solve, post/cache fan-"
        "out); the stages sum to wall_s by construction — `obs report` "
        "renders the p50-vs-p95 stage table from these"),
    "serve_stop": (
        ("requests", "wall_s"),
        "the service exited after draining and flushing metrics"),
    "serve_ladder": (
        ("candidates", "sizes", "walls_ms"),
        "cost-driven batch-ladder refinement (RAFT_TPU_SERVE_LADDER="
        "cost): candidate rungs whose measured per-dispatch wall was "
        "flat vs the next rung were pruned after warmup — `sizes` is "
        "the serving ladder, every rung of it warmed"),
    # ------------------------------------------------------ serving fleet
    "replica_join": (
        ("replica", "port", "designs", "root"),
        "a warmed replica claimed its membership lease in the _fleet/ "
        "ledger (O_CREAT|O_EXCL — raft_tpu.serve.fleet); the router "
        "admits it to the hash ring on its next prober pass"),
    "replica_drain": (
        ("replica", "reason", "root"),
        "a replica released its membership lease at drain START "
        "(SIGTERM / POST /drain): the router stops routing new work "
        "here while the accepted work finishes"),
    "replica_evict": (
        ("replica", "reason", "age_s", "root"),
        "an expired membership lease was atomically removed (dead "
        "replica: SIGKILL/OOM/wedged host — exactly one evictor wins "
        "the rename) and the replica leaves the hash ring"),
    "fleet_spawn": (
        ("root", "replica", "pid"),
        "the fleet coordinator spawned one replica server subprocess"),
    "router_start": (
        ("host", "port", "fleet_dir", "n_replicas", "replicas"),
        "the failover router bound its socket (after the first "
        "membership pass populated the ring)"),
    "router_stop": (
        ("requests", "retries"),
        "the router exited after letting in-flight proxied requests "
        "finish"),
    "router_ring_update": (
        ("added", "removed", "n_replicas", "replaced?"),
        "the membership prober reconciled the hash ring against the "
        "lease ledger (join/drain/evict — zero router restarts); "
        "replaced names replicas whose lease moved to a new endpoint "
        "under the SAME id (rolling-upgrade takeover): their vnodes "
        "stay put, only the endpoint + breaker reset"),
    "router_request": (
        ("replica", "code", "attempts", "hedged", "design", "wall_s",
         "provenance?"),
        "one proxied /evaluate resolved: which replica answered, the "
        "final HTTP code, and how many failover attempts it took "
        "(replica=None on a 503 rejection)"),
    "router_retry": (
        ("replica", "attempt", "reason", "delay_s"),
        "the failover ladder moved a request to the next ring replica "
        "after a backoff (reason: connect | dropped | timeout | "
        "http_5xx) — duplicate dispatch is benign by construction "
        "(content-addressed result/program caches)"),
    "router_hedge": (
        ("primary", "replica", "hedge_ms"),
        "a hedged copy of a straggling first attempt was fired at the "
        "next ring replica (RAFT_TPU_ROUTER_HEDGE_MS); first good "
        "response wins"),
    "router_reject": (
        ("reason", "attempts", "retry_after_s"),
        "every owning replica was dead or breaker-open: the client got "
        "503 + Retry-After (graceful degradation, never a dropped "
        "connection)"),
    "breaker_open": (
        ("replica", "reason", "fails", "cooldown_s"),
        "a replica's circuit breaker opened after consecutive upstream "
        "failures; no traffic until the cooldown's half-open trial"),
    "breaker_close": (
        ("replica", "probe?"),
        "a half-open trial (live request, or probe=true for the "
        "prober's /healthz recovery check) succeeded and the "
        "replica's breaker closed"),
    # --------------------------------------------- live fleet health
    "alert_fire": (
        ("rule", "severity", "metric", "value", "threshold", "context"),
        "one alert rule's condition held past its for-duration and the "
        "alert FIRED (raft_tpu.obs.alerts; also appended to the "
        "RAFT_TPU_ALERTS JSONL sink and counted in alerts_active/"
        "alerts_fired); context carries the publishing subsystem's "
        "detail payload — the canary names the offending provenance "
        "here"),
    "alert_resolve": (
        ("rule", "severity", "metric", "duration_s", "value"),
        "a firing alert's condition stayed clean past its clear_s "
        "hysteresis and the alert RESOLVED (duration_s = how long it "
        "fired)"),
    "canary_golden": (
        ("design", "key", "status", "replica"),
        "one content-addressed golden row captured (design content "
        "hash + exact canary case bits + out_keys -> outputs + int32 "
        "status — raft_tpu.serve.canary); replica names the source of "
        "a router-side capture, None for a replica's own warmup "
        "capture"),
    "canary_check": (
        ("design", "replica", "ok", "reason", "provenance_ok", "key"),
        "one canary probe compared against its golden: ok=false means "
        "numeric/status drift vs the golden OR a cross-replica "
        "provenance split (stale bank, env skew, flag divergence) — "
        "feeds canary_pass/canary_fail and the canary-parity alert "
        "rule"),
    "replica_takeover": (
        ("replica", "port", "prev_port", "root"),
        "a rolling-upgrade replacement SEIZED an existing live lease "
        "under the same replica id (atomic rewrite, then /drain to "
        "the predecessor): the router sees one endpoint replacement, "
        "never a remove+add ring churn pair"),
    # ---------------------------------- releases & rolling upgrades
    "release_cut": (
        ("release", "parent", "entries", "label?"),
        "an immutable content-addressed release manifest was cut from "
        "the current bank snapshot (python -m raft_tpu.aot release "
        "cut): bank entry keys + payload shas + code hash + flags "
        "fingerprint + parent release, signed by its own sha"),
    "release_promote": (
        ("release", "previous"),
        "the releases/current pointer was flipped (atomic rename) to "
        "a new release — replicas resolve their bank through this "
        "pointer at warmup"),
    "release_rollback": (
        ("release", "to"),
        "the current pointer was re-pointed at the release's parent "
        "(operator rollback, or the rollout driver's automatic "
        "rollback on a canary failure)"),
    "release_resolve": (
        ("release", "root"),
        "a serve replica resolved its bank through the current "
        "release pointer at warmup; the release id is stamped into "
        "every x-raft-provenance response header"),
    "release_preflight": (
        ("release", "unwarmed", "total", "reason?"),
        "the release-vs-designs bank preflight ran (aot release "
        "verify --against-designs, or a require-mode replica dying "
        "on a BankMissError): how many design/rung programs are "
        "unwarmed and the mismatch class (code | flags | ladder | "
        "avals)"),
    "rollout_start": (
        ("to", "from", "replicas", "root"),
        "a canary-gated rolling upgrade began: current flipped to the "
        "candidate release, the rollout marker written, replicas to "
        "be surf-replaced one at a time (raft_tpu.serve.rollout)"),
    "rollout_step": (
        ("replica", "phase", "ok", "wall_s?"),
        "one rollout step finished (phase: spawn | join | canary): "
        "the named replica was replaced in place and the mixed-"
        "version fleet's canary verdict gated promotion to the next"),
    "rollout_rollback": (
        ("to", "reason", "aborted"),
        "the rollout aborted (canary failure, alert fire, or a step "
        "timeout) and automatically rolled back: current re-pointed "
        "at the parent release, upgraded replicas rolled back the "
        "same surf-replace way; aborted names the abandoned release"),
    "rollout_done": (
        ("to", "ok", "replaced", "rolled_back", "wall_s"),
        "the rolling upgrade finished: every replica replaced and "
        "canary-green (ok=true), or rolled back to the parent "
        "release (ok=false) — one run record + one merged trace per "
        "rollout either way"),
    # --------------------------------------------- SLO autoscaler
    "autoscale_out": (
        ("replicas", "reason", "pressure"),
        "the autoscaler added a replica on sustained hot alert state "
        "(slo-breach / breaker-storm firing past "
        "RAFT_TPU_AUTOSCALE_OUT_FOR_S): warm-bank spawn, zero real "
        "XLA compiles (raft_tpu.serve.autoscale)"),
    "autoscale_in": (
        ("replica", "replicas", "reason", "occupancy"),
        "the autoscaler drained one replica after sustained low "
        "cost-ledger occupancy (under RAFT_TPU_AUTOSCALE_LOW_OCC for "
        "RAFT_TPU_AUTOSCALE_IN_FOR_S, past the cooldown, never below "
        "RAFT_TPU_AUTOSCALE_MIN)"),
    # --------------------------------------------- run-record store
    "run_record": (
        ("kind", "path", "label?"),
        "one schema-versioned run record was appended to the "
        "RAFT_TPU_RUNS_DIR store (raft_tpu.obs.runs) — the "
        "longitudinal perf-trajectory entry a later `obs runs "
        "regress` compares against the pinned baseline"),
    "regression_detected": (
        ("metric", "base", "new", "threshold", "baseline", "record"),
        "`obs runs regress` found one watched metric worse than the "
        "pinned baseline past its noise threshold (the CLI exits 1)"),
    # ------------------------------------------------- AOT program bank
    "aot_load": (
        ("kind", "key", "bytes", "wall_s"),
        "a banked executable was deserialized and dispatched — no "
        "trace, no XLA compilation (raft_tpu.aot.bank)"),
    "aot_miss": (
        ("kind", "key", "mode"),
        "no bank entry for this program key; 'require' mode raises "
        "BankMissError here unless RAFT_TPU_AOT_MISS=compile"),
    "aot_store": (
        ("kind", "key", "bytes", "compile_s"),
        "a freshly-compiled program was exported into the bank for "
        "the next process"),
    "aot_unbankable": (
        ("kind",),
        "a sweep closure carries no program-identity stamp "
        "(_raft_program_key) and is dispatched without the bank — "
        "stamp it (see README) to make it warm-loadable"),
    "aot_error": (
        ("error", "kind?", "key?"),
        "a bank entry could not be serialized/deserialized (corrupt, "
        "truncated, backend refuses) — logged and treated as a miss, "
        "never fatal"),
    "aot_gc": (
        ("removed", "kept", "bytes_freed", "dry_run"),
        "bank garbage collection removed stale/orphaned entries "
        "(python -m raft_tpu.aot gc)"),
    "aot_warmup": (
        ("kind", "n", "loaded", "compiled", "wall_s", "n_buckets?"),
        "one warmup sweep dispatched (python -m raft_tpu.aot warmup); "
        "bucketed kind warms n rows per bucket signature"),
    "compile_budget_exceeded": (
        ("count", "budget", "action"),
        "a backend compilation exceeded RAFT_TPU_COMPILE_BUDGET; "
        "action 'error' raised RecompilationError at the dispatch"),
    # -------------------------------------------------- flight recorder
    "flight_dump": (
        ("trigger", "path", "records"),
        "the black-box flight ring was persisted as one atomic JSONL "
        "shard (raft_tpu.obs.flight): trigger names the cause — an "
        "alert dump embeds the firing rule (alert-<rule>), plus "
        "quarantine-severe / compile-budget / crash-<exc> / sigterm / "
        "manual; `obs flight show` summarizes the shard and `obs trace "
        "--merge` places it on the shared timeline"),
    "flight_metrics": (
        ("counters",),
        "periodic metric-snapshot delta record inside a flight-dump "
        "shard (never emitted to the live stream): the counter "
        "movement since the previous flight snapshot "
        "(RAFT_TPU_FLIGHT_SNAP_S) — rate context for a postmortem"),
    "exemplar_recorded": (
        ("metric", "value"),
        "a histogram observation was admitted to a top-K-per-bucket "
        "exemplar slot (raft_tpu.obs.metrics): the free-form rest of "
        "the payload carries the caller-stamped attrs — trace/span "
        "ids, design content hash, bucket signature, dispatched rows, "
        "cache-hit bit, replica id, int32 status word — and is the "
        "join key `obs report --tail` uses to render the actual tail "
        "request's span tree"),
    # -------------------------------------------------- device-cost ledger
    "program_cost": (
        ("kind", "key", "source", "flops?", "bytes_accessed?",
         "arg_bytes?", "transcendentals?"),
        "XLA cost_analysis of one banked/compiled program (source: "
        "store | load | compile) — the per-program entry of the "
        "device-cost ledger, persisted in the bank's .json sidecar"),
    "program_dispatch": (
        ("key", "kind", "wall_s", "gflops_s?", "utilization?"),
        "one bank-fronted program execution with its achieved GFLOP/s "
        "and fraction of RAFT_TPU_PEAK_TFLOPS (wall includes "
        "block-until-ready, so the rate is honest, not async-deflated)"),
}

#: Span-name registry, mirroring EVENTS for the names used with
#: ``obs.span(...)``: a typo'd span name silently forks the wall-time
#: tree (and mints a stray ``span_<name>_s`` histogram) instead of
#: crashing — the ``span-name`` lint rule holds call sites to this
#: table.  name -> help.
SPANS: dict[str, str] = {
    "driver.run": "one full analysis via raft_tpu.drivers.run",
    "driver.run_farm": "one farm analysis via raft_tpu.drivers.run_farm",
    "solve_statics": "per-case statics equilibrium solve",
    "solve_dynamics": "per-case dynamics (drag-linearised) solve",
    "sweep": "one checkpointed/fabric sweep, root of the shard tree",
    "shard": "one shard's fault-tolerant evaluation",
    "shard_attempt": "one retry attempt inside a shard",
    "escalation_rung": "one escalation-ladder re-solve of a flagged row",
    "sweep_dispatch": "one compiled-program dispatch (cases/full/bucket/"
                      "serve)",
    "serve_request": "one /evaluate request, HTTP accept to response "
                     "(adopts the client's traceparent when sent)",
    "serve_tick": "one non-empty batcher tick; `links` names every "
                  "coalesced request span it dispatched for",
    "router_request": "one proxied /evaluate at the fleet router, "
                      "HTTP accept through the failover ladder to the "
                      "response (adopts the client's traceparent; its "
                      "ids are forwarded so the replica's serve_request "
                      "span joins the same trace)",
    "router_upstream": "one upstream attempt of the failover ladder "
                       "(child of router_request; retries and hedges "
                       "each get their own)",
    "rollout": "one canary-gated rolling upgrade, pointer flip through "
               "the last replica replacement (or the automatic "
               "rollback) — root of the rollout_step tree",
    "rollout_step": "one replica's surf-replacement inside a rollout "
                    "(spawn + ledger join + canary gate), child of "
                    "the rollout span",
}


def is_registered_span(name):
    return name in SPANS


def describe_spans():
    """Yield ``(name, help)`` rows sorted by name (README span table)."""
    for name in sorted(SPANS):
        yield name, SPANS[name]


def is_registered(name):
    return name in EVENTS


def describe():
    """Yield ``(name, fields, help)`` rows sorted by name (the README
    event table and ``python -m raft_tpu.obs events`` render from
    this)."""
    for name in sorted(EVENTS):
        fields, help_ = EVENTS[name]
        yield name, fields, help_
