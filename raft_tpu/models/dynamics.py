"""Frequency-domain dynamics solve (jax) — the framework's hot path.

Equivalent of ``Model.solveDynamics`` (``/root/reference/raft/
raft_model.py:966-1255``): iterative stochastic drag linearisation
around the response spectrum, then the complex impedance solve

    Z(w) xi(w) = F(w),   Z = -w^2 M(w) + i w B(w) + C

per frequency and excitation heading.

TPU-first design:
* the per-frequency dense solves run through the batched small-N
  complex solver in :mod:`raft_tpu.ops.linsolve` (pivot-free blocked
  elimination of the real 2N x 2N embedding, ``RAFT_TPU_SOLVER`` flag;
  generic ``jnp.linalg.solve`` fallback) over the stacked
  (nw, nDOF, nDOF) tensor — no Python loop over frequencies (reference
  loops at raft_model.py:1084-1089);
* everything iteration-invariant is hoisted out of the fixed point:
  the base impedance ``Z0 = -w^2 M + i w B + C + Z_extra`` is built
  once and each iteration only adds the ``i w B_drag`` update, and the
  drag linearisation runs through
  :func:`raft_tpu.physics.morison.drag_lin_precompute` /
  :func:`~raft_tpu.physics.morison.drag_lin_iter` so no geometry is
  re-derived per iteration;
* the fixed-point drag-linearisation iteration is a fixed-trip
  ``lax.scan`` with the reference's convergence test and 0.2/0.8
  under-relaxation (raft_model.py:1103-1133) applied through
  ``jnp.where`` masking — bit-compatible with the previous
  ``lax.while_loop`` (the masked body is idempotent at the converged
  state; tests/test_dynamics_hotpath.py), but with a static trip count
  XLA can fuse and schedule (and vmap) without dynamic-loop overhead;
* the compute dtype is an explicit policy
  (:mod:`raft_tpu.utils.dtypes`): derived from the inputs by default
  (float64 golden parity), float32/complex64 via ``RAFT_TPU_DTYPE``;
* the system response for all headings is a single batched solve
  against the (nWaves, nDOF, nw) excitation tensor.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from raft_tpu.ops import linsolve
from raft_tpu.physics import morison
from raft_tpu.utils import config, health
from raft_tpu.utils.dtypes import compute_dtypes


def impedance(w, M, B, C):
    """Z (nw, nDOF, nDOF) from M/B (nDOF, nDOF, nw) and C (nDOF, nDOF)."""
    Mw = jnp.moveaxis(M, -1, 0)
    Bw = jnp.moveaxis(B, -1, 0)
    return (-(w**2)[:, None, None] * Mw + 1j * w[:, None, None] * Bw + C[None, :, :])


def fused_response_enabled():
    """True when the rigid single-heading evaluators should take their
    wave response straight from :func:`solve_dynamics_fowt`'s returned
    ``Xi`` — the fused case hot path (``RAFT_TPU_FUSED``, trace-time).

    The fixed point's final ``update(XiLast)`` already solves
    ``Z xi = F_lin + F_drag`` with ``F_drag`` assembled through the
    separable per-ω drag-excitation fold of
    :func:`raft_tpu.physics.morison.drag_lin_precompute` (three
    ``(S, nDOF) x (c_d * proj_d)`` contractions).  The staged tail the
    evaluators used to run — :func:`raft_tpu.physics.morison.
    drag_excitation` (the full ``Bmat @ u`` / moment / segment-sum /
    T-reduction chain) followed by a second :func:`system_response`
    solve — recomputes the algebraically identical quantity, so fusing
    drops one full batched complex solve plus the whole staged
    excitation chain per case.  Fold-vs-chain summation order differs
    at the last few ulps: parity vs the staged path is gated at 1e-10
    with bit-equal status (tests/test_fused.py); ``RAFT_TPU_FUSED=off``
    restores the staged tail as the parity oracle."""
    return config.get("FUSED") == "on"


def fixed_point_mode():
    """Fixed-point loop driver: 'scan', 'while', or the default 'auto'
    (``RAFT_TPU_FIXED_POINT`` flag, read at trace time).

    'scan' drives the fixed point through fixed-trip ``lax.scan``
    blocks of ``RAFT_TPU_SCAN_CHUNK`` (default 4) masked iterations —
    XLA sees static trip counts it can fuse/unroll/schedule — with an
    early-exit check between blocks so converged batches do not pay for
    the full reference cap (a chunk >= the cap degenerates to one fully
    static scan).  'while' is the per-iteration ``lax.while_loop``.
    Both produce the SAME bits (the masked step is idempotent at the
    converged state; tests/test_dynamics_hotpath.py), so 'auto' picks
    by backend: 'while' on CPU, where XLA's loop-invariant code motion
    already serves the dynamic loop well and each skipped trip is pure
    profit (measured: while 1.08x vs the static scan's 0.55x on
    early-converging sea states), 'scan' on accelerators, where static
    trip counts compile to better-scheduled loop nests."""
    mode = config.get("FIXED_POINT")
    if mode == "auto":
        mode = "while" if jax.default_backend() == "cpu" else "scan"
    return mode


def solve_dynamics_fowt(
    fs, ss, hc, u0, M_lin, B_lin, C_lin, F_lin, w, Tn, r_nodes,
    n_iter=15, Xi_start=0.1, tol=0.01, Z_extra=None, n_iter_extra=0,
    dtype=None,
):
    """Iterative linearised solve for one FOWT's impedance and response.

    M_lin/B_lin : (nDOF, nDOF, nw); C_lin : (nDOF, nDOF);
    F_lin : (nDOF, nw) complex (primary-heading excitation);
    u0 : (S, 3, nw) wave velocities at strips for the primary heading.
    Z_extra : optional (nw, nDOF, nDOF) complex impedance added to Z
    (e.g. the frequency-dependent lumped-mass mooring impedance of
    moorMod 2, replacing the constant C_moor in C_lin).
    dtype : optional 'float32'/'float64' compute-policy override
    (default: ``RAFT_TPU_DTYPE`` env, else derived from the inputs).

    Returns (Z (nw,nDOF,nDOF), Xi (nDOF,nw), Bmat (S,3,3),
    diag dict with drag_resid (scalar) / drag_converged (bool) — the
    stopping-rule residual of the returned linearisation point —
    n_iter_drag, the realized iteration count of the fixed point,
    cond_Z, the max one-step Hager estimate of kappa_1(Z(w)) (0 unless
    RAFT_TPU_COND_CHECK), and status, the int32 solver-health word
    (DRAG_CAP_HIT / ILL_CONDITIONED_Z / NONFINITE_INTERMEDIATE bits,
    see :mod:`raft_tpu.utils.health`)).
    """
    nDOF, nw = F_lin.shape
    rdt, cdt = compute_dtypes(M_lin, F_lin, w, policy=dtype)
    w = jnp.asarray(w, dtype=rdt)
    M_lin = jnp.asarray(M_lin, dtype=rdt)
    B_lin = jnp.asarray(B_lin, dtype=rdt)
    C_lin = jnp.asarray(C_lin, dtype=rdt)
    F_lin = jnp.asarray(F_lin).astype(cdt)
    u0 = jnp.asarray(u0).astype(cdt)
    if Z_extra is None:
        Z_extra = jnp.zeros((nw, nDOF, nDOF), dtype=cdt)
    else:
        Z_extra = jnp.asarray(Z_extra).astype(cdt)

    # everything Xi-independent leaves the loop: geometry/sea-state
    # tensors of the linearisation ...
    pre = morison.drag_lin_precompute(
        fs, ss, hc, u0, Tn, r_nodes, w, dtype=(rdt, cdt))
    # ... and the base impedance (the per-iteration update is only the
    # rank-structured i w B_drag term)
    Z0 = impedance(w, M_lin, B_lin, C_lin).astype(cdt) + Z_extra
    iw = (1j * w).astype(cdt)

    def update(XiLast):
        """One full (un-relaxed) linearise-and-solve step."""
        out = morison.drag_lin_iter(pre, XiLast)
        B_drag, Bmat, F_drag = (
            out["B_hydro_drag"], out["Bmat"], out["F_hydro_drag"])
        Z = Z0 + iw[:, None, None] * B_drag[None, :, :]
        F = F_lin + F_drag
        Xi = linsolve.solve(Z, jnp.moveaxis(F, -1, 0))
        return jnp.moveaxis(Xi, 0, -1), Z, Bmat  # (nDOF, nw)

    # Iteration budget: the reference's cap is n_iter (break on
    # convergence, warn otherwise, raft_model.py:1133-1143).  The
    # default n_iter_extra=0 reproduces the reference EXACTLY, including
    # its cap-limited states — the flexible-model goldens correspond to
    # the capped fixed-point iterate (both cases of the flexible design,
    # measured: enabling extra iterations moves the no-wind case off its
    # 1e-10-level golden parity), so parity demands stopping where the
    # reference stops even when the stopping rule is unmet (the
    # flexible-tower wind case sits at residual ~1.03e-2 against tol
    # 1e-2).  Sweeps that prefer the true fixed point over golden
    # compatibility can grant n_iter_extra additional under-relaxed
    # iterations, taken ONLY when the reference cap strikes unconverged.
    # RAFT_TPU_ITER_SCALE (trace-time, default 1) multiplies the base
    # budget — the escalation re-solver's "larger budget" rung; at 1
    # the cap is exactly the reference's.
    iter_scale = max(int(config.get("ITER_SCALE")), 1)
    cap = n_iter * iter_scale + 1 + max(int(n_iter_extra), 0)

    def step(XiLast, it):
        """One masked fixed-point step (shared by both loop drivers).

        Keeps the final LINEARISATION POINT: on convergence the
        reference breaks before relaxing, and when the iteration cap
        strikes it keeps the response computed at the last
        linearisation — relaxing once more before the final solve
        would be one extra iteration vs the reference (measured at
        ~1e-3 in cap-limited resonance bands)."""
        Xi, _, _ = update(XiLast)
        tolCheck = jnp.abs(Xi - XiLast) / (jnp.abs(Xi) + tol)
        done = jnp.all(tolCheck < tol)
        last = it + 1 >= cap
        XiNext = jnp.where(done | last, XiLast, 0.2 * XiLast + 0.8 * Xi)
        return XiNext, done

    def run_fixed_point_scan(f, Xinit):
        # fixed-trip scan blocks: once `done` the carry is a fixed
        # point of the (pure, deterministic) masked body — XiNext ==
        # XiLast exactly and every later trip recomputes the identical
        # masked step — so the final carry is bit-identical to the
        # while_loop's regardless of where the block boundaries fall,
        # while XLA gets static trip counts to fuse/unroll/schedule.
        # Steps past the cap are likewise no-ops (`last` masks them and
        # the realized-iteration counter excludes them).  A masked step
        # still EVALUATES the update — the masking buys bit-compat, not
        # zero cost — so blocks are clamped to the cap and the outer
        # early-exit check bounds the waste to chunk-1 trips.
        chunk = min(max(1, config.get("SCAN_CHUNK")), cap)

        def block(carry, it0):
            def body(c, j):
                XiLast, done_prev, n_real = c
                it = it0 + j
                XiNext, done = step(XiLast, it)
                # float counter: custom_root's JVP rule cannot produce
                # the float0 tangent an int aux output would need (rdt
                # literals: weak python floats are f64 under x64, which
                # would put a 64-bit select in every masked trip)
                n_real = n_real + jnp.where(done_prev | (it >= cap),
                                            jnp.asarray(0.0, dtype=rdt),
                                            jnp.asarray(1.0, dtype=rdt))
                return (XiNext, done_prev | done, n_real), None

            # full unroll: each block lowers to straight-line code (no
            # inner loop construct at all) that XLA can fuse/parallelise
            carry, _ = jax.lax.scan(body, carry, jnp.arange(chunk),
                                    unroll=True)
            return carry

        carry0 = (Xinit, jnp.asarray(False), jnp.asarray(0.0, dtype=rdt))
        if chunk == cap:
            XiLast, _, n_real = block(carry0, jnp.asarray(0, jnp.int32))
            return XiLast, n_real

        def outer_body(state):
            carry, it0 = state
            return block(carry, it0), it0 + chunk

        def outer_cond(state):
            (_, done, _), it0 = state
            return (it0 < cap) & (~done)

        (XiLast, _, n_real), _ = jax.lax.while_loop(
            outer_cond, outer_body, (carry0, jnp.asarray(0, jnp.int32)))
        return XiLast, n_real

    def run_fixed_point_while(f, Xinit):
        def body(carry):
            XiLast, it, _ = carry
            XiNext, done = step(XiLast, jnp.asarray(it, dtype=jnp.int32))
            return XiNext, it + 1.0, done

        def cond(carry):
            _, it, done = carry
            return (it < cap) & (~done)

        # float counter: custom_root's JVP rule cannot produce the
        # float0 tangent an int aux output would need
        XiLast, it, _ = jax.lax.while_loop(
            cond, body, (Xinit, jnp.asarray(0.0, dtype=rdt),
                         jnp.asarray(False)))
        return XiLast, it

    run_fixed_point = (run_fixed_point_while if fixed_point_mode() == "while"
                       else run_fixed_point_scan)

    def residual(X):
        Xi, _, _ = update(X)
        return X - Xi

    def tangent_solve(g, y):
        # g(x) = x - A x with A the (contractive) linearised drag
        # coupling — solve by Neumann iteration x <- y + (x - g(x)),
        # which converges at the same rate as the fixed point itself
        x = y
        for _ in range(10):
            x = y + (x - g(x))
        return x

    # implicit differentiation of the drag-linearisation fixed point
    # (lax.custom_root): forward value identical to the reference-style
    # under-relaxed iteration; jax.grad works through the converged
    # point instead of unrolling the loop (SURVEY.md §7.1)
    Xi0 = jnp.full((nDOF, nw), Xi_start, dtype=cdt)
    XiLast, n_real = jax.lax.custom_root(
        residual, Xi0, run_fixed_point, tangent_solve, has_aux=True)
    n_real = jnp.asarray(jax.lax.stop_gradient(n_real), dtype=jnp.int32)
    # final response/impedance at the converged linearisation (exactly
    # the quantities the loop's last iteration produced)
    Xi, Z, Bmat = update(XiLast)
    # convergence diagnostic: does the returned point satisfy the
    # stopping rule?  (the reference warns on non-convergence,
    # raft_model.py:1138-1140; sweeps use this to flag bad cases)
    tolCheck = jnp.max(jnp.abs(Xi - XiLast) / (jnp.abs(Xi) + tol))
    drag_converged = tolCheck < tol
    # solver-health word (raft_tpu.utils.health): in-band, vmap-safe
    # bits that survive where a host warning cannot (pjit sweeps)
    status = health.set_bit(jnp.zeros((), dtype=jnp.int32),
                            health.DRAG_CAP_HIT, ~drag_converged)
    status = health.set_bit(status, health.NONFINITE_INTERMEDIATE,
                            ~jnp.all(jnp.isfinite(Xi)))
    if config.get("COND_CHECK"):
        # guarded numerics: one-step Hager estimate of kappa_1(Z(w))
        # (one extra batched solve, trace-time gated so the default
        # program is untouched)
        cond_Z = jnp.max(linsolve.cond_estimate(Z))
        status = health.set_bit(status, health.ILL_CONDITIONED_Z,
                                cond_Z > config.get("COND_THRESHOLD"))
    else:
        cond_Z = jnp.zeros((), dtype=rdt)
    return Z, Xi, Bmat, dict(
        drag_resid=tolCheck, drag_converged=drag_converged,
        n_iter_drag=n_real, cond_Z=cond_Z, status=status)


def system_response(Z_sys, F_waves):
    """Response for every excitation source.

    Z_sys : (nw, nDOF, nDOF); F_waves : (nH, nDOF, nw) ->
    Xi : (nH, nDOF, nw).  One batched solve (native small-N kernel or
    generic fallback, see :mod:`raft_tpu.ops.linsolve`) replaces the
    reference's explicit inverse + per-(heading, frequency) matmuls
    (raft_model.py:1189-1236)."""
    F = jnp.moveaxis(F_waves, -1, 1)          # (nH, nw, nDOF)
    Xi = linsolve.solve(Z_sys, F)
    return jnp.moveaxis(Xi, 1, -1)
