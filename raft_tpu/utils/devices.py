"""Device-placement helpers.

Build-time model assembly does small *eager* jax computations (statics
matrices, strip constants).  On TPU images those would otherwise land
on the accelerator and then need device-to-host pulls when embedded as
jit constants — and the axon TPU tunnel in this environment only
implements f32 transfers.  ``on_cpu()`` pins eager build work to the
host CPU backend; jitted hot-path programs still run wherever the
caller places them.
"""

from __future__ import annotations

import contextlib

import jax


@contextlib.contextmanager
def on_cpu():
    try:
        cpu = jax.local_devices(backend="cpu")[0]
    except RuntimeError:
        yield
        return
    with jax.default_device(cpu):
        yield


def to_host(tree):
    """Pull a pytree of arrays to host numpy."""
    import numpy as np

    return jax.tree_util.tree_map(
        lambda x: np.asarray(x) if hasattr(x, "dtype") else x, tree
    )
