"""WAMIT-format hydrodynamic coefficient file I/O.

The reference consumes pre-computed potential-flow coefficients in the
WAMIT interchange format via pyHAMS (``/root/reference/raft/
raft_fowt.py:1444-1509`` readHydro; ``readQTF`` :2081-2129), which this
framework keeps as its potential-flow interchange schema (SURVEY.md
§7.1):

* ``.1``  — added mass / radiation damping: rows of
  [period, i, j, Abar(, Bbar)], nondimensional (A = rho Abar,
  B = rho w Bbar).  Sentinel periods: T < 0 is zero frequency,
  T = 0 is infinite frequency.
* ``.3``  — excitation: [period, heading, i, |X|, phase, Re, Im]
  (nondimensional; X = rho g Xbar).
* ``.12d`` — difference-frequency QTFs.

Parsing is numpy at build time; the interpolated model-grid tensors are
constants for the traced solves.
"""

from __future__ import annotations

import os

import numpy as np


def read_wamit1(path):
    """Read a .1 file -> (w (nfreq,), A (6,6,nfreq), B (6,6,nfreq)),
    nondimensional, sorted by ascending frequency.  Zero-frequency /
    infinite-frequency sentinel rows are mapped to w = 0 / np.inf."""
    data = np.loadtxt(path)
    T = data[:, 0]
    w = np.where(T < 0, 0.0, np.where(T == 0, np.inf, 2 * np.pi / np.where(T == 0, 1, T)))
    freqs = np.unique(w)
    A = np.zeros((6, 6, len(freqs)))
    B = np.zeros((6, 6, len(freqs)))
    idx = {f: n for n, f in enumerate(freqs)}
    for row, wi in zip(data, w):
        i, j = int(row[1]) - 1, int(row[2]) - 1
        n = idx[wi]
        A[i, j, n] = row[3]
        if len(row) > 4:
            B[i, j, n] = row[4]
    return freqs, A, B


def read_wamit3(path):
    """Read a .3 file -> (w (nf,), headings (nh,), X (nh,6,nf) complex),
    nondimensional."""
    data = np.loadtxt(path)
    T = data[:, 0]
    w = np.where(T < 0, 0.0, np.where(T == 0, np.inf, 2 * np.pi / np.where(T == 0, 1, T)))
    freqs = np.unique(w)
    heads = np.unique(data[:, 1])
    X = np.zeros((len(heads), 6, len(freqs)), dtype=np.complex128)
    fi = {f: n for n, f in enumerate(freqs)}
    hi = {h: n for n, h in enumerate(heads)}
    for row, wi in zip(data, w):
        X[hi[row[1]], int(row[2]) - 1, fi[wi]] = row[5] + 1j * row[6]
    return freqs, heads, X


def write_wamit1(path, w, A, B, rho=1025.0, ulen=1.0):
    """Write added mass / radiation damping in the WAMIT .1 interchange
    format (nondimensional: Abar = A/(rho ULEN^k), Bbar = B/(rho w ULEN^k);
    ULEN exponent handled as in read_wamit1's inverse)."""
    w = np.asarray(w)
    with open(path, "w") as f:
        for iw, wi in enumerate(w):
            T = 2 * np.pi / wi
            for i in range(6):
                for j in range(6):
                    f.write(f" {T: .6e} {i+1:5d} {j+1:5d}"
                            f" {A[i, j, iw] / rho: .6e}"
                            f" {B[i, j, iw] / (rho * wi): .6e}\n")


def write_wamit3(path, w, headings_deg, X, rho=1025.0, g=9.81):
    """Write excitation coefficients in the WAMIT .3 format
    (X (nh, 6, nw) complex, dimensional; file stores X/(rho g))."""
    w = np.asarray(w)
    with open(path, "w") as f:
        for iw, wi in enumerate(w):
            T = 2 * np.pi / wi
            for ih, h in enumerate(headings_deg):
                for i in range(6):
                    x = X[ih, i, iw] / (rho * g)
                    f.write(f" {T: .6e} {h: .4f} {i+1:5d}"
                            f" {abs(x): .6e} {np.degrees(np.angle(x)): .6e}"
                            f" {x.real: .6e} {x.imag: .6e}\n")


def write_rao_4(path, w, Xi, beta_deg=0.0):
    """Write motion RAOs in the WAMIT .4 column layout the reference
    emits next to its QTF outputs (raft_fowt.py:2027-2041): rows of
    [period, heading, DoF, |x|, phase(rad), Re x, Im x] for
    ``Xi`` (ndof, nw) complex (response per unit wave amplitude)."""
    w = np.asarray(w)
    Xi = np.asarray(Xi)
    with open(path, "w") as f:
        for idof in range(Xi.shape[0]):
            for wi, x in zip(w, Xi[idof]):
                f.write(f"{2 * np.pi / wi: 8.6e} {beta_deg: 8.4e} "
                        f"{idof + 1} {np.abs(x): 8.6e} "
                        f"{np.angle(x): 8.6e} {x.real: 8.6e} "
                        f"{x.imag: 8.6e}\n")


def read_rao_4(path):
    """Read a WAMIT .4 motion-RAO file (as written by write_rao_4 /
    the reference's QTF debug output) -> (w (nw,), headings_deg (nh,),
    Xi (nh, ndof, nw) complex), frequencies ascending."""
    data = np.loadtxt(path)
    w_all = 2 * np.pi / data[:, 0]
    freqs = np.unique(w_all)
    heads = np.unique(data[:, 1])
    ndof = int(np.max(data[:, 2]))
    Xi = np.zeros((len(heads), ndof, len(freqs)), dtype=np.complex128)
    fi = {f: n for n, f in enumerate(freqs)}
    hi = {h: n for n, h in enumerate(heads)}
    for row, wi in zip(data, w_all):
        Xi[hi[row[1]], int(row[2]) - 1, fi[wi]] = row[5] + 1j * row[6]
    return freqs, heads, Xi


def read_wamit_p2(path, rho=1.0, ulen=1.0, g=1.0):
    """Read a WAMIT .p2 second-order (sum/difference) output file into
    per-DOF complex matrices — the readWAMIT_p2 equivalent
    (/root/reference/raft/helpers.py:1434-1469).

    Rows: [period, heading, DoF, |F|, phase, Re, Im].  Returns a dict
    keyed 'surge'...'yaw' of (n_period, n_heading) complex arrays
    dimensionalised by rho g ULEN^k (k = 2 for forces, 3 for moments),
    plus 'period' and 'heading' vectors.  Defaults keep the data
    nondimensional, as the reference does."""
    data = np.loadtxt(path)
    heads = np.unique(data[:, 1])
    periods = np.unique(data[:, 0])
    names = ["surge", "sway", "heave", "roll", "pitch", "yaw"]
    k_ulen = [2, 2, 2, 3, 3, 3]
    out = {}
    for idof, name in enumerate(names):
        rows = data[data[:, 2] == idof + 1]
        rows = rows[np.lexsort((rows[:, 1], rows[:, 0]))]
        re = rows[:, 5].reshape(-1, len(heads))
        im = rows[:, 6].reshape(-1, len(heads))
        out[name] = (re + 1j * im) * rho * g * ulen ** k_ulen[idof]
    out["period"] = periods
    out["heading"] = heads
    return out


def _interp_freq(w_model, w_data, Y, pad_zero_freq=None):
    """Linear interpolation along the last axis onto the model grid,
    with an optional value prepended at w = 0 (the reference pads the
    zero-frequency added mass / zero damping, raft_fowt.py:1469-1473)."""
    finite = np.isfinite(w_data)
    wd = w_data[finite]
    Yd = Y[..., finite]
    if pad_zero_freq is not None and (len(wd) == 0 or wd[0] > 0):
        wd = np.hstack([[0.0], wd])
        Yd = np.concatenate([pad_zero_freq[..., None], Yd], axis=-1)
    out = np.zeros(Y.shape[:-1] + (len(w_model),))
    for k in range(len(w_model)):
        out[..., k] = _interp_point(w_model[k], wd, Yd)
    return out


def _interp_point(x, xs, Ys):
    i = np.searchsorted(xs, x)
    if i <= 0:
        return Ys[..., 0]
    if i >= len(xs):
        return Ys[..., -1]
    f = (x - xs[i - 1]) / (xs[i] - xs[i - 1])
    return Ys[..., i - 1] * (1 - f) + Ys[..., i] * f


def load_bem_coefficients(hydro_path, w_model, rho, g, r_ref=None):
    """Model-grid BEM tensors from WAMIT files, reference conventions:

    A_BEM (6,6,nw) = rho * Abar translated to the reference point;
    B_BEM (6,6,nw) = rho * w * Bbar translated;
    X coefficients (nh, 6, nw) rotated heading-relative
    (raft_fowt.py:1476-1501).  Returns dict; X entries zero if no .3
    file is present (the snapshot's OC4 dataset ships only the .1).
    """
    from raft_tpu.ops import transforms as tf
    import jax.numpy as jnp

    nw = len(w_model)
    out = dict(
        A_BEM=np.zeros((6, 6, nw)),
        B_BEM=np.zeros((6, 6, nw)),
        X_BEM=np.zeros((1, 6, nw), dtype=np.complex128),
        headings=np.array([0.0]),
    )

    p1 = hydro_path + ".1"
    if os.path.exists(p1):
        w1, Abar, Bbar = read_wamit1(p1)
        # zero-frequency added mass used as the low-frequency pad
        if np.any(w1 == 0):
            A0 = Abar[:, :, np.where(w1 == 0)[0][0]]
        else:
            A0 = Abar[:, :, 0]
        mask = np.isfinite(w1) & (w1 > 0)
        A_i = _interp_freq(w_model, w1[mask], Abar[:, :, mask], pad_zero_freq=A0)
        B_i = _interp_freq(w_model, w1[mask], Bbar[:, :, mask],
                           pad_zero_freq=np.zeros((6, 6)))
        r_off = np.zeros(3) if r_ref is None else -np.asarray(r_ref)
        for iw in range(nw):
            out["A_BEM"][:, :, iw] = np.asarray(
                tf.translate_matrix_6to6(jnp.asarray(rho * A_i[:, :, iw]), jnp.asarray(r_off)))
            out["B_BEM"][:, :, iw] = np.asarray(
                tf.translate_matrix_6to6(jnp.asarray(rho * w_model[iw] * B_i[:, :, iw]), jnp.asarray(r_off)))

    p3 = hydro_path + ".3"
    if os.path.exists(p3):
        w3, heads, Xbar = read_wamit3(p3)
        heads = np.asarray(heads) % 360
        order = np.argsort(heads)
        heads = heads[order]
        Xbar = Xbar[order]
        mask = np.isfinite(w3) & (w3 > 0)
        Xr = _interp_freq(w_model, w3[mask], Xbar.real[:, :, mask],
                          pad_zero_freq=np.zeros((len(heads), 6)))
        Xi = _interp_freq(w_model, w3[mask], Xbar.imag[:, :, mask],
                          pad_zero_freq=np.zeros((len(heads), 6)))
        X = rho * g * (Xr + 1j * Xi)
        # rotate DOFs heading-relative (raft_fowt.py:1489-1498)
        Xrot = np.zeros_like(X)
        for ih, h in enumerate(heads):
            ch, sh = np.cos(np.radians(h)), np.sin(np.radians(h))
            Xrot[ih, 0] = ch * X[ih, 0] + sh * X[ih, 1]
            Xrot[ih, 1] = -sh * X[ih, 0] + ch * X[ih, 1]
            Xrot[ih, 2] = X[ih, 2]
            Xrot[ih, 3] = ch * X[ih, 3] + sh * X[ih, 4]
            Xrot[ih, 4] = -sh * X[ih, 3] + ch * X[ih, 4]
            Xrot[ih, 5] = X[ih, 5]
        out["X_BEM"] = Xrot
        out["headings"] = heads

    return out


def interp_heading(X_BEM, headings, beta_deg):
    """Wrap-around heading interpolation of excitation coefficients
    (raft_fowt.py:1805-1833) + rotation back to the global frame
    (:1837-1846).  Returns (6, nw) complex for one wave heading."""
    beta = beta_deg % 360
    nhs = len(headings)
    if beta <= headings[0]:
        hlast = headings[-1] - 360
        i1, i2 = nhs - 1, 0
        f2 = (beta - hlast) / (headings[0] - hlast)
    elif beta >= headings[-1]:
        hfirst = headings[0] + 360
        i1, i2 = nhs - 1, 0
        f2 = (beta - headings[-1]) / (hfirst - headings[-1])
    else:
        for i in range(nhs - 1):
            if headings[i + 1] > beta:
                i1, i2 = i, i + 1
                f2 = (beta - headings[i]) / (headings[i + 1] - headings[i])
                break
    X_prime = X_BEM[i1] * (1 - f2) + X_BEM[i2] * f2

    b = np.radians(beta_deg)
    sb, cb = np.sin(b), np.cos(b)
    X = np.zeros_like(X_prime)
    X[0] = X_prime[0] * cb - X_prime[1] * sb
    X[1] = X_prime[0] * sb + X_prime[1] * cb
    X[2] = X_prime[2]
    X[3] = X_prime[3] * cb - X_prime[4] * sb
    X[4] = X_prime[3] * sb + X_prime[4] * cb
    X[5] = X_prime[5]
    return X
